"""Overload management tests (serving/overload.py + the reworked
admission path): priority classes, tenant quotas, scaled Retry-After,
pre-dispatch deadline drops, AIMD convergence/recovery, the brownout
ladder round trip, and the chaos acceptance (serving.overload armed
against a two-tenant three-priority mix).

Strategy: policy decisions and the AIMD/brownout controller are
exercised in-process with manual ticks and injected clocks (fast,
deterministic); one real-HTTP test per wire contract (headers, tenant
isolation, client backoff); the sustained 10x-offered-load variant is
@pytest.mark.slow with a scaled-down tier-1 proxy riding the same
invariants.
"""

import threading
import time

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.analysis import lockcheck
from deeplearning4j_tpu.parallel.inference import (
    InferenceDeadlineExpired,
    ParallelInference,
)
from deeplearning4j_tpu.resilience.faults import (
    FaultInjector,
    set_fault_injector,
)
from deeplearning4j_tpu.serving import (
    AdmissionController,
    BadRequestError,
    BrownoutLadder,
    BrownoutRung,
    DeadlineExceededError,
    DeadlineExpiredError,
    ModelRegistry,
    ModelServer,
    OverloadManager,
    OverloadPolicy,
    QueueFullError,
    ServingClient,
    TenantQuotaError,
    TenantQuotas,
    error_from_code,
    spec,
)
from deeplearning4j_tpu.serving.metrics import ServingMetrics

# ---------------------------------------------------------------------------
# helpers


def _scale_forward(v, x):
    import jax.numpy as jnp

    return jnp.zeros((x.shape[0], 1), jnp.float32) + v["scale"]


def _overload_server(policy, **kw):
    registry = ModelRegistry()
    registry.register("scale", _scale_forward, {"scale": 1.0},
                      input_spec=spec((4,)), version="v1", mode="batched",
                      max_batch_size=8, devices=jax.devices()[:2])
    server = ModelServer(registry, port=0, overload=policy,
                         sentinel=False, **kw)
    return server, registry


def _manager(metrics=None, **policy_kw):
    policy_kw.setdefault("min_in_flight", 2)
    policy_kw.setdefault("max_in_flight", 8)
    m = metrics if metrics is not None else ServingMetrics()
    ov = OverloadManager(OverloadPolicy(**policy_kw), metrics=m)
    ov.bind_limit(policy_kw["max_in_flight"])
    return ov, m


X1 = np.zeros((1, 4), np.float32)


# ---------------------------------------------------------------------------
# policy + token buckets


def test_policy_validation():
    OverloadPolicy().validate()
    with pytest.raises(ValueError):
        OverloadPolicy(min_in_flight=0).validate()
    with pytest.raises(ValueError):
        OverloadPolicy(min_in_flight=8, max_in_flight=4).validate()
    with pytest.raises(ValueError):
        OverloadPolicy(decrease_factor=1.0).validate()
    with pytest.raises(ValueError):
        OverloadPolicy(class_fractions={"critical": 0.5}).validate()
    with pytest.raises(ValueError):
        # critical must shed last: its fraction must be the largest
        OverloadPolicy(class_fractions={
            "critical": 0.5, "normal": 0.9, "batch": 0.7}).validate()
    with pytest.raises(ValueError):
        OverloadPolicy(tenant_rate=-1.0).validate()


def test_token_bucket_refill_and_wait():
    q = TenantQuotas(rate=2.0, burst=3.0)  # 2 tokens/s, burst 3
    ok, _ = q.take("a", now=0.0)
    ok2, _ = q.take("a", now=0.0)
    ok3, _ = q.take("a", now=0.0)
    assert ok and ok2 and ok3
    refused, wait = q.take("a", now=0.0)
    assert not refused and wait == pytest.approx(0.5)  # 1 token / 2 per s
    # after the exact wait, exactly one token is back
    ok4, _ = q.take("a", now=0.5)
    assert ok4
    refused2, _ = q.take("a", now=0.5)
    assert not refused2
    # another tenant is untouched
    assert q.take("b", now=0.5)[0]


def test_token_bucket_lru_bound():
    q = TenantQuotas(rate=1.0, burst=1.0, max_tenants=4)
    for i in range(10):
        q.take(f"t{i}", now=0.0)
    assert len(q) == 4  # oldest evicted, never unbounded


# ---------------------------------------------------------------------------
# priority-class admission (no HTTP)


def test_lowest_class_sheds_first_and_critical_borrows():
    ac = AdmissionController(max_in_flight=8)
    ov, _ = _manager()  # fractions 1.0 / 0.9 / 0.7 over limit 8
    ac.attach_overload(ov)
    # batch threshold ceil(8*0.7)=6: 6 admit, the 7th sheds
    batch = [ac.admit("batch") for _ in range(6)]
    with pytest.raises(QueueFullError):
        ac.admit("batch")
    # normal threshold ceil(8*0.9)=8: 2 more admit (total 8), then shed
    normal = [ac.admit("normal") for _ in range(2)]
    with pytest.raises(QueueFullError):
        ac.admit("normal")
    # PRIORITY-INVERSION REGRESSION: total is at the limit, but batch
    # work is in flight — critical must NEVER be shed in that state
    crit = [ac.admit("critical") for _ in range(3)]
    assert ac.in_flight == 11  # bounded borrow over the limit of 8
    # ...but the borrow is HARD-CAPPED at 2x the ceiling: a flood of
    # client-chosen critical headers cannot pile up without bound
    # behind one slow batch request
    crit += [ac.admit("critical") for _ in range(16 - 11)]
    with pytest.raises(QueueFullError):
        ac.admit("critical")
    for t in crit + normal + batch:
        t.release()
    # with NO lower-class work in flight, critical is bounded at the limit
    crit = [ac.admit("critical") for _ in range(8)]
    with pytest.raises(QueueFullError):
        ac.admit("critical")
    for t in crit:
        t.release()
    assert ac.in_flight == 0
    assert ac.class_in_flight() == {"critical": 0, "normal": 0, "batch": 0}


def test_invalid_priority_rejected():
    ac = AdmissionController(max_in_flight=2)
    with pytest.raises(BadRequestError):
        ac.admit("urgent")


def test_brownout_batch_shed_flag():
    ac = AdmissionController(max_in_flight=8)
    ov, _ = _manager()
    ac.attach_overload(ov)
    ov.shed_batch = True
    with pytest.raises(QueueFullError, match="brownout"):
        ac.admit("batch")
    ac.admit("normal").release()  # other classes unaffected
    ov.shed_batch = False
    ac.admit("batch").release()


def test_tenant_quota_shed_is_distinct_and_isolated():
    ac = AdmissionController(max_in_flight=8)
    ov, _ = _manager(tenant_rate=1.0, tenant_burst=2)
    ac.attach_overload(ov)
    ac.admit("normal", tenant="hog").release()
    ac.admit("normal", tenant="hog").release()
    with pytest.raises(TenantQuotaError) as ei:
        ac.admit("normal", tenant="hog")
    # server-supplied backoff: the exact refill wait, far over 50 ms
    assert ei.value.retry_after_ms >= 900.0
    assert ei.value.code == "TENANT_QUOTA" and ei.value.retryable
    # the hog's quota does not touch other tenants or capacity
    ac.admit("normal", tenant="polite").release()


def test_capacity_shed_never_burns_tenant_token():
    """Global overload must not drain well-behaved tenants' quotas:
    a request shed for capacity is checked BEFORE its tenant bucket."""
    ac = AdmissionController(max_in_flight=4)
    ov, _ = _manager(min_in_flight=2, max_in_flight=4,
                     tenant_rate=1.0, tenant_burst=2)
    ac.attach_overload(ov)
    held = [ac.admit("normal", tenant=f"f{i}") for i in range(4)]
    for _ in range(5):
        with pytest.raises(QueueFullError):
            ac.admit("normal", tenant="victim")
    for t in held:
        t.release()
    # the victim's burst of 2 is fully intact after 5 capacity sheds
    ac.admit("normal", tenant="victim").release()
    ac.admit("normal", tenant="victim").release()
    with pytest.raises(TenantQuotaError):
        ac.admit("normal", tenant="victim")


def test_tenant_and_brownout_sheds_do_not_feed_overload_signal():
    """A contained runaway (quota sheds) or the ladder's own batch
    sheds must not latch the shed-rate overload verdict."""
    ac = AdmissionController(max_in_flight=8)
    ov, _ = _manager(min_in_flight=2, max_in_flight=8,
                     tenant_rate=1.0, tenant_burst=1,
                     shed_rate_overload=5.0)
    ac.attach_overload(ov)
    clock = [0.0]
    ov._clock = lambda: clock[0]
    ov.tick()
    ac.admit("normal", tenant="hog").release()
    for _ in range(50):  # quota sheds: contained, not overload
        with pytest.raises(TenantQuotaError):
            ac.admit("normal", tenant="hog")
    ov.shed_batch = True
    for _ in range(50):  # brownout policy sheds: not overload either
        with pytest.raises(QueueFullError):
            ac.admit("batch", tenant="b")
    ov.shed_batch = False
    clock[0] += 1.0
    ov.tick()
    assert not ov.last_overloaded
    assert ov.effective_limit == 8


# ---------------------------------------------------------------------------
# Retry-After overshoot scaling (satellite 1)


def test_retry_after_scales_with_measured_overshoot():
    ac = AdmissionController(max_in_flight=4, retry_after_ms=50.0)
    held = [ac.admit() for _ in range(4)]
    # no service-time data yet: the fixed fallback hint
    with pytest.raises(QueueFullError) as ei:
        ac.admit()
    assert ei.value.retry_after_ms == 50.0
    # feed batch service times -> the hint becomes overshoot * EWMA
    for _ in range(8):
        ac.observe_service_time(0.2)
    with pytest.raises(QueueFullError) as ei:
        ac.admit()
    # (4+1)/4 * ~200ms = ~250ms
    assert 200.0 <= ei.value.retry_after_ms <= 300.0
    for t in held:
        t.release()
    # capped: a pathological EWMA cannot ask clients to wait forever
    ac2 = AdmissionController(max_in_flight=1, max_retry_after_ms=1000.0)
    ac2.observe_service_time(30.0)
    t = ac2.admit()
    with pytest.raises(QueueFullError) as ei:
        ac2.admit()
    assert ei.value.retry_after_ms == 1000.0
    t.release()


# ---------------------------------------------------------------------------
# pre-dispatch deadline drop (satellite 2)


def test_deadline_expired_dropped_before_dispatch():
    """A request whose deadline passes while queued must be dropped
    before dispatch (typed error, counted), never burn a batch slot."""
    dispatched_rows = []
    expired_counts = []

    def forward(v, x):
        import jax.numpy as jnp

        return jnp.zeros((x.shape[0], 1), jnp.float32)

    gate = threading.Event()

    def slow_forward(v, x):
        gate.wait(2.0)
        return forward(v, x)

    pi = ParallelInference(forward, {"w": 1.0},
                           devices=jax.devices()[:1], mode="batched",
                           max_batch_size=4,
                           on_expired=expired_counts.append)
    orig_fn, pi._fn = pi._fn, lambda v, x: (
        dispatched_rows.append(int(x.shape[0])), slow_forward(v, x))[1]
    try:
        # request A occupies the single worker (slow dispatch)
        errs = []

        def run_a():
            try:
                pi.output(X1, timeout=5.0)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ta = threading.Thread(target=run_a)
        ta.start()
        time.sleep(0.2)  # A is in dispatch, holding the worker
        # request B: generous caller timeout but a deadline that expires
        # while it waits in the queue behind A
        with pytest.raises(InferenceDeadlineExpired):
            pi.output(X1, timeout=5.0,
                      deadline=time.monotonic() + 0.1)
        gate.set()
        ta.join(timeout=5)
        assert not errs, errs
    finally:
        gate.set()
        pi.shutdown()
    assert sum(expired_counts) >= 1, "drop must be counted"
    # only A's single row was ever dispatched — B never burned a slot
    assert dispatched_rows and all(r == 1 for r in dispatched_rows)


def test_deadline_expired_wire_code_roundtrip():
    err = error_from_code("DEADLINE_EXPIRED", "queued too long")
    assert isinstance(err, DeadlineExpiredError)
    assert isinstance(err, DeadlineExceededError)  # handlers keep working
    assert not err.retryable and err.http_status == 504


# ---------------------------------------------------------------------------
# AIMD convergence + recovery (manual ticks, synthetic latency)


def _feed(metrics, seconds, n=10):
    for _ in range(n):
        metrics.request_latency.observe(seconds, model="m")


def test_aimd_converges_under_degraded_p99_then_recovers():
    ov, m = _manager(min_in_flight=2, max_in_flight=8,
                     min_history=4, min_samples_per_tick=4,
                     increase_step=2.0, decrease_factor=0.5,
                     degrade_ratio=1.2, z_threshold=2.0,
                     shed_rate_overload=None)
    clock = [0.0]
    ov._clock = lambda: clock[0]

    def tick():
        clock[0] += 1.0
        return ov.tick()

    tick()  # anchors the histogram-delta probe
    for _ in range(6):  # healthy warmup: baseline learns ~2 ms p99
        _feed(m, 0.002)
        tick()
    assert len(ov.baseline) >= 4
    assert ov.effective_limit == 8
    # degraded p99 -> multiplicative shrink to the floor ("converges")
    for _ in range(4):
        _feed(m, 0.4)
        tick()
    assert ov.effective_limit == 2, ov.describe()
    assert ov.last_overloaded
    # baseline was FROZEN while degraded: it still says ~2 ms
    assert ov.baseline.median() < 0.1
    # healthy again -> additive regrowth to the ceiling ("recovers")
    for _ in range(6):
        _feed(m, 0.002)
        tick()
    assert ov.effective_limit == 8, ov.describe()
    assert float(m.effective_limit.value()) == 8.0


def test_shed_rate_signal_marks_overload():
    ov, m = _manager(min_in_flight=2, max_in_flight=8,
                     shed_rate_overload=5.0)
    clock = [0.0]
    ov._clock = lambda: clock[0]
    ov.tick()  # anchors shed accounting
    for _ in range(50):
        ov.note_shed()
    clock[0] += 1.0  # 50 sheds/s >> 5/s
    ov.tick()
    assert ov.last_overloaded
    assert ov.effective_limit < 8


# ---------------------------------------------------------------------------
# brownout ladder


def test_brownout_ladder_orders_and_survives_rung_errors():
    log = []

    def rung(name, fail=False):
        def engage():
            log.append(("engage", name))
            if fail:
                raise RuntimeError("rung exploded")

        def disengage():
            log.append(("disengage", name))

        return BrownoutRung(name, engage, disengage)

    events = []
    ladder = BrownoutLadder(
        [rung("a"), rung("b", fail=True), rung("c")],
        on_transition=lambda *a: events.append(a))
    assert ladder.step_down() == "a"
    assert ladder.step_down() == "b"  # engage raised; level advances
    assert ladder.level == 2
    assert ladder.step_down() == "c"
    assert ladder.step_down() is None  # bottom
    assert ladder.step_up() == "c"
    assert ladder.step_up() == "b"
    assert ladder.step_up() == "a"
    assert ladder.step_up() is None and ladder.level == 0
    assert [e[:2] for e in log] == [
        ("engage", "a"), ("engage", "b"), ("engage", "c"),
        ("disengage", "c"), ("disengage", "b"), ("disengage", "a")]
    # the failed engage rode the transition event, not an exception
    assert any(e[4] is not None for e in events)


def test_manager_walks_ladder_with_hysteresis():
    ov, m = _manager(min_in_flight=2, max_in_flight=8,
                     min_history=4, min_samples_per_tick=4,
                     degrade_ratio=1.2, z_threshold=2.0,
                     brownout_down_after=2, brownout_up_after=3,
                     shed_rate_overload=None)
    walked = []
    ov.ladder = BrownoutLadder(
        [BrownoutRung("one", lambda: walked.append("+one"),
                      lambda: walked.append("-one")),
         BrownoutRung("two", lambda: walked.append("+two"),
                      lambda: walked.append("-two"))],
        on_transition=ov._on_brownout_transition)
    clock = [0.0]
    ov._clock = lambda: clock[0]

    def tick():
        clock[0] += 1.0
        ov.tick()

    tick()
    for _ in range(6):
        _feed(m, 0.002)
        tick()
    # overload: down_after=2 -> one step per 2 consecutive bad ticks
    for i in range(4):
        _feed(m, 0.4)
        tick()
    assert ov.ladder.level == 2 and walked == ["+one", "+two"]
    # recovery needs up_after=3 consecutive healthy ticks per step
    for i in range(6):
        _feed(m, 0.002)
        tick()
    assert ov.ladder.level == 0
    assert walked == ["+one", "+two", "-two", "-one"]
    assert float(m.brownout_level.value()) == 0.0
    assert m.brownout_transitions_total.value(direction="down") == 2
    assert m.brownout_transitions_total.value(direction="up") == 2


# ---------------------------------------------------------------------------
# over real HTTP: headers, tenant isolation, client backoff


def test_http_priority_header_validated_and_tenant_isolation():
    policy = OverloadPolicy(min_in_flight=2, max_in_flight=8,
                            tenant_rate=2.0, tenant_burst=2,
                            interval_s=3600.0)
    server, registry = _overload_server(policy)
    with server:
        client = ServingClient(server.url)
        # priority/tenant kwargs emit headers; valid ones serve
        r = client.predict("scale", X1, priority="critical", tenant="acme")
        assert r["version"] == "v1"
        with pytest.raises(BadRequestError):
            client.predict("scale", X1, priority="urgent")
        # tenant isolation over the wire: the hog exhausts its bucket...
        with pytest.raises(TenantQuotaError) as ei:
            for _ in range(4):
                client.predict("scale", X1, tenant="hog")
        assert ei.value.retry_after_ms and ei.value.retry_after_ms > 100.0
        # ...while another tenant (and the anonymous-free case when
        # quotas are per-tenant) is untouched
        client.predict("scale", X1, tenant="polite")
        assert server.metrics.shed_total.value(
            model="scale", reason="tenant_quota") >= 1
        assert server.metrics.tenant_shed_total.value() >= 1
        # /debug/overload renders the live manager state
        dbg = client._request("/debug/overload")
        assert dbg["effective_limit"] == 8
        assert dbg["tenants"]["tenants"] >= 2
        assert dbg["brownout"]["rungs"] == [
            "shrink_batch_wait", "shed_batch_class", "serve_fallback"]


def test_client_retry_uses_server_refill_schedule_for_tenant_quota():
    policy = OverloadPolicy(min_in_flight=2, max_in_flight=8,
                            tenant_rate=5.0, tenant_burst=1,
                            interval_s=3600.0)
    server, _ = _overload_server(policy)
    with server:
        sleeps = []

        def recording_sleep(s):
            # record AND really wait: the bucket refills in real time
            sleeps.append(s)
            time.sleep(s)

        client = ServingClient(server.url, max_retries=2,
                               backoff_base_s=0.001, backoff_max_s=0.002,
                               retry_seed=0, sleep=recording_sleep)
        client.predict("scale", X1, tenant="t")   # burns the only token
        # the retry waits the server's refill interval (~200 ms at
        # 5/s), NEVER the 1-2 ms local schedule
        t0 = time.monotonic()
        client.predict("scale", X1, tenant="t")
        assert sleeps, "quota shed must have been retried"
        assert all(s >= 0.1 for s in sleeps), sleeps
        # sleep was injected, so wall time stayed fast
        assert time.monotonic() - t0 < 5.0


# ---------------------------------------------------------------------------
# chaos acceptance (tier-1 fast proxy; the 10x HTTP mix is @slow)


def _chaos_policy(**kw):
    kw.setdefault("min_in_flight", 2)
    kw.setdefault("max_in_flight", 8)
    kw.setdefault("min_history", 4)
    kw.setdefault("min_samples_per_tick", 4)
    kw.setdefault("degrade_ratio", 1.2)
    kw.setdefault("z_threshold", 2.0)
    # bucket-resolved p99 on a zero-MAD fast baseline: scheduling
    # jitter on a loaded CI host reaches the 0.05 s bucket, so the
    # floor sits ABOVE that bucket and below the injected 0.08 s
    # (bucket 0.1) — only the synthetic overload reads as degraded
    kw.setdefault("min_degraded_p99_s", 0.06)
    kw.setdefault("increase_step", 4.0)
    kw.setdefault("brownout_down_after", 1)
    kw.setdefault("brownout_up_after", 2)
    kw.setdefault("shed_rate_overload", None)
    kw.setdefault("tenant_rate", 50.0)
    kw.setdefault("tenant_burst", 50.0)
    kw.setdefault("interval_s", 3600.0)  # manual ticks drive the test
    return OverloadPolicy(**kw)


def _mixed_phase(server, n_rounds, outcomes, overload_ticks=0):
    """One traffic phase: each round sends critical+normal (tenant-a)
    and batch (tenant-b) requests concurrently through handle_predict,
    then manually ticks the manager."""
    lock = threading.Lock()

    def send(prio, tenant):
        status, body = server.handle_predict(
            "scale", {"inputs": X1.tolist()}, priority=prio, tenant=tenant)
        with lock:
            outcomes.append((prio, status, body))

    for _ in range(n_rounds):
        threads = [threading.Thread(target=send, args=(p, t))
                   for p, t in (("critical", "a"), ("critical", "a"),
                                ("normal", "a"), ("normal", "b"),
                                ("batch", "b"), ("batch", "b"))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        server.overload.tick()


def test_chaos_overload_brownout_full_roundtrip(monkeypatch):
    """The acceptance loop at tier-1 scale: serving.overload armed
    against a two-tenant, three-priority mix -> AIMD shrinks, the
    ladder walks all the way down (batch shed, fallback serving), no
    critical request is ever shed, and after the fault clears the
    ladder re-escalates to level 0 with the original version serving
    (metrics prove the round trip).

    Runs with the lockorder sanitizer armed: every lock built through
    the overload/admission/registry planes is instrumented, and the
    test asserts the whole brownout round trip produced zero
    order-inversion / long-hold violations — the chaos path re-proves
    the serving plane's lock discipline on every run."""
    monkeypatch.setenv("DL4J_TPU_SANITIZERS", "lockorder")
    # a generous long-hold threshold: a >1 s GIL/scheduler stall while
    # a lock is held would otherwise fail the zero-violation assert
    # with no real defect on a loaded CI machine
    monkeypatch.setenv("DL4J_TPU_LOCKCHECK_HOLD_S", "30")
    lockcheck.reset()
    server, registry = _overload_server(_chaos_policy())
    registry.get("scale").set_fallback({"scale": 9.0})
    outcomes = []
    inj = FaultInjector()
    set_fault_injector(inj)
    try:
        with server:
            # phase 1 — healthy warmup: baseline learns fast p99. A
            # loaded host can spike one judged warmup tick into the
            # 0.1 s bucket (bucket-quantized p99 over a near-zero-MAD
            # baseline) and engage rung 1 with down_after=1 — that is
            # scheduler noise, not a failure: healthy ticks heal it
            # (up_after=2), so give them the chance before asserting
            _mixed_phase(server, 7, outcomes)
            noise_rounds = 0
            while server.overload.ladder.level > 0 and noise_rounds < 12:
                _mixed_phase(server, 2, outcomes)
                noise_rounds += 2
            assert server.overload.effective_limit == 8
            assert server.overload.ladder.level == 0
            # phase 2 — sustained synthetic overload (~80 ms/request)
            inj.plan("serving.overload", at=1, times=4 * 6, arg=0.08)
            _mixed_phase(server, 4, outcomes)
            assert server.overload.ladder.level == 3, \
                server.overload.describe()
            assert server.overload.effective_limit == 2
            # deepest rung: the fallback version is serving
            status, body = server.handle_predict(
                "scale", {"inputs": X1.tolist()}, priority="critical",
                tenant="a")
            assert status == 200 and body["version"] == "v1-fallback"
            assert np.asarray(body["outputs"])[0][0] == 9.0
            # batch class is fully shed while the ladder is at >= 2
            status, body = server.handle_predict(
                "scale", {"inputs": X1.tolist()}, priority="batch",
                tenant="b")
            assert status == 429, body
            # phase 3 — fault budget exhausted: healthy traffic walks
            # the ladder back up (up_after=2 -> 6 healthy ticks)
            _mixed_phase(server, 8, outcomes)
            assert server.overload.ladder.level == 0, \
                server.overload.describe()
            assert server.overload.effective_limit == 8
            status, body = server.handle_predict(
                "scale", {"inputs": X1.tolist()}, priority="batch",
                tenant="b")
            assert status == 200 and body["version"] == "v1"
            assert np.asarray(body["outputs"])[0][0] == 1.0
            m = server.metrics
            downs = m.brownout_transitions_total.value(direction="down")
            ups = m.brownout_transitions_total.value(direction="up")
            # every engage was matched by a disengage (full recovery),
            # and the real overload walked all 3 rungs; phase-1 noise
            # pairs (healed above) may add symmetric extras
            assert downs == ups >= 3, (downs, ups)
            assert float(m.brownout_level.value()) == 0.0
    finally:
        set_fault_injector(None)
        server.stop()
    # the acceptance invariant: critical availability 100% here — no
    # critical request was ever shed, through overload and brownout
    crit = [(s, b) for p, s, b in outcomes if p == "critical"]
    assert crit and all(s == 200 for s, _ in crit), \
        [b for s, b in crit if s != 200][:3]
    # and the armed lockorder sanitizer saw a clean run
    assert lockcheck.violations() == [], lockcheck.render_report()


@pytest.mark.slow
def test_sustained_10x_overload_three_priorities_over_http():
    """Heavy acceptance variant over real HTTP: offered concurrency 10x
    the admission ceiling, manager on its own thread, serving.overload
    armed for the middle third. Invariants: critical availability
    >= 99%, zero critical sheds (batch/normal absorb them all),
    brownout engages then fully re-escalates to level 0."""
    policy = _chaos_policy(interval_s=0.25, tenant_rate=500.0,
                           tenant_burst=500.0)
    server, registry = _overload_server(policy)
    registry.get("scale").set_fallback({"scale": 9.0})
    inj = FaultInjector()
    set_fault_injector(inj)
    results = {"critical": [], "normal": [], "batch": []}
    lock = threading.Lock()
    stop = threading.Event()

    def worker(prio, tenant):
        client = ServingClient(server.url)
        while not stop.is_set():
            try:
                client.predict("scale", X1, priority=prio, tenant=tenant,
                               deadline_ms=10000)
                code = 200
            except BadRequestError:
                raise
            except Exception as e:  # noqa: BLE001 — typed sheds expected
                code = getattr(e, "http_status", 599)
            with lock:
                results[prio].append(code)

    try:
        with server:
            # 10x the max_in_flight=8 ceiling: 80 offered concurrency
            # (4 critical, 16 normal, 60 batch across two tenants)
            threads = (
                [threading.Thread(target=worker, args=("critical", "a"))
                 for _ in range(4)]
                + [threading.Thread(target=worker, args=("normal", "a"))
                   for _ in range(8)]
                + [threading.Thread(target=worker, args=("normal", "b"))
                   for _ in range(8)]
                + [threading.Thread(target=worker, args=("batch", "b"))
                   for _ in range(60)])
            for t in threads:
                t.start()
            time.sleep(2.0)        # healthy baseline
            inj.plan("serving.overload", prob=1.0, times=100000, arg=0.05)
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline \
                    and server.overload.ladder.level < 3:
                time.sleep(0.2)
            assert server.overload.ladder.level >= 1, \
                server.overload.describe()
            engaged_level = server.overload.ladder.level
            # clear the fault: exhaust the budget instantly
            inj.reset()
            inj._plans.clear()
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline \
                    and server.overload.ladder.level > 0:
                time.sleep(0.2)
            stop.set()
            for t in threads:
                t.join(timeout=30)
                assert not t.is_alive(), "client thread hung"
            assert engaged_level >= 1
            assert server.overload.ladder.level == 0, \
                server.overload.describe()
            assert server.overload.effective_limit == 8
    finally:
        stop.set()
        set_fault_injector(None)
        server.stop()
    crit = results["critical"]
    assert crit, "critical clients never completed a request"
    availability = crit.count(200) / len(crit)
    assert availability >= 0.99, f"critical availability {availability}"
    # the batch class absorbed the shed load
    assert any(c == 429 for c in results["batch"])
