"""Seeded blocking-under-lock: sleeps, network/file I/O, subprocess
spawn, and a jit entry all lexically inside a held-lock region. The
analyzer must flag every one (PR 8/14 shape: incident-bundle I/O and
fallback-prewarm compiles held under engine/entry locks)."""

import json
import subprocess
import threading
import time
import urllib.request

import jax


class Bundler:
    def __init__(self):
        self._lock = threading.Lock()
        self.doc = {}

    def capture(self):
        with self._lock:
            time.sleep(0.5)                      # seeded: sleeps

    def publish(self, url):
        with self._lock:
            urllib.request.urlopen(url)          # seeded: network I/O

    def persist(self, path):
        with self._lock:
            with open(path, "w") as fh:          # seeded: file I/O
                json.dump(self.doc, fh)          # seeded: file I/O

    def spawn(self):
        with self._lock:
            subprocess.run(["true"])             # seeded: process spawn

    def prewarm(self, fn):
        with self._lock:
            return jax.jit(fn)                   # seeded: enters jit

    def off_lock_is_fine(self):
        time.sleep(0.0)
        doc = None
        with self._lock:
            doc = dict(self.doc)
        return doc
