"""Seeded ABBA deadlock: Engine takes its own lock then calls into its
breaker (which takes the breaker lock); Breaker's transition path takes
the breaker lock then calls back into an Engine method that takes the
engine lock. The analyzer must report one lock-order cycle with
witnesses on both edges."""

import threading


class Engine:
    def __init__(self):
        self._lock = threading.Lock()
        self.breaker = Breaker()

    def note_result(self, ok):
        with self._lock:
            # engine -> breaker: the forward half (the bug)
            self.breaker.record(ok)

    def close_pool(self):
        with self._lock:
            self.pool = []


class Breaker:
    def __init__(self):
        self._lock = threading.Lock()
        # static type witness so the one-level resolver sees the
        # callback half (real code would declare a lock-edge instead)
        self.engine = Engine()

    def record(self, ok):
        with self._lock:
            self.state = ok

    def transition(self):
        with self._lock:
            # breaker -> engine: the callback half of the ABBA
            self.engine.close_pool()
