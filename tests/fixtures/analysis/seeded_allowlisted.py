"""Every seeded violation in this file carries a reasoned allow
comment: the analyzer must report ZERO active findings here, with the
suppressed count surfaced as `allowlisted`."""

import os
import threading
import time

from deeplearning4j_tpu.observability.flightrecorder import record_event


class QuietPlane:
    def __init__(self):
        self._lock = threading.Lock()

    def pause_all(self):
        # analysis: allow(blocking-under-lock) — seeded fixture: the
        # sleep is the whole point of the boundary pause
        with self._lock:
            time.sleep(0.01)

    def inline_form(self):
        with self._lock:
            # analysis: allow(blocking-under-lock) — seeded fixture
            time.sleep(0.01)

    def note(self):
        # analysis: allow(unregistered-event-kind) — seeded fixture
        record_event("quiet.widget_event", detail="suppressed")
        # analysis: allow(unregistered-knob) — seeded fixture
        return os.environ.get("DL4J_TPU_QUIET_BOGUS_KNOB")
