"""Seeded traced-hazards: host effects inside jit-traced functions (the
bench jit-sleep trap — the sleep runs once at trace time and is
compiled away). Decorated, passed-by-name, partial-wrapped, and lambda
forms must all be caught; the pure_callback escape must not."""

import random
import time
from functools import partial

import jax
import numpy as np


@jax.jit
def decorated_step(x):
    time.sleep(0.01)                 # seeded: traced sleep
    return x * 2


def named_step(x):
    t = time.time()                  # seeded: trace-time clock
    return x + t


compiled_named = jax.jit(named_step)


@partial(jax.jit, static_argnums=0)
def partial_decorated(n, x):
    noise = np.random.normal(size=n)   # seeded: host RNG frozen
    return x + noise


compiled_lambda = jax.jit(lambda x: x * random.random())  # seeded: RNG


@jax.jit
def callback_escape_is_fine(x):
    jax.pure_callback(lambda v: time.sleep(0.0), None, x)
    return x


@jax.jit
def callback_operand_is_traced(x):
    # only the callback FN escapes to the host — this operand is
    # evaluated at trace time and the clock value baked into the graph
    return jax.pure_callback(lambda v: v, x, x + time.time())  # seeded


def untraced_helper(x):
    time.sleep(0.01)                 # NOT traced: no finding here
    return x
