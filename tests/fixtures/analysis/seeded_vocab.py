"""Seeded vocabulary drift: a metric family missing from
slo.known_metric_names(), a flight-event kind undeclared in
observability/vocab.py, and a DL4J_TPU_* env knob unregistered in
analysis/knobs.py. One finding each."""

import os

from deeplearning4j_tpu.observability.flightrecorder import record_event
from deeplearning4j_tpu.observability.metrics import MetricsRegistry


class BogusPlane:
    def __init__(self):
        reg = MetricsRegistry()
        ns = "bogus"
        self.total = reg.counter(
            "unregistered_widget_total", "seeded drift", namespace=ns)

    def note(self):
        self.total.inc()
        record_event("bogus.widget_event", detail="seeded drift")
        return os.environ.get("DL4J_TPU_UNREGISTERED_BOGUS_KNOB")
