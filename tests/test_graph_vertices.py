"""Graph-vertex breadth (↔ org.deeplearning4j.nn.conf.graph.*Vertex:
Subset, Stack/Unstack, L2Normalize, Shift, Reshape, LastTimeStep,
DuplicateToTimeSeries, ReverseTimeSeries)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn import layers as L
from deeplearning4j_tpu.nn.config import (
    GraphConfig,
    GraphVertex,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.model import GraphModel


def _model(vertices, inputs, input_shapes, outputs):
    cfg = GraphConfig(net=NeuralNetConfiguration(seed=0), inputs=inputs,
                      input_shapes=input_shapes, vertices=vertices,
                      outputs=outputs)
    m = GraphModel(cfg)
    return m, m.init()


def _x(shape, seed=0):
    return jnp.asarray(np.random.default_rng(seed)
                       .normal(size=shape).astype(np.float32))


def test_subset_vertex_inclusive_range():
    m, v = _model({"sub": GraphVertex(kind="subset", inputs=["in"],
                                      args={"from": 1, "to": 3})},
                  ["in"], {"in": (6,)}, ["sub"])
    assert m.shapes["sub"] == (3,)
    x = _x((2, 6))
    out = m.output(v, x)["sub"]
    np.testing.assert_allclose(out, np.asarray(x)[:, 1:4])


def test_stack_unstack_roundtrip():
    verts = {
        "stacked": GraphVertex(kind="stack", inputs=["a", "b"]),
        "dense": GraphVertex(kind="layer", inputs=["stacked"],
                             layer=L.Dense(units=4)),
        "back_a": GraphVertex(kind="unstack", inputs=["dense"],
                              args={"from": 0, "of": 2}),
        "back_b": GraphVertex(kind="unstack", inputs=["dense"],
                              args={"from": 1, "of": 2}),
    }
    m, v = _model(verts, ["a", "b"], {"a": (5,), "b": (5,)},
                  ["back_a", "back_b"])
    xa, xb = _x((3, 5), 1), _x((3, 5), 2)
    out = m.apply(v, {"a": xa, "b": xb})[0]
    # shared weights: each slice equals applying the dense layer directly
    dense_p = v["params"]["dense"]
    ya, _ = m.config.vertices["dense"].layer.apply(dense_p, {}, xa)
    yb, _ = m.config.vertices["dense"].layer.apply(dense_p, {}, xb)
    np.testing.assert_allclose(np.asarray(out["back_a"]), np.asarray(ya),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out["back_b"]), np.asarray(yb),
                               rtol=1e-6)


def test_l2norm_and_shift():
    verts = {
        "n": GraphVertex(kind="l2norm", inputs=["in"]),
        "s": GraphVertex(kind="shift", inputs=["n"], args={"shift": 2.0}),
    }
    m, v = _model(verts, ["in"], {"in": (4,)}, ["s"])
    x = _x((3, 4))
    out = np.asarray(m.output(v, x)["s"]) - 2.0
    np.testing.assert_allclose(np.linalg.norm(out, axis=-1), 1.0, rtol=1e-5)


def test_reshape_vertex():
    m, v = _model({"r": GraphVertex(kind="reshape", inputs=["in"],
                                    args={"shape": [2, 3]})},
                  ["in"], {"in": (6,)}, ["r"])
    assert m.shapes["r"] == (2, 3)
    assert m.output(v, _x((4, 6)))["r"].shape == (4, 2, 3)


def test_timeseries_vertices():
    verts = {
        "rev": GraphVertex(kind="reverse_timeseries", inputs=["ts"]),
        "last": GraphVertex(kind="last_timestep", inputs=["rev"]),
        "dup": GraphVertex(kind="duplicate_to_timeseries",
                           inputs=["last", "ts"]),
    }
    m, v = _model(verts, ["ts"], {"ts": (5, 3)}, ["last", "dup"])
    assert m.shapes["last"] == (3,)
    assert m.shapes["dup"] == (5, 3)
    x = _x((2, 5, 3))
    out = m.apply(v, {"ts": x})[0]
    # last of reversed == first of original
    np.testing.assert_allclose(np.asarray(out["last"]),
                               np.asarray(x)[:, 0], rtol=1e-6)
    expected = np.broadcast_to(np.asarray(out["last"])[:, None, :],
                               (2, 5, 3))
    np.testing.assert_allclose(np.asarray(out["dup"]), expected, rtol=1e-6)


def test_vertices_trainable_end_to_end():
    """Gradients flow through the new vertices in a compiled train step."""
    from deeplearning4j_tpu.train.trainer import Trainer
    from deeplearning4j_tpu.train.updaters import Adam

    verts = {
        "dense": GraphVertex(kind="layer", inputs=["in"],
                             layer=L.Dense(units=6, activation="relu")),
        "sub": GraphVertex(kind="subset", inputs=["dense"],
                           args={"from": 0, "to": 3}),
        "norm": GraphVertex(kind="l2norm", inputs=["sub"]),
        "out": GraphVertex(kind="layer", inputs=["norm"],
                           layer=L.OutputLayer(units=3)),
    }
    cfg = GraphConfig(net=NeuralNetConfiguration(seed=0, updater=Adam(5e-2)),
                      inputs=["in"], input_shapes={"in": (5,)},
                      vertices=verts, outputs=["out"])
    model = GraphModel(cfg)
    tr = Trainer(model)
    ts = tr.init_state()
    r = np.random.default_rng(0)
    batch = {"features": r.normal(size=(16, 5)).astype(np.float32),
             "labels": np.eye(3, dtype=np.float32)[r.integers(0, 3, 16)]}
    losses = []
    for _ in range(40):
        ts, m_ = tr.train_step(ts, batch)
        losses.append(float(m_["total_loss"]))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_json_roundtrip_of_vertex_graph():
    verts = {"sub": GraphVertex(kind="subset", inputs=["in"],
                                args={"from": 0, "to": 1})}
    cfg = GraphConfig(net=NeuralNetConfiguration(seed=0), inputs=["in"],
                      input_shapes={"in": (4,)}, vertices=verts,
                      outputs=["sub"])
    js = cfg.to_json()
    cfg2 = GraphConfig.from_json(js)
    assert cfg2.to_json() == js
    m2 = GraphModel(cfg2)
    assert m2.shapes["sub"] == (2,)


def test_l2norm_zero_row_finite_gradient():
    """All-zero input row must not NaN the backward pass (safe-norm)."""
    m, v = _model({"n": GraphVertex(kind="l2norm", inputs=["in"])},
                  ["in"], {"in": (4,)}, ["n"])
    x = jnp.zeros((2, 4)).at[1].set(1.0)

    def f(x):
        return jnp.sum(m.apply(v, {"in": x})[0]["n"] ** 2)

    g = jax.grad(f)(x)
    assert bool(jnp.all(jnp.isfinite(g))), g


def test_output_single():
    """↔ ComputationGraph.outputSingle: one array for single-output
    graphs; multi-output graphs refuse."""
    import numpy as np
    import pytest

    from deeplearning4j_tpu.nn.config import (
        GraphConfig,
        GraphVertex,
        NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
    from deeplearning4j_tpu.nn.model import GraphModel

    cfg = GraphConfig(
        net=NeuralNetConfiguration(),
        inputs=["in"], input_shapes={"in": (4,)},
        vertices={
            "h": GraphVertex(kind="layer", inputs=["in"],
                             layer=Dense(units=8)),
            "out": GraphVertex(kind="layer", inputs=["h"],
                               layer=OutputLayer(units=2)),
        },
        outputs=["out"])
    m = GraphModel(cfg)
    v = m.init(seed=0)
    x = np.zeros((3, 4), np.float32)
    single = m.output_single(v, x)
    assert single.shape == (3, 2)
    np.testing.assert_allclose(np.asarray(single),
                               np.asarray(m.output(v, x)["out"]))
    cfg2 = GraphConfig(
        net=NeuralNetConfiguration(),
        inputs=["in"], input_shapes={"in": (4,)},
        vertices={
            "a": GraphVertex(kind="layer", inputs=["in"],
                             layer=OutputLayer(units=2)),
            "b": GraphVertex(kind="layer", inputs=["in"],
                             layer=OutputLayer(units=3)),
        },
        outputs=["a", "b"])
    with pytest.raises(ValueError, match="multi-output"):
        GraphModel(cfg2).output_single(GraphModel(cfg2).init(seed=0), x)


def test_graph_summary():
    """↔ ComputationGraph.summary(): vertex table with param counts."""
    import numpy as np

    from deeplearning4j_tpu.nn.config import (
        GraphConfig,
        GraphVertex,
        NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
    from deeplearning4j_tpu.nn.model import GraphModel

    cfg = GraphConfig(
        net=NeuralNetConfiguration(),
        inputs=["in"], input_shapes={"in": (4,)},
        vertices={
            "h": GraphVertex(kind="layer", inputs=["in"],
                             layer=Dense(units=8)),
            "m": GraphVertex(kind="merge", inputs=["h", "in"]),
            "out": GraphVertex(kind="layer", inputs=["m"],
                               layer=OutputLayer(units=2)),
        },
        outputs=["out"])
    m = GraphModel(cfg)
    v = m.init(seed=0)
    s = m.summary(v)
    assert "Dense" in s and "merge" in s and "outputs: out" in s
    want = m.num_params(v)
    assert f"total params: {want}" in s
