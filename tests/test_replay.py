"""Ledger-driven traffic replay (PR 17): trace export/scrub from the
request ledger, the trace document grammar, deterministic scenario
warps, gate math on synthetic client ledgers, and the live round-trip —
record mixed predict+generate traffic, export it over ``GET
/debug/requests?format=trace``, replay it, and land the same
plane/priority/tenant mix back on the server — plus open-loop arrival
fidelity at 1x.

Budget discipline: every HTTP test rides the shared ``mixed_server``
conftest fixture (one tiny-GPT engine + one predict model compiled per
module); everything else is pure math with no server at all.
"""

import json
import time
import urllib.request
from collections import Counter

import pytest

from deeplearning4j_tpu.observability import reqlog as rl
from deeplearning4j_tpu.observability.flightrecorder import (
    get_flight_recorder,
)
from deeplearning4j_tpu.resilience import gameday as gd
from deeplearning4j_tpu.resilience import replay as rp
from deeplearning4j_tpu.serving import ServingClient


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, json.loads(r.read())


def _trace_of(rows):
    """Wrap explicit rows into a valid trace document."""
    return rp.validate_trace({
        "version": rl.TRACE_VERSION, "kind": "dl4j_tpu_trace",
        "t0_wall": None, "count": len(rows),
        "duration_s": rows[-1]["arrival_offset_s"] if rows else 0.0,
        "rows": rows})


def _row(off, *, plane="predict", model="scale", priority="normal",
         tenant=None, shape=(1, 4), **extra):
    r = {"plane": plane, "model": model, "arrival_offset_s": off,
         "priority": priority, "tenant": tenant,
         "payload_shape": list(shape), "deadline_s": 30.0,
         "stream": False}
    r.update(extra)
    return r


# ---------------------------------------------------------------------------
# trace document grammar


class TestTraceDocument:
    def test_synthesize_is_deterministic_and_valid(self):
        spec = {"n": 40, "rate_rps": 50.0, "seed": 7,
                "models": [
                    {"name": "scale", "plane": "predict",
                     "payload_shape": [1, 4], "weight": 3.0},
                    {"name": "gpt", "plane": "generation",
                     "prompt_len": 6, "max_new_tokens": 4,
                     "stream": True}],
                "priorities": {"critical": 1, "normal": 4},
                "tenants": ["a", "b"]}
        t1 = rp.synthesize_trace(spec)
        t2 = rp.synthesize_trace(spec)
        assert t1["rows"] == t2["rows"]
        assert t1["count"] == 40
        planes = {r["plane"] for r in t1["rows"]}
        assert planes == {"predict", "generation"}
        for r in t1["rows"]:
            if r["plane"] == "generation":
                assert r["payload_shape"] == [6]
                assert r["max_new_tokens"] == 4
                assert r["stream"] is True

    def test_different_seed_different_trace(self):
        spec = {"n": 20, "rate_rps": 50.0, "tenants": ["a", "b", "c"]}
        t1 = rp.synthesize_trace(dict(spec, seed=1))
        t2 = rp.synthesize_trace(dict(spec, seed=2))
        assert t1["rows"] != t2["rows"]

    @pytest.mark.parametrize("mutate, msg", [
        (lambda t: t.update(kind="nope"), "not a dl4j_tpu_trace"),
        (lambda t: t.update(version=99), "unsupported trace version"),
        (lambda t: t.update(rows=None), "no rows list"),
        (lambda t: t["rows"].__setitem__(
            0, dict(t["rows"][0], arrival_offset_s=-1.0)),
         "bad arrival_offset_s"),
        (lambda t: t["rows"].__setitem__(
            0, dict(t["rows"][0], arrival_offset_s=9.0)),
         "arrives before"),
        (lambda t: t["rows"].__setitem__(
            1, dict(t["rows"][1], plane="training")), "unknown plane"),
        (lambda t: t["rows"].__setitem__(
            1, dict(t["rows"][1], model="")), "no model"),
    ])
    def test_validate_rejects_junk(self, mutate, msg):
        trace = _trace_of([_row(0.0), _row(0.5)])
        doc = json.loads(json.dumps(trace))  # deep copy
        mutate(doc)
        with pytest.raises(ValueError, match=msg):
            rp.validate_trace(doc)

    def test_save_load_round_trip(self, tmp_path):
        trace = rp.synthesize_trace({"n": 8, "seed": 3})
        path = str(tmp_path / "t.json")
        rp.save_trace(trace, path)
        assert rp.load_trace(path) == trace


# ---------------------------------------------------------------------------
# scenario warps: deterministic under a fixed seed


class TestWarps:
    def _base(self):
        return rp.synthesize_trace(
            {"n": 60, "rate_rps": 30.0, "seed": 11,
             "tenants": ["t0", "t1"]})

    def test_zipf_tenants_deterministic_and_skewed(self):
        base = self._base()
        w1 = rp.warp_zipf_tenants(base, n_tenants=6, s=1.5, seed=4)
        w2 = rp.warp_zipf_tenants(base, n_tenants=6, s=1.5, seed=4)
        assert w1["rows"] == w2["rows"]
        assert rp.warp_zipf_tenants(base, n_tenants=6, s=1.5,
                                    seed=5)["rows"] != w1["rows"]
        counts = Counter(r["tenant"] for r in w1["rows"])
        assert set(counts) <= {f"tenant-{k}" for k in range(6)}
        # Zipf head dominates the tail
        assert counts["tenant-0"] == max(counts.values())

    def test_diurnal_preserves_count_and_order(self):
        base = self._base()
        w = rp.warp_diurnal(base, depth=0.8)
        assert w["rows"] == rp.warp_diurnal(base, depth=0.8)["rows"]
        assert w["count"] == base["count"]
        offs = [r["arrival_offset_s"] for r in w["rows"]]
        assert offs == sorted(offs)
        # the re-timing actually moved arrivals
        assert offs != [r["arrival_offset_s"] for r in base["rows"]]

    def test_flash_crowd_compresses_the_window(self):
        base = self._base()
        w = rp.warp_flash_crowd(base, at_frac=0.5, width_frac=0.4,
                                magnitude=10.0)
        assert w["count"] == base["count"]
        assert w["duration_s"] < base["duration_s"]
        offs = [r["arrival_offset_s"] for r in w["rows"]]
        assert offs == sorted(offs)

    def test_duplicate_burst_appends_identical_rows(self):
        base = self._base()
        w = rp.warp_duplicate_burst(base, frac=0.5, copies=2,
                                    lag_s=0.01, seed=9)
        assert w["rows"] == rp.warp_duplicate_burst(
            base, frac=0.5, copies=2, lag_s=0.01, seed=9)["rows"]
        assert w["count"] > base["count"]
        # every added row is a byte-identical twin of an original
        # except its arrival time
        originals = {json.dumps({k: v for k, v in r.items()
                                 if k != "arrival_offset_s"},
                                sort_keys=True)
                     for r in base["rows"]}
        for r in w["rows"]:
            key = json.dumps({k: v for k, v in r.items()
                              if k != "arrival_offset_s"},
                             sort_keys=True)
            assert key in originals

    @pytest.mark.parametrize("fn, kw", [
        (rp.warp_zipf_tenants, {"n_tenants": 0}),
        (rp.warp_diurnal, {"depth": 1.5}),
        (rp.warp_flash_crowd, {"magnitude": 0.0}),
        (rp.warp_duplicate_burst, {"frac": 2.0}),
    ])
    def test_warp_parameter_validation(self, fn, kw):
        with pytest.raises(ValueError):
            fn(self._base(), **kw)


# ---------------------------------------------------------------------------
# ledger-level export: scrub, windowing, generation shape derivation


class TestLedgerExport:
    def _ledger_with_traffic(self):
        led = rl.RequestLedger(capacity=64)
        led.begin("p1", plane="predict", model="scale",
                  priority="critical", tenant="t0",
                  inputs=[[1.0, 2.0, 3.0, 4.0]])  # payload NEVER exported
        led.annotate("p1", payload_shape=[1, 4], deadline_s=5.0,
                     stream=False)
        led.finish("p1", outcome="ok", status=200)
        led.begin("g1", plane="generation", model="gpt",
                  priority="normal", tenant="t1", prompt_len=6,
                  max_new_tokens=4, prompt=[1, 2, 3, 4, 5, 6])
        led.annotate("g1", deadline_s=10.0, stream=True)
        led.finish("g1", outcome="ok", status=200)
        return led

    def test_rows_are_scrubbed_to_the_declared_fields(self):
        trace = self._ledger_with_traffic().export_trace()
        assert trace["kind"] == "dl4j_tpu_trace"
        assert trace["version"] == rl.TRACE_VERSION
        assert trace["count"] == 2
        for row in trace["rows"]:
            assert set(row) <= set(rl.TRACE_ROW_FIELDS)
            blob = json.dumps(row)
            assert "prompt" not in blob and "inputs" not in blob

    def test_generation_rows_derive_shape_from_prompt_len(self):
        trace = self._ledger_with_traffic().export_trace(
            plane="generation")
        assert trace["count"] == 1
        (row,) = trace["rows"]
        assert row["payload_shape"] == [6]
        assert row["max_new_tokens"] == 4
        assert row["stream"] is True
        assert row["tenant"] == "t1"

    def test_records_carry_absolute_wall_arrival(self):
        led = self._ledger_with_traffic()
        rec = led.get("p1")
        assert abs(rec["t_wall"] - time.time()) < 60.0
        # and the exported document anchors to it
        trace = led.export_trace()
        assert abs(trace["t0_wall"] - rec["t_wall"]) < 60.0

    def test_window_and_limit_filters(self):
        led = self._ledger_with_traffic()
        assert led.export_trace(window_s=0.0)["count"] == 0
        assert led.export_trace(limit=1)["count"] == 1
        # limit keeps the NEWEST arrival
        assert led.export_trace(limit=1)["rows"][0]["model"] == "gpt"
        assert led.export_trace(model="scale")["count"] == 1

    def test_offsets_rebase_to_the_first_kept_arrival(self):
        trace = self._ledger_with_traffic().export_trace(
            plane="generation")
        assert trace["rows"][0]["arrival_offset_s"] == 0.0


# ---------------------------------------------------------------------------
# gate math on synthetic client ledgers (no server)


def _res(idx, *, outcome="ok", priority="normal", t_send=0.0,
         latency=0.01):
    return {"idx": idx, "cid": f"r-{idx}", "plane": "predict",
            "model": "m", "priority": priority, "tenant": None,
            "outcome": outcome, "status": 200 if outcome == "ok" else 503,
            "latency_s": latency, "t_send": t_send,
            "t_done": t_send + latency, "send_lag_s": 0.0,
            "tokens": 0, "attempts": 1, "error": None}


class TestGateMath:
    def test_summarize_counts_and_percentiles(self):
        results = [_res(i, latency=0.01 * (i + 1)) for i in range(100)]
        results[3] = _res(3, outcome="shed", priority="critical")
        s = rp.summarize(results)
        assert s["requests"] == 100
        assert s["ok"] == 99
        assert s["availability"] == 0.99
        assert s["by_outcome"] == {"ok": 99, "shed": 1}
        # 99 sorted ok-latencies; ceil-index: p50 -> 50th, p99 -> 99th
        lats = sorted(r["latency_s"] for r in results
                      if r["outcome"] == "ok")
        assert s["latency_p50_s"] == round(lats[49], 6)
        assert s["latency_p99_s"] == round(lats[98], 6)
        assert [r["idx"] for r in s["critical_failures"]] == [3]

    def test_first_success_after(self):
        results = [_res(0, t_send=0.0), _res(1, outcome="error",
                                             t_send=5.0),
                   _res(2, t_send=7.0, latency=0.5)]
        assert rp.first_success_after(results, 1.0) == pytest.approx(6.5)
        assert rp.first_success_after(results, 8.0) is None

    def test_gate_critical_failures_and_availability(self):
        results = [_res(i) for i in range(10)]
        acts, fleet = [], {}
        g = gd.Gate("critical_failures")
        assert g.evaluate(results, acts, fleet)["passed"] is True
        results[0] = _res(0, outcome="shed", priority="critical")
        v = g.evaluate(results, acts, fleet)
        assert v["passed"] is False and v["value"] == 1
        v = gd.Gate("availability", min_ratio=0.95).evaluate(
            results, acts, fleet)
        assert v["passed"] is False and v["value"] == 0.9

    def test_gate_scope_filters_from_the_act_onward(self):
        # the pre-kill shed is outside a kill-scoped gate's window
        results = [_res(0, outcome="shed", t_send=1.0),
                   _res(1, t_send=3.0), _res(2, t_send=4.0)]
        act = gd.Act(2.0, "kill", name="kill-b1", fn=lambda: None)
        act.t_fired = 2.0
        g = gd.Gate("availability", scope="kill-b1", min_ratio=1.0)
        assert g.evaluate(results, [act], {})["passed"] is True
        g_run = gd.Gate("availability", min_ratio=1.0)
        assert g_run.evaluate(results, [act], {})["passed"] is False

    def test_gate_mttr_anchors_to_the_kill_act(self):
        act = gd.Act(0.0, "kill", name="k", fn=lambda: None)
        act.t_fired = 10.0
        results = [_res(0, t_send=12.0, latency=0.5)]
        v = gd.Gate("mttr", max_s=5.0).evaluate(results, [act], {})
        assert v["passed"] is True and v["value"] == pytest.approx(2.5)
        v = gd.Gate("mttr", max_s=1.0).evaluate(results, [act], {})
        assert v["passed"] is False
        # no kill act at all → the gate fails loudly, not silently
        v = gd.Gate("mttr").evaluate(results, [], {})
        assert v["passed"] is False

    def test_gate_recompiles_reads_the_fleet_scrape(self):
        g = gd.Gate("recompiles", max_count=0)
        ok = {"warmup_recompiles_after_warm_total": 0.0}
        bad = {"warmup_recompiles_after_warm_total": 2.0}
        assert g.evaluate([], [], ok)["passed"] is True
        assert g.evaluate([], [], bad)["passed"] is False
        assert g.evaluate([], [], {})["passed"] is False

    def test_act_and_gate_validation(self):
        with pytest.raises(ValueError, match="unknown act kind"):
            gd.Act(0.0, "meteor")
        with pytest.raises(ValueError, match="needs spec"):
            gd.Act(0.0, "fault")
        with pytest.raises(ValueError, match="needs fn"):
            gd.Act(0.0, "kill")
        with pytest.raises(ValueError, match="needs backend"):
            gd.Act(0.0, "drain")
        with pytest.raises(ValueError, match="unknown gate kind"):
            gd.Gate("vibes")

    def test_driver_parameter_validation(self):
        trace = _trace_of([_row(0.0)])
        with pytest.raises(ValueError, match="speed"):
            rp.ReplayDriver("http://x", trace, speed=0.0)
        with pytest.raises(ValueError, match="speed"):
            rp.ReplayDriver("http://x", trace, speed=rp.MAX_SPEED + 1)
        with pytest.raises(ValueError, match="clients"):
            rp.ReplayDriver("http://x", trace, clients=0)

    def test_synth_inputs_shapes(self):
        flat = rp._synth_inputs([2, 3], None)
        assert flat == [[0.0] * 3] * 2
        named = rp._synth_inputs({"x": [1, 2]}, None)
        assert named == {"x": [[0.0, 0.0]]}
        with pytest.raises(ValueError, match="no payload_shape"):
            rp._synth_inputs(None, None)


# ---------------------------------------------------------------------------
# live round-trip: record -> export over HTTP -> replay -> same mix


class TestRoundTrip:
    def test_record_export_replay_same_mix(self, mixed_server):
        """Satellite acceptance: traffic recorded by the ledger, exported
        as a trace, and replayed lands the SAME plane/priority/tenant
        mix back on the server — the trace is a faithful, scrubbed
        recording, not a lossy sketch."""
        url = f"http://127.0.0.1:{mixed_server.port}"
        c = ServingClient(url, max_retries=2)
        x = [[0.0, 0.0, 0.0, 0.0]]
        sent = []
        for prio, tenant in (("critical", "rt-a"), ("normal", "rt-a"),
                             ("normal", "rt-b")):
            c.predict("scale", x, priority=prio, tenant=tenant,
                      deadline_ms=15000)
            sent.append(("predict", prio, tenant))
        out = c.generate_tokens("gpt", [1, 2, 3, 4], max_new_tokens=3,
                                priority="normal", tenant="rt-a",
                                deadline_ms=20000)
        assert out["tokens"]
        sent.append(("generation", "normal", "rt-a"))
        tokens = list(c.generate("gpt", [1, 2, 3], max_new_tokens=3,
                                 priority="critical", tenant="rt-b",
                                 deadline_ms=20000))
        assert tokens
        sent.append(("generation", "critical", "rt-b"))

        status, doc = _get(f"{url}/debug/requests?format=trace")
        assert status == 200
        rows = [r for r in doc["rows"] if r["tenant"] in ("rt-a", "rt-b")]
        assert len(rows) == 5
        base = rows[0]["arrival_offset_s"]
        for r in rows:  # rebase: replay immediately, not after the
            r["arrival_offset_s"] = round(        # module's whole history
                r["arrival_offset_s"] - base, 6)
        trace = _trace_of(rows)
        # generation rows survived with wire mode + token budget intact
        gen = [r for r in rows if r["plane"] == "generation"]
        assert {r["stream"] for r in gen} == {False, True}
        assert all(r["max_new_tokens"] == 3 for r in gen)
        assert all(r["payload_shape"] in ([4], [3]) for r in gen)

        summary = rp.ReplayDriver(url, trace, speed=10.0,
                                  clients=3).run()
        assert summary["ok"] == 5, summary["by_outcome"]
        replayed = Counter((r["plane"], r["priority"], r["tenant"])
                           for r in summary["results"])
        assert replayed == Counter(sent)
        # the streamed row streamed again (tokens drained client-side)
        streamed = [r for r in summary["results"]
                    if r["plane"] == "generation" and r["tokens"]]
        assert streamed

    def test_replay_emits_flight_trail_and_metrics(self, mixed_server):
        url = f"http://127.0.0.1:{mixed_server.port}"
        trace = _trace_of([_row(0.0, tenant="fm-a"),
                           _row(0.05, tenant="fm-a")])
        m = rp.get_replay_metrics()
        before = m.requests_total.value(plane="predict", outcome="ok")
        runs_before = m.runs_total.value()
        rp.ReplayDriver(url, trace, speed=10.0, clients=2).run()
        assert m.requests_total.value(
            plane="predict", outcome="ok") == before + 2
        assert m.runs_total.value() == runs_before + 1
        kinds = [e["kind"] for e in get_flight_recorder().events(
            kinds=("replay.start", "replay.complete"), max_events=50)]
        assert "replay.start" in kinds and "replay.complete" in kinds


# ---------------------------------------------------------------------------
# open-loop arrival fidelity at 1x


class TestArrivalFidelity:
    def test_dispatch_tracks_recorded_offsets_at_1x(self, mixed_server):
        """Open-loop: each request leaves the driver at its recorded
        offset (tolerance covers scheduler jitter, not drift), and the
        measured send lag is reported rather than hidden."""
        url = f"http://127.0.0.1:{mixed_server.port}"
        offsets = [0.0, 0.3, 0.6, 0.9]
        trace = _trace_of([_row(o, tenant="af") for o in offsets])
        drv = rp.ReplayDriver(url, trace, speed=1.0, clients=4)
        summary = drv.run()
        assert summary["ok"] == 4
        t0 = drv.t_run0
        for r, off in zip(summary["results"], offsets):
            assert r["t_send"] - t0 == pytest.approx(off, abs=0.25)
            assert r["send_lag_s"] < 0.25
        # and the run took about as long as the recording
        assert 0.85 <= summary["results"][-1]["t_send"] - t0 <= 1.6

    def test_speed_compresses_wall_time(self, mixed_server):
        url = f"http://127.0.0.1:{mixed_server.port}"
        offsets = [0.0, 0.4, 0.8, 1.2, 1.6, 2.0]
        trace = _trace_of([_row(o, tenant="sp") for o in offsets])
        t0 = time.monotonic()
        summary = rp.ReplayDriver(url, trace, speed=10.0,
                                  clients=3).run()
        wall = time.monotonic() - t0
        assert summary["ok"] == 6
        # 2.0 s of recording at 10x ≈ 0.2 s of dispatching
        assert wall < 1.5
