"""Tests for the data/RL tail readers (VERDICT r3 next-round #7):
Arrow IPC reader (pyarrow-written files decoded by the dependency-free
reader), GeoJSON point reader + coordinate transforms, and the ALE-style
frame-stack connector."""

import json

import numpy as np
import pytest

from deeplearning4j_tpu.data.arrow import (ArrowRecordReader,
                                           read_arrow_file,
                                           read_arrow_stream)
from deeplearning4j_tpu.data.geo import (CoordinatesDistanceTransform,
                                         GeoJsonPointReader,
                                         IPAddressToCoordinatesTransform,
                                         haversine_m, parse_point)
from deeplearning4j_tpu.data.transform import Schema, TransformProcess
from deeplearning4j_tpu.rl.history import (FrameStackEnv, HistoryProcessor,
                                           SyntheticFrameEnv,
                                           resize_bilinear, to_grayscale)

# pyarrow is only the GROUND-TRUTH WRITER for the Arrow decoder tests; the
# geo/transform/RL tests below must keep running without it, so the skip is
# scoped to this fixture rather than the module.
pa = None
try:
    import pyarrow as pa  # noqa: N816
except ImportError:
    pass

needs_pyarrow = pytest.mark.skipif(
    pa is None, reason="pyarrow (oracle writer) unavailable")


# ---------------------------------------------------------------------------
# Arrow: the hand-written decoder vs pyarrow-written ground truth
# ---------------------------------------------------------------------------

def _write_table(path, table):
    import pyarrow.ipc

    with pa.ipc.new_file(path, table.schema) as w:
        w.write_table(table)


@needs_pyarrow
def test_arrow_file_primitives(tmp_path):
    t = pa.table({
        "i32": pa.array([1, -2, 3], pa.int32()),
        "i64": pa.array([10, 20, 30], pa.int64()),
        "u8": pa.array([0, 128, 255], pa.uint8()),
        "f32": pa.array([1.5, -2.5, 0.0], pa.float32()),
        "f64": pa.array([1e-8, 2.0, -3.25], pa.float64()),
        "b": pa.array([True, False, True]),
        "s": pa.array(["alpha", "", "γamma"]),
    })
    p = tmp_path / "t.arrow"
    _write_table(p, t)

    cols = read_arrow_file(p)
    assert set(cols) == {"i32", "i64", "u8", "f32", "f64", "b", "s"}
    np.testing.assert_array_equal(cols["i32"], [1, -2, 3])
    assert cols["i32"].dtype == np.int32
    np.testing.assert_array_equal(cols["i64"], [10, 20, 30])
    np.testing.assert_array_equal(cols["u8"], [0, 128, 255])
    assert cols["u8"].dtype == np.uint8
    np.testing.assert_allclose(cols["f32"], [1.5, -2.5, 0.0])
    np.testing.assert_allclose(cols["f64"], [1e-8, 2.0, -3.25])
    np.testing.assert_array_equal(cols["b"], [True, False, True])
    assert list(cols["s"]) == ["alpha", "", "γamma"]


@needs_pyarrow
def test_arrow_multiple_batches_and_nulls(tmp_path):
    import pyarrow.ipc

    schema = pa.schema([("x", pa.float64()), ("name", pa.string())])
    p = tmp_path / "m.arrow"
    with pa.ipc.new_file(p, schema) as w:
        w.write_batch(pa.record_batch(
            [pa.array([1.0, None]), pa.array(["a", None])], schema=schema))
        w.write_batch(pa.record_batch(
            [pa.array([3.0]), pa.array(["c"])], schema=schema))
    cols = read_arrow_file(p)
    assert len(cols["x"]) == 3
    assert cols["x"][0] == 1.0 and np.isnan(cols["x"][1]) and cols["x"][2] == 3.0
    assert list(cols["name"]) == ["a", None, "c"]


@needs_pyarrow
def test_arrow_stream_roundtrip():
    import pyarrow.ipc

    t = pa.table({"a": pa.array(np.arange(100, dtype=np.int64)),
                  "b": pa.array(np.linspace(0, 1, 100))})
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, t.schema) as w:
        w.write_table(t)
    cols = read_arrow_stream(sink.getvalue().to_pybytes())
    np.testing.assert_array_equal(cols["a"], np.arange(100))
    np.testing.assert_allclose(cols["b"], np.linspace(0, 1, 100))


@needs_pyarrow
def test_arrow_record_reader_and_pyarrow_path_agree(tmp_path):
    t = pa.table({"x": pa.array([1.0, 2.0]), "y": pa.array(["u", "v"])})
    p = tmp_path / "r.arrow"
    _write_table(p, t)

    rr = ArrowRecordReader().initialize(p)
    assert rr.column_names == ["x", "y"]
    rows = list(rr)
    assert rows[0][0] == 1.0 and rows[0][1] == "u"
    assert rows[1][0] == 2.0 and rows[1][1] == "v"
    rr.reset()
    assert rr.has_next()

    via_pa = ArrowRecordReader(use_pyarrow=True).initialize(p)
    assert [list(map(str, r)) for r in via_pa] == \
        [list(map(str, r)) for r in rows]


@needs_pyarrow
def test_arrow_unsupported_types_raise(tmp_path):
    t = pa.table({"l": pa.array([[1, 2], [3]], pa.list_(pa.int32()))})
    p = tmp_path / "l.arrow"
    _write_table(p, t)
    with pytest.raises(ValueError, match="unsupported"):
        read_arrow_file(p)
    with pytest.raises(ValueError, match="magic"):
        bad = tmp_path / "bad.arrow"
        bad.write_bytes(b"not arrow")
        read_arrow_file(bad)


# ---------------------------------------------------------------------------
# Geo
# ---------------------------------------------------------------------------

def test_parse_point_and_haversine():
    assert parse_point("48.85:2.35") == [48.85, 2.35]
    assert parse_point([1, 2.5]) == [1.0, 2.5]
    # Paris -> London ≈ 344 km
    d = haversine_m(48.8566, 2.3522, 51.5074, -0.1278)
    assert 330_000 < d < 350_000
    assert haversine_m(10.0, 20.0, 10.0, 20.0) == 0.0


def test_coordinates_distance_transform():
    schema = (Schema().add_string_column("a").add_string_column("b"))
    records = [["0:0", "3:4"], ["1:1", "1:1"]]
    tp = TransformProcess(schema).add(
        CoordinatesDistanceTransform("dist", "a", "b"))
    out = tp.execute(records)
    assert out[0][-1] == pytest.approx(5.0)
    assert out[1][-1] == 0.0
    assert tp.final_schema.names()[-1] == "dist"

    hav = CoordinatesDistanceTransform("d", "a", "b", metric="haversine")
    got = hav.apply([["48.8566:2.3522", "51.5074:-0.1278"]], schema)
    assert 330_000 < got[0][-1] < 350_000


def test_geoip_transform_refuses_clearly():
    schema = Schema().add_string_column("ip")
    t = IPAddressToCoordinatesTransform("ip")
    with pytest.raises(RuntimeError, match="MaxMind"):
        t.apply([["8.8.8.8"]], schema)


def test_geojson_point_reader(tmp_path):
    doc = {
        "type": "FeatureCollection",
        "features": [
            {"type": "Feature",
             "geometry": {"type": "Point", "coordinates": [2.35, 48.85]},
             "properties": {"name": "paris", "pop": "2M"}},
            {"type": "Feature",
             "geometry": {"type": "LineString",
                          "coordinates": [[0, 0], [1, 1]]},
             "properties": {"name": "skipme"}},
            {"type": "Feature",
             "geometry": {"type": "Point", "coordinates": [-0.13, 51.51]},
             "properties": {"name": "london"}},
        ],
    }
    p = tmp_path / "pts.geojson"
    p.write_text(json.dumps(doc))
    rd = GeoJsonPointReader().initialize(p)
    rows = list(rd)
    assert len(rows) == 2  # line skipped
    assert rows[0][:2] == [2.35, 48.85]
    assert rows[0][2] == "paris" and rows[0][3] == "2M"
    assert rows[1][2] == "london" and rows[1][3] is None
    assert rd.schema().names() == ["lon", "lat", "name", "pop"]

    with pytest.raises(ValueError, match="non-Point"):
        GeoJsonPointReader(strict=True).initialize(p)


# ---------------------------------------------------------------------------
# ALE-style connector
# ---------------------------------------------------------------------------

def test_grayscale_and_resize():
    rgb = np.zeros((4, 4, 3), np.uint8)
    rgb[..., 1] = 255  # pure green
    g = to_grayscale(rgb)
    np.testing.assert_allclose(g, 0.587 * 255, rtol=1e-6)
    # constant image stays constant under resize
    r = resize_bilinear(np.full((30, 40), 7.0), (84, 84))
    assert r.shape == (84, 84)
    np.testing.assert_allclose(r, 7.0, rtol=1e-6)
    # upscale of a gradient stays monotone along the gradient axis
    grad = np.tile(np.arange(10.0), (10, 1))
    up = resize_bilinear(grad, (20, 20))
    assert (np.diff(up, axis=1) >= -1e-6).all()


def test_history_processor_stack_order():
    hp = HistoryProcessor(stack=3, size=(8, 8), scale=1.0)
    hp.add(np.full((16, 16), 1.0))
    h = hp.history()
    assert h.shape == (3, 8, 8)
    np.testing.assert_allclose(h[0], 0.0)   # zero-padded oldest
    np.testing.assert_allclose(h[2], 1.0)   # newest last
    hp.add(np.full((16, 16), 2.0))
    hp.add(np.full((16, 16), 3.0))
    hp.add(np.full((16, 16), 4.0))          # rolls the 1.0 frame out
    h = hp.history()
    np.testing.assert_allclose(h[:, 0, 0], [2.0, 3.0, 4.0])
    hp.reset()
    with pytest.raises(RuntimeError):
        hp.history()


def test_frame_stack_env_episode():
    env = FrameStackEnv(SyntheticFrameEnv(episode_len=10),
                        stack=4, skip=4, size=(84, 84))
    obs = env.reset()
    assert obs.shape == (4, 84, 84)
    assert obs.dtype == np.float32
    assert 0.0 <= obs.min() and obs.max() <= 1.0
    total, steps = 0.0, 0
    done = False
    while not done:
        obs, r, done, _ = env.step(1)
        total += r
        steps += 1
        assert obs.shape == (4, 84, 84)
    # skip=4 over a 10-step episode → 3 agent steps; rewards accumulated
    assert steps == 3
    assert total > 0


def test_frame_stack_env_feeds_dqn_shapes():
    # the connector's observation is directly consumable as a flat feature
    env = FrameStackEnv(SyntheticFrameEnv(), stack=2, skip=2, size=(10, 10))
    obs = env.reset()
    flat = obs.reshape(-1)
    assert flat.shape == (200,)
