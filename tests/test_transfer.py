"""Transfer learning tests (VERDICT r2 Weak #3 / round-1 task #5 bar).

ref strategy: deeplearning4j-core TransferLearning*Test — surgery on a
trained net, frozen-prefix fine-tune, weight carry-over, nOutReplace.
The hard assertions: frozen params stay BIT-identical through fine-tuning,
the new head actually learns, and Adam moments of frozen layers stay zero
(gradients were masked before the updater, not after).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.lenet import lenet
from deeplearning4j_tpu.nn.layers import OutputLayer
from deeplearning4j_tpu.train.trainer import Trainer
from deeplearning4j_tpu.train.transfer import (
    FineTuneConfiguration,
    TransferLearning,
    TransferLearningHelper,
)
from deeplearning4j_tpu.train.updaters import Adam


def _tiny_batch(n=16, num_classes=5, seed=0):
    r = np.random.default_rng(seed)
    x = r.normal(size=(n, 28, 28, 1)).astype(np.float32)
    y = np.eye(num_classes, dtype=np.float32)[np.arange(n) % num_classes]
    return {"features": jnp.asarray(x), "labels": jnp.asarray(y)}


@pytest.fixture(scope="module")
def pretrained():
    """A briefly-trained LeNet standing in for a zoo checkpoint."""
    model = lenet()
    trainer = Trainer(model)
    ts = trainer.init_state(seed=0)
    r = np.random.default_rng(1)
    batch = {
        "features": jnp.asarray(r.normal(size=(16, 28, 28, 1)).astype(np.float32)),
        "labels": jnp.asarray(np.eye(10, dtype=np.float32)[np.arange(16) % 10]),
    }
    for _ in range(3):
        ts, _ = trainer.train_step(ts, batch)
    return model, {"params": jax.device_get(ts.params),
                   "state": jax.device_get(ts.model_state)}


def _surgery(model, variables, num_classes=5):
    feature_boundary = model.layer_names[-2]  # dense under the old head
    tl = (TransferLearning(model, variables)
          .fine_tune_configuration(FineTuneConfiguration(updater=Adam(1e-2)))
          .set_feature_extractor(feature_boundary)
          .remove_last_layers(1)
          .add_layer(OutputLayer(units=num_classes, activation="softmax",
                                 loss="mcxent")))
    return tl.build(seed=7)


class TestTransferLearningBuilder:
    def test_weights_carry_over(self, pretrained):
        model, variables = pretrained
        new_model, new_vars, frozen = _surgery(model, variables)
        # every retained layer's params are the pretrained values, verbatim
        for name in new_model.layer_names[:-1]:
            if name not in variables["params"]:
                continue
            old = variables["params"][name]
            new = new_vars["params"][name]
            for k in old:
                np.testing.assert_array_equal(np.asarray(old[k]),
                                              np.asarray(new[k]))
        # the fresh head exists with the new width
        head = new_vars["params"][new_model.layer_names[-1]]
        assert head["W"].shape[-1] == 5

    def test_frozen_list_covers_prefix(self, pretrained):
        model, variables = pretrained
        new_model, _, frozen = _surgery(model, variables)
        # all parameterized layers up to and incl. the boundary are frozen
        assert frozen  # non-empty
        boundary = len(new_model.layer_names) - 2
        for name in frozen:
            assert new_model.layer_names.index(name) <= boundary
        assert new_model.layer_names[-1] not in frozen

    def test_fine_tune_config_overrides(self, pretrained):
        model, variables = pretrained
        new_model, _, _ = _surgery(model, variables)
        assert isinstance(new_model.net.updater, Adam)
        assert float(new_model.net.updater.lr) == pytest.approx(1e-2)

    def test_n_out_replace(self, pretrained):
        model, variables = pretrained
        tl = TransferLearning(model, variables)
        tl.n_out_replace(model.layer_names[-1], 3)
        new_model, new_vars, _ = tl.build(seed=3)
        head = new_vars["params"][new_model.layer_names[-1]]
        assert head["W"].shape[-1] == 3


class TestFrozenFineTune:
    def test_frozen_backbone_fine_tune(self, pretrained):
        """The round-1 'done' bar: frozen layers bit-identical, head learns,
        frozen Adam moments stay exactly zero."""
        model, variables = pretrained
        new_model, new_vars, frozen = _surgery(model, variables)

        trainer = Trainer(new_model, frozen_layers=frozen)
        ts = trainer.init_state(variables=new_vars)
        before = jax.device_get(ts.params)

        batch = _tiny_batch()
        losses = []
        for _ in range(30):
            ts, metrics = trainer.train_step(ts, batch)
            losses.append(float(jax.device_get(metrics["total_loss"])))

        after = jax.device_get(ts.params)

        # 1. frozen layers: BIT-identical
        for name in frozen:
            for k in before[name]:
                np.testing.assert_array_equal(
                    np.asarray(before[name][k]), np.asarray(after[name][k]),
                    err_msg=f"frozen layer {name}/{k} moved")

        # 2. the head learned: loss dropped substantially on the fixed batch
        assert losses[-1] < losses[0] * 0.7, losses

        # 3. head params actually moved
        head = new_model.layer_names[-1]
        assert any(
            not np.array_equal(np.asarray(before[head][k]),
                               np.asarray(after[head][k]))
            for k in before[head])

        # 4. Adam moments of frozen layers are exactly zero (grads masked
        #    BEFORE the updater, so no moment leakage)
        opt = jax.device_get(ts.opt_state)
        for moment in ("m", "v"):
            for name in frozen:
                for k, v in opt[moment][name].items():
                    assert not np.any(np.asarray(v)), \
                        f"Adam {moment} of frozen {name}/{k} non-zero"
        # and the head's second moment is non-zero (it did train)
        assert any(np.any(np.asarray(v)) for v in opt["v"][head].values())


class TestTransferLearningHelper:
    def test_featurize_matches_full_forward(self, pretrained):
        model, variables = pretrained
        boundary = model.layer_names[-3]
        helper = TransferLearningHelper(model, variables, boundary)
        x = _tiny_batch(n=4)["features"]

        feats = helper.featurize(x)
        tail, tail_vars = helper.unfrozen_graph()
        tail_out, _ = tail.apply(tail_vars, feats, up_to=len(tail.layers) - 1)

        full_out, _ = model.apply(variables, x, up_to=len(model.layers) - 1)
        np.testing.assert_allclose(np.asarray(tail_out), np.asarray(full_out),
                                   rtol=1e-5, atol=1e-5)


# --- GraphTransferLearning (round 3: ComputationGraph transfer path) --------


class TestGraphTransferLearning:
    def _tiny_graph(self):
        """input -> conv -> pool -> dense -> output (as a DAG)."""
        import jax

        from deeplearning4j_tpu.nn import layers as L
        from deeplearning4j_tpu.nn.config import (
            GraphConfig,
            GraphVertex,
            NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.model import GraphModel

        v = {
            "conv": GraphVertex(kind="layer", inputs=["input"],
                                layer=L.Conv2D(filters=4, kernel=3,
                                               activation="relu")),
            "pool": GraphVertex(kind="layer", inputs=["conv"],
                                layer=L.GlobalPooling()),
            "dense": GraphVertex(kind="layer", inputs=["pool"],
                                 layer=L.Dense(units=8, activation="relu")),
            "output": GraphVertex(kind="layer", inputs=["dense"],
                                  layer=L.OutputLayer(units=10)),
        }
        cfg = GraphConfig(net=NeuralNetConfiguration(seed=0),
                          inputs=["input"],
                          input_shapes={"input": (8, 8, 3)},
                          vertices=v, outputs=["output"])
        m = GraphModel(cfg)
        return m, m.init()

    def test_nout_replace_and_freeze(self):
        import numpy as np

        from deeplearning4j_tpu.train.transfer import (
            FineTuneConfiguration,
            GraphTransferLearning,
        )
        from deeplearning4j_tpu.train.updaters import Adam

        model, variables = self._tiny_graph()
        gtl = (GraphTransferLearning(model, variables)
               .fine_tune_configuration(FineTuneConfiguration(updater=Adam(1e-3)))
               .set_feature_extractor("dense")
               .n_out_replace("output", 5))
        new_model, new_vars, frozen = gtl.build()
        assert frozen == ["conv", "dense"]
        # carried weights are identical; replaced head is fresh 5-wide
        np.testing.assert_array_equal(
            np.asarray(new_vars["params"]["conv"]["W"]),
            np.asarray(variables["params"]["conv"]["W"]))
        assert new_vars["params"]["output"]["W"].shape == (8, 5)
        out = new_model.output(new_vars, np.zeros((2, 8, 8, 3), np.float32))
        assert out["output"].shape == (2, 5)

    def test_frozen_training_keeps_backbone(self):
        import numpy as np

        from deeplearning4j_tpu.train.trainer import Trainer
        from deeplearning4j_tpu.train.transfer import GraphTransferLearning
        from deeplearning4j_tpu.train.updaters import Adam

        model, variables = self._tiny_graph()
        gtl = (GraphTransferLearning(model, variables)
               .set_feature_extractor("dense")
               .n_out_replace("output", 3))
        new_model, new_vars, frozen = gtl.build()
        new_model.net.updater = Adam(1e-2)
        # snapshot BEFORE training: train_step donates the state buffers
        conv_before = np.asarray(new_vars["params"]["conv"]["W"]).copy()
        head_before = np.asarray(new_vars["params"]["output"]["W"]).copy()
        tr = Trainer(new_model, frozen_layers=frozen)
        ts = tr.init_state(variables=new_vars)
        r = np.random.default_rng(0)
        batch = {"features": r.normal(size=(8, 8, 8, 3)).astype(np.float32),
                 "labels": np.eye(3, dtype=np.float32)[r.integers(0, 3, 8)]}
        for _ in range(5):
            ts, m = tr.train_step(ts, batch)
        after = tr.variables(ts)["params"]
        np.testing.assert_array_equal(np.asarray(after["conv"]["W"]),
                                      conv_before)
        assert not np.allclose(np.asarray(after["output"]["W"]), head_before)

    def test_remove_vertex_and_add_new_head(self):
        import numpy as np

        from deeplearning4j_tpu.nn import layers as L
        from deeplearning4j_tpu.nn.config import GraphVertex
        from deeplearning4j_tpu.train.transfer import GraphTransferLearning

        model, variables = self._tiny_graph()
        gtl = (GraphTransferLearning(model, variables)
               .remove_vertex("dense")  # drops dense AND output
               .add_vertex("newhead", GraphVertex(
                   kind="layer", inputs=["pool"],
                   layer=L.OutputLayer(units=2)))
               .set_outputs("newhead"))
        new_model, new_vars, _ = gtl.build()
        out = new_model.output(new_vars, np.zeros((2, 8, 8, 3), np.float32))
        assert out["newhead"].shape == (2, 2)

    def test_zoo_resnet_surgery(self):
        """The reference's canonical use: re-head a zoo ResNet."""
        import numpy as np

        from deeplearning4j_tpu.models.zoo import resnet50
        from deeplearning4j_tpu.train.transfer import GraphTransferLearning

        model = resnet50(num_classes=10, input_shape=(32, 32, 3))
        variables = model.init(seed=0)
        gtl = (GraphTransferLearning(model, variables)
               .set_feature_extractor("avgpool")
               .n_out_replace("output", 4))
        new_model, new_vars, frozen = gtl.build()
        assert "avgpool" not in frozen  # pooling has no params
        assert "output" not in frozen  # the fresh head is trainable
        assert len(frozen) > 30  # every conv/bn vertex upstream
        out = new_model.output(new_vars, np.zeros((1, 32, 32, 3), np.float32))
        assert out["output"].shape == (1, 4)


    def test_nout_replace_midgraph_reinitializes_downstream(self):
        """nOutReplace on a non-terminal vertex: downstream vertices whose
        input width changed must re-init, not carry stale-shaped weights
        (DL4J's nOutReplace nIn rule; r3 review)."""
        import numpy as np

        from deeplearning4j_tpu.train.transfer import GraphTransferLearning

        model, variables = self._tiny_graph()
        gtl = GraphTransferLearning(model, variables).n_out_replace("dense", 16)
        new_model, new_vars, _ = gtl.build()
        assert new_vars["params"]["dense"]["W"].shape == (4, 16)
        assert new_vars["params"]["output"]["W"].shape == (16, 10)
        out = new_model.output(new_vars, np.zeros((2, 8, 8, 3), np.float32))
        assert out["output"].shape == (2, 10)

    def test_remove_vertex_validation_leaves_builder_intact(self):
        from deeplearning4j_tpu.train.transfer import GraphTransferLearning

        model, variables = self._tiny_graph()
        gtl = GraphTransferLearning(model, variables)
        with pytest.raises(ValueError, match="missing inputs"):
            gtl.remove_vertex("dense", and_descendants=False)
        # builder unchanged: a valid edit still works
        assert "dense" in gtl._vertices
        new_model, new_vars, _ = gtl.n_out_replace("output", 2).build()
        assert new_vars["params"]["output"]["W"].shape[-1] == 2


    def test_build_requires_outputs(self):
        from deeplearning4j_tpu.train.transfer import GraphTransferLearning

        model, variables = self._tiny_graph()
        gtl = GraphTransferLearning(model, variables).remove_vertex("dense")
        with pytest.raises(ValueError, match="no outputs"):
            gtl.build()


def test_sequential_remove_all_layers_raises(pretrained):
    model, variables = pretrained
    tl = TransferLearning(model, variables).remove_last_layers(
        len(model.layers))
    with pytest.raises(ValueError, match="no layers"):
        tl.build()
