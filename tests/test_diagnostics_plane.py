"""Diagnostics plane tests: flight recorder, crash-report timeline,
ModelServer /debug/* endpoints, and the end-to-end SLO acceptance story —
a server under injected serving.error/serving.latency faults drives the
availability and latency SLOs through ok → pending → firing and back to
resolved after the faults clear, with the alert transitions present in
the flight-recorder dump attached to a forced crash report."""

import base64
import gzip
import json
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.observability import flightrecorder as fr
from deeplearning4j_tpu.observability import metrics as om
from deeplearning4j_tpu.observability import slo
from deeplearning4j_tpu.resilience.faults import (
    FaultInjector,
    set_fault_injector,
)
from deeplearning4j_tpu.serving import ModelRegistry, ModelServer, spec

# ---------------------------------------------------------------------------
# fixtures / helpers


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    om.reset_default_registry()
    fr.set_flight_recorder(None)
    om.set_enabled(True)
    fr.set_recording(True)
    slo.set_default_engine(None)
    set_fault_injector(FaultInjector())  # empty: no faults armed
    yield
    set_fault_injector(None)
    slo.set_default_engine(None)
    om.reset_default_registry()
    fr.set_flight_recorder(None)


def _forward(v, x):
    return jnp.tanh(x @ v["w"])


def _server(**kw):
    registry = ModelRegistry()
    registry.register(
        "tiny", _forward,
        {"w": jnp.asarray(np.random.default_rng(0).normal(size=(4, 3)),
                          jnp.float32)},
        input_spec=spec((4,)), version="v1", mode="batched",
        max_batch_size=8, devices=jax.devices()[:1])
    return ModelServer(registry, port=0, **kw)


def _post(url, payload, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(url, timeout=30):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _wait_for(predicate, timeout=20.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


# ---------------------------------------------------------------------------
# flight recorder unit


class TestFlightRecorder:
    def test_ring_bounds_and_dropped_counter(self):
        rec = fr.FlightRecorder(capacity=8)
        for i in range(20):
            rec.record("k", i=i)
        assert len(rec) == 8
        assert rec.dropped_total == 12
        evs = rec.events()
        assert [e["data"]["i"] for e in evs] == list(range(12, 20))
        d = rec.dump()
        assert d["capacity"] == 8 and d["dropped_total"] == 12
        assert d["count"] == 8

    def test_last_seconds_window_and_kind_filter(self):
        rec = fr.FlightRecorder()
        old = rec.record("old")
        old["t"] -= 3600.0  # age it an hour
        rec.record("new", x=1)
        assert [e["kind"] for e in rec.events(last_seconds=60)] == ["new"]
        assert [e["kind"] for e in rec.events(kinds=["old"])] == ["old"]
        assert rec.dump(last_seconds=60)["count"] == 1

    def test_data_never_clobbers_envelope(self):
        rec = fr.FlightRecorder()
        ev = rec.record("k", t="not-a-time", kind="not-a-kind")
        assert isinstance(ev["t"], float)
        assert ev["kind"] == "k"
        assert ev["data"] == {"t": "not-a-time", "kind": "not-a-kind"}

    def test_recording_kill_switch(self):
        fr.set_recording(False)
        try:
            assert fr.record_event("k") is None
            assert len(fr.get_flight_recorder()) == 0
        finally:
            fr.set_recording(True)
        assert fr.record_event("k") is not None

    def test_snapshot_registries_compact(self):
        reg = om.MetricsRegistry()
        c = reg.counter("reqs_total", "t", ("code",))
        c.inc(3, code="200")
        c.inc(2, code="500")
        h = reg.histogram("lat_seconds", "t")
        h.observe(0.01), h.observe(0.02)
        ev = fr.FlightRecorder().snapshot_registries([reg])
        assert ev["data"]["series"] == {"reqs_total": 5.0,
                                        "lat_seconds_count": 2.0}

    def test_events_json_serializable(self):
        rec = fr.FlightRecorder()
        rec.record("k", nested={"a": [1, 2]}, s="x")
        json.dumps(rec.dump())  # must not raise

    def test_crash_report_ships_timeline(self, tmp_path):
        from deeplearning4j_tpu.utils.crash import write_crash_report

        fr.record_event("marker.event", detail="pre-crash breadcrumb")
        path = write_crash_report(str(tmp_path),
                                  exception=RuntimeError("boom"))
        report = json.loads(open(path).read())
        evs = report["flight_recorder"]["events"]
        assert any(e["kind"] == "marker.event" and
                   e["data"]["detail"] == "pre-crash breadcrumb"
                   for e in evs)


# ---------------------------------------------------------------------------
# producers across layers


class TestProducers:
    def test_admission_shed_recorded(self):
        from deeplearning4j_tpu.serving.admission import AdmissionController
        from deeplearning4j_tpu.serving.errors import QueueFullError

        ac = AdmissionController(max_in_flight=1)
        t1 = ac.admit()
        with pytest.raises(QueueFullError):
            ac.admit()
        t1.release()
        evs = fr.get_flight_recorder().events(
            kinds=["serving.admission_cap"])
        assert evs and evs[-1]["data"]["in_flight"] == 1

    def test_fault_injection_recorded(self):
        inj = FaultInjector().plan("serving.error", at=1)
        assert inj.fire("serving.error") is not None
        evs = fr.get_flight_recorder().events(kinds=["fault.injected"])
        assert evs[-1]["data"]["point"] == "serving.error"

    def test_rollback_and_quarantine_recorded(self, tmp_path):
        from deeplearning4j_tpu.serde.checkpoint import (
            quarantine_checkpoint,
            verify_checkpoint,
        )

        ckpt = tmp_path / "ckpt-000001"
        ckpt.mkdir()
        ok, reason = verify_checkpoint(ckpt)  # missing state.npz
        assert not ok
        evs = fr.get_flight_recorder().events(
            kinds=["checkpoint.verify_failed"])
        assert evs and reason in evs[-1]["data"]["reason"]
        assert quarantine_checkpoint(ckpt, reason="test") is not None
        assert fr.get_flight_recorder().events(
            kinds=["checkpoint.quarantined"])

    def test_data_starvation_detector_transitions(self):
        from deeplearning4j_tpu.train.trainer import _StepTelemetry

        tm = om.get_training_metrics()

        class _NoFlops:
            def step_flops(self, ts, batch):
                return None

        tele = _StepTelemetry(_NoFlops(), tm)
        # reads dominate the loop: starved flips on after MIN_STEPS
        for i in range(1, tele.MIN_STEPS + 1):
            tele.on_step(None, None, read_s=0.09, step_s=0.01, step_no=i)
        assert tm.data_starved.value() == 1.0
        evs = fr.get_flight_recorder().events(
            kinds=["train.data_starvation"])
        assert evs and evs[-1]["data"]["read_fraction"] > 0.5
        # fast reads for a full window: recovers
        for i in range(tele.MIN_STEPS + 1, tele.MIN_STEPS + tele.WINDOW + 2):
            tele.on_step(None, None, read_s=0.0001, step_s=0.01, step_no=i)
        assert tm.data_starved.value() == 0.0
        assert fr.get_flight_recorder().events(
            kinds=["train.data_recovered"])

    def test_trainer_fit_records_sampled_steps_and_epochs(self):
        from deeplearning4j_tpu.data import ArrayDataSetIterator
        from deeplearning4j_tpu.nn.config import (
            NeuralNetConfiguration,
            SequentialConfig,
        )
        from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
        from deeplearning4j_tpu.nn.model import SequentialModel
        from deeplearning4j_tpu.train.trainer import Trainer

        model = SequentialModel(SequentialConfig(
            net=NeuralNetConfiguration(seed=0),
            layers=[Dense(units=4, activation="tanh"),
                    OutputLayer(units=2, activation="softmax",
                                loss="mcxent")],
            input_shape=(6,)))
        trainer = Trainer(model)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(32, 6)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)]
        trainer.fit(trainer.init_state(),
                    ArrayDataSetIterator(x, y, batch_size=8), epochs=2)
        rec = fr.get_flight_recorder()
        steps = rec.events(kinds=["train.step"])
        assert steps and steps[0]["data"]["step"] == 1
        epochs = rec.events(kinds=["train.epoch"])
        assert [e["data"]["epoch"] for e in epochs] == [0, 1]


# ---------------------------------------------------------------------------
# /debug/* endpoints


class TestDebugEndpoints:
    # one server for the whole class: every test here is read-only
    # against the debug surface (tier-1 time budget — five
    # build/warm/drain cycles of the same tiny model told us nothing
    # four of the teardowns' ~0.5 s drains didn't)
    @pytest.fixture(scope="class")
    def server(self):
        s = _server(slo_interval_s=0.05,
                    slo_time_scale=1.0 / 600.0).start()
        yield s
        s.stop()

    def test_debug_health(self, server):
        status, body = _get(f"{server.url}/debug/health")
        assert status == 200
        h = json.loads(body)
        assert h["status"] == "ok"
        names = {r["name"] for r in h["rules"]}
        assert names == {"serving-availability", "serving-latency-p99"}
        for r in h["rules"]:
            assert r["state"] == "ok"
            assert r["windows"][0]["burn"] > 0
        status, body = _get(f"{server.url}/debug/health?format=text")
        assert status == 200
        assert b"serving-availability" in body

    def test_debug_flightrecorder(self, server):
        # the per-test ring reset wiped the class-scoped server's
        # serving.start; a fresh marker proves the endpoint serves the
        # LIVE ring just as well
        fr.record_event("diag.flightrecorder_probe", via="http")
        status, body = _get(f"{server.url}/debug/flightrecorder")
        assert status == 200
        d = json.loads(body)
        assert any(e["kind"] == "diag.flightrecorder_probe"
                   for e in d["events"])
        status, body = _get(
            f"{server.url}/debug/flightrecorder?seconds=0.000001")
        assert json.loads(body)["count"] <= 2
        status, _ = _get(f"{server.url}/debug/flightrecorder?seconds=zzz")
        assert status == 400

    def test_debug_costs(self, server):
        status, body = _get(f"{server.url}/debug/costs")
        assert status == 200
        models = json.loads(body)["models"]
        assert len(models) == 1
        m = models[0]
        assert m["model"] == "tiny" and m["version"] == "v1"
        assert m["available"] is True
        assert m["rows"] == 8
        assert m["flops"] > 0
        assert m["flops_per_row"] == pytest.approx(m["flops"] / 8)
        # arithmetic intensity present when the backend reports bytes
        if m.get("bytes_accessed"):
            assert m["arithmetic_intensity"] == pytest.approx(
                m["flops"] / m["bytes_accessed"])
        # rows override analyzes a different bucket
        status, body = _get(f"{server.url}/debug/costs?rows=1")
        assert json.loads(body)["models"][0]["rows"] == 1

    def test_debug_profile_live_traffic(self, server):
        stop = threading.Event()

        def traffic():
            while not stop.is_set():
                _post(f"{server.url}/v1/models/tiny:predict",
                      {"inputs": [[0.1, 0.2, 0.3, 0.4]]})
                # breathe: a zero-gap hammer loop contends with the
                # profiler's stop/flush on a loaded CI host
                time.sleep(0.005)

        t = threading.Thread(target=traffic, daemon=True)
        t.start()
        try:
            # generous read timeout: trace serialization + analysis after
            # stop_trace can take a while in a long-lived test process
            status, body = _post(f"{server.url}/debug/profile?ms=400", {},
                                 timeout=180)
        finally:
            stop.set()
            t.join(timeout=10)
        assert status == 200, body
        assert body["duration_ms"] >= 400
        # non-empty op breakdown from the live capture
        assert body["ops"], body
        assert all(r["total_us"] >= 0 for r in body["ops"])
        # the returned trace is loadable Perfetto/Chrome JSON
        raw = gzip.decompress(base64.b64decode(body["trace_gz_b64"]))
        trace = json.loads(raw)
        assert trace["traceEvents"]

    def test_debug_profile_validates_ms(self, server):
        status, _ = _post(f"{server.url}/debug/profile?ms=0", {})
        assert status == 400
        status, _ = _post(f"{server.url}/debug/profile?ms=99999999", {})
        assert status == 400
        status, _ = _post(f"{server.url}/debug/profile?ms=abc", {})
        assert status == 400

    def test_server_publishes_default_engine(self):
        # publication happens at start(): needs its own server — the
        # per-test reset clears the process default the class-scoped
        # server published
        s = _server(slo_interval_s=0.05,
                    slo_time_scale=1.0 / 600.0).start()
        try:
            assert slo.get_default_engine() is s.slo_engine
            assert s.slo_engine.running
        finally:
            s.stop()


# ---------------------------------------------------------------------------
# end-to-end acceptance: faults drive the SLOs through the full cycle


class TestEndToEndSLO:
    def test_faults_drive_slo_cycle_and_crash_report(self, tmp_path):
        # scaled-down rules: 0.5 s / 2 s windows, 0.1 s for/hold
        scale = 1.0 / 600.0
        rules = [
            slo.SLORule(
                name="availability", kind="availability", objective=0.99,
                total=slo.Selector("serving_requests_total"),
                bad=slo.Selector("serving_requests_total",
                                 match=(("code", "429|5.."),)),
                windows=(slo.BurnWindow(300.0, 1200.0, 2.0),),
                for_s=60.0, resolve_hold_s=60.0),
            slo.SLORule(
                name="latency", kind="latency", objective=0.9,
                threshold_s=0.05,
                histogram=slo.Selector("serving_request_latency_seconds"),
                windows=(slo.BurnWindow(300.0, 1200.0, 2.0),),
                for_s=60.0, resolve_hold_s=60.0),
        ]
        server = _server(slo_rules=rules, slo_interval_s=0.05,
                         slo_time_scale=scale).start()
        stop = threading.Event()

        def traffic():
            while not stop.is_set():
                _post(f"{server.url}/v1/models/tiny:predict",
                      {"inputs": [[0.1, 0.2, 0.3, 0.4]]})
                time.sleep(0.01)

        driver = threading.Thread(target=traffic, daemon=True)
        engine = server.slo_engine
        seen = {"availability": set(), "latency": set()}

        def note_states():
            for name, st in engine.states().items():
                seen[name].add(st)

        try:
            driver.start()
            # phase 1: healthy traffic
            assert _wait_for(lambda: (note_states(),
                                      engine.states() == {
                                          "availability": "ok",
                                          "latency": "ok"})[1])
            # phase 2: inject latency (0.12 s >> 0.05 s threshold) +
            # overload sheds (429) on every request
            set_fault_injector(
                FaultInjector()
                .plan("serving.latency", at=1, times=10**9, arg=0.12)
                .plan("serving.error", at=1, times=10**9))
            assert _wait_for(
                lambda: (note_states(),
                         engine.states() == {"availability": "firing",
                                             "latency": "firing"})[1],
                timeout=30), engine.states()
            # phase 3: crash WHILE firing — the report must carry the
            # alert timeline
            from deeplearning4j_tpu.utils.crash import write_crash_report

            path = write_crash_report(
                str(tmp_path), exception=RuntimeError("forced post-mortem"))
            # phase 4: faults clear; windows slide; alerts resolve
            set_fault_injector(FaultInjector())
            assert _wait_for(
                lambda: (note_states(),
                         all(st == "ok"
                             for st in engine.states().values()))[1],
                timeout=30), engine.states()
        finally:
            stop.set()
            driver.join(timeout=10)
            server.stop()
        # the full state machine was traversed for BOTH rules
        for rule in ("availability", "latency"):
            assert {"ok", "pending", "firing"} <= seen[rule], seen
        report = json.loads(open(path).read())
        evs = report["flight_recorder"]["events"]
        fired = [(e["data"]["rule"], e["data"]["to"]) for e in evs
                 if e["kind"] == "slo.transition"]
        assert ("availability", "firing") in fired
        assert ("latency", "firing") in fired
        # the injected faults are on the same timeline
        assert any(e["kind"] == "fault.injected" for e in evs)
        # resolution transitions landed in the live ring after the dump
        ring = fr.get_flight_recorder().events(kinds=["slo.transition"])
        resolved = [(e["data"]["rule"], e["data"]["to"]) for e in ring]
        assert ("availability", "resolved") in resolved
        assert ("latency", "resolved") in resolved
