"""Serving subsystem tests (serving/): registry, admission, warmup,
metrics, ModelServer lifecycle.

Strategy mirrors the repo's multi-node-without-cluster pattern: real
ThreadingHTTPServer on a port-0 loopback socket, real concurrent
clients, 8 virtual CPU devices — the identical code path a v5e slice
serves. Heavy sustained-load tests are @pytest.mark.slow (deselected by
default via pyproject addopts) so tier-1 stays fast.
"""

import re
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.serving import (
    AdmissionController,
    BadRequestError,
    DeadlineExceededError,
    ModelNotFoundError,
    ModelRegistry,
    ModelServer,
    QueueFullError,
    ServingClient,
    ServingError,
    bucket_sizes,
    spec,
)

# ---------------------------------------------------------------------------
# helpers


def _dense_model():
    from deeplearning4j_tpu.nn.config import (
        NeuralNetConfiguration,
        SequentialConfig,
    )
    from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
    from deeplearning4j_tpu.nn.model import SequentialModel

    net = NeuralNetConfiguration(seed=7)
    layers = [Dense(units=8, activation="relu"),
              OutputLayer(units=4, activation="softmax", loss="mcxent")]
    return SequentialModel(
        SequentialConfig(net=net, layers=layers, input_shape=(16,)))


def _scale_forward(v, x):
    """Every output row equals v['scale'] — a torn/mixed read is visible."""
    return jnp.zeros((x.shape[0], 1), jnp.float32) + v["scale"]


def _scale_server(**kw):
    registry = ModelRegistry()
    registry.register("scale", _scale_forward, {"scale": 1.0},
                      input_spec=spec((4,)), version="v1", mode="batched",
                      max_batch_size=8, devices=jax.devices()[:2])
    server = ModelServer(registry, port=0, **kw)
    return server, registry


def _block_active_fn(entry, seconds=0.5):
    """Make the entry's active replica set slow (worker-side sleep)."""
    pi = entry._active.pi
    orig = pi._fn

    def slow(v, x):
        time.sleep(seconds)
        return orig(v, x)

    pi._fn = slow
    return pi, orig


_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_LABELS = r'\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\}'
_VALUE = r"[-+]?([0-9]*\.?[0-9]+([eE][-+]?[0-9]+)?|Inf|NaN)"
# optional OpenMetrics-style exemplar suffix on histogram bucket lines
_EXEMPLAR = rf'( # \{{{_NAME}="[^"]*"\}} {_VALUE}( {_VALUE})?)?'
_SAMPLE_RE = re.compile(rf"^({_NAME})({_LABELS})? ({_VALUE}){_EXEMPLAR}$")


def parse_prometheus(text):
    """Strict-ish Prometheus text-format parser for the test assertions.

    Returns {family: {"type": ..., "help": ..., "samples": [(name,
    labels_str, value)]}}; raises AssertionError on malformed lines,
    samples without a preceding HELP/TYPE header, or non-monotonic
    histogram buckets."""
    families, current = {}, None
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            current = families.setdefault(
                name, {"help": help_text, "type": None, "samples": []})
        elif line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            assert name in families, f"TYPE before HELP: {line!r}"
            assert kind in ("counter", "gauge", "histogram", "untyped")
            families[name]["type"] = kind
        else:
            m = _SAMPLE_RE.match(line)
            assert m, f"malformed sample line: {line!r}"
            sample_name = m.group(1)
            family = next((f for f in families
                           if sample_name in (f, f + "_bucket", f + "_sum",
                                              f + "_count")), None)
            assert family is not None, f"sample without header: {line!r}"
            families[family]["samples"].append(
                (sample_name, m.group(2) or "", float(m.group(4))))
    for name, fam in families.items():
        if fam["type"] == "histogram":
            by_series = {}
            for sname, labels, value in fam["samples"]:
                if sname == name + "_bucket":
                    key = re.sub(r',?le="[^"]*"', "", labels)
                    by_series.setdefault(key, []).append(value)
            for key, counts in by_series.items():
                assert counts == sorted(counts), \
                    f"{name}{key}: non-cumulative buckets {counts}"
    return families


# ---------------------------------------------------------------------------
# registry + warmup (no HTTP)


def test_registry_predict_matches_direct_forward():
    model = _dense_model()
    variables = model.init(seed=0)
    registry = ModelRegistry()
    entry = registry.register(
        "dense", lambda v, x: model.output(v, x), variables,
        input_spec=spec((16,)), mode="batched", max_batch_size=8,
        devices=jax.devices()[:2], warm=True)
    assert entry.warmed
    x = np.random.default_rng(0).normal(size=(3, 16)).astype(np.float32)
    got = np.asarray(entry.predict(x))
    want = np.asarray(model.output(variables, x))
    np.testing.assert_allclose(got, want, rtol=1e-5)
    assert registry.get("dense").version == "v1"
    with pytest.raises(ModelNotFoundError):
        registry.get("nope")
    registry.shutdown_all()


def test_bucket_sizes_cover_max_batch():
    assert bucket_sizes(32) == [1, 2, 4, 8, 16, 32]
    assert bucket_sizes(24) == [1, 2, 4, 8, 16, 24]  # cap bucket kept
    assert bucket_sizes(1) == [1]
    assert bucket_sizes(64, mode="instant") == [1]


def test_parse_inputs_validation():
    registry = ModelRegistry()
    entry = registry.register(
        "d", _scale_forward, {"scale": 1.0},
        input_spec={"a": spec((2,)), "b": spec((3,), np.int32)},
        devices=jax.devices()[:1])
    feats = entry.parse_inputs({"a": [[1.0, 2.0]], "b": [[1, 2, 3]]})
    assert feats["a"].dtype == np.float32 and feats["b"].dtype == np.int32
    with pytest.raises(BadRequestError):
        entry.parse_inputs([[1.0, 2.0]])  # dict-spec model needs a dict
    with pytest.raises(BadRequestError):
        entry.parse_inputs({"a": [[1.0, 2.0]]})  # missing key
    with pytest.raises(BadRequestError):
        entry.parse_inputs({"a": [[1.0, 2.0]], "b": [[1, 2, 3]],
                            "c": [[0]]})  # unknown key
    with pytest.raises(BadRequestError):
        entry.parse_inputs({"a": [[1.0, 2.0]] * 2,
                            "b": [[1, 2, 3]]})  # batch mismatch
    with pytest.raises(BadRequestError):
        # oversized batch: outside the warmed buckets = a fresh compile
        # per distinct row count — rejected, not served
        entry.parse_inputs({"a": [[1.0, 2.0]] * 33,
                            "b": [[1, 2, 3]] * 33})
    registry.shutdown_all()


def test_failed_deploy_is_atomic():
    """A deploy whose warmup fails must leave no trace: the old version
    keeps serving and no phantom entry lands in the history."""
    registry = ModelRegistry()
    registry.register("m", _scale_forward, {"scale": 1.0},
                      input_spec=spec((4,)), version="v1",
                      devices=jax.devices()[:1], max_batch_size=4, warm=True)
    with pytest.raises(Exception):  # noqa: B017 - any compile/trace error
        registry.deploy("m", {"scale": "not a number"}, version="v2")
    entry = registry.get("m")
    assert [v for v, _ in entry.history] == ["v1"]
    assert entry.version == "v1"
    out = np.asarray(entry.predict(np.zeros((2, 4), np.float32)))
    assert np.all(out == 1.0), "old version must keep serving"
    with pytest.raises(ServingError):
        registry.rollback("m")  # v1 is all there is — nothing to pop
    registry.shutdown_all()


def test_rollback_requires_history():
    registry = ModelRegistry()
    registry.register("m", _scale_forward, {"scale": 1.0},
                      input_spec=spec((4,)), devices=jax.devices()[:1])
    with pytest.raises(ServingError):
        registry.rollback("m")
    registry.shutdown_all()


def test_history_bounds_variables_and_rollback_depth():
    """Only the previous version's variables stay resident (rollback
    depth 1) — older entries keep the name, not GBs of weights — and a
    shut-down entry sheds retryable 503s, not 500s."""
    from deeplearning4j_tpu.serving import NotReadyError

    registry = ModelRegistry()
    registry.register("m", _scale_forward, {"scale": 1.0},
                      input_spec=spec((4,)), devices=jax.devices()[:1],
                      max_batch_size=4)
    registry.deploy("m", {"scale": 2.0})  # v2
    registry.deploy("m", {"scale": 3.0})  # v3
    entry = registry.get("m")
    assert [v for v, _ in entry.history] == ["v1", "v2", "v3"]
    assert entry.history[0][1] is None, "v1's variables must be released"
    assert registry.rollback("m") == "v2"
    with pytest.raises(ServingError):
        registry.rollback("m")  # v1's variables are gone — refuse loudly
    registry.shutdown_all()
    with pytest.raises(NotReadyError):
        entry.predict(np.zeros((1, 4), np.float32))


def test_register_from_checkpoint(tmp_path):
    from deeplearning4j_tpu.serde.checkpoint import save_checkpoint
    from deeplearning4j_tpu.train.trainer import Trainer

    model = _dense_model()
    trainer = Trainer(model)
    ts = trainer.init_state()
    ckpt_dir = save_checkpoint(tmp_path, ts, model=model)

    registry = ModelRegistry()
    entry = registry.register_from_checkpoint(
        "dense", ckpt_dir, devices=jax.devices()[:1])
    x = np.random.default_rng(1).normal(size=(2, 16)).astype(np.float32)
    got = np.asarray(entry.predict(x))
    want = np.asarray(model.output(trainer.variables(ts), x))
    np.testing.assert_allclose(got, want, rtol=1e-5)
    registry.shutdown_all()


# ---------------------------------------------------------------------------
# admission control


def test_admission_cap_and_drain():
    depths = []
    ac = AdmissionController(max_in_flight=2, on_depth=depths.append)
    t1, t2 = ac.admit(), ac.admit()
    with pytest.raises(QueueFullError):
        ac.admit()
    t1.release()
    t1.release()  # idempotent
    assert ac.in_flight == 1
    t3 = ac.admit()
    t2.release(), t3.release()
    assert ac.drain(timeout=1.0)
    assert max(depths) == 2 and depths[-1] == 0
    with pytest.raises(BadRequestError):
        ac.timeout_s(-5)
    with pytest.raises(BadRequestError):
        ac.timeout_s("soon")
    with pytest.raises(BadRequestError):
        ac.timeout_s(float("nan"))  # valid JSON for Python's parser
    with pytest.raises(BadRequestError):
        ac.timeout_s(float("inf"))
    assert ac.timeout_s(10_000_000) == ac.max_deadline_ms / 1000.0


# ---------------------------------------------------------------------------
# ModelServer over real HTTP


def test_server_endpoints_metrics_and_errors():
    server, registry = _scale_server()
    with server:
        client = ServingClient(server.url)
        assert client.health() == {"status": "ok"}
        assert client.ready()["ready"]
        models = client.models()
        assert [m["name"] for m in models] == ["scale"]
        assert models[0]["warmed"]

        x = np.zeros((3, 4), np.float32)
        r = client.predict("scale", x)
        assert r["version"] == "v1"
        np.testing.assert_allclose(np.asarray(r["outputs"]),
                                   np.ones((3, 1)))
        with pytest.raises(ModelNotFoundError):
            client.predict("nope", x)
        with pytest.raises(BadRequestError):
            client.predict("scale", "not numbers")
        with pytest.raises(ServingError):
            client._request("/no/such/route", {})

        fams = parse_prometheus(client.metrics_text())
        assert fams["serving_requests_total"]["type"] == "counter"
        codes = {labels for (_, labels, _)
                 in fams["serving_requests_total"]["samples"]}
        assert any('code="200"' in c for c in codes)
        assert any('code="404"' in c for c in codes)
        for series in ("serving_request_latency_seconds",
                       "serving_device_latency_seconds",
                       "serving_batch_occupancy", "serving_queue_depth",
                       "serving_model_ready"):
            assert series in fams, f"missing family {series}"
        # JSON twin agrees on the request count
        twin = client.metrics_json()
        names = {m["name"] for m in twin["metrics"]}
        assert "serving_requests_total" in names
    assert not server.readiness()["ready"]


def test_readyz_flips_across_warmup_and_drain():
    server, registry = _scale_server()
    server.start(warm=False)  # registered but NOT warmed
    try:
        client = ServingClient(server.url)
        body = client.ready()
        assert body == {"ready": False, "draining": False,
                        "models": {"scale": False}}
        server.warm_all()
        assert client.ready()["ready"]

        # during drain: readyz flips false while HTTP still answers
        _block_active_fn(registry.get("scale"), seconds=0.6)
        results = []
        t = threading.Thread(target=lambda: results.append(
            client.predict("scale", np.zeros((1, 4), np.float32))))
        t.start()
        time.sleep(0.1)  # let the request get admitted
        stopper = threading.Thread(target=server.stop)
        stopper.start()
        deadline = time.monotonic() + 2.0
        saw_draining = False
        while time.monotonic() < deadline and not saw_draining:
            try:
                body = client.ready()
            except Exception:  # noqa: BLE001 - HTTP loop already stopped
                break
            saw_draining = body["draining"] and not body["ready"]
        t.join(timeout=5)
        stopper.join(timeout=10)
        assert saw_draining, "readyz never reported draining"
        assert results, "in-flight request was dropped by graceful drain"
    finally:
        server.stop()


def test_deadline_exceeded_returns_structured_504():
    server, registry = _scale_server()
    with server:
        _block_active_fn(registry.get("scale"), seconds=0.5)
        client = ServingClient(server.url)
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceededError) as ei:
            client.predict("scale", np.zeros((1, 4), np.float32),
                           deadline_ms=50)
        assert time.monotonic() - t0 < 5.0
        assert ei.value.code == "DEADLINE_EXCEEDED"
        assert not ei.value.retryable
        assert server.metrics.shed_total.value(
            model="scale", reason="deadline") == 1


def test_admission_shed_returns_structured_429():
    server, registry = _scale_server(
        admission=AdmissionController(max_in_flight=1))
    with server:
        _block_active_fn(registry.get("scale"), seconds=0.5)
        client = ServingClient(server.url)
        results = []
        t = threading.Thread(target=lambda: results.append(
            client.predict("scale", np.zeros((1, 4), np.float32))))
        t.start()
        time.sleep(0.1)  # first request holds the single admission slot
        with pytest.raises(QueueFullError) as ei:
            client.predict("scale", np.zeros((1, 4), np.float32))
        assert ei.value.retryable
        t.join(timeout=5)
        assert results, "admitted request must still be served"
        assert server.metrics.shed_total.value(
            model="scale", reason="queue_full") == 1
        fams = parse_prometheus(client.metrics_text())
        sheds = fams["serving_shed_total"]["samples"]
        assert any('reason="queue_full"' in labels for _, labels, _ in sheds)


def _mixed_load(client, model, n_threads, per_thread, verify):
    """Closed-loop concurrent clients with mixed batch sizes. Every
    request must be answered correctly or fail with a typed retryable
    backpressure error — anything else (hang, crash, silent drop) fails."""
    ok, shed, broken = [], [], []

    def run(tid):
        rng = np.random.default_rng(tid)
        for i in range(per_thread):
            rows = 1 + (tid + i) % 5
            x = rng.normal(size=(rows, 4)).astype(np.float32)
            try:
                r = client.predict(model, x, deadline_ms=30000)
                verify(x, r)
                ok.append(rows)
            except (QueueFullError, DeadlineExceededError) as e:
                shed.append(e)
            except Exception as e:  # noqa: BLE001 - anything else = bug
                broken.append(e)

    threads = [threading.Thread(target=run, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive(), "load thread hung"
    assert not broken, f"non-backpressure failures: {broken[:3]}"
    return ok, shed


def test_concurrent_load_zero_dropped_requests():
    server, registry = _scale_server()
    with server:
        client = ServingClient(server.url)

        def verify(x, r):
            np.testing.assert_allclose(
                np.asarray(r["outputs"]), np.ones((x.shape[0], 1)))

        ok, shed = _mixed_load(client, "scale", n_threads=8, per_thread=4,
                               verify=verify)
        total = len(ok) + len(shed)
        assert total == 32, f"dropped without error: {32 - total}"
        assert ok, "at least some requests must be served"
        # accounting: every issued request shows up in requests_total
        fams = parse_prometheus(client.metrics_text())
        served = sum(v for name, labels, v
                     in fams["serving_requests_total"]["samples"]
                     if 'model="scale"' in labels)
        assert served == total


def test_hot_swap_under_traffic_no_torn_model():
    server, registry = _scale_server()
    with server:
        client = ServingClient(server.url)
        seen = set()

        def verify(x, r):
            out = np.asarray(r["outputs"])
            assert out.shape == (x.shape[0], 1)
            # a torn model would mix 1.0 and 2.0 rows inside one response
            assert np.all(out == out[0, 0]), f"torn response: {out.ravel()}"
            assert out[0, 0] in (1.0, 2.0)
            expected = 1.0 if r["version"] == "v1" else 2.0
            assert out[0, 0] == expected, \
                f"version {r['version']} served value {out[0, 0]}"
            seen.add(r["version"])

        swap_done = threading.Event()

        def swapper():
            time.sleep(0.05)
            registry.deploy("scale", {"scale": 2.0}, version="v2")
            swap_done.set()

        sw = threading.Thread(target=swapper)
        sw.start()
        ok, shed = _mixed_load(client, "scale", n_threads=8, per_thread=6,
                               verify=verify)
        sw.join(timeout=30)
        assert swap_done.is_set()
        assert len(ok) + len(shed) == 48
        # after the swap settles every response is v2
        r = client.predict("scale", np.zeros((2, 4), np.float32))
        assert r["version"] == "v2"
        assert np.all(np.asarray(r["outputs"]) == 2.0)
        assert registry.rollback("scale") == "v1"
        r = client.predict("scale", np.zeros((2, 4), np.float32))
        assert np.all(np.asarray(r["outputs"]) == 1.0)


@pytest.mark.slow
def test_sustained_load_with_repeated_hot_swaps():
    """Heavy tier-2 load test: sustained mixed-size traffic through
    repeated warmed hot-swaps, then graceful drain. Invariants: zero
    dropped-without-error requests, zero torn responses, drain serves
    everything admitted."""
    server, registry = _scale_server()
    with server:
        client = ServingClient(server.url)
        stop = threading.Event()
        versions = {"v1": 1.0, "v2": 2.0, "v3": 3.0, "v4": 4.0}

        def verify(x, r):
            out = np.asarray(r["outputs"])
            assert np.all(out == out[0, 0])
            assert out[0, 0] == versions[r["version"]]

        def swapper():
            i = 2
            while not stop.is_set() and i <= 4:
                time.sleep(0.2)
                registry.deploy("scale", {"scale": float(i)},
                                version=f"v{i}")
                i += 1

        sw = threading.Thread(target=swapper)
        sw.start()
        ok, shed = _mixed_load(client, "scale", n_threads=16, per_thread=12,
                               verify=verify)
        stop.set()
        sw.join(timeout=30)
        assert len(ok) + len(shed) == 16 * 12
        assert len(ok) > 0
    assert server.stop(), "graceful drain timed out"
