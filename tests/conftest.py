"""Test configuration: force an 8-virtual-device CPU platform.

This is the TPU analogue of the reference's multi-node-without-cluster test
strategy (SURVEY §4: Spark local[N] + embedded Aeron media driver): all mesh
and pjit tests run against 8 fake CPU devices, so the identical SPMD
programs that run on a v5e slice are validated in CI with no TPU attached.

NOTE: this environment's sitecustomize imports jax at interpreter startup
with JAX_PLATFORMS=axon already in the env, so plain env-var edits here are
too late — use jax.config.update instead (backends initialize lazily, so
this still lands before any backend is created).
"""

import os
import re

flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
               os.environ.get("XLA_FLAGS", ""))
os.environ["XLA_FLAGS"] = (
    flags + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)


# -- shared mixed predict+generation server ------------------------------------
#
# ONE tiny-GPT engine + one batched predict model behind one ModelServer,
# compiled once per module and shared by every test in that module. The
# replay/game-day modules both ride this instead of each compiling their
# own fleet (the PR 6/7 budget pattern, hoisted to conftest so the
# fixture exists exactly once).


@pytest.fixture(scope="module")
def mixed_server():
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.gpt import gpt_tiny
    from deeplearning4j_tpu.serving import (
        GenerationEngine,
        ModelRegistry,
        ModelServer,
        spec,
    )

    def fwd(v, x):
        return jnp.zeros((x.shape[0], 1), jnp.float32) + v["scale"]

    reg = ModelRegistry()
    reg.register("scale", fwd, {"scale": 2.0}, input_spec=spec((4,)),
                 mode="batched", max_batch_size=8,
                 devices=jax.devices()[:1])
    model = gpt_tiny()
    eng = GenerationEngine(
        model, model.init(seed=0), name="gpt", num_slots=2, max_len=32,
        max_new_tokens=24, min_kv_bucket=8, min_prompt_bucket=8,
        idle_wait_s=0.002, temperature=0.0, max_waiting=16, seed=0)
    srv = ModelServer(reg, port=0, sentinel=False,
                      generators={"gpt": eng})
    srv.start(warm=True)
    yield srv
    srv.stop()


# -- session thread-leak guard ------------------------------------------------
#
# Exporter/prober/evaluator shutdown bugs historically leaked non-daemon
# threads that kept CI processes alive past the last test. The guard
# snapshots live threads at session start and fails the run if the
# session ends with extra non-daemon threads still alive (after a grace
# window for in-flight joins). Named allowlist for infrastructure that
# legitimately outlives the session.

import sys  # noqa: E402
import threading  # noqa: E402
import time  # noqa: E402
from fnmatch import fnmatch  # noqa: E402

# thread-name patterns allowed to survive the session: executor pools
# are reclaimed by their atexit join, and pytest plugins may keep a
# watcher around
_THREAD_ALLOWLIST = (
    "ThreadPoolExecutor-*",
    "pytest-watcher*",
)


def _leaked_threads(initial):
    # `initial` holds the thread OBJECTS (not idents — CPython recycles
    # idents, so a leaked thread could inherit a session-start ident
    # and escape; the snapshot set keeps the objects alive, identity
    # can't be reused)
    cur = threading.current_thread()
    return [
        th for th in threading.enumerate()
        if th.is_alive() and not th.daemon and th is not cur
        and th not in initial
        and not any(fnmatch(th.name, pat) for pat in _THREAD_ALLOWLIST)
    ]


def pytest_sessionstart(session):
    session._initial_threads = set(threading.enumerate())


def pytest_sessionfinish(session, exitstatus):
    initial = getattr(session, "_initial_threads", None)
    if initial is None:
        return
    deadline = time.monotonic() + 3.0
    leaked = _leaked_threads(initial)
    while leaked and time.monotonic() < deadline:
        time.sleep(0.05)
        leaked = _leaked_threads(initial)
    if not leaked:
        return
    frames = sys._current_frames()
    tr = session.config.pluginmanager.get_plugin("terminalreporter")
    lines = ["", "=== thread-leak guard: non-daemon thread(s) leaked by "
                 "the test session ==="]
    import traceback
    for th in leaked:
        lines.append(f"  {th.name!r} (ident {th.ident})")
        frame = frames.get(th.ident)
        if frame is not None:
            lines.extend("    " + ln for ln in
                         "".join(traceback.format_stack(frame, limit=8))
                         .rstrip().splitlines())
    lines.append("fix the owning component's shutdown (or extend "
                 "tests/conftest.py _THREAD_ALLOWLIST with a reason)")
    text = "\n".join(lines)
    if tr is not None:
        tr.write_line(text, red=True)
    else:  # pragma: no cover - terminal plugin disabled
        print(text, file=sys.stderr)
    session.exitstatus = 1
