"""Test configuration: force an 8-virtual-device CPU platform.

This is the TPU analogue of the reference's multi-node-without-cluster test
strategy (SURVEY §4: Spark local[N] + embedded Aeron media driver): all mesh
and pjit tests run against 8 fake CPU devices, so the identical SPMD
programs that run on a v5e slice are validated in CI with no TPU attached.

NOTE: this environment's sitecustomize imports jax at interpreter startup
with JAX_PLATFORMS=axon already in the env, so plain env-var edits here are
too late — use jax.config.update instead (backends initialize lazily, so
this still lands before any backend is created).
"""

import os
import re

flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
               os.environ.get("XLA_FLAGS", ""))
os.environ["XLA_FLAGS"] = (
    flags + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
