"""End-to-end slice test: LeNet on (synthetic) MNIST converges.

ref: the reference's tiny-dataset convergence sanity tests
('pretrain on N examples, assert score < x' — SURVEY §4) and benchmark
config #1 (LeNet-5 MNIST, PR1 ref).
"""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.data import ArrayDataSetIterator, AsyncDataSetIterator, load_mnist
from deeplearning4j_tpu.evaluation import evaluate_model
from deeplearning4j_tpu.models.lenet import lenet
from deeplearning4j_tpu.train.listeners import ScoreIterationListener
from deeplearning4j_tpu.train.trainer import Trainer


def test_lenet_learns_and_evaluates():
    from deeplearning4j_tpu.train.updaters import Adam

    (xtr, ytr), (xte, yte), _ = load_mnist(n_train=512, n_test=256)
    model = lenet(updater=Adam(3e-3))
    trainer = Trainer(model)
    ts = trainer.init_state()

    it = ArrayDataSetIterator(xtr, ytr, batch_size=64, seed=0)
    score0 = model.score(trainer.variables(ts), {"features": jnp.asarray(xtr[:64]),
                                                 "labels": jnp.asarray(ytr[:64])})
    listener = ScoreIterationListener(every=4)
    ts = trainer.fit(ts, AsyncDataSetIterator(it), epochs=6, listeners=[listener])

    score1 = model.score(trainer.variables(ts), {"features": jnp.asarray(xtr[:64]),
                                                 "labels": jnp.asarray(ytr[:64])})
    assert score1 < score0 * 0.7, f"loss did not drop: {score0} -> {score1}"

    ev = evaluate_model(model, trainer.variables(ts),
                        ArrayDataSetIterator(xte, yte, batch_size=64, shuffle=False),
                        num_classes=10)
    # Synthetic MNIST is template+noise; a working conv net separates it well.
    assert ev.accuracy() > 0.5, ev.stats()


def test_lenet_full_batch_shapes():
    model = lenet()
    v = model.init()
    x = np.zeros((4, 28, 28, 1), np.float32)
    y = model.output(v, x)
    assert y.shape == (4, 10)
    np.testing.assert_allclose(np.asarray(jnp.sum(y, axis=-1)), 1.0, rtol=1e-5)


def test_trainer_step_count_and_state_updates():
    model = lenet()
    trainer = Trainer(model)
    ts = trainer.init_state()
    batch = {
        "features": jnp.zeros((8, 28, 28, 1)),
        "labels": jax.nn.one_hot(jnp.arange(8) % 10, 10),
    }
    ts2, metrics = trainer.train_step(ts, batch)
    assert int(ts2.step) == 1
    assert "total_loss" in metrics
