"""Fleet autoscaler tests (serving/autoscaler.py +
resilience/backendpool.py + the router's topology/park plane).

Coverage map:

- pure units: the fire_after/clear_after hysteresis machine, policy
  validation + env construction, the FailStreak dead-slot discipline,
  launcher contracts (manifest shipping through ProcessBackendLauncher
  child envs);
- deterministic decision-pipeline tests: ``tick(signals=...)`` feeds
  the control loop synthetic signal sequences — the single-tick-spike
  proof (one jittery tick NEVER scales), scale-out under sustained
  overload + cooldown, scale-in floors, dead-backend replacement and
  the give-up path, page-in, flap accounting, and the dry-run ==
  live decision-equivalence proof;
- in-process integration: runtime add/remove on a live FleetRouter
  (probe-gated admission of a new backend), the parked-request path
  (timeout → typed 503; resumed → 200), the /debug/autoscaler and
  /admin/autoscaler/pressure endpoints, the scale-to-zero round trip
  (idle retire → park → page-in → served by the respawned backend),
  fast in-process self-healing (a dead spawned backend is replaced
  and the replacement serves), the rolling-deploy manifest ride-along,
  and a spawn_pressure game-day drill judged by the autoscaler gate;
- THE chaos acceptance (@slow): SIGKILL a subprocess backend under
  load → the autoscaler classifies it dead and launches a replacement
  that warms, passes /readyz, and is re-admitted — zero client-visible
  critical failures, lockorder sanitizer armed throughout.

Budget discipline: units use injected clocks/signals (no HTTP, no
jax); integration classes share class-scoped in-process ModelServers;
only the @slow chaos class pays for subprocesses.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.analysis import lockcheck
from deeplearning4j_tpu.resilience import gameday as gd
from deeplearning4j_tpu.resilience import replay as rp
from deeplearning4j_tpu.resilience.backendpool import (
    BackendLauncher,
    CallableBackendLauncher,
    FailStreak,
    ProcessBackendLauncher,
    free_port,
)
from deeplearning4j_tpu.serving import (
    FleetRouter,
    ModelRegistry,
    ModelServer,
    RouterPolicy,
    ServingClient,
    WarmupManifest,
    spec,
)
from deeplearning4j_tpu.serving.autoscaler import (
    Autoscaler,
    AutoscalerMetrics,
    AutoscalerPolicy,
    _Hysteresis,
)

# ---------------------------------------------------------------------------
# helpers


def _scale_forward(v, x):
    return jnp.zeros((x.shape[0], 1), jnp.float32) + v["scale"]


def _mk_server(scale, *, version="v1"):
    registry = ModelRegistry()
    registry.register("scale", _scale_forward, {"scale": scale},
                      input_spec=spec((4,)), version=version,
                      mode="batched", max_batch_size=8,
                      devices=jax.devices()[:1])
    server = ModelServer(registry, port=0, sentinel=False)
    server.start(warm=True)
    return server


class _ServerHandle:
    """CallableBackendLauncher factory product with an honest
    ``alive()`` (a plain ModelServer counts as alive while registered,
    which hides in-process 'deaths' from the launcher)."""

    def __init__(self, server):
        self.server = server
        self._alive = True

    @property
    def url(self):
        return self.server.url

    def alive(self):
        return self._alive

    def kill(self):
        """In-process SIGKILL analogue: stop serving AND report dead."""
        self._alive = False
        self.server.stop(drain=False)

    def stop(self):
        self._alive = False
        self.server.stop(drain=False)


def _wait(cond, timeout_s, interval_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval_s)
    return cond()


def _sig(**kw):
    base = dict(live=1, routable=1, warming=0, in_flight=0,
                shed_rate=0.0, occupancy=0.0, capacity_verdict="ok",
                dead=[], pressure=False)
    base.update(kw)
    return base


_OVERLOAD = dict(shed_rate=5.0, occupancy=1.0)


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class _FakeBackend:
    def __init__(self, name, *, routable=True):
        self.name = name
        self.in_flight = 0
        self.routable = routable
        self.warming = None


class _FakeRouter:
    def __init__(self, names=("b0",), *, new_routable=True):
        self.backends = [_FakeBackend(n) for n in names]
        self.new_routable = new_routable
        self.drained = []
        self.autoscaler = None
        self.page_in_hook = None

    def add_backend(self, name, url):
        b = _FakeBackend(name, routable=self.new_routable)
        self.backends.append(b)
        return b

    def remove_backend(self, name):
        self.backend(name)
        self.backends = [b for b in self.backends if b.name != name]

    def drain(self, name, timeout_s=None):
        self.drained.append(name)
        return True

    def backend(self, name):
        for b in self.backends:
            if b.name == name:
                return b
        raise KeyError(name)

    def set_page_in_hook(self, hook):
        self.page_in_hook = hook


class _StubLauncher(BackendLauncher):
    def __init__(self):
        self.spawned = []
        self.retired = []
        self._alive = {}

    def spawn(self, name):
        self.spawned.append(name)
        self._alive[name] = True
        return f"http://127.0.0.1:9/{name}"

    def retire(self, name):
        self.retired.append(name)
        self._alive.pop(name, None)

    def alive(self, name):
        return self._alive.get(name, False)


def _unit_policy(**kw):
    base = dict(min_backends=1, max_backends=3, fire_after=2,
                clear_after=1, idle_fire_after=2, cooldown_s=5.0,
                dead_fire_after=1, tick_interval_s=0.05)
    base.update(kw)
    return AutoscalerPolicy(**base).validate()


def _mk_unit(policy, *, names=("b0",), new_routable=True):
    router = _FakeRouter(names, new_routable=new_routable)
    launcher = _StubLauncher()
    clock = _Clock()
    a = Autoscaler(router, launcher, policy=policy,
                   metrics=AutoscalerMetrics(), clock=clock)
    return a, router, launcher, clock


# ---------------------------------------------------------------------------
# units: hysteresis / policy / fail streaks / launchers


class TestHysteresis:
    def test_fires_only_after_streak_and_transition_once(self):
        h = _Hysteresis(3, 2)
        assert h.update(True) is False
        assert h.update(True) is False
        assert h.update(True) is True       # the transition tick
        assert h.firing
        assert h.update(True) is False      # already firing: no re-fire
        assert h.update(False) is False     # cool 1 of 2
        assert h.firing
        h.update(False)                     # cool 2 of 2 -> clears
        assert not h.firing

    def test_calm_tick_resets_the_hot_streak(self):
        h = _Hysteresis(2, 1)
        h.update(True)
        h.update(False)                     # streak broken
        assert h.update(True) is False      # back to 1 of 2
        assert h.update(True) is True


class TestAutoscalerPolicy:
    def test_single_tick_fire_rejected(self):
        with pytest.raises(ValueError, match="fire_after"):
            AutoscalerPolicy(fire_after=1).validate()
        with pytest.raises(ValueError, match="idle_fire_after"):
            AutoscalerPolicy(idle_fire_after=1).validate()

    def test_bounds_rejected(self):
        with pytest.raises(ValueError, match="max_backends"):
            AutoscalerPolicy(min_backends=5, max_backends=3).validate()
        with pytest.raises(ValueError, match="ledger_capacity"):
            AutoscalerPolicy(ledger_capacity=0).validate()

    def test_from_env_reads_knobs_and_overrides_win(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_AUTOSCALER_MAX_BACKENDS", "7")
        monkeypatch.setenv("DL4J_TPU_AUTOSCALER_FIRE_AFTER", "4")
        monkeypatch.setenv("DL4J_TPU_AUTOSCALER_SCALE_TO_ZERO", "1")
        monkeypatch.setenv("DL4J_TPU_AUTOSCALER_DRY_RUN", "true")
        monkeypatch.setenv("DL4J_TPU_AUTOSCALER_COOLDOWN_S", "2.5")
        p = AutoscalerPolicy.from_env(min_backends=0)
        assert p.max_backends == 7 and p.fire_after == 4
        assert p.scale_to_zero and p.dry_run
        assert p.cooldown_s == 2.5 and p.min_backends == 0


class TestFailStreak:
    def test_immediate_exits_burn_the_slot(self):
        fs = FailStreak(immediate_exit_s=5.0, dead_slot_threshold=3)
        assert fs.note_exit("b2", 1.0) is False
        assert fs.note_exit("b2", 0.5) is False
        assert fs.note_exit("b2", 2.0) is True       # third strike
        assert fs.is_dead("b2")
        assert fs.note_exit("b2", 0.1) is False      # already dead

    def test_long_life_or_unknown_resets(self):
        fs = FailStreak(immediate_exit_s=5.0, dead_slot_threshold=3)
        fs.note_exit("s", 0.5)
        fs.note_exit("s", 0.5)
        assert fs.note_exit("s", 100.0) is False     # proved it CAN run
        assert fs.describe()["streaks"]["s"] == 1
        assert fs.note_exit("s", None) is False      # seed backend
        assert fs.describe()["streaks"]["s"] == 1

    def test_routable_replacement_clears(self):
        fs = FailStreak(dead_slot_threshold=2)
        fs.note_exit("s", 0.5)
        fs.note_healthy("s")
        assert fs.note_exit("s", 0.5) is False
        assert not fs.is_dead("s")


class TestLaunchers:
    def test_callable_launcher_lifecycle(self):
        stopped = []

        class _Srv:
            def __init__(self, name):
                self.url = f"http://x/{name}"

            def stop(self):
                stopped.append(1)

        lau = CallableBackendLauncher(lambda name: _Srv(name))
        url = lau.spawn("a")
        assert url == "http://x/a" and lau.alive("a")
        assert not lau.alive("nope")
        assert lau.describe()["backends"] == ["a"]
        lau.retire("a")
        assert stopped == [1] and not lau.alive("a")
        lau.retire("a")                              # idempotent
        assert stopped == [1]

    def test_process_launcher_child_env_ships_manifest(self, tmp_path):
        m = WarmupManifest(tmp_path / "warm.json")
        m.note_batch("scale", 8)
        lau = ProcessBackendLauncher(lambda n, p: ["true"], manifest=m,
                                     env={"EXTRA_FLAG": "on"})
        env = lau._child_env()
        assert env["DL4J_TPU_WARMUP_MANIFEST"] == str(tmp_path /
                                                      "warm.json")
        assert env["EXTRA_FLAG"] == "on"
        # the manifest hit disk: the child reads it at startup
        assert (tmp_path / "warm.json").exists()
        # without a manifest the launcher adds nothing
        lau2 = ProcessBackendLauncher(lambda n, p: ["true"])
        assert (lau2._child_env().get("DL4J_TPU_WARMUP_MANIFEST")
                == os.environ.get("DL4J_TPU_WARMUP_MANIFEST"))

    def test_free_port_is_bindable(self):
        import socket
        p = free_port()
        s = socket.socket()
        try:
            s.bind(("127.0.0.1", p))
        finally:
            s.close()


# ---------------------------------------------------------------------------
# the decision pipeline, deterministically (injected signals + clock)


class TestTickDecisions:
    def test_single_tick_spike_never_scales(self):
        """THE hysteresis acceptance: one jittery overloaded tick (or
        several, separated by calm ticks) produces NO scale decision."""
        a, _, launcher, clock = _mk_unit(_unit_policy(fire_after=3))
        for _ in range(4):
            assert a.tick(_sig(**_OVERLOAD)) == []
            assert a.tick(_sig()) == []              # calm resets
            clock.advance(1.0)
        assert launcher.spawned == [] and a.ledger() == []

    def test_sustained_overload_scales_out_under_cooldown(self):
        a, router, launcher, clock = _mk_unit(_unit_policy())
        assert a.tick(_sig(**_OVERLOAD)) == []
        d = a.tick(_sig(**_OVERLOAD))
        assert [e["action"] for e in d] == ["scale_out"]
        assert d[0]["executed"] and launcher.spawned == ["as1"]
        assert any(b.name == "as1" for b in router.backends)
        # still firing, but inside the cooldown window: no new decision
        clock.advance(1.0)
        assert a.tick(_sig(live=2, **_OVERLOAD)) == []
        # past cooldown + still overloaded -> scales again, to max
        clock.advance(10.0)
        d = a.tick(_sig(live=2, **_OVERLOAD))
        assert [e["action"] for e in d] == ["scale_out"]
        # at the ceiling nothing more happens
        clock.advance(10.0)
        assert a.tick(_sig(live=3, **_OVERLOAD)) == []
        assert a.metrics.overload_ticks_total.value() >= 4

    def test_capacity_verdict_alone_is_an_overload_signal(self):
        a, _, launcher, _ = _mk_unit(_unit_policy())
        a.tick(_sig(capacity_verdict="exhausted"))
        d = a.tick(_sig(capacity_verdict="exhausted"))
        assert [e["action"] for e in d] == ["scale_out"]
        assert launcher.spawned == ["as1"]

    def test_idle_scales_in_but_respects_the_floor(self):
        a, router, launcher, clock = _mk_unit(
            _unit_policy(cooldown_s=0.0), names=("b0", "b1"))
        a.tick(_sig(live=2))
        d = a.tick(_sig(live=2))                     # idle streak = 2
        assert [e["action"] for e in d] == ["scale_in"]
        assert router.drained and launcher.retired
        # at the floor (min_backends=1): idle forever, no decision
        for _ in range(5):
            clock.advance(1.0)
            assert a.tick(_sig(live=1)) == []

    def test_scale_to_zero_retires_the_last_backend(self):
        a, router, _, _ = _mk_unit(
            _unit_policy(cooldown_s=0.0, scale_to_zero=True))
        a.tick(_sig())
        d = a.tick(_sig())
        assert [e["action"] for e in d] == ["scale_in"]
        assert router.backends == [] and a.describe()["desired"] == 0

    def test_dead_backend_replaced_with_slot_lineage(self):
        a, router, launcher, clock = _mk_unit(
            _unit_policy(dead_fire_after=2))
        assert a.tick(_sig(dead=["b0"])) == []       # streak 1 of 2
        d = a.tick(_sig(dead=["b0"]))
        assert [e["action"] for e in d] == ["replace"]
        assert d[0]["replacement"] == "b0-r1"
        assert launcher.spawned == ["b0-r1"]
        assert launcher.retired == ["b0"]
        assert not any(b.name == "b0" for b in router.backends)
        # a tick where the backend is healthy again resets the streak
        a2, _, l2, _ = _mk_unit(_unit_policy(dead_fire_after=2))
        a2.tick(_sig(dead=["b0"]))
        a2.tick(_sig())                              # recovered
        a2.tick(_sig(dead=["b0"]))
        assert l2.spawned == []

    def test_replacement_churn_gives_up_on_the_slot(self):
        """Supervisor discipline at fleet scope: replacements that die
        younger than immediate_exit_s burn the slot's streak; after
        dead_slot_threshold the autoscaler stops feeding it."""
        a, router, launcher, clock = _mk_unit(
            _unit_policy(dead_fire_after=1, dead_slot_threshold=3,
                         immediate_exit_s=5.0),
            new_routable=False)                      # stays pending
        actions = []
        for name in ("b0", "b0-r1", "b0-r2"):
            clock.advance(1.0)                       # young lifetimes
            actions += [e["action"]
                        for e in a.tick(_sig(dead=[name]))]
        assert actions == ["replace", "replace", "give_up"]
        assert launcher.spawned == ["b0-r1", "b0-r2"]
        assert a.describe()["slots"]["dead_slots"] == ["b0"]
        assert router.backends == []                 # corpse removed

    def test_page_in_fires_without_hysteresis(self):
        a, router, launcher, _ = _mk_unit(
            _unit_policy(min_backends=0, scale_to_zero=True), names=(),
            new_routable=False)              # spawn stays pending/warm
        a.note_page_in("scale")
        d = a.tick(_sig(live=0, routable=0))
        assert [e["action"] for e in d] == ["page_in"]
        assert d[0]["models"] == ["scale"]
        assert launcher.spawned == ["as1"]
        # the still-warming spawn suppresses duplicate page-ins
        a.note_page_in("scale")
        assert a.tick(_sig(live=1, routable=0, in_flight=1)) == []
        assert launcher.spawned == ["as1"]

    def test_flap_reversal_is_counted(self):
        a, _, _, clock = _mk_unit(
            _unit_policy(cooldown_s=0.0, flap_window_s=60.0))
        a.tick(_sig(**_OVERLOAD))
        a.tick(_sig(**_OVERLOAD))                    # scale_out
        assert a.metrics.flaps_total.value() == 0
        clock.advance(1.0)
        a.tick(_sig(live=2))
        a.tick(_sig(live=2))                         # scale_in: reversal
        assert a.metrics.flaps_total.value() == 1
        assert a.metrics.decisions_total.value(action="scale_out") == 1
        assert a.metrics.decisions_total.value(action="scale_in") == 1

    def test_dry_run_records_identical_decisions_to_live(self):
        """THE dry-run acceptance: on the same replayed signal trace,
        dry-run and live mode record the identical decision sequence —
        dry-run just never executes."""
        trace = ([_sig(**_OVERLOAD)] * 2       # -> scale_out on tick 2
                 + [_sig(**_OVERLOAD)]         # cooldown blocks a repeat
                 + [_sig(in_flight=1)]         # clears overload, not idle
                 + [_sig(live=2)] * 2          # -> scale_in on tick 6
                 + [_sig(live=2)]              # cooldown blocks a repeat
                 + [_sig(in_flight=1, dead=["b0"])] * 2)  # -> replace
        runs = {}
        for mode, dry in (("live", False), ("dry", True)):
            a, router, launcher, clock = _mk_unit(
                _unit_policy(cooldown_s=100.0, dead_fire_after=2,
                             dry_run=dry))
            for s in trace:
                a.tick(dict(s))
                clock.advance(1.0)
            runs[mode] = (a, launcher)
        live, live_lau = runs["live"]
        dry, dry_lau = runs["dry"]
        assert [(e["action"], e["reason"]) for e in dry.ledger()] == \
            [(e["action"], e["reason"]) for e in live.ledger()]
        assert [e["action"] for e in live.ledger()] == [
            "scale_out", "scale_in", "replace"]
        # dry-run never touched the launcher; live did
        assert all(e["mode"] == "dry_run" and not e["executed"]
                   for e in dry.ledger())
        assert all(e["mode"] == "live" and e["executed"]
                   for e in live.ledger())
        assert dry_lau.spawned == [] and live_lau.spawned != []
        # decisions metric counts BOTH modes (the ledger is the audit)
        assert (dry.metrics.decisions_total.value(action="scale_out")
                == live.metrics.decisions_total.value(
                    action="scale_out") == 1)

    def test_describe_is_the_debug_document(self):
        a, _, _, _ = _mk_unit(_unit_policy(dry_run=True))
        a.tick(_sig(**_OVERLOAD))
        a.tick(_sig(**_OVERLOAD))
        doc = a.describe()
        assert doc["mode"] == "dry_run" and doc["running"] is False
        assert doc["hysteresis"]["overload"]["firing"]
        assert doc["policy"]["fire_after"] == 2
        assert doc["ledger"][0]["action"] == "scale_out"
        assert doc["signals"]["occupancy"] == 1.0
        json.dumps(doc)                              # wire-serializable


# ---------------------------------------------------------------------------
# in-process integration: runtime topology + park + endpoints


@pytest.fixture(scope="class")
def topo():
    """One live router over server A; server B joins/leaves at runtime."""
    a, b = _mk_server(1.0), _mk_server(2.0)
    policy = RouterPolicy(probe_interval_s=0.1, probe_timeout_s=0.5,
                          reprobe_after_s=0.3, park_timeout_s=5.0,
                          deadline_headroom_s=0.2)
    router = FleetRouter([("b0", a.url)], policy=policy).start()
    ns = type("Topo", (), {})()
    ns.a, ns.b, ns.router = a, b, router
    ns.client = ServingClient(router.url, max_retries=2)
    ns.x = np.zeros((1, 4), np.float32)
    yield ns
    router.stop()
    a.stop(drain=False)
    b.stop(drain=False)


class TestRouterTopology:
    def test_add_backend_is_probe_gated_then_serves(self, topo):
        b = topo.router.add_backend("b1", topo.b.url)
        assert not b.routable                # un-probed: not routable
        assert topo.router.wait_routable("b1", timeout_s=5.0)
        seen = {topo.client.predict("scale", topo.x)["outputs"][0][0]
                for _ in range(16)}
        assert seen == {1.0, 2.0}            # ring rebuilt, traffic spreads

    def test_duplicate_and_unknown_names_are_typed(self, topo):
        with pytest.raises(ValueError, match="duplicate"):
            topo.router.add_backend("b0", topo.b.url)
        with pytest.raises(KeyError):
            topo.router.remove_backend("ghost")

    def test_remove_backend_prunes_gauges_and_traffic(self, topo):
        topo.router.remove_backend("b1")
        assert [b.name for b in topo.router.backends] == ["b0"]
        seen = {topo.client.predict("scale", topo.x)["outputs"][0][0]
                for _ in range(8)}
        assert seen == {1.0}
        m = topo.router.metrics
        assert not any(s["labels"].get("backend") == "b1"
                       for s in m.backend_health.to_json()["samples"])

    def test_park_times_out_to_typed_503(self, topo):
        """Zero routable backends + no page-in plane: the request parks
        for park_timeout_s (bounded by its deadline), then sheds."""
        topo.router.remove_backend("b0")
        try:
            t0 = time.monotonic()
            with pytest.raises(urllib.error.HTTPError) as ei:
                body = json.dumps({"inputs": [[0.0] * 4],
                                   "deadline_ms": 800}).encode()
                req = urllib.request.Request(
                    topo.router.url + "/v1/models/scale:predict",
                    data=body,
                    headers={"Content-Type": "application/json"})
                urllib.request.urlopen(req, timeout=10)
            waited = time.monotonic() - t0
            assert ei.value.code == 503
            # parked to the request deadline (0.8s + 0.2s headroom),
            # not the full 5s park window
            assert 0.5 <= waited < 4.0
            m = topo.router.metrics
            assert m.parked_total.value(outcome="timeout") >= 1
        finally:
            topo.router.add_backend("b0", topo.a.url)
            assert topo.router.wait_routable("b0", timeout_s=5.0)

    def test_park_resumes_when_a_backend_pages_in(self, topo):
        topo.router.remove_backend("b0")
        paged = []

        def hook(model):
            paged.append(model)
            topo.router.add_backend("b0", topo.a.url)

        topo.router.set_page_in_hook(hook)
        try:
            out = topo.client.predict("scale", topo.x)
            assert out["outputs"][0][0] == 1.0
            assert paged == ["scale"]
            m = topo.router.metrics
            assert m.parked_total.value(outcome="resumed") >= 1
        finally:
            topo.router.set_page_in_hook(None)
            if not topo.router.backends:
                topo.router.add_backend("b0", topo.a.url)
            topo.router.wait_routable("b0", timeout_s=5.0)

    def test_debug_and_pressure_endpoints(self, topo):
        url = topo.router.url
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url + "/debug/autoscaler",
                                   timeout=5)
        assert ei.value.code == 404          # nothing attached yet
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                url + "/admin/autoscaler/pressure", data=b""),
                timeout=5)
        assert ei.value.code == 404
        a = Autoscaler(topo.router,
                       CallableBackendLauncher(lambda n: None),
                       policy=_unit_policy(dry_run=True)).attach()
        assert topo.router.autoscaler is a
        with urllib.request.urlopen(url + "/debug/autoscaler",
                                    timeout=5) as r:
            doc = json.loads(r.read())
        assert doc["mode"] == "dry_run" and doc["ledger"] == []
        with urllib.request.urlopen(urllib.request.Request(
                url + "/admin/autoscaler/pressure?duration_s=3.5",
                data=b""), timeout=5) as r:
            assert json.loads(r.read()) == {"pressure_s": 3.5}
        assert a.describe()["pressure_remaining_s"] > 0
        # bad duration is a typed 400, not a crash
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                url + "/admin/autoscaler/pressure?duration_s=1.2.3",
                data=b""), timeout=5)
        assert ei.value.code == 400
        topo.router.autoscaler = None
        topo.router.set_page_in_hook(None)

    def test_rolling_deploy_ships_the_manifest(self, topo, tmp_path):
        """ROADMAP item 8 residual: the fleet's live warmup manifest is
        exported for deploy_fn's restarts and restored afterwards."""
        m = WarmupManifest(tmp_path / "roll.json")
        m.note_batch("scale", 8)
        seen = []

        def deploy_fn(name, url):
            seen.append((name,
                         os.environ.get("DL4J_TPU_WARMUP_MANIFEST")))

        before = os.environ.get("DL4J_TPU_WARMUP_MANIFEST")
        report = topo.router.rolling_deploy(deploy_fn, manifest=m)
        assert [s["backend"] for s in report] == ["b0"]
        assert report[0]["routable"]
        assert seen == [("b0", str(tmp_path / "roll.json"))]
        assert (tmp_path / "roll.json").exists()
        assert os.environ.get("DL4J_TPU_WARMUP_MANIFEST") == before


# ---------------------------------------------------------------------------
# in-process integration: the control loop end to end


@pytest.fixture()
def loop_fleet():
    """A launcher-owned seed backend behind a router, ready for an
    autoscaler; the factory respawns real in-process ModelServers."""
    launcher = CallableBackendLauncher(
        lambda name: _ServerHandle(_mk_server(1.0)))
    seed_url = launcher.spawn("m0")
    policy = RouterPolicy(probe_interval_s=0.1, probe_timeout_s=0.5,
                          reprobe_after_s=0.3, park_timeout_s=20.0)
    router = FleetRouter([("m0", seed_url)], policy=policy).start()
    ns = type("LoopFleet", (), {})()
    ns.launcher, ns.router = launcher, router
    ns.client = ServingClient(router.url, max_retries=3)
    ns.x = np.zeros((1, 4), np.float32)
    ns.autoscaler = None
    yield ns
    if ns.autoscaler is not None:
        ns.autoscaler.stop()
    router.stop()
    launcher.stop_all()


class TestScaleToZeroRoundTrip:
    def test_idle_retire_then_first_request_pages_back_in(
            self, loop_fleet):
        """THE scale-to-zero acceptance: the idle model is retired to
        zero backends; the first subsequent request parks under the
        retry budget and is served by the respawned warm backend."""
        router, launcher = loop_fleet.router, loop_fleet.launcher
        assert router.wait_routable("m0", timeout_s=10.0)
        a = Autoscaler(
            router, launcher,
            policy=AutoscalerPolicy(
                min_backends=0, max_backends=2, fire_after=2,
                clear_after=1, idle_fire_after=2, cooldown_s=0.2,
                tick_interval_s=0.05, scale_to_zero=True,
                spawn_grace_s=60.0)).attach()
        loop_fleet.autoscaler = a
        # the loop must mark m0's spawn time so retire is launcher-aware
        a._spawned_t["m0"] = a._clock()
        a._slot_of["m0"] = "m0"
        a.start()
        # idle ticks drain-and-retire the fleet to ZERO backends
        assert _wait(lambda: len(router.backends) == 0, timeout_s=10.0)
        assert _wait(lambda: any(e["action"] == "scale_in"
                                 for e in a.ledger()), timeout_s=5.0)
        assert not launcher.alive("m0")
        # first request: parks -> page-in hook -> respawn -> served
        t0 = time.monotonic()
        out = loop_fleet.client.predict("scale", loop_fleet.x,
                                        deadline_ms=30000)
        respawn_s = time.monotonic() - t0
        assert out["outputs"][0][0] == 1.0
        ledger = a.ledger()
        assert any(e["action"] == "page_in" and e["executed"]
                   for e in ledger)
        m = router.metrics
        assert m.parked_total.value(outcome="resumed") >= 1
        # generous CPU bound; the bench gates the real number
        assert respawn_s < 25.0, f"respawn took {respawn_s:.1f}s"
        # the NEXT tick's _watch_pending stamps spawn-to-routable
        assert _wait(
            lambda: a.metrics.spawn_to_routable_seconds.to_json()
            ["samples"], timeout_s=5.0)      # MTTR evidence recorded


class TestSelfHealingFast:
    def test_dead_spawned_backend_is_replaced_and_serves(
            self, loop_fleet):
        """Fast in-process proxy for the @slow SIGKILL acceptance: the
        launcher reports the spawned backend dead; the autoscaler
        replaces it with slot lineage and the replacement serves."""
        router, launcher = loop_fleet.router, loop_fleet.launcher
        assert router.wait_routable("m0", timeout_s=10.0)
        a = Autoscaler(
            router, launcher,
            policy=AutoscalerPolicy(
                min_backends=1, max_backends=3, fire_after=2,
                clear_after=1, idle_fire_after=999999,
                cooldown_s=60.0, dead_fire_after=2,
                tick_interval_s=0.05, spawn_grace_s=60.0)).attach()
        loop_fleet.autoscaler = a
        a._spawned_t["m0"] = a._clock()
        a._slot_of["m0"] = "m0"
        a.start()
        # in-process SIGKILL: stops serving AND the launcher sees it
        launcher.server("m0").kill()
        assert _wait(lambda: any(e["action"] == "replace"
                                 for e in a.ledger()), timeout_s=10.0)
        entry = next(e for e in a.ledger() if e["action"] == "replace")
        assert entry["backend"] == "m0"
        assert entry["replacement"] == "m0-r1" and entry["executed"]
        assert router.wait_routable("m0-r1", timeout_s=15.0)
        out = loop_fleet.client.predict("scale", loop_fleet.x)
        assert out["outputs"][0][0] == 1.0
        assert not any(b.name == "m0" for b in router.backends)


# ---------------------------------------------------------------------------
# game-day: the spawn_pressure act + autoscaler gate


class TestGameDayAutoscalerGate:
    def test_act_validation_and_defaults(self):
        act = gd.Act(0.5, "spawn_pressure")
        assert act.duration_s == 10.0
        assert gd.Act(0.5, "spawn_pressure",
                      duration_s=3).duration_s == 3.0
        with pytest.raises(ValueError, match="duration_s"):
            gd.Act(0.5, "spawn_pressure", duration_s=0)
        d = act.describe()
        assert d["kind"] == "spawn_pressure" and d["duration_s"] == 10.0

    def test_gate_judges_the_ledger(self):
        act = gd.Act(0.0, "spawn_pressure", name="p", duration_s=2.0)
        act.t_fired = 100.0
        ledger = [{"action": "scale_out", "mono": 100.6},
                  {"action": "scale_in", "mono": 103.1}]
        v = gd.Gate("autoscaler", max_s=1.0).evaluate(
            [], [act], {}, autoscaler={"ledger": ledger})
        assert v["passed"]
        assert v["value"] == {"scale_out_after_s": 0.6,
                              "scaled_in": True}
        # slow scale-out breaches
        slow = [{"action": "scale_out", "mono": 102.5},
                {"action": "scale_in", "mono": 103.0}]
        v = gd.Gate("autoscaler", max_s=1.0).evaluate(
            [], [act], {}, autoscaler={"ledger": slow})
        assert not v["passed"]
        # no scale-in after the window breaches unless waived
        out_only = [{"action": "scale_out", "mono": 100.2}]
        v = gd.Gate("autoscaler", max_s=1.0).evaluate(
            [], [act], {}, autoscaler={"ledger": out_only})
        assert not v["passed"]
        v = gd.Gate("autoscaler", max_s=1.0,
                    require_scale_in=False).evaluate(
            [], [act], {}, autoscaler={"ledger": out_only})
        assert v["passed"]

    def test_gate_breaches_on_missing_ledger_or_anchor(self):
        v = gd.Gate("autoscaler").evaluate([], [], {}, autoscaler=None)
        assert not v["passed"] and "unavailable" in v["budget"]
        act = gd.Act(0.0, "spawn_pressure")      # never fired
        v = gd.Gate("autoscaler").evaluate(
            [], [act], {}, autoscaler={"ledger": []})
        assert not v["passed"]

    def test_spawn_pressure_drill_scales_out_then_back_in(
            self, loop_fleet, tmp_path):
        """The drill: a spawn_pressure act injects synthetic overload
        through the admin endpoint; the gate asserts scale-out within
        the bound from the autoscaler's own ledger (attached to the
        report artifact); after the act clears, the fleet scales back
        in."""
        router, launcher = loop_fleet.router, loop_fleet.launcher
        assert router.wait_routable("m0", timeout_s=10.0)
        a = Autoscaler(
            router, launcher,
            policy=AutoscalerPolicy(
                min_backends=1, max_backends=2, fire_after=2,
                clear_after=1, idle_fire_after=3, cooldown_s=0.2,
                tick_interval_s=0.05, spawn_grace_s=60.0)).attach()
        loop_fleet.autoscaler = a
        a.start()
        rows = [{"plane": "predict", "model": "scale",
                 "arrival_offset_s": round(i * 0.05, 3),
                 "priority": "normal", "tenant": "gd",
                 "payload_shape": [1, 4], "deadline_s": 20.0,
                 "stream": False} for i in range(12)]
        trace = rp.validate_trace({
            "version": 1, "kind": "dl4j_tpu_trace", "t0_wall": None,
            "count": len(rows),
            "duration_s": rows[-1]["arrival_offset_s"], "rows": rows})
        drill = gd.GameDay(
            router.url, trace, name="spawn-pressure-drill",
            speed=1.0, clients=3, report_dir=str(tmp_path),
            acts=[gd.Act(0.05, "spawn_pressure", name="pressure",
                         duration_s=0.5)],
            gates=[gd.Gate("autoscaler", max_s=20.0,
                           require_scale_in=False),
                   gd.Gate("critical_failures")])
        report = drill.run()
        by_gate = {v["gate"]: v for v in report["gates"]}
        assert by_gate["autoscaler"]["passed"], report["gates"]
        assert by_gate["autoscaler"]["value"]["scale_out_after_s"] \
            is not None
        # the decision ledger rides the report artifact
        assert report["autoscaler"]["ledger"]
        assert any(e["action"] == "scale_out"
                   for e in report["autoscaler"]["ledger"])
        files = list(tmp_path.glob("spawn-pressure-drill-*.json"))
        assert files and json.loads(
            files[0].read_text())["autoscaler"]["ledger"]
        # after the act clears and traffic stops: scaled back in
        assert _wait(lambda: any(e["action"] == "scale_in"
                                 for e in a.ledger()), timeout_s=15.0)
        assert _wait(lambda: len(router.backends) == 1, timeout_s=10.0)


# ---------------------------------------------------------------------------
# THE chaos acceptance (@slow): SIGKILL under load -> automatic
# replacement that warms, passes /readyz, and is re-admitted


_POOL_BACKEND_SCRIPT = textwrap.dedent("""
    import sys, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from deeplearning4j_tpu.serving import (ModelRegistry, ModelServer,
                                            spec)
    port, scale = int(sys.argv[1]), float(sys.argv[2])

    def fwd(v, x):
        return jnp.zeros((x.shape[0], 1), jnp.float32) + v["scale"]

    reg = ModelRegistry()
    reg.register("scale", fwd, {"scale": scale}, input_spec=spec((4,)),
                 version="v1", mode="batched", max_batch_size=8)
    srv = ModelServer(reg, port=port, sentinel=False)
    srv.start(warm=True)
    while True:
        time.sleep(3600)
""")


def _pool_argv(name, port):
    # scale derives from the SLOT ("b1-r1" -> "b1" -> 2.0), so a
    # replacement provably answers for its dead predecessor's share
    slot = name.split("-")[0]
    scale = 1.0 + float(int(slot.lstrip("b")))
    return [sys.executable, "-c", _POOL_BACKEND_SCRIPT, str(port),
            str(scale)]


@pytest.mark.slow
class TestChaosSelfHealing:
    def test_sigkill_under_load_spawns_warm_replacement(self):
        """SIGKILL a subprocess backend mid-load: the autoscaler
        classifies it dead via the launcher, launches a replacement
        that warms and passes /readyz, and the router re-admits it —
        zero client-visible critical failures; lockorder sanitizer
        armed across router + autoscaler + launcher the whole time."""
        with pytest.MonkeyPatch.context() as mp:
            mp.setenv("DL4J_TPU_SANITIZERS", "lockorder")
            mp.setenv("DL4J_TPU_LOCKCHECK_HOLD_S", "30")
            lockcheck.reset()
            env = dict(os.environ, JAX_PLATFORMS="cpu")
            launcher = ProcessBackendLauncher(_pool_argv, env=env,
                                              grace_s=5.0)
            policy = RouterPolicy(probe_interval_s=0.25,
                                  probe_timeout_s=0.5,
                                  reprobe_after_s=0.5)
            # seed through add_backend, not the constructor: the
            # warming stamp holds traffic until a probe sees a real
            # ready /readyz, so wait_routable below means "the
            # subprocess is genuinely serving" — constructor seeds are
            # optimistically routable while the child still imports
            router = FleetRouter([], policy=policy).start()
            urls = [(n, launcher.spawn(n)) for n in ("b0", "b1")]
            for n, u in urls:
                router.add_backend(n, u)
            a = Autoscaler(
                router, launcher,
                policy=AutoscalerPolicy(
                    min_backends=2, max_backends=4, fire_after=3,
                    clear_after=2, idle_fire_after=999999,
                    cooldown_s=60.0, dead_fire_after=2,
                    tick_interval_s=0.25, spawn_grace_s=120.0)).attach()
            for n, _ in urls:
                a._spawned_t[n] = a._clock()
                a._slot_of[n] = n
            try:
                for n, _ in urls:
                    assert router.wait_routable(n, timeout_s=90.0), \
                        f"{n} never became routable"
                a.start()
                served, failures = [], []
                lock = threading.Lock()
                stop = threading.Event()

                def client_loop(tid):
                    c = ServingClient(router.url, max_retries=3,
                                      backoff_base_s=0.02,
                                      retry_seed=tid)
                    x = np.zeros((1, 4), np.float32)
                    while not stop.is_set():
                        try:
                            out = c.predict("scale", x,
                                            deadline_ms=30000)
                            with lock:
                                served.append(out["outputs"][0][0])
                        except Exception as e:  # noqa: BLE001
                            with lock:
                                failures.append(e)
                        time.sleep(0.02)

                ts = [threading.Thread(target=client_loop, args=(i,))
                      for i in range(4)]
                for t in ts:
                    t.start()
                time.sleep(1.0)                  # load is flowing
                victim = launcher._procs["b1"]
                t_kill = time.monotonic()
                victim.send_signal(signal.SIGKILL)
                victim.wait(timeout=10)
                # the loop replaces the corpse with slot lineage
                assert _wait(
                    lambda: any(e["action"] == "replace"
                                and e.get("backend") == "b1"
                                for e in a.ledger()),
                    timeout_s=20.0), a.ledger()
                # the replacement warms and is re-admitted
                assert router.wait_routable("b1-r1", timeout_s=90.0)
                mttr_s = time.monotonic() - t_kill
                stop.set()
                for t in ts:
                    t.join(timeout=30)
                assert failures == [], [repr(f) for f in failures[:3]]
                assert len(served) > 50
                # the replacement actually serves slot b1's model
                c = ServingClient(router.url, max_retries=2)
                x = np.zeros((1, 4), np.float32)
                seen = {c.predict("scale", x)["outputs"][0][0]
                        for _ in range(16)}
                assert len(seen) == 2, seen
                assert mttr_s < 120.0, f"MTTR {mttr_s:.1f}s"
                hist = a.metrics.spawn_to_routable_seconds.to_json()
                assert hist["samples"]
                assert lockcheck.violations() == [], \
                    lockcheck.render_report()
            finally:
                a.stop()
                router.stop()
                launcher.stop_all()
