"""Scripted game-days (PR 17): the fast single-server drill matrix
(fault act + gate table + report artifact + client-vs-fleet
reconciliation + metrics/flight trail, failing gates, the JSON script
grammar with hook binding) and THE slow acceptance: a ledger-recorded
mixed predict+generate trace replayed at 10x against a 3-subprocess-
backend router fleet while one backend is SIGKILLed and
``serving.latency`` fires on a survivor — zero critical-class failures,
every gate green, and the report artifact carries the survivor's
incident bundle, the per-act verdicts, and a consistent client-vs-fleet
reconciliation.

Budget discipline: the fast drills ride the shared ``mixed_server``
conftest fixture (tier-1 proxies for the drill semantics); only the
slow class pays for subprocess backends.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import time
import urllib.request

import pytest

from deeplearning4j_tpu.observability.flightrecorder import (
    get_flight_recorder,
)
from deeplearning4j_tpu.resilience import faults as ft
from deeplearning4j_tpu.resilience import gameday as gd
from deeplearning4j_tpu.resilience import replay as rp
from deeplearning4j_tpu.serving import (
    FleetRouter,
    RouterPolicy,
    ServingClient,
)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


@pytest.fixture(autouse=True)
def _clean_injector():
    """Fault acts install on the PROCESS injector; never leak an armed
    plan into the rest of the suite."""
    yield
    ft.set_fault_injector(ft.FaultInjector())


def _predict_trace(n, *, rate=40.0, critical_every=4):
    rows = []
    t = 0.0
    for i in range(n):
        rows.append({
            "plane": "predict", "model": "scale",
            "arrival_offset_s": round(t, 6),
            "priority": "critical" if i % critical_every == 0
            else "normal",
            "tenant": f"gdt-{i % 2}", "payload_shape": [1, 4],
            "deadline_s": 20.0, "stream": False})
        t += 1.0 / rate
    return rp.validate_trace({
        "version": 1, "kind": "dl4j_tpu_trace", "t0_wall": None,
        "count": n, "duration_s": rows[-1]["arrival_offset_s"],
        "rows": rows})


# ---------------------------------------------------------------------------
# fast drills against the shared in-process mixed server


class TestGameDayFast:
    def test_drill_with_fault_act_reports_and_reconciles(
            self, mixed_server, tmp_path):
        """A passing drill: mixed predict+generate replay at 10x, a
        timed ``serving.latency`` fault act, full gate table green,
        report artifact on disk, fleet counters reconciling with the
        client ledger, and the ``gameday.*`` flight trail."""
        url = f"http://127.0.0.1:{mixed_server.port}"
        trace = rp.synthesize_trace({
            "n": 14, "rate_rps": 30.0, "seed": 5,
            "models": [
                {"name": "scale", "plane": "predict",
                 "payload_shape": [1, 4], "weight": 3.0,
                 "deadline_s": 20.0},
                {"name": "gpt", "plane": "generation", "prompt_len": 4,
                 "max_new_tokens": 3, "deadline_s": 20.0}],
            "priorities": {"critical": 1, "normal": 3},
            "tenants": ["gd-a", "gd-b"]})
        m = gd.get_gameday_metrics()
        runs_before = m.runs_total.value(verdict="pass")
        drill = gd.GameDay(
            url, trace, name="fast-drill", speed=10.0, clients=4,
            report_dir=str(tmp_path),
            acts=[gd.Act(0.05, "fault",
                         spec="serving.latency@1x3:0.02",
                         name="latency-burst")],
            gates=[gd.Gate("critical_failures"),
                   gd.Gate("availability", min_ratio=0.9),
                   gd.Gate("p99", max_s=10.0),
                   gd.Gate("recompiles", max_count=0)])
        report = drill.run()
        assert report["verdict"] == "pass", report["gates"]
        assert all(v["passed"] for v in report["gates"])
        assert report["acts"] == [
            {"name": "latency-burst", "kind": "fault", "at_s": 0.05,
             "spec": "serving.latency@1x3:0.02", "backend": None,
             "fired": True, "error": None}]
        rec = report["reconciliation"]
        assert rec["consistent"] is True
        assert rec["client_requests"] == 14
        assert rec["fleet_served_total"] >= rec["client_ok"]
        assert "serving_requests_total" in rec["fleet_counters"]
        # artifact on disk, loadable, same verdict
        files = list(tmp_path.glob("fast-drill-*.json"))
        assert len(files) == 1
        on_disk = json.loads(files[0].read_text())
        assert on_disk["verdict"] == "pass"
        assert len(on_disk["gates"]) == 4
        assert m.runs_total.value(verdict="pass") == runs_before + 1
        kinds = {e["kind"] for e in get_flight_recorder().events(
            kinds=("gameday.start", "gameday.act", "gameday.gate",
                   "gameday.report", "gameday.complete"),
            max_events=100)}
        assert kinds == {"gameday.start", "gameday.act", "gameday.gate",
                         "gameday.report", "gameday.complete"}

    def test_breached_gate_fails_the_drill_and_counts(self,
                                                      mixed_server):
        url = f"http://127.0.0.1:{mixed_server.port}"
        m = gd.get_gameday_metrics()
        breach_before = m.gates_total.value(result="breach")
        fail_before = m.runs_total.value(verdict="fail")
        drill = gd.GameDay(
            url, _predict_trace(4), name="doomed", speed=10.0,
            clients=2,
            gates=[gd.Gate("p99", max_s=0.0),  # unmeetable
                   gd.Gate("availability", min_ratio=0.5)])
        report = drill.run()
        assert report["verdict"] == "fail"
        by_gate = {v["gate"]: v for v in report["gates"]}
        assert by_gate["p99"]["passed"] is False
        assert by_gate["availability"]["passed"] is True
        assert m.gates_total.value(result="breach") == breach_before + 1
        assert m.runs_total.value(verdict="fail") == fail_before + 1
        # worst requests are ranked and bounded
        assert report["worst_requests"]
        assert len(report["worst_requests"]) <= 8

    def test_from_script_binds_hooks_and_runs_kill_gates(
            self, mixed_server):
        """The declarative JSON grammar: a kill act bound through a
        named hook, an MTTR gate anchored to it, and a scoped
        availability gate judged from the kill onward."""
        url = f"http://127.0.0.1:{mixed_server.port}"
        fired = []
        script = {
            "name": "scripted",
            "speed": 10, "clients": 3,
            "acts": [{"at_s": 0.0, "kind": "kill",
                      "hook": "kill-victim", "name": "kill-victim"}],
            "gates": [{"kind": "mttr", "max_s": 10.0},
                      {"kind": "availability", "scope": "kill-victim",
                       "min_ratio": 0.9,
                       "name": "availability-after-kill"},
                      {"kind": "critical_failures"}]}
        drill = gd.GameDay.from_script(
            script, base_url=url, trace=_predict_trace(20, rate=10.0),
            hooks={"kill-victim": lambda: fired.append(True)})
        report = drill.run()
        assert fired == [True]
        assert report["verdict"] == "pass", report["gates"]
        by_gate = {v["gate"]: v for v in report["gates"]}
        assert by_gate["mttr"]["value"] is not None
        assert by_gate["availability-after-kill"]["scope"] == \
            "kill-victim"

    def test_from_script_rejects_unbound_hook(self, mixed_server):
        with pytest.raises(ValueError, match="unbound hook"):
            gd.GameDay.from_script(
                {"acts": [{"at_s": 0.0, "kind": "kill",
                           "hook": "nope"}]},
                base_url="http://127.0.0.1:1", trace=_predict_trace(1))

    def test_act_errors_are_reported_not_raised(self, mixed_server):
        """A hook that blows up marks ITS act and the drill keeps
        running — a half-executed script still yields a report."""
        url = f"http://127.0.0.1:{mixed_server.port}"

        def boom():
            raise RuntimeError("chaos tooling fell over")

        drill = gd.GameDay(
            url, _predict_trace(4), name="act-err", speed=10.0,
            clients=2,
            acts=[gd.Act(0.0, "call", fn=boom, name="boom")],
            gates=[gd.Gate("availability", min_ratio=0.9)])
        report = drill.run()
        (act,) = report["acts"]
        assert act["fired"] is True
        assert "chaos tooling fell over" in act["error"]
        assert report["verdict"] == "pass"


class TestFleetHealthGate:
    """The ``fleet_health`` gate (PR 19): judged from the target's own
    SLO federation — the server-side cross-check of the client-ledger
    gates. Pure-logic units plus one live-drill leg on the shared
    mixed server."""

    def test_passes_when_no_rule_fires(self):
        g = gd.Gate("fleet_health")
        health = {"status": "ok", "rules": [
            {"name": "fleet-availability", "state": "ok"},
            {"name": "fleet-latency-p99", "state": "pending"}]}
        v = g.evaluate([], [], {}, health)
        assert v["passed"] is True
        assert v["value"] == 0
        assert v["kind"] == "fleet_health"

    def test_breaches_on_any_firing_rule_and_names_them(self):
        g = gd.Gate("fleet_health")
        health = {"status": "firing", "rules": [
            {"name": "fleet-ejection-churn", "state": "firing"},
            {"name": "fleet-availability", "state": "firing"},
            {"name": "fleet-latency-p99", "state": "ok"}]}
        v = g.evaluate([], [], {}, health)
        assert v["passed"] is False
        assert v["value"] == ["fleet-availability",
                              "fleet-ejection-churn"]

    def test_unreachable_health_is_a_breach_not_a_crash(self):
        g = gd.Gate("fleet_health")
        assert g.evaluate([], [], {}, None)["passed"] is False
        # a malformed doc (no rules list) is just as unusable
        assert g.evaluate([], [], {},
                          {"status": "ok"})["passed"] is False

    def test_from_script_and_live_drill_carry_fleet_health(
            self, mixed_server):
        """A drill scripted with a fleet_health gate polls the
        target's ``/debug/health`` and the report carries the rule
        states it judged."""
        url = f"http://127.0.0.1:{mixed_server.port}"
        drill = gd.GameDay.from_script(
            {"name": "fleet-health-drill", "speed": 10, "clients": 2,
             "gates": [{"kind": "fleet_health"},
                       {"kind": "availability", "min_ratio": 0.5}]},
            base_url=url, trace=_predict_trace(4))
        report = drill.run()
        assert report["fleet_health"] is not None
        assert all(set(r) == {"name", "state"}
                   for r in report["fleet_health"]["rules"])
        by_gate = {v["gate"]: v for v in report["gates"]}
        assert by_gate["fleet_health"]["passed"] is True

    def test_fetch_fleet_health_none_on_unreachable(self):
        assert gd.fetch_fleet_health(
            f"http://127.0.0.1:{_free_port()}") is None


# ---------------------------------------------------------------------------
# THE slow acceptance: recorded trace at 10x vs a subprocess router
# fleet, one backend SIGKILLed, serving.latency firing on a survivor


_GD_BACKEND_SCRIPT = textwrap.dedent("""
    import sys, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from deeplearning4j_tpu.models.gpt import gpt_tiny
    from deeplearning4j_tpu.observability import sentinel as sn
    from deeplearning4j_tpu.serving import (GenerationEngine,
                                            ModelRegistry, ModelServer,
                                            spec)
    port, scale, incident_dir = (int(sys.argv[1]), float(sys.argv[2]),
                                 sys.argv[3])

    def fwd(v, x):
        return jnp.zeros((x.shape[0], 1), jnp.float32) + v["scale"]

    reg = ModelRegistry()
    reg.register("scale", fwd, {"scale": scale}, input_spec=spec((4,)),
                 mode="batched", max_batch_size=8)
    model = gpt_tiny()
    eng = GenerationEngine(
        model, model.init(seed=0), name="gpt", num_slots=2, max_len=32,
        max_new_tokens=24, min_kv_bucket=8, min_prompt_bucket=8,
        idle_wait_s=0.002, temperature=0.0, max_waiting=16, seed=0)
    if incident_dir != "-":
        # a tight absolute p99 ceiling: the injected serving.latency
        # (0.06 s) trips it within two sentinel ticks and opens an
        # incident bundle the router then federates
        det = sn.Detector(
            "p99", sn.HistogramQuantileProbe(
                "serving_request_latency_seconds", q=0.99, min_count=1),
            mode="ceiling", threshold=0.04, fire_after=2,
            clear_after=10000)
        kw = dict(sentinel=True, sentinel_detectors=[det],
                  sentinel_interval_s=0.15, incident_dir=incident_dir)
    else:
        kw = dict(sentinel=False)
    srv = ModelServer(reg, port=port, generators={"gpt": eng}, **kw)
    srv.start(warm=True)
    print("READY", srv.port, flush=True)
    while True:
        time.sleep(3600)
""")


def _spawn_gd_backend(port, scale, *, incident_dir=None, faults=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DL4J_TPU_FAULTS", None)
    if faults:
        env["DL4J_TPU_FAULTS"] = faults
    return subprocess.Popen(
        [sys.executable, "-c", _GD_BACKEND_SCRIPT, str(port),
         str(scale), incident_dir or "-"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)


def _await_ready(proc, timeout_s=180.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("READY"):
            return True
        if proc.poll() is not None:
            return False
    return False


@pytest.fixture(scope="class")
def gameday_fleet(tmp_path_factory):
    """3 REAL subprocess mixed predict+generation backends behind one
    router: b1 is the SIGKILL victim; b2 the survivor with
    ``serving.latency`` armed via its environment AND a sentinel whose
    p99 ceiling detector opens the incident bundle the drill report
    must carry."""
    incident_dir = str(tmp_path_factory.mktemp("gd-incidents"))
    ports = [_free_port() for _ in range(3)]
    procs = [
        _spawn_gd_backend(ports[0], 1.0),
        _spawn_gd_backend(ports[1], 2.0),
        _spawn_gd_backend(ports[2], 3.0, incident_dir=incident_dir,
                          faults="serving.latency@1x300:0.06"),
    ]
    try:
        if not all(_await_ready(p) for p in procs):
            pytest.skip("subprocess backends failed to start")
        policy = RouterPolicy(probe_interval_s=0.25,
                              probe_timeout_s=0.5,
                              reprobe_after_s=0.5)
        router = FleetRouter(
            [(f"b{i}", f"http://127.0.0.1:{p}")
             for i, p in enumerate(ports)], policy=policy).start()
        try:
            ns = type("GameDayFleet", (), {})()
            ns.ports = ports
            ns.procs = procs
            ns.router = router
            ns.incident_dir = incident_dir
            yield ns
        finally:
            router.stop()
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass


def _record_mixed_trace(server, *, n=30, gap_s=0.2):
    """Drive REAL mixed traffic through the shared in-process server so
    its ledger records it, then export the trace over HTTP — the drill
    replays a recording, not a synthetic guess. Critical rows stay on
    the retryable wire modes (predict / collected generate)."""
    url = f"http://127.0.0.1:{server.port}"
    c = ServingClient(url, max_retries=2)
    x = [[0.0, 0.0, 0.0, 0.0]]
    for i in range(n):
        prio = "critical" if i % 4 == 0 else "normal"
        tenant = f"gd-acc-{i % 3}"
        if i % 5 == 3:
            c.generate_tokens("gpt", [1, 2, 3, 4], max_new_tokens=3,
                              priority=prio, tenant=tenant,
                              deadline_ms=20000)
        elif i % 10 == 6:
            list(c.generate("gpt", [1, 2, 3], max_new_tokens=3,
                            priority="normal", tenant=tenant,
                            deadline_ms=20000))
        else:
            c.predict("scale", x, priority=prio, tenant=tenant,
                      deadline_ms=20000)
        time.sleep(gap_s)
    doc = _get(f"{url}/debug/requests?format=trace")
    rows = [r for r in doc["rows"]
            if (r["tenant"] or "").startswith("gd-acc-")]
    assert len(rows) == n
    base = rows[0]["arrival_offset_s"]
    for r in rows:
        r["arrival_offset_s"] = round(r["arrival_offset_s"] - base, 6)
    return rp.validate_trace({
        "version": 1, "kind": "dl4j_tpu_trace", "t0_wall": None,
        "count": n, "duration_s": rows[-1]["arrival_offset_s"],
        "rows": rows})


@pytest.mark.slow
class TestGameDayAcceptance:
    def test_recorded_trace_10x_sigkill_and_latency_all_gates_green(
            self, gameday_fleet, mixed_server, tmp_path):
        """THE acceptance. A trace recorded from real mixed traffic is
        replayed at 10x against the router fleet; mid-replay the script
        SIGKILLs b1 while b2's environment-armed ``serving.latency``
        degrades it enough to trip its sentinel. Zero critical-class
        client-visible failures, availability / MTTR / p99 / recompile
        gates all green, the report artifact carries the survivor's
        incident bundle and per-act verdicts, and the client-side
        counts reconcile against the federated fleet scrape."""
        trace = _record_mixed_trace(mixed_server, n=30, gap_s=0.2)
        router = gameday_fleet.router
        victim = gameday_fleet.procs[1]

        def kill_victim():
            victim.send_signal(signal.SIGKILL)
            victim.wait(timeout=10)

        def await_incident():
            """Hold the drill open until the survivor's sentinel fires,
            sustaining probe traffic AT the degraded survivor so its
            delta-based p99 probe sees elevated samples on consecutive
            ticks (the quantile probe judges per-tick deltas; a replay
            tail too sparse to land a request every tick would leave it
            unjudgeable, not healthy)."""
            pump = ServingClient(
                f"http://127.0.0.1:{gameday_fleet.ports[2]}",
                max_retries=1)
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if _get(router.url + "/debug/incidents")["incidents"]:
                    return
                try:
                    pump.predict("scale", [[0.0, 0.0, 0.0, 0.0]],
                                 deadline_ms=5000)
                except Exception:  # noqa: BLE001 — pump only
                    time.sleep(0.1)

        script = {
            "name": "evacuate-b1",
            "speed": 10, "clients": 6,
            "acts": [
                {"at_s": 0.25, "kind": "kill", "hook": "kill-victim",
                 "name": "kill-victim"},
                {"at_s": 0.4, "kind": "fault",
                 "spec": "router.backend_latency@1x20:0.01",
                 "name": "router-latency"},
                {"at_s": 1.0, "kind": "call", "hook": "await-incident",
                 "name": "await-incident"},
            ],
            "gates": [
                {"kind": "critical_failures", "max_count": 0},
                {"kind": "availability", "min_ratio": 0.97},
                {"kind": "mttr", "max_s": 8.0},
                {"kind": "p99", "max_s": 10.0},
                {"kind": "recompiles", "max_count": 0},
                {"kind": "availability", "scope": "kill-victim",
                 "min_ratio": 0.97, "name": "availability-after-kill"},
            ]}
        drill = gd.GameDay.from_script(
            script, base_url=router.url, trace=trace,
            hooks={"kill-victim": kill_victim,
                   "await-incident": await_incident},
            report_dir=str(tmp_path), token_read_delay_s=0.01)
        report = drill.run()

        # every gate green, zero critical-class client failures
        assert report["verdict"] == "pass", report["gates"]
        by_gate = {v["gate"]: v for v in report["gates"]}
        assert by_gate["critical_failures"]["value"] == 0
        assert by_gate["availability"]["value"] >= 0.97
        assert by_gate["mttr"]["value"] <= 8.0
        assert by_gate["recompiles"]["value"] == 0
        assert report["replay"]["requests"] == 30
        assert report["replay"]["by_outcome"].get("shed", 0) == 0

        # per-act verdicts: everything fired, nothing errored
        acts = {a["name"]: a for a in report["acts"]}
        assert set(acts) == {"kill-victim", "router-latency",
                             "await-incident"}
        assert all(a["fired"] and a["error"] is None
                   for a in acts.values())

        # the survivor's sentinel opened an incident under the injected
        # latency and the router federated it into the report
        assert report["incidents"], "no incident bundle in the report"

        # client counts reconcile against the federated fleet scrape
        rec = report["reconciliation"]
        assert rec["consistent"] is True, rec
        assert rec["client_ok"] == 30
        assert rec["fleet_served_total"] >= rec["client_ok"]

        # the artifact on disk tells the same story
        files = list(tmp_path.glob("evacuate-b1-*.json"))
        assert len(files) == 1
        on_disk = json.loads(files[0].read_text())
        assert on_disk["verdict"] == "pass"
        assert on_disk["incidents"]

        # and the victim really is dead and ejected
        assert victim.poll() is not None
        assert not router.backend("b1").routable
