"""Stateful RNN inference + text generation (↔ rnnTimeStep +
TextGenerationLSTM sampling loop)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.zoo.classic import (
    text_generation_lstm,
    text_generation_lstm_config,
)
from deeplearning4j_tpu.nn import layers as L
from deeplearning4j_tpu.nn.generation import RnnTimeStepper, generate
from deeplearning4j_tpu.nn.model import SequentialModel


@pytest.fixture(scope="module")
def char_model():
    model = text_generation_lstm(vocab_size=11, hidden=16, seq_len=8)
    variables = model.init(seed=0)
    return model, variables


def test_time_step_matches_full_sequence(char_model):
    """Stepping one timestep at a time must equal the full-sequence forward
    (the reference's rnnTimeStep-vs-output consistency contract)."""
    model, variables = char_model
    x = jax.nn.one_hot(
        np.random.default_rng(0).integers(0, 11, (3, 8)), 11)
    full = model.output(variables, x)  # [3, 8, 11] per-step softmax
    stepper = RnnTimeStepper(model, variables)
    outs = [stepper.time_step(x[:, t]) for t in range(8)]
    np.testing.assert_allclose(np.asarray(outs[-1]), np.asarray(full[:, -1]),
                               rtol=2e-5, atol=2e-5)
    # every intermediate step matches too
    for t in range(8):
        np.testing.assert_allclose(np.asarray(outs[t]),
                                   np.asarray(full[:, t]),
                                   rtol=2e-5, atol=2e-5)


def test_time_step_clear_state(char_model):
    model, variables = char_model
    x0 = jax.nn.one_hot(jnp.zeros((2,), jnp.int32), 11)
    stepper = RnnTimeStepper(model, variables)
    a = stepper.time_step(x0)
    stepper.time_step(x0)  # advance state
    stepper.clear_state()
    b = stepper.time_step(x0)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_time_step_multi_step_input(char_model):
    model, variables = char_model
    x = jax.nn.one_hot(
        np.random.default_rng(1).integers(0, 11, (2, 5)), 11)
    s1 = RnnTimeStepper(model, variables)
    out_chunk = s1.time_step(x)  # [N,T,C] at once
    s2 = RnnTimeStepper(model, variables)
    for t in range(5):
        out_seq = s2.time_step(x[:, t])
    np.testing.assert_allclose(np.asarray(out_chunk), np.asarray(out_seq),
                               rtol=1e-6)


def test_generate_shapes_and_determinism(char_model):
    model, variables = char_model
    ids = generate(model, variables, n_steps=12, rng=jax.random.key(0),
                   prime=jnp.array([1, 2, 3]), temperature=0.8, batch_size=2)
    assert ids.shape == (2, 12)
    assert int(ids.min()) >= 0 and int(ids.max()) < 11
    ids2 = generate(model, variables, n_steps=12, rng=jax.random.key(0),
                    prime=jnp.array([1, 2, 3]), temperature=0.8, batch_size=2)
    np.testing.assert_array_equal(np.asarray(ids), np.asarray(ids2))


def test_generate_learns_pattern():
    """Overfit a repeating sequence; generation must reproduce it (the
    zoo TextGenerationLSTM capability check)."""
    from deeplearning4j_tpu.train.trainer import Trainer
    from deeplearning4j_tpu.train.updaters import Adam

    vocab, period = 6, 6
    seq = np.tile(np.arange(period), 20)  # 0 1 2 3 4 5 0 1 2 ...
    T = 24
    windows = np.stack([seq[i:i + T + 1] for i in range(40)])
    eye = np.eye(vocab, dtype=np.float32)
    batch = {"features": eye[windows[:, :-1]], "labels": eye[windows[:, 1:]]}

    model = SequentialModel(text_generation_lstm_config(
        vocab_size=vocab, hidden=32, seq_len=T, updater=Adam(5e-3), seed=3))
    tr = Trainer(model)
    ts = tr.init_state()
    for _ in range(150):
        ts, m = tr.train_step(ts, batch)
    assert float(m["total_loss"]) < 0.3, float(m["total_loss"])

    ids = generate(model, tr.variables(ts), n_steps=18,
                   rng=jax.random.key(1), prime=jnp.array([0, 1, 2]),
                   temperature=0.2)
    got = np.asarray(ids[0])
    expected = np.arange(3, 3 + 18) % period
    assert (got == expected).mean() > 0.8, (got, expected)


def test_generation_rejects_non_recurrent_models():
    from deeplearning4j_tpu.nn.config import (
        NeuralNetConfiguration,
        SequentialConfig,
    )

    model = SequentialModel(SequentialConfig(
        net=NeuralNetConfiguration(seed=0), input_shape=(4,),
        layers=[L.Dense(units=3), L.OutputLayer(units=2)]))
    with pytest.raises(ValueError, match="no recurrent"):
        RnnTimeStepper(model, model.init())


def test_generate_prime_batch_mismatch_raises(char_model):
    model, variables = char_model
    with pytest.raises(ValueError, match="batch"):
        generate(model, variables, n_steps=3, rng=jax.random.key(0),
                 prime=jnp.ones((4, 3), jnp.int32), batch_size=1)


def test_generate_reuses_compiled_runner(char_model):
    model, variables = char_model
    generate(model, variables, n_steps=5, rng=jax.random.key(0))
    cache = model.__dict__["_generate_cache"]
    assert (5, 1.0) in cache
    before = cache[(5, 1.0)]
    generate(model, variables, n_steps=5, rng=jax.random.key(1))
    assert cache[(5, 1.0)] is before  # no rebuild


def test_generate_rejects_vocab_mismatch():
    from deeplearning4j_tpu.nn.config import (
        NeuralNetConfiguration,
        SequentialConfig,
    )

    model = SequentialModel(SequentialConfig(
        net=NeuralNetConfiguration(seed=0), input_shape=(4, 5),
        layers=[L.SimpleRnn(units=6),
                L.RnnOutputLayer(units=9)]))  # head 9 != input one-hot 5
    with pytest.raises(ValueError, match="head width"):
        generate(model, model.init(), n_steps=2, rng=jax.random.key(0))


def test_time_step_empty_time_axis_raises(char_model):
    model, variables = char_model
    stepper = RnnTimeStepper(model, variables)
    with pytest.raises(ValueError, match="empty time axis"):
        stepper.time_step(jnp.zeros((2, 0, 11)))


class TestBeamSearch:
    """Oracles for the compiled beam search (KV-cache expand/reorder
    inside one lax.scan program)."""

    def _model(self, vocab=16):
        from deeplearning4j_tpu.models.gpt import gpt_tiny

        m = gpt_tiny(vocab_size=vocab, hidden=32, num_layers=2,
                     num_heads=2, intermediate=64, max_position=32)
        return m, m.init(seed=0)

    def test_beam1_equals_greedy(self):
        """beam_size=1 with no penalty IS greedy decoding — must match
        generate(temperature=0) token for token."""
        m, v = self._model()
        prime = jnp.asarray([[3, 5, 7], [2, 4, 6]], jnp.int32)
        greedy = m.generate(v, prime, n_steps=6, rng=jax.random.key(0),
                            temperature=0.0)
        seqs, scores = m.beam_search(v, prime, n_steps=6, beam_size=1)
        np.testing.assert_array_equal(np.asarray(seqs[:, 0]),
                                      np.asarray(greedy))
        assert scores.shape == (2, 1)

    def test_beam_equals_bruteforce_when_exact(self):
        """With beam_size == vocab and depth 2, beam search is EXACT:
        compare the returned top beams against brute-force enumeration
        of all vocab^2 continuations scored by the full forward."""
        V = 6
        m, v = self._model(vocab=V)
        prime = jnp.asarray([[1, 2]], jnp.int32)
        seqs, scores = m.beam_search(v, prime, n_steps=2, beam_size=V)

        # brute force: log p(a|prime) + log p(b|prime+a) via full forward
        def logits_for(ids):
            out, _ = m.apply(v, jnp.asarray([ids], jnp.int32))
            return jax.nn.log_softmax(out[0, -1].astype(jnp.float32))

        base = logits_for([1, 2])
        all_scores = {}
        for a in range(V):
            nxt = logits_for([1, 2, a])
            for b in range(V):
                all_scores[(a, b)] = float(base[a]) + float(nxt[b])
        want = sorted(all_scores.items(), key=lambda kv: -kv[1])[:V]
        got = [(tuple(int(t) for t in seqs[0, i]), float(scores[0, i]))
               for i in range(V)]
        for (w_seq, w_score), (g_seq, g_score) in zip(want, got):
            assert w_seq == g_seq, (want, got)
            np.testing.assert_allclose(g_score, w_score, rtol=1e-4,
                                       atol=1e-5)

    def test_reported_scores_match_full_forward(self):
        """Whatever sequences come back, their reported score must equal
        the sum of next-token log-probs computed by the FULL forward
        (KV-cache path == full-attention path, plus correct backtrace)."""
        m, v = self._model()
        prime = jnp.asarray([[4, 9, 2, 7]], jnp.int32)
        n_steps, B = 5, 3
        seqs, scores = m.beam_search(v, prime, n_steps=n_steps, beam_size=B)
        for bi in range(B):
            ids = list(map(int, prime[0])) + [int(t) for t in seqs[0, bi]]
            out, _ = m.apply(v, jnp.asarray([ids], jnp.int32))
            lp = jax.nn.log_softmax(out[0].astype(jnp.float32), axis=-1)
            want = sum(float(lp[len(prime[0]) - 1 + t, ids[len(prime[0]) + t]])
                       for t in range(n_steps))
            np.testing.assert_allclose(float(scores[0, bi]), want,
                                       rtol=1e-4, atol=1e-5)
        # sorted best-first
        s = np.asarray(scores[0])
        assert np.all(s[:-1] >= s[1:] - 1e-6)

    def test_eos_freezes_beam(self):
        """A beam that emits eos keeps continuing on eos with logprob 0:
        its score stops changing and its tail is all eos."""
        V = 8
        m, v = self._model(vocab=V)
        prime = jnp.asarray([[1, 2, 3]], jnp.int32)
        eos = 0
        seqs, scores = m.beam_search(v, prime, n_steps=6, beam_size=V,
                                     eos_id=eos)
        found = False
        for bi in range(V):
            row = [int(t) for t in seqs[0, bi]]
            if eos in row:
                k = row.index(eos)
                assert all(t == eos for t in row[k:]), row
                found = True
        assert found, "with beam_size == vocab some beam must hit eos"
