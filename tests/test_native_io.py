"""Native CSV fast-path tests (native/src/fast_io.cpp via ctypes shim).

Parity oracle: the native parser against numpy/python parsing of the
same files — the same strategy the native-runtime tests use (compile if
needed, skip when no toolchain)."""

import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

ROOT = Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def native_lib():
    from deeplearning4j_tpu.data import native_csv

    if not native_csv.available():
        # build on demand (no PJRT dependency for the IO lib)
        r = subprocess.run(["make", "-C", str(ROOT / "native"),
                            "lib/libdl4j_tpu_io.so"],
                           capture_output=True, text=True)
        native_csv._lib = None  # re-probe
        if not native_csv.available():
            pytest.skip(f"native IO lib unavailable: {r.stderr[-300:]}")
    return native_csv


def test_parity_with_numpy(native_lib, tmp_path):
    rng = np.random.default_rng(0)
    want = rng.normal(size=(200, 7)).astype(np.float32)
    p = tmp_path / "data.csv"
    np.savetxt(p, want, delimiter=",", fmt="%.6e")
    got = native_lib.read_csv_f32(p)
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_header_blank_lines_spaces_and_empties(native_lib, tmp_path):
    p = tmp_path / "messy.csv"
    p.write_text("a,b,c\n"            # header
                 "1, 2 ,3\n"
                 "\n"                  # blank line ignored
                 " 4,,6\r\n"           # empty field -> NaN; CRLF trimmed
                 "7,8.5e-1,-9\n")
    got = native_lib.read_csv_f32(p, skip_header=True)
    assert got.shape == (3, 3)
    np.testing.assert_allclose(got[0], [1, 2, 3])
    assert np.isnan(got[1, 1]) and got[1, 0] == 4 and got[1, 2] == 6
    np.testing.assert_allclose(got[2], [7, 0.85, -9])


def test_ragged_and_nonnumeric_rejected(native_lib, tmp_path):
    ragged = tmp_path / "ragged.csv"
    ragged.write_text("1,2,3\n4,5\n")
    with pytest.raises(ValueError, match="ragged"):
        native_lib.read_csv_f32(ragged)
    bad = tmp_path / "bad.csv"
    bad.write_text("1,2\n3,dog\n")
    with pytest.raises(ValueError, match="parse error"):
        native_lib.read_csv_f32(bad)
    with pytest.raises(ValueError, match="open"):
        native_lib.read_csv_f32(tmp_path / "missing.csv")


def test_empty_file(native_lib, tmp_path):
    p = tmp_path / "empty.csv"
    p.write_text("")
    got = native_lib.read_csv_f32(p)
    assert got.shape[0] == 0


def test_reader_read_numeric_native_and_fallback(native_lib, tmp_path,
                                                 monkeypatch):
    from deeplearning4j_tpu.data import native_csv
    from deeplearning4j_tpu.data.records import CSVRecordReader

    rng = np.random.default_rng(1)
    want = rng.normal(size=(50, 4)).astype(np.float32)
    a, b = tmp_path / "a.csv", tmp_path / "b.csv"
    np.savetxt(a, want[:30], delimiter=",", fmt="%.6e")
    np.savetxt(b, want[30:], delimiter=",", fmt="%.6e")
    got = CSVRecordReader([a, b]).read_numeric()
    np.testing.assert_allclose(got, want, rtol=1e-6)

    # python fallback (library hidden) must agree
    monkeypatch.setattr(native_csv, "_lib", None)
    monkeypatch.setenv("DL4J_TPU_IO_LIB", "/nonexistent.so")
    got_py = CSVRecordReader([a, b]).read_numeric()
    np.testing.assert_allclose(got_py, want, rtol=1e-6)
    monkeypatch.setattr(native_csv, "_lib", None)  # re-probe next use


def test_throughput_smoke(native_lib, tmp_path):
    """Not a benchmark (CI box), just evidence the fast path is not slower
    than Python csv parsing on a non-trivial file."""
    import csv as _csv
    import time

    rng = np.random.default_rng(2)
    want = rng.normal(size=(20000, 16)).astype(np.float32)
    p = tmp_path / "big.csv"
    np.savetxt(p, want, delimiter=",", fmt="%.6e")

    t0 = time.perf_counter()
    native = native_lib.read_csv_f32(p)
    t_native = time.perf_counter() - t0

    t0 = time.perf_counter()
    with open(p) as f:
        rows = [[float(v) for v in r] for r in _csv.reader(f)]
    py = np.asarray(rows, np.float32)
    t_py = time.perf_counter() - t0

    np.testing.assert_allclose(native, py, rtol=1e-6)
    assert t_native < t_py, (t_native, t_py)


def test_quoted_numeric_falls_back_to_csv_path(native_lib, tmp_path):
    from deeplearning4j_tpu.data.records import CSVRecordReader

    p = tmp_path / "quoted.csv"
    p.write_text('"1.5","2.5"\n"3.0","4.0"\n')
    got = CSVRecordReader(p).read_numeric()
    np.testing.assert_allclose(got, [[1.5, 2.5], [3.0, 4.0]])


def test_skip_header_is_first_physical_line(native_lib, tmp_path):
    """Native and python paths agree on skip-first-PHYSICAL-line
    semantics even when the file starts oddly."""
    from deeplearning4j_tpu.data import native_csv
    from deeplearning4j_tpu.data.records import CSVRecordReader

    p = tmp_path / "h.csv"
    p.write_text("a,b\n1,2\n3,4\n")
    got_native = native_csv.read_csv_f32(p, skip_header=True)
    got_reader = CSVRecordReader(p, skip_lines=1).read_numeric()
    np.testing.assert_allclose(got_native, [[1, 2], [3, 4]])
    np.testing.assert_allclose(got_reader, got_native)


def test_tab_delimiter_empty_row_kept(native_lib, tmp_path):
    p = tmp_path / "tabs.tsv"
    p.write_text("1\t2\t3\n\t\t\n4\t5\t6\n")
    got = native_lib.read_csv_f32(p, delimiter="\t")
    assert got.shape == (3, 3)
    assert np.isnan(got[1]).all()
