"""DropConnect / WeightNoise tests (↔ weightnoise.* in the reference;
TestWeightNoise pattern: train-time transform, inference untouched)."""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn import layers as L
from deeplearning4j_tpu.nn.config import (NeuralNetConfiguration,
                                          SequentialConfig, config_from_json,
                                          config_to_json)
from deeplearning4j_tpu.nn.model import SequentialModel
from deeplearning4j_tpu.nn.weightnoise import DropConnect, WeightNoise
from deeplearning4j_tpu.train.trainer import Trainer
from deeplearning4j_tpu.train.updaters import Adam


def _model(noise):
    return SequentialModel(SequentialConfig(
        net=NeuralNetConfiguration(seed=0, updater=Adam(1e-2)),
        input_shape=(8,),
        layers=[
            L.Dense(units=32, activation="relu", weight_noise=noise),
            L.OutputLayer(units=4, activation="softmax", loss="mcxent"),
        ]))


def test_dropconnect_masks_at_train_only():
    model = _model(DropConnect(p=0.5))
    v = model.init(seed=0)
    x = jnp.ones((16, 8))
    y_inf, _ = model.apply(v, x)
    y_inf2, _ = model.apply(v, x)
    np.testing.assert_array_equal(np.asarray(y_inf), np.asarray(y_inf2))

    y_tr1, _ = model.apply(v, x, train=True, rng=jax.random.key(1))
    y_tr2, _ = model.apply(v, x, train=True, rng=jax.random.key(2))
    # different masks -> different activations; both differ from inference
    assert np.abs(np.asarray(y_tr1) - np.asarray(y_tr2)).max() > 1e-6
    assert np.abs(np.asarray(y_tr1) - np.asarray(y_inf)).max() > 1e-6


def test_dropconnect_keep_fraction_and_scaling():
    dc = DropConnect(p=0.8)
    w = jnp.ones((64, 64))
    out = dc.transform({"W": w, "b": jnp.ones((64,))},
                       jax.random.key(0), train=True)
    vals = np.asarray(out["W"]).ravel()
    kept = vals != 0.0
    assert abs(kept.mean() - 0.8) < 0.05
    np.testing.assert_allclose(vals[kept], 1.0 / 0.8, rtol=1e-6)
    # bias untouched by default
    np.testing.assert_array_equal(np.asarray(out["b"]), 1.0)


def test_weight_noise_additive_and_multiplicative():
    w = jnp.full((32, 32), 2.0)
    add = WeightNoise(std=0.1, additive=True).transform(
        {"W": w}, jax.random.key(0), train=True)["W"]
    mul = WeightNoise(std=0.1, additive=False).transform(
        {"W": w}, jax.random.key(0), train=True)["W"]
    d_add = np.asarray(add) - 2.0
    d_mul = np.asarray(mul) - 2.0
    assert 0.05 < d_add.std() < 0.2
    # multiplicative: w*(1+n) -> deviation std = 2*std(n)
    assert 0.1 < d_mul.std() < 0.4
    # train=False is identity
    same = WeightNoise(std=0.1).transform({"W": w}, jax.random.key(0),
                                          train=False)["W"]
    np.testing.assert_array_equal(np.asarray(same), np.asarray(w))


def test_config_json_roundtrip_with_noise():
    cfg = _model(DropConnect(p=0.7)).config
    back = config_from_json(config_to_json(cfg))
    assert isinstance(back.layers[0].weight_noise, DropConnect)
    assert back.layers[0].weight_noise.p == 0.7

    cfg2 = _model(WeightNoise(std=0.05, additive=False)).config
    back2 = config_from_json(config_to_json(cfg2))
    wn = back2.layers[0].weight_noise
    assert isinstance(wn, WeightNoise) and not wn.additive


def test_trains_with_dropconnect():
    model = _model(DropConnect(p=0.9))
    trainer = Trainer(model)
    ts = trainer.init_state()
    r = np.random.default_rng(0)
    batch = {"features": jnp.asarray(r.normal(size=(32, 8)),
                                     dtype=jnp.float32),
             "labels": jnp.asarray(
                 np.eye(4, dtype=np.float32)[r.integers(0, 4, 32)])}
    losses = []
    for _ in range(30):
        ts, m = trainer.train_step(ts, batch)
        losses.append(float(m["total_loss"]))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])


def test_weight_noise_on_output_layer_loss_path():
    """Noise on the OUTPUT layer must reach compute_loss (the output layer
    is excluded from the forward loop)."""
    model = SequentialModel(SequentialConfig(
        net=NeuralNetConfiguration(seed=0),
        input_shape=(8,),
        layers=[L.Dense(units=16),
                L.OutputLayer(units=4, loss="mcxent", activation="softmax",
                              weight_noise=WeightNoise(std=0.5))]))
    v = model.init(seed=0)
    r = np.random.default_rng(1)
    batch = {"features": jnp.asarray(r.normal(size=(8, 8)), jnp.float32),
             "labels": jnp.asarray(
                 np.eye(4, dtype=np.float32)[r.integers(0, 4, 8)])}
    l1, _ = model.loss_fn(v["params"], v["state"], batch,
                          rng=jax.random.key(1))
    l2, _ = model.loss_fn(v["params"], v["state"], batch,
                          rng=jax.random.key(2))
    assert abs(float(l1) - float(l2)) > 1e-6
