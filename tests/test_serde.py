"""Config JSON round-trip + checkpoint save/restore tests.

ref: config serde round-trip tests (MultiLayerTest JSON/YAML) and
ModelSerializer round-trip tests (SURVEY §4).
"""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models.lenet import lenet, lenet_config
from deeplearning4j_tpu.nn.config import SequentialConfig, config_from_json
from deeplearning4j_tpu.nn.model import SequentialModel
from deeplearning4j_tpu.serde.checkpoint import (
    latest_checkpoint,
    restore_checkpoint,
    save_checkpoint,
)
from deeplearning4j_tpu.train.trainer import Trainer
from deeplearning4j_tpu.utils.pytree import (
    from_flat_vector,
    to_flat_vector,
    tree_allclose,
)


def test_config_json_roundtrip():
    cfg = lenet_config()
    js = cfg.to_json()
    cfg2 = SequentialConfig.from_json(js)
    assert cfg2.to_json() == js
    assert len(cfg2.layers) == len(cfg.layers)
    assert cfg2.net.updater.lr == cfg.net.updater.lr


def test_rebuilt_model_same_output():
    cfg = lenet_config()
    m1 = SequentialModel(cfg)
    m2 = SequentialModel(SequentialConfig.from_json(cfg.to_json()))
    v1 = m1.init(seed=3)
    v2 = m2.init(seed=3)
    x = np.random.default_rng(0).normal(size=(2, 28, 28, 1)).astype(np.float32)
    y1 = m1.output(v1, x)
    y2 = m2.output(v2, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), rtol=1e-6)


def test_checkpoint_roundtrip(tmp_path):
    model = lenet()
    trainer = Trainer(model)
    ts = trainer.init_state()
    d = save_checkpoint(tmp_path, ts, model=model, tag="t")
    ts2 = restore_checkpoint(d, ts)
    assert tree_allclose(ts.params, ts2.params)
    assert int(ts2.step) == int(ts.step)


def test_checkpoint_rotation(tmp_path):
    model = lenet()
    trainer = Trainer(model)
    ts = trainer.init_state()
    import dataclasses

    for i in range(5):
        ts = dataclasses.replace(ts, step=jnp.asarray(i, jnp.int32))
        save_checkpoint(tmp_path, ts, keep_last=2)
    import json

    idx = json.loads((tmp_path / "checkpoint_index.json").read_text())
    assert len(idx["checkpoints"]) == 2
    assert latest_checkpoint(tmp_path).endswith("checkpoint_4")


def test_flat_vector_roundtrip():
    model = lenet()
    v = model.init(seed=0)
    flat = to_flat_vector(v["params"])
    assert flat.ndim == 1
    back = from_flat_vector(v["params"], flat)
    assert tree_allclose(v["params"], back)


def test_checkpoint_roundtrip_rbg_rng(tmp_path):
    """A TrainState whose rng uses a non-default PRNG impl (rbg) must
    restore with the same impl — rbg key data is uint32[4], and wrapping
    it with the default threefry impl would misread it."""
    import jax

    from deeplearning4j_tpu.serde.checkpoint import (
        load_state_tree, save_state_tree)

    tree = {"rng": jax.random.key(7, impl="rbg"),
            "w": jnp.ones((3,), jnp.float32)}
    save_state_tree(tmp_path / "s", tree)
    back = load_state_tree(tmp_path / "s", tree)
    assert str(jax.random.key_impl(back["rng"])) == "rbg"
    a = jax.random.bernoulli(tree["rng"], 0.5, (16,))
    b = jax.random.bernoulli(back["rng"], 0.5, (16,))
    assert (np.asarray(a) == np.asarray(b)).all()


def test_trainer_rng_impl_config():
    import jax

    model = lenet()
    model.net.rng_impl = "rbg"
    trainer = Trainer(model)
    ts = trainer.init_state()
    assert str(jax.random.key_impl(ts.rng)) == "rbg"
