"""Config JSON round-trip + checkpoint save/restore tests.

ref: config serde round-trip tests (MultiLayerTest JSON/YAML) and
ModelSerializer round-trip tests (SURVEY §4).
"""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models.lenet import lenet, lenet_config
from deeplearning4j_tpu.nn.config import SequentialConfig, config_from_json
from deeplearning4j_tpu.nn.model import SequentialModel
from deeplearning4j_tpu.serde.checkpoint import (
    latest_checkpoint,
    restore_checkpoint,
    save_checkpoint,
)
from deeplearning4j_tpu.train.trainer import Trainer
from deeplearning4j_tpu.utils.pytree import (
    from_flat_vector,
    to_flat_vector,
    tree_allclose,
)


def test_config_json_roundtrip():
    cfg = lenet_config()
    js = cfg.to_json()
    cfg2 = SequentialConfig.from_json(js)
    assert cfg2.to_json() == js
    assert len(cfg2.layers) == len(cfg.layers)
    assert cfg2.net.updater.lr == cfg.net.updater.lr


def test_rebuilt_model_same_output():
    cfg = lenet_config()
    m1 = SequentialModel(cfg)
    m2 = SequentialModel(SequentialConfig.from_json(cfg.to_json()))
    v1 = m1.init(seed=3)
    v2 = m2.init(seed=3)
    x = np.random.default_rng(0).normal(size=(2, 28, 28, 1)).astype(np.float32)
    y1 = m1.output(v1, x)
    y2 = m2.output(v2, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), rtol=1e-6)


def test_checkpoint_roundtrip(tmp_path):
    model = lenet()
    trainer = Trainer(model)
    ts = trainer.init_state()
    d = save_checkpoint(tmp_path, ts, model=model, tag="t")
    ts2 = restore_checkpoint(d, ts)
    assert tree_allclose(ts.params, ts2.params)
    assert int(ts2.step) == int(ts.step)


def test_checkpoint_rotation(tmp_path):
    model = lenet()
    trainer = Trainer(model)
    ts = trainer.init_state()
    import dataclasses

    for i in range(5):
        ts = dataclasses.replace(ts, step=jnp.asarray(i, jnp.int32))
        save_checkpoint(tmp_path, ts, keep_last=2)
    import json

    idx = json.loads((tmp_path / "checkpoint_index.json").read_text())
    assert len(idx["checkpoints"]) == 2
    assert latest_checkpoint(tmp_path).endswith("checkpoint_4")


def test_flat_vector_roundtrip():
    model = lenet()
    v = model.init(seed=0)
    flat = to_flat_vector(v["params"])
    assert flat.ndim == 1
    back = from_flat_vector(v["params"], flat)
    assert tree_allclose(v["params"], back)


def test_checkpoint_roundtrip_rbg_rng(tmp_path):
    """A TrainState whose rng uses a non-default PRNG impl (rbg) must
    restore with the same impl — rbg key data is uint32[4], and wrapping
    it with the default threefry impl would misread it."""
    import jax

    from deeplearning4j_tpu.serde.checkpoint import (
        load_state_tree, save_state_tree)

    tree = {"rng": jax.random.key(7, impl="rbg"),
            "w": jnp.ones((3,), jnp.float32)}
    save_state_tree(tmp_path / "s", tree)
    back = load_state_tree(tmp_path / "s", tree)
    assert str(jax.random.key_impl(back["rng"])) == "rbg"
    a = jax.random.bernoulli(tree["rng"], 0.5, (16,))
    b = jax.random.bernoulli(back["rng"], 0.5, (16,))
    assert (np.asarray(a) == np.asarray(b)).all()


def test_trainer_rng_impl_config():
    import jax

    model = lenet()
    model.net.rng_impl = "rbg"
    trainer = Trainer(model)
    ts = trainer.init_state()
    assert str(jax.random.key_impl(ts.rng)) == "rbg"


def test_async_checkpointer_roundtrip_and_rotation(tmp_path):
    """AsyncCheckpointer writes off-thread with save_checkpoint's exact
    on-disk format (restore path is shared) and rotates via the index."""
    import dataclasses

    from deeplearning4j_tpu.serde.checkpoint import AsyncCheckpointer

    model = lenet()
    trainer = Trainer(model)
    ts = trainer.init_state()
    with AsyncCheckpointer() as ck:
        for i in range(4):
            ts = dataclasses.replace(ts, step=jnp.asarray(i, jnp.int32))
            d = ck.save(tmp_path, ts, model=model, keep_last=2)
        ck.wait_until_finished()
    import json

    idx = json.loads((tmp_path / "checkpoint_index.json").read_text())
    assert [c["step"] for c in idx["checkpoints"]] == [2, 3]
    ts2 = restore_checkpoint(d, ts)
    assert tree_allclose(ts.params, ts2.params)
    # config.json written by the worker too
    from deeplearning4j_tpu.serde.checkpoint import load_model_config

    assert load_model_config(d).to_json() == model.config.to_json()


def test_async_checkpointer_snapshot_isolated_from_later_mutation(tmp_path):
    """The write must capture the state AT save() time: snapshot happens on
    the caller thread, so a train step donating/overwriting buffers after
    save() cannot corrupt the checkpoint."""
    import dataclasses

    from deeplearning4j_tpu.serde.checkpoint import AsyncCheckpointer

    model = lenet()
    trainer = Trainer(model)
    ts = trainer.init_state()
    want = np.asarray(jax.device_get(
        jax.tree_util.tree_leaves(ts.params)[0])).copy()
    with AsyncCheckpointer() as ck:
        d = ck.save(tmp_path, ts, tag="snap")
        # mutate the live state while the write may still be in flight
        ts = dataclasses.replace(
            ts, params=jax.tree_util.tree_map(lambda p: p * 0.0, ts.params))
    got = restore_checkpoint(d, ts)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(jax.tree_util.tree_leaves(got.params)[0])),
        want)


def test_async_checkpointer_surfaces_worker_errors(tmp_path):
    """A failed background write re-raises on the next save/wait instead of
    vanishing (orbax semantics)."""
    import pytest

    from deeplearning4j_tpu.serde.checkpoint import AsyncCheckpointer

    model = lenet()
    ts = Trainer(model).init_state()
    ck = AsyncCheckpointer()
    target = tmp_path / "blocked"
    target.mkdir()
    (target / "checkpoint_0").write_text("a file where the dir must go")
    ck.save(target, ts)
    with pytest.raises((OSError, NotADirectoryError, FileExistsError)):
        ck.wait_until_finished()
    ck.close()


def test_checkpoint_listener_async(tmp_path):
    """CheckpointListener(async_save=True) produces restorable rotating
    checkpoints through a real fit loop."""
    from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
    from deeplearning4j_tpu.train.listeners import CheckpointListener

    model = lenet()
    trainer = Trainer(model)
    ts = trainer.init_state()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 28, 28, 1)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 32)]
    it = ArrayDataSetIterator(x, y, batch_size=16)
    lst = CheckpointListener(str(tmp_path), every_epochs=1, keep_last=2,
                             model=model, async_save=True)
    ts = trainer.fit(ts, it, epochs=3, listeners=[lst])
    latest = latest_checkpoint(tmp_path)
    assert latest is not None and latest.endswith("epoch2")
    restored = restore_checkpoint(latest, ts)
    assert tree_allclose(ts.params, restored.params)


def test_fit_end_runs_on_midfit_failure(tmp_path):
    """on_fit_end fires even when a step raises, so the async checkpoint
    worker is joined/closed and its in-flight errors surface (review
    finding: teardown must not depend on the happy path)."""
    import pytest

    from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
    from deeplearning4j_tpu.train.listeners import TrainingListener

    class Boom(TrainingListener):
        def __init__(self):
            self.ended = 0

        def on_iteration(self, epoch, step, ts, metrics):
            raise RuntimeError("mid-fit failure")

        def on_fit_end(self, trainer, ts):
            self.ended += 1

    model = lenet()
    trainer = Trainer(model)
    ts = trainer.init_state()
    rng = np.random.default_rng(0)
    it = ArrayDataSetIterator(
        rng.normal(size=(16, 28, 28, 1)).astype(np.float32),
        np.eye(10, dtype=np.float32)[rng.integers(0, 10, 16)], batch_size=8)
    lst = Boom()
    with pytest.raises(RuntimeError, match="mid-fit failure"):
        trainer.fit(ts, it, epochs=1, listeners=[lst])
    assert lst.ended == 1
