"""Op-catalog conformance matrix (VERDICT r2 Weak #5 / round-1 task #6).

ref strategy: nd4j OpValidationSuite — every op in the public catalog gets a
golden test against an fp64 numpy oracle, swept across dtypes. The catalog
under test is ops/math.py (↔ NDMath), including every bare ``jnp`` alias:
an alias block is only an implemented op catalog if each alias is pinned to
reference semantics by a test. A coverage gate at the bottom enforces that
the matrix stays complete as ops are added.
"""

import math as pymath

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops import math as M

# ---------------------------------------------------------------------------
# Input generators (deterministic per case; fp64 ground truth)
# ---------------------------------------------------------------------------

SHAPE = (4, 6)


def _gen(kind, seed):
    r = np.random.default_rng(seed)
    if kind == "any":
        return (r.uniform(-3, 3, SHAPE),)
    if kind == "offint":
        # values >= 0.1 away from every integer: ceil/floor/round stay
        # stable under bf16 input rounding (rel err ~0.4% << 0.1)
        return (r.integers(-3, 3, SHAPE) + r.uniform(0.1, 0.9, SHAPE),)
    if kind == "pos":
        return (r.uniform(0.1, 3, SHAPE),)
    if kind == "unit":
        return (r.uniform(-0.9, 0.9, SHAPE),)
    if kind == "ge1":
        return (r.uniform(1.1, 3, SHAPE),)
    if kind == "distinct":
        x = np.arange(SHAPE[0] * SHAPE[1], dtype=np.float64)
        return (r.permutation(x).reshape(SHAPE) - x.size / 2,)
    if kind == "prob":
        x = r.uniform(0.05, 1.0, SHAPE)
        return (x / x.sum(axis=-1, keepdims=True),)
    if kind == "binary_any":
        return r.uniform(-3, 3, SHAPE), r.uniform(-3, 3, SHAPE)
    if kind == "binary_pos":
        return r.uniform(0.1, 3, SHAPE), r.uniform(0.1, 3, SHAPE)
    if kind == "bool2":
        return (r.integers(0, 2, SHAPE).astype(bool),
                r.integers(0, 2, SHAPE).astype(bool))
    if kind == "int2":
        return (r.integers(0, 5, SHAPE).astype(np.int32),
                r.integers(1, 5, SHAPE).astype(np.int32))
    raise ValueError(kind)


class C:
    """One conformance case: catalog fn vs fp64 numpy oracle."""

    def __init__(self, fn, oracle, kind="any", dtypes=("float32", "bfloat16"),
                 tol=None, exact=False, postprocess=None):
        self.fn = fn
        self.oracle = oracle
        self.kind = kind
        self.dtypes = dtypes
        self.tol = tol or {}
        self.exact = exact
        self.postprocess = postprocess  # applied to BOTH results


_TOL = {"float32": dict(rtol=2e-5, atol=1e-5), "bfloat16": dict(rtol=6e-2, atol=6e-2)}

_erf = np.vectorize(pymath.erf)
_erfc = np.vectorize(pymath.erfc)


def _np_clip_by_norm(x, max_norm):
    n = np.sqrt(np.square(x).sum())
    return x * min(1.0, max_norm / max(n, 1e-12))


def _np_segment(op, data, ids, num):
    out = np.zeros((num,) + data.shape[1:])
    if op in ("max", "min"):
        out[:] = -np.inf if op == "max" else np.inf
    for i, s in enumerate(ids):
        if op == "sum":
            out[s] += data[i]
        elif op == "max":
            out[s] = np.maximum(out[s], data[i])
        elif op == "min":
            out[s] = np.minimum(out[s], data[i])
    return out


F32 = ("float32",)

CASES = {
    # --- transforms -------------------------------------------------------
    "abs": C(M.abs, np.abs),
    "ceil": C(M.ceil, np.ceil, "offint"),
    "floor": C(M.floor, np.floor, "offint"),
    "round": C(M.round, np.round, "offint"),
    "rint": C(M.rint, np.rint, "offint"),
    "exp": C(M.exp, np.exp),
    "expm1": C(M.expm1, np.expm1),
    "log": C(M.log, np.log, "pos"),
    "log1p": C(M.log1p, np.log1p, "pos"),
    "log2": C(M.log2, np.log2, "pos"),
    "log10": C(M.log10, np.log10, "pos"),
    "sqrt": C(M.sqrt, np.sqrt, "pos"),
    "cbrt": C(M.cbrt, np.cbrt, "pos"),
    "square": C(M.square, np.square),
    "reciprocal": C(M.reciprocal, lambda x: 1.0 / x, "pos"),
    "neg": C(M.neg, np.negative),
    "sign": C(M.sign, np.sign),
    "sin": C(M.sin, np.sin),
    "cos": C(M.cos, np.cos),
    "tan": C(M.tan, np.tan, "unit"),
    "asin": C(M.asin, np.arcsin, "unit"),
    "acos": C(M.acos, np.arccos, "unit"),
    "atan": C(M.atan, np.arctan),
    "atan2": C(M.atan2, np.arctan2, "binary_any"),
    "sinh": C(M.sinh, np.sinh),
    "cosh": C(M.cosh, np.cosh),
    "tanh": C(M.tanh, np.tanh),
    "asinh": C(M.asinh, np.arcsinh),
    "acosh": C(M.acosh, np.arccosh, "ge1"),
    "atanh": C(M.atanh, np.arctanh, "unit"),
    "erf": C(M.erf, _erf),
    "erfc": C(M.erfc, _erfc),
    "pow": C(M.pow, np.power, "binary_pos"),
    "cube": C(M.cube, lambda x: x ** 3),
    "rsqrt": C(M.rsqrt, lambda x: 1.0 / np.sqrt(x), "pos"),
    "clip_by_value": C(lambda x: M.clip_by_value(x, -1.0, 1.0),
                       lambda x: np.clip(x, -1.0, 1.0)),
    "clip_by_norm": C(lambda x: M.clip_by_norm(x, 2.0),
                      lambda x: _np_clip_by_norm(x, 2.0)),
    "clip_by_global_norm": C(
        lambda x: M.clip_by_global_norm({"a": x, "b": 2 * x}, 1.5)[0]["a"],
        lambda x: _np_clip_by_norm_global(x), dtypes=F32),
    # --- pairwise / comparison -------------------------------------------
    "add": C(M.add, np.add, "binary_any"),
    "sub": C(M.sub, np.subtract, "binary_any"),
    "mul": C(M.mul, np.multiply, "binary_any"),
    "div": C(M.div, np.divide, "binary_pos"),
    "floordiv": C(M.floordiv, np.floor_divide, "int2", dtypes=F32, exact=True),
    "mod": C(M.mod, np.mod, "int2", dtypes=F32, exact=True),
    "maximum": C(M.maximum, np.maximum, "binary_any"),
    "minimum": C(M.minimum, np.minimum, "binary_any"),
    "eq": C(M.eq, np.equal, "int2", dtypes=F32, exact=True),
    "neq": C(M.neq, np.not_equal, "int2", dtypes=F32, exact=True),
    "gt": C(M.gt, np.greater, "binary_any", dtypes=F32, exact=True),
    "gte": C(M.gte, np.greater_equal, "binary_any", dtypes=F32, exact=True),
    "lt": C(M.lt, np.less, "binary_any", dtypes=F32, exact=True),
    "lte": C(M.lte, np.less_equal, "binary_any", dtypes=F32, exact=True),
    "logical_and": C(M.logical_and, np.logical_and, "bool2", dtypes=F32, exact=True),
    "logical_or": C(M.logical_or, np.logical_or, "bool2", dtypes=F32, exact=True),
    "logical_not": C(lambda a, b: M.logical_not(a), lambda a, b: np.logical_not(a),
                     "bool2", dtypes=F32, exact=True),
    "logical_xor": C(M.logical_xor, np.logical_xor, "bool2", dtypes=F32, exact=True),
    "where": C(lambda x, y: M.where(x > 0, x, y),
               lambda x, y: np.where(x > 0, x, y), "binary_any"),
    # --- reductions -------------------------------------------------------
    "sum": C(lambda x: M.sum(x, axis=-1), lambda x: np.sum(x, axis=-1)),
    "prod": C(lambda x: M.prod(x, axis=-1), lambda x: np.prod(x, axis=-1), "unit"),
    "mean": C(lambda x: M.mean(x, axis=-1), lambda x: np.mean(x, axis=-1)),
    "var": C(lambda x: M.var(x, axis=-1), lambda x: np.var(x, axis=-1)),
    "std": C(lambda x: M.std(x, axis=-1), lambda x: np.std(x, axis=-1)),
    "max": C(lambda x: M.max(x, axis=-1), lambda x: np.max(x, axis=-1)),
    "min": C(lambda x: M.min(x, axis=-1), lambda x: np.min(x, axis=-1)),
    "argmax": C(lambda x: M.argmax(x, axis=-1), lambda x: np.argmax(x, axis=-1),
                "distinct", dtypes=F32, exact=True),
    "argmin": C(lambda x: M.argmin(x, axis=-1), lambda x: np.argmin(x, axis=-1),
                "distinct", dtypes=F32, exact=True),
    "any": C(lambda a, b: M.any(a, axis=-1), lambda a, b: np.any(a, axis=-1),
             "bool2", dtypes=F32, exact=True),
    "all": C(lambda a, b: M.all(a, axis=-1), lambda a, b: np.all(a, axis=-1),
             "bool2", dtypes=F32, exact=True),
    "cumsum": C(lambda x: M.cumsum(x, axis=-1), lambda x: np.cumsum(x, axis=-1)),
    "cumprod": C(lambda x: M.cumprod(x, axis=-1), lambda x: np.cumprod(x, axis=-1),
                 "unit"),
    "norm1": C(lambda x: M.norm1(x, axis=-1),
               lambda x: np.abs(x).sum(axis=-1)),
    "norm2": C(lambda x: M.norm2(x, axis=-1),
               lambda x: np.sqrt(np.square(x).sum(axis=-1))),
    "norm_max": C(lambda x: M.norm_max(x, axis=-1),
                  lambda x: np.abs(x).max(axis=-1)),
    "count_nonzero": C(lambda a, b: M.count_nonzero(a),
                       lambda a, b: np.count_nonzero(a), "int2", dtypes=F32,
                       exact=True),
    "count_zero": C(lambda a, b: M.count_zero(a),
                    lambda a, b: a.size - np.count_nonzero(a), "int2",
                    dtypes=F32, exact=True),
    "entropy": C(lambda x: M.entropy(x, axis=-1),
                 lambda x: -(x * np.log(x)).sum(axis=-1), "prob"),
    "log_entropy": C(lambda x: M.log_entropy(x, axis=-1),
                     lambda x: np.log(-(x * np.log(x)).sum(axis=-1)), "prob"),
    "shannon_entropy": C(lambda x: M.shannon_entropy(x, axis=-1),
                         lambda x: -(x * np.log2(x)).sum(axis=-1), "prob"),
    "amean": C(lambda x: M.amean(x, axis=-1), lambda x: np.abs(x).mean(axis=-1)),
    "amax": C(lambda x: M.amax(x, axis=-1), lambda x: np.abs(x).max(axis=-1)),
    "amin": C(lambda x: M.amin(x, axis=-1), lambda x: np.abs(x).min(axis=-1)),
    "asum": C(lambda x: M.asum(x, axis=-1), lambda x: np.abs(x).sum(axis=-1)),
    # --- reduce3 ----------------------------------------------------------
    "cosine_similarity": C(
        M.cosine_similarity,
        lambda x, y: (x * y).sum(-1) / (np.linalg.norm(x, axis=-1)
                                        * np.linalg.norm(y, axis=-1)),
        "binary_any"),
    "cosine_distance": C(
        M.cosine_distance,
        lambda x, y: 1 - (x * y).sum(-1) / (np.linalg.norm(x, axis=-1)
                                            * np.linalg.norm(y, axis=-1)),
        "binary_any"),
    "euclidean_distance": C(M.euclidean_distance,
                            lambda x, y: np.linalg.norm(x - y, axis=-1),
                            "binary_any"),
    "manhattan_distance": C(M.manhattan_distance,
                            lambda x, y: np.abs(x - y).sum(-1), "binary_any"),
    "hamming_distance": C(M.hamming_distance,
                          lambda x, y: (x != y).sum(-1).astype(float),
                          "int2", dtypes=F32),
    "jaccard_distance": C(
        M.jaccard_distance,
        lambda x, y: 1 - np.minimum(x, y).sum(-1) / np.maximum(x, y).sum(-1),
        "binary_pos"),
    "dot": C(M.dot, lambda x, y: (x * y).sum(-1), "binary_any"),
    # --- index reductions -------------------------------------------------
    "iamax": C(lambda x: M.iamax(x, axis=-1),
               lambda x: np.argmax(np.abs(x), axis=-1), "distinct",
               dtypes=F32, exact=True),
    "iamin": C(lambda x: M.iamin(x, axis=-1),
               lambda x: np.argmin(np.abs(x), axis=-1), "distinct",
               dtypes=F32, exact=True),
    "first_index": C(lambda x: M.first_index(x, x[1, 2]),
                     lambda x: np.argmax(x == x[1, 2], axis=-1), "distinct",
                     dtypes=F32, exact=True),
    # --- matrix -----------------------------------------------------------
    "matmul": C(lambda x, y: M.matmul(x, y.T),
                lambda x, y: x @ y.T, "binary_any",
                tol={"float32": dict(rtol=1e-4, atol=1e-4)}),
    "mmul": C(lambda x, y: M.mmul(x, y, transpose_a=True),
              lambda x, y: x.T @ y, "binary_any",
              tol={"float32": dict(rtol=1e-4, atol=1e-4)}),
    "tensordot": C(lambda x, y: M.tensordot(x, y.T, axes=1),
                   lambda x, y: np.tensordot(x, y.T, axes=1), "binary_any",
                   tol={"float32": dict(rtol=1e-4, atol=1e-4)}),
    "einsum": C(lambda x, y: M.einsum("ij,kj->ik", x, y),
                lambda x, y: np.einsum("ij,kj->ik", x, y), "binary_any",
                tol={"float32": dict(rtol=1e-4, atol=1e-4)}),
    "trace": C(M.trace, np.trace),
    "diag": C(lambda x: M.diag(x[0]), lambda x: np.diag(x[0])),
    "outer": C(lambda x, y: M.outer(x[0], y[0]),
               lambda x, y: np.outer(x[0], y[0]), "binary_any"),
    "kron": C(lambda x, y: M.kron(x[:2, :2], y[:2, :2]),
              lambda x, y: np.kron(x[:2, :2], y[:2, :2]), "binary_any"),
    # --- shape ops --------------------------------------------------------
    "reshape": C(lambda x: M.reshape(x, (3, 8)), lambda x: x.reshape(3, 8),
                 exact=True, dtypes=F32),
    "transpose": C(M.transpose, np.transpose, exact=True, dtypes=F32),
    "permute": C(M.permute, np.transpose, exact=True, dtypes=F32),
    "concat": C(lambda x, y: M.concat([x, y], axis=0),
                lambda x, y: np.concatenate([x, y], axis=0), "binary_any",
                exact=True, dtypes=F32),
    "stack": C(lambda x, y: M.stack([x, y], axis=1),
               lambda x, y: np.stack([x, y], axis=1), "binary_any",
               exact=True, dtypes=F32),
    "unstack": C(lambda x: M.unstack(x, axis=0)[2], lambda x: x[2],
                 exact=True, dtypes=F32),
    "split": C(lambda x: M.split(x, 2, axis=1)[1],
               lambda x: np.split(x, 2, axis=1)[1], exact=True, dtypes=F32),
    "tile": C(lambda x: M.tile(x, (2, 1)), lambda x: np.tile(x, (2, 1)),
              exact=True, dtypes=F32),
    "repeat": C(lambda x: M.repeat(x, 2, axis=1),
                lambda x: np.repeat(x, 2, axis=1), exact=True, dtypes=F32),
    "squeeze": C(lambda x: M.squeeze(x[None]), lambda x: x, exact=True,
                 dtypes=F32),
    "expand_dims": C(lambda x: M.expand_dims(x, 1),
                     lambda x: np.expand_dims(x, 1), exact=True, dtypes=F32),
    "flip": C(lambda x: M.flip(x, axis=1), lambda x: np.flip(x, axis=1),
              exact=True, dtypes=F32),
    "roll": C(lambda x: M.roll(x, 2, axis=1), lambda x: np.roll(x, 2, axis=1),
              exact=True, dtypes=F32),
    "pad": C(lambda x: M.pad(x, ((1, 1), (0, 2))),
             lambda x: np.pad(x, ((1, 1), (0, 2))), exact=True, dtypes=F32),
    "gather": C(lambda x: M.gather(x, np.array([2, 0, 1]), axis=0),
                lambda x: np.take(x, [2, 0, 1], axis=0), exact=True,
                dtypes=F32),
    "take_along_axis": C(
        lambda x: M.take_along_axis(x, np.argsort(np.asarray(x), axis=1), axis=1),
        lambda x: np.take_along_axis(x, np.argsort(x, axis=1), axis=1),
        "distinct", exact=True, dtypes=F32),
    "gather_nd": C(
        lambda x: M.gather_nd(x, np.array([[0, 1], [3, 5], [2, 2]])),
        lambda x: x[[0, 3, 2], [1, 5, 2]], exact=True, dtypes=F32),
    "scatter_update": C(
        lambda x: M.scatter_update(x, np.array([1, 3]), jnp.zeros((2, SHAPE[1]), x.dtype)),
        lambda x: _np_scatter(x, "set"), exact=True, dtypes=F32),
    "scatter_add": C(
        lambda x: M.scatter_add(x, np.array([1, 1]), jnp.ones((2, SHAPE[1]), x.dtype)),
        lambda x: _np_scatter(x, "add"), dtypes=F32),
    "one_hot": C(lambda a, b: M.one_hot(a[0] % 5, 5, on_value=0.9, off_value=0.1),
                 lambda a, b: np.eye(5)[a[0] % 5] * 0.8 + 0.1, "int2",
                 dtypes=F32),
    # --- segment ops ------------------------------------------------------
    "segment_sum": C(
        lambda x: M.segment_sum(x, np.array([0, 0, 1, 3]), 4),
        lambda x: _np_segment("sum", x, [0, 0, 1, 3], 4), dtypes=F32),
    "segment_max": C(
        lambda x: M.segment_max(x, np.array([0, 0, 1, 3]), 4),
        lambda x: _np_segment("max", x, [0, 0, 1, 3], 4), dtypes=F32,
        postprocess=lambda a: np.where(np.isfinite(a), a, 0.0)),
    "segment_min": C(
        lambda x: M.segment_min(x, np.array([0, 0, 1, 3]), 4),
        lambda x: _np_segment("min", x, [0, 0, 1, 3], 4), dtypes=F32,
        postprocess=lambda a: np.where(np.isfinite(a), a, 0.0)),
    "segment_mean": C(
        lambda x: M.segment_mean(x, np.array([0, 0, 1, 1]), 2),
        lambda x: np.stack([x[:2].mean(0), x[2:4].mean(0)]), dtypes=F32),
    "unsorted_segment_sum": C(
        lambda x: M.unsorted_segment_sum(x, np.array([2, 0, 2, 1]), 3),
        lambda x: _np_segment("sum", x, [2, 0, 2, 1], 3), dtypes=F32),
    # --- top-k / sort -----------------------------------------------------
    "top_k": C(lambda x: M.top_k(x, 3)[0],
               lambda x: -np.sort(-x, axis=-1)[:, :3], "distinct",
               exact=True, dtypes=F32),
    "sort": C(lambda x: M.sort(x, axis=-1), lambda x: np.sort(x, axis=-1),
              "distinct", exact=True, dtypes=F32),
    "argsort": C(lambda x: M.argsort(x, axis=-1),
                 lambda x: np.argsort(x, axis=-1), "distinct", exact=True,
                 dtypes=F32),
    "in_top_k": C(
        lambda x: M.in_top_k(x, np.argmax(np.asarray(x), axis=-1), 2),
        lambda x: np.ones(x.shape[0], bool), "distinct", exact=True,
        dtypes=F32),
    # --- misc -------------------------------------------------------------
    "is_nan": C(lambda x: M.is_nan(_specials(x)),
                lambda x: np.isnan(_specials(x)), exact=True, dtypes=F32),
    "is_inf": C(lambda x: M.is_inf(_specials(x)),
                lambda x: np.isinf(_specials(x)), exact=True, dtypes=F32),
    "is_finite": C(lambda x: M.is_finite(_specials(x)),
                   lambda x: np.isfinite(_specials(x)), exact=True, dtypes=F32),
    "nan_to_num": C(lambda x: M.nan_to_num(_specials(x)),
                    lambda x: np.nan_to_num(_specials(x)), dtypes=F32),
    "unique": C(lambda a, b: M.unique(a), lambda a, b: np.unique(a), "int2",
                exact=True, dtypes=F32),
    "searchsorted": C(lambda x: M.searchsorted(np.sort(np.asarray(x[0])), x[1]),
                      lambda x: np.searchsorted(np.sort(x[0]), x[1]),
                      exact=True, dtypes=F32),
    "linspace": C(lambda x: M.linspace(0.0, 5.0, 7),
                  lambda x: np.linspace(0.0, 5.0, 7), dtypes=F32),
    "arange": C(lambda x: M.arange(1, 17, 3), lambda x: np.arange(1, 17, 3),
                exact=True, dtypes=F32),
    "eye": C(lambda x: M.eye(5), lambda x: np.eye(5), exact=True, dtypes=F32),
    "meshgrid": C(lambda x: M.meshgrid(x[0], x[1])[0],
                  lambda x: np.meshgrid(x[0], x[1])[0], exact=True, dtypes=F32),
    "zeros_like": C(M.zeros_like, np.zeros_like, exact=True, dtypes=F32),
    "ones_like": C(M.ones_like, np.ones_like, exact=True, dtypes=F32),
    "full_like": C(lambda x: M.full_like(x, 3.5),
                   lambda x: np.full_like(x, 3.5), exact=True, dtypes=F32),
    "moments": C(lambda x: M.moments(x, axes=-1)[1],
                 lambda x: np.var(x, axis=-1)),
    "standardize": C(
        M.standardize,
        lambda x: (x - x.mean(-1, keepdims=True)) / x.std(-1, keepdims=True),
        tol={"bfloat16": dict(rtol=1e-1, atol=1e-1)}),
    "zero_fraction": C(lambda a, b: M.zero_fraction(a),
                       lambda a, b: (a == 0).mean(), "int2", dtypes=F32),
    "confusion_matrix": C(
        lambda a, b: M.confusion_matrix(a[0] % 4, b[0] % 4, 4),
        lambda a, b: _np_confusion(a[0] % 4, b[0] % 4, 4), "int2", dtypes=F32),
}


def _np_clip_by_norm_global(x):
    tree = [x, 2 * x]
    g = np.sqrt(sum(np.square(t).sum() for t in tree))
    return x * min(1.0, 1.5 / max(g, 1e-12))


def _np_scatter(x, mode):
    c = np.asarray(x).copy()
    if mode == "set":
        c[[1, 3]] = 0.0
    else:
        c[1] = c[1] + 2.0  # two updates accumulate at the same index
    return c


def _specials(x):
    x = np.asarray(x, np.float32).copy()
    x[0, 0] = np.nan
    x[1, 1] = np.inf
    x[2, 2] = -np.inf
    return x


def _np_confusion(labels, preds, n):
    out = np.zeros((n, n))
    for l, p in zip(labels.ravel(), preds.ravel()):
        out[l, p] += 1
    return out


# ---------------------------------------------------------------------------
# The matrix
# ---------------------------------------------------------------------------

_PARAMS = [(name, dt) for name, case in sorted(CASES.items())
           for dt in case.dtypes]


@pytest.mark.parametrize("name,dtype", _PARAMS, ids=[f"{n}-{d}" for n, d in _PARAMS])
def test_op_conformance(name, dtype):
    import zlib

    case = CASES[name]
    raw = _gen(case.kind, seed=zlib.crc32(name.encode()) % 2 ** 31)

    def cast(a):
        if a.dtype.kind in "fc":
            return jnp.asarray(a, dtype=jnp.dtype(dtype))
        return jnp.asarray(a)

    got = case.fn(*[cast(a) for a in raw])
    if case.exact:
        # structural ops: the oracle sees the SAME cast inputs (bit-identity)
        oracle = np.asarray(case.oracle(*[np.asarray(cast(a)) for a in raw]))
        np.testing.assert_array_equal(np.asarray(got, oracle.dtype), oracle,
                                      err_msg=name)
    else:
        # numeric ops: fp64 ground truth, dtype-scaled tolerance
        oracle = np.asarray(case.oracle(*raw), np.float64)
        got = np.asarray(got, np.float64)
        if case.postprocess is not None:
            got = case.postprocess(got)
            oracle = case.postprocess(oracle)
        tol = dict(_TOL[dtype])
        tol.update(case.tol.get(dtype, {}))
        np.testing.assert_allclose(got, oracle, err_msg=name, **tol)


def test_catalog_coverage():
    """Every public callable/alias in ops/math.py must be in the matrix."""
    public = set()
    for n, v in vars(M).items():
        if n.startswith("_") or n in ("annotations", "jax", "jnp", "lax"):
            continue
        if callable(v):
            public.add(n)
    covered = set(CASES)
    missing = sorted(public - covered)
    frac = len(public & covered) / max(len(public), 1)
    assert frac >= 0.95, f"op catalog coverage {frac:.0%}; missing: {missing}"
