"""SameDiffLayer escape-hatch tests (↔ the reference's samediff custom-layer
suites: define params + graph, drop into a network, train through it)."""

from dataclasses import dataclass

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.nn.config import (
    NeuralNetConfiguration,
    SequentialConfig,
    register_config,
)
from deeplearning4j_tpu.nn.layers import (
    OutputLayer,
    SameDiffLambdaLayer,
    SameDiffLayer,
)
from deeplearning4j_tpu.nn.model import SequentialModel
from deeplearning4j_tpu.train.trainer import Trainer
from deeplearning4j_tpu.train.updaters import Adam


@register_config
@dataclass
class CustomDense(SameDiffLayer):
    """User-defined tanh dense layer, graph built with SameDiff ops."""

    units: int = 8

    def define_parameters(self, input_shape):
        return {"W": (input_shape[-1], self.units), "b": (self.units,)}

    def define_layer(self, sd, x, params):
        return sd.math.tanh(x.mmul(params["W"]) + params["b"])


def _model(units=16):
    cfg = SequentialConfig(
        net=NeuralNetConfiguration(updater=Adam(1e-2), seed=0),
        layers=[CustomDense(units=units),
                OutputLayer(units=2, activation="softmax", loss="mcxent")],
        input_shape=(6,),
    )
    return SequentialModel(cfg)


def _batch(n=16, seed=0):
    r = np.random.default_rng(seed)
    return {"features": r.normal(size=(n, 6)).astype(np.float32),
            "labels": np.eye(2, dtype=np.float32)[r.integers(0, 2, n)]}


class TestSameDiffLayer:
    def test_shape_inference_through_custom_graph(self):
        m = _model(units=12)
        assert m.shapes == [(6,), (12,), (2,)]

    def test_forward_matches_manual_math(self):
        m = _model()
        v = m.init(seed=0)
        x = _batch(4)["features"]
        out, _ = m.apply(v, x, up_to=1)
        name = m.layer_names[0]
        w = np.asarray(v["params"][name]["W"])
        b = np.asarray(v["params"][name]["b"])
        np.testing.assert_allclose(np.asarray(out), np.tanh(x @ w + b),
                                   rtol=1e-5, atol=1e-6)

    def test_trains_through_custom_layer(self):
        m = _model()
        trainer = Trainer(m)
        ts = trainer.init_state(seed=0)
        batch = _batch()
        losses = []
        for _ in range(40):
            ts, met = trainer.train_step(ts, batch)
            losses.append(float(jax.device_get(met["total_loss"])))
        assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])
        # the custom layer's own params moved
        w = np.asarray(jax.device_get(ts.params[m.layer_names[0]]["W"]))
        w0 = np.asarray(m.init(seed=0)["params"][m.layer_names[0]]["W"])
        assert not np.array_equal(w, w0)

    def test_batch_polymorphic_replay(self):
        """Graph is built once (batch 1) and replayed at other batch sizes."""
        m = _model()
        v = m.init(seed=0)
        for n in (1, 4, 32):
            out, _ = m.apply(v, _batch(n)["features"], up_to=1)
            assert out.shape == (n, 16)

    def test_lambda_layer(self):
        lam = SameDiffLambdaLayer(
            forward_fn=lambda sd, x: sd.math.tanh(x) * 2.0)
        cfg = SequentialConfig(
            net=NeuralNetConfiguration(seed=0),
            layers=[lam, OutputLayer(units=2, activation="softmax",
                                     loss="mcxent")],
            input_shape=(6,),
        )
        m = SequentialModel(cfg)
        v = m.init(0)
        x = _batch(4)["features"]
        out, _ = m.apply(v, x, up_to=1)
        np.testing.assert_allclose(np.asarray(out), np.tanh(x) * 2.0,
                                   rtol=1e-6)

    def test_lambda_without_fn_raises(self):
        lam = SameDiffLambdaLayer()
        with pytest.raises(ValueError, match="forward_fn"):
            lam.output_shape((4,))
