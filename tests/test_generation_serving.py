"""Generative serving engine tests (serving/generation.py + the Gpt
decode-step APIs): math parity against the whole-loop generator,
continuous batching over real HTTP (staggered join/leave proven via
flight events, zero recompiles after warmup across mixed prefix
lengths), priority preemption with client retry, the token brownout
rung, and the TTFT sentinel detector.

Strategy (the PR 6/7 budget pattern): scheduler decisions are exercised
white-box with manual ``_admit()`` calls (deterministic, no races); one
engine is compiled ONCE per module and shared; the sustained load /
overload-storm variants are ``@pytest.mark.slow`` behind these fast
proxies.
"""

import json
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.gpt import gpt_tiny
from deeplearning4j_tpu.nn.generation import sample_token
from deeplearning4j_tpu.observability import sentinel as sn
from deeplearning4j_tpu.observability import slo
from deeplearning4j_tpu.observability.flightrecorder import (
    get_flight_recorder,
)
from deeplearning4j_tpu.observability.runtime import get_runtime_collector
from deeplearning4j_tpu.serving import (
    BadRequestError,
    GenerationEngine,
    ModelServer,
    NotReadyError,
    OverloadPolicy,
    QueueFullError,
    ServingClient,
    SlotPreemptedError,
    TenantQuotaError,
)
from deeplearning4j_tpu.serving.metrics import ServingMetrics

# ---------------------------------------------------------------------------
# shared model + engine (compiled once per module; warm is the expensive part)


@pytest.fixture(scope="module")
def gpt_model():
    model = gpt_tiny()
    return model, model.init(seed=0)


@pytest.fixture(scope="module")
def engine(gpt_model):
    model, variables = gpt_model
    eng = GenerationEngine(
        model, variables, name="gpt", num_slots=3, max_len=48,
        max_new_tokens=40, min_kv_bucket=8, min_prompt_bucket=8,
        idle_wait_s=0.005, temperature=0.0, max_waiting=16, seed=0)
    eng.warm()
    return eng


def _events(kind, model="gpt"):
    return [e["data"] for e in get_flight_recorder().events(kinds=[kind])
            if e["data"].get("model") == model]


# ---------------------------------------------------------------------------
# model-level parity (the decode-capable Gpt step API)


class TestGptStepAPI:
    def test_slot_decode_matches_scalar_decode(self, gpt_model):
        model, variables = gpt_model
        params = variables["params"]
        caches = model.init_cache(2, 16)
        ids = jnp.asarray([3, 7], jnp.int32)
        for pos in range(3):
            lg_scalar, caches_scalar = model.decode_step(
                params, caches, ids, pos)
            lg_slots, caches = model.decode_step_slots(
                params, caches, ids, jnp.full(2, pos, jnp.int32))
            np.testing.assert_allclose(np.asarray(lg_slots),
                                       np.asarray(lg_scalar),
                                       atol=2e-5, rtol=1e-4)
            ids = jnp.argmax(lg_slots, axis=-1).astype(jnp.int32)
            for a, b in zip(caches, caches_scalar):
                np.testing.assert_allclose(np.asarray(a["k"]),
                                           np.asarray(b["k"]), atol=2e-5)

    def test_prefill_chunk_matches_decode_scan(self, gpt_model):
        model, variables = gpt_model
        params = variables["params"]
        prompt = jnp.asarray([[5, 9, 2, 11, 60]], jnp.int32)
        lg_seq, kvs = model.prefill_chunk(params, prompt)
        caches = model.init_cache(1, 5)
        scans = []
        for t in range(5):
            lg, caches = model.decode_step(params, caches, prompt[:, t], t)
            scans.append(lg)
        np.testing.assert_allclose(np.asarray(lg_seq),
                                   np.asarray(jnp.stack(scans, axis=1)),
                                   atol=2e-5, rtol=1e-4)
        for kv, cache in zip(kvs, caches):
            np.testing.assert_allclose(np.asarray(kv["k"]),
                                       np.asarray(cache["k"]), atol=2e-5)
            np.testing.assert_allclose(np.asarray(kv["v"]),
                                       np.asarray(cache["v"]), atol=2e-5)

    def test_sample_token_greedy_rows_and_sampled_rows(self):
        logits = jnp.asarray([[0.0, 5.0, 0.0], [9.0, 0.0, 0.0]])
        toks = sample_token(logits, jax.random.key(0),
                            jnp.asarray([0.0, 0.7]))
        assert int(toks[0]) == 1  # greedy row takes the argmax
        assert 0 <= int(toks[1]) < 3


# ---------------------------------------------------------------------------
# engine semantics (white-box: manual _admit, no scheduler races)


class TestEngineScheduling:
    def test_greedy_engine_matches_whole_loop_generate(self, gpt_model,
                                                       engine):
        model, variables = gpt_model
        engine.start()
        prime = np.asarray([5, 9, 2, 11], np.int32)
        res = engine.submit(prime, max_new_tokens=6,
                            temperature=0.0).result(timeout=30)
        ref = model.generate(variables, prime[None, :], n_steps=6,
                             rng=jax.random.key(0), temperature=0.0)
        assert res["tokens"] == np.asarray(ref)[0].tolist()
        assert res["finish_reason"] == "length"
        assert engine.compiles_after_warm == 0

    def test_eos_finishes_stream(self, engine):
        engine.start()
        # greedy from this prompt emits 84 first (pinned above via the
        # whole-loop parity); declaring it eos ends the stream at once
        res = engine.submit([5, 9, 2, 11], max_new_tokens=6,
                            temperature=0.0, eos_id=84).result(timeout=30)
        assert res["finish_reason"] == "eos"
        assert len(res["tokens"]) == 1

    def test_submit_validation(self, engine):
        with pytest.raises(BadRequestError):
            engine.submit([])
        with pytest.raises(BadRequestError):
            engine.submit([1], priority="vip")
        with pytest.raises(BadRequestError):
            engine.submit([1], max_new_tokens=0)
        with pytest.raises(BadRequestError):
            engine.submit([1], temperature=-1.0)
        with pytest.raises(BadRequestError):
            engine.submit([10 ** 6])  # out-of-vocab id
        with pytest.raises(BadRequestError):
            engine.submit(np.zeros(4096, np.int32))  # over max_prompt
        with pytest.raises(BadRequestError):
            engine.submit([46.7])  # fractional id: rejected, not truncated
        engine.submit([46.0]).cancel()  # whole-number float is fine
        # the slabs belong to the live scheduler: no warm() mid-flight
        engine.start()
        with pytest.raises(RuntimeError):
            engine.warm()

    def test_critical_preempts_lowest_class_slot(self, engine):
        engine.stop()  # drive the scheduler by hand
        engine._stopflag = False
        engine._draining = False
        victims = [engine.submit([1, 2], priority="batch")
                   for _ in range(engine.num_slots)]
        engine._admit()
        assert all(v.state == "active" for v in victims)
        crit = engine.submit([3], priority="critical", max_new_tokens=2)
        engine._admit()
        assert crit.state == "active"
        preempted = [v for v in victims if v.finish_reason == "preempted"]
        assert len(preempted) == 1
        # newest batch join is the victim (least sunk decode work)
        assert preempted[0] is victims[-1]
        with pytest.raises(SlotPreemptedError) as ei:
            list(preempted[0].tokens(timeout=1))
        assert ei.value.retryable and ei.value.retry_after_ms is not None
        evs = _events("generation.preempt")
        assert evs and evs[-1]["victim_priority"] == "batch"
        # finish the survivors on the real scheduler
        engine.start()
        assert crit.result(timeout=30)["finish_reason"] == "length"
        for v in victims[:-1]:
            v.result(timeout=30)

    def test_queue_full_and_tenant_shed_paths(self, gpt_model):
        model, variables = gpt_model
        eng = GenerationEngine(model, variables, name="g2", num_slots=1,
                               max_len=16, max_waiting=1)

        class _Ov:  # the hot-path surface the engine consults
            shed_batch = False

            @staticmethod
            def tenant_take(tenant):
                return (tenant != "hog"), 0.25

            @staticmethod
            def note_shed():
                _Ov.sheds = getattr(_Ov, "sheds", 0) + 1

        eng.attach_overload(_Ov)
        # tenant quota checked while capacity remains (it is checked
        # LAST, so a request the queue would shed never burns a token)
        with pytest.raises(TenantQuotaError) as ei:
            eng.submit([1], tenant="hog")
        assert ei.value.retry_after_ms == 250.0
        eng.submit([1])  # fills the waiting queue (scheduler not running)
        with pytest.raises(QueueFullError):
            eng.submit([1])
        assert getattr(_Ov, "sheds", 0) == 1
        # with the queue full, even a quota-less tenant sheds on
        # capacity BEFORE the quota is consulted (no token burned)
        with pytest.raises(QueueFullError):
            eng.submit([1], tenant="hog")
        assert getattr(_Ov, "sheds", 0) == 2
        _Ov.shed_batch = True
        with pytest.raises(QueueFullError):
            eng.submit([1], priority="batch")
        eng.stop()
        with pytest.raises(NotReadyError):
            eng.submit([1])

    def test_token_brownout_trims_in_flight_streams(self, engine):
        engine.start()
        try:
            engine.engage_token_brownout()
            res = engine.submit([5, 9], max_new_tokens=40,
                                temperature=0.0).result(timeout=30)
            assert res["finish_reason"] == "length"
            assert len(res["tokens"]) == engine.brownout_max_new_tokens
        finally:
            engine.disengage_token_brownout()
        assert engine.token_cap == engine.default_max_new_tokens


# ---------------------------------------------------------------------------
# the e2e acceptance: staggered streaming requests share one decode batch
# over real HTTP, with jax.monitoring-counted compiles after warmup == 0


class TestHTTPStreaming:
    def test_staggered_streams_share_one_decode_batch(self, engine):
        server = ModelServer(port=0, sentinel=False,
                             generators={"gpt": engine})
        server.start(warm=True)
        try:
            collector = get_runtime_collector()
            compiles_before = collector.jit_compiles_total.value()
            steps_before = engine.steps
            # mixed prefix lengths across different prompt buckets
            # (longest + 20 new tokens still fits max_len=48). The
            # first stream decodes 40 tokens so the staggered joiners
            # land inside its decode window even on a fast host — a
            # fixed stagger against a uniform 20-token decode let a
            # quick machine finish each stream before the next client
            # arrived, serializing the batch and failing the overlap
            # assertion below.
            prompts = [[5, 9, 2], [1] * 9, [2] * 17, [3] * 27]
            want = {0: 40, 1: 20, 2: 20, 3: 20}
            results = {}
            lock = threading.Lock()

            def run(i):
                time.sleep(0.005 * i)  # staggered arrivals
                client = ServingClient(server.url)
                toks = list(client.generate(
                    "gpt", prompts[i], max_new_tokens=want[i],
                    temperature=0.7))
                with lock:
                    results[i] = toks

            threads = [threading.Thread(target=run, args=(i,))
                       for i in range(len(prompts))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
                assert not t.is_alive(), "streaming client hung"
            assert sorted(results) == [0, 1, 2, 3]
            assert all(len(v) == want[k] for k, v in results.items()), {
                k: len(v) for k, v in results.items()}
            # join/leave mid-decode: some request joined the batch at a
            # later decode step than another's join and before its leave
            joins = {e["req"]: e["step"]
                     for e in _events("generation.join")
                     if e["step"] >= steps_before}
            leaves = {e["req"]: e["step"]
                      for e in _events("generation.leave")
                      if e["step"] >= steps_before}
            assert len(joins) >= 4
            shared = [(a, b) for a in joins for b in joins
                      if a != b and joins[a] < joins[b] < leaves[a]]
            assert shared, (joins, leaves)
            # zero compiles after warmup across mixed prefix lengths
            assert collector.jit_compiles_total.value() \
                == compiles_before
            assert engine.compiles_after_warm == 0
            # occupancy > 1 slot proves actual batch sharing on-device
            occ = server.metrics.generation_slot_occupancy.summary(
                model="gpt")
            assert occ["count"] > 0
            ttft = server.metrics.generation_ttft.summary(model="gpt")
            assert ttft["count"] >= 4
        finally:
            server.stop()

    def test_chaos_critical_preempts_batch_and_client_retries(self, engine):
        policy = OverloadPolicy(min_in_flight=2, max_in_flight=8,
                                interval_s=60.0)
        server = ModelServer(port=0, sentinel=False, overload=policy,
                             generators={"gpt": engine})
        assert [r.name for r in server.overload.ladder.rungs] == [
            "shrink_batch_wait", "shed_batch_class",
            "shrink_generation_tokens", "serve_fallback"]
        server.start(warm=True)
        try:
            pre_before = server.metrics.generation_preemptions_total.value(
                model="gpt", priority="batch")
            results = {}
            lock = threading.Lock()

            def batch_run(i):
                client = ServingClient(server.url, max_retries=6,
                                       retry_seed=i)
                r = client.generate_tokens(
                    "gpt", [1 + i, 2], max_new_tokens=40, temperature=0.0,
                    priority="batch")
                with lock:
                    results[i] = r

            threads = [threading.Thread(target=batch_run, args=(i,))
                       for i in range(engine.num_slots)]
            for t in threads:
                t.start()
            # wait until every decode slot is held by a batch stream
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if engine.describe()["active"] == engine.num_slots:
                    break
                time.sleep(0.002)
            assert engine.describe()["active"] == engine.num_slots
            client = ServingClient(server.url)
            r = client.generate_tokens("gpt", [7], max_new_tokens=3,
                                       temperature=0.0,
                                       priority="critical")
            assert r["n_tokens"] == 3
            for t in threads:
                t.join(timeout=60)
                assert not t.is_alive(), "batch client hung"
            # a batch slot WAS preempted, and the preempted client's
            # retry still completed its full request
            assert server.metrics.generation_preemptions_total.value(
                model="gpt", priority="batch") > pre_before
            assert sorted(results) == list(range(engine.num_slots))
            assert all(r["n_tokens"] == 40 for r in results.values())
        finally:
            server.stop()

    def test_nonstream_shed_maps_to_typed_http_error(self, gpt_model):
        model, variables = gpt_model
        eng = GenerationEngine(model, variables, name="g3", num_slots=1,
                               max_len=16, max_waiting=16)
        server = ModelServer(port=0, sentinel=False,
                             generators={"tiny": eng})
        # not started: the route sheds with a retryable 503
        status, body, stream = server.handle_generate(
            "tiny", {"prompt": [1]})
        assert status == 503 and stream is None
        assert body["error"]["code"] == "UNAVAILABLE"
        status, body, _ = server.handle_generate("nope", {"prompt": [1]})
        assert status == 404
        try:
            server.start(warm=False)  # bad payloads never reach the device
            status, body, _ = server.handle_generate("tiny", {"bad": 1})
            assert status == 400
            status, body, _ = server.handle_generate(
                "tiny", {"prompt": [1], "max_new_tokens": "many"})
            assert status == 400
            # deadline validated BEFORE submit — streaming included — so
            # a 400 never leaves an orphaned stream decoding into a
            # slot nobody reads
            for stream in (False, True):
                status, body, _ = server.handle_generate(
                    "tiny", {"prompt": [1], "stream": stream,
                             "deadline_ms": "bogus"})
                assert status == 400, (stream, body)
            d = eng.describe()
            assert d["waiting"] == 0 and d["active"] == 0
        finally:
            server.stop()

    def test_result_timeout_is_a_total_budget(self, gpt_model):
        import queue as _q

        model, variables = gpt_model
        eng = GenerationEngine(model, variables, name="g5", num_slots=1,
                               max_len=16)
        h = eng.submit([1])  # scheduler never started: no tokens come
        t0 = time.monotonic()
        with pytest.raises(_q.Empty):
            h.result(timeout=0.1)
        assert time.monotonic() - t0 < 5.0
        # the streaming wire protocol enforces the same total budget:
        # an expired deadline cancels the request and ends the stream
        # with a terminal DEADLINE_EXCEEDED line
        h2 = eng.submit([1])
        h2._wire_timeout = 0.05
        evs = list(h2.wire_events())
        assert evs[-1]["error"]["code"] == "DEADLINE_EXCEEDED"
        # server-side deadline miss: outcome "deadline" (burns the
        # generation-availability rule), NOT a client "cancelled"
        assert h2.finish_reason == "deadline"
        eng.stop()


# ---------------------------------------------------------------------------
# brownout rung + observability wiring (satellites)


class TestBrownoutAndObservability:
    def test_generation_rung_sits_ahead_of_fallback(self, gpt_model):
        model, variables = gpt_model
        eng = GenerationEngine(model, variables, name="g4", num_slots=1,
                               max_len=16, max_new_tokens=32,
                               brownout_max_new_tokens=4)
        policy = OverloadPolicy(min_in_flight=2, max_in_flight=8,
                                interval_s=60.0)
        server = ModelServer(port=0, sentinel=False, overload=policy,
                             generators={"g4": eng})
        ladder = server.overload.ladder
        names = [r.name for r in ladder.rungs]
        assert names.index("shrink_generation_tokens") \
            == names.index("serve_fallback") - 1
        for _ in range(3):
            ladder.step_down()
        assert eng.token_cap == 4
        assert server.metrics.generation_max_new_tokens.value(
            model="g4") == 4.0
        evs = [e["data"] for e in get_flight_recorder().events(
            kinds=["serving.brownout"])]
        assert any(e["rung"] == "shrink_generation_tokens"
                   and e["direction"] == "down" for e in evs)
        for _ in range(3):
            ladder.step_up()
        assert eng.token_cap == 32
        eng.stop()
        server.stop()

    def test_ttft_detector_fires_on_regression(self):
        det = next(d for d in sn.default_detectors(min_history=4)
                   if d.name == "generation_ttft_regression")
        m = ServingMetrics()
        families = lambda: slo._doc_map([m.registry])  # noqa: E731
        t = 0.0
        for _ in range(8):  # learn a fast-TTFT baseline
            for _ in range(4):
                m.generation_ttft.observe(0.01, model="gpt")
            det.observe(families(), t)
            t += 1.0
        assert det.state == "ok"
        for _ in range(4):  # sustained 100x TTFT regression
            for _ in range(4):
                m.generation_ttft.observe(1.0, model="gpt")
            det.observe(families(), t)
            t += 1.0
        assert det.state == "firing", det.verdict()

    def test_generation_metric_families_in_slo_vocabulary(self):
        known = slo.known_metric_names()
        for name in ("generation_requests_total", "generation_ttft_seconds",
                     "generation_tokens_total", "generation_slot_occupancy",
                     "generation_preemptions_total"):
            assert name in known, name


# ---------------------------------------------------------------------------
# heavy load / storm variants (slow-marked behind the proxies above)


@pytest.mark.slow
def test_streaming_load_tokens_flow_and_zero_recompiles(gpt_model):
    """Sustained streaming load: 8 closed-loop clients over HTTP for
    several rounds — every stream completes, recompiles stay 0, and the
    slot-occupancy histogram shows real batch sharing."""
    model, variables = gpt_model
    eng = GenerationEngine(model, variables, name="gpt", num_slots=4,
                           max_len=48, max_new_tokens=24,
                           min_prompt_bucket=8, idle_wait_s=0.002,
                           temperature=0.8, max_waiting=64)
    server = ModelServer(port=0, sentinel=False, generators={"gpt": eng})
    server.start(warm=True)
    try:
        collector = get_runtime_collector()
        compiles_before = collector.jit_compiles_total.value()
        done, broken = [], []
        lock = threading.Lock()

        def run(tid):
            rng = np.random.default_rng(tid)
            client = ServingClient(server.url, max_retries=4)
            for _ in range(6):
                prompt = rng.integers(0, 127,
                                      size=1 + int(rng.integers(0, 24)))
                try:
                    r = client.generate_tokens("gpt", prompt,
                                               temperature=0.8)
                    with lock:
                        done.append(r["n_tokens"])
                except Exception as e:  # noqa: BLE001 — any failure = bug
                    with lock:
                        broken.append(repr(e))

        threads = [threading.Thread(target=run, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not broken, broken[:3]
        assert len(done) == 48
        assert collector.jit_compiles_total.value() == compiles_before
        occ = server.metrics.generation_slot_occupancy.summary(model="gpt")
        assert occ["mean"] > 0.5  # real sharing, not 1-slot serial decode
    finally:
        server.stop()


@pytest.mark.slow
def test_generation_overload_storm_preempts_and_recovers(gpt_model):
    """Storm variant: a wall of batch streams over HTTP plus a stream of
    critical requests; critical availability stays 100% (preemption +
    priority queue), every preempted batch client eventually completes
    via retry, and the engine ends drained with zero recompiles."""
    model, variables = gpt_model
    eng = GenerationEngine(model, variables, name="gpt", num_slots=2,
                           max_len=48, max_new_tokens=32,
                           min_prompt_bucket=8, idle_wait_s=0.002,
                           temperature=0.0, max_waiting=64)
    policy = OverloadPolicy(min_in_flight=1, max_in_flight=8,
                            interval_s=60.0)
    server = ModelServer(port=0, sentinel=False, overload=policy,
                         generators={"gpt": eng})
    server.start(warm=True)
    try:
        crit_ok, crit_bad, batch_done, broken = [], [], [], []
        lock = threading.Lock()
        stop = threading.Event()

        def batch_run(tid):
            client = ServingClient(server.url, max_retries=8,
                                   retry_seed=tid)
            while not stop.is_set():
                try:
                    r = client.generate_tokens("gpt", [tid % 100, 2],
                                               priority="batch",
                                               temperature=0.0)
                    with lock:
                        batch_done.append(r["n_tokens"])
                except QueueFullError:
                    time.sleep(0.01)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        broken.append(repr(e))

        threads = [threading.Thread(target=batch_run, args=(t,))
                   for t in range(6)]
        for t in threads:
            t.start()
        client = ServingClient(server.url, max_retries=4)
        for i in range(10):
            try:
                r = client.generate_tokens("gpt", [i], max_new_tokens=2,
                                           priority="critical",
                                           temperature=0.0)
                crit_ok.append(r["n_tokens"])
            except Exception as e:  # noqa: BLE001
                crit_bad.append(repr(e))
            time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "batch client hung"
        assert not crit_bad, crit_bad[:3]
        assert len(crit_ok) == 10
        assert not broken, broken[:3]
        assert batch_done, "no batch stream ever completed"
        assert server.metrics.generation_preemptions_total.value(
            model="gpt", priority="batch") >= 1.0
        assert eng.compiles_after_warm == 0
    finally:
        server.stop()
