"""Pipeline parallelism (P8) + ParallelInference (P6) + multi-host utils.

Mesh tests run on the 8-virtual-CPU-device platform per SURVEY §4.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel import (
    ParallelInference,
    pipeline_apply,
    stack_stage_params,
    stage_params_sharding,
)
from deeplearning4j_tpu.runtime import distributed
from deeplearning4j_tpu.runtime.device import MeshSpec, build_mesh


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stages(n, dim, seed=0):
    rs = np.random.RandomState(seed)
    return [
        {"w": jnp.asarray(rs.randn(dim, dim).astype(np.float32) * 0.4),
         "b": jnp.asarray(rs.randn(dim).astype(np.float32) * 0.1)}
        for _ in range(n)
    ]


def _sequential(per_stage, x):
    for p in per_stage:
        x = _stage_fn(p, x)
    return x


class TestPipeline:
    @pytest.fixture(scope="class")
    def mesh(self):
        return build_mesh(MeshSpec(data=-1, stage=4))

    def test_matches_sequential(self, mesh):
        per_stage = _stages(4, 8)
        stacked = stack_stage_params(per_stage)
        rs = np.random.RandomState(1)
        x = jnp.asarray(rs.randn(16, 8).astype(np.float32))
        want = _sequential(per_stage, x)
        got = pipeline_apply(_stage_fn, stacked, x, mesh=mesh, n_microbatches=8)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_gradients_match_sequential(self, mesh):
        per_stage = _stages(4, 8, seed=2)
        stacked = stack_stage_params(per_stage)
        rs = np.random.RandomState(3)
        x = jnp.asarray(rs.randn(8, 8).astype(np.float32))

        def loss_pipe(p):
            return jnp.sum(pipeline_apply(_stage_fn, p, x, mesh=mesh,
                                          n_microbatches=4) ** 2)

        def loss_seq(p):
            h = x
            for i in range(4):
                h = _stage_fn(jax.tree_util.tree_map(lambda a: a[i], p), h)
            return jnp.sum(h ** 2)

        g_pipe = jax.grad(loss_pipe)(stacked)
        g_seq = jax.grad(loss_seq)(stacked)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            g_pipe, g_seq)

    def test_jit_with_sharded_params(self, mesh):
        per_stage = _stages(4, 8, seed=4)
        stacked = stack_stage_params(per_stage)
        sharding = stage_params_sharding(mesh, stacked)
        stacked_sh = jax.device_put(stacked, sharding)
        rs = np.random.RandomState(5)
        x = jnp.asarray(rs.randn(16, 8).astype(np.float32))
        f = jax.jit(lambda p, x: pipeline_apply(
            _stage_fn, p, x, mesh=mesh, n_microbatches=8))
        got = f(stacked_sh, x)
        want = _sequential(per_stage, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)

    def test_no_stage_axis_sequential_fallback(self):
        mesh = build_mesh(MeshSpec(data=-1))
        per_stage = _stages(3, 4, seed=6)
        stacked = stack_stage_params(per_stage)
        x = jnp.ones((4, 4), jnp.float32)
        got = pipeline_apply(_stage_fn, stacked, x, mesh=mesh, n_microbatches=2)
        want = _sequential(per_stage, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5)

    def test_bad_microbatch_count_raises(self, mesh):
        stacked = stack_stage_params(_stages(4, 8))
        with pytest.raises(ValueError, match="not divisible"):
            pipeline_apply(_stage_fn, stacked, jnp.ones((10, 8)), mesh=mesh,
                           n_microbatches=4)

    def test_wrong_stage_count_raises(self, mesh):
        stacked = stack_stage_params(_stages(3, 8))
        with pytest.raises(ValueError, match="leading dim"):
            pipeline_apply(_stage_fn, stacked, jnp.ones((8, 8)), mesh=mesh,
                           n_microbatches=4)

    def test_grad_finite_with_norm_stage(self, mesh):
        # sqrt at 0 has an infinite derivative: guards the bubble-carry
        # initialization (must be real data, not zeros).
        def norm_stage(params, x):
            h = jnp.tanh(x @ params["w"] + params["b"])
            return h / (1e-3 + jnp.sqrt(jnp.sum(h * h, -1, keepdims=True)))

        per_stage = _stages(4, 8, seed=7)
        stacked = stack_stage_params(per_stage)
        x = jnp.asarray(np.random.RandomState(8).randn(8, 8).astype(np.float32))

        def loss(p):
            return jnp.sum(pipeline_apply(norm_stage, p, x, mesh=mesh,
                                          n_microbatches=4) ** 2)

        g = jax.grad(loss)(stacked)
        for leaf in jax.tree_util.tree_leaves(g):
            assert np.isfinite(np.asarray(leaf)).all()

    def test_composes_with_data_axis(self, mesh):
        # mesh is (data=2, stage=4): each data replica pipelines its shard.
        per_stage = _stages(4, 8, seed=9)
        stacked = stack_stage_params(per_stage)
        x = jnp.asarray(np.random.RandomState(10).randn(16, 8).astype(np.float32))
        got = pipeline_apply(_stage_fn, stacked, x, mesh=mesh, n_microbatches=4)
        want = _sequential(per_stage, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-6)


class TestParallelInference:
    def _model(self):
        w = jnp.asarray(np.random.RandomState(0).randn(4, 3).astype(np.float32))

        def forward(variables, x):
            return x @ variables["w"]

        return forward, {"w": w}

    def test_instant_mode(self):
        forward, variables = self._model()
        with ParallelInference(forward, variables,
                               devices=jax.devices()[:2]) as pi:
            x = np.ones((5, 4), np.float32)
            out = pi.output(x)
            np.testing.assert_allclose(out, np.asarray(x @ variables["w"]),
                                       rtol=1e-5)

    def test_batched_mode_concurrent_clients(self):
        forward, variables = self._model()
        rs = np.random.RandomState(1)
        inputs = [rs.randn(3, 4).astype(np.float32) for _ in range(16)]
        results = [None] * 16
        with ParallelInference(forward, variables, devices=jax.devices()[:4],
                               mode="batched", max_batch_size=8) as pi:
            def client(i):
                results[i] = pi.output(inputs[i])

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(16)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        for i in range(16):
            np.testing.assert_allclose(
                results[i], np.asarray(inputs[i] @ np.asarray(variables["w"])),
                rtol=1e-4, atol=1e-5)

    def test_error_propagates(self):
        def forward(variables, x):
            return x @ variables["w"]  # wrong shape triggers error

        with ParallelInference(forward, {"w": jnp.ones((4, 3))},
                               devices=jax.devices()[:1]) as pi:
            with pytest.raises(Exception):
                pi.output(np.ones((2, 7), np.float32))

    def test_bad_mode_raises(self):
        forward, variables = self._model()
        with pytest.raises(ValueError, match="valid"):
            ParallelInference(forward, variables, mode="nope")

    def test_shutdown_serves_pending_then_rejects(self):
        forward, variables = self._model()
        pi = ParallelInference(forward, variables, devices=jax.devices()[:1])
        x = np.ones((2, 4), np.float32)
        assert pi.output(x).shape == (2, 3)
        pi.shutdown()
        with pytest.raises(RuntimeError, match="shut down"):
            pi.output(x)
        pi.shutdown()  # idempotent

    def test_batched_respects_max_batch_rows(self):
        rows_seen = []

        def forward(variables, x):
            rows_seen.append(x.shape[0])
            return x @ variables["w"]

        w = jnp.eye(4)
        with ParallelInference(forward, {"w": w}, devices=jax.devices()[:1],
                               mode="batched", max_batch_size=8) as pi:
            import concurrent.futures as cf

            xs = [np.full((5, 4), i, np.float32) for i in range(6)]
            with cf.ThreadPoolExecutor(6) as ex:
                outs = list(ex.map(pi.output, xs))
        # 5-row requests with cap 8: batches must never merge two (10 > 8),
        # and padding buckets to 8 — traced shapes only ever 5 (instant
        # single) padded to 8.
        assert all(r <= 8 for r in rows_seen)
        for i, o in enumerate(outs):
            np.testing.assert_allclose(o, xs[i])


class TestDistributedSingleProcess:
    def test_noop_initialize_and_barrier(self):
        distributed.initialize()  # no coordinator: no-op
        assert distributed.process_count() == 1
        assert distributed.process_index() == 0
        assert not distributed.is_multiprocess()
        distributed.barrier()  # no-op
        assert distributed.broadcast_host_data({"a": 1}) == {"a": 1}

    def test_global_mesh(self):
        mesh = distributed.global_mesh()
        assert int(np.prod(list(mesh.shape.values()))) == jax.device_count()
