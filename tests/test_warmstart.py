"""Cold-start robustness tests (runtime/compilecache.py +
serving/warmstart.py): the compile-cache integrity matrix (flipped
byte / truncation / version skew -> quarantine + fresh-compile
fallback), warmup-manifest recording/restriction/persistence, /readyz
warmup progress, the zero-compile fallback engage regression, the
supervisor env arming, and THE restart-under-load chaos acceptance
(router + SIGKILLed backend restarted with warm cache + manifest).

Strategy mirrors the checkpoint corruption matrix (test_resilience):
integrity units run against hand-written artifact files (no jax compile
in the loop); one real persistent-cache round trip proves the jax
wiring; the chaos acceptance uses real subprocess backends behind a
FleetRouter with the test_router spawn idiom.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import textwrap
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.observability import flightrecorder as fr
from deeplearning4j_tpu.observability import metrics as om
from deeplearning4j_tpu.resilience.faults import (
    FaultInjector,
    set_fault_injector,
)
from deeplearning4j_tpu.runtime import compilecache as cc
from deeplearning4j_tpu.serving import (
    ModelRegistry,
    ModelServer,
    NotReadyError,
    ServingClient,
    WarmupManifest,
    spec,
)

# ---------------------------------------------------------------------------
# fixtures / helpers


@pytest.fixture(autouse=True)
def _fresh_telemetry():
    om.reset_default_registry()
    fr.set_flight_recorder(None)
    om.set_enabled(True)
    fr.set_recording(True)
    cc.set_compile_cache(None)
    yield
    cc.set_compile_cache(None)
    set_fault_injector(None)
    om.reset_default_registry()
    fr.set_flight_recorder(None)


def _wm():
    return om.get_warmstart_metrics()


def _fake_cache(tmp_path, n=3):
    """A cache dir with hand-written artifacts + a sealed manifest —
    the integrity layer is format-agnostic, so the corruption matrix
    needs no real compiles."""
    d = tmp_path / "cache"
    d.mkdir()
    for i in range(n):
        (d / f"jit_fn-{i:02d}abc-cache").write_bytes(
            bytes(range(40 + i)) * 20)
    cache = cc.CompileCache(d)
    cache.seal()
    return cache


def _quarantine_reasons():
    fam = _wm().cache_quarantined_total
    return {labels: v for labels, v in fam._data.items()}


def _scale_forward(v, x):
    import jax.numpy as jnp

    return jnp.zeros((x.shape[0], 1), jnp.float32) + v["scale"]


def _server(tmp_path=None, *, manifest=False, cache=False,
            max_batch=8, forward=_scale_forward, **kw):
    reg = ModelRegistry()
    reg.register("scale", forward, {"scale": np.float32(1.0)},
                 input_spec=spec((4,)), version="v1", mode="batched",
                 max_batch_size=max_batch)
    srv = ModelServer(reg, port=0, sentinel=False, slo_interval_s=3600.0,
                      warmup_manifest=manifest, compile_cache=cache, **kw)
    return srv, reg


def _get_json(url, path):
    with urllib.request.urlopen(url + path) as r:
        return json.loads(r.read())


def _count_compiles():
    """Process-wide XLA backend compiles via the runtime collector's
    counter (jax.monitoring-fed) — the oracle the zero-compile engage
    regression reads."""
    from deeplearning4j_tpu.observability.runtime import (
        get_runtime_collector,
    )

    return get_runtime_collector().jit_compiles_total.value()


# ---------------------------------------------------------------------------
# compile-cache integrity matrix (mirrors the checkpoint corruption tests)


class TestCompileCacheIntegrity:
    def test_seal_then_verify_clean(self, tmp_path):
        cache = _fake_cache(tmp_path)
        doc = json.loads(cache.manifest_path.read_text())
        assert len(doc["entries"]) == 3
        assert all(e["sha256"] and e["size"] for e in
                   doc["entries"].values())
        v = cache.verify()
        assert v == {"checked": 3, "quarantined": 0, "unlisted": 0}
        assert cache.quarantined == []

    def test_flipped_byte_quarantined_with_metric(self, tmp_path):
        cache = _fake_cache(tmp_path)
        victim = sorted(cache.directory.glob("*-cache"))[0]
        raw = bytearray(victim.read_bytes())
        raw[7] ^= 0xFF
        victim.write_bytes(raw)  # same size: only the digest catches it
        v = cache.verify()
        assert v["quarantined"] == 1 and v["checked"] == 3
        assert not victim.exists()
        assert (cache.quarantine_dir / victim.name).exists()
        assert cache.quarantined == [
            {"artifact": victim.name, "reason": "corrupt"}]
        assert _quarantine_reasons() == {("corrupt",): 1.0}

    def test_truncated_quarantined(self, tmp_path):
        cache = _fake_cache(tmp_path)
        victim = sorted(cache.directory.glob("*-cache"))[1]
        victim.write_bytes(victim.read_bytes()[:10])
        cache.verify()
        assert cache.quarantined == [
            {"artifact": victim.name, "reason": "truncated"}]
        assert _quarantine_reasons() == {("truncated",): 1.0}

    def test_version_skew_quarantines_all(self, tmp_path):
        cache = _fake_cache(tmp_path)
        doc = json.loads(cache.manifest_path.read_text())
        doc["jax"] = "0.0.0-somebody-else"
        cache.manifest_path.write_text(json.dumps(doc))
        v = cache.verify()
        assert v["quarantined"] == 3
        assert {q["reason"] for q in cache.quarantined} == {"version_skew"}
        assert _quarantine_reasons() == {("version_skew",): 3.0}
        # re-seal adopts nothing (dir is empty of artifacts now)
        assert cache.seal()["entries"] == 0

    def test_torn_manifest_treated_as_absent(self, tmp_path):
        cache = _fake_cache(tmp_path)
        cache.manifest_path.write_text('{"entries": [truncated')
        v = cache.verify()  # no manifest = nothing to distrust
        assert v["quarantined"] == 0
        assert cache.seal()["entries"] == 3  # re-sealed from disk

    def test_unlisted_artifacts_pass_through_and_seal(self, tmp_path):
        cache = _fake_cache(tmp_path)
        (cache.directory / "jit_new-ff-cache").write_bytes(b"x" * 64)
        v = cache.verify()
        assert v["quarantined"] == 0 and v["unlisted"] == 1
        assert cache.seal()["entries"] == 4

    def test_activate_arms_jax_and_survives_chaos_corrupt(self, tmp_path):
        """``compile.cache_corrupt`` armed: activation flips bytes in a
        cached artifact, the walk quarantines it, and the process
        degrades to a fresh compile — never a crash, never a poisoned
        executable (acceptance criterion)."""
        import jax
        import jax.numpy as jnp

        cache = _fake_cache(tmp_path)
        inj = FaultInjector()
        inj.plan("compile.cache_corrupt", at=1)
        set_fault_injector(inj)
        verdict = cache.activate()
        assert verdict["quarantined"] == 1
        assert cache.quarantined[0]["reason"] == "corrupt"
        assert jax.config.jax_compilation_cache_dir == str(cache.directory)
        assert cache.active
        # fresh compile fallback: compiled work still runs fine
        out = jax.jit(lambda x: (x * 2).sum())(jnp.ones(8))
        assert float(out) == 16.0
        evs = fr.get_flight_recorder().events(
            kinds=["compile_cache.quarantined"])
        assert len(evs) == 1 and evs[0]["data"]["reason"] == "corrupt"

    def test_cache_stall_fault_delays_activation(self, tmp_path):
        inj = FaultInjector()
        inj.plan("compile.cache_stall", at=1, arg=0.3)
        set_fault_injector(inj)
        cache = cc.CompileCache(tmp_path / "c")
        t0 = time.monotonic()
        cache.activate()
        assert time.monotonic() - t0 >= 0.3

    def test_real_persistent_cache_roundtrip(self, tmp_path):
        """The jax wiring end to end: activate -> compile -> artifacts
        on disk -> seal records them -> a fresh verify passes clean."""
        import jax
        import jax.numpy as jnp

        cache = cc.CompileCache(tmp_path / "cc")
        cache.activate()
        jax.jit(lambda x: (x @ x).sum() * 3)(
            jnp.ones((32, 32))).block_until_ready()
        sealed = cache.seal()
        assert sealed["entries"] >= 1 and sealed["bytes"] > 0
        fresh = cc.CompileCache(tmp_path / "cc")
        assert fresh.verify()["quarantined"] == 0
        assert _wm().cache_entries.value() >= 1.0

    def test_maybe_enable_from_env_is_idempotent(self, tmp_path,
                                                 monkeypatch):
        monkeypatch.setenv(cc.ENV_COMPILE_CACHE_DIR,
                           str(tmp_path / "envcc"))
        c1 = cc.maybe_enable_compile_cache()
        c2 = cc.maybe_enable_compile_cache()
        assert c1 is c2 and c1.active
        assert _wm().cache_active.value() == 1.0
        monkeypatch.delenv(cc.ENV_COMPILE_CACHE_DIR)
        cc.set_compile_cache(None)
        assert cc.maybe_enable_compile_cache() is None


# ---------------------------------------------------------------------------
# warmup manifest


class TestWarmupManifest:
    def test_note_save_load_roundtrip(self, tmp_path):
        p = tmp_path / "wm.json"
        m = WarmupManifest(p, autosave_every=10_000)
        m.note_batch("lenet", 8)
        m.note_batch("lenet", 8)
        m.note_prefill("gpt", 16)
        m.note_decode("gpt", 2, 64)
        assert m.save()
        assert not list(tmp_path.glob("*.tmp"))  # atomic, no litter
        m2 = WarmupManifest(p)
        assert m2.predict_buckets("lenet") == [8]
        assert m2.prefill_buckets("gpt") == [16]
        assert m2.decode_pairs("gpt") == [(2, 64)]
        assert m2.predict_buckets("nope") is None
        row = [e for e in m2.entries()
               if e["plane"] == "predict"][0]
        assert row["count"] == 2
        assert _wm().manifest_writes_total.value() >= 1.0

    def test_bounded_lru_eviction(self, tmp_path):
        m = WarmupManifest(max_entries=3)
        for i, b in enumerate([1, 2, 4, 8]):
            m.note_batch("m", b)
            time.sleep(0.002)  # distinct last_seen stamps
        assert len(m) == 3
        assert m.predict_buckets("m") == [2, 4, 8]  # bucket 1 was oldest

    def test_torn_file_loads_as_empty(self, tmp_path):
        p = tmp_path / "wm.json"
        p.write_text('{"entries": [{"plane": "predi')
        m = WarmupManifest(p)
        assert len(m) == 0

    def test_autosave_on_new_shape(self, tmp_path):
        p = tmp_path / "wm.json"
        m = WarmupManifest(p)
        m.note_batch("m", 4)  # a NEW shape saves immediately
        assert p.is_file()
        assert json.loads(p.read_text())["entries"][0]["shape"] == [4]


# ---------------------------------------------------------------------------
# server integration: progress-reporting readiness + manifest warmup


def _slow_forward(v, x):
    import jax.numpy as jnp

    time.sleep(0.12)  # trace-time cost: every bucket compile pays it
    return jnp.zeros((x.shape[0], 1), jnp.float32) + v["scale"]


class TestReadyzWarmupProgress:
    def test_readyz_503_carries_progress_then_flips(self):
        srv, reg = _server(forward=_slow_forward)
        try:
            srv.start(warm=True, warm_async=True)
            saw_warming = None
            saw_shed = None
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                try:
                    body = _get_json(srv.url, "/readyz")
                    break  # 200: warm
                except urllib.error.HTTPError as e:
                    b = json.loads(e.read())
                    if b.get("total"):
                        saw_warming = (b, e.headers.get("Retry-After"))
                        if saw_shed is None:
                            # a predict DURING warmup must shed
                            # retryably, never sneak a compile in
                            c = ServingClient(srv.url)
                            try:
                                c.predict("scale",
                                          np.zeros((1, 4), np.float32))
                                saw_shed = False
                            except NotReadyError as err:
                                saw_shed = err
                time.sleep(0.01)
            assert body["ready"] is True
            assert "warmed" not in body  # progress keys gone once ready
            assert saw_warming is not None, "never saw warming progress"
            prog, retry_after = saw_warming
            assert 0 <= prog["warmed"] < prog["total"] == 4
            assert prog["retry_after_ms"] >= 50.0
            assert retry_after is not None and int(retry_after) >= 1
            assert isinstance(saw_shed, NotReadyError), (
                "predict during warmup did not shed retryably")
            assert saw_shed.retryable
            # after warm: traffic flows
            out = ServingClient(srv.url).predict(
                "scale", np.zeros((2, 4), np.float32))
            assert out["version"] == "v1"
        finally:
            srv.stop()

    def test_manifest_restricts_warmup_and_detects_recompile(self):
        manifest = WarmupManifest()
        manifest.note_batch("scale", 2)
        srv, reg = _server(manifest=manifest)
        try:
            srv.start(warm=True)
            entry = reg.get("scale")
            assert entry.warmed_buckets == {2}
            fams = dict(_wm().warmup_shapes_total._data)
            assert fams[("predict", "manifest")] == 1.0
            # traffic inside the manifest: no recompile counted
            c = ServingClient(srv.url)
            c.predict("scale", np.zeros((2, 4), np.float32))
            assert _wm().recompiles_after_warm_total._data == {}
            # traffic OUTSIDE the warmed set: the recompile is counted
            # once and the flight ring names the bucket
            c.predict("scale", np.zeros((3, 4), np.float32))  # bucket 4
            assert _wm().recompiles_after_warm_total._data == {
                ("predict",): 1.0}
            c.predict("scale", np.zeros((3, 4), np.float32))
            assert _wm().recompiles_after_warm_total._data == {
                ("predict",): 1.0}  # counted once
            evs = fr.get_flight_recorder().events(
                kinds=["serving.recompile_after_warm"])
            assert [e["data"]["bucket"] for e in evs] == [4]
        finally:
            srv.stop()

    def test_live_traffic_recorded_and_persisted_on_stop(self, tmp_path):
        p = tmp_path / "wm.json"
        srv, reg = _server(manifest=str(p))
        with srv:
            c = ServingClient(srv.url)
            c.predict("scale", np.zeros((3, 4), np.float32))  # bucket 4
        doc = json.loads(p.read_text())
        rows = [(e["plane"], e["shape"]) for e in doc["entries"]]
        assert ("predict", [4]) in rows
        # a restart warms exactly the recorded mix
        srv2, reg2 = _server(manifest=str(p))
        with srv2:
            assert reg2.get("scale").warmed_buckets == {4}


# ---------------------------------------------------------------------------
# zero-compile fallback engage (the brownout satellite regression)


class TestFallbackPrewarm:
    def test_engage_fallback_causes_zero_compiles(self):
        srv, reg = _server(max_batch=4)
        try:
            srv.start(warm=True)
            entry = reg.get("scale")
            entry.set_fallback({"scale": np.float32(9.0)}, "v1-cheap")
            assert entry._fallback_pi is not None  # prewarmed + parked
            c = ServingClient(srv.url)
            before = _count_compiles()
            version = reg.engage_fallback("scale")
            out = c.predict("scale", np.zeros((2, 4), np.float32))
            assert version == "v1-cheap"
            assert out["version"] == "v1-cheap"
            assert out["outputs"][0][0] == 9.0
            assert _count_compiles() == before, (
                "engage_fallback compiled under overload — the exact "
                "storm prewarm exists to kill")
            assert entry.fallback_engaged
        finally:
            srv.stop()

    def test_disengage_reprewarms_for_the_next_cycle(self):
        srv, reg = _server(max_batch=2)
        try:
            srv.start(warm=True)
            entry = reg.get("scale")
            entry.set_fallback({"scale": np.float32(9.0)}, "v1-cheap")
            reg.engage_fallback("scale")
            assert entry._fallback_pi is None  # consumed by the engage
            restored = reg.disengage_fallback("scale")
            assert restored == "v1"
            deadline = time.monotonic() + 30
            while entry._fallback_pi is None \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert entry._fallback_pi is not None, (
                "background re-prewarm never completed")
            before = _count_compiles()
            assert reg.engage_fallback("scale") == "v1-cheap"
            assert _count_compiles() == before
        finally:
            srv.stop()

    def test_prewarm_false_keeps_lazy_engage(self):
        srv, reg = _server(max_batch=2)
        try:
            srv.start(warm=True)
            entry = reg.get("scale")
            entry.set_fallback({"scale": np.float32(9.0)}, "v1-cheap",
                               prewarm=False)
            assert entry._fallback_pi is None
            assert reg.engage_fallback("scale") == "v1-cheap"  # old path
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# generation engine: manifest-restricted warm + after-warm accounting


class TestGenerationManifestWarm:
    @pytest.fixture(scope="class")
    def gpt_model(self):
        from deeplearning4j_tpu.models.gpt import gpt_tiny

        model = gpt_tiny()
        return model, model.init(seed=0)

    def _engine(self, gpt_model):
        from deeplearning4j_tpu.serving import GenerationEngine

        model, variables = gpt_model
        return GenerationEngine(
            model, variables, name="gpt", num_slots=2, max_len=32,
            max_new_tokens=4, min_kv_bucket=16, min_prompt_bucket=8,
            idle_wait_s=0.005, temperature=0.0, seed=0)

    def test_manifest_plan_restricts_and_falls_back(self, gpt_model):
        eng = self._engine(gpt_model)
        full_pairs = [(b, kv) for b in eng.slot_buckets
                      for kv in eng.kv_buckets]
        # no manifest: full vocabulary
        p_list, pairs = eng.manifest_warm_plan(None)
        assert p_list == list(eng.prompt_buckets)
        assert pairs == full_pairs
        # observed subset: exactly that subset
        m = WarmupManifest()
        m.note_prefill("gpt", eng.prompt_buckets[0])
        m.note_decode("gpt", eng.slot_buckets[0], eng.kv_buckets[0])
        p_list, pairs = eng.manifest_warm_plan(m)
        assert p_list == [eng.prompt_buckets[0]]
        assert pairs == [(eng.slot_buckets[0], eng.kv_buckets[0])]
        # stale shapes outside the vocabulary: full fallback, never a
        # zero-shape warmup
        m2 = WarmupManifest()
        m2.note_prefill("gpt", 999)
        m2.note_decode("gpt", 999, 999)
        p_list, pairs = eng.manifest_warm_plan(m2)
        assert p_list == list(eng.prompt_buckets) and pairs == full_pairs

    def test_restricted_warm_counts_after_warm_compiles(self, gpt_model):
        eng = self._engine(gpt_model)
        m = WarmupManifest()
        m.note_prefill("gpt", eng.prompt_buckets[0])  # smallest bucket
        for kv in eng.kv_buckets:
            m.note_decode("gpt", eng.slot_buckets[0], kv)
        eng.attach_manifest(m)
        p_list, pairs = eng.manifest_warm_plan()
        eng.warm(prompt_buckets=p_list, decode_pairs=pairs,
                 source="manifest")
        assert eng.warmed
        assert eng.compiles_total == len(p_list) + len(pairs)
        assert eng.compiles_after_warm == 0
        try:
            eng.start()
            # a prompt in the warmed bucket: zero after-warm compiles
            h = eng.submit([1, 2, 3], max_new_tokens=2)
            h.result(timeout=30)
            assert eng.compiles_after_warm == 0
            # a LONG prompt outside the manifest: the prefill compile is
            # counted as after-warm and feeds the warmstart counter
            long_prompt = list(range(eng.prompt_buckets[0] + 1))
            h = eng.submit(long_prompt, max_new_tokens=2)
            h.result(timeout=30)
            assert eng.compiles_after_warm >= 1
            assert _wm().recompiles_after_warm_total.value(
                plane="generation") >= 1.0
            # and the live mix recorded what actually ran
            assert len(m.prefill_buckets("gpt")) == 2
        finally:
            eng.stop()


# ---------------------------------------------------------------------------
# supervisor arming


class TestSupervisorArming:
    def test_generation_env_carries_cache_and_manifest(self, tmp_path):
        from deeplearning4j_tpu.resilience.supervisor import (
            ElasticSupervisor,
        )

        dump = ("import os, json; print(json.dumps({k: v for k, v in "
                "os.environ.items() if 'COMPILE_CACHE' in k or "
                "'WARMUP_MANIFEST' in k}))")
        sup = ElasticSupervisor(
            [sys.executable, "-c", dump], num_workers=1,
            workdir=tmp_path, max_restarts=0,
            compile_cache_dir=tmp_path / "cc",
            warmup_manifest=tmp_path / "wm.json")
        sup.run()
        env = json.loads(sup.worker_log(0).read_text().strip())
        assert env["DL4J_TPU_COMPILE_CACHE_DIR"] == str(tmp_path / "cc")
        assert env["DL4J_TPU_WARMUP_MANIFEST"] == str(
            tmp_path / "wm.json")
        assert (tmp_path / "cc").is_dir()  # pre-created for the worker

    def test_unarmed_supervisor_leaves_env_alone(self, tmp_path):
        from deeplearning4j_tpu.resilience.supervisor import (
            ElasticSupervisor,
        )

        dump = ("import os, json; print(json.dumps([k for k in "
                "os.environ if 'COMPILE_CACHE' in k or "
                "'WARMUP_MANIFEST' in k]))")
        env = {k: v for k, v in os.environ.items()
               if "COMPILE_CACHE" not in k and "WARMUP_MANIFEST" not in k}
        sup = ElasticSupervisor([sys.executable, "-c", dump],
                                num_workers=1, workdir=tmp_path,
                                max_restarts=0, env=env)
        sup.run()
        assert json.loads(sup.worker_log(0).read_text().strip()) == []


# ---------------------------------------------------------------------------
# THE chaos acceptance: restart-under-load takes traffic warm


_BACKEND_SCRIPT = textwrap.dedent("""
    import sys, threading, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from deeplearning4j_tpu.serving import (ModelRegistry, ModelServer,
                                            spec)
    port = int(sys.argv[1])

    def fwd(v, x):
        time.sleep(0.15)   # trace-time cost: makes warmup observable
        return jnp.zeros((x.shape[0], 1), jnp.float32) + v["scale"]

    reg = ModelRegistry()
    reg.register("scale", fwd, {"scale": float(sys.argv[2])},
                 input_spec=spec((4,)), version=sys.argv[3],
                 mode="batched", max_batch_size=8)
    srv = ModelServer(reg, port=port, sentinel=False,
                      slo_interval_s=3600.0)
    t0 = time.monotonic()
    srv.start(warm=True, warm_async=True)
    print("READY", srv.port, flush=True)   # port bound; still warming
    while not srv.readiness()["ready"]:
        time.sleep(0.01)
    print("WARMED", round(time.monotonic() - t0, 3), flush=True)
    while True:
        time.sleep(3600)
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_backend(port, scale, version, *, cache_dir, manifest,
                   faults=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DL4J_TPU_COMPILE_CACHE_DIR=str(cache_dir),
               DL4J_TPU_WARMUP_MANIFEST=str(manifest))
    if faults:
        env["DL4J_TPU_FAULTS"] = faults
    return subprocess.Popen(
        [sys.executable, "-c", _BACKEND_SCRIPT, str(port), str(scale),
         version],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)


def _await_line(proc, prefix, timeout_s=90.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith(prefix):
            return line.split()
        if proc.poll() is not None:
            return None
    return None


def _wait(cond, timeout_s=10.0, interval_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval_s)
    return False


def _backend_metric(port, family):
    """Sum one counter family off a backend's classic /metrics scrape."""
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics") as r:
        text = r.read().decode()
    total = 0.0
    seen = False
    for line in text.splitlines():
        if line.startswith(family) and not line.startswith("#"):
            seen = True
            total += float(line.rsplit(" ", 1)[1])
    return total if seen else 0.0


class TestWarmRestartChaos:
    # Tier-1 budget relief (the PR 6/7 pattern, paying for the PR 20
    # autoscaler suite): subprocess chaos rides tier-2; the corrupt-
    # cache restart leg below keeps the degrade-clean path fast, and
    # the warm-count discipline runs every tier-1 via
    # TestGenerationManifestWarm.
    @pytest.mark.slow
    def test_sigkill_restart_with_warm_cache_takes_traffic_warm(
            self, tmp_path):
        """THE acceptance: 2 backends under router load, one SIGKILLed,
        restarted against the persistent cache + the manifest its own
        traffic wrote -> zero client-visible failures, /readyz flips
        only after manifest warmup, zero recompiles after the first
        post-restart request, re-admission measured."""
        from deeplearning4j_tpu.serving import FleetRouter, RouterPolicy

        cache_dir = tmp_path / "cc"
        cache_dir.mkdir()
        manifests = {i: tmp_path / f"wm{i}.json" for i in (0, 1)}
        ports = [_free_port() for _ in range(2)]
        procs = [_spawn_backend(ports[i], float(i + 1), "v1",
                                cache_dir=cache_dir,
                                manifest=manifests[i])
                 for i in (0, 1)]
        router = None
        try:
            warm_cold = {}
            for i, p in enumerate(procs):
                assert _await_line(p, "READY"), "backend failed to start"
                warmed = _await_line(p, "WARMED")
                assert warmed, "backend never flipped ready"
                warm_cold[i] = float(warmed[1])
            router = FleetRouter(
                [(f"b{i}", f"http://127.0.0.1:{ports[i]}")
                 for i in (0, 1)],
                policy=RouterPolicy(probe_interval_s=0.25,
                                    probe_timeout_s=0.5,
                                    reprobe_after_s=0.5)).start()
            assert _wait(lambda: router.backend("b1").routable,
                         timeout_s=10.0)

            served, failures = [], []
            lock = threading.Lock()
            stop_load = threading.Event()

            def load(tid):
                c = ServingClient(router.url, max_retries=3,
                                  backoff_base_s=0.05, retry_seed=tid)
                x = np.zeros((1, 4), np.float32)
                while not stop_load.is_set():
                    try:
                        out = c.predict("scale", x, deadline_ms=30000)
                        with lock:
                            served.append(out["outputs"][0][0])
                    except Exception as e:  # noqa: BLE001 — chaos
                        with lock:          # collects everything
                            failures.append(e)
                    time.sleep(0.02)

            threads = [threading.Thread(target=load, args=(i,))
                       for i in range(3)]
            for t in threads:
                t.start()
            # traffic flows (and writes both manifests + the cache)
            assert _wait(lambda: len(served) >= 20, timeout_s=20.0)

            victim = procs[1]
            victim.send_signal(signal.SIGKILL)
            t_kill = time.monotonic()
            victim.wait(timeout=10)
            assert _wait(lambda: not router.backend("b1").routable,
                         timeout_s=4.0, interval_s=0.01)

            # restart on the same port with the WARM assets
            procs[1] = _spawn_backend(ports[1], 2.0, "v2",
                                      cache_dir=cache_dir,
                                      manifest=manifests[1])
            assert _await_line(procs[1], "READY")
            # /readyz gates on warmup: while the child warms, direct
            # probes answer 503 with progress — the router must show
            # the backend as warming, not re-admit it early
            saw_warming = False
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                try:
                    _get_json(f"http://127.0.0.1:{ports[1]}", "/readyz")
                    break  # 200: warm
                except urllib.error.HTTPError as e:
                    b = json.loads(e.read())
                    if b.get("total"):
                        saw_warming = True
                        assert not router.backend("b1").routable, (
                            "router re-admitted a still-warming backend")
                except Exception:  # noqa: BLE001 — socket not up yet
                    pass
                time.sleep(0.01)
            assert saw_warming, "restart never reported warmup progress"
            warmed = _await_line(procs[1], "WARMED")
            assert warmed

            # re-admission to first post-restart success via the router
            assert _wait(lambda: router.backend("b1").routable,
                         timeout_s=15.0)
            c = ServingClient(router.url, max_retries=2)
            x = np.zeros((1, 4), np.float32)
            assert _wait(lambda: c.predict("scale", x)["outputs"][0][0]
                         == 2.0, timeout_s=10.0)
            mttr_s = time.monotonic() - t_kill
            stop_load.set()
            for t in threads:
                t.join(timeout=30)

            # zero client-visible failures across kill + restart
            assert failures == [], [repr(f) for f in failures[:3]]
            # zero recompiles after the restarted backend declared warm
            # (its manifest covered the live mix; machine-checked off
            # its own scrape)
            assert _backend_metric(
                ports[1], "warmup_recompiles_after_warm_total") == 0.0
            # the restarted process rode the sealed cache: its scrape
            # says the cache is active with entries
            assert _backend_metric(ports[1], "compile_cache_active") == 1.0
            # evidence trail for the bench gate (not asserted here: the
            # timing gate lives in bench.py warmstart where the host is
            # quiet): cold vs warm warmup seconds + MTTR
            print(f"warmstart-chaos: cold={warm_cold[1]:.2f}s "
                  f"warm={float(warmed[1]):.2f}s mttr={mttr_s:.2f}s")
        finally:
            stop_load_ev = locals().get("stop_load")
            if stop_load_ev is not None:
                stop_load_ev.set()
            if router is not None:
                router.stop()
            for p in procs:
                if p.poll() is None:
                    p.kill()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass

    def test_restart_with_corrupt_cache_degrades_clean(self, tmp_path):
        """compile.cache_corrupt armed on a restart: the backend still
        comes up warm (fresh compiles), quarantine is visible on its
        scrape, and traffic is served — never a crash."""
        cache_dir = tmp_path / "cc"
        cache_dir.mkdir()
        manifest = tmp_path / "wm.json"
        port = _free_port()
        p1 = _spawn_backend(port, 1.0, "v1", cache_dir=cache_dir,
                            manifest=manifest)
        try:
            assert _await_line(p1, "READY") and _await_line(p1, "WARMED")
            # one request so the manifest records a bucket
            c = ServingClient(f"http://127.0.0.1:{port}")
            c.predict("scale", np.zeros((1, 4), np.float32))
            p1.send_signal(signal.SIGKILL)
            p1.wait(timeout=10)
            p2 = _spawn_backend(port, 1.0, "v2", cache_dir=cache_dir,
                                manifest=manifest,
                                faults="compile.cache_corrupt@1")
        finally:
            if p1.poll() is None:
                p1.kill()
        try:
            assert _await_line(p2, "READY") and _await_line(p2, "WARMED")
            assert _backend_metric(
                port, "compile_cache_quarantined_total") >= 1.0
            out = ServingClient(f"http://127.0.0.1:{port}").predict(
                "scale", np.zeros((1, 4), np.float32))
            assert out["version"] == "v2"
        finally:
            if p2.poll() is None:
                p2.kill()
            try:
                p2.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
