"""Server-integrated cache tests: the exact-match response cache on the
predict plane (consulted BEFORE a batch slot is taken, tenant-scoped,
epoch-invalidated on hot-swap, bypass header, stale-serve under the
``cache_pressure`` brownout rung, ``/debug/cache``) and prefix-KV reuse
on the generation plane (graft + suffix-feed greedy parity with a cold
prefill, ledger ``prefix_hit`` annotation, zero recompiles after warm).

Budget discipline: one module-scoped cached ModelServer drives most
predict tests through ``handle_predict`` (no HTTP except the /debug
routes); the hot-swap test runs against the SAME server and later tests
must not assume version v1; one short-TTL function server covers
stale-serve; one module-scoped prefix-armed GenerationEngine covers the
generation plane — that class is ``@pytest.mark.slow`` (the engine warm
dominates its cost; the store's correctness invariants stay in tier-1
via test_prefixkv.py, and greedy parity is also gated by ``bench.py
cache``).
"""

import json
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.gpt import gpt_tiny
from deeplearning4j_tpu.observability import reqlog as _rl
from deeplearning4j_tpu.serving import (
    GenerationEngine,
    ModelRegistry,
    ModelServer,
    ResponseCache,
    spec,
)


def _scale_forward(v, x):
    return jnp.zeros((x.shape[0], 1), jnp.float32) + v["scale"]


def _mk_cached_server(cache=True, scale=1.0, ttl_s=None):
    registry = ModelRegistry()
    registry.register("scale", _scale_forward, {"scale": scale},
                      input_spec=spec((4,)), version="v1",
                      mode="batched", max_batch_size=8,
                      devices=jax.devices()[:1])
    if ttl_s is not None:
        cache = ResponseCache(capacity=64, ttl_s=ttl_s,
                              max_bytes=1 << 20)
    server = ModelServer(registry, port=0, sentinel=False, cache=cache)
    server.start(warm=True)
    return server, registry


@pytest.fixture(scope="module")
def cache_server():
    """One cached server for the whole module. The hot-swap test
    deploys v2 with scale=5 — tests that run after it must not assume
    v1/scale=1, and every test uses its own distinct payloads."""
    server, registry = _mk_cached_server()
    yield server, registry
    server.stop(drain=False)


def _payload(seed, rows=1):
    rng = np.random.default_rng(seed)
    return {"inputs": rng.normal(size=(rows, 4)).round(4).tolist()}


def _ledger_cache(cid):
    rec = _rl.get_request_ledger(create=True).get(cid)
    return None if rec is None else rec.get("cache")


class TestResponseCacheServer:
    def test_miss_then_hit_with_ledger_fields(self, cache_server):
        server, _ = cache_server
        payload = _payload(1)
        s1, b1 = server.handle_predict("scale", dict(payload),
                                       correlation_id="cache-miss-1")
        s2, b2 = server.handle_predict("scale", dict(payload),
                                       correlation_id="cache-hit-1")
        assert s1 == s2 == 200
        assert "cached" not in b1 and b2.get("cached") is True
        assert b2["outputs"] == b1["outputs"]
        assert _ledger_cache("cache-miss-1") == "miss"
        assert _ledger_cache("cache-hit-1") == "hit"

    def test_hit_consumes_no_batch_slot(self, cache_server):
        server, _ = cache_server
        payload = _payload(2)
        server.handle_predict("scale", dict(payload))  # fill
        before = server.metrics.device_latency.summary(
            model="scale")["count"]
        hits_before = server.response_cache.describe()["hits"]
        for _ in range(5):
            s, b = server.handle_predict("scale", dict(payload))
            assert s == 200 and b.get("cached") is True
        after = server.metrics.device_latency.summary(
            model="scale")["count"]
        # the proof the tier exists for: 5 answers, ZERO device batches
        assert after == before
        assert server.response_cache.describe()["hits"] == hits_before + 5

    def test_bypass_header_skips_lookup_and_fill(self, cache_server):
        server, _ = cache_server
        payload = _payload(3)
        for cid in ("cache-byp-1", "cache-byp-2"):
            s, b = server.handle_predict("scale", dict(payload),
                                         correlation_id=cid,
                                         cache_bypass=True)
            assert s == 200 and "cached" not in b
            assert _ledger_cache(cid) == "bypass"
        # bypass didn't fill either: a plain request still misses
        s, b = server.handle_predict("scale", dict(payload),
                                     correlation_id="cache-byp-3")
        assert s == 200 and "cached" not in b

    def test_cross_tenant_lookup_never_hits(self, cache_server):
        server, _ = cache_server
        payload = _payload(4)
        s, b = server.handle_predict("scale", dict(payload), tenant="a")
        assert s == 200 and "cached" not in b
        s, b = server.handle_predict("scale", dict(payload), tenant="a")
        assert b.get("cached") is True  # a's repeat hits
        # the SAME payload from tenant b (and anonymous) must miss
        s, b2 = server.handle_predict("scale", dict(payload), tenant="b")
        assert s == 200 and "cached" not in b2
        s, b3 = server.handle_predict("scale", dict(payload))
        assert s == 200 and "cached" not in b3

    def test_debug_cache_renders_over_http(self, cache_server):
        server, _ = cache_server
        with urllib.request.urlopen(server.url + "/debug/cache",
                                    timeout=10) as r:
            doc = json.loads(r.read())
        assert doc["response_cache"]["plane"] == "serving"
        assert doc["response_cache"]["entries"] >= 1

    def test_cache_pressure_rung_wires_stale_serve(self, cache_server):
        server, _ = cache_server
        rungs = server._default_brownout_rungs()
        assert rungs[0].name == "cache_pressure"
        rc = server.response_cache
        for i in range(4):
            rc.put("rung", f"k{i}", {"i": i}, model="m", version="v")
        entries_before = rc.describe()["entries"]
        rungs[0].engage()
        assert rc.stale_serve
        assert rc.describe()["entries"] <= entries_before // 2 + 1
        rungs[0].disengage()
        assert not rc.stale_serve

    # -- hot-swap invalidation: everything below runs post-deploy ----------

    def test_hot_swap_invalidates_and_epoch_keys(self, cache_server):
        server, registry = cache_server
        payload = _payload(5)
        s, b = server.handle_predict("scale", dict(payload))
        s, b = server.handle_predict("scale", dict(payload))
        assert b.get("cached") is True and b["version"] == "v1"
        entry = registry.get("scale")
        epoch_before = entry.epoch
        inval_before = server.response_cache.describe()["evictions"]
        registry.deploy("scale", {"scale": 5.0}, version="v2")
        assert entry.epoch == epoch_before + 1
        # the swap dropped the model's entries AND the epoch in the key
        # makes any stale survivor unreachable: fresh compute, v2 answer
        s, b = server.handle_predict("scale", dict(payload))
        assert s == 200 and "cached" not in b
        assert b["version"] == "v2"
        assert np.asarray(b["outputs"])[0][0] == 5.0
        assert server.response_cache.describe()["evictions"] > inval_before

    def test_cross_tenant_still_isolated_after_hot_swap(self,
                                                        cache_server):
        server, _ = cache_server
        payload = _payload(6)
        server.handle_predict("scale", dict(payload), tenant="a")
        s, b = server.handle_predict("scale", dict(payload), tenant="a")
        assert b.get("cached") is True
        s, b = server.handle_predict("scale", dict(payload), tenant="b")
        assert s == 200 and "cached" not in b


class TestStaleServeAndDisabled:
    def test_stale_serve_end_to_end(self):
        server, _ = _mk_cached_server(ttl_s=0.15)
        try:
            payload = _payload(7)
            s, b1 = server.handle_predict("scale", dict(payload))
            time.sleep(0.25)  # past TTL
            # strict TTL: the expired entry misses (and evicts)
            s, b = server.handle_predict("scale", dict(payload))
            assert "cached" not in b
            # re-fill, expire again, then engage brownout rung 0:
            # the expired entry now serves, marked stale
            server.handle_predict("scale", dict(payload))
            time.sleep(0.25)
            server._default_brownout_rungs()[0].engage()
            s, b = server.handle_predict(
                "scale", dict(payload), correlation_id="cache-stale-1")
            assert s == 200 and b.get("cached") is True
            assert b.get("cache_stale") is True
            assert b["outputs"] == b1["outputs"]
            assert _ledger_cache("cache-stale-1") == "stale"
        finally:
            server.stop(drain=False)

    def test_debug_cache_404_when_disabled(self):
        server = ModelServer(ModelRegistry(), port=0, sentinel=False)
        server.start(warm=False)
        try:
            assert server.response_cache is None
            with pytest.raises(urllib.request.HTTPError) as ei:
                urllib.request.urlopen(server.url + "/debug/cache",
                                       timeout=10)
            assert ei.value.code == 404
        finally:
            server.stop(drain=False)


# ---------------------------------------------------------------------------
# prefix-KV reuse on the generation plane


@pytest.fixture(scope="module")
def prefix_engine():
    model = gpt_tiny()
    engine = GenerationEngine(
        model, model.init(seed=0), name="gpt", num_slots=2, max_len=48,
        max_new_tokens=8, min_kv_bucket=8, min_prompt_bucket=8,
        idle_wait_s=0.005, temperature=0.0, max_waiting=16, seed=0,
        prefix_cache=True)
    engine.warm()
    prev = _rl.get_request_ledger()
    _rl.set_request_ledger(_rl.RequestLedger(256))
    engine.start()
    yield engine
    engine.stop()
    _rl.set_request_ledger(prev)


@pytest.mark.slow
class TestPrefixReuse:
    def test_prefix_hit_greedy_parity_and_ledger(self, prefix_engine):
        engine = prefix_engine
        # 33 tokens: the cold prefill publishes the 32-token bucket
        # prefix (strictly shorter — a suffix token must remain)
        prompt = (np.arange(1, 34, dtype=np.int32) * 3) % 128
        r1 = engine.submit(prompt, correlation_id="pfx-cold").result(
            timeout=60)
        assert engine.prefix_cache.describe()["entries"] >= 1
        hits_before = engine.prefix_cache.describe()["hits"]
        r2 = engine.submit(prompt, correlation_id="pfx-hit").result(
            timeout=60)
        # greedy decode from the grafted slab is BIT-identical to the
        # cold prefill: the KV column for position j depends only on
        # the token and position
        assert r2["tokens"] == r1["tokens"]
        assert engine.prefix_cache.describe()["hits"] == hits_before + 1
        rec = _rl.get_request_ledger(create=True).get("pfx-hit")
        assert rec["cache"] == "prefix_hit"
        assert rec["prefix_len"] == 32
        assert _rl.get_request_ledger().get("pfx-cold")["cache"] == "miss"

    def test_distinct_prefix_misses_and_no_recompiles(self,
                                                      prefix_engine):
        engine = prefix_engine
        other = (np.arange(1, 34, dtype=np.int32) * 5 + 7) % 128
        misses_before = engine.prefix_cache.describe()["misses"]
        engine.submit(other, correlation_id="pfx-other").result(
            timeout=60)
        assert engine.prefix_cache.describe()["misses"] \
            == misses_before + 1
        # the whole prefix path (graft + suffix-feed) was warmed at
        # deploy: nothing recompiled
        assert engine.compiles_after_warm == 0
        assert engine.describe()["prefix_cache"]["entries"] >= 2
