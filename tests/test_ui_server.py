"""Training UI server tests (SURVEY §2.7 Training UI; VERDICT r2 Missing #3).

The server must list runs, serve scalar series parsed from BOTH storage
formats the listeners write (JSONL and TB event files), and render the
dashboard page — all verified over real HTTP against a live instance.
"""

import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.train.tensorboard import TensorBoardWriter
from deeplearning4j_tpu.train.ui import UIServer


@pytest.fixture()
def ui(tmp_path):
    # run 1: JSONL metrics
    with open(tmp_path / "run1.jsonl", "w") as fh:
        for step in range(5):
            fh.write(json.dumps({"step": step, "epoch": 0,
                                 "total_loss": 2.0 - 0.3 * step,
                                 "note": "non-numeric ignored"}) + "\n")
    # run 2: TB event files
    w = TensorBoardWriter(str(tmp_path / "run2"))
    for step in range(4):
        w.add_scalar("loss", 1.0 - 0.1 * step, step)
        w.add_scalar("acc", 0.5 + 0.1 * step, step)
    w.close()

    server = UIServer(str(tmp_path), port=0).start()
    yield server
    server.stop()


def _get(server, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}{path}", timeout=10) as r:
        return r.status, r.read()


class TestUIServer:
    def test_dashboard_page(self, ui):
        status, body = _get(ui, "/")
        assert status == 200
        assert b"training UI" in body and b"/api/metrics" in body

    def test_runs_listing(self, ui):
        status, body = _get(ui, "/api/runs")
        assert status == 200
        assert json.loads(body) == ["run1.jsonl", "run2"]

    def test_jsonl_metrics(self, ui):
        _, body = _get(ui, "/api/metrics?run=run1.jsonl")
        series = json.loads(body)
        assert "total_loss" in series and "note" not in series
        pts = series["total_loss"]
        assert pts[0] == [0, 2.0]
        assert pts[-1][0] == 4
        assert pts[-1][1] == pytest.approx(0.8)

    def test_tb_metrics_parsed_by_own_reader(self, ui):
        _, body = _get(ui, "/api/metrics?run=run2")
        series = json.loads(body)
        assert set(series) == {"loss", "acc"}
        np.testing.assert_allclose(
            [v for _, v in series["loss"]],
            [1.0, 0.9, 0.8, 0.7], rtol=1e-6)
        assert [s for s, _ in series["acc"]] == [0, 1, 2, 3]

    def test_unknown_run_empty(self, ui):
        _, body = _get(ui, "/api/metrics?run=nope")
        assert json.loads(body) == {}

    def test_path_traversal_refused(self, ui):
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(ui, "/api/metrics?run=../etc")
        assert ei.value.code == 400

    def test_404(self, ui):
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(ui, "/nope")
        assert ei.value.code == 404


def test_remote_stats_routing(tmp_path):
    """↔ RemoteUIStatsStorageRouter: listener on the 'training host' POSTs
    metric records; the UI server's run/metrics API charts them."""
    import jax.numpy as jnp

    from deeplearning4j_tpu.train.ui import RemoteStatsListener, UIServer

    server = UIServer(str(tmp_path), port=0).start()
    try:
        url = f"http://127.0.0.1:{server.port}"
        lis = RemoteStatsListener(url, "remote-run", flush_every=2)
        for step in range(5):
            lis.on_iteration(0, step, None,
                             {"total_loss": jnp.asarray(1.0 / (step + 1))})
        lis.on_fit_end(None, None)
        assert lis.last_error is None, lis.last_error
        assert "remote-run.jsonl" in server.runs()
        series = server.metrics("remote-run.jsonl")
        assert len(series["total_loss"]) == 5
        assert series["total_loss"][0][1] == 1.0
    finally:
        server.stop()


def test_remote_stats_post_rejects_bad_run(tmp_path):
    import urllib.error
    import urllib.request

    from deeplearning4j_tpu.train.ui import UIServer

    server = UIServer(str(tmp_path), port=0).start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/api/post?run=../evil",
            data=b'{"step": 1}\n')
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(req, timeout=2)
    finally:
        server.stop()


def test_remote_stats_listener_survives_dead_server(tmp_path):
    from deeplearning4j_tpu.train.ui import RemoteStatsListener

    lis = RemoteStatsListener("http://127.0.0.1:9", "r", flush_every=1,
                              timeout=0.5)
    lis.on_iteration(0, 0, None, {"total_loss": 1.0})  # must not raise
    assert lis.last_error is not None


def test_remote_stats_listener_through_trainer_fit(tmp_path):
    """The listener rides a real Trainer.fit loop (protocol compliance)."""
    import numpy as np

    from deeplearning4j_tpu.data import ArrayDataSetIterator
    from deeplearning4j_tpu.models.lenet import lenet
    from deeplearning4j_tpu.train.trainer import Trainer
    from deeplearning4j_tpu.train.ui import RemoteStatsListener, UIServer

    server = UIServer(str(tmp_path), port=0).start()
    try:
        url = f"http://127.0.0.1:{server.port}"
        lis = RemoteStatsListener(url, "fit-run", flush_every=4)
        model = lenet()
        tr = Trainer(model)
        ts = tr.init_state()
        r = np.random.default_rng(0)
        x = r.normal(size=(16, 28, 28, 1)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[r.integers(0, 10, 16)]
        tr.fit(ts, ArrayDataSetIterator(x, y, batch_size=8), epochs=2,
               listeners=[lis])
        assert lis.last_error is None, lis.last_error
        series = server.metrics("fit-run.jsonl")
        assert len(series["total_loss"]) >= 4
    finally:
        server.stop()


def test_remote_stats_requeues_on_failure(tmp_path):
    """A failed flush keeps the records and delivers them once the server
    is reachable (the router's queue-don't-drop contract)."""
    from deeplearning4j_tpu.train.ui import RemoteStatsListener, UIServer

    server = UIServer(str(tmp_path), port=0).start()
    port = server.port
    server.stop()  # now unreachable
    lis = RemoteStatsListener(f"http://127.0.0.1:{port}", "q", flush_every=1,
                              timeout=0.5)
    lis.on_iteration(0, 0, None, {"total_loss": 3.0})
    assert lis.last_error is not None and lis._buf  # queued, not dropped
    server2 = UIServer(str(tmp_path), port=port).start()
    try:
        lis.on_iteration(0, 1, None, {"total_loss": 2.0})
        series = server2.metrics("q.jsonl")
        assert len(series["total_loss"]) == 2  # both records arrived
    finally:
        server2.stop()


def test_health_page_without_engine(tmp_path):
    """/health renders even with no SLO engine published: the live
    default-registry scrape plus a no-engine notice."""
    from deeplearning4j_tpu.observability import metrics as om
    from deeplearning4j_tpu.observability import slo

    om.reset_default_registry()
    slo.set_default_engine(None)
    server = UIServer(str(tmp_path), port=0).start()
    try:
        om.get_training_metrics().steps_total.inc(5)
        status, body = _get(server, "/health")
        assert status == 200
        assert b"no SLO engine running" in body
        assert b"train_steps_total 5" in body  # live scrape on the page
        status, body = _get(server, "/api/health")
        assert status == 200
        doc = json.loads(body)
        assert doc["slo"] is None
        names = {m["name"] for m in doc["metrics"]["metrics"]}
        assert "train_steps_total" in names
    finally:
        server.stop()
        om.reset_default_registry()


def test_health_page_renders_slo_states(tmp_path):
    """With a published engine, /health shows per-rule alert states —
    the zero-install dashboard answers "is training healthy?"."""
    from deeplearning4j_tpu.observability import metrics as om
    from deeplearning4j_tpu.observability import slo
    from deeplearning4j_tpu.serving.metrics import ServingMetrics

    om.reset_default_registry()
    sm = ServingMetrics()
    rule = slo.SLORule(
        name="ui-avail", kind="availability", objective=0.9,
        total=slo.Selector("serving_requests_total"),
        bad=slo.Selector("serving_requests_total",
                         match=(("code", "5.."),)),
        windows=(slo.BurnWindow(10.0, 40.0, 1.0),),
        for_s=0.0, resolve_hold_s=10.0)
    clock = [0.0]
    engine = slo.HealthEngine([rule], registries=[sm.registry],
                              interval_s=1.0, clock=lambda: clock[0],
                              snapshot_every_s=0)
    engine.tick()
    slo.set_default_engine(engine)
    server = UIServer(str(tmp_path), port=0).start()
    try:
        status, body = _get(server, "/health")
        assert status == 200
        assert b"ui-avail" in body and b">OK<" in body
        # drive the rule to firing; the page reflects it live
        for t in (1.0, 2.0):
            clock[0] = t
            sm.requests_total.inc(20, model="m", code="500")
            engine.tick()
        status, body = _get(server, "/health")
        assert b"FIRING" in body
        doc = json.loads(_get(server, "/api/health")[1])
        assert doc["slo"]["rules"][0]["state"] == "firing"
    finally:
        server.stop()
        slo.set_default_engine(None)
        om.reset_default_registry()
