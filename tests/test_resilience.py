"""Fault-tolerance subsystem tests (resilience/ + serde integrity +
serving retry): deterministic injection, verified checkpoints with
fallback + quarantine, retrying data iterator, auto-recovering training.

ISSUE 2 acceptance: with seeded fault injection, a run that hits one
poison batch and one corrupted checkpoint still completes with the same
final step count as the fault-free run, and ``verify_checkpoint``
detects a single flipped byte in ``state.npz``.
"""

import json
import math
import os
from pathlib import Path

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.data import ArrayDataSetIterator
from deeplearning4j_tpu.nn.config import (
    NeuralNetConfiguration,
    SequentialConfig,
)
from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
from deeplearning4j_tpu.nn.model import SequentialModel
from deeplearning4j_tpu.resilience import (
    FaultInjector,
    FaultTolerantTrainer,
    InjectedFault,
    RecoveryPolicy,
    parse_fault_spec,
    retrying,
    set_fault_injector,
)
from deeplearning4j_tpu.serde.checkpoint import (
    latest_checkpoint,
    latest_verified_checkpoint,
    restore_checkpoint,
    save_checkpoint,
    verify_checkpoint,
)
from deeplearning4j_tpu.train.trainer import Trainer
from deeplearning4j_tpu.train.updaters import Sgd
from deeplearning4j_tpu.utils.pytree import tree_allclose

jax.config.update("jax_platforms", "cpu")


@pytest.fixture(autouse=True)
def _isolated_injector():
    """Every test starts and ends with an empty process-wide injector."""
    set_fault_injector(FaultInjector())
    yield
    set_fault_injector(FaultInjector())


def _mlp():
    cfg = SequentialConfig(
        net=NeuralNetConfiguration(updater=Sgd(0.05), seed=0),
        layers=[Dense(units=16, activation="tanh"),
                OutputLayer(units=2, activation="softmax", loss="mcxent")],
        input_shape=(8,),
    )
    return SequentialModel(cfg)


def _data(n=64, batch=8, seed=0):
    r = np.random.default_rng(seed)
    x = r.normal(size=(n, 8)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(-1) > 0).astype(int)]
    return ArrayDataSetIterator(x, y, batch_size=batch, shuffle=False)


# ---------------------------------------------------------------------------
# FaultInjector


class TestFaultInjector:
    def test_at_trigger_fires_once_deterministically(self):
        inj = FaultInjector(seed=0).plan("p", at=3)
        fires = [inj.fire("p") is not None for _ in range(6)]
        assert fires == [False, False, True, False, False, False]

    def test_times_extends_consecutive_firings(self):
        inj = FaultInjector().plan("p", at=2, times=3)
        fires = [inj.fire("p") is not None for _ in range(6)]
        assert fires == [False, True, True, True, False, False]

    def test_prob_is_seed_deterministic(self):
        a = FaultInjector(seed=7).plan("p", prob=0.5, times=100)
        b = FaultInjector(seed=7).plan("p", prob=0.5, times=100)
        seq_a = [a.fire("p") is not None for _ in range(50)]
        seq_b = [b.fire("p") is not None for _ in range(50)]
        assert seq_a == seq_b and any(seq_a) and not all(seq_a)

    def test_unplanned_point_is_noop_and_uncounted(self):
        inj = FaultInjector().plan("other", at=1)
        assert inj.fire("p") is None
        assert inj.triggers("p") == 0

    def test_reset_replays_schedule(self):
        inj = FaultInjector().plan("p", at=2)
        [inj.fire("p") for _ in range(3)]
        assert len(inj.log) == 1
        inj.reset()
        assert [inj.fire("p") is not None for _ in range(3)] == \
            [False, True, False]

    def test_maybe_fail_raises_typed(self):
        inj = FaultInjector().plan("p", at=1)
        with pytest.raises(IOError, match="boom"):
            inj.maybe_fail("p", exc=IOError, msg="boom")

    def test_spec_parsing(self):
        plans = parse_fault_spec(
            "train.step_nan@8;checkpoint.write_crash@3!kill,"
            "serving.latency@1x5:0.25;data.read%0.01x2")
        assert plans[0] == {"point": "train.step_nan", "at": 8, "prob": 0.0,
                            "times": 1, "arg": 0.0, "mode": "raise"}
        assert plans[1]["mode"] == "kill"
        assert plans[2]["times"] == 5 and plans[2]["arg"] == 0.25
        assert plans[3]["at"] is None and plans[3]["prob"] == 0.01
        with pytest.raises(ValueError, match="bad fault spec"):
            parse_fault_spec("nonsense@@3")

    def test_env_config_builds_process_injector(self, monkeypatch):
        from deeplearning4j_tpu.resilience.faults import get_fault_injector
        from deeplearning4j_tpu.runtime.environment import (
            Environment,
            get_environment,
            set_environment,
        )

        monkeypatch.setenv("DL4J_TPU_FAULTS", "data.read@2x3")
        monkeypatch.setenv("DL4J_TPU_FAULT_SEED", "11")
        prev = get_environment()
        set_environment(Environment())
        set_fault_injector(None)  # force rebuild from env
        try:
            inj = get_fault_injector()
            assert inj.enabled and inj.seed == 11
            assert inj._plans["data.read"][0].at == 2
        finally:
            set_environment(prev)
            set_fault_injector(FaultInjector())

    def test_poison_batch_nanifies_float_features_only(self):
        inj = FaultInjector().plan("train.step_nan", at=1)
        batch = {"features": np.ones((2, 3), np.float32),
                 "labels": np.ones((2,), np.int32)}
        out = inj.maybe_poison_batch(batch)
        assert np.isnan(out["features"]).all()
        assert (out["labels"] == 1).all()
        # second trigger: untouched
        again = inj.maybe_poison_batch(batch)
        assert not np.isnan(again["features"]).any()


# ---------------------------------------------------------------------------
# verified checkpoints


# Child for the subprocess SIGKILL test: two async saves; the injector
# (armed via DL4J_TPU_FAULTS in the parent) SIGKILLs the process inside
# the second save's write window. "SECOND_SAVED" must never print.
_SIGKILL_CHILD = """
import sys

import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

from deeplearning4j_tpu.nn.config import (
    NeuralNetConfiguration,
    SequentialConfig,
)
from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
from deeplearning4j_tpu.nn.model import SequentialModel
from deeplearning4j_tpu.serde.checkpoint import AsyncCheckpointer
from deeplearning4j_tpu.train.trainer import Trainer
from deeplearning4j_tpu.train.updaters import Sgd

ckpt_dir = sys.argv[1]
model = SequentialModel(SequentialConfig(
    net=NeuralNetConfiguration(updater=Sgd(0.05), seed=0),
    layers=[Dense(units=16, activation="tanh"),
            OutputLayer(units=2, activation="softmax", loss="mcxent")],
    input_shape=(8,),
))
trainer = Trainer(model)
ts = trainer.init_state()
r = np.random.default_rng(0)
x = r.normal(size=(8, 8)).astype(np.float32)
batch = {"features": x,
         "labels": np.eye(2, dtype=np.float32)[(x.sum(-1) > 0).astype(int)]}
ck = AsyncCheckpointer()
ts, _ = trainer.train_step(ts, batch)
ck.save(ckpt_dir, ts, model=model, tag="t")
ck.wait_until_finished()
print("FIRST_SAVED", flush=True)
ts, _ = trainer.train_step(ts, batch)
ck.save(ckpt_dir, ts, model=model, tag="t")
ck.wait_until_finished()
print("SECOND_SAVED", flush=True)
"""


def _trained_state(tmp_path, saves=1, tag="t"):
    model = _mlp()
    trainer = Trainer(model)
    ts = trainer.init_state()
    batch = next(iter(_data(n=8))).as_dict()
    paths = []
    for _ in range(saves):
        ts, _ = trainer.train_step(ts, batch)
        paths.append(save_checkpoint(tmp_path, ts, model=model, tag=tag))
    return model, trainer, ts, paths


class TestVerifiedCheckpoints:
    def test_manifest_written_and_verifies(self, tmp_path):
        _, _, _, (p,) = _trained_state(tmp_path)
        d = Path(p)
        assert (d / "manifest.json").is_file()
        assert not list(d.glob("*.tmp")), "atomic writes must not leave tmp"
        assert verify_checkpoint(d) == (True, "ok")
        ok, why = verify_checkpoint(d, deep=True)
        assert ok, why
        man = json.loads((d / "manifest.json").read_text())
        assert man["state_npz"]["size"] == (d / "state.npz").stat().st_size
        assert all(len(rec["sha256"]) == 64
                   for rec in man["arrays"].values())

    def test_single_flipped_byte_detected(self, tmp_path):
        # acceptance: verify_checkpoint detects one flipped byte
        _, _, _, (p,) = _trained_state(tmp_path)
        npz = Path(p) / "state.npz"
        raw = bytearray(npz.read_bytes())
        raw[len(raw) // 2] ^= 0x01
        npz.write_bytes(bytes(raw))
        ok, why = verify_checkpoint(p)
        assert not ok and "sha256" in why

    def test_truncated_checkpoint_falls_back_and_quarantines(self, tmp_path):
        model, trainer, ts, paths = _trained_state(tmp_path, saves=2)
        newest = Path(paths[-1])
        with open(newest / "state.npz", "r+b") as f:
            f.truncate(100)
        got = latest_verified_checkpoint(tmp_path)
        assert got == paths[0]
        # bad dir moved aside, reason recorded
        q = tmp_path / "quarantine" / newest.name
        assert q.is_dir() and not newest.exists()
        assert "truncated" in (q / "QUARANTINE.txt").read_text()
        # restore from the fallback works
        ts2 = restore_checkpoint(got, ts)
        assert int(jax.device_get(ts2.step)) == 1

    def test_missing_dir_skipped_not_raised(self, tmp_path):
        import shutil

        _, _, _, paths = _trained_state(tmp_path, saves=3)
        shutil.rmtree(paths[-1])
        assert latest_checkpoint(tmp_path) == paths[-2]
        assert latest_verified_checkpoint(tmp_path) == paths[-2]

    def test_legacy_checkpoint_without_manifest_still_verifies(self, tmp_path):
        _, _, _, (p,) = _trained_state(tmp_path)
        (Path(p) / "manifest.json").unlink()
        ok, why = verify_checkpoint(p)
        assert ok and "legacy" in why
        assert latest_verified_checkpoint(tmp_path) == p

    def test_write_crash_injection_leaves_previous_state_restorable(
            self, tmp_path):
        """Crash between the tmp write and the rename: no truncated
        state.npz at the final path, the index never learns the name,
        and the previous checkpoint stays the verified latest."""
        from deeplearning4j_tpu.serde.checkpoint import AsyncCheckpointer

        model, trainer, ts, (first,) = _trained_state(tmp_path)
        set_fault_injector(
            FaultInjector().plan("checkpoint.write_crash", at=1))
        batch = next(iter(_data(n=8))).as_dict()
        ts, _ = trainer.train_step(ts, batch)
        with pytest.raises(InjectedFault):
            with AsyncCheckpointer() as ck:
                ck.save(tmp_path, ts, model=model, tag="t")
        crashed = tmp_path / "checkpoint_2_t"
        assert not (crashed / "state.npz").exists()
        entries = json.loads(
            (tmp_path / "checkpoint_index.json").read_text())["checkpoints"]
        assert [e["name"] for e in entries] == ["checkpoint_1_t"]
        assert latest_verified_checkpoint(tmp_path) == first

    def test_sigkill_mid_async_save_resumes_from_verified(self, tmp_path):
        """Real crash consistency: a subprocess is SIGKILLed (mode="kill",
        no Python cleanup) inside ``AsyncCheckpointer.save``'s write
        window — between the tmp write and the rename. The relaunch path
        (``latest_verified_checkpoint`` + restore) must come back at the
        previous checkpoint's step, not crash on the torn write."""
        import subprocess
        import sys

        env = dict(os.environ)
        # first save = trigger 1 (clean), second save = trigger 2 → SIGKILL
        env["DL4J_TPU_FAULTS"] = "checkpoint.write_crash@2!kill"
        proc = subprocess.run(
            [sys.executable, "-c", _SIGKILL_CHILD, str(tmp_path)],
            capture_output=True, text=True, timeout=240, env=env,
            cwd=Path(__file__).resolve().parent.parent)
        assert proc.returncode == -9, proc.stderr  # SIGKILL, not sys.exit
        assert "FIRST_SAVED" in proc.stdout
        assert "SECOND_SAVED" not in proc.stdout

        # the torn write never reached the final path or the index
        crashed = tmp_path / "checkpoint_2_t"
        assert not (crashed / "state.npz").exists()
        entries = json.loads(
            (tmp_path / "checkpoint_index.json").read_text())["checkpoints"]
        assert [e["name"] for e in entries] == ["checkpoint_1_t"]

        # relaunch resumes from the last verified checkpoint
        latest = latest_verified_checkpoint(tmp_path)
        assert latest == str(tmp_path / "checkpoint_1_t")
        assert verify_checkpoint(latest, deep=True) == (True, "ok")
        trainer = Trainer(_mlp())
        ts = restore_checkpoint(latest, trainer.init_state())
        assert int(jax.device_get(ts.step)) == 1

    def test_resave_same_step_dedups_index(self, tmp_path):
        """A rolled-back run re-saving the same step must not leave a
        duplicate index entry that rotation could double-free."""
        model, trainer, ts, _ = _trained_state(tmp_path)
        save_checkpoint(tmp_path, ts, model=model, tag="t")
        entries = json.loads(
            (tmp_path / "checkpoint_index.json").read_text())["checkpoints"]
        assert [e["name"] for e in entries] == ["checkpoint_1_t"]
        assert verify_checkpoint(tmp_path / "checkpoint_1_t")[0]


# ---------------------------------------------------------------------------
# retrying data iterator


class TestRetryingIterator:
    def test_transient_read_failure_is_retried(self):
        set_fault_injector(FaultInjector().plan("data.read", at=2))
        base = _data(n=32, batch=8)  # 4 batches
        sleeps = []
        it = retrying(base, max_retries=3, base_delay=0.01, seed=0,
                      sleep=sleeps.append)
        batches = list(it)
        assert len(batches) == 4
        assert len(it.retry_log) == 1 and len(sleeps) == 1
        # delivered batches identical to a clean pass
        clean = list(_data(n=32, batch=8))
        for a, b in zip(batches, clean):
            assert np.allclose(a.features, b.features)

    def test_shuffled_iterator_retry_preserves_stream(self):
        """shuffle=True must be retry-safe: the permutation is derived
        from (seed, epoch), so an aborted pass re-iterates in the SAME
        order and fast-forward re-delivers the exact stream."""
        set_fault_injector(FaultInjector().plan("data.read", at=3))

        def shuffled():
            r = np.random.default_rng(0)
            x = r.normal(size=(32, 8)).astype(np.float32)
            y = np.eye(2, dtype=np.float32)[(x.sum(-1) > 0).astype(int)]
            return ArrayDataSetIterator(x, y, batch_size=8, shuffle=True,
                                        seed=5)

        got = list(retrying(shuffled(), max_retries=3, base_delay=0.0,
                            sleep=lambda _s: None))
        clean = list(shuffled())
        assert len(got) == len(clean) == 4
        for a, b in zip(got, clean):
            assert np.array_equal(np.asarray(a.features),
                                  np.asarray(b.features))
        # and the NEXT epoch still reshuffles (epoch advanced on the
        # completed pass)
        it = shuffled()
        first = [np.asarray(d.features) for d in it]
        second = [np.asarray(d.features) for d in it]
        assert not all(np.array_equal(a, b)
                       for a, b in zip(first, second))

    def test_abandoned_pass_reshuffles_after_reset(self):
        """steps_per_epoch-style consumers break mid-pass then reset();
        the next pass must use a NEW permutation, not replay the same
        prefix forever (the epoch advances on abandon, via reset)."""

        def make():
            r = np.random.default_rng(0)
            x = r.normal(size=(32, 8)).astype(np.float32)
            y = np.eye(2, dtype=np.float32)[(x.sum(-1) > 0).astype(int)]
            return ArrayDataSetIterator(x, y, batch_size=8, shuffle=True,
                                        seed=5)

        it = make()
        first = []
        for i, d in enumerate(it):
            first.append(np.asarray(d.features))
            if i == 1:
                break  # abandon mid-pass
        it.reset()
        second = [np.asarray(d.features) for i, d in zip(range(2), it)]
        assert not all(np.array_equal(a, b)
                       for a, b in zip(first, second))

    def test_set_epoch_pins_permutation(self):
        r = np.random.default_rng(0)
        x = r.normal(size=(32, 8)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x.sum(-1) > 0).astype(int)]
        it = ArrayDataSetIterator(x, y, batch_size=8, shuffle=True, seed=5)
        it.set_epoch(3)
        a = [np.asarray(d.features) for d in it]   # completes → epoch 4
        assert it.epoch == 4
        it.set_epoch(3)
        b = [np.asarray(d.features) for d in it]
        for p, q in zip(a, b):
            assert np.array_equal(p, q)
        # retrying() delegates the pin
        wrapped = retrying(it)
        wrapped.set_epoch(3)
        assert wrapped.epoch == 3

    def test_one_shot_generator_raises_instead_of_truncating(self):
        def gen():
            yield 1
            raise IOError("transient")

        it = retrying(gen(), max_retries=3, base_delay=0.0,
                      sleep=lambda _s: None)
        out = []
        with pytest.raises(IOError):
            for v in it:
                out.append(v)
        assert out == [1]  # surfaced, not silently ended at 1 item

    def test_shrunken_base_raises_instead_of_truncating(self):
        class Shrinking:
            """Yields 4 items, fails mid-pass, then only has 1 item."""

            def __init__(self):
                self.passes = 0

            def __iter__(self):
                self.passes += 1
                if self.passes == 1:
                    yield from (1, 2)
                    raise IOError("transient")
                yield 1

        with pytest.raises(RuntimeError, match="already"):
            list(retrying(Shrinking(), max_retries=3, base_delay=0.0,
                          sleep=lambda _s: None))

    def test_persistent_failure_exhausts_budget(self):
        set_fault_injector(
            FaultInjector().plan("data.read", at=1, times=100))
        it = retrying(_data(n=32, batch=8), max_retries=2, base_delay=0.0,
                      sleep=lambda _s: None)
        with pytest.raises(IOError):
            list(it)
        assert len(it.retry_log) == 3  # initial + 2 retries, all failed

    def test_backoff_restarts_per_failure_streak(self):
        # two separate transients (a recovered streak between them): the
        # second streak's first delay restarts at the base, it does not
        # continue the escalation of a streak recovered long ago
        set_fault_injector(FaultInjector()
                           .plan("data.read", at=2)
                           .plan("data.read", at=5))
        sleeps = []
        it = retrying(_data(n=32, batch=8), max_retries=3, base_delay=0.01,
                      jitter=0.0, seed=0, sleep=sleeps.append)
        assert len(list(it)) == 4
        assert len(sleeps) == 2 and sleeps[0] == sleeps[1]

    def test_backoff_delays_no_overflow_deep_in_schedule(self):
        from deeplearning4j_tpu.resilience import backoff_delays

        ds = backoff_delays(base=0.01, cap=1.0, jitter=0.0)
        seq = [next(ds) for _ in range(1200)]  # 2.0**1200 would overflow
        assert seq[-1] == 1.0

    def test_backoff_delays_capped_and_jitter_bounded(self):
        from deeplearning4j_tpu.resilience import backoff_delays

        import random as _random

        ds = backoff_delays(base=0.1, cap=1.0, jitter=0.5,
                            rng=_random.Random(0))
        seq = [next(ds) for _ in range(10)]
        assert all(0.0 <= d <= 1.0 for d in seq)
        assert seq[5] > seq[0]  # grows before the cap bites


# ---------------------------------------------------------------------------
# auto-recovering training


def _clean_steps(tmp_path, epochs=2):
    trainer = Trainer(_mlp())
    ft = FaultTolerantTrainer(
        trainer, tmp_path,
        policy=RecoveryPolicy(checkpoint_every=5, keep_last=3))
    ts = ft.fit(trainer.init_state(), _data(), epochs=epochs)
    return int(jax.device_get(ts.step))


class TestFaultTolerantTrainer:
    def test_nan_injection_rolls_back_and_resumes(self, tmp_path):
        clean = _clean_steps(tmp_path / "clean")
        assert clean == 16  # 8 batches x 2 epochs

        set_fault_injector(FaultInjector().plan("train.step_nan", at=7))
        trainer = Trainer(_mlp())
        ft = FaultTolerantTrainer(
            trainer, tmp_path / "faulty",
            policy=RecoveryPolicy(checkpoint_every=5, keep_last=3))
        steps_seen = []

        class Record:
            def on_fit_start(self, t, s): pass
            def on_epoch_start(self, e): pass
            def on_iteration(self, e, step, s, m):
                steps_seen.append(step)
                return False
            def on_epoch_end(self, e, s): return False
            def on_fit_end(self, t, s): pass

        ts = ft.fit(trainer.init_state(), _data(), epochs=2,
                    listeners=[Record()])
        # completed with the fault-free step count
        assert int(jax.device_get(ts.step)) == clean
        # exactly one rollback, to the last verified checkpoint (step 5)
        rb = [r for r in ft.recoveries if r["kind"] == "rollback"]
        assert len(rb) == 1 and rb[0]["to_step"] == 5
        # training resumed AT the rolled-back step: step 6 ran twice
        assert steps_seen.count(6) == 2 and max(steps_seen) == clean
        # final loss is finite
        loss = float(jax.device_get(
            trainer.model.loss_fn(ts.params, ts.model_state,
                                  next(iter(_data(n=8))).as_dict())[0]))
        assert math.isfinite(loss)

    def test_poison_batch_and_corrupt_checkpoint_acceptance(self, tmp_path):
        """ISSUE acceptance: one poison batch AND one corrupted (indexed)
        checkpoint — the run completes and matches the fault-free step
        count; the corrupt checkpoint lands in quarantine."""
        clean = _clean_steps(tmp_path / "clean")

        set_fault_injector(FaultInjector(seed=3)
                           .plan("train.step_nan", at=7)
                           .plan("checkpoint.corrupt", at=2))
        trainer = Trainer(_mlp())
        ft = FaultTolerantTrainer(
            trainer, tmp_path / "faulty",
            policy=RecoveryPolicy(checkpoint_every=5, keep_last=3))
        ts = ft.fit(trainer.init_state(), _data(), epochs=2)
        assert int(jax.device_get(ts.step)) == clean
        rb = [r for r in ft.recoveries if r["kind"] == "rollback"]
        # the step-5 checkpoint was the corrupted one: fell back to init
        assert len(rb) == 1 and rb[0]["to_step"] == 0
        qdir = tmp_path / "faulty" / "quarantine"
        assert qdir.is_dir() and any(qdir.iterdir())

    def test_rollback_budget_exhausts_loudly(self, tmp_path):
        from deeplearning4j_tpu.resilience import NonFiniteLossError

        # every batch poisoned, skipping disabled: recovery must give up
        # after max_rollbacks instead of looping forever
        set_fault_injector(
            FaultInjector().plan("train.step_nan", at=1, times=1000))
        trainer = Trainer(_mlp())
        ft = FaultTolerantTrainer(
            trainer, tmp_path,
            policy=RecoveryPolicy(max_rollbacks=2, checkpoint_every=5,
                                  skip_poison_after=0))
        with pytest.raises(NonFiniteLossError):
            ft.fit(trainer.init_state(), _data(), epochs=1)
        assert len([r for r in ft.recoveries
                    if r["kind"] == "rollback"]) == 2

    def test_persistent_poison_batch_is_skipped(self, tmp_path):
        # the SAME batch NaNs on every replay (bad data, not transient):
        # after skip_poison_after failures it is skipped and the run
        # completes with one fewer step. Poison triggers 3 and 6: first
        # pass poisons batch 2, the replay from the step-0 anchor hits
        # batch 2 again (triggers 4,5,6) → second failure → skip.
        set_fault_injector(FaultInjector()
                           .plan("train.step_nan", at=3)
                           .plan("train.step_nan", at=6))
        trainer = Trainer(_mlp())
        ft = FaultTolerantTrainer(
            trainer, tmp_path,
            policy=RecoveryPolicy(max_rollbacks=5, checkpoint_every=100,
                                  skip_poison_after=2))
        ts = ft.fit(trainer.init_state(), _data(), epochs=1)
        skips = [r for r in ft.recoveries if r["kind"] == "skip_batch"]
        assert len(skips) == 1 and skips[0]["batch"] == 2
        assert int(jax.device_get(ts.step)) == 7  # 8 batches - 1 skipped

    def test_lr_cut_wrapper_uninstalled_after_fit(self, tmp_path):
        """The update-scaling patch must not outlive fit(): a later plain
        trainer.fit (or retrace) on the shared Trainer would otherwise
        silently bake in the stale cut scale."""
        set_fault_injector(FaultInjector().plan("train.step_nan", at=4))
        trainer = Trainer(_mlp())
        orig_upd = trainer._upd_update
        ft = FaultTolerantTrainer(
            trainer, tmp_path,
            policy=RecoveryPolicy(checkpoint_every=2, lr_cut=0.5))
        ft.fit(trainer.init_state(), _data(), epochs=1)
        assert ft._lr_scale == 0.5
        assert trainer._upd_update is orig_upd
        # a second fit starts back at full LR, not the previous cut
        set_fault_injector(FaultInjector())
        ft.fit(trainer.init_state(), _data(), epochs=1, resume=False)
        assert ft._lr_scale == 1.0

    def test_non_finite_params_never_checkpointed(self, tmp_path):
        """A poisoned state must not become a rollback target: NaN params
        hash cleanly, so the guard is at save time, not verify time."""
        trainer = Trainer(_mlp())
        ft = FaultTolerantTrainer(trainer, tmp_path)
        ts = trainer.init_state()
        import dataclasses

        poisoned = dataclasses.replace(ts, params=jax.tree_util.tree_map(
            lambda a: np.full_like(np.asarray(a), np.nan), ts.params))
        ft._save(poisoned, epoch=0, batch_in_epoch=0, tag="bad")
        assert latest_verified_checkpoint(tmp_path) is None
        assert any(r["kind"] == "skip_checkpoint" for r in ft.recoveries)
        ft._save(ts, epoch=0, batch_in_epoch=0, tag="good")
        assert latest_verified_checkpoint(tmp_path) is not None

    def test_unknown_point_in_env_spec_rejected(self):
        with pytest.raises(ValueError, match="unknown injection point"):
            parse_fault_spec("checkpoint.writecrash@3!kill")  # typo

    def test_lr_cut_applied_on_rollback(self, tmp_path):
        set_fault_injector(FaultInjector().plan("train.step_nan", at=4))
        trainer = Trainer(_mlp())
        ft = FaultTolerantTrainer(
            trainer, tmp_path,
            policy=RecoveryPolicy(checkpoint_every=2, lr_cut=0.5))
        ts = ft.fit(trainer.init_state(), _data(), epochs=1)
        assert ft._lr_scale == 0.5
        assert any(r["kind"] == "lr_cut" and r["scale"] == 0.5
                   for r in ft.recoveries)
        assert int(jax.device_get(ts.step)) == 8

    def test_resume_from_directory_continues(self, tmp_path):
        trainer = Trainer(_mlp())
        ft = FaultTolerantTrainer(
            trainer, tmp_path,
            policy=RecoveryPolicy(checkpoint_every=4))
        ts = ft.fit(trainer.init_state(), _data(), epochs=1)
        assert int(jax.device_get(ts.step)) == 8
        # relaunch: a fresh wrapper resumes from the epoch checkpoint
        trainer2 = Trainer(_mlp())
        ft2 = FaultTolerantTrainer(
            trainer2, tmp_path,
            policy=RecoveryPolicy(checkpoint_every=4))
        ts2 = ft2.fit(trainer2.init_state(), _data(), epochs=2)
        assert int(jax.device_get(ts2.step)) == 16

    def test_tbptt_refused(self, tmp_path):
        model = _mlp()
        trainer = Trainer(model)
        trainer.net.backprop_type = "tbptt"
        with pytest.raises(ValueError, match="TBPTT"):
            FaultTolerantTrainer(trainer, tmp_path)
        trainer.net.backprop_type = "standard"


# ---------------------------------------------------------------------------
# serving: injected overload + client retry with Retry-After


def _scale_server():
    import jax.numpy as jnp

    from deeplearning4j_tpu.serving import ModelRegistry, ModelServer, spec

    registry = ModelRegistry()
    registry.register(
        "scale", lambda v, x: jnp.zeros((x.shape[0], 1), jnp.float32)
        + v["scale"],
        {"scale": 1.0}, input_spec=spec((4,)), version="v1", mode="batched",
        max_batch_size=8)
    return ModelServer(registry, port=0)


class TestServingRetry:
    def test_client_retries_injected_shed_and_honors_retry_after(self):
        from deeplearning4j_tpu.serving import ServingClient

        set_fault_injector(
            FaultInjector().plan("serving.error", at=1, arg=0.2))
        server = _scale_server().start(warm=True)
        try:
            sleeps = []
            client = ServingClient(
                server.url, max_retries=2, backoff_base_s=0.01,
                retry_seed=0, sleep=sleeps.append)
            out = client.predict("scale", np.zeros((2, 4), np.float32))
            assert out["outputs"][0] == [1.0]
            # one retry happened, and it waited at least the server's
            # retry_after hint (0.2 s) rather than the 10 ms backoff
            assert len(sleeps) == 1 and sleeps[0] >= 0.2
        finally:
            server.stop()

    def test_retry_off_by_default(self):
        from deeplearning4j_tpu.serving import QueueFullError, ServingClient

        set_fault_injector(
            FaultInjector().plan("serving.error", at=1, arg=0.05))
        server = _scale_server().start(warm=True)
        try:
            client = ServingClient(server.url)
            with pytest.raises(QueueFullError) as ei:
                client.predict("scale", np.zeros((1, 4), np.float32))
            assert ei.value.retry_after_ms == pytest.approx(50.0)
        finally:
            server.stop()

    def test_latency_injection_observable(self):
        import time as _time

        from deeplearning4j_tpu.serving import ServingClient

        set_fault_injector(
            FaultInjector().plan("serving.latency", at=1, arg=0.3))
        server = _scale_server().start(warm=True)
        try:
            client = ServingClient(server.url)
            t0 = _time.monotonic()
            client.predict("scale", np.zeros((1, 4), np.float32))
            slow = _time.monotonic() - t0
            t0 = _time.monotonic()
            client.predict("scale", np.zeros((1, 4), np.float32))
            fast = _time.monotonic() - t0
            assert slow >= 0.3 and slow > fast
        finally:
            server.stop()

    def test_unparseable_503_body_still_maps_retryable(self, monkeypatch):
        """A proxy/LB shedding with a plain-text 503 + Retry-After must
        map to the retryable typed error so the retry loop engages."""
        import io
        import urllib.error
        import urllib.request
        from email.message import Message

        from deeplearning4j_tpu.serving import NotReadyError, ServingClient

        calls = {"n": 0}

        def fake_urlopen(req, timeout=None):
            calls["n"] += 1
            if calls["n"] == 1:
                hdrs = Message()
                hdrs["Retry-After"] = "1"
                raise urllib.error.HTTPError(
                    "http://x", 503, "Service Unavailable", hdrs,
                    io.BytesIO(b"<html>busy</html>"))

            class R:
                def __enter__(self):
                    return self

                def __exit__(self, *a):
                    return False

                def read(self):
                    return b'{"ok": true}'

            return R()

        monkeypatch.setattr(urllib.request, "urlopen", fake_urlopen)
        sleeps = []
        client = ServingClient("http://x", max_retries=2, sleep=sleeps.append)
        assert client._request("/p") == {"ok": True}
        assert calls["n"] == 2 and sleeps and sleeps[0] >= 1.0  # header hint
        # and with retries off it surfaces as the typed retryable error
        calls["n"] = 0
        with pytest.raises(NotReadyError) as ei:
            ServingClient("http://x")._request("/p")
        # fake_urlopen succeeds on the 2nd call; retries-off must not get
        # there
        assert calls["n"] == 1
        assert ei.value.retry_after_ms == pytest.approx(1000.0)

    def test_non_retryable_error_not_retried(self):
        from deeplearning4j_tpu.serving import (
            ModelNotFoundError,
            ServingClient,
        )

        server = _scale_server().start(warm=True)
        try:
            sleeps = []
            client = ServingClient(server.url, max_retries=3,
                                   sleep=sleeps.append)
            with pytest.raises(ModelNotFoundError):
                client.predict("nope", np.zeros((1, 4), np.float32))
            assert sleeps == []
        finally:
            server.stop()
