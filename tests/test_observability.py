"""Profiler + TensorBoard writer tests (VERDICT r2 Missing #1/#3).

Oracles: event files are read back with REAL TensorFlow's summary_iterator
(independent reader — our writer can't be self-consistently wrong), and the
profiler's chrome trace is parsed from the actual jax.profiler capture.
"""

import glob
import os

import numpy as np
import pytest

from deeplearning4j_tpu.nn.config import NeuralNetConfiguration, SequentialConfig
from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
from deeplearning4j_tpu.nn.model import SequentialModel
from deeplearning4j_tpu.train.profiling import (
    ProfilingListener,
    analyze_trace,
    compare_traces,
)
from deeplearning4j_tpu.train.tensorboard import (
    TensorBoardListener,
    TensorBoardWriter,
    _masked_crc,
    crc32c,
)
from deeplearning4j_tpu.train.trainer import Trainer
from deeplearning4j_tpu.train.updaters import Adam


def _model():
    cfg = SequentialConfig(
        net=NeuralNetConfiguration(updater=Adam(1e-2), seed=0),
        layers=[Dense(units=16, activation="relu"),
                OutputLayer(units=2, activation="softmax", loss="mcxent")],
        input_shape=(8,),
    )
    return SequentialModel(cfg)


def _data(n=32):
    r = np.random.default_rng(0)
    x = r.normal(size=(n, 8)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[r.integers(0, 2, n)]
    return [{"features": x, "labels": y}]


def _read_events(log_dir):
    from tensorflow.python.summary.summary_iterator import summary_iterator

    files = glob.glob(os.path.join(log_dir, "events.out.tfevents.*"))
    assert files, f"no event file in {log_dir}"
    events = []
    for f in files:
        events.extend(summary_iterator(f))
    return events


class TestCRC32C:
    def test_known_vectors(self):
        # canonical CRC-32C check value + empty string
        assert crc32c(b"") == 0x0
        assert crc32c(b"123456789") == 0xE3069283

    def test_mask_roundtrip_is_deterministic(self):
        assert _masked_crc(b"hello") == _masked_crc(b"hello")
        assert _masked_crc(b"hello") != _masked_crc(b"hellp")


class TestTensorBoardWriter:
    def test_scalars_read_back_by_tensorflow(self, tmp_path):
        w = TensorBoardWriter(str(tmp_path))
        w.add_scalar("loss", 2.5, step=1, wall_time=123.0)
        w.add_scalar("loss", 1.25, step=2, wall_time=124.0)
        w.add_scalar("acc", 0.75, step=2)
        w.close()

        events = _read_events(str(tmp_path))
        assert events[0].file_version == "brain.Event:2"
        scalars = [(e.step, v.tag, v.simple_value)
                   for e in events for v in e.summary.value
                   if v.HasField("simple_value")]
        assert (1, "loss", 2.5) in scalars
        assert (2, "loss", 1.25) in scalars
        assert any(t == "acc" and abs(v - 0.75) < 1e-6
                   for _, t, v in scalars)
        # wall_time survives the round trip
        assert any(abs(e.wall_time - 123.0) < 1e-6 for e in events)

    def test_histogram_read_back_by_tensorflow(self, tmp_path):
        r = np.random.default_rng(0)
        values = r.normal(size=1000)
        w = TensorBoardWriter(str(tmp_path))
        w.add_histogram("weights", values, step=5)
        w.close()

        events = _read_events(str(tmp_path))
        histos = [(e.step, v.tag, v.histo)
                  for e in events for v in e.summary.value
                  if v.HasField("histo")]
        assert len(histos) == 1
        step, tag, h = histos[0]
        assert step == 5 and tag == "weights"
        assert h.num == pytest.approx(1000)
        assert h.min == pytest.approx(values.min())
        assert h.max == pytest.approx(values.max())
        assert h.sum == pytest.approx(values.sum(), rel=1e-6)
        assert sum(h.bucket) == pytest.approx(1000)
        assert len(h.bucket_limit) == len(h.bucket)

    def test_add_scalars_one_event(self, tmp_path):
        w = TensorBoardWriter(str(tmp_path))
        w.add_scalars({"a": 1.0, "b": 2.0}, step=3)
        w.close()
        events = _read_events(str(tmp_path))
        multi = [e for e in events if len(e.summary.value) == 2]
        assert len(multi) == 1 and multi[0].step == 3


class TestTensorBoardListener:
    def test_fit_writes_scalars_and_histograms(self, tmp_path):
        model = _model()
        trainer = Trainer(model)
        ts = trainer.init_state(seed=0)
        lst = TensorBoardListener(str(tmp_path), every=1,
                                  histogram_every_epochs=2)
        trainer.fit(ts, _data(), epochs=4, listeners=[lst])

        events = _read_events(str(tmp_path))
        tags = {v.tag for e in events for v in e.summary.value}
        assert "train/total_loss" in tags
        assert any(t.startswith("params/") for t in tags)
        losses = [v.simple_value for e in events for v in e.summary.value
                  if v.tag == "train/total_loss"]
        assert len(losses) == 4
        assert losses[-1] < losses[0]  # it trained


class TestProfilingListener:
    def test_trace_captured_and_analyzed(self, tmp_path):
        model = _model()
        trainer = Trainer(model)
        ts = trainer.init_state(seed=0)
        log_dir = str(tmp_path / "prof")
        lst = ProfilingListener(log_dir, start_step=2, end_step=4)
        trainer.fit(ts, _data(), epochs=6, listeners=[lst])

        rep = lst.report()
        assert rep["steps"] >= 2
        assert rep["p50_ms"] > 0

        rows = analyze_trace(log_dir)
        assert rows, "no events aggregated from trace"
        assert all({"name", "total_us", "count", "pct"} <= set(r) for r in rows)
        assert rows[0]["total_us"] >= rows[-1]["total_us"]

    def test_compare_traces(self, tmp_path):
        model = _model()
        for run in ("a", "b"):
            trainer = Trainer(model)
            ts = trainer.init_state(seed=0)
            lst = ProfilingListener(str(tmp_path / run), start_step=1,
                                    end_step=3)
            trainer.fit(ts, _data(), epochs=4, listeners=[lst])
        rows = compare_traces(str(tmp_path / "a"), str(tmp_path / "b"))
        assert rows and all("delta_us" in r for r in rows)


class TestModelStatsListener:
    """↔ StatsListener: per-layer mean magnitudes + update:param ratio."""

    def _fit(self, tmp_path, **kw):
        import jax.numpy as jnp
        from deeplearning4j_tpu.data import ArrayDataSetIterator
        from deeplearning4j_tpu.train.listeners import ModelStatsListener

        m = _model()
        tr = Trainer(m)
        ts = tr.init_state()
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(64, 8)).astype(np.float32))
        y = jnp.asarray(np.eye(2, dtype=np.float32)[rng.integers(0, 2, 64)])
        listener = ModelStatsListener(every=4, **kw)
        tr.fit(ts, ArrayDataSetIterator(x, y, batch_size=16), epochs=4,
               listeners=[listener])
        return m

    def test_jsonl_records_ratios_per_layer(self, tmp_path):
        import json as _json

        path = str(tmp_path / "stats.jsonl")
        m = self._fit(tmp_path, jsonl_path=path)
        rows = [_json.loads(l) for l in open(path)]
        assert rows, "no stats records written"
        layer_names = [n for n, _ in m.named_layers()]
        for row in rows:
            for name in layer_names:
                assert f"param_mm/{name}" in row
                assert f"update_mm/{name}" in row
                ratio = row[f"update_ratio/{name}"]
                # Adam with lr 1e-2 on a converging net: ratios are small
                # positive numbers; 0 would mean the diff saw no update
                assert 0 < ratio < 1.0

    def test_tensorboard_scalars_and_histograms(self, tmp_path):
        tf = pytest.importorskip("tensorflow")
        tb_dir = str(tmp_path / "tb")
        w = TensorBoardWriter(tb_dir)
        self._fit(tmp_path, tensorboard=w, histograms=True)
        w.close()
        events = glob.glob(os.path.join(tb_dir, "events.out.tfevents.*"))
        assert events
        tags = set()
        for e in tf.compat.v1.train.summary_iterator(events[0]):
            for v in e.summary.value:
                tags.add(v.tag)
        assert any(t.startswith("update_ratio/") for t in tags)
        assert any(t.startswith("params/") for t in tags)

    def test_nested_param_groups_bidirectional(self, tmp_path):
        """Bidirectional layers have {'fwd': {...}, 'bwd': {...}} params —
        the stats walk must traverse nested groups, not assume two dict
        levels."""
        import json as _json

        import jax.numpy as jnp
        from deeplearning4j_tpu.data import ArrayDataSetIterator
        from deeplearning4j_tpu.nn.config import SequentialConfig
        from deeplearning4j_tpu.nn.layers import (LSTM, Bidirectional,
                                                  RnnOutputLayer)
        from deeplearning4j_tpu.nn.model import SequentialModel
        from deeplearning4j_tpu.train.listeners import ModelStatsListener

        cfg = SequentialConfig(
            net=NeuralNetConfiguration(updater=Adam(1e-2), seed=0),
            input_shape=(6, 4),
            layers=[Bidirectional(LSTM(units=8)),
                    RnnOutputLayer(units=2, activation="softmax",
                                   loss="mcxent")])
        m = SequentialModel(cfg)
        tr = Trainer(m)
        rng = np.random.default_rng(0)
        x = np.asarray(rng.normal(size=(32, 6, 4)), np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (32, 6))]
        path = str(tmp_path / "bi.jsonl")
        tr.fit(tr.init_state(), ArrayDataSetIterator(jnp.asarray(x),
                                                     jnp.asarray(y),
                                                     batch_size=16),
               epochs=4, listeners=[ModelStatsListener(every=3,
                                                       jsonl_path=path)])
        rows = [_json.loads(l) for l in open(path)]
        assert rows
        bi_name = m.layer_names[0]
        assert any(f"update_ratio/{bi_name}" in r for r in rows)

    def test_reuse_across_fits_resets_snapshot(self, tmp_path):
        """A listener reused for a second fit must not diff across the two
        models' unrelated initializations."""
        import json as _json

        import jax.numpy as jnp
        from deeplearning4j_tpu.data import ArrayDataSetIterator
        from deeplearning4j_tpu.train.listeners import ModelStatsListener

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(32, 8)).astype(np.float32))
        y = jnp.asarray(np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)])
        path = str(tmp_path / "r.jsonl")
        # every=4, 2 steps/epoch, 2 epochs -> 4 steps: snapshot at step 3,
        # fit ends with _prev set
        lis = ModelStatsListener(every=4, jsonl_path=path)
        for _ in range(2):
            m = _model()
            tr = Trainer(m)
            tr.fit(tr.init_state(), ArrayDataSetIterator(x, y, batch_size=16),
                   epochs=2, listeners=[lis])
        rows = [_json.loads(l) for l in open(path)]
        for row in rows:
            for k, v in row.items():
                if k.startswith("update_ratio/"):
                    assert v < 0.5, (
                        "cross-fit diff leaked into ratios: %r" % row)

    def test_tbptt_identical_params_not_reported_as_dead(self, tmp_path):
        """Under TBPTT, windows between batch updates see identical params;
        those must be skipped, not written as update_ratio=0."""
        import json as _json

        import jax.numpy as jnp
        from deeplearning4j_tpu.data import ArrayDataSetIterator
        from deeplearning4j_tpu.nn.config import SequentialConfig
        from deeplearning4j_tpu.nn.layers import LSTM, RnnOutputLayer
        from deeplearning4j_tpu.nn.model import SequentialModel
        from deeplearning4j_tpu.train.listeners import ModelStatsListener

        cfg = SequentialConfig(
            net=NeuralNetConfiguration(updater=Adam(1e-2), seed=0,
                                       backprop_type="tbptt",
                                       tbptt_length=4),
            input_shape=(16, 3),
            layers=[LSTM(units=8),
                    RnnOutputLayer(units=2, activation="softmax",
                                   loss="mcxent")])
        m = SequentialModel(cfg)
        tr = Trainer(m)
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(16, 16, 3)).astype(np.float32))
        y = jnp.asarray(np.eye(2, dtype=np.float32)[
            rng.integers(0, 2, (16, 16))])
        path = str(tmp_path / "tb.jsonl")
        tr.fit(tr.init_state(), ArrayDataSetIterator(x, y, batch_size=8),
               epochs=6,
               listeners=[ModelStatsListener(every=2, jsonl_path=path)])
        rows = [_json.loads(l) for l in open(path)]
        ratios = [v for r in rows for k, v in r.items()
                  if k.startswith("update_ratio/")]
        assert ratios, "no reports emitted at all under tbptt"
        assert all(v > 0 for v in ratios), "zero-update report leaked"


class TestOpCosts:
    """Static HLO cost analysis (↔ OpProfiler counters; profiling.op_costs)."""

    def test_matmul_flops_and_intensity(self):
        import jax.numpy as jnp

        from deeplearning4j_tpu.train.profiling import (
            arithmetic_intensity,
            op_costs,
        )

        def f(a, b):
            return jnp.tanh(a @ b).sum()

        c = op_costs(f, jnp.ones((64, 64), jnp.float32),
                     jnp.ones((64, 64), jnp.float32))
        # dominated by the 2*64^3 matmul; cost model may add elementwise
        assert c["flops"] >= 2 * 64**3
        ai = arithmetic_intensity(c)
        if ai is not None:  # CPU backend reports byte traffic
            assert 0 < ai < 1000

    def test_train_step_costs(self):
        from deeplearning4j_tpu.models.lenet import lenet
        from deeplearning4j_tpu.train.profiling import op_costs
        from deeplearning4j_tpu.train.trainer import Trainer

        model = lenet()
        tr = Trainer(model)
        ts = tr.init_state()
        import numpy as np

        batch = {"features": np.zeros((8, 28, 28, 1), np.float32),
                 "labels": np.zeros((8, 10), np.float32)}
        c = op_costs(tr.train_step, ts, batch)
        # fwd+bwd+Adam of LeNet at b8 is far beyond 1 MFLOP
        assert c["flops"] > 1e6


class TestActivationStatsListener:
    def test_jsonl_and_tensorboard(self, tmp_path):
        import json as _json

        from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator
        from deeplearning4j_tpu.models.lenet import lenet
        from deeplearning4j_tpu.train.listeners import (
            ActivationStatsListener,
        )
        from deeplearning4j_tpu.train.tensorboard import TensorBoardWriter
        from deeplearning4j_tpu.train.trainer import Trainer

        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 28, 28, 1)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 16)]
        model = lenet()
        trainer = Trainer(model)
        ts = trainer.init_state()
        path = tmp_path / "acts.jsonl"
        tb = TensorBoardWriter(str(tmp_path / "tb"))
        lst = ActivationStatsListener(x[:4], every=2, jsonl_path=str(path),
                                      tensorboard=tb, histograms=True)
        ts = trainer.fit(ts, ArrayDataSetIterator(x, y, batch_size=8),
                         epochs=2, listeners=[lst])
        tb.close()
        rows = [_json.loads(l) for l in open(path)]
        assert rows, "no activation reports"
        keys = [k for k in rows[0] if k.startswith("activation_mm/")]
        assert len(keys) == len(model.layers)
        assert all(np.isfinite(r[k]) for r in rows for k in keys)

    def test_rejects_model_without_feed_forward(self):
        from deeplearning4j_tpu.train.listeners import (
            ActivationStatsListener,
        )

        class FakeTrainer:
            model = object()

        lst = ActivationStatsListener(np.zeros((1, 4), np.float32))
        import pytest

        with pytest.raises(TypeError, match="feed_forward"):
            lst.on_fit_start(FakeTrainer(), None)

    def test_graph_model_inputs_excluded(self, tmp_path):
        import json as _json

        from deeplearning4j_tpu.nn.config import (
            GraphConfig,
            GraphVertex,
            NeuralNetConfiguration,
        )
        from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
        from deeplearning4j_tpu.nn.model import GraphModel
        from deeplearning4j_tpu.train.listeners import (
            ActivationStatsListener,
        )
        from deeplearning4j_tpu.train.trainer import Trainer

        cfg = GraphConfig(
            net=NeuralNetConfiguration(),
            inputs=["in"], input_shapes={"in": (4,)},
            vertices={
                "h": GraphVertex(kind="layer", inputs=["in"],
                                 layer=Dense(units=8, activation="relu")),
                "out": GraphVertex(kind="layer", inputs=["h"],
                                   layer=OutputLayer(units=2)),
            },
            outputs=["out"])
        m = GraphModel(cfg)
        trainer = Trainer(m)
        ts = trainer.init_state()
        rng = np.random.default_rng(0)
        x = rng.normal(size=(8, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)]
        path = tmp_path / "g.jsonl"
        lst = ActivationStatsListener(x[:2], every=1,
                                      jsonl_path=str(path))
        from deeplearning4j_tpu.data.iterators import ArrayDataSetIterator

        trainer.fit(ts, ArrayDataSetIterator(x, y, batch_size=4),
                    epochs=1, listeners=[lst])
        rows = [_json.loads(l) for l in open(path)]
        keys = {k for r in rows for k in r if k.startswith("activation_mm/")}
        assert keys == {"activation_mm/h", "activation_mm/out"}  # no input
