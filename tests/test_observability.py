"""Profiler + TensorBoard writer tests (VERDICT r2 Missing #1/#3).

Oracles: event files are read back with REAL TensorFlow's summary_iterator
(independent reader — our writer can't be self-consistently wrong), and the
profiler's chrome trace is parsed from the actual jax.profiler capture.
"""

import glob
import os

import numpy as np
import pytest

from deeplearning4j_tpu.nn.config import NeuralNetConfiguration, SequentialConfig
from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
from deeplearning4j_tpu.nn.model import SequentialModel
from deeplearning4j_tpu.train.profiling import (
    ProfilingListener,
    analyze_trace,
    compare_traces,
)
from deeplearning4j_tpu.train.tensorboard import (
    TensorBoardListener,
    TensorBoardWriter,
    _masked_crc,
    crc32c,
)
from deeplearning4j_tpu.train.trainer import Trainer
from deeplearning4j_tpu.train.updaters import Adam


def _model():
    cfg = SequentialConfig(
        net=NeuralNetConfiguration(updater=Adam(1e-2), seed=0),
        layers=[Dense(units=16, activation="relu"),
                OutputLayer(units=2, activation="softmax", loss="mcxent")],
        input_shape=(8,),
    )
    return SequentialModel(cfg)


def _data(n=32):
    r = np.random.default_rng(0)
    x = r.normal(size=(n, 8)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[r.integers(0, 2, n)]
    return [{"features": x, "labels": y}]


def _read_events(log_dir):
    from tensorflow.python.summary.summary_iterator import summary_iterator

    files = glob.glob(os.path.join(log_dir, "events.out.tfevents.*"))
    assert files, f"no event file in {log_dir}"
    events = []
    for f in files:
        events.extend(summary_iterator(f))
    return events


class TestCRC32C:
    def test_known_vectors(self):
        # canonical CRC-32C check value + empty string
        assert crc32c(b"") == 0x0
        assert crc32c(b"123456789") == 0xE3069283

    def test_mask_roundtrip_is_deterministic(self):
        assert _masked_crc(b"hello") == _masked_crc(b"hello")
        assert _masked_crc(b"hello") != _masked_crc(b"hellp")


class TestTensorBoardWriter:
    def test_scalars_read_back_by_tensorflow(self, tmp_path):
        w = TensorBoardWriter(str(tmp_path))
        w.add_scalar("loss", 2.5, step=1, wall_time=123.0)
        w.add_scalar("loss", 1.25, step=2, wall_time=124.0)
        w.add_scalar("acc", 0.75, step=2)
        w.close()

        events = _read_events(str(tmp_path))
        assert events[0].file_version == "brain.Event:2"
        scalars = [(e.step, v.tag, v.simple_value)
                   for e in events for v in e.summary.value
                   if v.HasField("simple_value")]
        assert (1, "loss", 2.5) in scalars
        assert (2, "loss", 1.25) in scalars
        assert any(t == "acc" and abs(v - 0.75) < 1e-6
                   for _, t, v in scalars)
        # wall_time survives the round trip
        assert any(abs(e.wall_time - 123.0) < 1e-6 for e in events)

    def test_histogram_read_back_by_tensorflow(self, tmp_path):
        r = np.random.default_rng(0)
        values = r.normal(size=1000)
        w = TensorBoardWriter(str(tmp_path))
        w.add_histogram("weights", values, step=5)
        w.close()

        events = _read_events(str(tmp_path))
        histos = [(e.step, v.tag, v.histo)
                  for e in events for v in e.summary.value
                  if v.HasField("histo")]
        assert len(histos) == 1
        step, tag, h = histos[0]
        assert step == 5 and tag == "weights"
        assert h.num == pytest.approx(1000)
        assert h.min == pytest.approx(values.min())
        assert h.max == pytest.approx(values.max())
        assert h.sum == pytest.approx(values.sum(), rel=1e-6)
        assert sum(h.bucket) == pytest.approx(1000)
        assert len(h.bucket_limit) == len(h.bucket)

    def test_add_scalars_one_event(self, tmp_path):
        w = TensorBoardWriter(str(tmp_path))
        w.add_scalars({"a": 1.0, "b": 2.0}, step=3)
        w.close()
        events = _read_events(str(tmp_path))
        multi = [e for e in events if len(e.summary.value) == 2]
        assert len(multi) == 1 and multi[0].step == 3


class TestTensorBoardListener:
    def test_fit_writes_scalars_and_histograms(self, tmp_path):
        model = _model()
        trainer = Trainer(model)
        ts = trainer.init_state(seed=0)
        lst = TensorBoardListener(str(tmp_path), every=1,
                                  histogram_every_epochs=2)
        trainer.fit(ts, _data(), epochs=4, listeners=[lst])

        events = _read_events(str(tmp_path))
        tags = {v.tag for e in events for v in e.summary.value}
        assert "train/total_loss" in tags
        assert any(t.startswith("params/") for t in tags)
        losses = [v.simple_value for e in events for v in e.summary.value
                  if v.tag == "train/total_loss"]
        assert len(losses) == 4
        assert losses[-1] < losses[0]  # it trained


class TestProfilingListener:
    def test_trace_captured_and_analyzed(self, tmp_path):
        model = _model()
        trainer = Trainer(model)
        ts = trainer.init_state(seed=0)
        log_dir = str(tmp_path / "prof")
        lst = ProfilingListener(log_dir, start_step=2, end_step=4)
        trainer.fit(ts, _data(), epochs=6, listeners=[lst])

        rep = lst.report()
        assert rep["steps"] >= 2
        assert rep["p50_ms"] > 0

        rows = analyze_trace(log_dir)
        assert rows, "no events aggregated from trace"
        assert all({"name", "total_us", "count", "pct"} <= set(r) for r in rows)
        assert rows[0]["total_us"] >= rows[-1]["total_us"]

    def test_compare_traces(self, tmp_path):
        model = _model()
        for run in ("a", "b"):
            trainer = Trainer(model)
            ts = trainer.init_state(seed=0)
            lst = ProfilingListener(str(tmp_path / run), start_step=1,
                                    end_step=3)
            trainer.fit(ts, _data(), epochs=4, listeners=[lst])
        rows = compare_traces(str(tmp_path / "a"), str(tmp_path / "b"))
        assert rows and all("delta_us" in r for r in rows)
