"""Dataset fetcher tests (VERDICT r2 Missing #5).

ref strategy: the reference's iterator tests assert shapes/classes/label
encoding per fetcher. Synthetic-fallback loaders must additionally be
LEARNABLE (the MNIST pattern) — a linear probe beats chance by a wide
margin — and the real-file parsers are oracle-tested against files we
write in the on-disk formats (CIFAR pickle, EMNIST idx, iris csv).
"""

import gzip
import pickle
import struct

import numpy as np
import pytest

from deeplearning4j_tpu.data import (
    load_cifar10,
    load_cifar100,
    load_emnist,
    load_iris,
    load_mnist,
    load_tiny_imagenet,
)


def _linear_probe_acc(x, y, xte, yte, *, steps=200, lr=0.5):
    """Tiny softmax regression in numpy — independent of the framework."""
    n, d = x.reshape(len(x), -1).shape
    c = y.shape[1]
    xf = x.reshape(n, -1)
    w = np.zeros((d, c))
    for _ in range(steps):
        p = np.exp(xf @ w - (xf @ w).max(1, keepdims=True))
        p /= p.sum(1, keepdims=True)
        w -= lr / n * xf.T @ (p - y)
    pte = xte.reshape(len(xte), -1) @ w
    return (pte.argmax(1) == yte.argmax(1)).mean()


class TestSyntheticFallbacks:
    def test_cifar10_shapes_and_learnable(self):
        (xtr, ytr), (xte, yte), is_real = load_cifar10(n_train=512, n_test=256)
        assert xtr.shape == (512, 32, 32, 3) and ytr.shape == (512, 10)
        assert xtr.dtype == np.float32 and 0.0 <= xtr.min() <= xtr.max() <= 1.0
        acc = _linear_probe_acc(xtr, ytr, xte, yte)
        assert acc > 0.5, f"fallback not learnable: {acc}"

    def test_cifar100_classes(self):
        (xtr, ytr), _, _ = load_cifar100(n_train=256, n_test=64)
        assert ytr.shape == (256, 100)
        assert set(np.unique(ytr)) == {0.0, 1.0}

    def test_emnist_splits(self):
        for split, classes in (("balanced", 47), ("letters", 26),
                               ("digits", 10)):
            (xtr, ytr), _, _ = load_emnist(split, n_train=128, n_test=32)
            assert xtr.shape == (128, 28, 28, 1)
            assert ytr.shape == (128, classes)
        with pytest.raises(ValueError, match="unknown EMNIST split"):
            load_emnist("nope")

    def test_tiny_imagenet_shapes(self):
        (xtr, ytr), _, _ = load_tiny_imagenet(n_train=64, n_test=16)
        assert xtr.shape == (64, 64, 64, 3) and ytr.shape == (64, 200)

    def test_iris_stratified_and_learnable(self):
        (xtr, ytr), (xte, yte), is_real = load_iris(test_frac=0.2)
        assert xtr.shape[1] == 4 and ytr.shape[1] == 3
        assert len(xtr) + len(xte) == 150
        # stratified: every class appears in both splits
        assert (ytr.sum(0) > 0).all() and (yte.sum(0) > 0).all()
        acc = _linear_probe_acc(xtr, ytr, xte, yte, steps=500, lr=0.1)
        assert acc > 0.7, f"iris probe only {acc}"

    def test_int_labels_mode(self):
        (xtr, ytr), _, _ = load_cifar10(n_train=32, n_test=8, one_hot=False)
        assert ytr.ndim == 1 and ytr.dtype.kind in "iu"

    def test_deterministic(self):
        a = load_cifar10(n_train=16, n_test=4)[0][0]
        b = load_cifar10(n_train=16, n_test=4)[0][0]
        np.testing.assert_array_equal(a, b)


class TestRealFileParsers:
    """Write files in the real on-disk formats and check the parsers."""

    def test_cifar10_pickle_batches(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.data import datasets as ds

        d = tmp_path / "cifar-10-batches-py"
        d.mkdir()
        r = np.random.default_rng(0)
        for i in range(1, 6):
            data = r.integers(0, 256, (20, 3072), dtype=np.uint8)
            with open(d / f"data_batch_{i}", "wb") as f:
                pickle.dump({b"data": data,
                             b"labels": list(r.integers(0, 10, 20))}, f)
        test = r.integers(0, 256, (10, 3072), dtype=np.uint8)
        with open(d / "test_batch", "wb") as f:
            pickle.dump({b"data": test, b"labels": list(range(10))}, f)

        monkeypatch.setattr(ds, "_search",
                            lambda names: d if "cifar-10-batches-py" in names[0]
                            else None)
        (xtr, ytr), (xte, yte), is_real = ds.load_cifar10()
        assert is_real
        assert xtr.shape == (100, 32, 32, 3) and xte.shape == (10, 32, 32, 3)
        # NCHW->NHWC transpose oracle on one pixel
        np.testing.assert_allclose(
            xte[0, 0, 0], test[0].reshape(3, 32, 32)[:, 0, 0] / 255.0)
        assert yte.argmax(1).tolist() == list(range(10))

    def test_emnist_idx_files(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.data import datasets as ds

        d = tmp_path / "emnist"
        d.mkdir()
        r = np.random.default_rng(0)

        def write_idx(path, arr):
            with gzip.open(path, "wb") as f:
                f.write(struct.pack(">I", (arr.ndim) | 0x0800))
                for s in arr.shape:
                    f.write(struct.pack(">I", s))
                f.write(arr.tobytes())

        xtr = r.integers(0, 256, (30, 28, 28), dtype=np.uint8)
        ytr = r.integers(1, 27, 30, dtype=np.uint8)  # letters: 1-indexed
        xte = r.integers(0, 256, (10, 28, 28), dtype=np.uint8)
        yte = r.integers(1, 27, 10, dtype=np.uint8)
        write_idx(d / "emnist-letters-train-images-idx3-ubyte.gz", xtr)
        write_idx(d / "emnist-letters-train-labels-idx1-ubyte.gz", ytr)
        write_idx(d / "emnist-letters-test-images-idx3-ubyte.gz", xte)
        write_idx(d / "emnist-letters-test-labels-idx1-ubyte.gz", yte)

        def search(names):
            for n in names:
                p = tmp_path / n
                if p.exists():
                    return p
            return None

        monkeypatch.setattr(ds, "_search", search)
        (x, y), _, is_real = ds.load_emnist("letters")
        assert is_real
        assert x.shape == (30, 28, 28, 1)
        assert y.shape == (30, 26)
        # labels rebased to 0..25
        assert y.argmax(1).min() >= 0 and y.argmax(1).max() <= 25
        # idx transpose oracle
        np.testing.assert_allclose(x[0, :, :, 0], xtr[0].T / 255.0)

    def test_iris_csv(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.data import datasets as ds

        rows = ["5.1,3.5,1.4,0.2,Iris-setosa",
                "7.0,3.2,4.7,1.4,Iris-versicolor",
                "6.3,3.3,6.0,2.5,Iris-virginica"] * 10
        p = tmp_path / "iris.csv"
        p.write_text("\n".join(rows))
        monkeypatch.setattr(ds, "_search",
                            lambda names: p if any("iris" in n for n in names)
                            else None)
        (xtr, ytr), (xte, yte), is_real = ds.load_iris(test_frac=0.3)
        assert is_real
        assert xtr.shape[1] == 4
        assert len(xtr) + len(xte) == 30
        assert ytr.shape[1] == 3


class TestTrainOnDataset:
    # Tier-1 budget relief (the PR 6/7 pattern, paying for the PR 20
    # autoscaler suite): the loader surface stays wired every tier-1
    # run via TestSyntheticFallbacks/TestRealFileParsers, and the
    # identical lenet train-and-evaluate path runs in test_lenet_e2e;
    # the fit-on-emnist convergence leg rides tier-2.
    @pytest.mark.slow
    def test_lenet_fits_emnist_digits(self):
        """End-to-end: a zoo model trains on a fetched dataset."""
        import jax

        from deeplearning4j_tpu.data import ArrayDataSetIterator
        from deeplearning4j_tpu.models.lenet import lenet
        from deeplearning4j_tpu.train.trainer import Trainer
        from deeplearning4j_tpu.train.updaters import Adam

        (xtr, ytr), _, _ = load_emnist("digits", n_train=256, n_test=32)
        model = lenet(updater=Adam(3e-3))
        trainer = Trainer(model)
        ts = trainer.init_state(seed=0)
        it = ArrayDataSetIterator(xtr, ytr, batch_size=32)
        losses = []

        class Cap:
            def on_fit_start(self, t, s):
                pass

            def on_epoch_start(self, e):
                pass

            def on_iteration(self, e, s, ts_, m):
                losses.append(float(jax.device_get(m["total_loss"])))
                return False

            def on_epoch_end(self, e, ts_):
                return False

            def on_fit_end(self, t, s):
                pass

        trainer.fit(ts, it, epochs=12, listeners=[Cap()])
        assert losses[-1] < losses[0] * 0.5
