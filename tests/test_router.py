"""Fleet router tests (serving/router.py): health table + ejection /
re-probe, retry-budget accounting, drain state machine, hash affinity,
retry-elsewhere failover, fleet-level priority shed, federation
endpoints, the stream proxy, client transport-error typing — and THE
chaos acceptance: 3 real subprocess backends under load, one SIGKILLed
mid-stream → zero client-visible failures for retryable traffic,
ejection < 2 s, re-admission after restart; plus a rolling drain deploy
with zero failed or dropped in-flight requests.

Budget discipline: pure-logic units use injected clocks and fake
transports (no HTTP, no jax); the integration fleet is 3 in-process
ModelServers behind one class-scoped fixture; only the chaos class pays
for subprocess backends (class-scoped, one spawn for every test in it);
the 10x-load variant is @pytest.mark.slow.
"""

import json
import os
import re
import signal
import socket
import subprocess
import sys
import textwrap
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import urllib.request

from deeplearning4j_tpu.analysis import lockcheck
from deeplearning4j_tpu.resilience.faults import (
    FaultInjector,
    set_fault_injector,
)
from deeplearning4j_tpu.serving import (
    ConnectionFailedError,
    FleetRouter,
    HashRing,
    ModelRegistry,
    ModelServer,
    QueueFullError,
    RetryBudget,
    RouterPolicy,
    ServingClient,
    spec,
)
from deeplearning4j_tpu.serving.router import ADMIN_DRAINING, Backend

# ---------------------------------------------------------------------------
# helpers


def _scale_forward(v, x):
    """Every output row equals v['scale'] — which backend served a
    request is readable straight off the response."""
    return jnp.zeros((x.shape[0], 1), jnp.float32) + v["scale"]


def _mk_backend_server(scale, *, port=0, version="v1"):
    registry = ModelRegistry()
    registry.register("scale", _scale_forward, {"scale": scale},
                      input_spec=spec((4,)), version=version,
                      mode="batched", max_batch_size=8,
                      devices=jax.devices()[:1])
    server = ModelServer(registry, port=port, sentinel=False)
    server.start(warm=True)
    return server, registry


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _raw_predict(url, *, headers=None, rows=1):
    body = json.dumps({"inputs": [[0.0] * 4] * rows}).encode()
    req = urllib.request.Request(
        url + "/v1/models/scale:predict", data=body,
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def _fleet_debug(url):
    with urllib.request.urlopen(url + "/debug/fleet", timeout=10) as r:
        return json.loads(r.read())


def _wait(cond, timeout_s, interval_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval_s)
    return cond()


# ---------------------------------------------------------------------------
# units: retry budget


class TestRetryBudget:
    def test_deposit_spend_and_cap(self):
        b = RetryBudget(ratio=0.5, initial=0.0, cap=2.0)
        assert not b.try_spend()          # empty
        for _ in range(2):
            b.deposit()
        assert b.balance == 1.0
        assert b.try_spend()              # 2 deposits fund 1 retry
        assert not b.try_spend()
        for _ in range(100):
            b.deposit()                   # cap bounds the bank
        assert b.balance == 2.0

    def test_exhaustion_is_counted(self):
        b = RetryBudget(ratio=0.1, initial=1.0, cap=10.0)
        assert b.try_spend()
        assert not b.try_spend()
        assert not b.try_spend()
        d = b.describe()
        assert d["spent_total"] == 1 and d["exhausted_total"] == 2

    def test_steady_state_ratio(self):
        # 100 requests at ratio 0.1 fund exactly ~10 retries
        b = RetryBudget(ratio=0.1, initial=0.0, cap=100.0)
        for _ in range(100):
            b.deposit()
        n = 0
        while b.try_spend():
            n += 1
        assert n in (9, 10)               # fp accumulation of 0.1s


# ---------------------------------------------------------------------------
# units: consistent-hash ring


class TestHashRing:
    def test_stable_and_deterministic(self):
        r1 = HashRing(["a", "b", "c"], replicas=32)
        r2 = HashRing(["a", "b", "c"], replicas=32)
        for k in ("k1", "k2", "user-42"):
            assert r1.owner(k, {"a", "b", "c"}) == \
                r2.owner(k, {"a", "b", "c"})

    def test_falls_through_to_next_eligible(self):
        ring = HashRing(["a", "b", "c"], replicas=32)
        keys = [f"key-{i}" for i in range(200)]
        owners = {k: ring.owner(k, {"a", "b", "c"}) for k in keys}
        # every backend owns some keys (64 vnodes spread well)
        assert set(owners.values()) == {"a", "b", "c"}
        # removing one backend moves ONLY its keys; others stay pinned
        for k in keys:
            o2 = ring.owner(k, {"a", "c"})
            if owners[k] != "b":
                assert o2 == owners[k]
            else:
                assert o2 in ("a", "c")

    def test_no_eligible_returns_none(self):
        ring = HashRing(["a"], replicas=4)
        assert ring.owner("k", set()) is None


# ---------------------------------------------------------------------------
# units: policy validation


class TestRouterPolicy:
    @pytest.mark.parametrize("kw", [
        {"probe_interval_s": 0.0},
        {"eject_consecutive_failures": 0},
        {"readmit_probes": 0},
        {"circuit_failure_rate": 1.5},
        {"retry_budget_ratio": -0.1},
        {"retry_budget_cap": 0.0},
        {"fleet_max_in_flight": 0},
        {"class_fractions": {"critical": 1.0}},
        {"class_fractions": {"critical": 1.0, "normal": 2.0,
                             "batch": 0.5}},
    ])
    def test_bad_knobs_rejected(self, kw):
        with pytest.raises(ValueError):
            RouterPolicy(**kw).validate()

    def test_circuit_policy_derivation(self):
        cp = RouterPolicy(reprobe_after_s=2.5,
                          readmit_probes=4).circuit_policy()
        assert cp.open_duration_s == 2.5 and cp.half_open_probes == 4


# ---------------------------------------------------------------------------
# units: backend health / ejection / drain state machines (fake clock)


class TestBackendStateMachine:
    def _backend(self, **kw):
        t = [0.0]
        policy = RouterPolicy(**kw).validate()
        b = Backend("b0", "http://127.0.0.1:9", 0, policy,
                    clock=lambda: t[0])
        return b, t

    def test_consecutive_failures_trip_ejection(self):
        b, t = self._backend(eject_consecutive_failures=3)
        assert b.routable
        for _ in range(2):
            b.note_result(False, None)
        assert b.routable                 # 2 < 3: still in
        b.note_result(True, None)         # success resets the streak
        for _ in range(2):
            b.note_result(False, None)
        assert b.routable
        b.note_result(False, None)        # 3rd consecutive: ejected
        assert not b.routable
        assert b.circuit.state == "open"

    def test_neutral_does_not_reset_streak(self):
        b, _ = self._backend(eject_consecutive_failures=3)
        b.note_result(False, None)
        b.note_result(False, None)
        b.note_neutral(None)              # a 503 answer: says nothing
        b.note_result(False, None)
        assert not b.routable

    def test_half_open_reprobe_readmits(self):
        b, t = self._backend(eject_consecutive_failures=2,
                             reprobe_after_s=5.0, readmit_probes=2)
        b.note_result(False, None)
        b.note_result(False, None)
        assert b.circuit.state == "open"
        t[0] = 5.1                        # holdoff elapsed: half-open
        assert b.circuit.state == "half_open"
        for _ in range(2):                # two healthy probes re-close
            allowed, _, token = b.circuit.allow()
            assert allowed
            b.note_result(True, token)
        assert b.routable

    def test_failed_probe_reopens_half_open(self):
        b, t = self._backend(eject_consecutive_failures=2,
                             reprobe_after_s=5.0)
        b.note_result(False, None)
        b.note_result(False, None)
        t[0] = 5.1
        allowed, _, token = b.circuit.allow()
        assert allowed
        b.note_result(False, token)       # probe failed: back to open
        assert b.circuit.state == "open"
        assert not b.routable

    def test_drain_state_machine(self):
        # real clock: wait_idle's deadline math must actually advance
        b = Backend("b0", "http://127.0.0.1:9", 0,
                    RouterPolicy().validate())
        b.begin()
        b.admin_state = ADMIN_DRAINING
        assert not b.routable             # no new sends while draining
        assert not b.wait_idle(0.05)      # in-flight holds the drain

        def finish():
            time.sleep(0.05)
            b.end()

        th = threading.Thread(target=finish)
        th.start()
        assert b.wait_idle(2.0)           # drains once in-flight ends
        th.join()
        b.admin_state = "active"
        assert b.routable


# ---------------------------------------------------------------------------
# in-process fleet integration


@pytest.fixture(scope="module")
def backend_servers():
    """3 in-process ModelServers (scale = 1/2/3 so responses identify
    their backend), shared by every router class in this module. NOTE
    the rolling-deploy test hot-swaps them to scales 11/12/13 — later
    tests must not assume the original values."""
    servers = [_mk_backend_server(float(i + 1)) for i in range(3)]
    yield [s for s, _ in servers], [r for _, r in servers]
    set_fault_injector(None)
    for s, _ in servers:
        s.stop(drain=False)


_FLEET_SCALES = (1.0, 2.0, 3.0, 11.0, 12.0, 13.0)  # pre/post deploy


@pytest.fixture(scope="class")
def fleet(backend_servers):
    """The shared servers behind one FleetRouter with a fast probe
    cadence. Torn down (prober stopped) before the next class runs —
    classes that arm one-shot fault plans rely on that, because a live
    prober shares (and consumes) the process-global injector."""
    servers, registries = backend_servers
    policy = RouterPolicy(probe_interval_s=0.1, probe_timeout_s=0.5,
                          reprobe_after_s=0.3)
    router = FleetRouter(
        [(f"b{i}", s.url) for i, s in enumerate(servers)],
        policy=policy).start()
    ns = type("Fleet", (), {})()
    ns.servers = servers
    ns.registries = registries
    ns.router = router
    ns.client = ServingClient(router.url, max_retries=2)
    ns.x = np.zeros((2, 4), np.float32)
    yield ns
    set_fault_injector(None)
    router.stop()


class TestFleetIntegration:
    def test_predict_routes_and_spreads(self, fleet):
        seen = set()
        for _ in range(12):
            out = fleet.client.predict("scale", fleet.x)
            seen.add(out["outputs"][0][0])
        assert seen <= {1.0, 2.0, 3.0} and len(seen) >= 2
        d = fleet.router.describe()
        served = [b["requests_total"] for b in d["backends"]]
        assert sum(served) >= 12 and sum(1 for n in served if n) >= 2

    def test_affinity_key_pins_one_backend(self, fleet):
        outs = {_raw_predict(fleet.router.url,
                             headers={"X-Routing-Key": "tenant-7"}
                             )["outputs"][0][0]
                for _ in range(8)}
        assert len(outs) == 1             # same key → same backend
        # different keys spread across the ring
        many = {_raw_predict(fleet.router.url,
                             headers={"X-Routing-Key": f"k{i}"}
                             )["outputs"][0][0]
                for i in range(24)}
        assert len(many) >= 2

    def test_injected_outage_ejects_and_readmits(self, fleet):
        target = 2
        inj = FaultInjector()
        inj.plan("router.backend_down", at=1, times=10**6,
                 arg=float(target))
        set_fault_injector(inj)
        t0 = time.monotonic()
        try:
            assert _wait(
                lambda: not fleet.router.backend(f"b{target}").routable,
                timeout_s=3.0)
            eject_s = time.monotonic() - t0
            assert eject_s < 2.0, f"ejection took {eject_s:.2f}s"
            # traffic keeps flowing around the hole
            for _ in range(6):
                fleet.client.predict("scale", fleet.x)
        finally:
            set_fault_injector(None)
        # outage lifted: half-open probes re-admit the backend
        assert _wait(
            lambda: fleet.router.backend(f"b{target}").routable,
            timeout_s=5.0)
        m = fleet.router.metrics
        assert m.ejections_total._data  # at least one ejection counted

    def test_fleet_priority_shed_protects_critical(self, fleet):
        servers_urls = [(f"b{i}", s.url)
                        for i, s in enumerate(fleet.servers)]
        policy = RouterPolicy(probe_interval_s=5.0,
                              fleet_max_in_flight=2)
        router = FleetRouter(servers_urls, policy=policy).start()
        inj = FaultInjector()
        # every backend predict sleeps, holding fleet slots open
        inj.plan("serving.latency", at=1, times=50, arg=0.4)
        set_fault_injector(inj)
        try:
            c = ServingClient(router.url)
            done = []

            def occupy():
                done.append(c.predict("scale", fleet.x,
                                      priority="normal"))

            threads = [threading.Thread(target=occupy)
                       for _ in range(2)]
            for t in threads:
                t.start()
            assert _wait(
                lambda: sum(
                    b.in_flight for b in router.backends) >= 2,
                timeout_s=2.0)
            # fleet full: batch sheds at the ROUTER (no backend paid),
            # critical borrows through
            with pytest.raises(QueueFullError) as ei:
                c.predict("scale", fleet.x, priority="batch")
            assert "fleet over capacity" in str(ei.value)
            out = c.predict("scale", fleet.x, priority="critical")
            assert out["outputs"][0][0] in (1.0, 2.0, 3.0)
            for t in threads:
                t.join()
            assert len(done) == 2         # occupants were never harmed
        finally:
            set_fault_injector(None)
            router.stop()

    def test_readyz_models_and_fleet_debug(self, fleet):
        with urllib.request.urlopen(fleet.router.url + "/readyz",
                                    timeout=10) as r:
            ready = json.loads(r.read())
        assert ready["ready"] and len(ready["routable"]) == 3
        with urllib.request.urlopen(fleet.router.url + "/models",
                                    timeout=10) as r:
            models = json.loads(r.read())
        assert models["models"][0]["name"] == "scale"
        d = _fleet_debug(fleet.router.url)
        assert {b["name"] for b in d["backends"]} == {"b0", "b1", "b2"}
        assert set(d["retry_budget"]) >= {"balance", "ratio",
                                          "spent_total",
                                          "exhausted_total"}
        assert d["fleet"]["routable"] == 3
        assert d["policy"]["eject_consecutive_failures"] == 3

    def test_metrics_federation(self, fleet):
        fleet.client.predict("scale", fleet.x)
        with urllib.request.urlopen(fleet.router.url + "/metrics",
                                    timeout=10) as r:
            text = r.read().decode()
        # the router's own families
        assert "router_requests_total" in text
        assert "router_retry_budget_balance" in text
        # backend series federated under worker labels
        assert re.search(
            r'serving_requests_total\{[^}]*worker="\d"', text)
        with urllib.request.urlopen(
                fleet.router.url + "/metrics?format=json",
                timeout=10) as r:
            doc = json.loads(r.read())
        names = {f["name"] for f in doc["metrics"]}
        assert "router_requests_total" in names
        assert "serving_requests_total" in names

    def test_fleet_requests_ledger_federation(self, fleet):
        fleet.client.predict("scale", fleet.x)
        with urllib.request.urlopen(
                fleet.router.url + "/debug/requests?limit=50",
                timeout=10) as r:
            doc = json.loads(r.read())
        assert doc["count"] >= 1
        assert all("backend" in rec for rec in doc["records"])
        with urllib.request.urlopen(
                fleet.router.url + "/debug/incidents", timeout=10) as r:
            inc = json.loads(r.read())
        assert "incidents" in inc

    def test_rolling_deploy_zero_failures(self, fleet):
        """The drain acceptance: a rolling deploy across the fleet
        under steady load completes with zero failed or dropped
        in-flight requests, and every backend serves the new version
        afterwards."""
        stop = threading.Event()
        failures, served = [], []
        lock = threading.Lock()

        def load():
            c = ServingClient(fleet.router.url)  # NO client retries:
            while not stop.is_set():             # the router alone
                try:                             # must absorb it all
                    out = c.predict("scale", fleet.x)
                    with lock:
                        served.append(out["outputs"][0][0])
                except Exception as e:  # noqa: BLE001 - test collects
                    with lock:
                        failures.append(e)
                time.sleep(0.005)

        threads = [threading.Thread(target=load) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.1)
        try:
            def deploy(name, url):
                idx = int(name[1:])
                fleet.registries[idx].deploy(
                    "scale", {"scale": float(idx + 1) + 10.0},
                    version="v2")

            report = fleet.router.rolling_deploy(
                deploy, drain_timeout_s=10.0, readmit_timeout_s=10.0)
        finally:
            time.sleep(0.1)
            stop.set()
            for t in threads:
                t.join()
        assert failures == []
        assert len(report) == 3
        assert all(s["drained"] and s["routable"] for s in report)
        # the whole fleet serves the new versions now
        post = {fleet.client.predict("scale", fleet.x)["outputs"][0][0]
                for _ in range(12)}
        assert post <= {11.0, 12.0, 13.0}
        # old-version responses were fine DURING the roll; failed ones
        # were not
        assert served and all(
            v in (1.0, 2.0, 3.0, 11.0, 12.0, 13.0) for v in served)


class TestRouterFailover:
    """Runs AFTER TestFleetIntegration (file order): these tests arm
    small one-shot ``router.backend_down`` plans on the process-global
    injector, and any still-running prober would consume the firings
    before the request path saw them — the class-scoped fleet fixture
    (live prober) must already be torn down, and the routers built
    here park their own probing."""

    def _router(self, backend_servers, **kw):
        servers, _ = backend_servers
        return FleetRouter(
            [(f"b{i}", s.url) for i, s in enumerate(servers)],
            policy=RouterPolicy(probe_interval_s=30.0, **kw)).start()

    def test_retry_elsewhere_on_connect_failure(self, backend_servers):
        router = self._router(backend_servers)
        inj = FaultInjector()
        inj.plan("router.backend_down", at=1, times=1, arg=-1.0)
        set_fault_injector(inj)
        try:
            c = ServingClient(router.url)   # NO client retries: the
            x = np.zeros((2, 4), np.float32)  # router alone absorbs
            out = c.predict("scale", x)
            assert out["outputs"][0][0] in _FLEET_SCALES
            assert router.budget.spent_total == 1
            # exactly one consumed firing: the failover retry skips an
            # exhausted plan instead of counting another trigger
            assert inj.triggers("router.backend_down") == 1
        finally:
            set_fault_injector(None)
            router.stop()

    def test_timeout_neither_ejects_nor_fails_over(self, backend_servers):
        """A slow backend is not a dead one: a request timeout passes
        through as the typed retryable failure WITHOUT burning a
        failover (the request may still be executing) and WITHOUT
        feeding the ejection streak (three slow requests must not
        eject a healthy backend and cascade its load)."""
        router = self._router(backend_servers, request_timeout_s=0.2)
        inj = FaultInjector()
        inj.plan("serving.latency", at=1, times=10, arg=0.6)
        set_fault_injector(inj)
        try:
            c = ServingClient(router.url)
            with pytest.raises(ConnectionFailedError) as ei:
                c.predict("scale", np.zeros((1, 4), np.float32))
            assert "timeout" in str(ei.value)
            assert router.budget.spent_total == 0
            assert all(b.consecutive_failures == 0
                       for b in router.backends)
            assert all(b.routable for b in router.backends)
        finally:
            set_fault_injector(None)
            router.stop()

    def test_rolling_deploy_aborts_on_failed_drain(self, backend_servers):
        """A drain that times out with requests still in flight must
        NOT deploy over them — the walk re-admits and stops."""
        router = self._router(backend_servers)
        deployed = []
        b0 = router.backend("b0")
        b0.begin()  # a stuck in-flight request the drain cannot clear
        try:
            report = router.rolling_deploy(
                lambda name, url: deployed.append(name),
                drain_timeout_s=0.1)
        finally:
            b0.end()
            router.stop()
        assert deployed == []             # deploy_fn never ran
        assert len(report) == 1
        assert not report[0]["drained"]
        assert "deploy skipped" in report[0]["error"]
        assert report[0]["routable"]      # re-admitted untouched

    def test_retry_budget_exhaustion_passes_failure_through(
            self, backend_servers):
        # a zero-ratio, zero-balance budget cannot fund any failover
        router = self._router(backend_servers,
                              retry_budget_ratio=0.0,
                              retry_budget_initial=0.0)
        inj = FaultInjector()
        inj.plan("router.backend_down", at=1, times=10**6, arg=-1.0)
        set_fault_injector(inj)
        try:
            c = ServingClient(router.url)
            with pytest.raises(ConnectionFailedError):
                c.predict("scale", np.zeros((2, 4), np.float32))
            assert router.budget.exhausted_total >= 1
        finally:
            set_fault_injector(None)
            router.stop()


# ---------------------------------------------------------------------------
# stream proxy (stub backends: the router is payload-agnostic transport)


class _StreamStub(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    tokens = (1, 2, 3)
    die_after = None        # int → abort the socket after N tokens

    def log_message(self, *a):  # noqa: N802 - stdlib API
        pass

    def do_GET(self):  # noqa: N802 - stdlib API
        body = b'{"ready": true}'
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _chunk(self, line: bytes):
        self.wfile.write(b"%X\r\n" % len(line) + line + b"\r\n")
        self.wfile.flush()

    def do_POST(self):  # noqa: N802 - stdlib API
        n = int(self.headers.get("Content-Length", 0))
        payload = json.loads(self.rfile.read(n)) if n else {}
        if payload.get("stream", True) is False:
            body = json.dumps({"tokens": list(self.tokens),
                               "n_tokens": len(self.tokens),
                               "finish_reason": "length"}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        for i, t in enumerate(self.tokens):
            if self.die_after is not None and i >= self.die_after:
                self.wfile.flush()
                self.connection.shutdown(socket.SHUT_RDWR)
                self.close_connection = True
                return
            self._chunk(json.dumps({"token": t}).encode() + b"\n")
        self._chunk(json.dumps({"done": True}).encode() + b"\n")
        self.wfile.write(b"0\r\n\r\n")


@pytest.fixture()
def stream_stub():
    _StreamStub.die_after = None
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _StreamStub)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()
    httpd.server_close()


class TestStreamProxy:
    def test_clean_stream_relays_verbatim(self, stream_stub):
        with FleetRouter([("s", stream_stub)],
                         policy=RouterPolicy(
                             probe_interval_s=30.0)) as router:
            c = ServingClient(router.url, timeout=10)
            assert list(c.generate("gpt", [1, 2])) == [1, 2, 3]

    def test_failover_before_first_token(self, stream_stub):
        dead = f"http://127.0.0.1:{_free_port()}"
        with FleetRouter([("dead", dead), ("live", stream_stub)],
                         policy=RouterPolicy(
                             probe_interval_s=30.0)) as router:
            # affinity pins nothing here; retry may be needed — run a
            # few to make sure the dead backend is hit at least once
            c = ServingClient(router.url, timeout=10)
            for _ in range(4):
                assert list(c.generate("gpt", [1])) == [1, 2, 3]
            assert router.metrics.retries_total._data  # failed over

    def test_midstream_death_is_typed_terminal(self, stream_stub):
        _StreamStub.die_after = 2
        with FleetRouter([("s", stream_stub)],
                         policy=RouterPolicy(
                             probe_interval_s=30.0)) as router:
            c = ServingClient(router.url, timeout=10)
            got = []
            with pytest.raises(ConnectionFailedError):
                for t in c.generate("gpt", [1]):
                    got.append(t)
            assert got == [1, 2]          # relayed tokens stand

    def test_direct_client_midstream_death_is_typed(self, stream_stub):
        """The satellite covers the DIRECT path too: with no router in
        front, the stdlib chunked reader swallows the IncompleteRead,
        so a silent clean-looking EOF without a terminal done/error
        event must still raise the typed retryable error."""
        _StreamStub.die_after = 2
        c = ServingClient(stream_stub, timeout=5)
        got = []
        with pytest.raises(ConnectionFailedError):
            for t in c.generate("gpt", [1]):
                got.append(t)
        assert got == [1, 2]

    def test_nonstream_generate_routes_like_predict(self, stream_stub):
        with FleetRouter([("s", stream_stub)],
                         policy=RouterPolicy(
                             probe_interval_s=30.0)) as router:
            c = ServingClient(router.url, timeout=10)
            out = c.generate_tokens("gpt", [1], max_new_tokens=3)
            assert out["tokens"] == [1, 2, 3]


# ---------------------------------------------------------------------------
# client transport-error typing (satellite)


class TestClientTransportErrors:
    def test_connection_refused_is_typed_retryable(self):
        c = ServingClient(f"http://127.0.0.1:{_free_port()}")
        with pytest.raises(ConnectionFailedError) as ei:
            c.predict("scale", [[0.0] * 4])
        assert ei.value.retryable

    def test_reset_then_retry_succeeds(self):
        """First connection is aborted before any response (reset);
        the client's retry loop must treat it as retryable and the
        second attempt lands."""
        body = json.dumps({"model": "scale", "version": "v1",
                           "outputs": [[1.0]]}).encode()
        response = (b"HTTP/1.1 200 OK\r\n"
                    b"Content-Type: application/json\r\n"
                    + b"Content-Length: %d\r\n\r\n" % len(body) + body)
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(4)
        port = srv.getsockname()[1]
        state = {"n": 0}

        def serve():
            while state["n"] < 2:
                conn, _ = srv.accept()
                state["n"] += 1
                if state["n"] == 1:
                    # abort: RST instead of a response
                    conn.setsockopt(socket.SOL_SOCKET,
                                    socket.SO_LINGER,
                                    b"\x01\x00\x00\x00\x00\x00\x00\x00")
                    conn.close()
                    continue
                conn.recv(65536)
                conn.sendall(response)
                conn.close()

        th = threading.Thread(target=serve, daemon=True)
        th.start()
        try:
            c = ServingClient(f"http://127.0.0.1:{port}", max_retries=2,
                              backoff_base_s=0.01, retry_seed=0)
            out = c.predict("scale", [[0.0] * 4])
            assert out["outputs"] == [[1.0]]
        finally:
            srv.close()
            th.join(timeout=5)

    def test_incomplete_read_is_typed(self):
        """A response truncated mid-body (Content-Length larger than
        what arrives before the close) raises the typed retryable
        error, not a raw http.client.IncompleteRead."""
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(2)
        port = srv.getsockname()[1]

        def serve():
            conn, _ = srv.accept()
            conn.recv(65536)
            conn.sendall(b"HTTP/1.1 200 OK\r\n"
                         b"Content-Type: application/json\r\n"
                         b"Content-Length: 1000\r\n\r\n{\"par")
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                            b"\x01\x00\x00\x00\x00\x00\x00\x00")
            conn.close()

        th = threading.Thread(target=serve, daemon=True)
        th.start()
        try:
            c = ServingClient(f"http://127.0.0.1:{port}")
            with pytest.raises(ConnectionFailedError):
                c.predict("scale", [[0.0] * 4])
        finally:
            srv.close()
            th.join(timeout=5)


# ---------------------------------------------------------------------------
# chaos acceptance: 3 subprocess backends, SIGKILL mid-load


_BACKEND_SCRIPT = textwrap.dedent("""
    import sys, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from deeplearning4j_tpu.serving import (ModelRegistry, ModelServer,
                                            spec)
    port, scale = int(sys.argv[1]), float(sys.argv[2])

    def fwd(v, x):
        return jnp.zeros((x.shape[0], 1), jnp.float32) + v["scale"]

    reg = ModelRegistry()
    reg.register("scale", fwd, {"scale": scale}, input_spec=spec((4,)),
                 version=sys.argv[3], mode="batched", max_batch_size=8)
    srv = ModelServer(reg, port=port, sentinel=False)
    srv.start(warm=True)
    print("READY", srv.port, flush=True)
    while True:
        time.sleep(3600)
""")


def _spawn_backend(port, scale, version="v1"):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", _BACKEND_SCRIPT, str(port), str(scale),
         version],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    return proc


def _await_ready(proc, timeout_s=60.0):
    line = ""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.startswith("READY"):
            return True
        if proc.poll() is not None:
            return False
    return False


@pytest.fixture(scope="class")
def chaos_fleet():
    """3 REAL subprocess backends (SIGKILL-able) behind one router.

    The router (and through it every Backend/CircuitBreaker/RetryBudget
    lock) is constructed with the lockorder sanitizer ARMED: the SIGKILL
    chaos path exercises the circuit->backend callback ordering that
    deadlocked in the PR 13 ABBA, so every run re-proves the fix —
    the test asserts zero sanitizer violations after the storm."""
    # MonkeyPatch.context: the armed env is restored on EVERY exit from
    # this block — teardown, skip, or an exception anywhere in setup —
    # so a failed fixture can't leak instrumented locks into the rest
    # of the session
    with pytest.MonkeyPatch.context() as mp:
        mp.setenv("DL4J_TPU_SANITIZERS", "lockorder")
        # generous long-hold threshold: a >1 s scheduler stall under a
        # held lock is not a defect on a loaded CI machine
        mp.setenv("DL4J_TPU_LOCKCHECK_HOLD_S", "30")
        lockcheck.reset()
        ports = [_free_port() for _ in range(3)]
        procs = [_spawn_backend(p, float(i + 1))
                 for i, p in enumerate(ports)]
        try:
            ok = all(_await_ready(p) for p in procs)
            if not ok:
                pytest.skip("subprocess backends failed to start")
            policy = RouterPolicy(probe_interval_s=0.25,
                                  probe_timeout_s=0.5,
                                  reprobe_after_s=0.5)
            router = FleetRouter(
                [(f"b{i}", f"http://127.0.0.1:{p}")
                 for i, p in enumerate(ports)], policy=policy).start()
            try:
                ns = type("ChaosFleet", (), {})()
                ns.ports = ports
                ns.procs = procs
                ns.router = router
                yield ns
            finally:
                router.stop()
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass


def _chaos_load(url, *, threads, per_thread, pause_s, barrier=None):
    """Closed-loop load; returns (served_values, failures)."""
    served, failures = [], []
    lock = threading.Lock()

    def run(tid):
        c = ServingClient(url, max_retries=3, backoff_base_s=0.02,
                          retry_seed=tid)
        x = np.zeros((1, 4), np.float32)
        if barrier is not None:
            barrier.wait()
        for _ in range(per_thread):
            try:
                out = c.predict("scale", x, deadline_ms=30000)
                with lock:
                    served.append(out["outputs"][0][0])
            except Exception as e:  # noqa: BLE001 - chaos collects all
                with lock:
                    failures.append(e)
            time.sleep(pause_s)

    ts = [threading.Thread(target=run, args=(i,)) for i in range(threads)]
    for t in ts:
        t.start()
    return ts, served, failures


class TestFleetChaos:
    def test_sigkill_mid_load_is_invisible_then_readmits(
            self, chaos_fleet):
        """THE acceptance: under steady load, SIGKILL one backend →
        zero client-visible failures for retryable traffic, the dead
        backend ejected < 2 s, re-admitted after restart."""
        router = chaos_fleet.router
        barrier = threading.Barrier(5)
        ts, served, failures = _chaos_load(
            router.url, threads=4, per_thread=30, pause_s=0.01,
            barrier=barrier)
        barrier.wait()
        time.sleep(0.25)                  # load is flowing
        victim = chaos_fleet.procs[1]
        victim.send_signal(signal.SIGKILL)
        t_kill = time.monotonic()
        victim.wait(timeout=10)
        assert _wait(lambda: not router.backend("b1").routable,
                     timeout_s=4.0, interval_s=0.01)
        eject_s = time.monotonic() - t_kill
        for t in ts:
            t.join()
        # zero client-visible failures: router failover + typed client
        # retries absorbed the SIGKILL completely
        assert failures == [], [repr(f) for f in failures[:3]]
        assert len(served) == 4 * 30
        assert eject_s < 2.0, f"ejection took {eject_s:.2f}s"
        # restart on the same port: the prober must re-admit it
        chaos_fleet.procs[1] = _spawn_backend(
            chaos_fleet.ports[1], 2.0, version="v2")
        assert _await_ready(chaos_fleet.procs[1])
        assert _wait(lambda: router.backend("b1").routable,
                     timeout_s=10.0)
        # and traffic reaches it again
        c = ServingClient(router.url, max_retries=2)
        x = np.zeros((1, 4), np.float32)
        seen = {c.predict("scale", x)["outputs"][0][0]
                for _ in range(18)}
        assert 2.0 in seen
        # the armed lockorder sanitizer watched the whole storm —
        # SIGKILL, ejection (circuit trip -> close_pool under the
        # breaker lock), drain waits, re-admission — and saw no
        # order inversion or long hold
        assert lockcheck.violations() == [], lockcheck.render_report()

    def test_fleet_debug_reflects_restart_history(self, chaos_fleet):
        d = _fleet_debug(chaos_fleet.router.url)
        b1 = next(b for b in d["backends"] if b["name"] == "b1")
        assert b1["routable"] and b1["circuit"] == "closed"
        m = chaos_fleet.router.metrics
        assert m.ejections_total._data and m.readmissions_total._data


@pytest.mark.slow
class TestFleetChaosHeavy:
    def test_10x_load_sigkill_and_rolling_restart(self):
        """Heavy variant: 10x the offered load of the tier-1 chaos
        test, one SIGKILL mid-stream, then a rolling kill+restart over
        every backend — still zero client-visible failures."""
        ports = [_free_port() for _ in range(3)]
        procs = [_spawn_backend(p, float(i + 1))
                 for i, p in enumerate(ports)]
        assert all(_await_ready(p) for p in procs)
        policy = RouterPolicy(probe_interval_s=0.25,
                              reprobe_after_s=0.5)
        router = FleetRouter(
            [(f"b{i}", f"http://127.0.0.1:{p}")
             for i, p in enumerate(ports)], policy=policy).start()
        try:
            barrier = threading.Barrier(17)
            ts, served, failures = _chaos_load(
                router.url, threads=16, per_thread=75, pause_s=0.005,
                barrier=barrier)
            barrier.wait()
            time.sleep(0.5)
            procs[0].send_signal(signal.SIGKILL)
            procs[0].wait(timeout=10)
            time.sleep(1.5)
            procs[0] = _spawn_backend(ports[0], 1.0, version="v2")
            assert _await_ready(procs[0])
            for t in ts:
                t.join()
            assert failures == [], [repr(f) for f in failures[:3]]
            assert len(served) == 16 * 75
        finally:
            router.stop()
            for p in procs:
                if p.poll() is None:
                    p.kill()
