"""Examples smoke tests (↔ dl4j-examples being the de-facto integration
suite of the reference). Each example runs --quick in a subprocess with
the CPU platform; the cheap ones run always, the full set behind
DL4J_TPU_EXAMPLE_TESTS=1 (they re-train small models, ~1-2 min each)."""

import os
import pathlib
import subprocess
import sys

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
# Tier-1 budget relief (the PR 6/7 pattern, paying for the PR 20
# autoscaler suite): the seq2seq example re-trains an attention
# encoder/decoder (~10 s), so it rides tier-2 with the other training
# examples; the subprocess smoke path stays wired every tier-1 run via
# the two cheap FAST rows.
FAST = ["samediff_graph.py", "word2vec_similarity.py",
        pytest.param("seq2seq_attention.py", marks=pytest.mark.slow)]
SLOW = ["mnist_lenet.py", "transfer_learning.py", "bert_mlm_pretrain.py",
        "char_rnn_generation.py", "gpt_char_lm.py", "bert_finetune_classifier.py",
        "rl_dqn_cartpole.py", "data_parallel_mesh.py",
        "long_context_ring.py", "serving_http.py",
        "hyperparameter_search.py", "import_keras_lstm_finetune.py"]


def _run(name, extra_env=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(extra_env or {}))
    out = subprocess.run(
        [sys.executable, str(ROOT / "examples" / name), "--quick"],
        capture_output=True, text=True, timeout=900, env=env)
    assert out.returncode == 0, f"{name} failed:\n{out.stdout[-2000:]}\n{out.stderr[-2000:]}"
    return out.stdout


@pytest.mark.parametrize("name", FAST)
def test_fast_examples(name):
    _run(name)


@pytest.mark.skipif(os.environ.get("DL4J_TPU_EXAMPLE_TESTS") != "1",
                    reason="set DL4J_TPU_EXAMPLE_TESTS=1 to run all examples")
@pytest.mark.parametrize("name", SLOW)
def test_slow_examples(name):
    extra = {}
    if name in ("data_parallel_mesh.py", "long_context_ring.py"):
        extra["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    _run(name, extra)
