"""train/profiling.py tests: the entry points the /debug/profile and
/debug/costs endpoints depend on, previously untested.

- ``analyze_trace`` device-lane filtering on a synthetic Chrome trace
  (host Python lanes must NOT dilute the device-op percentages) and the
  no-device-lane fallback (CPU backend);
- ``ProfilingListener`` on the CPU backend: a trace file is actually
  produced under the TensorBoard profile layout, ``report()`` returns
  the step-time stats;
- ``op_costs`` / ``arithmetic_intensity`` / ``normalize_cost_analysis``
  including the None-cost-analysis fallback.
"""

import gzip
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.train.profiling import (
    ProfilingListener,
    _find_trace_file,
    analyze_trace,
    arithmetic_intensity,
    compare_traces,
    normalize_cost_analysis,
    op_costs,
)

# ---------------------------------------------------------------------------
# synthetic Chrome traces


def _write_trace(path, events):
    with gzip.open(path, "wt") as fh:
        json.dump({"traceEvents": events}, fh)


def _mixed_lane_events():
    """pid 1 = device lane (XLA ops), pid 2 = host python lane."""
    return [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "process_name", "pid": 2,
         "args": {"name": "/host:CPU"}},
        {"ph": "X", "pid": 1, "tid": 1, "name": "fusion.1",
         "ts": 0, "dur": 300.0},
        {"ph": "X", "pid": 1, "tid": 1, "name": "fusion.1",
         "ts": 400, "dur": 100.0},
        {"ph": "X", "pid": 1, "tid": 1, "name": "copy.2",
         "ts": 600, "dur": 100.0},
        # host-side work, 10x the device time: must not appear
        {"ph": "X", "pid": 2, "tid": 9, "name": "python_dispatch",
         "ts": 0, "dur": 5000.0},
    ]


class TestAnalyzeTrace:
    def test_device_lane_filter(self, tmp_path):
        _write_trace(tmp_path / "a.trace.json.gz", _mixed_lane_events())
        rows = analyze_trace(str(tmp_path))
        names = {r["name"] for r in rows}
        assert "python_dispatch" not in names
        by_name = {r["name"]: r for r in rows}
        assert by_name["fusion.1"]["total_us"] == 400.0
        assert by_name["fusion.1"]["count"] == 2
        # pct computed against DEVICE time only (500 us), undiluted by
        # the 5000 us host lane
        assert by_name["fusion.1"]["pct"] == pytest.approx(80.0)
        assert by_name["copy.2"]["pct"] == pytest.approx(20.0)

    def test_fallback_without_device_lane(self, tmp_path):
        # CPU-backend-style capture: host lanes only
        events = [
            {"ph": "M", "name": "process_name", "pid": 2,
             "args": {"name": "/host:CPU"}},
            {"ph": "X", "pid": 2, "tid": 1, "name": "convolution",
             "ts": 0, "dur": 60.0},
            {"ph": "X", "pid": 2, "tid": 1, "name": "dot_general",
             "ts": 100, "dur": 40.0},
        ]
        _write_trace(tmp_path / "a.trace.json.gz", events)
        rows = analyze_trace(str(tmp_path))
        by_name = {r["name"]: r for r in rows}
        assert by_name["convolution"]["pct"] == pytest.approx(60.0)
        assert by_name["dot_general"]["pct"] == pytest.approx(40.0)

    def test_gpu_lane_matches(self, tmp_path):
        events = [
            {"ph": "M", "name": "process_name", "pid": 7,
             "args": {"name": "/device:GPU:0 (NVIDIA A100)"}},
            {"ph": "M", "name": "process_name", "pid": 2,
             "args": {"name": "python"}},
            {"ph": "X", "pid": 7, "tid": 1, "name": "gemm",
             "ts": 0, "dur": 10.0},
            {"ph": "X", "pid": 2, "tid": 1, "name": "host_stuff",
             "ts": 0, "dur": 90.0},
        ]
        _write_trace(tmp_path / "a.trace.json.gz", events)
        rows = analyze_trace(str(tmp_path))
        assert [r["name"] for r in rows] == ["gemm"]
        assert rows[0]["pct"] == pytest.approx(100.0)

    def test_compare_traces_delta(self, tmp_path):
        a, b = tmp_path / "a", tmp_path / "b"
        a.mkdir(), b.mkdir()
        _write_trace(a / "x.trace.json.gz", _mixed_lane_events())
        evs = _mixed_lane_events()
        evs[2]["dur"] = 900.0  # fusion.1 regressed
        _write_trace(b / "x.trace.json.gz", evs)
        rows = compare_traces(str(a), str(b))
        assert rows[0]["name"] == "fusion.1"
        assert rows[0]["delta_us"] == pytest.approx(600.0)

    def test_missing_trace_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            analyze_trace(str(tmp_path))


# ---------------------------------------------------------------------------
# ProfilingListener on the CPU backend


def _tiny_trainer():
    from deeplearning4j_tpu.nn.config import (
        NeuralNetConfiguration,
        SequentialConfig,
    )
    from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
    from deeplearning4j_tpu.nn.model import SequentialModel
    from deeplearning4j_tpu.train.trainer import Trainer

    model = SequentialModel(SequentialConfig(
        net=NeuralNetConfiguration(seed=0),
        layers=[Dense(units=8, activation="tanh"),
                OutputLayer(units=2, activation="softmax", loss="mcxent")],
        input_shape=(12,),
    ))
    return Trainer(model)


def _tiny_data(n=48, batch=8):
    from deeplearning4j_tpu.data import ArrayDataSetIterator

    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 12)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)]
    return ArrayDataSetIterator(x, y, batch_size=batch, shuffle=False)


class TestProfilingListener:
    def test_cpu_capture_produces_trace_and_report(self, tmp_path):
        log_dir = str(tmp_path / "profile")
        trainer = _tiny_trainer()
        lst = ProfilingListener(log_dir, start_step=2, end_step=4)
        trainer.fit(trainer.init_state(), _tiny_data(), epochs=1,
                    listeners=[lst])
        # a trace file landed under the TB profile plugin layout
        path = _find_trace_file(log_dir)
        assert os.path.getsize(path) > 0
        report = lst.report()
        # intervals are recorded only while the trace is active
        # (steps [start_step, end_step) => end - start samples)
        assert report["steps"] >= 1
        for key in ("mean_ms", "p50_ms", "min_ms", "max_ms"):
            assert report[key] >= 0.0
        assert report["min_ms"] <= report["p50_ms"] <= report["max_ms"]
        # the analyzer parses the real capture (host lanes on CPU: the
        # fallback path) and returns a non-empty breakdown
        rows = analyze_trace(log_dir)
        assert rows
        assert all(set(r) == {"name", "total_us", "count", "pct"}
                   for r in rows)

    def test_report_empty_before_steps(self, tmp_path):
        lst = ProfilingListener(str(tmp_path), start_step=2)
        assert lst.report() == {"steps": 0}


# ---------------------------------------------------------------------------
# op_costs / arithmetic_intensity / normalize_cost_analysis


class TestOpCosts:
    def test_cpu_backend_reports_flops(self):
        def fn(a, b):
            return jnp.tanh(a @ b).sum()

        a = jnp.ones((32, 64), jnp.float32)
        b = jnp.ones((64, 16), jnp.float32)
        costs = op_costs(fn, a, b)
        assert costs["flops"] > 0
        # matmul dominates: 2*M*N*K
        assert costs["flops"] >= 2 * 32 * 64 * 16
        assert all(isinstance(v, float) for v in costs.values())

    def test_train_step_costs(self):
        trainer = _tiny_trainer()
        ts = trainer.init_state()
        batch = {"features": np.zeros((8, 12), np.float32),
                 "labels": np.zeros((8, 2), np.float32)}
        costs = op_costs(trainer._raw_step, ts, batch)
        assert costs.get("flops", 0) > 0

    def test_arithmetic_intensity(self):
        assert arithmetic_intensity(
            {"flops": 100.0, "bytes accessed": 50.0}) == pytest.approx(2.0)
        # None when the backend omits byte traffic (some PJRT plugins)
        assert arithmetic_intensity({"flops": 100.0}) is None
        assert arithmetic_intensity({}) is None

    def test_normalize_cost_analysis_fallbacks(self):
        # None: backend implements no cost analysis
        assert normalize_cost_analysis(None) == {}
        # version-dependent 1-element list shape
        assert normalize_cost_analysis(
            [{"flops": 3, "label": "x"}]) == {"flops": 3.0}
        assert normalize_cost_analysis([]) == {}
        # plain dict: non-numeric values dropped, numerics floated
        out = normalize_cost_analysis({"flops": 7, "name": "prog"})
        assert out == {"flops": 7.0}

    def test_step_flops_background_analysis(self):
        """Trainer.step_flops fills its cache off-thread and the fit loop
        sets the analytic gauges (the /debug MFU story end to end)."""
        import time

        from deeplearning4j_tpu.observability import metrics as om

        om.reset_default_registry()
        om.set_enabled(True)
        try:
            trainer = _tiny_trainer()
            ts = trainer.init_state()
            batch = {"features": np.zeros((8, 12), np.float32),
                     "labels": np.zeros((8, 2), np.float32)}
            assert trainer.step_flops(ts, batch) is None  # kicked off
            deadline = time.monotonic() + 60
            flops = None
            while time.monotonic() < deadline and flops is None:
                time.sleep(0.05)
                flops = trainer.step_flops(ts, batch)
            assert flops and flops > 0
            # a fit now publishes the gauges from the cached analysis
            trainer.fit(ts, _tiny_data(), epochs=1)
            text = om.default_registry().render_text()
            assert "train_step_flops" in text
            assert "train_flops_per_second" in text
        finally:
            om.reset_default_registry()

    def test_step_flops_kill_switch(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_STEP_COST_ANALYSIS", "0")
        trainer = _tiny_trainer()
        ts = trainer.init_state()
        batch = {"features": np.zeros((8, 12), np.float32),
                 "labels": np.zeros((8, 2), np.float32)}
        assert trainer.step_flops(ts, batch) is None
        assert trainer._step_cost_cache == {}
