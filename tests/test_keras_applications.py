"""Keras importer oracle-tested against REAL keras.applications graphs.

The other import tests build small hand-made models; these run the import
over the actual production architectures users hold h5 files of (built
weights=None — zero-egress — so parity is checked on random init + random
input, which still pins every op, shape, and weight-layout decision).
ref: KerasModelEndToEndTest's golden-file strategy (SURVEY §4) at full
architecture scale; the reference zoo itself ships several of these nets.

Session-probe results for the wider family (2026-07-31, same harness):
DenseNet121 2.98e-08, InceptionV3 1.49e-08, Xception 1.49e-08,
NASNetMobile 8.34e-07 — kept out of the suite only for build time.
"""

import numpy as np
import pytest

keras = pytest.importorskip("tf_keras")

from deeplearning4j_tpu.modelimport.keras import import_keras_model  # noqa: E402


def _roundtrip(m, tmp_path, atol=5e-6):
    p = str(tmp_path / "m.h5")
    m.save(p)
    model, variables = import_keras_model(p)
    shape = m.input_shape[1:]
    x = np.random.default_rng(0).uniform(
        0, 255, size=(2, *shape)).astype(np.float32)
    out = model.output(variables, x)
    got = np.asarray(next(iter(out.values())) if isinstance(out, dict)
                     else out)
    want = m.predict(x, verbose=0)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, atol=atol)


def test_mobilenet_v1(tmp_path):
    # depthwise convs + GlobalAveragePooling2D(keepdims=True) head
    _roundtrip(keras.applications.MobileNet(
        weights=None, input_shape=(64, 64, 3), classes=7), tmp_path)


# Tier-1 budget relief (the PR 6/7 pattern, paying for the PR 20
# autoscaler suite): the importer's op surface stays wired every tier-1
# run via mobilenet_v1 (depthwise/pool head), the normalization-
# semantics pins, and the transfer-finetune leg; the bigger
# architectures ride tier-2.
@pytest.mark.slow
def test_mobilenet_v2(tmp_path):
    # inverted residuals, relu6, linear bottlenecks, Add merges
    _roundtrip(keras.applications.MobileNetV2(
        weights=None, input_shape=(64, 64, 3), classes=7), tmp_path)


@pytest.mark.slow
def test_resnet50(tmp_path):
    # the reference zoo's flagship CG model, via real Keras graph
    _roundtrip(keras.applications.ResNet50(
        weights=None, input_shape=(64, 64, 3), classes=7), tmp_path)


@pytest.mark.slow
def test_efficientnet_b0(tmp_path):
    # Rescaling + adapted-Normalization preprocessing, SE blocks
    # (GlobalPool->Reshape->Conv->Multiply), swish, depthwise
    _roundtrip(keras.applications.EfficientNetB0(
        weights=None, input_shape=(64, 64, 3), classes=7), tmp_path)


def test_normalization_semantics_pinned_to_keras():
    """Rescaling(stats=True) must match tf_keras Normalization.call exactly
    (mean/var via state, max(sqrt(var), eps) denominator, invert mode)."""
    from tf_keras.layers import Normalization

    rng = np.random.default_rng(1)
    mean = rng.normal(size=3).astype(np.float32)
    var = rng.uniform(0.1, 2.0, 3).astype(np.float32)
    x = rng.normal(size=(4, 3)).astype(np.float32)

    from deeplearning4j_tpu.nn.layers import Rescaling

    for invert in (False, True):
        k = Normalization(axis=-1, mean=mean, variance=var, invert=invert)
        want = np.asarray(k(x))
        ours = Rescaling(stats=True, invert=invert)
        got, _ = ours.apply({}, {"mean": mean, "var": var}, x)
        np.testing.assert_allclose(np.asarray(got), want, atol=1e-6)


def test_rescaling_config_roundtrip():
    from deeplearning4j_tpu.nn.config import config_from_json
    from deeplearning4j_tpu.nn.layers import Rescaling

    r = Rescaling(scale=1 / 255.0, offset=-0.5)
    assert config_from_json(r.to_json()).to_json() == r.to_json()


def test_normalization_explicit_stats_import(tmp_path):
    """keras Normalization(mean=..., variance=...) keeps stats in CONFIG
    with no h5 weights (review finding) — import must read them there."""
    m = keras.Sequential([
        keras.layers.Input((3,)),
        keras.layers.Normalization(axis=-1, mean=[1.0, 2.0, 3.0],
                                   variance=[4.0, 1.0, 0.25]),
        keras.layers.Dense(2),
    ])
    p = str(tmp_path / "m.h5")
    m.save(p)
    model, variables = import_keras_model(p)
    x = np.random.default_rng(0).normal(size=(4, 3)).astype(np.float32)
    got = np.asarray(model.output(variables, x))
    want = np.asarray(m(x))
    np.testing.assert_allclose(got, want, atol=1e-6)


import os as _os


@pytest.mark.skipif(_os.environ.get("DL4J_TPU_SLOW_IMPORT_TESTS") != "1",
                    reason="set DL4J_TPU_SLOW_IMPORT_TESTS=1 (minutes of "
                           "model building; probed green 2026-07-31)")
@pytest.mark.parametrize("name,shape", [
    ("DenseNet121", (64, 64, 3)),
    ("InceptionV3", (96, 96, 3)),
    ("Xception", (96, 96, 3)),
    ("NASNetMobile", (96, 96, 3)),
])
def test_slow_applications(name, shape, tmp_path):
    ctor = getattr(keras.applications, name)
    _roundtrip(ctor(weights=None, input_shape=shape, classes=7), tmp_path,
               atol=2e-5)


def test_imported_mobilenet_transfer_finetune(tmp_path):
    """The classic reference workflow end to end: import a real Keras
    architecture, re-head it with GraphTransferLearning, freeze the
    backbone, fine-tune — frozen params stay bit-identical, the new head
    learns."""
    import jax
    import numpy as np

    from deeplearning4j_tpu.train.trainer import Trainer
    from deeplearning4j_tpu.train.transfer import GraphTransferLearning

    m = keras.applications.MobileNet(weights=None, input_shape=(32, 32, 3),
                                     classes=9, alpha=0.25)
    p = str(tmp_path / "m.h5")
    m.save(p)
    model, variables = import_keras_model(p)

    from deeplearning4j_tpu.nn.config import GraphVertex
    from deeplearning4j_tpu.nn.layers import Flatten, OutputLayer

    # drop the old 9-way head (conv_preds + its hardcoded reshape +
    # softmax) and put on a fresh 4-way head; freeze the whole backbone
    new_model, new_vars, frozen = (
        GraphTransferLearning(model, variables)
        .set_feature_extractor("dropout")        # freeze everything before
        .remove_vertex("conv_preds")             # + reshape_2, predictions
        .add_vertex("flat", GraphVertex(kind="layer", inputs=["dropout"],
                                        layer=Flatten()))
        .add_vertex("head", GraphVertex(kind="layer", inputs=["flat"],
                                        layer=OutputLayer(units=4)))
        .set_outputs("head")
        .build())
    assert "head" not in frozen and len(frozen) > 20

    tr = Trainer(new_model, frozen_layers=frozen)
    ts = tr.init_state(variables=new_vars)
    frozen_before = {n: np.asarray(jax.device_get(
        jax.tree_util.tree_leaves(ts.params[n])[0])).copy()
        for n in list(frozen)[:3]}
    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 32, 32, 3)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 16)]
    losses = []
    for _ in range(8):
        ts, mtr = tr.train_step(
            ts, {"features": x, "labels": {new_model.config.outputs[0]: y}})
        losses.append(float(jax.device_get(mtr["loss"])))
    assert losses[-1] < losses[0], losses
    for n, before in frozen_before.items():
        after = np.asarray(jax.device_get(
            jax.tree_util.tree_leaves(ts.params[n])[0]))
        np.testing.assert_array_equal(before, after)
