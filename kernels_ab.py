"""On-chip Pallas-vs-XLA kernel A/B: compiled parity + speedup.

Run via ``python bench.py --kernels`` on a machine with a TPU attached.
Answers VERDICT r2 Weak #4: the Pallas kernels had only ever been
correctness-checked in interpret mode on CPU, and their claimed speed was a
hypothesis. This module compiles BOTH the Pallas kernels and their XLA
reference implementations on the real chip, checks numerical parity of
forward AND backward, and A/B-times them with the same
forced-host-materialization sync that bench.py uses (the axon tunnel's
``block_until_ready`` returns at dispatch — see bench.py docstring).

Emits one JSON dict (bench.py --kernels prints it); the round artifact is
committed as KERNELS_TPU_r{N}.json.
"""

from __future__ import annotations

import os
import time


def _sync_scalar(x):
    """Force completion: materialize a scalar data-dependent on x."""
    import jax

    return float(jax.device_get(x.ravel()[0] if x.ndim else x))


def _one_window(fn, args, iters):
    """One honestly-synced timing window: async dispatch, one in-window
    materialization that is data-dependent on every call."""
    t0 = time.perf_counter()
    outs = []
    for _ in range(iters):
        o = fn(*args)
        outs.append(o if not isinstance(o, tuple) else o[0])
    # One scalar per call: every dispatch must have completed.
    s = sum(o.ravel()[0] for o in outs)
    _sync_scalar(s)
    return (time.perf_counter() - t0) / iters * 1000  # ms


def _warm(fn, args, n=2):
    for _ in range(n):
        out = fn(*args)
        _sync_scalar(out if not isinstance(out, tuple) else out[0])


def _time_fn(fn, args, iters=30):
    """Best of 3 honestly-synced windows (single-sided).

    The axon relay pollutes a program's EARLY re-executions with deferred
    server-side work (measured 2026-07-30: ResNet chained step 353-535 ms
    on early executions vs 19-25 ms steady — BASELINE.md r4 note). That
    artifact is what produced r3/r4's flash-fwd "0.10x" readings: the
    Pallas side was timed on its polluted early executions while the XLA
    side ran later in the process. The defense is min-of-3 honestly-synced
    windows (a discard execution alone was measured NOT to absorb the
    pollution reliably); the two warmup calls just keep window 1 from
    paying first-touch costs.
    """
    _warm(fn, args)
    best = None
    for _ in range(3):
        dt = _one_window(fn, args, iters)
        best = dt if best is None else min(best, dt)
    return best


def _time_pair(fn_a, fn_b, args, iters=30, rounds=3):
    """Time two implementations of the same computation INTERLEAVED:
    A,B,A,B,... window by window, min per side.

    Sequential per-side timing (all A windows, then all B windows) lets
    slow relay drift — server-side load that varies over seconds — land
    entirely on one side and flip a speedup ratio (observed 2026-07-31: an
    A/B run concurrent with a CPU-saturating test suite read the LSTM fwd
    at 0.74x where quiet runs read ~1.1x). Alternating windows gives both
    sides the same exposure to drift; min-of-rounds still rejects the
    early-execution pollution.
    """
    _warm(fn_a, args)
    _warm(fn_b, args)
    best_a = best_b = None
    for _ in range(rounds):
        da = _one_window(fn_a, args, iters)
        db = _one_window(fn_b, args, iters)
        best_a = da if best_a is None else min(best_a, da)
        best_b = db if best_b is None else min(best_b, db)
    return best_a, best_b


def _max_rel_err(a, b):
    import jax
    import numpy as np

    a = np.asarray(jax.device_get(a), np.float32)
    b = np.asarray(jax.device_get(b), np.float32)
    denom = np.maximum(np.abs(b).max(), 1e-6)
    return float(np.abs(a - b).max() / denom)


def _flash_ab(iters=30, B=8, H=12, T=512, D=64, causal=False):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.kernels.flash_attention import (
        flash_attention,
        reference_attention,
    )

    r = np.random.default_rng(0)
    q = jnp.asarray(r.normal(size=(B, H, T, D)), jnp.float32)
    k = jnp.asarray(r.normal(size=(B, H, T, D)), jnp.float32)
    v = jnp.asarray(r.normal(size=(B, H, T, D)), jnp.float32)
    lens = r.integers(T // 2, T + 1, B)
    key_mask = jnp.asarray(
        (np.arange(T)[None, :] < lens[:, None]).astype(np.float32))

    out = {"shape": f"B{B} H{H} T{T} D{D}", "iters": iters}

    flash_f = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, key_mask=key_mask, causal=causal, backend="pallas"))
    ref_f = jax.jit(lambda q, k, v: reference_attention(
        q, k, v, key_mask=key_mask, causal=causal))

    of, orf = flash_f(q, k, v), ref_f(q, k, v)
    # Padded key rows of the reference produce uniform-attention outputs that
    # callers never read; compare only live queries (all queries are live —
    # key_mask masks keys, so outputs differ only via masked softmax: both
    # implement it, all rows comparable).
    out["fwd_max_rel_err"] = _max_rel_err(of, orf)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(
            q, k, v, key_mask=key_mask, causal=causal,
            backend="pallas") ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(
            q, k, v, key_mask=key_mask, causal=causal) ** 2)

    gflash = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))
    gref = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))
    gf, gr = gflash(q, k, v), gref(q, k, v)
    out["bwd_max_rel_err"] = max(_max_rel_err(a, b) for a, b in zip(gf, gr))

    fp, fx = _time_pair(flash_f, ref_f, (q, k, v), iters)
    out["fwd_ms"] = {"pallas": fp, "xla": fx}
    bp, bx = _time_pair(lambda *a: gflash(*a)[0], lambda *a: gref(*a)[0],
                        (q, k, v), iters)
    out["bwd_ms"] = {"pallas": bp, "xla": bx}
    out["fwd_speedup"] = round(out["fwd_ms"]["xla"] / out["fwd_ms"]["pallas"], 3)
    out["bwd_speedup"] = round(out["bwd_ms"]["xla"] / out["bwd_ms"]["pallas"], 3)
    out["parity"] = bool(out["fwd_max_rel_err"] < 2e-2
                         and out["bwd_max_rel_err"] < 2e-2)
    return out


def _lstm_ab(iters=30):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.kernels import lstm_scan
    from deeplearning4j_tpu.ops import rnn as opsrnn

    N, T, H, C = 32, 256, 256, 256
    r = np.random.default_rng(0)
    x = jnp.asarray(r.normal(size=(N, T, C)) * 0.1, jnp.float32)
    w_x = jnp.asarray(r.normal(size=(C, 4 * H)) * 0.05, jnp.float32)
    w_h = jnp.asarray(r.normal(size=(H, 4 * H)) * 0.05, jnp.float32)
    b = jnp.zeros((4 * H,), jnp.float32)
    peep = tuple(jnp.asarray(r.normal(size=(H,)) * 0.05, jnp.float32)
                 for _ in range(3))

    out = {"shape": f"N{N} T{T} H{H}", "iters": iters}

    pallas_f = jax.jit(lambda x: lstm_scan.lstm(x, w_x, w_h, b, peepholes=peep,
                                                forget_bias=1.0)[0])
    xla_f = jax.jit(lambda x: opsrnn.lstm(x, w_x, w_h, b, peepholes=peep,
                                          forget_bias=1.0)[0])
    op, ox = pallas_f(x), xla_f(x)
    out["fwd_max_rel_err"] = _max_rel_err(op, ox)

    gpallas = jax.jit(jax.grad(lambda x: jnp.sum(pallas_f(x) ** 2)))
    gxla = jax.jit(jax.grad(lambda x: jnp.sum(xla_f(x) ** 2)))
    gp, gx = gpallas(x), gxla(x)
    out["bwd_max_rel_err"] = _max_rel_err(gp, gx)

    fp, fx = _time_pair(pallas_f, xla_f, (x,), iters)
    out["fwd_ms"] = {"pallas": fp, "xla": fx}
    bp, bx = _time_pair(gpallas, gxla, (x,), iters)
    out["bwd_ms"] = {"pallas": bp, "xla": bx}
    out["fwd_speedup"] = round(out["fwd_ms"]["xla"] / out["fwd_ms"]["pallas"], 3)
    out["bwd_speedup"] = round(out["bwd_ms"]["xla"] / out["bwd_ms"]["pallas"], 3)
    out["parity"] = bool(out["fwd_max_rel_err"] < 2e-2
                         and out["bwd_max_rel_err"] < 2e-2)
    return out


def _gru_ab(iters=30):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.kernels import gru_scan
    from deeplearning4j_tpu.ops import rnn as opsrnn

    N, T, H, C = 32, 256, 256, 256
    r = np.random.default_rng(0)
    x = jnp.asarray(r.normal(size=(N, T, C)) * 0.1, jnp.float32)
    w_x = jnp.asarray(r.normal(size=(C, 3 * H)) * 0.05, jnp.float32)
    w_h = jnp.asarray(r.normal(size=(H, 3 * H)) * 0.05, jnp.float32)
    b = jnp.asarray(r.normal(size=(3 * H,)) * 0.05, jnp.float32)

    out = {"shape": f"N{N} T{T} H{H}", "iters": iters}

    pallas_f = jax.jit(lambda x: gru_scan.gru(x, w_x, w_h, b)[0])
    xla_f = jax.jit(lambda x: opsrnn.gru(x, w_x, w_h, b)[0])
    op, ox = pallas_f(x), xla_f(x)
    out["fwd_max_rel_err"] = _max_rel_err(op, ox)

    gpallas = jax.jit(jax.grad(lambda x: jnp.sum(pallas_f(x) ** 2)))
    gxla = jax.jit(jax.grad(lambda x: jnp.sum(xla_f(x) ** 2)))
    gp, gx = gpallas(x), gxla(x)
    out["bwd_max_rel_err"] = _max_rel_err(gp, gx)

    fp, fx = _time_pair(pallas_f, xla_f, (x,), iters)
    out["fwd_ms"] = {"pallas": fp, "xla": fx}
    bp, bx = _time_pair(gpallas, gxla, (x,), iters)
    out["bwd_ms"] = {"pallas": bp, "xla": bx}
    out["fwd_speedup"] = round(out["fwd_ms"]["xla"] / out["fwd_ms"]["pallas"], 3)
    out["bwd_speedup"] = round(out["bwd_ms"]["xla"] / out["bwd_ms"]["pallas"], 3)
    out["parity"] = bool(out["fwd_max_rel_err"] < 2e-2
                         and out["bwd_max_rel_err"] < 2e-2)
    return out


def _flash_tune(iters=8, B=8, H=12, T=512, D=64, causal=False):
    """On-chip block-size sweep for the flash kernel (VERDICT r3 #2).

    Times fwd+bwd at each (block_q, block_k) geometry and reports the best;
    the dispatch defaults (kernels/_dispatch.flash_block_sizes) can then be
    promoted via DL4J_TPU_FLASH_BLOCK_Q/K without a code change.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.kernels.flash_attention import flash_attention

    r = np.random.default_rng(0)
    q = jnp.asarray(r.normal(size=(B, H, T, D)), jnp.float32)
    k = jnp.asarray(r.normal(size=(B, H, T, D)), jnp.float32)
    v = jnp.asarray(r.normal(size=(B, H, T, D)), jnp.float32)

    geometries = [(128, 128), (128, 256), (256, 256), (256, 512),
                  (512, 512), (128, 512),
                  # r5: wider kv blocks for the T=1024 fwd gap (0.83x in
                  # r4) — bk=T collapses the sequential kv sweep to one
                  # iteration; score tile 512x1024 f32 = 2 MB, in VMEM
                  (256, 1024), (512, 1024), (1024, 1024)]
    out = {"shape": f"B{B} H{H} T{T} D{D} causal={causal}", "iters": iters,
           "sweep": {}}
    best = None
    for bq, bk in geometries:
        if bq > T or bk > T:
            continue
        key = f"q{bq}_k{bk}"
        try:
            f = jax.jit(lambda q, k, v, bq=bq, bk=bk: flash_attention(
                q, k, v, causal=causal, backend="pallas",
                block_q=bq, block_k=bk))
            g = jax.jit(jax.grad(
                lambda q, k, v, bq=bq, bk=bk: jnp.sum(flash_attention(
                    q, k, v, causal=causal, backend="pallas",
                    block_q=bq, block_k=bk) ** 2), argnums=(0, 1, 2)))
            fwd = _time_fn(f, (q, k, v), iters)
            bwd = _time_fn(lambda *a: g(*a)[0], (q, k, v), iters)
            out["sweep"][key] = {"fwd_ms": round(fwd, 3), "bwd_ms": round(bwd, 3)}
            if best is None or fwd + bwd < best[1]:
                best = (key, fwd + bwd)
        except Exception as e:  # noqa: BLE001 - record, keep sweeping
            out["sweep"][key] = {"error": str(e)[:160]}
    if best:
        out["best"] = best[0]
    return out


def run_kernels_ab(diag: dict, include_tune: bool = True,
                   canonical: bool = False) -> dict:
    import jax

    platform = jax.devices()[0].platform
    if platform not in ("tpu", "axon"):
        # Off-TPU an explicit backend='pallas' request silently falls back
        # to XLA (flash_attention hard constraint), so the "A/B" would
        # compare XLA against itself and record a fake parity artifact.
        return {"metric": "pallas_kernel_ab",
                "error": f"refusing to A/B on platform '{platform}': the "
                         "Pallas side would silently run XLA", **diag}
    result = {"metric": "pallas_kernel_ab", "platform": platform, **diag}
    # The long-context shape is where the flash kernel's O(T) memory is the
    # point (the T^2 score materialization of the XLA reference is ~1 GiB
    # here): record whether the dispatch policy's DL4J_TPU_FLASH_MIN_SEQ
    # crossover is justified.
    flash_long = lambda: _flash_ab(iters=10, B=2, H=8, T=4096, D=64,
                                   causal=True)
    # The auto-dispatch crossover (DL4J_TPU_FLASH_MIN_SEQ=1024): measure
    # the A/B exactly at the boundary shape so the policy is justified by
    # a recorded number rather than interpolation.
    flash_1024 = lambda: _flash_ab(iters=15, B=4, H=12, T=1024, D=64,
                                   causal=True)
    tune_long = lambda: _flash_tune(iters=6, B=2, H=8, T=2048, D=64,
                                    causal=True)
    tune_1024 = lambda: _flash_tune(iters=8, B=4, H=12, T=1024, D=64,
                                    causal=True)
    tune_legs = [("flash_tune_512", _flash_tune),
                 ("flash_tune_1024", tune_1024),
                 ("flash_tune_2048", tune_long)] if include_tune else []
    legs = ([("flash_attention", _flash_ab),
             ("flash_attention_1024", flash_1024),
             ("flash_attention_long", flash_long)]
            + tune_legs
            + [("lstm_scan", _lstm_ab), ("gru_scan", _gru_ab)])
    # Canonical-protocol provenance: the r4 pair of contradictory tables
    # traced to concurrent host load (see _time_pair docstring). Sample
    # the load average BEFORE and AFTER the legs — a quiet start instant
    # does not certify a minutes-long run — and mark the table canonical
    # only when both samples are quiet.
    try:
        load_before = os.getloadavg()
    except OSError:  # pragma: no cover
        load_before = None
    # Per-LEG load certification: each sample's own-CPU correction uses
    # only that leg's interval, so it tracks the 1-min loadavg EWMA far
    # better than a whole-run average (which would let early compile
    # bursts mask late foreign load, or a long quiet tail fail a clean
    # run). foreign ~ loadavg - own_cpu_share over the same interval.
    leg_loads = []
    certified = load_before is not None and load_before[0] < 2.0
    t_leg, cpu_leg = time.time(), sum(os.times()[:4])
    for name, fn in legs:
        try:
            result[name] = fn()
        except Exception as e:  # noqa: BLE001 - record, keep going
            result[name] = {"error": str(e)[:300]}
        try:
            la = os.getloadavg()[0]
        except OSError:  # pragma: no cover
            certified = False
            continue
        now, cpu_now = time.time(), sum(os.times()[:4])
        own = (cpu_now - cpu_leg) / max(now - t_leg, 1e-6)
        foreign = max(0.0, la - own)
        leg_loads.append({"leg": name, "load1": round(la, 2),
                          "own_cpu_util": round(own, 2),
                          "foreign_est": round(foreign, 2)})
        if foreign >= 2.0:
            certified = False
        t_leg, cpu_leg = now, cpu_now
    if load_before is not None:
        result["host_loadavg"] = {
            "before": [round(x, 2) for x in load_before],
            "per_leg": leg_loads}
        result["canonical"] = bool(canonical and certified)
    return result
