#!/usr/bin/env python
"""Benchmark driver — prints ONE JSON line with the headline metric.

Headline: BERT-base MLM pretraining throughput (tokens/sec/chip) on the
attached TPU chip — north-star workload #4. The reference publishes no
numbers (BASELINE.md: measured, not copied), so vs_baseline is the ratio
against the recorded round-2 measurement in BASELINE.md once it lands.

The axon TPU backend rides a shared tunnel that wedges transiently when
another PJRT client holds the claim; round 1 recorded 0.0 because a single
init failure aborted the run. Backend init therefore retries with backoff
for several minutes, and the emitted line carries diagnostics (platform,
device count, compile seconds) so a failure is attributable.
"""

import json
import subprocess
import sys
import time

# Recorded first real measurement (round 2). vs_baseline = value / this.
BASELINE_TOKENS_PER_SEC = None  # set after BENCH_r02 lands

_TPU_PLATFORMS = ("tpu", "axon")


def _probe_backend(timeout_s: float):
    """Probe backend init in a THROWAWAY subprocess.

    The axon tunnel's failure mode is a multi-minute hang inside the PJRT
    client claim (not an exception), and jax caches a partially-initialized
    backend set forever — so the probe must run out-of-process, where a
    hang becomes a kill-able timeout and a wedged claim dies with the
    process instead of poisoning this one.
    Returns (platform, n_devices) or raises.
    """
    out = subprocess.run(
        [sys.executable, "-c",
         "import jax; d = jax.devices(); print(d[0].platform, len(d))"],
        capture_output=True, text=True, timeout=timeout_s,
    )
    if out.returncode != 0:
        raise RuntimeError(out.stderr.strip().splitlines()[-1][:200]
                           if out.stderr.strip() else f"rc={out.returncode}")
    platform, n = out.stdout.split()[-2:]
    return platform, int(n)


def _init_backend(max_wait_s: float = 420.0):
    """Return (devices, diag), retrying transient tunnel wedges.

    Probes sparingly (the tunnel serializes grants; hammering it with
    rapid client creates makes the wedge worse) and only touches jax
    in-process once a probe subprocess has initialized cleanly.
    """
    deadline = time.monotonic() + max_wait_s
    delay = 30.0
    last_err = None
    attempt = 0
    while True:
        attempt += 1
        try:
            platform, _ = _probe_backend(timeout_s=120.0)
            if platform not in _TPU_PLATFORMS:
                raise RuntimeError(
                    f"backend came up as '{platform}', not a TPU — refusing "
                    "to record a CPU number as the per-chip metric"
                )
            break
        except (subprocess.TimeoutExpired, RuntimeError) as e:
            last_err = e
            if time.monotonic() + delay > deadline:
                raise RuntimeError(
                    f"backend init failed after {attempt} attempts: {last_err}"
                )
            time.sleep(delay)
            delay = min(delay * 2, 120.0)

    import jax

    devs = jax.devices()
    if devs[0].platform not in _TPU_PLATFORMS:
        raise RuntimeError(f"in-process backend is '{devs[0].platform}'")
    return devs, {
        "platform": devs[0].platform,
        "n_devices": len(devs),
        "init_attempts": attempt,
    }


def bench_bert(batch_size: int = 32, seq_len: int = 128, warmup: int = 3,
               iters: int = 10, diag: dict | None = None):
    import jax

    from deeplearning4j_tpu.models.bert import bert_base, make_mlm_batch
    from deeplearning4j_tpu.train.trainer import Trainer

    model = bert_base()
    trainer = Trainer(model)
    ts = trainer.init_state()
    batch = make_mlm_batch(0, batch_size=batch_size, seq_len=seq_len,
                           vocab_size=model.config.vocab_size)
    batch = jax.device_put(batch)

    t0 = time.perf_counter()
    ts, _ = trainer.train_step(ts, batch)  # first call compiles
    jax.block_until_ready(ts.params)
    if diag is not None:
        diag["compile_s"] = round(time.perf_counter() - t0, 1)

    for _ in range(warmup - 1):
        ts, metrics = trainer.train_step(ts, batch)
    jax.block_until_ready(ts.params)

    t0 = time.perf_counter()
    for _ in range(iters):
        ts, metrics = trainer.train_step(ts, batch)
    jax.block_until_ready(ts.params)
    dt = time.perf_counter() - t0

    if diag is not None:
        diag["step_ms"] = round(dt / iters * 1000, 1)
        diag["batch"] = batch_size
        diag["seq_len"] = seq_len
    return batch_size * seq_len * iters / dt


def main():
    diag = {}
    try:
        _, init_diag = _init_backend()
        diag.update(init_diag)
        value = bench_bert(diag=diag)
        vs = (round(value / BASELINE_TOKENS_PER_SEC, 3)
              if BASELINE_TOKENS_PER_SEC else 1.0)
        result = {
            "metric": "bert_base_mlm_train_tokens_per_sec_per_chip",
            "value": round(value, 1),
            "unit": "tokens/sec/chip",
            "vs_baseline": vs,
            **diag,
        }
    except Exception as e:  # noqa: BLE001 - bench must always emit one line
        result = {
            "metric": "bert_base_mlm_train_tokens_per_sec_per_chip",
            "value": 0.0,
            "unit": "tokens/sec/chip",
            "vs_baseline": 0.0,
            "error": str(e)[:300],
            **diag,
        }
    print(json.dumps(result))


if __name__ == "__main__":
    main()


