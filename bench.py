#!/usr/bin/env python
"""Benchmark driver — prints ONE JSON line with the headline metric.

Round-1 headline: LeNet-5 MNIST training throughput (samples/sec/chip) on
the attached TPU chip (benchmark config #1; BASELINE.md policy: measured,
not copied — the reference publishes no numbers, so vs_baseline is the
ratio against the recorded first measurement in BASELINE.md once it lands).
"""

import json
import sys
import time


def bench_lenet(batch_size: int = 256, warmup: int = 5, iters: int = 30):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.models.lenet import lenet
    from deeplearning4j_tpu.train.trainer import Trainer
    from deeplearning4j_tpu.train.updaters import Adam

    model = lenet(updater=Adam(1e-3))
    trainer = Trainer(model)
    ts = trainer.init_state()

    rng = np.random.default_rng(0)
    x = rng.normal(0.3, 0.25, (batch_size, 28, 28, 1)).astype(np.float32)
    y = np.zeros((batch_size, 10), np.float32)
    y[np.arange(batch_size), rng.integers(0, 10, batch_size)] = 1.0
    batch = {"features": jnp.asarray(x), "labels": jnp.asarray(y)}

    for _ in range(warmup):
        ts, metrics = trainer.train_step(ts, batch)
    jax.block_until_ready(ts.params)

    t0 = time.perf_counter()
    for _ in range(iters):
        ts, metrics = trainer.train_step(ts, batch)
    jax.block_until_ready(ts.params)
    dt = time.perf_counter() - t0

    samples_per_sec = batch_size * iters / dt
    return samples_per_sec


def main():
    try:
        value = bench_lenet()
        result = {
            "metric": "lenet_mnist_train_samples_per_sec_per_chip",
            "value": round(value, 1),
            "unit": "samples/sec/chip",
            "vs_baseline": 1.0,
        }
    except Exception as e:  # noqa: BLE001 - bench must always emit one line
        result = {
            "metric": "lenet_mnist_train_samples_per_sec_per_chip",
            "value": 0.0,
            "unit": "samples/sec/chip",
            "vs_baseline": 0.0,
            "error": str(e)[:200],
        }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
