#!/usr/bin/env python
"""Benchmark driver — prints ONE JSON line with the headline metric.

Headline: BERT-base MLM pretraining throughput (tokens/sec/chip) on the
attached TPU chip — north-star workload #4. The reference publishes no
numbers (BASELINE.md: measured, not copied), so vs_baseline is the ratio
against the first recorded measurement once BENCH_r1.json lands.
"""

import json
import time


def bench_bert(batch_size: int = 32, seq_len: int = 128, warmup: int = 3,
               iters: int = 10):
    import jax

    from deeplearning4j_tpu.models.bert import bert_base, make_mlm_batch
    from deeplearning4j_tpu.train.trainer import Trainer

    model = bert_base()
    trainer = Trainer(model)
    ts = trainer.init_state()
    batch = make_mlm_batch(0, batch_size=batch_size, seq_len=seq_len,
                           vocab_size=model.config.vocab_size)
    batch = jax.device_put(batch)

    for _ in range(warmup):
        ts, metrics = trainer.train_step(ts, batch)
    jax.block_until_ready(ts.params)

    t0 = time.perf_counter()
    for _ in range(iters):
        ts, metrics = trainer.train_step(ts, batch)
    jax.block_until_ready(ts.params)
    dt = time.perf_counter() - t0

    return batch_size * seq_len * iters / dt


def main():
    try:
        value = bench_bert()
        result = {
            "metric": "bert_base_mlm_train_tokens_per_sec_per_chip",
            "value": round(value, 1),
            "unit": "tokens/sec/chip",
            "vs_baseline": 1.0,
        }
    except Exception as e:  # noqa: BLE001 - bench must always emit one line
        result = {
            "metric": "bert_base_mlm_train_tokens_per_sec_per_chip",
            "value": 0.0,
            "unit": "tokens/sec/chip",
            "vs_baseline": 0.0,
            "error": str(e)[:200],
        }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
