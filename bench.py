#!/usr/bin/env python
"""Benchmark driver — prints ONE JSON line with the headline metric.

Headline: BERT-base MLM pretraining throughput (tokens/sec/chip) on the
attached TPU chip — north-star workload #4 — plus co-primary ResNet-50,
GravesLSTM char-RNN (Pallas scan path) and LeNet configs in the same line
(``configs`` field). BASELINE.md policy: the reference publishes no numbers,
so the baseline is measured-not-copied and later runs must not regress it.

Measurement integrity (round-3 hardening):

* **The axon tunnel's ``block_until_ready`` does NOT synchronize.** Measured
  this round: a chained 4096^3 bf16 matmul loop "timed" with
  ``block_until_ready`` reports 6264 TFLOP/s — 30x over the v5e's 197 TFLOP/s
  bf16 peak, i.e. the call returns at dispatch, not completion. That is what
  inflated round 2's 1.38M tokens/sec (0.9 PFLOP/s "sustained" on a chip that
  peaks at 0.197). Every timing window here therefore ends with a forced host
  materialization (``jax.device_get``) of values data-dependent on the last
  step, which cannot complete before the device work has.
* **MFU attribution.** Each config computes model FLOPs/step analytically
  (formulas inline below) and emits MFU against the chip's published bf16
  peak, looked up from ``device_kind``. An MFU > 1.0 is physically impossible
  and fails the run rather than recording a fantasy number.
* **Correctness gating.** Every timed window retains the per-step losses and
  asserts all are finite and that loss decreased over the window (each config
  re-fits one fixed batch, so decrease is guaranteed for a working step);
  a step that NaNs can no longer record a time.

The tunnel also serializes dispatches at ~69 ms round-trip latency but
pipelines async dispatches at ~1.4 ms/call, so steps are dispatched
asynchronously and synced once, inside the timing window.
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import time

# Ports the axon relay (the container's only path to the TPU) listens on
# locally. If none accepts a TCP connect, the relay process is dead and no
# amount of PJRT probing can reach the chip — fail fast instead of burning
# 3 x 300 s of probe subprocesses (VERDICT r3 Weak #5).
_RELAY_PORTS = (8082, 8083, 8087, 8092)


def enable_compile_cache():
    """Persistent XLA compilation cache under the repo root.

    Through the relay a cold compile costs 20-40 s per program and the full
    bench compiles ~15 programs (5 configs x warm/chain + 8 kernel A/B
    pairs) — wall-clock that can blow a driver timeout before a single
    timed window runs. The cache survives across processes, so an
    in-session warming run makes the driver's end-of-round invocation
    mostly cache hits. Backends whose PJRT plugin can't serialize
    executables simply never write entries — enabling is then a no-op, so
    this is safe on every platform.
    """
    import jax

    cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # noqa: BLE001 - older jax: cache flags absent
        pass

# Set per-config by main() under --profile: _timed_train wraps its timed
# window in jax.profiler.trace(_PROFILE_DIR).
_PROFILE_DIR = None

# Set by _cpu_evidence: the CPU integrity fallback wants the host-driven
# window (a chained-scan train step compiles for minutes on CPU, and the
# integrity record needs no dispatch-overhead-free timing anyway).
_FORCE_HOST_WINDOW = False

# Per-chip baselines (tokens|samples)/sec/chip. Round 2's recorded 1,382,357
# tok/s BERT figure was a sync artifact (block_until_ready returns at
# dispatch — see module docstring; the implied 0.9 PFLOP/s exceeds the v5e's
# 197 TFLOP/s peak by 4.5x, as the r2 judge computed) and is VOID, not a
# baseline. None = no honest measurement recorded yet: the first green
# driver run with this methodology becomes the baseline (update these from
# BENCH_r03.json's per-config values, per BASELINE.md policy).
# Measured 2026-07-30 on the live TPU v5 lite chip with the r4 methodology:
# on-device chained window; one compile+warmup execution, then THREE timed
# windows with the MIN recorded (the axon relay pollutes a program's early
# re-executions with deferred server-side work, see BASELINE.md r4 note);
# losses finite on every window AND decreasing on the first; MFU
# sanity-gated. See BASELINE.md's measured table and
# BENCH_insession_r04.json. Later runs must not regress these. The r3
# values (bert 44489 / resnet50 199.5 / lstm 194017 / lenet 6605) carried
# per-step tunnel-dispatch overhead and exec2 pollution inside the window;
# the jump to these numbers is a measurement correction documented in
# BASELINE.md, not a hardware speedup.
BASELINES = {
    # r4b config: gathered MLM head (P=20) + rbg dropout, mfu .475
    # (BASELINE.md r4b row; a 2026-07-31 full re-run read 168,610 = 0.985x)
    "bert": 171181.3,    # tokens/sec/chip, b32 x s128, bf16 mixed
    "resnet50": 1684.0,  # samples/sec/chip, b32 224x224, bf16 mixed (mfu .21)
    "lstm": 2724053.1,   # tokens/sec/chip, b32 x s256, GravesLSTM pallas
    "lenet": 263659.4,   # samples/sec/chip, b256 28x28
}

# Published dense bf16 peak FLOP/s per chip, keyed by device_kind substring
# (ordered: first match wins; more specific names first).
_PEAK_BF16 = [
    ("TPU7x", 2307e12),
    ("TPU v6 lite", 918e12),
    ("TPU v6", 918e12),
    ("TPU v5p", 459e12),
    ("TPU v5 lite", 197e12),   # v5e
    ("TPU v5", 459e12),
    ("TPU v4", 275e12),
    ("TPU v3", 123e12),
    ("TPU v2", 45e12),
]

_TPU_PLATFORMS = ("tpu", "axon")


def peak_bf16_flops(device_kind: str):
    for key, peak in _PEAK_BF16:
        if key.lower() in device_kind.lower():
            return peak
    return None


def _probe_backend(timeout_s: float):
    """Probe backend init in a THROWAWAY subprocess.

    The axon tunnel's failure mode is a multi-minute hang inside the PJRT
    client claim (not an exception), and jax caches a partially-initialized
    backend set forever — so the probe must run out-of-process, where a
    hang becomes a kill-able timeout and a wedged claim dies with the
    process instead of poisoning this one.
    Returns (platform, n_devices) or raises.
    """
    out = subprocess.run(
        [sys.executable, "-c",
         "import jax; d = jax.devices(); print(d[0].platform, len(d))"],
        capture_output=True, text=True, timeout=timeout_s,
    )
    if out.returncode != 0:
        raise RuntimeError(out.stderr.strip().splitlines()[-1][:200]
                           if out.stderr.strip() else f"rc={out.returncode}")
    platform, n = out.stdout.split()[-2:]
    return platform, int(n)


def _relay_alive(timeout_s: float = 1.0) -> bool:
    """True if the axon relay accepts a TCP connect on any of its ports."""
    for port in _RELAY_PORTS:
        try:
            socket.create_connection(("127.0.0.1", port), timeout_s).close()
            return True
        except OSError:
            continue
    return False


def _init_backend(max_wait_s: float = 900.0):
    """Return (devices, diag), retrying transient tunnel wedges.

    Patience over retry count: killing a probe mid-claim can strand a
    server-side claim that re-wedges the NEXT probe, so few long-timeout
    attempts beat many short ones. (A probe that NEVER succeeds can also
    mean the relay process carrying the tunnel died — observed r3 —
    which no amount of client-side retrying recovers.) A dead relay is
    detected up front by a TCP liveness probe and bounded at ONE short
    attempt, so the failure path costs ~2 min, not 15.
    """
    # Fail fast ONLY when this is recognizably the relay-tunneled container
    # (the relay script exists) and the relay isn't listening — then no
    # probe can ever succeed. On any other host (direct TPU VM, changed
    # ports) keep the full patient retry loop: a transient cold-init there
    # must not zero the perf record.
    relay_env = os.path.exists("/root/.relay.py")
    if relay_env and not _relay_alive():
        try:
            platform, _ = _probe_backend(timeout_s=120.0)
            if platform not in _TPU_PLATFORMS:
                raise RuntimeError(f"backend came up as '{platform}'")
        except (subprocess.TimeoutExpired, RuntimeError) as e:
            raise RuntimeError(
                "TPU unreachable: relay process dead (not listening on any "
                f"of {_RELAY_PORTS}) and a single 120s probe failed ({e})"
            ) from e
    else:
        deadline = time.monotonic() + max_wait_s
        delay = 30.0
        last_err = None
        attempt = 0
        while True:
            attempt += 1
            try:
                platform, _ = _probe_backend(timeout_s=300.0)
                if platform not in _TPU_PLATFORMS:
                    raise RuntimeError(
                        f"backend came up as '{platform}', not a TPU — "
                        "refusing to record a CPU number as the per-chip "
                        "metric"
                    )
                break
            except (subprocess.TimeoutExpired, RuntimeError) as e:
                last_err = e
                if time.monotonic() + delay > deadline:
                    raise RuntimeError(
                        f"backend init failed after {attempt} attempts: "
                        f"{last_err}"
                    )
                time.sleep(delay)
                delay = min(delay * 2, 120.0)

    import jax

    devs = jax.devices()
    if devs[0].platform not in _TPU_PLATFORMS:
        raise RuntimeError(f"in-process backend is '{devs[0].platform}'")
    kind = devs[0].device_kind
    return devs, {
        "platform": devs[0].platform,
        "device_kind": kind,
        "peak_bf16_tflops": (peak_bf16_flops(kind) or 0) / 1e12 or None,
        "n_devices": len(devs),
        "init_attempts": attempt,
    }


# --------------------------------------------------------------------------
# Timing core
# --------------------------------------------------------------------------

def _gate_and_record(host_losses, dt, iters, *, flops_per_step,
                     units_per_step, peak_flops, info):
    """Shared integrity gates: finite + decreasing losses, MFU sanity."""
    import numpy as np

    host_losses = [float(x) for x in host_losses]
    if not all(np.isfinite(l) for l in host_losses):
        raise RuntimeError(f"non-finite loss in timed window: {host_losses}")
    k = max(1, iters // 4)
    decreasing = float(np.mean(host_losses[-k:])) < float(np.mean(host_losses[:k]))
    # Fixed-batch refits converge: a loss that has already collapsed to ~0
    # by the timed window is trained, not broken — only a FLAT NON-SMALL
    # loss means the step isn't training.
    converged = float(np.mean(host_losses[-k:])) < 1e-2
    step_s = dt / iters
    info.update({
        "step_ms": round(step_s * 1000, 3),
        "iters": iters,
        "loss_first": round(host_losses[0], 4),
        "loss_last": round(host_losses[-1], 4),
        "decreasing": bool(decreasing),
        "flops_per_step": flops_per_step,
    })
    if converged and not decreasing:
        info["converged"] = True
    if peak_flops:
        mfu = flops_per_step / step_s / peak_flops
        info["mfu"] = round(mfu, 4)
        if mfu > 1.0:
            raise RuntimeError(
                f"MFU {mfu:.2f} > 1.0 — measurement artifact (sync failure?)"
            )
    if not decreasing and not converged:
        # Hard failure, not a warning: every config re-fits one fixed batch,
        # so a working step MUST reduce the loss across the window — a flat
        # loss means the step isn't training and its time is meaningless.
        raise RuntimeError(
            f"loss did not decrease over timed window "
            f"({host_losses[0]:.4f} -> {host_losses[-1]:.4f})")
    return units_per_step / step_s


def _timed_train(trainer, ts, batch, *, warmup: int, iters: int,
                 flops_per_step: float, units_per_step: float,
                 peak_flops, info: dict):
    """Time `iters` train steps ON-DEVICE with forced-materialization sync.

    The timed window is ONE jitted ``lax.scan`` chain of `iters` steps
    (Trainer.make_chained_step): the device iterates without host round
    trips, so the number measures the chip, not the ~35-45 ms/dispatch
    axon-tunnel cost that dominated small-model rows in r3 (BASELINE.md
    overhead note; VERDICT r3 next-round #4b). The window still closes with
    a device_get of the per-step loss vector AND a final-params element —
    both data-dependent on every step, so the clock cannot stop early. One
    tunnel round-trip (~69 ms) remains in the window; amortized over the
    window it is <5% for every config's iters.

    Falls back to the r3 host-driven loop if the chained program fails to
    build (info["window"] records which path ran).
    """
    import jax
    import numpy as np

    if _FORCE_HOST_WINDOW:
        info["window"] = "host-driven (integrity mode)"
        return _timed_train_host(
            trainer, ts, batch, warmup=warmup, iters=iters,
            flops_per_step=flops_per_step, units_per_step=units_per_step,
            peak_flops=peak_flops, info=info)

    try:
        chained = trainer.make_chained_step(iters)
        t0 = time.perf_counter()
        ts, losses = chained(ts, batch)  # compile + warmup window
        warm = np.asarray(jax.device_get(losses))
        info["compile_s"] = round(time.perf_counter() - t0, 1)
        if not np.isfinite(warm).all():
            raise RuntimeError(f"non-finite loss in warmup window: {warm[:8]}")

        import contextlib

        # Min-of-3 windows: the axon relay pollutes a program's EARLY
        # re-executions with deferred server-side work — measured 2026-07-30,
        # the first timed window after the compile run read 4-28x slow for
        # every config (e.g. ResNet-50 b32 534.7 ms/step vs 19.0 steady;
        # window_ms_all in the emitted JSON records all three), and a
        # dedicated discard execution did NOT reliably absorb it. Each
        # window is honestly synced (device_get of the loss vector + a
        # final-params element, both data-dependent on every step), so min
        # discards transient relay noise, not device work. Finiteness is
        # gated on EVERY window; the decrease gate runs on window 1's
        # losses (the earliest, least-converged window). The profiler, when
        # requested, wraps ONLY the last window — the one least likely to
        # carry relay pollution — so the top-op attribution describes model
        # ops, not relay artifacts.
        # Cheap windows buy noise immunity: configs whose whole window is
        # sub-second (lenet/lstm) get 6 windows instead of 3 — observed
        # 2026-07-31, chip-side throughput varies run-to-run well beyond
        # the ±5% the min-of-3 absorbs on the shortest windows.
        dts, host_losses = [], None
        n_windows = 3
        w = 0
        while w < n_windows:
            prof = (jax.profiler.trace(_PROFILE_DIR)
                    if _PROFILE_DIR and w == n_windows - 1
                    else contextlib.nullcontext())
            with prof:
                t0 = time.perf_counter()
                ts, losses = chained(ts, batch)
                got = np.asarray(jax.device_get(losses))
                last_leaf = jax.tree_util.tree_leaves(ts.params)[0]
                float(jax.device_get(last_leaf.ravel()[0]))
                dts.append(time.perf_counter() - t0)
            if not np.isfinite(got).all():
                raise RuntimeError(
                    f"non-finite loss in timed window: {got[:8]}")
            if host_losses is None:
                host_losses = list(got)
                if dts[0] < 1.0:
                    n_windows = 6
            w += 1
        dt = min(dts)
        info["window_ms_all"] = [round(d / iters * 1000, 3) for d in dts]
        info["window"] = "on-device-chained"
    except Exception as e:  # noqa: BLE001 - fall back to host-driven timing
        if isinstance(e, RuntimeError) and "non-finite" in str(e):
            raise
        info["window"] = f"host-driven (chained failed: {str(e)[:120]})"
        # A runtime failure mid-window happens AFTER ts's buffers were
        # donated to the chained program — rebuild the state before the
        # host-driven rescue path touches it.
        ts = trainer.init_state()
        return _timed_train_host(
            trainer, ts, batch, warmup=warmup, iters=iters,
            flops_per_step=flops_per_step, units_per_step=units_per_step,
            peak_flops=peak_flops, info=info)

    return _gate_and_record(
        host_losses, dt, iters, flops_per_step=flops_per_step,
        units_per_step=units_per_step, peak_flops=peak_flops, info=info)


def _timed_train_host(trainer, ts, batch, *, warmup: int, iters: int,
                      flops_per_step: float, units_per_step: float,
                      peak_flops, info: dict):
    """r3 host-driven timing loop (one dispatch per step, async, one sync)."""
    import jax
    import numpy as np

    t0 = time.perf_counter()
    ts, m = trainer.train_step(ts, batch)
    first = float(jax.device_get(m["total_loss"]))
    info.setdefault("compile_s", round(time.perf_counter() - t0, 1))
    if not np.isfinite(first):
        raise RuntimeError(f"non-finite loss at step 1: {first}")

    for _ in range(warmup):
        ts, m = trainer.train_step(ts, batch)
    float(jax.device_get(m["total_loss"]))  # sync before opening the window

    import jax.numpy as jnp

    losses = []
    t0 = time.perf_counter()
    for _ in range(iters):
        ts, m = trainer.train_step(ts, batch)
        losses.append(m["total_loss"])
    # Stack on device first: ONE tunnel round-trip for the whole loss
    # vector (a python-list get fetches each tiny buffer separately),
    # still data-dependent on every step.
    host_losses = [float(x) for x in jax.device_get(jnp.stack(losses))]
    # Force the last param update too (loss i depends only on params i-1).
    last_leaf = jax.tree_util.tree_leaves(ts.params)[0]
    float(jax.device_get(last_leaf.ravel()[0]))
    dt = time.perf_counter() - t0

    return _gate_and_record(
        host_losses, dt, iters, flops_per_step=flops_per_step,
        units_per_step=units_per_step, peak_flops=peak_flops, info=info)


# --------------------------------------------------------------------------
# Analytic FLOPs (train step ~= 3x forward for matmul-dominated models)
# --------------------------------------------------------------------------

def bert_train_flops(batch, seq, cfg, max_predictions=None) -> float:
    """Matmul FLOPs for one BERT MLM+NSP train step.

    fwd = L*(8*B*T*H^2 [QKV+O] + 4*B*T^2*H [QK^T + AV] + 4*B*T*H*I [FFN])
          + 2*B*P*H^2 [MLM transform] + 2*B*P*H*V [tied decoder]; bwd = 2x.
    P = max_predictions when the gathered MLM head is used (the decoder GEMM
    runs over the P masked slots only), else the full T — the MFU
    denominator counts the FLOPs the model actually issues.
    """
    b, t = batch, seq
    p = t if max_predictions is None else max_predictions
    h, i, l, v = cfg.hidden, cfg.intermediate, cfg.num_layers, cfg.vocab_size
    fwd = l * (8 * b * t * h * h + 4 * b * t * t * h + 4 * b * t * h * i)
    fwd += 2 * b * p * h * h + 2 * b * p * h * v
    return 3.0 * fwd


def gpt_train_flops(batch, seq, cfg) -> float:
    """Matmul FLOPs for one GPT causal-LM train step.

    Same encoder arithmetic as BERT (the attention score/AV GEMMs are
    issued dense, causality is a mask) plus the tied LM head over ALL T
    positions: 2*B*T*H*V. train = 3x fwd.
    """
    b, t = batch, seq
    h, i, l, v = cfg.hidden, cfg.intermediate, cfg.num_layers, cfg.vocab_size
    fwd = l * (8 * b * t * h * h + 4 * b * t * t * h + 4 * b * t * h * i)
    fwd += 2 * b * t * h * v
    return 3.0 * fwd


def lstm_train_flops(batch, seq, hidden, vocab, layers=2) -> float:
    """GravesLSTM char-RNN: per step per layer the cell does the fused gate
    GEMM 2*(4H*(H+in)) MACs; head is 2*B*T*H*V. FLOPs = 2*MACs; train = 3x fwd.
    """
    b, t, h, v = batch, seq, hidden, vocab
    fwd = 0.0
    inp = v
    for _ in range(layers):
        fwd += b * t * 2 * (4 * h * (h + inp))
        inp = h
    fwd += 2 * b * t * h * v
    return 3.0 * fwd


# ResNet-50 224x224 forward = 4.09e9 MACs (standard torchvision count of the
# conv/fc MACs for the v1.5 graph); FLOPs = 2*MACs, train = 3x forward.
RESNET50_TRAIN_FLOPS_PER_SAMPLE = 3.0 * 2.0 * 4.09e9

# LeNet (our models/lenet.py geometry: SAME-padded convs, 28x28): conv1
# 5x5x1x20 @ 28^2 (0.39e6) + conv2 5x5x20x50 @ 14^2 (4.90e6) + fc 2450x500
# (1.23e6) + fc 500x10 ~= 6.52e6 MACs fwd.
LENET_TRAIN_FLOPS_PER_SAMPLE = 3.0 * 2.0 * 6.52e6


# --------------------------------------------------------------------------
# Configs
# --------------------------------------------------------------------------

def bench_bert(peak, *, batch_size=32, seq_len=128, warmup=4, iters=30,
               max_predictions=20):
    """max_predictions=20 selects the gathered MLM head (decoder GEMM over
    the 20 masked slots, ~15% of T=128, the standard BERT pretraining data
    layout); None falls back to the dense [N,T,V] head."""
    import jax

    from deeplearning4j_tpu.models.bert import bert_base, make_mlm_batch
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.train.trainer import Trainer
    from deeplearning4j_tpu.train.updaters import Adam

    # rng_impl="rbg": hardware RngBitGenerator for the dropout masks —
    # threefry cost BERT-base ~12 ms of a 34 ms step (~150M random
    # bits/step); see NeuralNetConfiguration.rng_impl.
    model = bert_base(
        max_position=max(512, seq_len),
        net=NeuralNetConfiguration(
            updater=Adam(1e-4), mixed_precision=True, rng_impl="rbg"))
    trainer = Trainer(model)
    ts = trainer.init_state()
    batch = jax.device_put(make_mlm_batch(
        0, batch_size=batch_size, seq_len=seq_len,
        vocab_size=model.config.vocab_size,
        max_predictions=max_predictions))

    info = {"batch": batch_size, "seq_len": seq_len, "dtype": "bf16-mixed",
            "mlm_head": ("dense" if max_predictions is None
                         else f"gathered(P={max_predictions})"),
            "unit": "tokens/sec/chip"}
    value = _timed_train(
        trainer, ts, batch, warmup=warmup, iters=iters,
        flops_per_step=bert_train_flops(batch_size, seq_len, model.config,
                                        max_predictions),
        units_per_step=batch_size * seq_len, peak_flops=peak, info=info)
    info["value"] = round(value, 1)
    return info


def bench_gpt(peak, *, batch_size=8, seq_len=512, warmup=3, iters=15,
              tiny=False):
    """GPT-2-small causal-LM pretraining step (models/gpt.py): the
    decoder-only counterpart of the BERT row. Next-token CE over all
    positions; bf16 mixed; hardware-RNG dropout (same rationale as BERT).
    ``tiny`` swaps in 2L/128H dims — the CPU config-integrity leg only
    (a 12-layer CPU compile costs minutes the dead-relay path can't
    afford; loss-decrease evidence doesn't need GPT-2-small dims)."""
    import jax
    import numpy as np

    from deeplearning4j_tpu.models.gpt import Gpt, GptConfig
    from deeplearning4j_tpu.nn.config import NeuralNetConfiguration
    from deeplearning4j_tpu.train.trainer import Trainer
    from deeplearning4j_tpu.train.updaters import Adam

    dims = (dict(hidden=128, num_layers=2, num_heads=2, intermediate=256,
                 vocab_size=1000) if tiny else {})
    model = Gpt(GptConfig(
        max_position=max(512, seq_len),
        net=NeuralNetConfiguration(
            updater=Adam(1e-4), mixed_precision=True, rng_impl="rbg"),
        **dims))
    trainer = Trainer(model)
    ts = trainer.init_state()
    r = np.random.default_rng(0)
    ids = r.integers(0, model.config.vocab_size,
                     (batch_size, seq_len)).astype(np.int32)
    batch = jax.device_put({"features": {"token_ids": ids}})

    info = {"batch": batch_size, "seq_len": seq_len, "dtype": "bf16-mixed",
            "unit": "tokens/sec/chip"}
    value = _timed_train(
        trainer, ts, batch, warmup=warmup, iters=iters,
        flops_per_step=gpt_train_flops(batch_size, seq_len, model.config),
        units_per_step=batch_size * seq_len, peak_flops=peak, info=info)
    info["value"] = round(value, 1)
    return info


def bench_resnet50(peak, *, batch_size=32, warmup=3, iters=20):
    import jax
    import numpy as np

    from deeplearning4j_tpu.models.zoo import resnet50
    from deeplearning4j_tpu.train.trainer import Trainer
    from deeplearning4j_tpu.train.updaters import Adam

    model = resnet50(num_classes=1000, updater=Adam(1e-3))
    model.net.mixed_precision = True
    trainer = Trainer(model)
    ts = trainer.init_state()
    r = np.random.default_rng(0)
    labels = np.eye(1000, dtype=np.float32)[r.integers(0, 1000, batch_size)]
    batch = jax.device_put({
        "features": r.normal(size=(batch_size, 224, 224, 3)).astype(np.float32),
        "labels": labels,
    })

    info = {"batch": batch_size, "image": 224, "dtype": "bf16-mixed",
            "unit": "samples/sec/chip"}
    value = _timed_train(
        trainer, ts, batch, warmup=warmup, iters=iters,
        flops_per_step=RESNET50_TRAIN_FLOPS_PER_SAMPLE * batch_size,
        units_per_step=batch_size, peak_flops=peak, info=info)
    info["value"] = round(value, 1)
    return info


def bench_lstm(peak, *, batch_size=32, seq_len=256, hidden=256, vocab=77,
               warmup=4, iters=60):
    import jax
    import numpy as np

    from deeplearning4j_tpu.models.zoo.classic import text_generation_lstm
    from deeplearning4j_tpu.train.trainer import Trainer
    from deeplearning4j_tpu.train.updaters import Adam

    model = text_generation_lstm(
        vocab_size=vocab, hidden=hidden, seq_len=seq_len,
        updater=Adam(1e-3), backend="pallas")
    trainer = Trainer(model)
    ts = trainer.init_state()
    r = np.random.default_rng(0)
    ids = r.integers(0, vocab, (batch_size, seq_len + 1))
    eye = np.eye(vocab, dtype=np.float32)
    batch = jax.device_put({
        "features": eye[ids[:, :-1]], "labels": eye[ids[:, 1:]]})

    info = {"batch": batch_size, "seq_len": seq_len, "hidden": hidden,
            "kernel": "pallas", "unit": "tokens/sec/chip"}
    value = _timed_train(
        trainer, ts, batch, warmup=warmup, iters=iters,
        flops_per_step=lstm_train_flops(batch_size, seq_len, hidden, vocab),
        units_per_step=batch_size * seq_len, peak_flops=peak, info=info)
    info["value"] = round(value, 1)
    return info


def bench_lenet(peak, *, batch_size=256, warmup=4, iters=200):
    import jax
    import numpy as np

    from deeplearning4j_tpu.models.lenet import lenet
    from deeplearning4j_tpu.train.trainer import Trainer

    model = lenet()
    trainer = Trainer(model)
    ts = trainer.init_state()
    r = np.random.default_rng(0)
    batch = jax.device_put({
        "features": r.normal(size=(batch_size, 28, 28, 1)).astype(np.float32),
        "labels": np.eye(10, dtype=np.float32)[r.integers(0, 10, batch_size)],
    })

    info = {"batch": batch_size, "unit": "samples/sec/chip"}
    value = _timed_train(
        trainer, ts, batch, warmup=warmup, iters=iters,
        flops_per_step=LENET_TRAIN_FLOPS_PER_SAMPLE * batch_size,
        units_per_step=batch_size, peak_flops=peak, info=info)
    info["value"] = round(value, 1)
    return info


def bench_serving(peak, *, n_threads=8, requests_per_thread=40,
                  max_batch=16):
    """Serving-path benchmark: requests/sec and p50/p99 end-to-end latency
    at a fixed offered load (N closed-loop client threads, mixed batch
    sizes) through the full stack — real loopback HTTP, ModelServer,
    admission control, ParallelInference dynamic batching — plus mean
    batch occupancy from the worker-side metrics hook. ``peak`` (chip
    FLOPs) is unused: the metric is end-to-end serving capacity, not MFU.
    """
    import threading

    import numpy as np

    from deeplearning4j_tpu.models.lenet import lenet
    from deeplearning4j_tpu.serving import (
        DeadlineExceededError,
        ModelRegistry,
        ModelServer,
        QueueFullError,
        ServingClient,
        spec,
    )

    model = lenet()
    registry = ModelRegistry()
    registry.register(
        "lenet", lambda v, x: model.output(v, x), model.init(seed=0),
        input_spec=spec((28, 28, 1)), version="v1", mode="batched",
        max_batch_size=max_batch)
    server = ModelServer(registry, port=0)
    server.start(warm=True)  # buckets pre-compiled: no compile in the window
    try:
        client = ServingClient(server.url)
        lock = threading.Lock()
        latencies, rows_served, shed, broken = [], [], [], []
        barrier = threading.Barrier(n_threads + 1)

        def run(tid):
            rng = np.random.default_rng(tid)
            barrier.wait()
            for i in range(requests_per_thread):
                rows = 1 + (tid + i) % 4
                x = rng.normal(size=(rows, 784)).astype(np.float32)
                t0 = time.monotonic()
                try:
                    client.predict("lenet", x, deadline_ms=30000)
                    dt = time.monotonic() - t0
                    with lock:
                        latencies.append(dt)
                        rows_served.append(rows)
                except (QueueFullError, DeadlineExceededError) as e:
                    with lock:
                        shed.append(e)
                except Exception as e:  # noqa: BLE001 - anything else = bug
                    with lock:
                        broken.append(e)

        threads = [threading.Thread(target=run, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        barrier.wait()  # all clients poised: the window starts here
        t_start = time.monotonic()
        for t in threads:
            t.join()
        wall = time.monotonic() - t_start

        occupancy = server.metrics.batch_occupancy.summary(model="lenet")
        device = server.metrics.device_latency.summary(model="lenet")
        lat_ms = (np.sort(np.asarray(latencies)) if latencies
                  else np.zeros(1)) * 1e3
        total = n_threads * requests_per_thread
        info = {
            "n_threads": n_threads, "offered": total,
            "served": len(latencies), "shed": len(shed),
            "broken": len(broken), "max_batch": max_batch,
            "p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
            "p99_ms": round(float(np.percentile(lat_ms, 99)), 2),
            "rows_per_sec": round(sum(rows_served) / wall, 1),
            "batch_occupancy_mean": round(occupancy["mean"], 3),
            "device_batches": device["count"],
            "device_ms_mean": round(device["mean"] * 1e3, 2),
            # rides the CPU config-integrity machinery: ok = every request
            # either served or shed with a typed error, and some served
            "converged": bool(latencies) and not broken,
            "unit": "requests/sec",
        }
        info["value"] = round(len(latencies) / wall, 1)
        return info
    finally:
        server.stop()


def bench_overload(peak, *, critical_threads=4, normal_threads=8,
                   batch_threads=28, duration_s=8.0, max_in_flight=4,
                   max_batch=16, p99_gate_ms=2000.0,
                   min_critical_availability=0.99):
    """Overload-discipline benchmark (serving/overload.py): critical-class
    goodput and p99 while offered concurrency is ~10x the admission
    ceiling — a closed-loop three-priority, two-tenant client mix
    through the full stack (HTTP, priority admission, AIMD limit,
    brownout ladder). Gates: critical availability >= 99% and critical
    p99 under ``p99_gate_ms`` — the server must protect its most
    important traffic while shedding the rest with typed backpressure.
    ``value`` = critical requests/sec served through the storm. ``peak``
    is unused: the metric is overload goodput, not MFU.
    """
    import threading

    import numpy as np

    from deeplearning4j_tpu.models.lenet import lenet
    from deeplearning4j_tpu.serving import (
        ModelRegistry,
        ModelServer,
        OverloadPolicy,
        ServingClient,
        ServingError,
        spec,
    )

    model = lenet()
    registry = ModelRegistry()
    registry.register(
        "lenet", lambda v, x: model.output(v, x), model.init(seed=0),
        input_spec=spec((28, 28, 1)), version="v1", mode="batched",
        max_batch_size=max_batch)
    policy = OverloadPolicy(
        min_in_flight=2, max_in_flight=max_in_flight, interval_s=0.5,
        min_degraded_p99_s=0.05,
        # quotas effectively open: this config measures priority
        # discipline, not tenant policing (tested elsewhere)
        tenant_rate=10000.0, tenant_burst=10000.0)
    server = ModelServer(registry, port=0, overload=policy, sentinel=False)
    server.start(warm=True)
    try:
        lock = threading.Lock()
        lat = {"critical": [], "normal": [], "batch": []}
        shed = {"critical": 0, "normal": 0, "batch": 0}
        broken = []
        stop = threading.Event()
        n_threads = critical_threads + normal_threads + batch_threads
        barrier = threading.Barrier(n_threads + 1)

        def run(prio, tenant, tid):
            rng = np.random.default_rng(tid)
            client = ServingClient(server.url)
            barrier.wait()
            while not stop.is_set():
                x = rng.normal(size=(1, 784)).astype(np.float32)
                t0 = time.monotonic()
                try:
                    client.predict("lenet", x, deadline_ms=30000,
                                   priority=prio, tenant=tenant)
                    dt = time.monotonic() - t0
                    with lock:
                        lat[prio].append(dt)
                except ServingError as e:
                    # typed backpressure (sheds/deadlines) is the
                    # designed overload behavior; anything else = bug
                    if getattr(e, "retryable", False) \
                            or e.http_status in (429, 503, 504):
                        with lock:
                            shed[prio] += 1
                    else:
                        with lock:
                            broken.append(e)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        broken.append(e)

        threads = []
        tid = 0
        for n, prio, tenant in ((critical_threads, "critical", "a"),
                                (normal_threads, "normal", "a"),
                                (batch_threads, "batch", "b")):
            for _ in range(n):
                threads.append(threading.Thread(
                    target=run, args=(prio, tenant, tid)))
                tid += 1
        for t in threads:
            t.start()
        barrier.wait()
        t_start = time.monotonic()
        time.sleep(duration_s)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        wall = time.monotonic() - t_start

        crit = np.sort(np.asarray(lat["critical"]))
        crit_offered = len(crit) + shed["critical"]
        availability = (len(crit) / crit_offered) if crit_offered else 0.0
        p99_ms = (float(np.percentile(crit, 99)) * 1e3 if len(crit)
                  else float("inf"))
        info = {
            "offered_concurrency": n_threads,
            "admission_ceiling": max_in_flight,
            "overload_factor": round(n_threads / max_in_flight, 1),
            "critical_served": len(crit),
            "critical_shed": shed["critical"],
            "critical_availability": round(availability, 4),
            "critical_p99_ms": round(p99_ms, 2),
            "p99_gate_ms": p99_gate_ms,
            "normal_served": len(lat["normal"]),
            "normal_shed": shed["normal"],
            "batch_served": len(lat["batch"]),
            "batch_shed": shed["batch"],
            "broken": len(broken),
            "effective_limit_final": server.overload.effective_limit,
            "brownout_level_final": server.overload.ladder.level,
            # config-integrity gate: critical goodput + p99 both inside
            # their bounds and every failure a typed shed
            "converged": (len(crit) > 0 and not broken
                          and availability >= min_critical_availability
                          and p99_ms <= p99_gate_ms),
            "unit": "critical requests/sec under ~10x overload",
        }
        info["value"] = round(len(crit) / wall, 1)
        return info
    finally:
        server.stop()


def bench_generation(peak, *, n_clients=6, requests_per_client=4,
                     num_slots=4, max_new_tokens=32, max_len=96,
                     hidden=128, num_layers=3, num_heads=4, vocab=512,
                     prompt_lens=(4, 11, 23), temperature=0.8):
    """Generative-serving benchmark (serving/generation.py): tokens/sec
    at a fixed offered load of closed-loop STREAMING clients through the
    full stack — real loopback HTTP, continuous batching, bucketed KV
    slabs — plus client-measured p50/p99 time-to-first-token, mean
    decode-slot occupancy, and the recompile discipline gate:
    jax.monitoring-counted compilations after warmup must be exactly 0
    across the mixed prefix lengths. ``peak`` is unused: the metric is
    end-to-end decode throughput, not MFU.
    """
    import threading

    import numpy as np

    from deeplearning4j_tpu.models.gpt import Gpt, GptConfig
    from deeplearning4j_tpu.observability.runtime import (
        get_runtime_collector,
    )
    from deeplearning4j_tpu.serving import (
        GenerationEngine,
        ModelServer,
        ServingClient,
    )

    model = Gpt(GptConfig(
        vocab_size=vocab, hidden=hidden, num_layers=num_layers,
        num_heads=num_heads, intermediate=hidden * 4,
        max_position=max_len, dropout=0.0, attention_dropout=0.0))
    variables = model.init(seed=0)
    engine = GenerationEngine(
        model, variables, name="gpt", num_slots=num_slots,
        max_len=max_len, max_new_tokens=max_new_tokens,
        idle_wait_s=0.002, temperature=temperature,
        max_waiting=2 * n_clients * requests_per_client)
    server = ModelServer(port=0, sentinel=False, generators={"gpt": engine})
    server.start(warm=True)  # every (slot, kv) + prompt bucket compiled
    try:
        collector = get_runtime_collector()
        compiles_before = collector.jit_compiles_total.value()
        lock = threading.Lock()
        ttfts, tokens_done, broken = [], [], []
        barrier = threading.Barrier(n_clients + 1)

        def run(tid):
            rng = np.random.default_rng(tid)
            client = ServingClient(server.url, max_retries=4)
            barrier.wait()
            for i in range(requests_per_client):
                plen = prompt_lens[(tid + i) % len(prompt_lens)]
                prompt = rng.integers(0, vocab - 1, size=plen)
                t0 = time.monotonic()
                first, n = None, 0
                try:
                    for _tok in client.generate("gpt", prompt,
                                                temperature=temperature):
                        if first is None:
                            first = time.monotonic() - t0
                        n += 1
                    with lock:
                        ttfts.append(first)
                        tokens_done.append(n)
                except Exception as e:  # noqa: BLE001 - any failure = bug
                    with lock:
                        broken.append(repr(e))

        threads = [threading.Thread(target=run, args=(t,))
                   for t in range(n_clients)]
        for t in threads:
            t.start()
        barrier.wait()  # all clients poised: the window starts here
        t_start = time.monotonic()
        for t in threads:
            t.join()
        wall = time.monotonic() - t_start

        recompiles = int(collector.jit_compiles_total.value()
                         - compiles_before)
        occupancy = server.metrics.generation_slot_occupancy.summary(
            model="gpt")
        ttft_ms = (np.sort(np.asarray([t for t in ttfts if t is not None]))
                   if ttfts else np.zeros(1)) * 1e3
        offered = n_clients * requests_per_client
        total_tokens = int(sum(tokens_done))
        info = {
            "n_clients": n_clients, "offered": offered,
            "served": len(tokens_done), "broken": len(broken),
            "num_slots": num_slots, "max_new_tokens": max_new_tokens,
            "total_tokens": total_tokens,
            "ttft_p50_ms": round(float(np.percentile(ttft_ms, 50)), 2),
            "ttft_p99_ms": round(float(np.percentile(ttft_ms, 99)), 2),
            "slot_occupancy_mean": (round(occupancy["mean"], 3)
                                    if occupancy["count"] else 0.0),
            "decode_steps": engine.steps,
            "recompiles_after_warmup": recompiles,
            "engine_compiles_after_warm": engine.compiles_after_warm,
            # config-integrity gate: every stream completed, tokens
            # flowed, and NO decode/prefill recompiled after warmup
            "converged": (len(tokens_done) == offered and not broken
                          and total_tokens > 0 and recompiles == 0
                          and engine.compiles_after_warm == 0),
            "unit": "tokens/sec",
        }
        info["value"] = round(total_tokens / wall, 1)
        return info
    finally:
        server.stop()


def bench_router(peak, *, backends=3, n_threads=8, requests_per_thread=25,
                 per_row_ms=15.0, overhead_rounds=6, overhead_requests=30,
                 mttr_timeout_s=10.0):
    """Fleet-router benchmark (serving/router.py): the two ROADMAP
    item 5 gates plus the chaos MTTR probe.

    - **Goodput scaling 1→N local backends**: closed-loop clients
      against a router over 1 backend, then over ``backends`` backends
      of the same fleet; each backend's forward costs ``per_row_ms``
      per row (a controlled service time — the sleep releases the GIL,
      so in-process backends scale like separate hosts; it must sit
      WELL above the ~2-3 ms GIL-serialized per-request Python
      overhead all in-process backends share, or that overhead — not
      backend capacity — caps throughput and hides the scaling). Gate:
      aggregate requests/sec scales ~linearly (>= 2x at 3 backends).
    - **Router-added latency**: paired interleaved rounds of the SAME
      sequential request train direct-to-backend vs through the router
      (zero per-row cost so the hop dominates); per-round p50/p99,
      added = median of paired deltas, floored at 0. Gate: added p99
      < 1 ms — with an absolute-floor guard: when the router-free
      leg's own round-to-round p99 wobble exceeds 0.25 ms, the host
      cannot resolve a sub-ms p99 delta, and the robust paired-median
      (added p50 < 1 ms) carries the gate instead.
    - **MTTR probe** (the ``router.backend_down`` fault point): wall
      time from arming a synthetic outage of one backend to its
      ejection, and from lifting it to re-admission.

    ``peak`` is unused: the metrics are routing capacity and overhead.
    """
    import gc
    import threading

    import jax
    import numpy as np

    from deeplearning4j_tpu.resilience.faults import (
        FaultInjector,
        set_fault_injector,
    )
    from deeplearning4j_tpu.serving import (
        FleetRouter,
        ModelRegistry,
        ModelServer,
        RouterPolicy,
        ServingClient,
        spec,
    )

    cfg = {"per_row_s": per_row_ms / 1000.0}

    def make_backend():
        import jax.numpy as jnp

        def fwd(v, x):
            return jnp.zeros((x.shape[0], 1), jnp.float32)

        reg = ModelRegistry()
        reg.register("m", fwd, {"w": np.zeros(1, np.float32)},
                     input_spec=spec((4,)), version="v1", mode="batched",
                     max_batch_size=8, devices=jax.devices()[:1])
        srv = ModelServer(reg, port=0, slo_interval_s=3600.0,
                          sentinel=False)
        srv.start(warm=True)
        # per-ROW host-side service time, patched onto the replica's
        # worker fn AFTER warmup (inside the forward it would be jit-
        # traced away): capacity per backend is rows/sec regardless of
        # batching, so fleet goodput is the router's fan-out to
        # measure. The sleep releases the GIL — in-process backends
        # serve concurrently like separate hosts.
        pi = reg.get("m")._active.pi
        orig = pi._fn

        def slow(v, x):
            if cfg["per_row_s"] > 0:
                time.sleep(cfg["per_row_s"] * int(x.shape[0]))
            return orig(v, x)

        pi._fn = slow
        return srv

    def run_load(url, threads, per_thread):
        lock = threading.Lock()
        latencies, broken = [], []
        barrier = threading.Barrier(threads + 1)

        def run(tid):
            c = ServingClient(url, max_retries=2, retry_seed=tid)
            x = np.zeros((1, 4), np.float32)
            barrier.wait()
            for _ in range(per_thread):
                t0 = time.monotonic()
                try:
                    c.predict("m", x, deadline_ms=30000)
                    with lock:
                        latencies.append(time.monotonic() - t0)
                except Exception as e:  # noqa: BLE001 - any = broken
                    with lock:
                        broken.append(e)

        ts = [threading.Thread(target=run, args=(t,))
              for t in range(threads)]
        for t in ts:
            t.start()
        barrier.wait()
        t_start = time.monotonic()
        for t in ts:
            t.join()
        return latencies, broken, time.monotonic() - t_start

    servers = [make_backend() for _ in range(backends)]
    policy = RouterPolicy(probe_interval_s=0.25, probe_timeout_s=0.5,
                          reprobe_after_s=0.5)
    router1 = FleetRouter([("b0", servers[0].url)], policy=policy).start()
    router_n = FleetRouter(
        [(f"b{i}", s.url) for i, s in enumerate(servers)],
        policy=policy).start()
    try:
        # -- goodput scaling 1 -> N ----------------------------------------
        run_load(router1.url, 2, 4)  # warm every hop (compiles, pools)
        run_load(router_n.url, 2, 4)
        lat1, broken1, wall1 = run_load(router1.url, n_threads,
                                        requests_per_thread)
        lat_n, broken_n, wall_n = run_load(router_n.url, n_threads,
                                           requests_per_thread)
        rps1 = len(lat1) / wall1 if wall1 > 0 else 0.0
        rps_n = len(lat_n) / wall_n if wall_n > 0 else 0.0
        scaling = rps_n / rps1 if rps1 > 0 else 0.0

        # -- router-added latency (paired interleaved rounds) --------------
        # Keep-alive on BOTH legs: a fresh urllib connection per
        # request spawns a new handler thread per hop, and that
        # scheduler jitter (not the router) would own the p99. One
        # persistent connection per leg isolates the hop the router
        # actually adds — which is how fleet clients talk to it.
        import http.client as _hc

        cfg["per_row_s"] = 0.0  # the hop, not the model, is under test

        class _KAClient:
            def __init__(self, url):
                host, port = url.split("//")[1].split(":")
                self.conn = _hc.HTTPConnection(host, int(port),
                                               timeout=10)
                self.body = json.dumps(
                    {"inputs": [[0.0, 0.0, 0.0, 0.0]]}).encode()

            def predict(self):
                self.conn.request(
                    "POST", "/v1/models/m:predict", body=self.body,
                    headers={"Content-Type": "application/json"})
                resp = self.conn.getresponse()
                raw = resp.read()
                if resp.status != 200:
                    raise RuntimeError(f"predict {resp.status}: "
                                       f"{raw[:120]!r}")

            def close(self):
                self.conn.close()

        direct = _KAClient(servers[0].url)
        via = _KAClient(router1.url)
        for c in (direct, via):
            for _ in range(10):
                c.predict()  # warm connections + code paths
        d50, d99, r50, r99 = [], [], [], []
        gc_was = gc.isenabled()
        gc.disable()  # gen-2 pauses swamp sub-ms paired deltas
        try:
            for _ in range(overhead_rounds):
                for client, p50s, p99s in ((direct, d50, d99),
                                           (via, r50, r99)):
                    ls = []
                    for _ in range(overhead_requests):
                        t0 = time.monotonic()
                        client.predict()
                        ls.append(time.monotonic() - t0)
                    arr = np.sort(np.asarray(ls)) * 1e3
                    p50s.append(float(np.percentile(arr, 50)))
                    p99s.append(float(np.percentile(arr, 99)))
        finally:
            if gc_was:
                gc.enable()
            direct.close()
            via.close()
        added_p50_ms = max(0.0, float(np.median(
            np.asarray(r50) - np.asarray(d50))))
        added_p99_ms = max(0.0, float(np.median(
            np.asarray(r99) - np.asarray(d99))))
        # absolute-floor guard: the ROUTER-FREE leg's own round-to-
        # round p99 wobble measures what the host's scheduler does to
        # a sub-ms signal. When that wobble eats the gate's headroom,
        # the p99 delta is jitter, not router cost — fall back to the
        # robust paired-median (p50) evidence instead of failing a
        # 1 ms gate on noise the router never caused.
        direct_jitter_ms = float(np.median(np.abs(
            np.asarray(d99) - np.median(d99))))
        p99_gate_ok = added_p99_ms < 1.0 or (
            direct_jitter_ms > 0.25 and added_p50_ms < 1.0)

        # -- MTTR probe (router.backend_down fault point) ------------------
        cfg["per_row_s"] = per_row_ms / 1000.0
        inj = FaultInjector()
        inj.plan("router.backend_down", at=1, times=10 ** 9, arg=1.0)
        set_fault_injector(inj)
        t0 = time.monotonic()
        try:
            deadline = t0 + mttr_timeout_s
            while router_n.backend("b1").routable \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            mttr_eject_s = (time.monotonic() - t0
                            if not router_n.backend("b1").routable
                            else None)
        finally:
            set_fault_injector(None)
        t1 = time.monotonic()
        deadline = t1 + mttr_timeout_s
        while not router_n.backend("b1").routable \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        mttr_readmit_s = (time.monotonic() - t1
                          if router_n.backend("b1").routable else None)

        lat_ms = (np.sort(np.asarray(lat_n)) if lat_n
                  else np.zeros(1)) * 1e3
        info = {
            "backends": backends, "n_threads": n_threads,
            "offered": n_threads * requests_per_thread,
            "served_1": len(lat1), "served_n": len(lat_n),
            "broken": len(broken1) + len(broken_n),
            "rps_1_backend": round(rps1, 1),
            "rps_n_backends": round(rps_n, 1),
            "goodput_scaling": round(scaling, 2),
            "p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
            "p99_ms": round(float(np.percentile(lat_ms, 99)), 2),
            "router_added_p50_ms": round(added_p50_ms, 3),
            "router_added_p99_ms": round(added_p99_ms, 3),
            "direct_p99_jitter_ms": round(direct_jitter_ms, 3),
            "mttr_eject_s": (round(mttr_eject_s, 3)
                             if mttr_eject_s is not None else None),
            "mttr_readmit_s": (round(mttr_readmit_s, 3)
                               if mttr_readmit_s is not None else None),
            # the ROADMAP item 5 gates: ~linear goodput 1->3 local
            # backends, router-added p99 < 1 ms (jitter-floored), plus
            # chaos MTTR sanity
            "converged": (not broken1 and not broken_n
                          and scaling >= 2.0 and p99_gate_ok
                          and mttr_eject_s is not None
                          and mttr_eject_s < 2.0
                          and mttr_readmit_s is not None),
            "unit": "requests/sec",
        }
        info["value"] = round(rps_n, 1)
        return info
    finally:
        set_fault_injector(None)
        router1.stop()
        router_n.stop()
        for s in servers:
            s.stop(drain=False)


_WARMSTART_CHILD = r"""
import json, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from deeplearning4j_tpu.models.lenet import lenet
from deeplearning4j_tpu.observability.runtime import get_runtime_collector
from deeplearning4j_tpu.serving import (ModelRegistry, ModelServer,
                                        ServingClient, spec)

t_proc = time.monotonic()
model = lenet()
reg = ModelRegistry()
reg.register("lenet", lambda v, x: model.output(v, x), model.init(seed=0),
             input_spec=spec((28, 28, 1)), version="v1", mode="batched",
             max_batch_size=16, devices=jax.devices()[:1])
srv = ModelServer(reg, port=0, sentinel=False, slo_interval_s=3600.0)
t0 = time.monotonic()
srv.start(warm=True)   # cache + manifest picked up from env
ready_s = time.monotonic() - t0
col = get_runtime_collector()
client = ServingClient(srv.url)
x = np.zeros((2, 28, 28, 1), np.float32)
before = col.jit_compiles_total.value()
t1 = time.monotonic()
client.predict("lenet", x)
ttfs_s = time.monotonic() - t1
first_req_compiles = col.jit_compiles_total.value() - before
for _ in range(4):   # steady traffic: populates the manifest (bucket 2)
    client.predict("lenet", x)
post_compiles = col.jit_compiles_total.value() - before - first_req_compiles
cache = srv.compile_cache.describe() if srv.compile_cache else None
warmed = sorted(reg.get("lenet").warmed_buckets)
srv.stop()   # flushes the manifest
print("RESULT " + json.dumps({
    "ready_s": round(ready_s, 3),
    "ttfs_s": round(ttfs_s, 4),
    "proc_to_first_success_s": round(time.monotonic() - t_proc, 3),
    "first_request_compiles": first_req_compiles,
    "post_first_compiles": post_compiles,
    "warmed_buckets": warmed,
    "cache_entries": cache["manifest_entries"] if cache else 0,
}), flush=True)
"""


def bench_warmstart(peak, *, min_speedup=1.3):
    """Cold-start robustness benchmark (runtime/compilecache.py +
    serving/warmstart.py): the same serving process started twice in
    fresh interpreters against one cache/manifest directory pair.

    Round 1 (cold): empty persistent compile cache, no warmup manifest —
    the full bucket vocabulary compiles from scratch; live traffic then
    writes the manifest and warmup seals the cache. Round 2 (warm
    restart): the child finds both on disk — it AOT-compiles exactly
    the manifest's observed buckets, each a verified disk read. Gates:

    - warm-restart time-to-ready at least ``min_speedup``x below cold
      (the MTTR lever ROADMAP item 6 names), and
    - recompiles after the first post-restart request == 0 (the warm
      process serves its first request at steady state; the cold round
      is allowed first-hit compiles — that is the baseline being
      beaten).

    ``value`` = cold/warm ready-time speedup. ``peak`` unused: the
    metric is restart latency, not MFU.
    """
    import json as _json
    import shutil
    import subprocess
    import sys
    import tempfile

    tmp = tempfile.mkdtemp(prefix="dl4j-warmstart-")
    cache_dir = os.path.join(tmp, "compile_cache")
    manifest = os.path.join(tmp, "warmup_manifest.json")
    os.makedirs(cache_dir, exist_ok=True)

    def run_child():
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   DL4J_TPU_COMPILE_CACHE_DIR=cache_dir,
                   DL4J_TPU_WARMUP_MANIFEST=manifest)
        out = subprocess.run(
            [sys.executable, "-c", _WARMSTART_CHILD], env=env,
            capture_output=True, text=True, timeout=600)
        for line in out.stdout.splitlines():
            if line.startswith("RESULT "):
                return _json.loads(line[len("RESULT "):])
        raise RuntimeError(
            f"warmstart child emitted no RESULT: {out.stdout[-400:]} "
            f"{out.stderr[-400:]}")

    try:
        cold = run_child()
        warm = run_child()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    speedup = cold["ready_s"] / max(warm["ready_s"], 1e-6)
    return {
        "cold": cold,
        "warm": warm,
        "ready_speedup": round(speedup, 2),
        "warm_restart_recompiles_after_first_request":
            warm["first_request_compiles"] + warm["post_first_compiles"],
        # config-integrity gate: the warm restart must be measurably
        # faster to ready AND serve its first request with zero
        # compiles — restarts/re-expansions/fallback swaps take
        # traffic warm
        "converged": (speedup >= min_speedup
                      and warm["first_request_compiles"] == 0
                      and warm["post_first_compiles"] == 0
                      and warm["cache_entries"] >= 1),
        "unit": "cold/warm time-to-ready speedup",
        "value": round(speedup, 2),
    }


def bench_resilience(peak, *, sizes_mb=(1, 8, 64), repeats=3, epochs=2):
    """Fault-tolerance benchmark (resilience/ + serde integrity):
    verified-checkpoint save/verify/restore latency vs. snapshot size
    (what the SHA-256 manifest + atomic tmp/replace write costs over a
    bare ``np.savez``), and the wall-clock recovery overhead of a
    training run that hits one injected poison batch — rollback to the
    last verified checkpoint plus replay — against the same run fault
    free. ``peak`` (chip FLOPs) is unused: the metrics are host-side IO
    and recovery latency, not MFU.
    """
    import shutil
    import tempfile

    import jax
    import numpy as np

    from deeplearning4j_tpu.data import ArrayDataSetIterator
    from deeplearning4j_tpu.nn.config import (
        NeuralNetConfiguration,
        SequentialConfig,
    )
    from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
    from deeplearning4j_tpu.nn.model import SequentialModel
    from deeplearning4j_tpu.resilience import (
        FaultInjector,
        FaultTolerantTrainer,
        RecoveryPolicy,
        set_fault_injector,
    )
    from deeplearning4j_tpu.serde.checkpoint import (
        load_state_tree,
        save_state_tree,
        verify_checkpoint,
    )
    from deeplearning4j_tpu.train.trainer import Trainer
    from deeplearning4j_tpu.train.updaters import Sgd

    tmp_root = tempfile.mkdtemp(prefix="bench_resilience_")
    rows = []
    try:
        rng = np.random.default_rng(0)
        for mb in sizes_mb:
            per = max(1, int(mb * (1 << 20)) // (4 * 4))  # 4 float32 leaves
            tree = {f"w{i}": rng.normal(size=(per,)).astype(np.float32)
                    for i in range(4)}
            d = os.path.join(tmp_root, f"snap_{mb}mb")
            t_save, t_verify, t_restore = [], [], []
            for _ in range(repeats):
                shutil.rmtree(d, ignore_errors=True)
                t0 = time.perf_counter()
                save_state_tree(d, tree)
                t_save.append(time.perf_counter() - t0)
                t0 = time.perf_counter()
                ok, why = verify_checkpoint(d, deep=True)
                t_verify.append(time.perf_counter() - t0)
                if not ok:
                    raise RuntimeError(f"verify_checkpoint failed: {why}")
                t0 = time.perf_counter()
                load_state_tree(d, tree)
                t_restore.append(time.perf_counter() - t0)
            rows.append({
                "size_mb": mb,
                "save_ms": round(min(t_save) * 1e3, 2),
                "verify_deep_ms": round(min(t_verify) * 1e3, 2),
                "restore_ms": round(min(t_restore) * 1e3, 2),
                "save_mb_per_s": round(mb / min(t_save), 1),
            })

        # recovery wall-clock: identical tiny-MLP fits, one with a poison
        # batch injected mid-training (NaN loss → rollback to the last
        # verified checkpoint → replay); a warmup fit populates the jit
        # cache first so the delta is rollback+replay cost, not jit skew
        def _mlp():
            return SequentialModel(SequentialConfig(
                net=NeuralNetConfiguration(updater=Sgd(0.05), seed=0),
                layers=[Dense(units=32, activation="tanh"),
                        OutputLayer(units=2, activation="softmax",
                                    loss="mcxent")],
                input_shape=(16,),
            ))

        def _data():
            r = np.random.default_rng(0)
            x = r.normal(size=(64, 16)).astype(np.float32)
            y = np.eye(2, dtype=np.float32)[(x.sum(-1) > 0).astype(int)]
            return ArrayDataSetIterator(x, y, batch_size=8, shuffle=False)

        def _fit(tag, injector):
            set_fault_injector(injector)
            trainer = Trainer(_mlp())
            ft = FaultTolerantTrainer(
                trainer, os.path.join(tmp_root, tag),
                policy=RecoveryPolicy(checkpoint_every=4, keep_last=3))
            t0 = time.perf_counter()
            ts = ft.fit(trainer.init_state(), _data(), epochs=epochs)
            return (time.perf_counter() - t0,
                    int(jax.device_get(ts.step)), ft.recoveries)

        _fit("warmup", FaultInjector())
        clean_wall, clean_steps, _ = _fit("clean", FaultInjector())
        faulty_wall, faulty_steps, recoveries = _fit(
            "faulty", FaultInjector().plan("train.step_nan", at=6))
        rollbacks = sum(1 for r in recoveries if r["kind"] == "rollback")

        info = {
            "snapshots": rows,
            "clean_fit_s": round(clean_wall, 3),
            "faulty_fit_s": round(faulty_wall, 3),
            "recovery_overhead_s": round(faulty_wall - clean_wall, 3),
            "rollbacks": rollbacks,
            "steps_clean": clean_steps,
            "steps_faulty": faulty_steps,
            # integrity gate: the faulted run recovered AND finished with
            # the fault-free step count
            "converged": bool(rollbacks >= 1
                              and faulty_steps == clean_steps),
            "unit": "MB/s verified save",
        }
        info["value"] = rows[-1]["save_mb_per_s"]
        return info
    finally:
        # None = drop back to the env-built injector, so a DL4J_TPU_FAULTS
        # plan armed for other configs in this process stays armed
        set_fault_injector(None)
        shutil.rmtree(tmp_root, ignore_errors=True)


def bench_observability(peak, *, steps=64, batch_size=128, hidden=512,
                        span_n=5000, series=1000):
    """Telemetry-layer self-cost benchmark (observability/): the cost of
    the instrumentation itself, so the layer that watches regressions
    cannot silently become one. Four numbers:

    - instrumented vs BARE ``Trainer.fit`` step time (the global
      ``set_enabled``/``set_tracing_enabled`` switches toggle the same
      code path the production loop runs) — min-of-3 windows each,
      interleaved, to shed host jitter. The probe MLP is sized so the
      step sits in the low-ms class of the real configs (lenet b256 ≈
      1 ms, bert ≈ 24 ms): the per-step instrument cost is ~10 µs of
      host work, so the honest denominators are ms-scale steps; the
      absolute cost is reported too (``overhead_us_per_step``) so
      sub-ms-step models can budget it;
    - the DIAGNOSTICS-plane increment, gated < 2%
      (``diag_overhead_pct``): the flight recorder's in-loop cost (same
      instrumented fit with recording on, vs off) PLUS the SLO
      evaluator's tick cost amortized at its production 10 s cadence —
      the layer that answers "is this healthy?" must not itself make it
      unhealthy;
    - span enter/exit cost (``with span(...)``) in µs;
    - registry render latency with ``series`` live counter series plus a
      populated histogram (the /metrics scrape cost at 1k-series scale).

    ``peak`` (chip FLOPs) is unused: the metric is host-side overhead.
    """
    import numpy as np

    from deeplearning4j_tpu.data import ArrayDataSetIterator
    from deeplearning4j_tpu.nn.config import (
        NeuralNetConfiguration,
        SequentialConfig,
    )
    from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
    from deeplearning4j_tpu.nn.model import SequentialModel
    from deeplearning4j_tpu.observability import flightrecorder as fr
    from deeplearning4j_tpu.observability import slo
    from deeplearning4j_tpu.observability.metrics import MetricsRegistry
    from deeplearning4j_tpu.observability import metrics as om
    from deeplearning4j_tpu.observability.trace import (
        set_tracing_enabled,
        span,
    )
    from deeplearning4j_tpu.train.trainer import Trainer
    from deeplearning4j_tpu.train.updaters import Sgd

    import jax

    model = SequentialModel(SequentialConfig(
        net=NeuralNetConfiguration(updater=Sgd(0.01), seed=0),
        layers=[Dense(units=hidden, activation="tanh"),
                OutputLayer(units=2, activation="softmax", loss="mcxent")],
        input_shape=(32,),
    ))
    trainer = Trainer(model)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch_size * steps, 32)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, batch_size * steps)]
    data = ArrayDataSetIterator(x, y, batch_size=batch_size, shuffle=False)

    def timed_fit(instrumented: bool, recorder: bool = False) -> float:
        om.set_enabled(instrumented)
        set_tracing_enabled(instrumented)
        fr.set_recording(recorder)
        ts = trainer.init_state()
        t0 = time.perf_counter()
        ts = trainer.fit(ts, data, epochs=1)
        # forced host materialization: the window must include the work
        leaf = jax.tree_util.tree_leaves(ts.params)[0]
        float(jax.device_get(leaf.ravel()[0]))
        return time.perf_counter() - t0

    # the diagnostics plane under test: an evaluator over the train
    # families the instrumented fit feeds (ticked manually below so its
    # cost is measured, not sampled)
    engine = slo.HealthEngine(
        [slo.SLORule(
            name="bench-step-latency", kind="latency", objective=0.9,
            threshold_s=1.0,
            histogram=slo.Selector("train_step_seconds"),
            windows=(slo.BurnWindow(60.0, 300.0, 2.0),),
            for_s=30.0, resolve_hold_s=30.0)],
        interval_s=10.0, snapshot_every_s=1.0)

    try:
        timed_fit(True)  # compile + warm the jit cache outside any window
        # Drain EVERY in-flight background step-cost analysis BEFORE any
        # timed window — ours from the warmup fit, and any left running
        # by configs that ran earlier in this process (bench_resilience's
        # FaultTolerantTrainers each spawn one): a compile thread stealing
        # CPU mid-window reads as instrumentation overhead that isn't.
        from deeplearning4j_tpu.train import trainer as _trainer_mod

        for th in list(_trainer_mod._COST_THREADS):
            th.join(timeout=30)
        t_wait = time.perf_counter()
        while any(v == "pending"
                  for v in trainer._step_cost_cache.values()) and \
                time.perf_counter() - t_wait < 30:
            time.sleep(0.02)
        # Interleaved rounds, all three variants per round: host-load
        # drift (CPU scaling, noisy neighbors) hits every variant alike
        # instead of biasing whichever phase ran last. MEDIAN of rounds,
        # not min: with ~50 ms windows a single unusually-clean round on
        # one variant swings a min-based ratio by several percent. The
        # diag windows price the flight recorder IN the loop; the
        # evaluator is priced separately below (a tick every interval_s
        # regardless of step count — landing 0-or-1 ticks in a short
        # window would read as quantization noise, not cost).
        import statistics

        bare, instr, diag = [], [], []
        for _ in range(9):
            bare.append(timed_fit(False))
            instr.append(timed_fit(True))
            diag.append(timed_fit(True, recorder=True))
        # PAIRED differences per round, then the median across rounds:
        # this host's load drifts ±10% between rounds, which swamps an
        # unpaired median-vs-median ratio; within one ~0.5 s round the
        # three variants see the same machine, so their differences
        # isolate the instrumentation.
        bare_s = statistics.median(bare)
        instr_s = statistics.median(instr)
        diag_s = statistics.median(diag)
        d_instr = statistics.median(
            i - b for b, i in zip(bare, instr))
        d_diag = statistics.median(
            d - i for i, d in zip(instr, diag))
        overhead_pct = d_instr / bare_s * 100.0
        recorder_pct = d_diag / instr_s * 100.0

        # evaluator tick cost on the LIVE (possibly large) registry state,
        # amortized at the production default cadence (10 s): the thread
        # wakes once per interval whatever the step rate, so its honest
        # per-step price is tick_seconds / interval_seconds.
        engine.tick()  # warm lazy bundles outside the timed loop
        t0 = time.perf_counter()
        for _ in range(50):
            engine.tick()
        tick_s = (time.perf_counter() - t0) / 50
        evaluator_pct = tick_s / 10.0 * 100.0
        diag_overhead_pct = recorder_pct + evaluator_pct

        set_tracing_enabled(True)
        t0 = time.perf_counter()
        for _ in range(span_n):
            with span("bench.span"):
                pass
        span_us = (time.perf_counter() - t0) / span_n * 1e6

        reg = MetricsRegistry()
        # analysis: allow(unregistered-metric) — throwaway families on a
        # private registry pricing render_text; never scraped, never
        # referenced by an SLO rule
        c = reg.counter("bench_series_total", "render-latency probe",
                        ("idx",))
        for i in range(series):
            c.inc(idx=str(i))
        # analysis: allow(unregistered-metric) — same render-latency probe
        h = reg.histogram("bench_latency_seconds", "render-latency probe")
        for i in range(256):
            h.observe(i * 1e-4)
        t_render = []
        for _ in range(3):
            t0 = time.perf_counter()
            text = reg.render_text()
            t_render.append(time.perf_counter() - t0)

        info = {
            "steps": steps, "batch": batch_size,
            "bare_step_ms": round(bare_s / steps * 1e3, 4),
            "instrumented_step_ms": round(instr_s / steps * 1e3, 4),
            "diagnostics_step_ms": round(diag_s / steps * 1e3, 4),
            "overhead_pct": round(overhead_pct, 2),
            "overhead_us_per_step": round(d_instr / steps * 1e6, 2),
            "diag_overhead_pct": round(diag_overhead_pct, 2),
            "recorder_pct": round(recorder_pct, 2),
            "recorder_us_per_step": round(d_diag / steps * 1e6, 2),
            "evaluator_tick_ms": round(tick_s * 1e3, 3),
            "evaluator_pct_at_10s": round(evaluator_pct, 4),
            "span_enter_exit_us": round(span_us, 2),
            "render_series": series,
            "render_ms": round(min(t_render) * 1e3, 3),
            "render_bytes": len(text),
            # integrity gates: the telemetry layer's own cost stays < 5%,
            # and the diagnostics plane (evaluator + flight recorder)
            # adds < 2% on the already-instrumented step
            "converged": bool(overhead_pct < 5.0
                              and diag_overhead_pct < 2.0),
            "unit": "% instrumented step-time overhead",
        }
        info["value"] = round(max(overhead_pct, 0.0), 3)
        return info
    finally:
        om.set_enabled(True)
        set_tracing_enabled(True)
        fr.set_recording(True)


def bench_robustness(peak, *, steps=96, batch_size=128, hidden=1024,
                     rounds=10, mttr_rounds=3, load_threads=3):
    """Cluster-robustness benchmark (resilience/cluster+supervisor +
    serving worker supervision): what the self-healing layer costs when
    nothing is failing, and how fast serving heals when something is.

    - **Serving failover MTTR**: a ModelServer under background load has
      a ParallelInference worker killed (injected
      ``serving.worker_crash``); MTTR is the wall time from the first
      failed response to the first subsequent success (worker respawn +
      retry path), median over ``mttr_rounds``.
    - **Watchdog steady-state overhead**, gated < 1% on ``Trainer.fit``:
      the per-step cost of the armed supervision plane — the heartbeat
      progress beat (``touch_heartbeat``) in the fit loop plus the
      background beacon-writer thread — measured as paired
      armed-vs-bare fit windows, median of ``rounds``. The deadline
      guard itself costs nothing per step (collectives are per-epoch,
      not per-step), so this IS the whole steady-state bill.

    ``peak`` (chip FLOPs) is unused: host-side latency metrics.
    """
    import shutil
    import tempfile
    import threading

    import jax
    import numpy as np

    from deeplearning4j_tpu.data import ArrayDataSetIterator
    from deeplearning4j_tpu.nn.config import (
        NeuralNetConfiguration,
        SequentialConfig,
    )
    from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
    from deeplearning4j_tpu.nn.model import SequentialModel
    from deeplearning4j_tpu.resilience import FaultInjector, set_fault_injector
    from deeplearning4j_tpu.resilience.cluster import (
        HeartbeatWriter,
        set_process_heartbeat,
    )
    from deeplearning4j_tpu.serving import (
        ModelRegistry,
        ModelServer,
        ServingClient,
        ServingError,
    )
    from deeplearning4j_tpu.serving.warmup import spec
    from deeplearning4j_tpu.train.trainer import Trainer
    from deeplearning4j_tpu.train.updaters import Sgd

    tmp_root = tempfile.mkdtemp(prefix="bench_robustness_")
    try:
        # -- serving failover MTTR ------------------------------------------
        reg = ModelRegistry()
        reg.register("probe", lambda v, x: x @ v,
                     np.eye(8, dtype=np.float32), input_spec=spec((8,)),
                     mode="batched", max_batch_size=16,
                     devices=jax.devices()[:1])
        # measure bare respawn MTTR: no circuit breaker, and no sentinel
        # either — its always-on host sampler outlives the server (by
        # design) and would wake 20x/s inside the <1% watchdog windows
        # this config times NEXT (the sentinel plane has its own gate)
        srv = ModelServer(reg, slo_interval_s=3600.0,
                          circuit_policy=None, sentinel=False)
        srv.start()
        stop = threading.Event()
        outcomes = []  # (t_monotonic, ok) from EVERY client thread

        def client_loop():
            c = ServingClient(srv.url)
            x = [[0.1] * 8]
            while not stop.is_set():
                try:
                    c.predict("probe", x, deadline_ms=2000)
                    outcomes.append((time.monotonic(), True))
                except ServingError:
                    outcomes.append((time.monotonic(), False))
                time.sleep(0.002)

        threads = [threading.Thread(target=client_loop, daemon=True)
                   for _ in range(load_threads)]
        for t in threads:
            t.start()
        mttrs, respawns = [], 0
        try:
            for _ in range(mttr_rounds):
                # healthy traffic flowing, then kill a worker: MTTR is
                # first-failure -> first-subsequent-success across ALL
                # clients (whichever request the crashed batch held)
                time.sleep(0.05)
                mark = len(outcomes)
                set_fault_injector(
                    FaultInjector().plan("serving.worker_crash", at=1))
                deadline = time.monotonic() + 30.0
                t_fail = None
                while time.monotonic() < deadline:
                    snap = outcomes[mark:]
                    if t_fail is None:
                        t_fail = next((t for t, ok in snap if not ok), None)
                    if t_fail is not None:
                        t_ok = next((t for t, ok in snap
                                     if ok and t > t_fail), None)
                        if t_ok is not None:
                            mttrs.append(t_ok - t_fail)
                            break
                    time.sleep(0.001)
                set_fault_injector(None)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5)
            set_fault_injector(None)
            entry = reg.get("probe")
            respawns = entry._active.pi.worker_respawns \
                if entry._active is not None else 0
            srv.stop()

        # -- watchdog steady-state overhead on Trainer.fit ------------------
        model = SequentialModel(SequentialConfig(
            net=NeuralNetConfiguration(updater=Sgd(0.05), seed=0),
            layers=[Dense(units=hidden, activation="tanh"),
                    OutputLayer(units=8, activation="softmax",
                                loss="mcxent")],
            input_shape=(32,),
        ))
        trainer = Trainer(model)
        r = np.random.default_rng(0)
        x = r.normal(size=(steps * batch_size, 32)).astype(np.float32)
        y = np.eye(8, dtype=np.float32)[r.integers(0, 8, steps * batch_size)]

        class StepTimes:
            # per-step timestamps: ~rounds x steps samples per arm, so
            # the median is immune to a multi-second busy burst that a
            # window-level comparison would book entirely to one arm
            def __init__(self):
                self.deltas = []
                self._last = None

            def on_fit_start(self, t, s):
                self._last = None

            def on_epoch_start(self, e):
                pass

            def on_iteration(self, e, step, s, m):
                now = time.perf_counter()
                if self._last is not None:
                    self.deltas.append(now - self._last)
                self._last = now
                return False

            def on_epoch_end(self, e, s):
                return False

            def on_fit_end(self, t, s):
                pass

        def fit_window(sink):
            data = ArrayDataSetIterator(x, y, batch_size=batch_size,
                                        shuffle=False)
            ts = trainer.init_state()
            t0 = time.perf_counter()
            ts = trainer.fit(ts, data, epochs=1, listeners=[sink])
            jax.block_until_ready(ts.params)
            return time.perf_counter() - t0

        # Isolate the watchdog plane: the instrumentation/diagnostics
        # cost is gated by the observability config; here both arms run
        # the BARE loop so the armed-vs-bare delta is heartbeat-only
        # (background span/recorder/step-cost threads otherwise add
        # asymmetric scheduler noise well above the ~0.1 µs/step cost
        # this gate polices).
        from deeplearning4j_tpu.observability import flightrecorder as fr
        from deeplearning4j_tpu.observability import metrics as om
        from deeplearning4j_tpu.observability.trace import (
            set_tracing_enabled,
        )

        om.set_enabled(False)
        set_tracing_enabled(False)
        fr.set_recording(False)
        prev_cost = os.environ.get("DL4J_TPU_STEP_COST_ANALYSIS")
        os.environ["DL4J_TPU_STEP_COST_ANALYSIS"] = "0"
        try:
            from statistics import median as _median

            fit_window(StepTimes())  # jit warmup
            hb_dir = os.path.join(tmp_root, "hb")

            def bare_window():
                sink = StepTimes()
                wall = fit_window(sink)
                return wall, _median(sink.deltas)

            def armed_window():
                hb = HeartbeatWriter(hb_dir, 0, interval_s=0.5).start()
                set_process_heartbeat(hb)
                sink = StepTimes()
                try:
                    wall = fit_window(sink)
                finally:
                    set_process_heartbeat(None)
                    hb.stop()
                return wall, _median(sink.deltas)

            # The host's step time drifts by a few % over the run
            # (frequency/heap aging) — far above the ~0.01% true cost.
            # Cancel it in two layers: (1) each round compares ADJACENT
            # windows (per-round paired diff of per-step medians, drift
            # over one pair is tiny), alternating which arm leads;
            # (2) average each (bare-led, armed-led) round pair so the
            # residual position bias cancels, and take the median of
            # those bias-free samples.
            import gc

            bare_s = armed_s = 0.0
            round_diffs = []
            rounds += rounds % 2
            gc.collect()
            gc.disable()  # gen-2 pauses in a long-lived process dwarf
            try:          # the ~0.01% cost this gate polices
                for i in range(rounds):
                    if i % 2 == 0:
                        (bw, bm), (aw, am) = bare_window(), armed_window()
                    else:
                        (aw, am), (bw, bm) = armed_window(), bare_window()
                    bare_s, armed_s = bare_s + bw, armed_s + aw
                    round_diffs.append((am - bm) / bm * 100.0)
            finally:
                gc.enable()
            pair_diffs = [(round_diffs[k] + round_diffs[k + 1]) / 2.0
                          for k in range(0, len(round_diffs), 2)]
            overhead_pct = _median(pair_diffs)
        finally:
            om.set_enabled(True)
            set_tracing_enabled(True)
            fr.set_recording(True)
            if prev_cost is None:
                os.environ.pop("DL4J_TPU_STEP_COST_ANALYSIS", None)
            else:
                os.environ["DL4J_TPU_STEP_COST_ANALYSIS"] = prev_cost

        from statistics import median as _stat_median

        mttr_ms = _stat_median(mttrs) * 1e3 if mttrs else None
        info = {
            "mttr_rounds": mttr_rounds,
            "mttr_measured": len(mttrs),
            "failover_mttr_ms": round(mttr_ms, 2) if mttr_ms else None,
            "worker_respawns": int(respawns),
            "watchdog_rounds": rounds,
            "watchdog_steps": steps,
            "bare_step_ms": round(bare_s / (rounds * steps) * 1e3, 4),
            "armed_step_ms": round(armed_s / (rounds * steps) * 1e3, 4),
            "watchdog_overhead_pct": round(overhead_pct, 3),
            # integrity gates: every kill healed, and the supervision
            # plane's steady-state cost stays < 1% of the fit step
            "gate_overhead_ok": bool(overhead_pct < 1.0),
            "converged": bool(len(mttrs) == mttr_rounds
                              and overhead_pct < 1.0),
            "unit": "ms serving failover MTTR",
        }
        info["value"] = round(mttr_ms, 2) if mttr_ms else 0.0
        return info
    finally:
        set_fault_injector(None)
        shutil.rmtree(tmp_root, ignore_errors=True)


_ELASTIC_BENCH_WORKER = """
import json, os, pathlib, sys, time
slot = os.environ["DL4J_TPU_SLOT_ID"]
wid = os.environ["DL4J_TPU_WORKER_ID"]
gen = os.environ["DL4J_TPU_GENERATION"]
run = pathlib.Path(os.environ["RUN_DIR"])
if slot == "1" and not (run / "heal").exists():
    sys.exit(7)  # the dead slot crash-loops until healed
ckpt = pathlib.Path(os.environ["CKPT_DIR"])
ckpt.mkdir(parents=True, exist_ok=True)
steps = run / ("steps_g%s_w%s.jsonl" % (gen, wid))
with steps.open("a") as fh:
    for i in range(4000):
        if (run / "stop").exists():
            break
        fh.write(json.dumps({"t": time.time(), "step": i}) + "\\n")
        fh.flush()
        if wid == "0" and i % 5 == 4:
            # epoch-boundary save: the rotation-index write is what the
            # supervisor's expansion boundary watch keys on
            (ckpt / "checkpoint_index.json").write_text(
                json.dumps({"step": i}))
        time.sleep(0.02)
"""


def bench_elastic(peak, *, rounds=3, step_s=0.02,
                  mttr_gate_s=5.0, disruption_gate_s=5.0):
    """Elastic degraded-mode benchmark (resilience/supervisor shrink /
    probe / expand): what a permanently dead slot costs the cohort.

    - **Shrink MTTR** (kill -> first post-shrink step): wall time from
      the supervisor *detecting* the dead slot's final fatal exit to
      the shrunken cohort's first step — classification + teardown +
      env re-derivation + relaunch. Workers here are process-light
      (no jax import, a ``step_s`` sleep per step), so this prices the
      SUPERVISOR plane itself; a real cohort adds its own bootstrap +
      checkpoint-restore time on top.
    - **Expand disruption** (pause at the checkpoint boundary): wall
      time between the degraded cohort's last step and the re-expanded
      full cohort's first step — the planned-teardown window the
      boundary wait is designed to bound.

    Both are medians over ``rounds``; ``peak`` (chip FLOPs) is unused —
    host-side process-control latency.
    """
    import shutil
    import tempfile
    import threading
    from statistics import median as _median

    from deeplearning4j_tpu.observability.flightrecorder import (
        get_flight_recorder,
    )
    from deeplearning4j_tpu.resilience.supervisor import ElasticSupervisor

    def _steps(run_dir, gen):
        out = []
        for p in run_dir.glob(f"steps_g{gen}_w*.jsonl"):
            for line in p.read_text().splitlines():
                try:
                    out.append(json.loads(line)["t"])
                except (ValueError, KeyError):
                    pass
        return sorted(out)

    import pathlib

    tmp_root = pathlib.Path(tempfile.mkdtemp(prefix="bench_elastic_"))
    mttrs, disruptions = [], []
    try:
        for rnd in range(rounds):
            run_dir = tmp_root / f"round{rnd}"
            run_dir.mkdir(parents=True)
            ckpt = run_dir / "ckpt"
            env = dict(os.environ, RUN_DIR=str(run_dir),
                       CKPT_DIR=str(ckpt))
            for k in ("DL4J_TPU_WORKER_ID", "DL4J_TPU_NUM_WORKERS",
                      "DL4J_TPU_GENERATION", "DL4J_TPU_SLOT_ID",
                      "DL4J_TPU_FAULTS"):
                env.pop(k, None)
            t0 = time.time()
            sup = ElasticSupervisor(
                [sys.executable, "-c", _ELASTIC_BENCH_WORKER],
                num_workers=2, max_restarts=4, workdir=run_dir, env=env,
                backoff_base_s=0.02, backoff_max_s=0.05, grace_s=5.0,
                min_workers=1, dead_slot_threshold=2,
                immediate_exit_s=5.0, checkpoint_dir=ckpt,
                probe_interval_s=0.05, probe_max_interval_s=0.2,
                slot_healthy=lambda s: (run_dir / "heal").exists())
            box = {}

            def _run():
                try:
                    box["result"] = sup.run()
                except Exception as e:  # noqa: BLE001 — recorded below
                    box["error"] = e

            th = threading.Thread(target=_run, daemon=True)
            th.start()

            def _wait(cond, timeout):
                deadline = time.monotonic() + timeout
                while time.monotonic() < deadline:
                    if cond():
                        return True
                    time.sleep(0.005)
                return cond()

            try:
                if not _wait(lambda: sup.shrinks >= 1, 30):
                    raise RuntimeError(
                        f"never shrank: {box.get('error')}")
                (run_dir / "heal").write_text("ok")
                if not _wait(lambda: sup.expands >= 1, 30):
                    raise RuntimeError(
                        f"never expanded: {box.get('error')}")
                # a few full-strength steps, then wind the run down
                time.sleep(0.5)
                (run_dir / "stop").write_text("ok")
                th.join(timeout=30)
            finally:
                (run_dir / "heal").write_text("ok")
                (run_dir / "stop").write_text("ok")
                sup.stop()
                th.join(timeout=10)
            if "error" in box:
                raise box["error"]

            evs = [e for e in get_flight_recorder().events()
                   if e["t"] >= t0]
            shrunk_gen = next(e["data"]["generation"] for e in evs
                              if e["kind"] == "supervisor.shrink")
            expand_gen = next(e["data"]["generation"] for e in evs
                              if e["kind"] == "supervisor.expand") + 1
            # kill -> first post-shrink step: detection of the dead
            # slot's FINAL fatal exit vs the shrunken gen's first step
            t_kill = max(e["t"] for e in evs
                         if e["kind"] == "supervisor.worker_exit"
                         and e["data"].get("slot") == 1)
            shrunk_steps = _steps(run_dir, shrunk_gen + 1)
            expand_steps = _steps(run_dir, expand_gen)
            if not shrunk_steps or not expand_steps:
                raise RuntimeError("worker step telemetry missing")
            mttrs.append(shrunk_steps[0] - t_kill)
            disruptions.append(expand_steps[0] - shrunk_steps[-1])
        mttr_s = _median(mttrs)
        disruption_s = _median(disruptions)
        info = {
            "rounds": rounds,
            "worker_step_ms": round(step_s * 1e3, 1),
            "shrink_mttr_ms": round(mttr_s * 1e3, 2),
            "expand_disruption_ms": round(disruption_s * 1e3, 2),
            "shrink_mttr_ms_all": [round(v * 1e3, 2) for v in mttrs],
            "expand_disruption_ms_all": [round(v * 1e3, 2)
                                         for v in disruptions],
            # integrity gates: every round shrank AND re-expanded, and
            # both transitions stay inside their latency budgets
            "gate_mttr_ok": bool(mttr_s < mttr_gate_s),
            "gate_disruption_ok": bool(disruption_s < disruption_gate_s),
            "converged": bool(len(mttrs) == rounds
                              and mttr_s < mttr_gate_s
                              and disruption_s < disruption_gate_s),
            "note": ("process-light workers: prices the supervisor "
                     "plane; real cohorts add bootstrap+restore"),
            "unit": "ms shrink MTTR (kill -> first post-shrink step)",
        }
        info["value"] = info["shrink_mttr_ms"]
        return info
    finally:
        shutil.rmtree(tmp_root, ignore_errors=True)


def bench_federation(peak, *, steps=96, batch_size=128, hidden=1024,
                     rounds=10, poll_interval_s=0.02,
                     production_poll_interval_s=1.0):
    """Cluster-telemetry-federation benchmark (observability/federation):
    what the per-worker exporter + supervisor-side aggregator cost a
    RUNNING training worker.

    One process plays both sides — worst case for the gate: the worker
    trains (`Trainer.fit`, full instrumentation on in BOTH arms) while
    its `TelemetryExporter` serves HTTP snapshots and a
    `ClusterAggregator` polls a 2-worker cohort (this worker over HTTP
    + a file-sink peer) every ``poll_interval_s``, so every snapshot
    render, JSON parse, and federation rebuild contends on this GIL.

    The bench polls at ~50x the production cadence so a ~100 ms fit
    window still sees several polls; the gated number then bills the
    ENTIRE measured per-poll wall time (snapshot build + HTTP + file
    read + federation rebuild — as if every microsecond stole the
    training thread's GIL, though much of it is parallel IO) once per
    ``production_poll_interval_s``, as a % of step time — the same
    amortization the diagnostics gate uses for its evaluator tick.
    That upper bound is gated **< 2%** — federation must be free to
    leave on at its real cadence. The raw oversampled armed-vs-bare
    step delta is recorded alongside as evidence (on this host it sits
    inside the ±1% run-to-run jitter band).

    ``peak`` (chip FLOPs) is unused: host-side latency metrics.
    """
    import gc
    import json as _json
    import shutil
    import tempfile
    import threading
    from statistics import median as _median

    import jax
    import numpy as np

    from deeplearning4j_tpu.data import ArrayDataSetIterator
    from deeplearning4j_tpu.nn.config import (
        NeuralNetConfiguration,
        SequentialConfig,
    )
    from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
    from deeplearning4j_tpu.nn.model import SequentialModel
    from deeplearning4j_tpu.observability.federation import (
        ClusterAggregator,
        TelemetryExporter,
    )
    from deeplearning4j_tpu.train.trainer import Trainer
    from deeplearning4j_tpu.train.updaters import Sgd

    tmp_root = tempfile.mkdtemp(prefix="bench_federation_")
    prev_cost = os.environ.get("DL4J_TPU_STEP_COST_ANALYSIS")
    # step-cost analysis spawns its own background compile thread —
    # asymmetric scheduler noise orders of magnitude above the cost
    # this gate polices (same isolation as the robustness bench)
    os.environ["DL4J_TPU_STEP_COST_ANALYSIS"] = "0"
    try:
        model = SequentialModel(SequentialConfig(
            net=NeuralNetConfiguration(updater=Sgd(0.05), seed=0),
            layers=[Dense(units=hidden, activation="tanh"),
                    OutputLayer(units=8, activation="softmax",
                                loss="mcxent")],
            input_shape=(32,),
        ))
        trainer = Trainer(model)
        r = np.random.default_rng(0)
        x = r.normal(size=(steps * batch_size, 32)).astype(np.float32)
        y = np.eye(8, dtype=np.float32)[r.integers(0, 8, steps * batch_size)]

        class StepTimes:
            def __init__(self):
                self.deltas = []
                self._last = None

            def on_fit_start(self, t, s):
                self._last = None

            def on_epoch_start(self, e):
                pass

            def on_iteration(self, e, step, s, m):
                now = time.perf_counter()
                if self._last is not None:
                    self.deltas.append(now - self._last)
                self._last = now
                return False

            def on_epoch_end(self, e, s):
                return False

            def on_fit_end(self, t, s):
                pass

        def fit_window():
            data = ArrayDataSetIterator(x, y, batch_size=batch_size,
                                        shuffle=False)
            sink = StepTimes()
            ts = trainer.init_state()
            trainer.fit(ts, data, epochs=1, listeners=[sink])
            return _median(sink.deltas)

        fit_window()  # jit warmup

        sink_dir = os.path.join(tmp_root, "telemetry")
        os.makedirs(sink_dir)

        def armed_window():
            exp = TelemetryExporter(port=0, sink_dir=sink_dir).start()
            # the cohort's second worker: a file-sink peer, so each
            # poll exercises BOTH fetch paths (HTTP + file fallback)
            peer = dict(exp.snapshot(), worker=1)
            with open(os.path.join(sink_dir, "worker_1.json"), "w") as fh:
                _json.dump(peer, fh, default=str)
            agg = ClusterAggregator(num_workers=2, port_base=exp.port,
                                    sink_dir=sink_dir,
                                    liveness_window_s=3600.0)
            stop = threading.Event()

            def poll_loop():
                while not stop.wait(poll_interval_s):
                    try:
                        agg.poll()
                    except Exception:  # noqa: BLE001 - keep polling
                        pass

            th = threading.Thread(target=poll_loop, daemon=True)
            th.start()
            try:
                med = fit_window()
            finally:
                stop.set()
                th.join(timeout=5)
                exp.stop()
            return med, agg

        # adjacent-pair drift cancellation + balanced lead order +
        # GC off (same protocol the other <2% host gates use)
        rounds += rounds % 2
        round_diffs, bare_meds = [], []
        poll_sum = poll_n = 0.0
        gc.collect()
        gc.disable()
        try:
            for i in range(rounds):
                if i % 2 == 0:
                    bm = fit_window()
                    am, agg = armed_window()
                else:
                    am, agg = armed_window()
                    bm = fit_window()
                bare_meds.append(bm)
                round_diffs.append((am - bm) / bm * 100.0)
                # pool poll timings across EVERY round's aggregator —
                # gating on one round's ~5 samples would let a single
                # noisy window flip the gate
                s = agg.metrics.poll_seconds.summary()
                poll_sum += s["sum"]
                poll_n += s["count"]
                agg.close()  # release this round's fetch-pool threads
        finally:
            gc.enable()
        pair_diffs = [(round_diffs[k] + round_diffs[k + 1]) / 2.0
                      for k in range(0, len(round_diffs), 2)]
        raw_pct = _median(pair_diffs)
        fed_series = len(agg.federated_instruments())
        polls_per_window = int(poll_n // rounds)
        bare_step_ms = _median(bare_meds) * 1e3
        poll_ms = poll_sum / poll_n * 1e3 if poll_n else 0.0
        # worst-case bill: the whole poll wall time charged against the
        # fit loop, once per production interval, as a % of step time
        production_pct = (poll_ms / (production_poll_interval_s * 1e3)
                          * 100.0)

        info = {
            "rounds": rounds,
            "steps": steps,
            "poll_interval_s": poll_interval_s,
            "production_poll_interval_s": production_poll_interval_s,
            "poll_ms_mean": round(poll_ms, 3),
            "polls_per_window": polls_per_window,
            "federated_families": fed_series,
            "bare_step_ms": round(bare_step_ms, 4),
            "oversampled_overhead_pct": round(raw_pct, 3),
            "aggregator_overhead_pct": round(production_pct, 4),
            # integrity gate: a live 2-worker cohort's exporter +
            # aggregator polling at the production cadence costs the
            # training step < 2%
            "gate_overhead_ok": bool(production_pct < 2.0),
            "converged": bool(production_pct < 2.0 and fed_series > 0
                              and poll_n > 0),
            "unit": "% step-time overhead at the production poll cadence",
        }
        info["value"] = round(production_pct, 4)
        return info
    finally:
        if prev_cost is None:
            os.environ.pop("DL4J_TPU_STEP_COST_ANALYSIS", None)
        else:
            os.environ["DL4J_TPU_STEP_COST_ANALYSIS"] = prev_cost
        shutil.rmtree(tmp_root, ignore_errors=True)


def bench_sentinel(peak, *, steps=96, batch_size=128, hidden=1024,
                   rounds=10, sampler_hz=20.0,
                   production_tick_interval_s=10.0):
    """Anomaly-sentinel benchmark (observability/sentinel + hostsampler):
    what the ALWAYS-ON detection plane costs a running training step —
    the layer that catches regressions must not be one.

    Two priced components, gated together **< 2%** of step time:

    - the **20 Hz host stack sampler**: armed-vs-bare instrumented
      ``Trainer.fit`` step time with the sampler thread walking
      ``sys._current_frames()`` at its always-on rate (adjacent-pair
      drift cancellation, balanced lead order, GC off — the same
      protocol every other sub-1% host gate here uses, since gen-2 GC
      pauses alone dwarf the true cost);
    - the **detector tick**: one full sentinel pass (registry JSON walk
      + probes + baselines for all built-in detectors) over the LIVE
      post-fit registry state, amortized at the production 10 s
      cadence — the same amortization the diagnostics gate uses for
      the SLO evaluator.

    The per-sample cost of one stack walk is reported absolutely
    (``sample_us``) so deployments with many threads can budget it.

    ``peak`` (chip FLOPs) is unused: host-side overhead metrics.
    """
    import gc
    from statistics import median as _median

    import jax
    import numpy as np

    from deeplearning4j_tpu.data import ArrayDataSetIterator
    from deeplearning4j_tpu.nn.config import (
        NeuralNetConfiguration,
        SequentialConfig,
    )
    from deeplearning4j_tpu.nn.layers import Dense, OutputLayer
    from deeplearning4j_tpu.nn.model import SequentialModel
    from deeplearning4j_tpu.observability.hostsampler import HostStackSampler
    from deeplearning4j_tpu.observability.sentinel import (
        Sentinel,
        default_detectors,
    )
    from deeplearning4j_tpu.train.trainer import Trainer
    from deeplearning4j_tpu.train.updaters import Sgd

    prev_cost = os.environ.get("DL4J_TPU_STEP_COST_ANALYSIS")
    # background step-cost compiles are scheduler noise orders above
    # the cost this gate polices (same isolation as the other host gates)
    os.environ["DL4J_TPU_STEP_COST_ANALYSIS"] = "0"
    try:
        model = SequentialModel(SequentialConfig(
            net=NeuralNetConfiguration(updater=Sgd(0.05), seed=0),
            layers=[Dense(units=hidden, activation="tanh"),
                    OutputLayer(units=8, activation="softmax",
                                loss="mcxent")],
            input_shape=(32,),
        ))
        trainer = Trainer(model)
        r = np.random.default_rng(0)
        x = r.normal(size=(steps * batch_size, 32)).astype(np.float32)
        y = np.eye(8, dtype=np.float32)[r.integers(0, 8, steps * batch_size)]

        def fit_window():
            data = ArrayDataSetIterator(x, y, batch_size=batch_size,
                                        shuffle=False)
            ts = trainer.init_state()
            t0 = time.perf_counter()
            ts = trainer.fit(ts, data, epochs=1)
            # forced host materialization: the window must include the work
            leaf = jax.tree_util.tree_leaves(ts.params)[0]
            float(jax.device_get(leaf.ravel()[0]))
            return time.perf_counter() - t0

        fit_window()  # jit warmup

        def armed_window():
            sampler = HostStackSampler(hz=sampler_hz).start()
            try:
                return sampler, fit_window()
            finally:
                sampler.stop()

        rounds += rounds % 2
        round_diffs, bare_s, samples_seen = [], [], 0
        gc.collect()
        gc.disable()
        try:
            for i in range(rounds):
                if i % 2 == 0:
                    bm = fit_window()
                    sampler, am = armed_window()
                else:
                    sampler, am = armed_window()
                    bm = fit_window()
                bare_s.append(bm)
                samples_seen += sampler.samples_total
                round_diffs.append((am - bm) / bm * 100.0)
        finally:
            gc.enable()
        pair_diffs = [(round_diffs[k] + round_diffs[k + 1]) / 2.0
                      for k in range(0, len(round_diffs), 2)]
        sampler_pct = max(0.0, _median(pair_diffs))
        bare_step_ms = _median(bare_s) / steps * 1e3

        # absolute per-sample cost of one stack walk (off-thread caller
        # exclusion does not change the walk cost)
        probe = HostStackSampler()
        probe.sample()  # warm the fold path
        t0 = time.perf_counter()
        for _ in range(200):
            probe.sample()
        sample_us = (time.perf_counter() - t0) / 200 * 1e6

        # detector tick over the LIVE registry the fits populated, every
        # built-in detector armed; amortized at the production cadence
        sent = Sentinel(default_detectors())
        sent.tick()  # warm lazy bundles / probe anchors
        t0 = time.perf_counter()
        for _ in range(50):
            sent.tick()
        tick_ms = (time.perf_counter() - t0) / 50 * 1e3
        tick_pct = tick_ms / (production_tick_interval_s * 1e3) * 100.0

        total_pct = sampler_pct + tick_pct
        info = {
            "rounds": rounds,
            "steps": steps,
            "sampler_hz": sampler_hz,
            "bare_step_ms": round(bare_step_ms, 4),
            "sampler_overhead_pct": round(sampler_pct, 3),
            "sampler_samples_per_window": samples_seen // rounds,
            "sample_us": round(sample_us, 2),
            "detectors": len(sent.detectors),
            "tick_ms": round(tick_ms, 3),
            "tick_pct_at_10s": round(tick_pct, 4),
            "always_on_overhead_pct": round(total_pct, 3),
            # integrity gate: the whole always-on plane (20 Hz sampler +
            # detector tick at the 10 s cadence) costs the training step
            # < 2%
            "gate_overhead_ok": bool(total_pct < 2.0),
            "converged": bool(total_pct < 2.0 and samples_seen > 0),
            "unit": "% step-time overhead, always-on sentinel plane",
        }
        info["value"] = round(total_pct, 3)
        return info
    finally:
        if prev_cost is None:
            os.environ.pop("DL4J_TPU_STEP_COST_ANALYSIS", None)
        else:
            os.environ["DL4J_TPU_STEP_COST_ANALYSIS"] = prev_cost


def bench_reqtrace(peak, *, requests=10, rounds=8, num_slots=2,
                   max_new_tokens=16, max_len=48, hidden=64, num_layers=2,
                   num_heads=2, vocab=128, prompt_len=5):
    """Request-ledger + tail-sampling benchmark (observability/reqlog +
    trace.TailSampler): what the ALWAYS-ON per-request observability
    plane costs the serving hot path. Every generation request pays a
    ledger begin/annotate/finish, span staging (prefill + sampled
    decode-step legs into the tail buffer), and the completion-time
    retention decision; the gate is that all of it together costs
    **< 2%** of serving step time.

    Protocol: one warmed GenerationEngine (no HTTP — the gate prices
    the plane, not the socket stack); each round drives ``requests``
    identical greedy streams through the live scheduler to completion
    and times the window, alternating ledger-enabled/disabled order per
    round (adjacent-pair drift cancellation, GC off — the same sub-1%
    discipline every other host gate here uses). The absolute per-record
    cost (begin + 3 annotates + finish with a 6-span staging buffer) is
    reported in µs so deployments can budget it per request.

    ``peak`` (chip FLOPs) is unused: host-side overhead metrics.
    """
    import gc
    from statistics import median as _median

    import numpy as np

    from deeplearning4j_tpu.models.gpt import Gpt, GptConfig
    from deeplearning4j_tpu.observability import reqlog as _rl
    from deeplearning4j_tpu.observability import trace as _tr
    from deeplearning4j_tpu.serving import GenerationEngine

    model = Gpt(GptConfig(
        vocab_size=vocab, hidden=hidden, num_layers=num_layers,
        num_heads=num_heads, intermediate=hidden * 4,
        max_position=max_len, dropout=0.0, attention_dropout=0.0))
    variables = model.init(seed=0)
    engine = GenerationEngine(
        model, variables, name="reqtrace", num_slots=num_slots,
        max_len=max_len, max_new_tokens=max_new_tokens,
        idle_wait_s=0.001, temperature=0.0,
        max_waiting=4 * requests)
    engine.warm()
    # a fresh ledger + sampler: the bench prices the default plane, not
    # whatever state earlier configs left in the process globals
    prev_ledger = _rl.get_request_ledger()
    prev_sampler = _tr.get_tail_sampler()
    sampler = _tr.TailSampler()
    _tr.set_tail_sampler(sampler)
    _rl.set_request_ledger(_rl.RequestLedger(2048, sampler=sampler))
    _rl.set_ledger_enabled(True)
    engine.start()
    try:
        prompt = np.arange(1, prompt_len + 1, dtype=np.int32) % vocab

        def window():
            t0 = time.perf_counter()
            handles = [engine.submit(prompt,
                                     max_new_tokens=max_new_tokens)
                       for _ in range(requests)]
            for h in handles:
                h.result(timeout=60)
            return time.perf_counter() - t0

        window()  # scheduler + cache warm
        rounds += rounds % 2
        round_diffs, bare_s = [], []
        gc.collect()
        gc.disable()
        try:
            for i in range(rounds):
                if i % 2 == 0:
                    _rl.set_ledger_enabled(False)
                    bm = window()
                    _rl.set_ledger_enabled(True)
                    am = window()
                else:
                    _rl.set_ledger_enabled(True)
                    am = window()
                    _rl.set_ledger_enabled(False)
                    bm = window()
                bare_s.append(bm)
                round_diffs.append((am - bm) / bm * 100.0)
        finally:
            gc.enable()
            _rl.set_ledger_enabled(True)
        pair_diffs = [(round_diffs[k] + round_diffs[k + 1]) / 2.0
                      for k in range(0, len(round_diffs), 2)]
        overhead_pct = max(0.0, _median(pair_diffs))
        total_tokens = requests * max_new_tokens
        steps_per_window = max(1, engine.steps // (2 * rounds + 1))

        # absolute per-record cost: begin + 3 annotates + finish with a
        # typical staging buffer (root + prefill + 4 decode legs)
        led = _rl.get_request_ledger()
        n_micro = 500
        t0 = time.perf_counter()
        for i in range(n_micro):
            cid = _tr.new_id()
            led.begin(cid, plane="generation", model="reqtrace",
                      priority="normal", admission="admitted")
            led.annotate(cid, slot=0, queue_wait_s=0.0, ttft_s=0.001)
            led.annotate(cid, deadline_s=30.0)
            led.annotate(cid, prompt_bucket=8)
            for k in range(6):
                _tr.record_span(f"leg{k}", trace_id=cid, start=0.0,
                                end=0.001)
            led.finish(cid, outcome="ok", status=200, tokens=16)
        record_us = (time.perf_counter() - t0) / n_micro * 1e6

        ledger_state = led.describe()
        info = {
            "rounds": rounds,
            "requests_per_window": requests,
            "tokens_per_window": total_tokens,
            "decode_steps_per_window": steps_per_window,
            "bare_window_ms": round(_median(bare_s) * 1e3, 2),
            "overhead_pct": round(overhead_pct, 3),
            "record_us": round(record_us, 2),
            "ledger_records": ledger_state["records"],
            "staged_now": ledger_state["staged"],
            # integrity gate: the always-on ledger + tail-staging plane
            # costs the serving step < 2%
            "gate_overhead_ok": bool(overhead_pct < 2.0),
            "converged": bool(overhead_pct < 2.0
                              and ledger_state["records"] > 0),
            "unit": "% serving-window overhead, always-on request "
                    "ledger + tail staging",
        }
        info["value"] = round(overhead_pct, 3)
        return info
    finally:
        engine.stop()
        _rl.set_ledger_enabled(True)
        _rl.set_request_ledger(prev_ledger)
        _tr.set_tail_sampler(prev_sampler)


def bench_timeseries(peak, *, requests=10, rounds=8, num_slots=2,
                     max_new_tokens=16, max_len=48, hidden=64,
                     num_layers=2, num_heads=2, vocab=128, prompt_len=5):
    """Historical telemetry tier benchmark (observability/timeseries +
    usage): what the armed mini-TSDB + usage-metering plane costs the
    serving hot path. Two priced components, gated together **< 2%**
    of serving step time:

    - the **usage sink**: one attribution call at every ledger finish
      (tenant/model account update) — armed-vs-disarmed serving-window
      A/B with adjacent-pair drift cancellation and GC off, the same
      protocol every other sub-1% host gate here uses (the sampler is
      killed via ``set_sampling_enabled(False)`` on both legs so its
      wakeups cannot alias the windows);
    - the **sampler scrape**: one full ``sample()`` pass (registry JSON
      walk into the tiered rings + the usage/capacity roll-up
      collectors, all due every pass) over the LIVE post-serving
      state, amortized at the finest-tier 1 s cadence — the same
      amortization the sentinel gate uses for its detector tick.

    The request ledger stays enabled on both A/B legs: its own cost is
    ``reqtrace``'s gate; this one prices the telemetry tier ON TOP of
    the always-on ledger. Absolute costs (per-record attribution and
    one scrape, both in µs) are reported so deployments can budget the
    cadence.

    ``peak`` (chip FLOPs) is unused: host-side overhead metrics.
    """
    import gc
    from statistics import median as _median

    import numpy as np

    from deeplearning4j_tpu.models.gpt import Gpt, GptConfig
    from deeplearning4j_tpu.observability import reqlog as _rl
    from deeplearning4j_tpu.observability import timeseries as _ts
    from deeplearning4j_tpu.observability import usage as _us
    from deeplearning4j_tpu.serving import GenerationEngine

    model = Gpt(GptConfig(
        vocab_size=vocab, hidden=hidden, num_layers=num_layers,
        num_heads=num_heads, intermediate=hidden * 4,
        max_position=max_len, dropout=0.0, attention_dropout=0.0))
    variables = model.init(seed=0)
    engine = GenerationEngine(
        model, variables, name="timeseries", num_slots=num_slots,
        max_len=max_len, max_new_tokens=max_new_tokens,
        idle_wait_s=0.001, temperature=0.0,
        max_waiting=4 * requests)
    engine.warm()
    # a fresh ledger (enabled both ways — its cost is reqtrace's gate,
    # not this one's) and a fresh store/meter pair wired exactly like
    # ModelServer wires them: sink at ledger finish, usage + capacity
    # collectors on the store, sampler at the finest-tier cadence
    prev_ledger = _rl.get_request_ledger()
    prev_sink = _rl.get_usage_sink()
    _rl.set_request_ledger(_rl.RequestLedger(2048))
    _rl.set_ledger_enabled(True)
    meter = _us.UsageMeter(max_accounts=64)
    store = _ts.TimeSeriesStore(interval_s=1.0, max_series=256)
    store.add_collector(meter.collect, every_s=1.0)
    evaluator = _us.CapacityEvaluator(store)
    store.add_collector(evaluator.collect, every_s=1.0)
    # sampler killed during the A/B legs: a 1 Hz scrape aliasing a
    # ~10 ms window would read as thousands of % — its true cost is
    # priced below, amortized at the cadence it actually runs at
    _ts.set_sampling_enabled(False)
    engine.start()
    try:
        prompt = np.arange(1, prompt_len + 1, dtype=np.int32) % vocab

        def window():
            t0 = time.perf_counter()
            handles = [engine.submit(prompt,
                                     max_new_tokens=max_new_tokens)
                       for _ in range(requests)]
            for h in handles:
                h.result(timeout=60)
            return time.perf_counter() - t0

        _rl.set_usage_sink(meter.on_record)
        window()  # scheduler + cache warm, and seeds the first accounts
        rounds += rounds % 2
        round_diffs, bare_s = [], []
        gc.collect()
        gc.disable()
        try:
            for i in range(rounds):
                if i % 2 == 0:
                    _rl.set_usage_sink(None)
                    bm = window()
                    _rl.set_usage_sink(meter.on_record)
                    am = window()
                else:
                    _rl.set_usage_sink(meter.on_record)
                    am = window()
                    _rl.set_usage_sink(None)
                    bm = window()
                bare_s.append(bm)
                round_diffs.append((am - bm) / bm * 100.0)
        finally:
            gc.enable()
            _rl.set_usage_sink(meter.on_record)
        pair_diffs = [(round_diffs[k] + round_diffs[k + 1]) / 2.0
                      for k in range(0, len(round_diffs), 2)]
        sink_pct = max(0.0, _median(pair_diffs))

        # absolute per-record attribution cost
        n_micro = 2000
        rec = {"model": "timeseries", "tenant": "bench",
               "plane": "generation", "outcome": "ok",
               "tokens": max_new_tokens, "prompt_len": prompt_len}
        t0 = time.perf_counter()
        for _ in range(n_micro):
            meter.on_record(rec)
        record_us = (time.perf_counter() - t0) / n_micro * 1e6

        # full sampler scrape over the live post-serving registry state
        # (all collectors due every pass via synthetic advancing clocks),
        # amortized at the finest-tier cadence
        _ts.set_sampling_enabled(True)
        anchor = time.time()
        ingested = store.sample(now=anchor)  # warm lazy bundles / caches
        t0 = time.perf_counter()
        n_scrapes = 50
        for k in range(n_scrapes):
            store.sample(now=anchor + (k + 1) * store.interval_s)
        sample_us = (time.perf_counter() - t0) / n_scrapes * 1e6
        scrape_pct = sample_us / (store.interval_s * 1e6) * 100.0

        total_pct = sink_pct + scrape_pct
        desc = store.describe()
        usage = meter.describe()
        info = {
            "rounds": rounds,
            "requests_per_window": requests,
            "bare_window_ms": round(_median(bare_s) * 1e3, 2),
            "sink_overhead_pct": round(sink_pct, 3),
            "record_us": round(record_us, 2),
            "sample_us": round(sample_us, 1),
            "scrape_pct_at_cadence": round(scrape_pct, 4),
            "samples_per_scrape": ingested,
            "tsdb_series": desc["series"],
            "tsdb_points": desc["points"],
            "usage_accounts": len(usage["tenants"]),
            "armed_overhead_pct": round(total_pct, 3),
            # integrity gate: the armed mini-TSDB + usage plane (sink
            # on the finish path + scrape at the 1 s cadence) costs the
            # serving step < 2%
            "gate_overhead_ok": bool(total_pct < 2.0),
            "converged": bool(total_pct < 2.0
                              and desc["series"] > 0
                              and desc["points"] > 0
                              and len(usage["tenants"]) > 0),
            "unit": "% serving-window overhead, armed mini-TSDB "
                    "sampler + usage metering",
        }
        info["value"] = round(total_pct, 3)
        return info
    finally:
        engine.stop()
        store.stop()
        _ts.set_sampling_enabled(True)
        _rl.set_usage_sink(prev_sink)
        _rl.set_request_ledger(prev_ledger)


def bench_cache(peak, *, n_threads=4, requests_per_thread=60,
                pool_size=24, zipf_a=1.5, dim=256, hidden=1024,
                depth=16, repeat_burst=20,
                prefix_requests=6, gen_hidden=128, gen_layers=3,
                gen_heads=4, gen_vocab=512, gen_max_len=96,
                gen_max_new=8):
    """Request & prefix caching benchmark (serving/cache.py +
    serving/prefixkv.py): what the caching tier buys on a realistic
    repeat-heavy mix. Three legs:

    1. **Goodput uplift** — N closed-loop clients draw payloads from a
       bounded pool with Zipf(a) popularity (a few payloads dominate —
       the retry/poll/shared-prompt shape) through real loopback HTTP
       against a deliberately compute-heavy MLP. The same mix runs once
       with `X-Cache-Bypass` on every request (cache-off baseline) and
       once against the armed response cache; gated on
       **goodput_on / goodput_off >= 2x**.
    2. **No-slot proof** — a burst of exact repeats against the warm
       cache must leave the device-batch counter EXACTLY flat: a cache
       hit is answered before admission takes a batch slot.
    3. **Prefix TTFT** — a GenerationEngine with prefix-KV reuse armed
       serves prompts sharing a long common prefix; client-measured
       TTFT on prefix hits (graft + suffix-feed) must beat cold
       prefills of the same total length.

    ``peak`` (chip FLOPs) is unused: end-to-end caching economics.
    """
    import threading

    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.models.gpt import Gpt, GptConfig
    from deeplearning4j_tpu.serving import (
        GenerationEngine,
        ModelRegistry,
        ModelServer,
        ServingClient,
        spec,
    )

    # --- leg 1+2: exact-match response cache over HTTP -----------------
    rng = np.random.default_rng(0)
    w0 = jnp.asarray(rng.normal(0, 0.05, (dim, hidden)), jnp.float32)
    wh = jnp.asarray(rng.normal(0, 0.05, (hidden, hidden)), jnp.float32)
    wo = jnp.asarray(rng.normal(0, 0.05, (hidden, 8)), jnp.float32)

    def forward(v, x):
        h = jnp.tanh(x @ v["w0"])
        for _ in range(depth):
            h = jnp.tanh(h @ v["wh"])
        return h @ v["wo"]

    registry = ModelRegistry()
    registry.register("zipf", forward, {"w0": w0, "wh": wh, "wo": wo},
                      input_spec=spec((dim,)), version="v1",
                      mode="batched", max_batch_size=8)
    server = ModelServer(registry, port=0, sentinel=False, cache=True)
    server.start(warm=True)
    try:
        pool = [rng.normal(size=(1, dim)).astype(np.float32)
                for _ in range(pool_size)]
        p = 1.0 / np.arange(1, pool_size + 1) ** zipf_a
        p /= p.sum()
        lock = threading.Lock()

        def window(bypass):
            latencies, broken = [], []
            barrier = threading.Barrier(n_threads + 1)

            def run(tid):
                draw = np.random.default_rng(100 + tid)
                client = ServingClient(server.url)
                picks = draw.choice(pool_size, size=requests_per_thread,
                                    p=p)
                barrier.wait()
                for k in picks:
                    t0 = time.monotonic()
                    try:
                        client.predict("zipf", pool[int(k)],
                                       cache_bypass=bypass,
                                       deadline_ms=30000)
                        with lock:
                            latencies.append(time.monotonic() - t0)
                    except Exception as e:  # noqa: BLE001 - any = bug
                        with lock:
                            broken.append(repr(e))

            threads = [threading.Thread(target=run, args=(t,))
                       for t in range(n_threads)]
            for t in threads:
                t.start()
            barrier.wait()
            t_start = time.monotonic()
            for t in threads:
                t.join()
            wall = time.monotonic() - t_start
            return len(latencies) / wall, broken

        goodput_off, broken_off = window(bypass=True)
        goodput_on, broken_on = window(bypass=False)
        uplift = goodput_on / max(goodput_off, 1e-9)
        cstate = server.response_cache.describe()

        # leg 2: a pure-repeat burst must not touch the device at all
        client = ServingClient(server.url)
        client.predict("zipf", pool[0])  # ensure the entry is resident
        dev_before = server.metrics.device_latency.summary(
            model="zipf")["count"]
        hits_before = server.response_cache.describe()["hits"]
        for _ in range(repeat_burst):
            client.predict("zipf", pool[0])
        dev_after = server.metrics.device_latency.summary(
            model="zipf")["count"]
        hits_after = server.response_cache.describe()["hits"]
        burst_hits = hits_after - hits_before
        burst_batches = dev_after - dev_before
    finally:
        server.stop()

    # --- leg 3: prefix-KV reuse TTFT ----------------------------------
    model = Gpt(GptConfig(
        vocab_size=gen_vocab, hidden=gen_hidden, num_layers=gen_layers,
        num_heads=gen_heads, intermediate=gen_hidden * 4,
        max_position=gen_max_len, dropout=0.0, attention_dropout=0.0))
    engine = GenerationEngine(
        model, model.init(seed=0), name="gpt", num_slots=2,
        max_len=gen_max_len, max_new_tokens=gen_max_new,
        idle_wait_s=0.002, temperature=0.0, prefix_cache=True,
        max_waiting=4 * prefix_requests)
    gserver = ModelServer(port=0, sentinel=False,
                          generators={"gpt": engine})
    gserver.start(warm=True)
    try:
        gclient = ServingClient(gserver.url)
        gdraw = np.random.default_rng(7)
        # prompts are one prompt-bucket plus one suffix token: a prefix
        # hit grafts the bucket-sized slab and feeds ONE token; a cold
        # prefill pads the whole prompt into the next bucket up
        pbucket = max(b for b in engine.prompt_buckets
                      if b + 1 < gen_max_len)
        plen = pbucket + 1

        def ttft(prompt):
            t0 = time.monotonic()
            for _tok in gclient.generate("gpt", prompt,
                                         temperature=0.0):
                return time.monotonic() - t0
            return time.monotonic() - t0

        # cold leg: every prompt has a DISTINCT prefix — no reuse ever
        cold = [ttft(gdraw.integers(0, gen_vocab - 1, size=plen))
                for _ in range(prefix_requests)]
        # hit leg: shared prefix, varied suffix token; the first request
        # publishes the slab and is excluded from the hit stats
        base = gdraw.integers(0, gen_vocab - 1, size=plen)
        ttft(base)
        hits = []
        for i in range(prefix_requests):
            pr = base.copy()
            pr[-1] = (int(pr[-1]) + 1 + i) % gen_vocab
            hits.append(ttft(pr))
        pstate = engine.prefix_cache.describe()
        ttft_cold_ms = float(np.median(cold) * 1e3)
        ttft_hit_ms = float(np.median(hits) * 1e3)
        ttft_ratio = ttft_hit_ms / max(ttft_cold_ms, 1e-9)
    finally:
        gserver.stop()

    info = {
        "offered_per_window": n_threads * requests_per_thread,
        "pool_size": pool_size, "zipf_a": zipf_a,
        "broken": len(broken_off) + len(broken_on),
        "goodput_off_rps": round(goodput_off, 1),
        "goodput_on_rps": round(goodput_on, 1),
        "goodput_uplift": round(uplift, 2),
        "cache_hits": cstate["hits"], "cache_misses": cstate["misses"],
        "burst_hits": burst_hits,
        "burst_device_batches": burst_batches,
        "prefix_hits": pstate["hits"],
        "prefix_len": pbucket,
        "ttft_cold_ms": round(ttft_cold_ms, 2),
        "ttft_prefix_hit_ms": round(ttft_hit_ms, 2),
        "ttft_ratio": round(ttft_ratio, 3),
        "compiles_after_warm": engine.compiles_after_warm,
        # integrity gates: >= 2x goodput on the Zipf mix, exact hits
        # consume ZERO batch slots, prefix hits measurably cut TTFT
        # with zero recompiles after warmup
        "gate_uplift_ok": bool(uplift >= 2.0),
        "gate_no_slot_ok": bool(burst_batches == 0
                                and burst_hits == repeat_burst),
        "gate_ttft_ok": bool(ttft_ratio < 0.9 and pstate["hits"]
                             >= prefix_requests),
        "converged": bool(
            uplift >= 2.0 and not broken_off and not broken_on
            and burst_batches == 0 and burst_hits == repeat_burst
            and ttft_ratio < 0.9 and pstate["hits"] >= prefix_requests
            and engine.compiles_after_warm == 0),
        "unit": "x goodput uplift, Zipf mix vs cache-off",
    }
    info["value"] = round(uplift, 2)
    return info


def bench_replay(peak, *, backends=3, rows=None, clients=6,
                 kill_at_s=0.2, speed_drill=10.0,
                 availability_slo=0.95, mttr_budget_s=8.0,
                 p99_budget_s=5.0, ready_timeout_s=180.0):
    """Ledger-driven traffic replay + scripted game-day
    (resilience/replay.py + gameday.py): the bundled reference trace
    (``resilience/reference_trace.json`` — 60 predict rows over ~6 s
    of Poisson arrivals, mixed critical/normal/batch priorities over
    three tenants; regenerate via ``synthesize_trace`` with seed 2026)
    replayed open-loop against a ``backends``-backend router fleet.
    Two legs:

    1. **Clean 1x replay** — arrival-faithful baseline: goodput,
       availability (gated exactly 1.0 — nothing is degraded), client
       p99, and open-loop send-lag fidelity.
    2. **10x game-day drill** — the same trace compressed 10x while
       one scripted act SIGKILLs a backend mid-replay; judged by the
       drill's own gates from the client-side ledger, cross-checked
       against the router's counters: zero critical-class failures,
       availability >= ``availability_slo``, kill->first-success MTTR
       <= ``mttr_budget_s``, client p99 <= ``p99_budget_s``, and the
       reconciliation row (fleet served >= client successes).

    Backends are subprocesses: a SIGKILL must take out a real process
    — an in-process backend cannot die under the router the way a
    host does. ``rows`` slices the trace's first N rows (CPU-integrity
    sizing). ``peak`` is unused: the metrics are resilience economics.
    """
    import textwrap

    from deeplearning4j_tpu.resilience import gameday as gd
    from deeplearning4j_tpu.resilience import replay as rp
    from deeplearning4j_tpu.serving import FleetRouter, RouterPolicy

    trace = rp.load_trace(os.path.join(
        os.path.dirname(rp.__file__), "reference_trace.json"))
    if rows is not None:
        sliced = trace["rows"][:int(rows)]
        trace = rp.validate_trace(dict(
            trace, rows=sliced, count=len(sliced),
            duration_s=sliced[-1]["arrival_offset_s"]))

    script = textwrap.dedent("""
        import sys, time
        import jax
        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        from deeplearning4j_tpu.serving import (ModelRegistry,
                                                ModelServer, spec)

        def fwd(v, x):
            return jnp.zeros((x.shape[0], 1), jnp.float32) + v["scale"]

        reg = ModelRegistry()
        reg.register("scale", fwd, {"scale": 1.0}, input_spec=spec((4,)),
                     mode="batched", max_batch_size=8)
        srv = ModelServer(reg, port=int(sys.argv[1]), sentinel=False)
        srv.start(warm=True)
        print("READY", srv.port, flush=True)
        while True:
            time.sleep(3600)
    """)

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DL4J_TPU_FAULTS", None)
    ports = [free_port() for _ in range(backends)]
    procs = [subprocess.Popen([sys.executable, "-c", script, str(p)],
                              stdout=subprocess.PIPE, text=True, env=env)
             for p in ports]

    def await_ready(proc):
        deadline = time.monotonic() + ready_timeout_s
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                return False
            if line.startswith("READY"):
                return True
        return False

    router = None
    try:
        if not all(await_ready(p) for p in procs):
            raise RuntimeError("replay bench backend failed to start")
        policy = RouterPolicy(probe_interval_s=0.25, probe_timeout_s=0.5,
                              reprobe_after_s=0.5)
        router = FleetRouter(
            [(f"b{i}", f"http://127.0.0.1:{p}")
             for i, p in enumerate(ports)], policy=policy).start()

        # -- leg A: clean arrival-faithful replay at 1x --------------------
        clean = rp.ReplayDriver(router.url, trace, speed=1.0,
                                clients=clients).run()
        clean.pop("results")

        # -- leg B: 10x drill with one scripted SIGKILL --------------------
        victim = procs[1]

        def kill_victim():
            victim.kill()
            victim.wait(timeout=10)

        drill = gd.GameDay.from_script(
            {"name": "bench-replay-sigkill",
             "speed": speed_drill, "clients": clients,
             "acts": [{"at_s": kill_at_s, "kind": "kill",
                       "name": "kill-b1", "hook": "kill-b1"}],
             "gates": [
                 {"kind": "critical_failures", "max_count": 0},
                 {"kind": "availability", "min_ratio": availability_slo},
                 {"kind": "mttr", "max_s": mttr_budget_s},
                 {"kind": "p99", "max_s": p99_budget_s}]},
            base_url=router.url, trace=trace,
            hooks={"kill-b1": kill_victim},
            scrape_urls=[router.url], incident_urls=[router.url])
        report = drill.run()
    finally:
        if router is not None:
            router.stop()
        for p in procs:
            p.kill()
            p.wait(timeout=10)

    gates = {v["gate"]: v for v in report["gates"]}
    mttr_s = gates["mttr"]["value"]
    rep = report["replay"]
    recon = report["reconciliation"]
    info = {
        "trace_rows": trace["count"],
        "trace_duration_s": trace["duration_s"],
        "backends": backends,
        "clean_goodput_rps": clean["goodput_rps"],
        "clean_availability": clean["availability"],
        "clean_p99_s": clean["latency_p99_s"],
        "clean_max_send_lag_s": clean["max_send_lag_s"],
        "drill_speed": speed_drill,
        "drill_goodput_rps": rep["goodput_rps"],
        "drill_availability": rep["availability"],
        "drill_p99_s": rep["latency_p99_s"],
        "drill_retries": rep["retries"],
        "mttr_s": mttr_s,
        "drill_verdict": report["verdict"],
        "reconciliation_consistent": recon["consistent"],
        # integrity gates: the undisturbed 1x leg loses NOTHING, and
        # the SIGKILL drill passes every scripted gate with the
        # client-side ledger reconciling against the router's counters
        "gate_clean_ok": bool(clean["availability"] == 1.0),
        "gate_drill_ok": bool(report["verdict"] == "pass"),
        "converged": bool(clean["availability"] == 1.0
                          and report["verdict"] == "pass"
                          and recon["consistent"]),
        "unit": "s kill->first-success MTTR, 10x replay + SIGKILL",
    }
    info["value"] = (round(mttr_s, 3) if isinstance(mttr_s, (int, float))
                     else None)
    return info


def bench_autoscale(peak, *, rows=72, rate_rps=6.0, magnitude=6.0,
                    service_ms=150.0, clients=6,
                    capacity_budget_s=60.0, respawn_budget_s=60.0,
                    quiesce_timeout_s=90.0):
    """Fleet autoscaling under a flash crowd (serving/autoscaler.py +
    resilience/backendpool.py): a synthetic Poisson trace warped by
    ``warp_flash_crowd`` (the middle half's arrival gaps compressed
    ``magnitude``x) replayed against a ONE-backend subprocess fleet
    with the autoscaler armed. Three gates:

    1. **time-to-capacity** — the spike trips the overload hysteresis;
       scale-out decision -> the spawned backend's first ready probe
       (real process start + jax import + warmup + probe admission)
       <= ``capacity_budget_s``.
    2. **scale-to-zero** — traffic stops; sustained idle drains and
       retires EVERY backend (floor 0).
    3. **page-in respawn** — one cold request against the empty fleet
       parks at the router, pages a backend in, and is served by the
       respawn <= ``respawn_budget_s`` round-trip.

    Per-request service time is pinned at ``service_ms`` via the
    ``serving.latency`` injection point in the backend subprocesses,
    so one backend's capacity — and therefore the spike's overload —
    is deterministic. ``peak`` is unused: the metrics are control-loop
    economics.
    """
    import textwrap
    import threading

    import numpy as np

    from deeplearning4j_tpu.resilience import replay as rp
    from deeplearning4j_tpu.resilience.backendpool import (
        ProcessBackendLauncher,
    )
    from deeplearning4j_tpu.serving import (
        FleetRouter,
        RouterPolicy,
        ServingClient,
    )
    from deeplearning4j_tpu.serving.autoscaler import (
        Autoscaler,
        AutoscalerPolicy,
    )

    at_frac, width_frac = 0.5, 0.5
    base = rp.synthesize_trace({
        "n": int(rows), "rate_rps": float(rate_rps), "seed": 2026,
        "models": [{"name": "scale", "plane": "predict",
                    "payload_shape": [1, 4], "deadline_s": 30.0}]})
    trace = rp.warp_flash_crowd(base, at_frac=at_frac,
                                width_frac=width_frac,
                                magnitude=float(magnitude))
    # spike onset in the WARPED timeline: warping keeps row order, so
    # the first row whose PRE-warp arrival falls inside the window
    # marks where the compressed burst lands after the warp
    lo = (at_frac - width_frac / 2.0) * base["duration_s"]
    spike_lo_s = next(
        (w["arrival_offset_s"]
         for b, w in zip(base["rows"], trace["rows"])
         if b["arrival_offset_s"] >= lo), 0.0)

    script = textwrap.dedent("""
        import sys, time
        import jax
        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        from deeplearning4j_tpu.serving import (ModelRegistry,
                                                ModelServer, spec)

        def fwd(v, x):
            return jnp.zeros((x.shape[0], 1), jnp.float32) + v["scale"]

        reg = ModelRegistry()
        reg.register("scale", fwd, {"scale": 1.0}, input_spec=spec((4,)),
                     mode="batched", max_batch_size=8)
        srv = ModelServer(reg, port=int(sys.argv[1]), sentinel=False)
        srv.start(warm=True)
        while True:
            time.sleep(3600)
    """)

    def argv(name, port):
        return [sys.executable, "-c", script, str(port)]

    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        DL4J_TPU_FAULTS=("serving.latency%%1x1000000:%g"
                         % (float(service_ms) / 1000.0)))
    launcher = ProcessBackendLauncher(argv, env=env, grace_s=5.0)
    policy = RouterPolicy(probe_interval_s=0.25, probe_timeout_s=0.5,
                          reprobe_after_s=0.5, park_timeout_s=60.0)
    # empty-seeded + add_backend: the seed takes traffic only after a
    # genuine ready probe (the subprocess imports jax before binding)
    router = FleetRouter([], policy=policy).start()
    a = a2 = None
    try:
        router.add_backend("b0", launcher.spawn("b0"))
        a = Autoscaler(
            router, launcher,
            policy=AutoscalerPolicy(
                min_backends=1, max_backends=3, tick_interval_s=0.2,
                fire_after=2, clear_after=2, idle_fire_after=999999,
                cooldown_s=2.0, occupancy_high=1.0,
                backend_slot_target=4, dead_fire_after=3,
                spawn_grace_s=120.0)).attach()
        a._spawned_t["b0"] = a._clock()
        a._slot_of["b0"] = "b0"
        if not router.wait_routable("b0", timeout_s=180.0):
            raise RuntimeError("autoscale bench seed backend never ready")
        a.start()

        # -- leg A: flash crowd -> scale-out -> time-to-capacity -----------
        t_capacity = [None]
        stop_watch = threading.Event()

        def _watch():
            while not stop_watch.is_set():
                if sum(1 for b in router.backends if b.routable) >= 2:
                    t_capacity[0] = time.monotonic()
                    return
                time.sleep(0.05)

        watcher = threading.Thread(target=_watch, daemon=True)
        watcher.start()
        t_replay0 = time.monotonic()
        rep = rp.ReplayDriver(router.url, trace, speed=1.0,
                              clients=clients).run()
        rep.pop("results", None)
        scale_outs = [e for e in a.ledger()
                      if e["action"] == "scale_out" and e["executed"]]
        if scale_outs:
            watcher.join(timeout=capacity_budget_s)
        stop_watch.set()
        watcher.join(timeout=5.0)
        time_to_capacity_s = (
            t_capacity[0] - scale_outs[0]["mono"]
            if scale_outs and t_capacity[0] is not None else None)
        spike_to_capacity_s = (
            t_capacity[0] - (t_replay0 + spike_lo_s)
            if t_capacity[0] is not None else None)
        a.stop()

        # every live backend genuinely serving before the retire wave:
        # draining a still-warming spawn would measure its warmup, not
        # the scale-in plane
        deadline = time.monotonic() + quiesce_timeout_s
        while time.monotonic() < deadline:
            if router.backends and all(b.routable
                                       for b in router.backends):
                break
            time.sleep(0.1)
        fleet_peak = len(router.backends)

        # -- legs B+C: idle -> scale-to-zero -> page-in respawn ------------
        a2 = Autoscaler(
            router, launcher,
            policy=AutoscalerPolicy(
                min_backends=0, max_backends=3, tick_interval_s=0.2,
                fire_after=2, clear_after=2, idle_fire_after=2,
                cooldown_s=0.4, dead_fire_after=3,
                spawn_grace_s=120.0, scale_to_zero=True),
            metrics=a.metrics).attach()
        a2.start()
        deadline = time.monotonic() + quiesce_timeout_s
        while time.monotonic() < deadline and router.backends:
            time.sleep(0.1)
        scaled_to_zero = not router.backends
        respawn_s = page_in_value_ok = None
        if scaled_to_zero:
            c = ServingClient(router.url, max_retries=2)
            x = np.zeros((1, 4), np.float32)
            t0 = time.monotonic()
            out = c.predict("scale", x, deadline_ms=90000)
            respawn_s = time.monotonic() - t0
            page_in_value_ok = bool(out["outputs"][0][0] == 1.0)
        page_ins = [e for e in a2.ledger()
                    if e["action"] == "page_in" and e["executed"]]
    finally:
        for ctl in (a, a2):
            if ctl is not None:
                ctl.stop()
        router.stop()
        launcher.stop_all()

    gate_capacity = (time_to_capacity_s is not None
                     and time_to_capacity_s <= capacity_budget_s)
    gate_respawn = (respawn_s is not None
                    and respawn_s <= respawn_budget_s)
    info = {
        "trace_rows": trace["count"],
        "trace_duration_s": trace["duration_s"],
        "spike_magnitude": magnitude,
        "service_ms": service_ms,
        "availability": rep["availability"],
        "goodput_rps": rep["goodput_rps"],
        "p99_s": rep["latency_p99_s"],
        "scale_out_decisions": len(scale_outs),
        "fleet_peak": fleet_peak,
        "time_to_capacity_s": (round(time_to_capacity_s, 3)
                               if time_to_capacity_s is not None
                               else None),
        "spike_to_capacity_s": (round(spike_to_capacity_s, 3)
                                if spike_to_capacity_s is not None
                                else None),
        "scaled_to_zero": scaled_to_zero,
        "page_in_executions": len(page_ins),
        "respawn_s": (round(respawn_s, 3)
                      if respawn_s is not None else None),
        "page_in_value_ok": page_in_value_ok,
        # integrity gates: the spike provably grew the fleet within
        # budget, idle provably drained it to zero, and one cold
        # request provably paged capacity back in within budget
        "gate_capacity_ok": bool(gate_capacity),
        "gate_respawn_ok": bool(gate_respawn),
        "converged": bool(gate_capacity and gate_respawn
                          and scaled_to_zero
                          and page_in_value_ok
                          and rep["availability"] >= 0.95),
        "unit": "s scale-out decision -> new capacity routable",
    }
    info["value"] = info["time_to_capacity_s"]
    return info


def bench_fleetobs(peak, *, backends=2, overhead_rounds=6,
                   overhead_requests=30, window_requests=40, ab_rounds=6):
    """Fleet-observability benchmark (serving/router.py request ledger +
    span plane + cross-tier stitching): what the router's ALWAYS-ON
    observability tier costs the hop it instruments. Two gates, both
    on the PR 12 pairing methodology:

    - **Router-added p99 with the plane armed**: paired interleaved
      keep-alive rounds of the SAME request train direct-to-backend vs
      through an observability-ON router (zero per-row model cost so
      the hop — including ledger begin/finish, pick/attempt/request
      span staging, and the phase histogram — dominates). Gate: added
      p99 < 1 ms, with bench_router's jitter-floor guard (when the
      router-free leg's own p99 wobble exceeds 0.25 ms the robust
      paired-median added p50 < 1 ms carries the gate).
    - **Ledger-plane A/B at the router vantage**: the same keep-alive
      window timed with the router's observability toggled off/on,
      alternating order per round (adjacent-pair drift cancellation,
      GC off). Only the router's plane flips — the backends keep
      their own ledgers armed both ways, so the diff prices exactly
      the tier this PR added. Gate: overhead **< 2%** of the serving
      window.

    Also reported (evidence, not gated thresholds beyond liveness):
    the absolute per-record cost of a router ledger record with its
    3-span staging buffer in µs, one ``/debug/requests/<cid>``
    stitched-trace round-trip in ms, and the ``/debug/health`` fleet
    verdict with its shipped rule count.

    ``peak`` is unused: host-side overhead metrics.
    """
    import gc
    from statistics import median as _median

    import jax
    import numpy as np

    from deeplearning4j_tpu.observability import reqlog as _rl
    from deeplearning4j_tpu.observability import trace as _tr
    from deeplearning4j_tpu.serving import (
        FleetRouter,
        ModelRegistry,
        ModelServer,
        RouterPolicy,
        spec,
    )

    def make_backend():
        import jax.numpy as jnp

        def fwd(v, x):
            return jnp.zeros((x.shape[0], 1), jnp.float32)

        reg = ModelRegistry()
        reg.register("m", fwd, {"w": np.zeros(1, np.float32)},
                     input_spec=spec((4,)), version="v1", mode="batched",
                     max_batch_size=8, devices=jax.devices()[:1])
        srv = ModelServer(reg, port=0, slo_interval_s=3600.0,
                          sentinel=False)
        srv.start(warm=True)
        return srv

    import http.client as _hc

    class _KAClient:
        def __init__(self, url):
            host, port = url.split("//")[1].split(":")
            self.conn = _hc.HTTPConnection(host, int(port), timeout=10)
            self.body = json.dumps(
                {"inputs": [[0.0, 0.0, 0.0, 0.0]]}).encode()

        def predict(self, cid=None):
            headers = {"Content-Type": "application/json"}
            if cid:
                headers["X-Correlation-ID"] = cid
            self.conn.request("POST", "/v1/models/m:predict",
                              body=self.body, headers=headers)
            resp = self.conn.getresponse()
            raw = resp.read()
            if resp.status != 200:
                raise RuntimeError(f"predict {resp.status}: {raw[:120]!r}")

        def get(self, path):
            self.conn.request("GET", path)
            resp = self.conn.getresponse()
            return resp.status, resp.read()

        def close(self):
            self.conn.close()

    prev_enabled = _rl.ledger_enabled()
    _rl.set_ledger_enabled(True)  # the plane under test must be armed
    servers = [make_backend() for _ in range(backends)]
    policy = RouterPolicy(probe_interval_s=0.25, probe_timeout_s=0.5,
                          reprobe_after_s=0.5)
    router = FleetRouter(
        [(f"b{i}", s.url) for i, s in enumerate(servers)],
        policy=policy, observability=True).start()
    try:
        direct = _KAClient(servers[0].url)
        via = _KAClient(router.url)
        for c in (direct, via):
            for _ in range(10):
                c.predict()  # warm connections + code paths

        # -- gate 1: router-added latency, observability armed -------------
        d50, d99, r50, r99 = [], [], [], []
        gc_was = gc.isenabled()
        gc.disable()  # gen-2 pauses swamp sub-ms paired deltas
        try:
            for _ in range(overhead_rounds):
                for client, p50s, p99s in ((direct, d50, d99),
                                           (via, r50, r99)):
                    ls = []
                    for _ in range(overhead_requests):
                        t0 = time.monotonic()
                        client.predict()
                        ls.append(time.monotonic() - t0)
                    arr = np.sort(np.asarray(ls)) * 1e3
                    p50s.append(float(np.percentile(arr, 50)))
                    p99s.append(float(np.percentile(arr, 99)))

            added_p50_ms = max(0.0, float(np.median(
                np.asarray(r50) - np.asarray(d50))))
            added_p99_ms = max(0.0, float(np.median(
                np.asarray(r99) - np.asarray(d99))))
            direct_jitter_ms = float(np.median(np.abs(
                np.asarray(d99) - np.median(d99))))
            p99_gate_ok = added_p99_ms < 1.0 or (
                direct_jitter_ms > 0.25 and added_p50_ms < 1.0)

            # -- gate 2: the router plane's A/B at the router vantage ------
            # flipping router._observability (read per request) arms and
            # disarms ONLY the router's ledger+span tier; the module-
            # global switch would silence the backends' planes too and
            # the diff would price the wrong thing
            def window():
                t0 = time.perf_counter()
                for _ in range(window_requests):
                    via.predict()
                return time.perf_counter() - t0

            window()
            ab_rounds += ab_rounds % 2
            round_diffs, bare_s = [], []
            for i in range(ab_rounds):
                if i % 2 == 0:
                    router._observability = False
                    bm = window()
                    router._observability = True
                    am = window()
                else:
                    router._observability = True
                    am = window()
                    router._observability = False
                    bm = window()
                bare_s.append(bm)
                round_diffs.append((am - bm) / bm * 100.0)
        finally:
            if gc_was:
                gc.enable()
            router._observability = True
        pair_diffs = [(round_diffs[k] + round_diffs[k + 1]) / 2.0
                      for k in range(0, len(round_diffs), 2)]
        overhead_pct = max(0.0, _median(pair_diffs))

        # -- absolute per-record cost: one ledger record + the router's
        # typical 3-span staging buffer (pick + attempt + request),
        # offered to the router-owned sampler exactly as _RequestObs does
        led, sampler, tracer = router.reqlog, router._sampler, router.tracer
        n_micro = 500
        t0 = time.perf_counter()
        for i in range(n_micro):
            cid = _tr.new_id()
            led.begin(cid, plane="predict", model="m", priority="normal",
                      admission="admitted")
            led.annotate(cid, backend="b0", attempts=1, retries=0)
            for name in ("router.pick", "router.attempt", "router.request"):
                s = _tr.Span(name, trace_id=cid, span_id=_tr.new_id(),
                             start=0.0, end=0.001)
                if not sampler.offer(s):
                    tracer.record(s)
            led.finish(cid, outcome="ok", status=200)
        record_us = (time.perf_counter() - t0) / n_micro * 1e6

        # -- stitched-trace + fleet-health round-trips (liveness) ----------
        stitch_cid = "bench-fleetobs-stitch"
        via.predict(cid=stitch_cid)
        t0 = time.perf_counter()
        st_status, st_raw = via.get(f"/debug/requests/{stitch_cid}")
        stitch_ms = (time.perf_counter() - t0) * 1e3
        st_doc = json.loads(st_raw) if st_status == 200 else {}
        stitch_ok = (st_status == 200 and "record" in st_doc
                     and "critical_path" in st_doc)
        h_status, h_raw = via.get("/debug/health")
        health = json.loads(h_raw) if h_status == 200 else {}
        health_rules = len(health.get("rules") or [])
        direct.close()
        via.close()

        ledger_state = router.reqlog.describe()
        info = {
            "backends": backends,
            "overhead_rounds": overhead_rounds,
            "requests_per_window": window_requests,
            "router_added_p50_ms": round(added_p50_ms, 3),
            "router_added_p99_ms": round(added_p99_ms, 3),
            "direct_p99_jitter_ms": round(direct_jitter_ms, 3),
            "bare_window_ms": round(_median(bare_s) * 1e3, 2),
            "overhead_pct": round(overhead_pct, 3),
            "record_us": round(record_us, 2),
            "stitch_ms": round(stitch_ms, 2),
            "stitch_backend_trace": st_doc.get("backend_trace"),
            "ledger_records": ledger_state["records"],
            "fleet_health_status": health.get("status"),
            "fleet_health_rules": health_rules,
            # the two ISSUE gates: router-added p99 < 1 ms with the
            # plane armed (jitter-floored), and the always-on router
            # ledger+span tier < 2% of the serving window — plus the
            # stitch/health endpoints answering with real documents
            "gate_added_p99_ok": bool(p99_gate_ok),
            "gate_overhead_ok": bool(overhead_pct < 2.0),
            "converged": bool(p99_gate_ok and overhead_pct < 2.0
                              and ledger_state["records"] > 0
                              and stitch_ok and health_rules >= 4),
            "unit": "% serving-window overhead, router ledger + span "
                    "plane armed",
        }
        info["value"] = round(overhead_pct, 3)
        return info
    finally:
        _rl.set_ledger_enabled(prev_enabled)
        router.stop()
        for s in servers:
            s.stop(drain=False)


_CONFIGS = {
    "bert": bench_bert,
    # Batch-size knee probe (no baseline row): how much of the remaining
    # b32 MFU gap is parallelism-bound.
    "bert_b64": lambda peak: bench_bert(peak, batch_size=64, iters=15,
                                        max_predictions=20),
    # Long-context leg: T=2048 crosses DL4J_TPU_FLASH_MIN_SEQ=1024, so the
    # encoder runs the Pallas flash-attention kernel inside the full model
    # (the shape class where XLA's O(T^2) score materialization loses —
    # BASELINE.md kernel A/B). P scales with T at the same 15% mask rate.
    "bert_long": lambda peak: bench_bert(peak, batch_size=4, seq_len=2048,
                                         iters=10, max_predictions=308),
    "resnet50": bench_resnet50,
    # Batch-size knee probe: same model, 4x the per-step work. No r3
    # baseline (baseline_pending); recorded to show how much of the b32
    # MFU gap is launch-bound vs intrinsic (BASELINE.md ResNet diagnosis).
    "resnet50_b128": lambda peak: bench_resnet50(peak, batch_size=128,
                                                 iters=10),
    "lstm": bench_lstm,
    "lenet": bench_lenet,
    # GPT causal-LM (decoder-only; first recorded r4 — no baseline row yet,
    # the first green driver value becomes the baseline per BASELINE.md).
    "gpt": bench_gpt,
    # End-to-end serving capacity through serving/ (HTTP + admission +
    # dynamic batching); first recorded round — no baseline row yet.
    "serving": bench_serving,
    # Overload discipline (serving/overload.py): critical-class goodput
    # and p99 at ~10x offered load through priority admission + AIMD +
    # brownout; gated on critical availability >= 99%.
    "overload": bench_overload,
    # Generative serving (serving/generation.py): tokens/sec at fixed
    # offered streaming load through continuous batching + bucketed KV
    # slabs, p99 time-to-first-token, slot occupancy; gated on zero
    # recompiles after warmup across mixed prefix lengths.
    "generation": bench_generation,
    # Fleet router (serving/router.py): aggregate goodput scaling
    # 1->3 local backends (~linear gated >= 2x), router-added p99
    # < 1 ms (paired medians, floored), and the backend_down MTTR
    # probe (eject < 2 s, re-admit on recovery).
    "router": bench_router,
    # Cold-start robustness (runtime/compilecache + serving/warmstart):
    # cold vs warm-restart time-to-ready through the persistent compile
    # cache + traffic-derived warmup manifest, gated on a >= 1.3x warm
    # speedup and zero recompiles after the first post-restart request.
    "warmstart": bench_warmstart,
    # Fault-tolerance path (resilience/ + serde integrity): verified
    # checkpoint save/verify/restore latency vs. snapshot size + recovery
    # wall-clock after an injected fault; first recorded round.
    "resilience": bench_resilience,
    # Telemetry self-cost (observability/): instrumented-vs-bare step
    # time, span enter/exit cost, registry render latency at 1k series.
    "observability": bench_observability,
    # Cluster robustness (resilience/cluster+supervisor, serving worker
    # supervision): serving failover MTTR after a killed worker, and the
    # armed watchdog/heartbeat plane's steady-state fit overhead (< 1%).
    "robustness": bench_robustness,
    # Cluster telemetry federation (observability/federation): exporter +
    # aggregator polling cost on a live training worker, gated < 2%/step.
    "federation": bench_federation,
    # Elastic degraded mode (resilience/supervisor shrink/probe/expand):
    # shrink MTTR (kill -> first post-shrink step) and expand disruption
    # (pause at the checkpoint boundary), both gated < 5 s.
    "elastic": bench_elastic,
    # Anomaly sentinel (observability/sentinel + hostsampler): the
    # always-on detection plane's cost — 20 Hz host stack sampler +
    # detector tick amortized at the 10 s cadence, gated < 2%/step.
    "sentinel": bench_sentinel,
    # Request ledger + tail-sampled tracing (observability/reqlog +
    # trace.TailSampler): the always-on per-request observability
    # plane's cost on the serving hot path, gated < 2% of step time.
    "reqtrace": bench_reqtrace,
    # Historical telemetry tier (observability/timeseries + usage): the
    # armed mini-TSDB sampler + usage-metering plane's cost on the
    # serving hot path, gated < 2% of step time.
    "timeseries": bench_timeseries,
    # Request & prefix caching tier (serving/cache + serving/prefixkv):
    # goodput uplift on a Zipf repeat mix vs cache-off (gated >= 2x),
    # exact hits proven to consume zero batch slots, and prefix-KV
    # TTFT reduction vs cold prefill at equal prompt length.
    "cache": bench_cache,
    # Ledger-driven traffic replay + scripted game-day (resilience/
    # replay + gameday): the bundled reference trace at 1x (clean
    # baseline) and 10x (drill) against a 3-backend subprocess router
    # fleet with one scripted SIGKILL act; goodput, availability,
    # kill->recovery MTTR and p99, judged by the drill's own gates
    # plus the ledger/fleet-counter reconciliation row.
    "replay": bench_replay,
    # Fleet autoscaling (serving/autoscaler.py + resilience/
    # backendpool.py): a flash-crowd-warped trace against a 1-backend
    # subprocess fleet with the autoscaler armed — time from the
    # scale-out decision to new capacity routable (gated), idle
    # drain-and-retire to zero, and the page-in respawn round trip for
    # one cold request against the empty fleet (gated).
    "autoscale": bench_autoscale,
    # Fleet observability tier (serving/router.py request ledger +
    # span plane + cross-tier stitching): router-added p99 with the
    # plane armed (< 1 ms, jitter-floored) and the always-on router
    # ledger+span tier's serving-window overhead (< 2%, adjacent-pair
    # A/B at the router vantage), plus per-record µs, one stitched
    # /debug/requests/<cid> round-trip, and the /debug/health verdict.
    "fleetobs": bench_fleetobs,
}

# Shrunken shapes for the CPU config-integrity fallback: prove every bench
# config's train step runs and reduces its loss even when the TPU is
# unreachable, so a dead relay never zeroes the round's entire perf record
# (VERDICT r3 Weak #5 / next-round #4a). No perf value is recorded from CPU.
_CPU_INTEGRITY = {
    "lenet": dict(batch_size=64, warmup=0, iters=8),
    "lstm": dict(batch_size=4, seq_len=32, hidden=64, warmup=0, iters=8),
    "bert": dict(batch_size=2, seq_len=32, warmup=0, iters=3),
    "resnet50": dict(batch_size=2, warmup=0, iters=3),
    "gpt": dict(batch_size=2, seq_len=32, warmup=0, iters=3, tiny=True),
    # serving reports "converged" = all requests served-or-typed-shed
    "serving": dict(n_threads=4, requests_per_thread=6, max_batch=8),
    # overload reports "converged" = critical availability >= 99% and
    # critical p99 inside its gate at ~6x offered load (smaller mix
    # than the 10x perf leg, same invariants)
    "overload": dict(critical_threads=2, normal_threads=3,
                     batch_threads=7, duration_s=3.0, max_in_flight=2,
                     max_batch=8),
    # generation reports "converged" = every stream completed, tokens
    # flowed, and zero recompiles after warmup (mixed prefix lengths)
    "generation": dict(n_clients=3, requests_per_client=2, num_slots=2,
                       max_new_tokens=8, max_len=32, hidden=64,
                       num_layers=2, num_heads=2, vocab=128,
                       prompt_lens=(3, 7)),
    # router reports "converged" = goodput scales >= 2x over 1->3
    # backends, router-added p99 < 1 ms, and the injected-outage MTTR
    # probe ejected < 2 s then re-admitted (same invariants as the
    # perf leg at a smaller offered load)
    "router": dict(backends=3, n_threads=6, requests_per_thread=8,
                   per_row_ms=15.0, overhead_rounds=4,
                   overhead_requests=20),
    # warmstart reports "converged" = warm restart reached ready
    # measurably faster than cold AND served its first post-restart
    # request with zero compiles (same gates as the perf leg — the
    # subprocess rounds are already CPU-sized)
    "warmstart": dict(),
    # resilience reports "converged" = faulted run recovered to the
    # fault-free step count
    "resilience": dict(sizes_mb=(1,), repeats=1, epochs=1),
    # observability reports "converged" = instrumentation overhead < 5%
    # AND diagnostics (evaluator + recorder) increment < 2%; 96 steps of
    # a ~2 ms step: this host's run-to-run jitter is ±30 µs/step, so
    # shorter/lighter windows read noise as overhead against the
    # ~35 µs/step instrumentation cost the gates actually police
    "observability": dict(steps=96, batch_size=128, hidden=1024,
                          span_n=500, series=128),
    # robustness reports "converged" = every injected worker kill healed
    # (MTTR measured) AND the armed supervision plane costs < 1%/step
    "robustness": dict(steps=96, batch_size=128, hidden=1024, rounds=10,
                       mttr_rounds=2, load_threads=2),
    # federation reports "converged" = exporter + aggregator polling a
    # 2-worker cohort costs the instrumented fit step < 2%
    "federation": dict(steps=96, batch_size=128, hidden=1024, rounds=10),
    # elastic reports "converged" = every round shrank AND re-expanded
    # with shrink MTTR and expand disruption inside their gates
    "elastic": dict(rounds=2),
    # sentinel reports "converged" = the always-on plane (20 Hz host
    # sampler + detector tick at the production cadence) costs the
    # instrumented fit step < 2%
    "sentinel": dict(steps=96, batch_size=128, hidden=1024, rounds=10),
    # reqtrace reports "converged" = the always-on ledger + tail-staging
    # plane costs the serving window < 2%
    "reqtrace": dict(requests=6, rounds=6, max_new_tokens=8, max_len=32),
    # timeseries reports "converged" = the armed mini-TSDB sampler +
    # usage metering plane costs the serving window < 2% AND the store
    # actually accumulated series/points and tenant accounts
    "timeseries": dict(requests=6, rounds=6, max_new_tokens=8,
                       max_len=32),
    # cache reports "converged" = >= 2x goodput on the Zipf mix vs
    # bypass, a pure-repeat burst consumed zero device batches, and
    # prefix hits beat cold prefills on TTFT with zero recompiles
    # (same gates as the perf leg at a smaller offered load)
    "cache": dict(n_threads=3, requests_per_thread=25, pool_size=10,
                  dim=128, hidden=1024, depth=16, repeat_burst=10,
                  prefix_requests=4, gen_hidden=64, gen_layers=2,
                  gen_heads=2, gen_vocab=128, gen_max_len=80,
                  gen_max_new=4),
    # replay reports "converged" = clean 1x leg availability exactly
    # 1.0 AND the 10x SIGKILL drill passes all scripted gates (zero
    # critical failures, availability >= SLO, MTTR and p99 in budget)
    # with the client ledger reconciling against the router counters
    # (first 24 trace rows, same invariants as the perf leg)
    "replay": dict(rows=24, clients=4),
    # autoscale reports "converged" = the flash crowd scaled the fleet
    # out within the capacity budget, sustained idle retired every
    # backend (scale-to-zero), and one cold request paged capacity
    # back in within the respawn budget with availability >= 95%
    # (same invariants as the perf leg over a shorter trace)
    "autoscale": dict(rows=36, rate_rps=6.0, clients=4),
    # fleetobs reports "converged" = router-added p99 < 1 ms with the
    # observability plane armed AND the router ledger+span tier costs
    # the serving window < 2% AND the stitch/health endpoints answer
    # (same invariants as the perf leg at a smaller offered load)
    "fleetobs": dict(backends=2, overhead_rounds=4, overhead_requests=15,
                     window_requests=12, ab_rounds=4),
}


def _quiesce_sentinel():
    """Stop the process-global host sampler between configs: a serving
    config's ModelServer starts it (by design it outlives the server),
    and its 20 Hz wakeups are scheduler noise the later sub-1% paired
    timing gates must not inherit. bench_sentinel builds its own."""
    try:
        from deeplearning4j_tpu.observability.hostsampler import (
            set_host_sampler,
        )

        set_host_sampler(None)
    except Exception:  # noqa: BLE001 - isolation is best-effort
        pass


def _cpu_evidence():
    """Run every config at tiny shapes on CPU; return integrity records."""
    import jax

    jax.config.update("jax_platforms", "cpu")  # env var alone cannot win
    global _FORCE_HOST_WINDOW
    _FORCE_HOST_WINDOW = True
    ev = {"platform": "cpu", "note": "config-integrity only; no perf values"}
    for name, kw in _CPU_INTEGRITY.items():
        info = {}
        try:
            _quiesce_sentinel()
            info = _CONFIGS[name](None, **kw)
            ev[name] = {k: info[k] for k in
                        ("loss_first", "loss_last", "decreasing", "iters")
                        if k in info}
            ev[name]["ok"] = bool(info.get("decreasing")
                                  or info.get("converged"))
        except Exception as e:  # noqa: BLE001 - record, keep going
            ev[name] = {"ok": False, "error": str(e)[:200]}
    return ev


def _cpu_kernel_parity():
    """Tiny interpret-mode Pallas-vs-XLA parity (kernel logic evidence)."""
    os.environ["DL4J_TPU_FORCE_PALLAS"] = "1"
    out = {}
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        from deeplearning4j_tpu.kernels.flash_attention import (
            flash_attention, reference_attention)

        r = np.random.default_rng(0)
        q, k, v = (jnp.asarray(r.normal(size=(1, 2, 128, 64)), jnp.float32)
                   for _ in range(3))
        of = flash_attention(q, k, v, causal=True, backend="pallas")
        orf = reference_attention(q, k, v, causal=True)
        err = float(jnp.max(jnp.abs(of - orf)) /
                    jnp.maximum(jnp.max(jnp.abs(orf)), 1e-6))
        out["flash_attention"] = {"max_rel_err": round(err, 6),
                                  "parity": bool(err < 2e-2)}
    except Exception as e:  # noqa: BLE001
        out["flash_attention"] = {"error": str(e)[:200]}
    finally:
        os.environ.pop("DL4J_TPU_FORCE_PALLAS", None)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs",
                    default="bert,resnet50,resnet50_b128,lstm,lenet,gpt,"
                            "serving,overload,generation,resilience,"
                            "observability,robustness,federation,elastic,"
                            "sentinel,reqtrace,timeseries,warmstart,"
                            "cache",
                    help="comma-separated subset of %s" % list(_CONFIGS))
    ap.add_argument("--kernels", action="store_true",
                    help="run the on-chip Pallas-vs-XLA kernel A/B instead")
    ap.add_argument("--canonical", action="store_true",
                    help="with --kernels: mark the table canonical "
                         "(requires a quiet host; recorded via loadavg)")
    ap.add_argument("--profile", metavar="DIR", default=None,
                    help="capture a jax.profiler trace of one timed window "
                         "per config into DIR and append a top-op table")
    args = ap.parse_args()

    diag = {}
    configs = {}
    try:
        _, init_diag = _init_backend()
        enable_compile_cache()
        diag.update(init_diag)
    except Exception as e:  # noqa: BLE001 - bench must always emit one line
        # TPU unreachable: the artifact still carries CPU-verified evidence
        # that every config trains and the kernel logic is sound, instead
        # of a bare error (VERDICT r3 next-round #4a). The evidence pass
        # itself is guarded — "bench must always emit one line" holds even
        # if jax is too broken to run on CPU.
        try:
            evidence = _cpu_evidence()
        except Exception as ev_e:  # noqa: BLE001
            evidence = {"error": str(ev_e)[:200]}
        try:
            kparity = _cpu_kernel_parity()
        except Exception as kp_e:  # noqa: BLE001
            kparity = {"error": str(kp_e)[:200]}
        print(json.dumps({
            "metric": "bert_base_mlm_train_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/sec/chip", "vs_baseline": 0.0,
            "error": str(e)[:300], **diag,
            "cpu_evidence": evidence,
            "cpu_kernel_parity": kparity,
        }))
        return

    if args.kernels:
        from kernels_ab import run_kernels_ab  # local module, repo root

        print(json.dumps(run_kernels_ab(diag, canonical=args.canonical)))
        return

    peak = peak_bf16_flops(diag.get("device_kind", "")) or None
    global _PROFILE_DIR
    for name in args.configs.split(","):
        name = name.strip()
        if not name:
            continue
        if args.profile:
            _PROFILE_DIR = os.path.join(args.profile, name)
        try:
            _quiesce_sentinel()
            info = _CONFIGS[name](peak)
            base = BASELINES.get(name)
            if base:
                info["vs_baseline"] = round(info["value"] / base, 3)
            if args.profile:
                try:
                    from deeplearning4j_tpu.train.profiling import analyze_trace

                    info["profile_top_ops"] = analyze_trace(_PROFILE_DIR, top=12)
                except Exception as e:  # noqa: BLE001
                    info["profile_error"] = str(e)[:200]
            configs[name] = info
        except Exception as e:  # noqa: BLE001 - keep other configs alive
            configs[name] = {"value": 0.0, "error": str(e)[:300]}
    _PROFILE_DIR = None

    # Pallas-vs-XLA kernel A/B (compiled on this chip): parity + speedup,
    # embedded so the driver's single bench invocation records it.
    kernels = None
    try:
        from kernels_ab import run_kernels_ab

        # A/B proof rows only: the block-size tune sweeps compile ~24 extra
        # kernel variants (minutes of wall) and are diagnostics, not proof —
        # they stay behind an explicit `--kernels` invocation.
        kernels = run_kernels_ab({}, include_tune=False)
        kernels.pop("metric", None)
    except Exception as e:  # noqa: BLE001
        kernels = {"error": str(e)[:300]}

    head = configs.get("bert", {})
    result = {
        "metric": "bert_base_mlm_train_tokens_per_sec_per_chip",
        "value": head.get("value", 0.0),
        "unit": "tokens/sec/chip",
        "vs_baseline": head.get(
            "vs_baseline",
            0.0 if "error" in head or not head else 1.0),
        "baseline_pending": BASELINES.get("bert") is None,
        "mfu": head.get("mfu"),
        "sync": "forced-host-materialization (axon block_until_ready is async)",
        **diag,
        "configs": configs,
        "kernels_ab": kernels,
    }
    if "error" in head:
        result["error"] = head["error"]
    print(json.dumps(result))


if __name__ == "__main__":
    main()
