"""Keras h5 import (↔ deeplearning4j-modelimport, SURVEY §2.7).

ref: org.deeplearning4j.nn.modelimport.keras.{KerasModelImport, KerasModel,
KerasSequentialModel, layers.**, Hdf5Archive} — ~60 per-layer mappers
translating Keras 1/2 h5 configs+weights to MLN/CG. Here the target is the
framework's config dataclasses (SequentialConfig/GraphConfig); the happy
difference from the reference is layout: Keras and this framework are both
channels-last with (in, out) dense kernels and HWIO conv kernels, so most
weights copy through unchanged (the reference had to transpose everything
into its NCHW/(out,in) conventions).

Supports the Keras-3 legacy-h5 format written by the environment's
tensorflow (`model.save("m.h5")`): `model_config` JSON attr + per-layer
weight groups. Sequential and Functional topologies; functional merge
layers map to GraphVertex kinds.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.nn import layers as L
from deeplearning4j_tpu.nn.config import (
    GraphConfig,
    GraphVertex,
    NeuralNetConfiguration,
    SequentialConfig,
)
from deeplearning4j_tpu.nn.layers.conv import (
    Conv1D,
    Conv2D,
    Conv3D,
    Cropping1D,
    Cropping2D,
    Deconv2D,
    DepthwiseConv2D,
    GlobalPooling,
    Pooling1D,
    Pooling2D,
    SeparableConv2D,
    Upsampling1D,
    Upsampling2D,
    ZeroPadding1D,
    ZeroPadding2D,
)
from deeplearning4j_tpu.nn.layers.core import (
    ActivationLayer,
    Dense,
    Dropout,
    Embedding,
    Flatten,
    Permute,
    PReLU,
    RepeatVector,
    Rescaling,
    Reshape,
)
from deeplearning4j_tpu.nn.layers.norm import BatchNorm, LayerNorm
from deeplearning4j_tpu.nn.layers.recurrent import (GRU, LSTM,
    ConvLSTM2D, SimpleRnn)


class KerasImportError(Exception):
    pass


_ACTIVATIONS = {
    "relu": "relu", "relu6": "relu6", "sigmoid": "sigmoid", "tanh": "tanh",
    "softmax": "softmax", "linear": "identity", "elu": "elu", "selu": "selu",
    "softplus": "softplus", "softsign": "softsign", "gelu": "gelu",
    "swish": "swish", "silu": "swish", "exponential": "exp",
    "hard_sigmoid": "hardsigmoid", "leaky_relu": "leakyrelu02",
    "mish": "mish",
}


def _act(name) -> str:
    if name is None:
        return "identity"
    if isinstance(name, dict):  # serialized Activation object
        name = name.get("class_name", "linear").lower()
    out = _ACTIVATIONS.get(str(name))
    if out is None:
        raise KerasImportError(f"unsupported Keras activation {name!r}")
    return out


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


def _padding(cfg) -> str:
    p = cfg.get("padding", "valid")
    if isinstance(p, str):
        return p.upper()
    raise KerasImportError(f"unsupported padding {p!r}")


# --- per-layer mappers -----------------------------------------------------
# mapper(cfg) -> (LayerConfig | None, weight_map) where weight_map maps our
# param name -> (keras weight suffix, transform fn | None). None layer means
# structural no-op (InputLayer).

def _dense(cfg):
    return Dense(units=cfg["units"], activation=_act(cfg.get("activation")),
                 use_bias=cfg.get("use_bias", True)), \
        {"W": ("kernel", None), "b": ("bias", None)}


def _conv2d(cfg):
    if cfg.get("data_format") not in (None, "channels_last"):
        raise KerasImportError("channels_first Conv2D not supported")
    return Conv2D(
        filters=cfg["filters"], kernel=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)), padding=_padding(cfg),
        dilation=_pair(cfg.get("dilation_rate", 1)),
        groups=cfg.get("groups", 1),
        activation=_act(cfg.get("activation")),
        use_bias=cfg.get("use_bias", True),
    ), {"W": ("kernel", None), "b": ("bias", None)}


def _conv1d(cfg):
    return Conv1D(
        filters=cfg["filters"], kernel=cfg["kernel_size"][0]
        if isinstance(cfg["kernel_size"], (list, tuple)) else cfg["kernel_size"],
        stride=cfg.get("strides", [1])[0] if isinstance(cfg.get("strides", 1), (list, tuple))
        else cfg.get("strides", 1),
        padding=_padding(cfg), activation=_act(cfg.get("activation")),
        use_bias=cfg.get("use_bias", True),
    ), {"W": ("kernel", None), "b": ("bias", None)}


def _depthwise(cfg):
    return DepthwiseConv2D(
        depth_multiplier=cfg.get("depth_multiplier", 1),
        kernel=_pair(cfg["kernel_size"]), stride=_pair(cfg.get("strides", 1)),
        padding=_padding(cfg), activation=_act(cfg.get("activation")),
        use_bias=cfg.get("use_bias", True),
        # keras 2 names it depthwise_kernel, keras 3 plain kernel
    ), {"W": (("depthwise_kernel", "kernel"), None), "b": ("bias", None)}


def _separable(cfg):
    return SeparableConv2D(
        filters=cfg["filters"], kernel=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)), padding=_padding(cfg),
        activation=_act(cfg.get("activation")),
        use_bias=cfg.get("use_bias", True),
    ), {"dW": ("depthwise_kernel", None), "pW": ("pointwise_kernel", None),
        "b": ("bias", None)}


def _pool(kind):
    def mapper(cfg):
        return Pooling2D(
            pool_type=kind, window=_pair(cfg.get("pool_size", 2)),
            stride=_pair(cfg.get("strides") or cfg.get("pool_size", 2)),
            padding=_padding(cfg),
        ), {}

    return mapper


def _global_pool(kind):
    def mapper(cfg):
        if cfg.get("data_format") not in (None, "channels_last"):
            raise KerasImportError(
                "channels_first global pooling not supported")
        return GlobalPooling(pool_type=kind,
                             keepdims=bool(cfg.get("keepdims"))), {}

    return mapper


def _batchnorm(cfg):
    axis = cfg.get("axis", -1)
    if isinstance(axis, list):
        axis = axis[0]
    # Our BatchNorm normalizes the LAST axis. Keras' axis counts the batch
    # dim, so a positive axis is channels-last iff it equals rank-1 — which
    # only the built model's shape inference knows. Stash the raw axis on
    # the layer; the import paths validate it post-build (r1 advisor: no
    # silent wrong-axis normalization; review r3: don't reject axis=2 on
    # rank-3 inputs where it IS the last axis).
    layer = BatchNorm(momentum=cfg.get("momentum", 0.99),
                      eps=cfg.get("epsilon", 1e-3))
    layer._keras_axis = axis
    return layer, {
        "gamma": ("gamma", None), "beta": ("beta", None),
        "state:mean": ("moving_mean", None),
        "state:var": ("moving_variance", None),
    }


def _check_bn_axis(layer, shape_nobatch, where: str) -> None:
    """Refuse channels-first normalization once the input rank is known —
    shared by every imported layer stashing ``_keras_axis`` (BatchNorm and
    the Normalization→Rescaling path); the error names the layer type.

    ``shape_nobatch`` excludes the batch dim, so the channels-last Keras
    axis index for this input is exactly ``len(shape_nobatch)``."""
    axis = getattr(layer, "_keras_axis", None)
    if axis is None or axis == -1:
        return
    last = len(shape_nobatch)
    if axis != last:
        raise KerasImportError(
            f"{type(layer).__name__} {where!r}: axis {axis} on "
            f"rank-{last + 1} input is channels-first; only channels-last "
            f"(axis=-1 or {last}) imports are supported")


def _layernorm(cfg):
    return LayerNorm(eps=cfg.get("epsilon", 1e-3)), {
        "gamma": ("gamma", None), "beta": ("beta", None)}


def _rescaling(cfg):
    scale = cfg.get("scale", 1.0)
    offset = cfg.get("offset", 0.0)
    if isinstance(scale, (list, tuple)) or isinstance(offset, (list, tuple)):
        raise KerasImportError(
            "Rescaling with per-channel scale/offset lists not supported")
    return Rescaling(scale=float(scale), offset=float(offset)), {}


def _normalization(cfg):
    # Adapted stats live as h5 weights (mean/variance/count); keras
    # epsilon 1e-7 matches Normalization.call's max(sqrt(var), eps).
    axis = cfg.get("axis", -1)
    if isinstance(axis, (list, tuple)):
        if len(axis) != 1:
            raise KerasImportError(
                f"Normalization over multiple axes {axis} not supported")
        axis = axis[0]
    if cfg.get("mean") is not None:
        # explicit-stats construction: keras stores mean/variance in the
        # CONFIG and creates no h5 weights
        mean = np.asarray(cfg["mean"], np.float32).reshape(-1)
        var = np.asarray(cfg["variance"], np.float32).reshape(-1)
        layer = Rescaling(invert=bool(cfg.get("invert", False)), eps=1e-7,
                          mean=[float(v) for v in mean],
                          var=[float(v) for v in var])
        layer._keras_axis = axis
        return layer, {}
    layer = Rescaling(invert=bool(cfg.get("invert", False)), eps=1e-7,
                      stats=True)
    # channels-last post-build check shared with BatchNorm (broadcast is
    # against the LAST axis here too)
    layer._keras_axis = axis
    return layer, {"state:mean": ("mean", None),
                   "state:var": ("variance", None)}


def _lstm(cfg):
    # forget_bias=0: keras' unit_forget_bias is already baked into the
    # saved bias vector; adding our layer's runtime forget_bias on top
    # would double it.
    layer = LSTM(units=cfg["units"],
                 return_sequences=cfg.get("return_sequences", False),
                 forget_bias=0.0)
    if _act(cfg.get("activation", "tanh")) != "tanh" or \
            cfg.get("recurrent_activation", "sigmoid") != "sigmoid":
        raise KerasImportError(
            "LSTM with non-default activations (incl. hard_sigmoid "
            "recurrent) does not match this framework's tanh/sigmoid cell")
    # keras gate order i,f,c,o == ours; unit_forget_bias already baked into b
    return layer, {"W": ("kernel", None), "RW": ("recurrent_kernel", None),
                   "b": ("bias", None)}


def _gru_reorder(w):
    """keras gate order z,r,h → ours r,z,n (blocks along last dim)."""
    h = w.shape[-1] // 3
    z, r, n = w[..., :h], w[..., h:2 * h], w[..., 2 * h:]
    return np.concatenate([r, z, n], axis=-1)


def _gru_bias(b):
    """keras reset_after bias [2, 3h] (input+recurrent). Our cell folds a
    single bias; only the input-side bias maps exactly — require the
    recurrent side to be ~0 (true for freshly-initialized and many trained
    nets; otherwise refuse rather than import wrong math)."""
    if b.ndim == 2:
        if np.abs(b[1]).max() > 1e-6:
            raise KerasImportError(
                "GRU with nonzero recurrent bias cannot be mapped exactly "
                "onto this framework's reset-after GRU cell; fold the "
                "recurrent bias into the input bias before export, or "
                "rebuild the layer natively")
        b = b[0]
    return _gru_reorder(b)


def _gru(cfg):
    if not cfg.get("reset_after", True):
        # keras reset_after=False applies the reset gate BEFORE the
        # recurrent projection; our cell (cuDNN variant) applies it after —
        # different math whenever r != 1, so refuse.
        raise KerasImportError(
            "GRU(reset_after=False) does not match this framework's "
            "reset-after GRU cell; re-export with reset_after=True")
    return GRU(units=cfg["units"],
               return_sequences=cfg.get("return_sequences", False)), {
        "W": ("kernel", _gru_reorder),
        "RW": ("recurrent_kernel", _gru_reorder),
        "b": ("bias", _gru_bias),
    }


def _simple_rnn(cfg):
    return SimpleRnn(units=cfg["units"],
                     return_sequences=cfg.get("return_sequences", False),
                     activation=_act(cfg.get("activation", "tanh"))), {
        "W": ("kernel", None), "RW": ("recurrent_kernel", None),
        "b": ("bias", None)}


def _conv_lstm2d(cfg):
    """↔ KerasConvLSTM2D. Gate order i,f,c,o and HWIO kernels match the
    native ConvLSTM2D layer verbatim; keras' unit_forget_bias is baked
    into the saved bias (unit_forget_bias=False stops init re-adding it).
    Train-time dropout/recurrent_dropout fields are inference no-ops and
    are ignored, as the reference importer does."""
    if cfg.get("data_format") not in (None, "channels_last"):
        raise KerasImportError("channels_first ConvLSTM2D not supported")
    if _pair(cfg.get("dilation_rate", 1)) != (1, 1):
        raise KerasImportError("dilated ConvLSTM2D not supported")
    if cfg.get("go_backwards"):
        raise KerasImportError("ConvLSTM2D(go_backwards=True) not supported")
    return ConvLSTM2D(
        filters=cfg["filters"], kernel=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)), padding=_padding(cfg),
        activation=_act(cfg.get("activation", "tanh")),
        recurrent_activation=_act(cfg.get("recurrent_activation", "sigmoid")),
        use_bias=cfg.get("use_bias", True), unit_forget_bias=False,
        return_sequences=cfg.get("return_sequences", False),
    ), {"W": ("kernel", None), "RW": ("recurrent_kernel", None),
        "b": ("bias", None)}


def _locally_connected2d(cfg):
    """↔ KerasLocallyConnected2D (keras-2 layer; removed in keras 3).

    keras impl-1 kernel is [oh*ow, kh*kw*c, f] with the patch axis
    (kh, kw, c) row-major; our LocallyConnected2D stores [oh, ow,
    c*kh*kw, f] with the patch C-major (lax conv_general_dilated_patches
    convention) — the transform splits + permutes, using the input shape
    to recover (oh, ow) from the flat output-position axis.
    """
    if cfg.get("data_format") not in (None, "channels_last"):
        raise KerasImportError("channels_first LocallyConnected2D "
                               "not supported")
    if cfg.get("implementation", 1) != 1:
        raise KerasImportError(
            "LocallyConnected2D implementation != 1 stores a different "
            "kernel layout; re-save with implementation=1")
    kh, kw = _pair(cfg["kernel_size"])
    layer = L.LocallyConnected2D(
        filters=cfg["filters"], kernel=(kh, kw),
        stride=_pair(cfg.get("strides", 1)), padding=_padding(cfg),
        activation=_act(cfg.get("activation")),
        use_bias=cfg.get("use_bias", True))

    def kernel_t(arr, input_shape):
        oh, ow, _f = layer.output_shape(input_shape)
        c = arr.shape[1] // (kh * kw)
        w = arr.reshape(oh, ow, kh, kw, c, cfg["filters"])
        w = np.transpose(w, (0, 1, 4, 2, 3, 5))  # patch → C-major
        return w.reshape(oh, ow, c * kh * kw, cfg["filters"])

    return layer, {"W": ("kernel", _ShapeAware(kernel_t)),
                   "b": ("bias", None)}


def _locally_connected1d(cfg):
    """↔ KerasLocallyConnected1D. keras kernel [ot, k*c, f] with the patch
    (k, c) row-major; ours is [ot, c*k, f] C-major."""
    if cfg.get("implementation", 1) != 1:
        raise KerasImportError(
            "LocallyConnected1D implementation != 1 stores a different "
            "kernel layout; re-save with implementation=1")
    k = cfg["kernel_size"]
    k = k[0] if isinstance(k, (list, tuple)) else k
    stride = cfg.get("strides", 1)
    stride = stride[0] if isinstance(stride, (list, tuple)) else stride
    layer = L.LocallyConnected1D(
        filters=cfg["filters"], kernel=k, stride=stride,
        padding=_padding(cfg), activation=_act(cfg.get("activation")),
        use_bias=cfg.get("use_bias", True))

    def kernel_t(arr):
        ot = arr.shape[0]  # output positions are the leading axis already
        c = arr.shape[1] // k
        w = arr.reshape(ot, k, c, cfg["filters"])
        return np.transpose(w, (0, 2, 1, 3)).reshape(
            ot, c * k, cfg["filters"])

    return layer, {"W": ("kernel", kernel_t), "b": ("bias", None)}


def _embedding(cfg):
    return Embedding(vocab_size=cfg["input_dim"], units=cfg["output_dim"]), {
        "W": ("embeddings", None)}


def _activation(cfg):
    return ActivationLayer(activation=_act(cfg.get("activation"))), {}


def _dropout(cfg):
    return Dropout(rate=cfg.get("rate", 0.5)), {}


def _flatten(cfg):
    return Flatten(), {}


def _reshape(cfg):
    return Reshape(target_shape=list(cfg["target_shape"])), {}


def _flat4(v) -> Tuple[int, int, int, int]:
    """Keras padding/cropping (int | (h,w) | ((t,b),(l,r))) → flat
    (top, bottom, left, right)."""
    if isinstance(v, int):
        return (v, v, v, v)
    a, b = v
    if isinstance(a, int):
        return (a, a, b, b)
    return (a[0], a[1], b[0], b[1])


def _zeropad(cfg):
    return ZeroPadding2D(padding=_flat4(cfg.get("padding", 1))), {}


def _upsample(cfg):
    if cfg.get("interpolation", "nearest") != "nearest":
        raise KerasImportError(
            "UpSampling2D interpolation != 'nearest' unsupported")
    s = cfg.get("size", 2)
    return Upsampling2D(scale=tuple(s) if isinstance(s, (list, tuple)) else s), {}


def _cropping(cfg):
    return Cropping2D(cropping=_flat4(cfg.get("cropping", 0))), {}


def _conv2d_transpose(cfg):
    if cfg.get("data_format") not in (None, "channels_last"):
        raise KerasImportError("channels_first Conv2DTranspose not supported")
    if _pair(cfg.get("dilation_rate", 1)) != (1, 1):
        raise KerasImportError("dilated Conv2DTranspose not supported")
    if cfg.get("output_padding") not in (None, [None, None]):
        raise KerasImportError(
            "Conv2DTranspose output_padding not supported")
    return Deconv2D(
        filters=cfg["filters"], kernel=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)), padding=_padding(cfg),
        activation=_act(cfg.get("activation")),
        use_bias=cfg.get("use_bias", True),
        # keras stores the transpose kernel (kh, kw, OUT, IN) with
        # gradient-of-conv semantics; our lax.conv_transpose takes
        # (kh, kw, IN, OUT) unflipped — so spatially flip + swap IO
        # (verified against tf.nn.conv2d_transpose for SAME/VALID, s=1/2)
    ), {"W": ("kernel",
              lambda w: np.ascontiguousarray(
                  w[::-1, ::-1].transpose(0, 1, 3, 2))),
        "b": ("bias", None)}


def _conv3d(cfg):
    if cfg.get("data_format") not in (None, "channels_last"):
        raise KerasImportError("channels_first Conv3D not supported")
    ks = cfg["kernel_size"]
    return Conv3D(
        filters=cfg["filters"],
        kernel=tuple(ks) if isinstance(ks, (list, tuple)) else ks,
        stride=tuple(cfg["strides"]) if isinstance(cfg.get("strides"),
                                                   (list, tuple))
        else cfg.get("strides", 1),
        padding=_padding(cfg), activation=_act(cfg.get("activation")),
        use_bias=cfg.get("use_bias", True),
    ), {"W": ("kernel", None), "b": ("bias", None)}


def _pool1d(kind):
    def mapper(cfg):
        if cfg.get("data_format") not in (None, "channels_last"):
            raise KerasImportError(
                f"channels_first {kind} 1D pooling not supported")

        def one(v, default):
            v = cfg.get(v) or default
            return v[0] if isinstance(v, (list, tuple)) else v

        return Pooling1D(
            pool_type=kind, window=one("pool_size", 2),
            stride=one("strides", cfg.get("pool_size", 2)),
            padding=_padding(cfg)), {}

    return mapper


def _adv_activation(name, alpha_keys=(), default=None):
    """alpha_keys: tried in order (keras 3 vs keras 2 config names)."""

    def mapper(cfg):
        alpha = default
        for k in alpha_keys:
            if cfg.get(k) is not None:
                alpha = float(cfg[k])
                break
        return ActivationLayer(activation=name, alpha=alpha), {}

    return mapper


def _relu_layer(cfg):
    if cfg.get("threshold"):
        raise KerasImportError("ReLU threshold != 0 not supported")
    if cfg.get("max_value") is not None:
        if float(cfg["max_value"]) == 6.0 and not cfg.get("negative_slope"):
            return ActivationLayer(activation="relu6"), {}
        raise KerasImportError("ReLU max_value != 6 not supported")
    if cfg.get("negative_slope"):
        return ActivationLayer(activation="leakyrelu",
                               alpha=float(cfg["negative_slope"])), {}
    return ActivationLayer(activation="relu"), {}


def _softmax_layer(cfg):
    if cfg.get("axis", -1) != -1:
        raise KerasImportError("Softmax over a non-last axis not supported")
    return ActivationLayer(activation="softmax"), {}


def _prelu(cfg):
    if cfg.get("shared_axes"):
        raise KerasImportError("PReLU shared_axes not supported")
    return PReLU(), {"alpha": ("alpha", None)}


def _noise(kind, key, default, as_stddev=False):
    def mapper(cfg):
        val = cfg.get(key, default)
        if as_stddev:
            return Dropout(rate=0.0, kind=kind, stddev=val), {}
        return Dropout(rate=val, kind=kind), {}

    return mapper


def _repeat_vector(cfg):
    return RepeatVector(n=cfg["n"]), {}


def _permute(cfg):
    return Permute(dims=tuple(cfg["dims"])), {}


def _zeropad1d(cfg):
    p = cfg.get("padding", 1)
    return ZeroPadding1D(padding=tuple(p) if isinstance(p, (list, tuple))
                         else p), {}


def _cropping1d(cfg):
    c = cfg.get("cropping", 1)
    return Cropping1D(cropping=tuple(c) if isinstance(c, (list, tuple))
                      else c), {}


def _upsampling1d(cfg):
    return Upsampling1D(size=cfg.get("size", 2)), {}


def _time_distributed(cfg):
    """TimeDistributed(inner): our Dense/Activation/Dropout already map over
    every leading axis, so the wrapper unwraps to the inner layer. Inner
    layers with spatial semantics (convs) would need real reshaping —
    refuse those."""
    inner = cfg.get("layer", {})
    cls = inner.get("class_name")
    if cls not in ("Dense", "Activation", "Dropout"):
        raise KerasImportError(
            f"TimeDistributed({cls}) not supported (Dense/Activation/"
            "Dropout unwrap; spatial inner layers need reshaping)")
    return LAYER_MAPPERS[cls](inner.get("config", {}))


def _dir_matcher(direction: str, suffix: str):
    """Full-path weight matcher for Bidirectional sub-layers: some path
    segment must start with '<direction>_' and the key must end with
    '/<suffix>'. Segment-anchored, not a bare substring: Keras names the
    sub-layers 'forward_<inner>'/'backward_<inner>', so for an inner layer
    itself named e.g. 'forward_enc' the backward path is
    'backward_forward_enc/...' — a substring 'forward_' test would match it
    and silently bind the forward params to the backward weights."""

    def match(key: str) -> bool:
        if not key.endswith("/" + suffix):
            return False
        return any(seg.startswith(f"{direction}_")
                   for seg in key.split("/"))

    match.optional = suffix in _OPTIONAL_SUFFIXES
    return match


def _bidirectional(cfg):
    """↔ KerasBidirectional: wraps LSTM/GRU/SimpleRNN; merge modes map to
    the Bidirectional layer's CONCAT/ADD/MUL/AVERAGE set."""
    inner = cfg.get("layer", {})
    cls = inner.get("class_name")
    if cls not in ("LSTM", "GRU", "SimpleRNN"):
        raise KerasImportError(f"Bidirectional({cls}) not supported")
    merge = cfg.get("merge_mode", "concat")
    merge_map = {"concat": "concat", "sum": "add", "mul": "mul",
                 "ave": "average"}
    if merge not in merge_map:
        raise KerasImportError(
            f"Bidirectional merge_mode={merge!r} not supported "
            "(concat/sum/mul/ave)")
    inner_layer, inner_map = LAYER_MAPPERS[cls](inner.get("config", {}))
    from deeplearning4j_tpu.nn.layers.recurrent import Bidirectional

    wmap = {}
    for ours, (sfx, transform) in inner_map.items():
        sfxs = (sfx,) if isinstance(sfx, str) else tuple(sfx)
        wmap[f"fwd/{ours}"] = (
            tuple(_dir_matcher("forward", s) for s in sfxs), transform)
        wmap[f"bwd/{ours}"] = (
            tuple(_dir_matcher("backward", s) for s in sfxs), transform)
    return Bidirectional(layer=inner_layer, merge=merge_map[merge]), wmap


def _masking(cfg):
    """↔ KerasMasking → MaskZeroLayer (the reference's mapping). Only
    mask_value=0.0 matches MaskZero semantics."""
    if float(cfg.get("mask_value", 0.0)) != 0.0:
        raise KerasImportError("Masking with mask_value != 0 not supported")
    from deeplearning4j_tpu.nn.layers.core import MaskZeroLayer

    return MaskZeroLayer(), {}


def _tuple3(v, default):
    if v is None:
        return (default,) * 3
    return tuple(v) if isinstance(v, (list, tuple)) else (int(v),) * 3


def _pool3d(kind):
    def mapper(cfg):
        if cfg.get("data_format") not in (None, "channels_last"):
            raise KerasImportError("channels_first 3D pooling not supported")
        from deeplearning4j_tpu.nn.layers.conv import Pooling3D

        window = _tuple3(cfg.get("pool_size"), 2)
        return Pooling3D(
            pool_type=kind, window=window,
            stride=_tuple3(cfg.get("strides"), window[0])
            if cfg.get("strides") is not None else window,
            padding=_padding(cfg)), {}

    return mapper


def _upsampling3d(cfg):
    from deeplearning4j_tpu.nn.layers.conv import Upsampling3D

    return Upsampling3D(scale=_tuple3(cfg.get("size"), 2)), {}


def _sym3(v, default=1):
    """Keras 3D padding/cropping config: int | [a,b,c] | [[lo,hi]x3] →
    our flat (d_lo, d_hi, h_lo, h_hi, w_lo, w_hi)."""
    if v is None:
        v = default
    if isinstance(v, int):
        return (v,) * 6
    out = []
    for item in v:
        if isinstance(item, (list, tuple)):
            out.extend([int(item[0]), int(item[1])])
        else:
            out.extend([int(item), int(item)])
    return tuple(out)


def _zeropad3d(cfg):
    from deeplearning4j_tpu.nn.layers.conv import ZeroPadding3D

    return ZeroPadding3D(padding=_sym3(cfg.get("padding"))), {}


def _cropping3d(cfg):
    from deeplearning4j_tpu.nn.layers.conv import Cropping3D

    return Cropping3D(cropping=_sym3(cfg.get("cropping"))), {}


LAYER_MAPPERS: Dict[str, Callable] = {
    "Dense": _dense,
    "Conv2D": _conv2d,
    "Convolution2D": _conv2d,
    "Conv1D": _conv1d,
    "DepthwiseConv2D": _depthwise,
    "SeparableConv2D": _separable,
    "MaxPooling2D": _pool("max"),
    "AveragePooling2D": _pool("avg"),
    "GlobalAveragePooling2D": _global_pool("avg"),
    "GlobalMaxPooling2D": _global_pool("max"),
    "GlobalAveragePooling1D": _global_pool("avg"),
    "BatchNormalization": _batchnorm,
    "LayerNormalization": _layernorm,
    "Rescaling": _rescaling,
    "Normalization": _normalization,
    "LSTM": _lstm,
    "GRU": _gru,
    "SimpleRNN": _simple_rnn,
    "ConvLSTM2D": _conv_lstm2d,
    "LocallyConnected2D": _locally_connected2d,
    "LocallyConnected1D": _locally_connected1d,
    "Embedding": _embedding,
    "Activation": _activation,
    "Dropout": _dropout,
    "SpatialDropout2D": _dropout,
    "Flatten": _flatten,
    "Reshape": _reshape,
    "ZeroPadding2D": _zeropad,
    "UpSampling2D": _upsample,
    "Cropping2D": _cropping,
    # --- breadth beyond the r2 set (≈ the reference's ~60-mapper surface)
    "Conv2DTranspose": _conv2d_transpose,
    "Convolution2DTranspose": _conv2d_transpose,
    "Conv3D": _conv3d,
    "Convolution3D": _conv3d,
    "MaxPooling1D": _pool1d("max"),
    "AveragePooling1D": _pool1d("avg"),
    "GlobalMaxPooling1D": _global_pool("max"),
    "LeakyReLU": _adv_activation("leakyrelu", ("negative_slope", "alpha"), 0.3),
    "ELU": _adv_activation("elu", ("alpha",), 1.0),
    "ThresholdedReLU": _adv_activation("thresholdedrelu", ("theta",), 1.0),
    "ReLU": _relu_layer,
    "Softmax": _softmax_layer,
    "PReLU": _prelu,
    "GaussianNoise": _noise("gaussian_noise", "stddev", 0.1, as_stddev=True),
    "GaussianDropout": _noise("gaussian_dropout", "rate", 0.5),
    "AlphaDropout": _noise("alpha", "rate", 0.5),
    "SpatialDropout1D": _dropout,
    "RepeatVector": _repeat_vector,
    "Permute": _permute,
    "ZeroPadding1D": _zeropad1d,
    "Cropping1D": _cropping1d,
    "UpSampling1D": _upsampling1d,
    "TimeDistributed": _time_distributed,
    "ActivityRegularization": lambda cfg: (
        ActivationLayer(activation="identity"), {}),
    # --- round-4 tail: wrappers, masking, the 3D family ---
    "Bidirectional": _bidirectional,
    "Masking": _masking,
    "MaxPooling3D": _pool3d("max"),
    "AveragePooling3D": _pool3d("avg"),
    "GlobalAveragePooling3D": _global_pool("avg"),
    "GlobalMaxPooling3D": _global_pool("max"),
    "UpSampling3D": _upsampling3d,
    "ZeroPadding3D": _zeropad3d,
    "Cropping3D": _cropping3d,
    "SpatialDropout3D": _dropout,
}

# functional merge layers → GraphVertex kinds
MERGE_KINDS = {
    "Add": "add", "Concatenate": "merge", "Multiply": "mul",
    "Average": "average", "Maximum": "max", "Minimum": "min",
    "Subtract": "subtract",
}


def register_keras_layer(class_name: str, mapper: Callable) -> None:
    """Custom-layer SPI (↔ KerasLayer.registerCustomLayer /
    KerasLayerUtils custom-layer registry).

    ``mapper(config_dict) -> (LayerConfig, weight_map)`` where weight_map
    maps our param names to (keras weight name, transform-or-None) — the
    same contract every built-in mapper follows. Registering an existing
    name overrides the built-in (the reference allows shadowing too).
    """
    LAYER_MAPPERS[class_name] = mapper


def _constraint(spec, *, keys):
    """One serialized keras constraint → nn.constraints config.

    ↔ KerasConstraintUtils — the reference maps keras kernel/bias
    constraints onto its LayerConstraint set on import so retraining the
    imported model keeps enforcing them. ``keys`` pins the constraint to
    the exact param it governed in keras (kernel_constraint → "W",
    bias_constraint → "b").
    """
    from deeplearning4j_tpu.nn import constraints as C

    name = spec.get("class_name")
    c = spec.get("config", {})
    axis = c.get("axis", 0)
    axis = axis[0] if isinstance(axis, list) and len(axis) == 1 else axis
    bias = "b" in keys
    if name == "MaxNorm":
        return C.MaxNorm(max_norm=c.get("max_value", 2.0), axis=axis,
                         apply_to_bias=bias, keys=keys)
    if name == "MinMaxNorm":
        return C.MinMaxNorm(min_norm=c.get("min_value", 0.0),
                            max_norm=c.get("max_value", 1.0),
                            rate=c.get("rate", 1.0), axis=axis,
                            apply_to_bias=bias, keys=keys)
    if name == "UnitNorm":
        return C.UnitNorm(axis=axis, apply_to_bias=bias, keys=keys)
    if name == "NonNeg":
        return C.NonNegative(apply_to_bias=bias, keys=keys)
    raise KerasImportError(f"unsupported keras constraint {name!r}")


def _attach_constraints(layer, cfg: dict):
    cons = []
    if cfg.get("kernel_constraint"):
        cons.append(_constraint(cfg["kernel_constraint"], keys=("W",)))
    if cfg.get("bias_constraint"):
        cons.append(_constraint(cfg["bias_constraint"], keys=("b",)))
    if cons and layer is not None:
        layer.constraints = cons
    return layer


def _map_layer(class_name: str, cfg: dict):
    if class_name == "InputLayer":
        return None, {}
    mapper = LAYER_MAPPERS.get(class_name)
    if mapper is None:
        raise KerasImportError(
            f"no mapper for Keras layer {class_name!r} "
            f"(supported: {sorted(LAYER_MAPPERS)}). Custom layers can be "
            "registered via register_keras_layer(class_name, mapper)")
    layer, wmap = mapper(cfg)
    return _attach_constraints(layer, cfg), wmap


# --- weights ---------------------------------------------------------------


def _layer_weights(h5file, layer_name: str) -> Dict[str, np.ndarray]:
    """Weight arrays for one layer, keyed by their last path component AND
    by their full path (":<idx>" stripped) — wrapper layers like
    Bidirectional have forward/backward weights whose last components
    collide, so their mappers match on the full path instead."""
    mw = h5file["model_weights"]
    if layer_name not in mw:
        return {}
    grp = mw[layer_name]
    names = [n if isinstance(n, str) else n.decode()
             for n in grp.attrs.get("weight_names", [])]
    out = {}
    for n in names:
        arr = np.asarray(grp[n])
        out[n.split("/")[-1].split(":")[0]] = arr
        out[n.split(":")[0]] = arr
    return out


# Suffixes allowed to be absent (use_bias=False, BN scale/center=False).
_OPTIONAL_SUFFIXES = {"bias", "gamma", "beta"}


class _ShapeAware:
    """Weight transform that additionally needs the layer's INPUT shape
    (LocallyConnected kernels: splitting the flat output-position axis into
    (oh, ow) takes the spatial dims only shape inference knows)."""

    needs_input_shape = True

    def __init__(self, fn):
        self.fn = fn

    def __call__(self, arr, input_shape):
        return self.fn(arr, input_shape)


def _fill_params(weight_map, kweights, layer_cls: str, input_shape=None):
    """weight_map entries: ours -> (suffixes, transform). A suffix may be a
    plain key, or a CALLABLE predicate matched against every available
    weight key (wrapper layers match on full paths this way). ``ours``
    containing '/' nests into sub-dicts (e.g. Bidirectional's fwd/W)."""
    params, state = {}, {}

    def put(tree, key, arr):
        parts = key.split("/")
        for p in parts[:-1]:
            tree = tree.setdefault(p, {})
        tree[parts[-1]] = arr

    for ours, (suffixes, transform) in weight_map.items():
        if isinstance(suffixes, str) or callable(suffixes):
            suffixes = (suffixes,)
        found = None
        for s in suffixes:
            if callable(s):
                found = next((k for k in kweights if s(k)), None)
            elif s in kweights:
                found = s
            if found is not None:
                break
        if found is None:
            if all((getattr(s, "optional", False) if callable(s)
                    else s in _OPTIONAL_SUFFIXES) for s in suffixes):
                continue
            # A required weight that didn't match would silently leave the
            # layer at its random initialization — refuse instead.
            raise KerasImportError(
                f"{layer_cls}: required weight {suffixes} not found in h5 "
                f"(available: {sorted(kweights)})")
        arr = kweights[found]
        if transform is not None:
            if getattr(transform, "needs_input_shape", False):
                if input_shape is None:
                    raise KerasImportError(
                        f"{layer_cls}: weight transform needs the layer "
                        "input shape but none was provided")
                arr = transform(arr, input_shape)
            else:
                arr = transform(arr)
        if ours.startswith("state:"):
            put(state, ours.split(":", 1)[1], arr)
        else:
            put(params, ours, arr)
    return params, state


def _input_shape_of(layer_cfg: dict) -> Optional[Tuple[int, ...]]:
    shape = layer_cfg.get("batch_shape") or layer_cfg.get("batch_input_shape")
    if shape is None:
        return None
    return tuple(d for d in shape[1:])


# --- entry points ----------------------------------------------------------


def import_keras_model(path, *, updater=None):
    """↔ KerasModelImport.importKerasSequentialModel/importKerasModel.

    Returns (model, variables): a SequentialModel or GraphModel plus the
    imported {params, state} pytree ready for model.apply.
    """
    import h5py

    with h5py.File(path, "r") as f:
        if "model_config" not in f.attrs:
            raise KerasImportError("h5 file has no model_config attr "
                                   "(not a Keras model save?)")
        raw = f.attrs["model_config"]
        cfg = json.loads(raw if isinstance(raw, str) else raw.decode())
        if cfg["class_name"] == "Sequential":
            return _import_sequential(f, cfg["config"], updater)
        if cfg["class_name"] in ("Functional", "Model"):
            return _import_functional(f, cfg["config"], updater)
        raise KerasImportError(f"unknown model class {cfg['class_name']!r}")


def _import_sequential(f, config: dict, updater):
    from deeplearning4j_tpu.nn.model import SequentialModel

    layers, per_layer = [], []
    input_shape = None
    for ld in config["layers"]:
        lcfg = ld["config"]
        if input_shape is None:
            shp = _input_shape_of(lcfg)
            if shp is not None:
                input_shape = shp
        layer, wmap = _map_layer(ld["class_name"], lcfg)
        if layer is None:
            continue
        layer.name = lcfg.get("name")
        layers.append(layer)
        per_layer.append((lcfg.get("name"), ld["class_name"], wmap))
    if input_shape is None:
        raise KerasImportError("could not infer input shape from config")
    if any(d is None for d in input_shape):
        raise KerasImportError(
            f"input shape {input_shape} has unknown (None) dims beyond batch")

    net = NeuralNetConfiguration(updater=updater)
    model = SequentialModel(SequentialConfig(
        net=net, layers=layers, input_shape=input_shape))
    for i, layer in enumerate(model.layers):
        _check_bn_axis(layer, model.shapes[i], model.layer_names[i])

    params, state = {}, {}
    for i, (model_name, (kname, kcls, wmap)) in enumerate(
            zip(model.layer_names, per_layer)):
        kweights = _layer_weights(f, kname)
        p, s = _fill_params(wmap, kweights, kcls,
                            input_shape=model.shapes[i])
        if p:
            params[model_name] = p
        if s:
            state[model_name] = s
    # layers without imported weights (pool/flatten/...) own no params.
    variables = _merge_with_init(model, params, state)
    return model, variables


def _inbound_names(inbound_nodes) -> List[str]:
    """Input layer names from Keras inbound_nodes (keras 2 and 3 formats)."""
    names: List[str] = []

    def walk(obj):
        if isinstance(obj, dict):
            if obj.get("class_name") == "__keras_tensor__":
                names.append(obj["config"]["keras_history"][0])
                return
            for v in obj.values():
                walk(v)
        elif isinstance(obj, (list, tuple)):
            # keras2 triplets: ["layer", node_idx, tensor_idx, {...}]
            if (len(obj) >= 3 and isinstance(obj[0], str)
                    and isinstance(obj[1], int) and isinstance(obj[2], int)):
                names.append(obj[0])
                return
            for v in obj:
                walk(v)

    walk(inbound_nodes)
    return names


def _import_functional(f, config: dict, updater):
    from deeplearning4j_tpu.nn.model import GraphModel

    vertices: Dict[str, GraphVertex] = {}
    weight_info: Dict[str, Tuple[str, dict]] = {}
    inputs: List[str] = []
    input_shapes: Dict[str, Tuple[int, ...]] = {}

    for ld in config["layers"]:
        lcfg = ld["config"]
        name = lcfg.get("name")
        inbound = _inbound_names(ld.get("inbound_nodes", []))
        if ld["class_name"] == "InputLayer":
            shp = _input_shape_of(lcfg)
            if shp is None or any(d is None for d in shp):
                raise KerasImportError(f"input {name}: unknown shape {shp}")
            inputs.append(name)
            input_shapes[name] = shp
            continue
        if ld["class_name"] in MERGE_KINDS:
            vertices[name] = GraphVertex(kind=MERGE_KINDS[ld["class_name"]],
                                         inputs=inbound)
            continue
        layer, wmap = _map_layer(ld["class_name"], lcfg)
        layer.name = name
        vertices[name] = GraphVertex(kind="layer", inputs=inbound, layer=layer)
        weight_info[name] = (ld["class_name"], wmap)

    out_names = _inbound_names(config.get("output_layers", []))
    if not out_names:
        raise KerasImportError("functional model without output_layers")

    net = NeuralNetConfiguration(updater=updater)
    model = GraphModel(GraphConfig(
        net=net, inputs=inputs, input_shapes=input_shapes,
        vertices=vertices, outputs=out_names))
    for name, v in vertices.items():
        if v.kind == "layer" and v.layer is not None:
            # BatchNorm preserves shape: the vertex's output shape IS its
            # input shape, which is what the axis check needs.
            _check_bn_axis(v.layer, model.shapes[name], name)

    params, state = {}, {}
    for name, (kcls, wmap) in weight_info.items():
        v = vertices[name]
        in_shape = (model.shapes.get(v.inputs[0]) if v.inputs else None)
        p, s = _fill_params(wmap, _layer_weights(f, name), kcls,
                            input_shape=in_shape)
        if p:
            params[name] = p
        if s:
            state[name] = s
    variables = _merge_with_init(model, params, state)
    return model, variables


def _merge_with_init(model, params, state):
    """Initialize then overwrite with imported tensors — guarantees the
    variables pytree has exactly the structure model.apply expects, and
    shape-checks every imported array against it. Recurses into nested
    param groups (wrapper layers like Bidirectional's fwd/bwd)."""
    variables = model.init(seed=0)

    def merge(dst, src, path):
        for k, v in src.items():
            if k not in dst:
                raise KerasImportError(f"{path}: unexpected param {k!r}")
            if isinstance(v, dict):
                if not isinstance(dst[k], dict):
                    raise KerasImportError(
                        f"{path}.{k}: imported a group where the model "
                        "expects an array")
                merge(dst[k], v, f"{path}.{k}")
                continue
            want = np.asarray(dst[k]).shape
            if tuple(v.shape) != tuple(want):
                raise KerasImportError(
                    f"{path}.{k}: shape {v.shape} != expected {want}")
            dst[k] = np.asarray(v, np.asarray(dst[k]).dtype)

    for scope, src in (("params", params), ("state", state)):
        dst = variables[scope]
        for lname, ptree in src.items():
            if lname not in dst:
                raise KerasImportError(
                    f"imported weights for unknown layer {lname!r}")
            merge(dst[lname], ptree, lname)
    return variables
