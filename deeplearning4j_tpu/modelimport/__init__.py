"""Model import layer (↔ deeplearning4j-modelimport + samediff-import,
SURVEY §2.3/§2.7).

- keras: Keras h5 (sequential + functional) → SequentialModel/GraphModel
- tf: frozen TF GraphDef → autodiff SameDiff program (the BERT path)
- onnx: ONNX ModelProto → autodiff SameDiff program (dependency-free
  protobuf wire codec in onnx_proto)
"""

from deeplearning4j_tpu.modelimport.keras import (
    KerasImportError,
    import_keras_model,
)
from deeplearning4j_tpu.modelimport.onnx import (
    ONNXImportError,
    import_onnx_model,
)
from deeplearning4j_tpu.modelimport.tf import (
    TFImportError,
    import_tf_graph,
)

__all__ = [
    "import_keras_model",
    "KerasImportError",
    "import_tf_graph",
    "TFImportError",
    "import_onnx_model",
    "ONNXImportError",
]
