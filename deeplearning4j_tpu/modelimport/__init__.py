"""Model import layer (↔ deeplearning4j-modelimport + samediff-import,
SURVEY §2.3/§2.7).

- keras: Keras h5 (sequential + functional) → SequentialModel/GraphModel
- tf: frozen TF GraphDef → autodiff SameDiff program (the BERT path)
"""

from deeplearning4j_tpu.modelimport.keras import (
    KerasImportError,
    import_keras_model,
)
from deeplearning4j_tpu.modelimport.tf import (
    TFImportError,
    import_tf_graph,
)

__all__ = [
    "import_keras_model",
    "KerasImportError",
    "import_tf_graph",
    "TFImportError",
]
