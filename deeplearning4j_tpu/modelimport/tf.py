"""TF frozen-GraphDef import → SameDiff program (↔ samediff-import, SURVEY §2.3).

ref: nd4j/samediff-import-tensorflow (OpMappingRegistry, TensorflowImporter)
and the legacy org.nd4j.imports.graphmapper.tf.TFGraphMapper: per-op mapping
rules translate GraphDef nodes into SameDiff ops. Same architecture here —
a registry of per-op mappers targeting the autodiff.SameDiff graph — with
the TPU-era difference downstream: the imported graph compiles as ONE XLA
program (SameDiff.output / export_stablehlo) instead of running through the
per-op interpreter (SURVEY §3.2's BERT call stack collapses to one dispatch).

Oracle testing (SURVEY §4 pattern): tests freeze small tf.functions with
convert_variables_to_constants_v2 and compare this importer's outputs
against tensorflow's own execution of the same graph.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import (
    OP_REGISTRY,
    SameDiff,
    SDVariable,
    register_op,
)


class TFImportError(Exception):
    pass


# --- extra ops needed by TF graphs (registered under tfimport.*) -----------

def _register_tfimport_ops():
    import jax
    import jax.numpy as jnp

    def strided_slice(x, begin, end, strides, begin_mask=0, end_mask=0,
                      shrink_axis_mask=0, new_axis_mask=0, ellipsis_mask=0):
        if ellipsis_mask or new_axis_mask:
            raise NotImplementedError("ellipsis/new_axis in StridedSlice")
        idx = []
        for i in range(len(begin)):
            b = None if (begin_mask >> i) & 1 else begin[i]
            e = None if (end_mask >> i) & 1 else end[i]
            s = strides[i]
            if (shrink_axis_mask >> i) & 1:
                idx.append(begin[i])
            else:
                idx.append(slice(b, e, s))
        return x[tuple(idx)]

    def fused_batch_norm(x, scale, offset, mean, var, epsilon=1e-3):
        inv = scale * jax.lax.rsqrt(var + epsilon)
        return x * inv + (offset - mean * inv)

    def conv2d_tf(x, w, strides, padding, dilations=(1, 1, 1, 1)):
        return jax.lax.conv_general_dilated(
            x, w, window_strides=tuple(strides[1:3]), padding=padding,
            rhs_dilation=tuple(dilations[1:3]),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def depthwise_conv2d_tf(x, w, strides, padding, dilations=(1, 1, 1, 1)):
        kh, kw, c, m = w.shape
        w2 = w.reshape(kh, kw, 1, c * m)
        return jax.lax.conv_general_dilated(
            x, w2, window_strides=tuple(strides[1:3]), padding=padding,
            rhs_dilation=tuple(dilations[1:3]),
            dimension_numbers=("NHWC", "HWIO", "NHWC"), feature_group_count=c)

    def pool_tf(x, ksize, strides, padding, kind):
        import jax.numpy as jnp

        window = (1, ksize[1], ksize[2], 1)
        stride = (1, strides[1], strides[2], 1)
        if kind == "max":
            return jax.lax.reduce_window(
                x, -jnp.inf, jax.lax.max, window, stride, padding)
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, stride, padding)
        if padding == "VALID":
            return s / (ksize[1] * ksize[2])
        ones = jnp.ones_like(x)
        cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, stride, padding)
        return s / cnt

    def batch_matmul(a, b, adj_x=False, adj_y=False):
        if adj_x:
            a = jnp.swapaxes(a, -1, -2)
        if adj_y:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b)

    def matmul_t(a, b, transpose_a=False, transpose_b=False):
        if transpose_a:
            a = a.T
        if transpose_b:
            b = b.T
        return jnp.matmul(a, b)

    def pad_tf(x, paddings, constant_value=0.0):
        return jnp.pad(x, [tuple(p) for p in paddings], constant_values=constant_value)

    def split_v(x, num_or_sizes, axis):
        return tuple(jnp.split(x, num_or_sizes, axis=axis))

    def einsum_tf(*xs, equation):
        return jnp.einsum(equation, *xs)

    def cumsum_tf(x, axis=0, exclusive=False, reverse=False):
        if reverse:
            x = jnp.flip(x, axis)
        y = jnp.cumsum(x, axis=axis)
        if exclusive:
            y = y - x  # shift: sum of strictly-earlier elements
        if reverse:
            y = jnp.flip(y, axis)
        return y

    def top_k_tf(x, k):
        return tuple(jax.lax.top_k(x, k))

    def resize_tf(x, size, method):
        n, _, _, c = x.shape
        return jax.image.resize(x, (n, int(size[0]), int(size[1]), c),
                                method=method)

    def conv2d_backprop_input(w, dy, input_sizes, strides, padding):
        # transpose_kernel flips spatial + swaps I/O, making conv_transpose
        # exactly the gradient of conv2d — the op Conv2DBackpropInput is.
        return jax.lax.conv_transpose(
            dy, w, strides=tuple(strides[1:3]), padding=padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            transpose_kernel=True)

    def mirror_pad(x, paddings, mode="REFLECT"):
        return jnp.pad(x, [tuple(p) for p in paddings],
                       mode="reflect" if mode == "REFLECT" else "symmetric")

    def index_dyn(x, begin):
        # pure-index StridedSlice with traced (loop-var) indices: x[i, j]
        # lowers to dynamic_slice — static shapes, XLA-friendly
        return x[tuple(begin[i] for i in range(begin.shape[0]))]

    # TensorList ops (keras RNN / TensorArray loops): a TF TensorList of
    # static length and uniform element shape IS a dense [L, ...] array on
    # TPU — SetItem is a dynamic_update_slice, GetItem a dynamic_slice,
    # Stack/FromTensor the identity. No variant handles, no host objects.
    def list_get(handle, index):
        return handle[index]

    def list_set(handle, index, item):
        return handle.at[index].set(item)

    table = {
        "tfimport.einsum": einsum_tf,
        "tfimport.cumsum": cumsum_tf,
        "tfimport.top_k": top_k_tf,
        "tfimport.resize": resize_tf,
        "tfimport.conv2d_backprop_input": conv2d_backprop_input,
        "tfimport.mirror_pad": mirror_pad,
        "tfimport.strided_slice": strided_slice,
        "tfimport.fused_batch_norm": fused_batch_norm,
        "tfimport.conv2d": conv2d_tf,
        "tfimport.depthwise_conv2d": depthwise_conv2d_tf,
        "tfimport.max_pool": lambda x, ksize, strides, padding: pool_tf(
            x, ksize, strides, padding, "max"),
        "tfimport.avg_pool": lambda x, ksize, strides, padding: pool_tf(
            x, ksize, strides, padding, "avg"),
        "tfimport.batch_matmul": batch_matmul,
        "tfimport.matmul": matmul_t,
        "tfimport.pad": pad_tf,
        "tfimport.split": split_v,
        "tfimport.leaky_relu": lambda x, alpha=0.2: jax.nn.leaky_relu(x, alpha),
        "tfimport.squared_difference": lambda a, b: jnp.square(a - b),
        "tfimport.rsqrt": jax.lax.rsqrt,
        "tfimport.erf": jax.scipy.special.erf,
        "tfimport.select": lambda c, a, b: jnp.where(c, a, b),
        "tfimport.range": lambda start, limit, delta: jnp.arange(start, limit, delta),
        "tfimport.fill": lambda dims, value: jnp.full(tuple(dims), value),
        "tfimport.floor_div": jnp.floor_divide,
        "tfimport.floor_mod": jnp.mod,
        "tfimport.index_dyn": index_dyn,
        "tfimport.list_get": list_get,
        "tfimport.list_set": list_set,
        "tfimport.list_length": lambda x: jnp.int32(x.shape[0]),
    }
    for name, fn in table.items():
        register_op(name, fn)


_TFIMPORT_OPS_REGISTERED = False


def ensure_tfimport_ops():
    """Idempotent registration of the tfimport.* ops. Deferred from module
    import (avoids forcing jax init for Keras-only users); call this before
    replaying a previously-saved SameDiff graph that contains tfimport ops
    in a process that hasn't run import_tf_graph."""
    global _TFIMPORT_OPS_REGISTERED
    if not _TFIMPORT_OPS_REGISTERED:
        _register_tfimport_ops()
        _TFIMPORT_OPS_REGISTERED = True


# --- node attr helpers -----------------------------------------------------


def _attr(node, name, default=None):
    if name not in node.attr:
        return default
    a = node.attr[name]
    kind = a.WhichOneof("value")
    if kind == "i":
        return int(a.i)
    if kind == "f":
        return float(a.f)
    if kind == "b":
        return bool(a.b)
    if kind == "s":
        return a.s.decode()
    if kind == "list":
        if a.list.i:
            return [int(v) for v in a.list.i]
        if a.list.f:
            return [float(v) for v in a.list.f]
        if a.list.s:
            return [v.decode() for v in a.list.s]
        return []
    if kind == "type":
        return int(a.type)
    if kind == "shape":
        return [d.size for d in a.shape.dim]
    if kind == "tensor":
        return a.tensor
    return default


_TF_DTYPES = {
    1: "float32", 2: "float64", 3: "int32", 4: "uint8", 5: "int16",
    6: "int8", 9: "int64", 10: "bool", 14: "bfloat16", 19: "float16",
    22: "uint16", 23: "uint32",
}


def _np_dtype(tf_type: int) -> str:
    # "bfloat16" passes through: ml_dtypes registers it with numpy/jax, so
    # Cast/Placeholder keep real bfloat16 semantics.
    if tf_type not in _TF_DTYPES:
        raise TFImportError(f"unsupported TF dtype enum {tf_type}")
    return _TF_DTYPES[tf_type]


# --- the import ------------------------------------------------------------


# --- host constant folding --------------------------------------------------
# Frozen graphs from real exporters (tf.function + convert_to_constants of
# keras models) compute Reshape/BroadcastTo arguments with on-graph shape
# arithmetic: Shape → StridedSlice → Pack / Mul / ConcatV2. The Shape mapper
# records its host value; these folders propagate it so const_value()
# consumers succeed. Best-effort; never replaces the emitted graph ops.


def _tf_fold_strided_slice(node, arrs):
    x, begin, end, strides = (np.asarray(a) for a in arrs[:4])
    if _attr(node, "new_axis_mask", 0) or _attr(node, "ellipsis_mask", 0):
        raise ValueError("unhandled mask")
    bm = _attr(node, "begin_mask", 0)
    em = _attr(node, "end_mask", 0)
    sm = _attr(node, "shrink_axis_mask", 0)
    sl = []
    shrink = []
    for i in range(len(begin)):
        if (sm >> i) & 1:
            sl.append(slice(int(begin[i]), int(begin[i]) + 1
                            if int(begin[i]) != -1 else None, 1))
            shrink.append(i)
            continue
        b = None if (bm >> i) & 1 else int(begin[i])
        e = None if (em >> i) & 1 else int(end[i])
        sl.append(slice(b, e, int(strides[i])))
    out = x[tuple(sl)]
    for i in reversed(shrink):
        out = np.squeeze(out, axis=i)
    return out


def _tf_fold_cast(node, arrs):
    return arrs[0].astype(_np_dtype(_attr(node, "DstT", 1)))


_FOLD_SIZE_CAP = 4096


def _capped(arr):
    if arr.size > _FOLD_SIZE_CAP:
        raise ValueError("fold output exceeds size cap")
    return arr


def _capped_fill(dims, value):
    n = 1
    for d in dims:
        n *= max(int(d), 0)
    if n > _FOLD_SIZE_CAP:
        raise ValueError("fold output exceeds size cap")
    return np.full(dims, value)


def _fold_reduce(fn, node, arrs):
    axes = tuple(np.atleast_1d(arrs[1]).astype(int))
    if not axes:
        return arrs[0]
    return fn(arrs[0], axis=axes,
              keepdims=bool(_attr(node, "keep_dims", 0)))


_TF_HOST_FOLDABLE = {
    "Pack": lambda n, a: np.stack(a, axis=_attr(n, "axis", 0)),
    "ConcatV2": lambda n, a: np.concatenate(
        [np.atleast_1d(x) for x in a[:-1]], axis=int(np.asarray(a[-1]))),
    "StridedSlice": _tf_fold_strided_slice,
    "Slice": lambda n, a: a[0][tuple(
        slice(int(b), int(b) + int(s)) if int(s) != -1 else slice(int(b), None)
        for b, s in zip(np.asarray(a[1]).reshape(-1),
                        np.asarray(a[2]).reshape(-1)))],
    "GatherV2": lambda n, a: np.take(
        a[0], a[1].astype(np.int64),
        axis=int(np.asarray(a[2]).reshape(())) if len(a) > 2 else 0),
    "Add": lambda n, a: a[0] + a[1],
    "AddV2": lambda n, a: a[0] + a[1],
    "Sub": lambda n, a: a[0] - a[1],
    "Mul": lambda n, a: a[0] * a[1],
    "Maximum": lambda n, a: np.maximum(a[0], a[1]),
    "Minimum": lambda n, a: np.minimum(a[0], a[1]),
    "FloorDiv": lambda n, a: a[0] // a[1],
    "FloorMod": lambda n, a: a[0] % a[1],
    "Neg": lambda n, a: -a[0],
    "Cast": _tf_fold_cast,
    "Squeeze": lambda n, a: np.squeeze(
        a[0], axis=tuple(_attr(n, "squeeze_dims", []) or []) or None),
    "ExpandDims": lambda n, a: np.expand_dims(
        a[0], int(np.asarray(a[1]).reshape(()))),
    "Prod": lambda n, a: np.prod(
        a[0], axis=tuple(np.atleast_1d(a[1]).astype(int)),
        keepdims=bool(_attr(n, "keep_dims", 0))),
    # keras RNNs compute maximum_iterations as Max(T, range(0, rank=0)) —
    # host-folding it makes the While init a static constant, which the
    # samediff scan-lowering (counter-bounded loops -> lax.scan) needs.
    # Empty axes = identity reduction.
    "Max": lambda n, a: _fold_reduce(np.max, n, a),
    "Min": lambda n, a: _fold_reduce(np.min, n, a),
    # Range/Fill GROW output from tiny inputs — cap the result size too (a
    # frozen graph may Fill a [N,T,T] attention mask; advisory folding must
    # not allocate it on host)
    "Range": lambda n, a: _capped(np.arange(
        *(np.asarray(x).reshape(()) for x in a))),
    "Fill": lambda n, a: _capped_fill(
        [int(v) for v in np.asarray(a[0]).reshape(-1)],
        np.asarray(a[1]).reshape(())),
    "Reshape": lambda n, a: a[0].reshape(
        [int(v) for v in np.asarray(a[1]).reshape(-1)]),
}


class _GraphImporter:
    """Walks GraphDef nodes, emitting SameDiff ops via the mapper registry
    (↔ TFGraphMapper.importGraph)."""

    def __init__(self, graph_def, input_shapes: Dict[str, Tuple], sd: SameDiff):
        self.gd = graph_def
        self.sd = sd
        self.input_shapes = input_shapes
        self.vars: Dict[str, Any] = {}  # tf tensor name -> SDVariable
        self.consts: Dict[str, np.ndarray] = {}  # host-known constant values
        # name -> FunctionDef, for functional control flow (While/If attrs
        # reference these; ↔ the reference's TF import resolves function
        # bodies the same way, SURVEY §2.3)
        self.library = ({f.signature.name: f for f in graph_def.library.function}
                        if graph_def is not None else {})

    def tensor(self, ref: str) -> SDVariable:
        name = ref.split(":")[0].lstrip("^")
        idx = int(ref.split(":")[1]) if ":" in ref else 0
        v = self.vars.get(name)
        if v is None:
            raise TFImportError(f"tensor {ref!r} produced by unknown node")
        if isinstance(v, tuple):
            return v[idx]
        if idx != 0:
            raise TFImportError(f"node {name} has one output; wanted :{idx}")
        return v

    def const_value(self, ref: str) -> np.ndarray:
        """Host-side value of a constant input (shapes, perms, axes...)."""
        name = ref.split(":")[0]
        if name not in self.consts:
            raise TFImportError(
                f"op needs host-known constant for {ref!r}, but {name!r} "
                "is not a Const node")
        return self.consts[name]

    def _try_fold(self, node) -> None:
        """Best-effort host evaluation when every input is host-known (see
        _TF_HOST_FOLDABLE); failures leave the graph untouched. The size
        cap keeps weight-sized const chains off the fold path — shape math
        is tiny."""
        fold = _TF_HOST_FOLDABLE.get(node.op)
        if fold is None or node.name in self.consts:
            return
        refs = [r.split(":")[0].lstrip("^") for r in node.input
                if not r.startswith("^")]
        if not all(r in self.consts for r in refs):
            return
        if any(self.consts[r].size > 4096 for r in refs):
            return
        try:
            self.consts[node.name] = np.asarray(
                fold(node, [self.consts[r] for r in refs]))
        except Exception:  # noqa: BLE001 - folding is advisory only
            pass

    def _process_node(self, node) -> None:
        """Dispatch one NodeDef into the SameDiff graph. Shared by the
        top-level walk, FunctionDef bodies, and raised TF1 frame
        subgraphs."""
        from tensorflow.python.framework import tensor_util

        op = node.op
        if op == "Placeholder":
            shape = self.input_shapes.get(node.name)
            if shape is None:
                shape = _attr(node, "shape")
                if shape is None:
                    raise TFImportError(
                        f"placeholder {node.name} needs an input_shapes entry")
                shape = tuple(None if d in (-1, None) else d for d in shape)
            dtype = _np_dtype(_attr(node, "dtype", 1))
            self.vars[node.name] = self.sd.placeholder(
                node.name, shape, dtype)
        elif op == "Const":
            arr = tensor_util.MakeNdarray(node.attr["value"].tensor)
            self.consts[node.name] = arr
            self.vars[node.name] = self.sd.constant(
                _uniq(self.sd, node.name), arr)
        elif op in ("Identity", "StopGradient", "PreventGradient",
                    "CheckNumerics", "LoopCond"):
            self.vars[node.name] = self.tensor(node.input[0])
            # Const→Identity chains (grappler leaves these) must keep the
            # host-known value visible to shape/axis consumers.
            src = node.input[0].split(":")[0].lstrip("^")
            if src in self.consts:
                self.consts[node.name] = self.consts[src]
        elif op == "NoOp":
            return
        else:
            mapper = TF_OP_MAPPERS.get(op)
            if mapper is None:
                raise TFImportError(
                    f"no mapper for TF op {op!r} (node {node.name}); "
                    f"supported: {sorted(TF_OP_MAPPERS)}")
            self.vars[node.name] = mapper(self, node)
            self._try_fold(node)

    def run(self, outputs: Sequence[str]) -> Dict[str, str]:
        frames = _collect_frames(self.gd)
        frame_of: Dict[str, "_Frame"] = {}
        for fr in frames:
            for n in fr.members:
                frame_of[n] = fr
        clusters = _collect_cond_clusters(self.gd, set(frame_of))
        for cl in clusters:
            for n in cl.members:
                frame_of.setdefault(n, cl)  # same skip/trigger protocol
        # data-consumer map: placeholders nobody reads (the lowered form
        # emits unused_control_flow_input placeholders) are skipped, and
        # control-only stragglers of a processed frame are droppable
        data_consumed = {r.split(":")[0] for n in self.gd.node
                         for r in n.input if not r.startswith("^")}
        name_map: Dict[str, str] = {}
        for node in self.gd.node:
            fr = frame_of.get(node.name)
            if fr is not None:
                if not fr.done and fr.ready(self):
                    fr.process(self)
                continue
            if (node.op == "Placeholder" and node.name not in data_consumed
                    and node.name not in (outputs or [])
                    and node.name not in self.input_shapes):
                continue
            try:
                self._process_node(node)
            except TFImportError:
                # a control-only consumer of frame internals (e.g. the
                # loop_body_control Identity) — droppable iff nothing
                # reads its data output
                if node.name not in data_consumed and any(
                        r.split(":")[0].lstrip("^") in frame_of
                        for r in node.input):
                    continue
                raise
        undone = [fr.name for fr in frames if not fr.done]
        undone += [m.name for cl in clusters if not cl.done
                   for m in cl.merges]
        if undone:
            raise TFImportError(
                f"could not resolve TF1 control-flow structure(s) "
                f"{undone}: entry inputs never became available "
                "(malformed or unsupported graph)")
        for out in outputs:
            name_map[out] = self.tensor(out).name
        return name_map


# --- control flow: TF1 frame raising + FunctionDef import ------------------
#
# The reference's TF import executes Switch/Merge/Enter/Exit/NextIteration
# frames with control-flow-aware sessions (SURVEY §2.3 sessions row, §3.2).
# On TPU the only compilable form is lax.while_loop/lax.cond, so this
# importer RAISES TF1 frames back to functional cond/body subgraphs and maps
# TF2 functional While/If (FunctionDef-carried) directly onto
# samediff.while_loop / samediff.cond — XLA-native structured control flow
# instead of a dataflow interpreter.

_FRAME_OPS = ("Enter", "Merge", "Switch", "NextIteration", "Exit", "LoopCond")


class _SubgraphImporter(_GraphImporter):
    """Demand-driven import of a subset of GraphDef nodes into a fresh
    SameDiff, with boundary tensors (loop-var Merges/Switches, invariant
    Enters) pre-bound to placeholders. Used for raised TF1 frame bodies,
    where node order in the GraphDef is not topological (cycles through
    NextIteration). ``child_frames`` maps member names of NESTED frames
    to their _Frame: reaching one (its Exit, from the parent body's
    compute) raises the inner loop recursively within THIS subgraph."""

    def __init__(self, by_name, library, sd: SameDiff, boundary,
                 child_frames=None):
        self.gd = None
        self.sd = sd
        self.input_shapes = {}
        self.vars = dict(boundary)  # boundary name -> placeholder (any :idx)
        self._boundary = set(boundary)
        self.consts = {}
        self.library = library
        self.by_name = by_name
        self.child_frames = child_frames or {}

    def tensor(self, ref: str) -> SDVariable:
        name = ref.split(":")[0].lstrip("^")
        if name in self._boundary:
            return self.vars[name]  # Switch:1 / Merge:0 both mean "the var"
        if name not in self.vars:
            self._ensure(name)
        return super().tensor(ref)

    def const_value(self, ref: str) -> np.ndarray:
        name = ref.split(":")[0]
        if name not in self.consts and name not in self.vars \
                and name not in self._boundary:
            self._ensure(name)
        return super().const_value(ref)

    def _ensure(self, name: str) -> None:
        unit = self.child_frames.get(name)  # nested _Frame or _CondCluster
        if unit is not None:
            # processed per-IMPORTER (keyed on the provided names being
            # present in OUR vars, not unit.done): a child read from both
            # the parent's cond and body subgraphs raises into each
            if not any(p in self.vars for p in unit.provided_names()):
                unit.process(self, self.by_name)
            if name not in self.vars:
                raise TFImportError(
                    f"control-flow-internal node {name!r} is consumed "
                    "outside its structure (only Exit/Merge values may "
                    "escape)")
            return
        node = self.by_name.get(name)
        if node is None:
            raise TFImportError(f"tensor {name!r}: no such node in graph")
        if node.op in _FRAME_OPS:
            raise TFImportError(
                f"node {name!r} ({node.op}) belongs to unstructured "
                "control flow this importer cannot raise (freeze with "
                "lower_control_flow=False for functional While/If)")
        for r in node.input:
            if r.startswith("^"):
                continue
            src = r.split(":")[0]
            if src not in self.vars and src not in self._boundary:
                self._ensure(src)
        self._process_node(node)


class _Frame:
    """One TF1 while-loop frame and its functional reconstruction:

        init_m  = Enter_m.input                     (outer graph)
        carry_m = Merge_m(Enter_m, NextIteration_m) (loop header phi)
        pred    = cond(carries) -> LoopCond
        Switch_m(carry_m, pred): :1 -> body, :0 -> Exit_m
        body outputs = NextIteration_m.input

    Loop-invariant Enters (is_constant=true, no Merge) become
    pass-through loop vars so in-body reads see a stable carry."""

    def __init__(self, name: str):
        self.name = name
        self.enters: list = []       # loop-var Enter, merge order
        self.inv_enters: list = []   # loop-invariant Enter
        self.merges: list = []
        self.switches: list = []     # per loop var; None if unused in body
        self.next_iters: list = []
        self.exits: Dict[int, Any] = {}
        self.loop_cond = None
        self.members: set = set()
        self.children: list = []     # frames nested inside this one
        self.cond_pred_ref = None
        self.done = False

    def ready(self, imp: _GraphImporter) -> bool:
        return all(e.input[0].split(":")[0].lstrip("^") in imp.vars
                   for e in self.enters + self.inv_enters)

    def provided_names(self) -> list:
        return [ex.name for ex in self.exits.values()]

    def _child_frame_map(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for ch in self.children:  # nested _Frame or in-frame _CondCluster
            for n in ch.members:
                out.setdefault(n, ch)
        return out

    def process(self, imp: _GraphImporter, by_name=None) -> None:
        if by_name is None:
            by_name = {n.name: n for n in imp.gd.node}
        inits = [_init_var(imp, e.input[0])
                 for e in self.enters + self.inv_enters]
        cond_sd, body_sd = SameDiff.create(), SameDiff.create()
        cond_bound, body_bound = {}, {}
        # placeholders declared in loop-var order: _as_branch_fn maps them
        # positionally onto the while carry
        for i, m in enumerate(self.merges):
            v = inits[i]
            cond_bound[m.name] = cond_sd.placeholder(
                m.name, v.shape, v.dtype or "float32")
            sw = self.switches[i]
            bname = sw.name if sw is not None else f"__var{i}_unused"
            body_bound[bname] = body_sd.placeholder(
                bname, v.shape, v.dtype or "float32")
        for j, e in enumerate(self.inv_enters):
            v = inits[len(self.merges) + j]
            cond_bound[e.name] = cond_sd.placeholder(
                e.name, v.shape, v.dtype or "float32")
            body_bound[e.name] = body_sd.placeholder(
                e.name, v.shape, v.dtype or "float32")
        kids = self._child_frame_map()
        cimp = _SubgraphImporter(by_name, imp.library, cond_sd, cond_bound,
                                 child_frames=kids)
        cond_sd.branch_outputs = [cimp.tensor(self.cond_pred_ref).name]
        bimp = _SubgraphImporter(by_name, imp.library, body_sd, body_bound,
                                 child_frames=kids)
        outs = [bimp.tensor(ni.input[0]).name for ni in self.next_iters]
        outs += [body_bound[e.name].name for e in self.inv_enters]
        body_sd.branch_outputs = outs
        res = imp.sd.while_loop(cond_sd, body_sd, inits)
        res = res if isinstance(res, tuple) else (res,)
        for i, ex in self.exits.items():
            imp.vars[ex.name] = res[i]
        self.done = True


def _collect_frames(gd) -> list:
    """Identify TF1 while frames (grouped by Enter frame_name) and
    precompute their membership + structure for raising. Nested frames
    are resolved recursively: an outer frame's interior walk absorbs any
    inner frame it reaches (via the inner Exit its body consumes) into
    its membership and records it as a child — the raising then happens
    inside the outer body's subgraph import. Returns only ROOT frames;
    children hang off ``frame.children``."""
    if gd is None:
        return []
    by_name = {n.name: n for n in gd.node}
    consumers: Dict[str, list] = {}
    data_consumed = set()
    for n in gd.node:
        for r in n.input:
            if not r.startswith("^"):
                consumers.setdefault(r.split(":")[0], []).append((n, r))
                data_consumed.add(r.split(":")[0])
    enters_by_frame: Dict[str, list] = {}
    for n in gd.node:
        if n.op == "Enter":
            fname = n.attr["frame_name"].s.decode()
            enters_by_frame.setdefault(fname, []).append(n)

    # phase 1: structure (enters/merges/switches/NIs/exits/LoopCond)
    frames: list = []
    struct_of: Dict[str, _Frame] = {}  # structural member name -> frame
    for fname, enters in enters_by_frame.items():
        fr = _Frame(fname)
        enter_names = {e.name for e in enters}
        merge_for_enter: Dict[str, Any] = {}
        for n in gd.node:
            if n.op == "Merge":
                for r in n.input:
                    src = r.split(":")[0]
                    if src in enter_names:
                        merge_for_enter[src] = n
        for e in enters:
            m = merge_for_enter.get(e.name)
            if m is None:
                fr.inv_enters.append(e)
                continue
            fr.enters.append(e)
            fr.merges.append(m)
            ni_name = next((r.split(":")[0] for r in m.input
                            if r.split(":")[0] != e.name), None)
            ni = by_name.get(ni_name)
            if ni is None or ni.op != "NextIteration":
                raise TFImportError(
                    f"frame {fname!r}: Merge {m.name} lacks a "
                    "NextIteration input (unsupported frame shape)")
            fr.next_iters.append(ni)
            sw = next((c for c, _ in consumers.get(m.name, [])
                       if c.op == "Switch"), None)
            fr.switches.append(sw)
            if sw is not None:
                lc = by_name.get(sw.input[1].split(":")[0])
                if lc is None or lc.op != "LoopCond":
                    raise TFImportError(
                        f"frame {fname!r}: Switch {sw.name} predicate is "
                        f"not a LoopCond")
                fr.loop_cond = lc
                ex = next((c for c, ref in consumers.get(sw.name, [])
                           if c.op == "Exit"), None)
                if ex is not None:
                    fr.exits[len(fr.merges) - 1] = ex
        if fr.loop_cond is None:
            raise TFImportError(
                f"frame {fname!r}: no LoopCond found (cond-only Switch/"
                "Merge graphs are not raiseable as loops)")
        fr.cond_pred_ref = fr.loop_cond.input[0]
        for nd in (fr.enters + fr.inv_enters + fr.merges + fr.next_iters
                   + [s for s in fr.switches if s is not None]
                   + list(fr.exits.values()) + [fr.loop_cond]):
            struct_of[nd.name] = fr
        frames.append(fr)

    # phase 2: full membership, innermost-first via recursion — an
    # interior walk reaching ANOTHER frame's structural node absorbs that
    # frame (children import inside the parent's body subgraph)
    def full_members(fr: _Frame, visiting: set) -> set:
        if fr.members:
            return fr.members
        if fr.name in visiting:
            raise TFImportError(
                f"frames {sorted(visiting)} are mutually entangled; "
                "cannot raise")
        visiting = visiting | {fr.name}
        boundary = ({m.name for m in fr.merges}
                    | {s.name for s in fr.switches if s is not None}
                    | {e.name for e in fr.inv_enters})
        interior: set = set()
        cond_kids: Dict[str, _CondCluster] = {}  # in-frame conds, by pred
        stack = [fr.cond_pred_ref] + [ni.input[0] for ni in fr.next_iters]
        stack = [r.split(":")[0].lstrip("^") for r in stack]
        while stack:
            name = stack.pop()
            if name in boundary or name in interior:
                continue
            other = struct_of.get(name)
            if other is not None and other is not fr:
                if other not in fr.children:
                    fr.children.append(other)
                    interior |= full_members(other, visiting)
                    # the child's loop-entry values are computed in OUR
                    # body — keep walking from its Enter inputs
                    stack.extend(e.input[0].split(":")[0].lstrip("^")
                                 for e in other.enters + other.inv_enters)
                continue
            node = by_name.get(name)
            if node is None:
                raise TFImportError(
                    f"frame {fr.name!r}: interior ref {name!r} missing")
            if node.op == "Merge":
                # a lowered tf.cond INSIDE the loop body: absorb it as a
                # child cluster (raised within the body subgraph import),
                # grouped by predicate so a multi-output cond still runs
                # its branches once, and keep walking from its operands
                single = _build_merge_cluster(node, by_name)
                cl = cond_kids.get(single.pred_ref)
                if cl is None:
                    cond_kids[single.pred_ref] = single
                    cl = single
                else:
                    cl.merges.extend(single.merges)
                    cl.true_refs.extend(single.true_refs)
                    cl.false_refs.extend(single.false_refs)
                    for sw in single.switches:
                        if sw.name not in {s.name for s in cl.switches}:
                            cl.switches.append(sw)
                    cl.members |= single.members
                interior |= single.members
                for sw in single.switches:
                    stack.append(sw.input[0].split(":")[0].lstrip("^"))
                    stack.append(sw.input[1].split(":")[0].lstrip("^"))
                continue
            if node.op in _FRAME_OPS:
                raise TFImportError(
                    f"frame {fr.name!r} touches unstructured {node.op} "
                    f"node {name!r}; cannot raise")
            interior.add(name)
            for r in node.input:
                stack.append(r.split(":")[0].lstrip("^"))
        # control-only stragglers hanging off loop machinery (pivot
        # identities, control NoOps): anything consuming a Switch/Merge
        # that only feeds control edges
        for s in list(boundary):
            for c, _ref in consumers.get(s, []):
                if (c.op in ("Identity", "NoOp")
                        and c.name not in data_consumed):
                    interior.add(c.name)
        fr.children.extend(cond_kids.values())
        fr.members = (interior | boundary
                      | {e.name for e in fr.enters + fr.inv_enters}
                      | {ni.name for ni in fr.next_iters}
                      | {e.name for e in fr.exits.values()}
                      | {fr.loop_cond.name})
        return fr.members

    for fr in frames:
        full_members(fr, set())
    nested = {ch.name for fr in frames for ch in fr.children
              if isinstance(ch, _Frame)}
    return [fr for fr in frames if fr.name not in nested]


class _CondCluster:
    """One lowered tf.cond: Switch(data, pred) pairs gate two branch
    bodies joined by Merges (one per cond output; a multi-output cond
    emits several Merges over ONE Switch set). Raised to a SINGLE
    samediff.cond (lax.cond) so shared branch computation runs once:

        pred = Switch.input[1]            (shared across the cluster)
        Switch_i(data_i, pred): :1 -> true branch, :0 -> false branch
        Merge_j(true_out_j, false_out_j) -> cond output j

    Branch membership of each Merge input is decided by WHICH switch
    output index its backward closure consumes (a constant-only branch
    still reaches its pivot Switch through control edges)."""

    def __init__(self, pred_ref: str):
        self.pred_ref = pred_ref
        self.merges: list = []
        self.true_refs: list = []
        self.false_refs: list = []
        self.switches: list = []
        self.members: set = set()
        self.done = False

    def provided_names(self) -> list:
        return [m.name for m in self.merges]

    def ready(self, imp: _GraphImporter) -> bool:
        return all(
            sw.input[0].split(":")[0].lstrip("^") in imp.vars
            and sw.input[1].split(":")[0].lstrip("^") in imp.vars
            for sw in self.switches)

    def process(self, imp: _GraphImporter, by_name=None) -> None:
        if by_name is None:
            by_name = {n.name: n for n in imp.gd.node}
        pred = imp.tensor(self.pred_ref)
        datas = [imp.tensor(sw.input[0]) for sw in self.switches]

        def build(branch_refs) -> SameDiff:
            sub = SameDiff.create()
            bound = {}
            for sw, d in zip(self.switches, datas):
                bound[sw.name] = sub.placeholder(
                    sw.name, d.shape, d.dtype or "float32")
            bimp = _SubgraphImporter(by_name, imp.library, sub, bound)
            sub.branch_outputs = [bimp.tensor(r).name for r in branch_refs]
            return sub

        res = imp.sd.cond(pred, build(self.true_refs),
                          build(self.false_refs), datas)
        res = res if isinstance(res, tuple) else (res,)
        for m, out in zip(self.merges, res):
            imp.vars[m.name] = out
        self.done = True


def _walk_cond_branch(by_name, start_ref: str, merge_name: str):
    """Backward closure (data + control) from one Merge input, stopping at
    Switch nodes. Returns (interior names, switch nodes in discovery
    order, consumed switch-output indices)."""
    interior, switches, idxs = set(), [], set()
    seen_sw = set()
    stack = [start_ref]
    while stack:
        ref = stack.pop()
        name = ref.lstrip("^").split(":")[0]
        if name in interior:
            continue
        node = by_name.get(name)
        if node is None:
            raise TFImportError(
                f"cond at Merge {merge_name!r}: ref {name!r} missing")
        if node.op == "Switch":
            if name not in seen_sw:
                seen_sw.add(name)
                switches.append(node)
            parts = ref.lstrip("^").split(":")
            idxs.add(int(parts[1]) if len(parts) > 1 else 0)
            continue
        if node.op in ("Merge", "Enter", "Exit", "NextIteration",
                       "LoopCond"):
            raise TFImportError(
                f"cond at Merge {merge_name!r} touches {node.op} node "
                f"{name!r}: nested lowered control flow is not supported "
                "(freeze with lower_control_flow=False)")
        interior.add(name)
        stack.extend(node.input)
    return interior, switches, idxs


def _build_merge_cluster(n, by_name) -> _CondCluster:
    """Single-Merge cond cluster: walk both inputs to the gating Switch
    set, decide true/false by consumed output index, validate one shared
    predicate. Raises TFImportError for unraiseable shapes."""
    data_in = [r for r in n.input if not r.startswith("^")]
    if len(data_in) != 2:
        raise TFImportError(
            f"Merge {n.name}: {len(data_in)} data inputs; only 2-way "
            "(tf.cond) merges are raiseable")
    sides = {}
    interior = set()
    switches = []
    for ref in data_in:
        br_interior, br_switches, idxs = _walk_cond_branch(
            by_name, ref, n.name)
        interior |= br_interior
        for sw in br_switches:
            if sw.name not in {s.name for s in switches}:
                switches.append(sw)
        if idxs == {1}:
            sides["true"] = ref
        elif idxs == {0}:
            sides["false"] = ref
        else:
            raise TFImportError(
                f"Merge {n.name}: branch {ref!r} consumes switch "
                f"outputs {sorted(idxs)}; cannot assign it to one side")
    if set(sides) != {"true", "false"}:
        raise TFImportError(
            f"Merge {n.name}: could not identify both branches")
    if not switches:
        raise TFImportError(f"Merge {n.name}: no gating Switch found")
    preds = {sw.input[1] for sw in switches}
    if len(preds) > 1:
        raise TFImportError(
            f"Merge {n.name}: switches disagree on the predicate "
            f"({sorted(preds)}); unsupported cond shape")
    cl = _CondCluster(switches[0].input[1])
    cl.merges.append(n)
    cl.true_refs.append(sides["true"])
    cl.false_refs.append(sides["false"])
    cl.switches.extend(switches)
    cl.members = interior | {n.name} | {sw.name for sw in switches}
    return cl


def _collect_cond_clusters(gd, exclude: set) -> list:
    """Identify lowered tf.cond clusters: Merges OUTSIDE while frames,
    grouped by predicate so a multi-output cond (several Merges over one
    Switch set) raises to ONE lax.cond with shared branch bodies."""
    if gd is None:
        return []
    by_name = {n.name: n for n in gd.node}
    by_pred: Dict[str, _CondCluster] = {}
    for n in gd.node:
        if n.op != "Merge" or n.name in exclude:
            continue
        single = _build_merge_cluster(n, by_name)
        if any(sw.input[0].split(":")[0].lstrip("^") in exclude
               or sw.input[1].split(":")[0].lstrip("^") in exclude
               for sw in single.switches):
            # frame-internal debris: a dead in-frame cond Merge (no live
            # consumer, unpruned freeze) gated by frame machinery — its
            # switch inputs can never resolve at top level; skip rather
            # than dooming run() to an unresolvable-structure error
            continue
        cl = by_pred.get(single.pred_ref)
        if cl is None:
            by_pred[single.pred_ref] = single
            continue
        cl.merges.extend(single.merges)
        cl.true_refs.extend(single.true_refs)
        cl.false_refs.extend(single.false_refs)
        for sw in single.switches:
            if sw.name not in {s.name for s in cl.switches}:
                cl.switches.append(sw)
        cl.members |= single.members
    return list(by_pred.values())


_TF_OUT_ARG_OFFSETS = {
    # multi-output-arg ops: FunctionDef refs are 'node:out_arg:idx'; flat
    # tuple position = offset(out_arg) + idx
    "TopKV2": {"values": 0, "indices": 1},
    "FusedBatchNorm": {"y": 0}, "FusedBatchNormV2": {"y": 0},
    "FusedBatchNormV3": {"y": 0},
    "Split": {"output": 0}, "SplitV": {"output": 0}, "Unpack": {"output": 0},
}


class _FunctionImporter(_GraphImporter):
    """Imports a FunctionDef (TF2 functional While/If branch) into a fresh
    SameDiff subgraph. FunctionDef tensor refs are 'node:out_arg:idx'
    (GraphDef uses 'node:idx') and function inputs are bare arg names;
    placeholders are declared in signature order so the branch maps
    positionally onto call-site operands."""

    def __init__(self, fdef, library, sd: SameDiff, arg_vars):
        self.gd = None
        self.sd = sd
        self.input_shapes = {}
        self.vars = {}
        self.consts = {}
        self.library = library
        self.fdef = fdef
        self._node_ops: Dict[str, str] = {}
        sig = fdef.signature
        if len(arg_vars) != len(sig.input_arg):
            raise TFImportError(
                f"function {sig.name!r} takes {len(sig.input_arg)} args, "
                f"got {len(arg_vars)}")
        for arg, v in zip(sig.input_arg, arg_vars):
            self.vars[arg.name] = self.sd.placeholder(
                arg.name, v.shape, v.dtype or "float32")

    def tensor(self, ref: str) -> SDVariable:
        parts = ref.lstrip("^").split(":")
        name = parts[0]
        if len(parts) >= 3:
            off = _TF_OUT_ARG_OFFSETS.get(
                self._node_ops.get(name, ""), {}).get(parts[1], 0)
            flat = off + int(parts[2])
        elif len(parts) == 2 and parts[1].isdigit():
            flat = int(parts[1])
        else:
            flat = 0
        v = self.vars.get(name)
        if v is None:
            raise TFImportError(f"tensor {ref!r} produced by unknown node")
        if isinstance(v, tuple):
            return v[flat]
        if flat != 0:
            raise TFImportError(f"node {name} has one output; wanted {ref!r}")
        return v

    def run_function(self) -> None:
        pending = list(self.fdef.node_def)
        while pending:
            rest = []
            for nd in pending:
                # control inputs (^node) don't gate dataflow readiness —
                # their targets (NoOps) register nothing in vars
                refs = [r.split(":")[0] for r in nd.input
                        if not r.startswith("^")]
                if all(r in self.vars for r in refs):
                    self._node_ops[nd.name] = nd.op
                    self._process_node(nd)
                else:
                    rest.append(nd)
            if len(rest) == len(pending):
                missing = sorted({r.split(":")[0] for nd in rest
                                  for r in nd.input
                                  if not r.startswith("^")
                                  and r.split(":")[0] not in self.vars})
                raise TFImportError(
                    f"function {self.fdef.signature.name!r}: unresolvable "
                    f"refs {missing[:5]} (cycle or unsupported structure)")
            pending = rest
        rets = []
        for oa in self.fdef.signature.output_arg:
            rets.append(self.tensor(self.fdef.ret[oa.name]).name)
        self.sd.branch_outputs = rets


def _import_function(imp: _GraphImporter, fname: str, arg_vars) -> SameDiff:
    fdef = imp.library.get(fname)
    if fdef is None:
        raise TFImportError(
            f"function {fname!r} not found in the graph's function library")
    sub = SameDiff.create()
    fimp = _FunctionImporter(fdef, imp.library, sub, arg_vars)
    fimp.run_function()
    return sub


def _func_name_attr(node, key: str) -> str:
    if key not in node.attr or not node.attr[key].func.name:
        raise TFImportError(
            f"node {node.name} ({node.op}) lacks function attr {key!r}")
    return node.attr[key].func.name


def _uniq(sd: SameDiff, base: str) -> str:
    name = base
    i = 0
    while name in sd._vars:
        i += 1
        name = f"{base}__{i}"
    return name


# mapper(importer, node) -> SDVariable | tuple

TF_OP_MAPPERS: Dict[str, Callable] = {}


def tf_op(*names):
    def deco(fn):
        for n in names:
            TF_OP_MAPPERS[n] = fn
        return fn

    return deco


def _simple(op_name):
    """Mapper for ops that take their TF inputs positionally."""

    def mapper(imp: _GraphImporter, node):
        ins = [imp.tensor(r) for r in node.input if not r.startswith("^")]
        return imp.sd._record(op_name, ins, {
            "__argspec__": ["var"] * len(ins), "__posattrs__": []})

    return mapper


for tf_name, our_op in {
    "Add": "add", "AddV2": "add", "Sub": "sub", "Mul": "mul",
    "RealDiv": "div", "Div": "div", "Pow": "pow", "Neg": "neg",
    "Maximum": "maximum", "Minimum": "minimum",
    "Relu": "relu", "Relu6": "relu6", "Elu": "elu", "Selu": "selu",
    "Sigmoid": "sigmoid", "Tanh": "tanh", "Softplus": "softplus",
    "Exp": "exp", "Log": "log", "Sqrt": "sqrt", "Square": "square",
    "Abs": "abs", "Sign": "math.sign", "Floor": "math.floor",
    "Ceil": "math.ceil", "Round": "math.round", "Sin": "math.sin",
    "Cos": "math.cos", "Erf": "tfimport.erf", "Rsqrt": "tfimport.rsqrt",
    "LogicalAnd": "math.logical_and" if "math.logical_and" in OP_REGISTRY else "mul",
    "Equal": "eq", "NotEqual": "neq", "Greater": "gt",
    "GreaterEqual": "gte", "Less": "lt", "LessEqual": "lte",
    "SquaredDifference": "tfimport.squared_difference",
    "Select": "tfimport.select", "SelectV2": "tfimport.select",
    "FloorDiv": "tfimport.floor_div", "FloorMod": "tfimport.floor_mod",
    "ZerosLike": "zeros_like", "OnesLike": "ones_like",
}.items():
    TF_OP_MAPPERS[tf_name] = _simple(our_op)


@tf_op("MatMul")
def _matmul(imp, node):
    a, b = (imp.tensor(r) for r in node.input[:2])
    return imp.sd._record("tfimport.matmul", [a, b], {
        "__argspec__": ["var", "var"], "__posattrs__": [],
        "transpose_a": _attr(node, "transpose_a", False),
        "transpose_b": _attr(node, "transpose_b", False)})


@tf_op("BatchMatMul", "BatchMatMulV2", "BatchMatMulV3")
def _batch_matmul(imp, node):
    a, b = (imp.tensor(r) for r in node.input[:2])
    return imp.sd._record("tfimport.batch_matmul", [a, b], {
        "__argspec__": ["var", "var"], "__posattrs__": [],
        "adj_x": _attr(node, "adj_x", False),
        "adj_y": _attr(node, "adj_y", False)})


@tf_op("BiasAdd")
def _bias_add(imp, node):
    a, b = (imp.tensor(r) for r in node.input[:2])
    if _attr(node, "data_format", "NHWC") == "NCHW":
        raise TFImportError("BiasAdd NCHW unsupported")
    return imp.sd._record("add", [a, b], {})


@tf_op("Softmax")
def _softmax(imp, node):
    return imp.sd._record("softmax", [imp.tensor(node.input[0])], {"axis": -1})


@tf_op("LogSoftmax")
def _log_softmax(imp, node):
    return imp.sd._record("log_softmax", [imp.tensor(node.input[0])], {"axis": -1})


@tf_op("LeakyRelu")
def _leaky(imp, node):
    return imp.sd._record("tfimport.leaky_relu", [imp.tensor(node.input[0])], {
        "__argspec__": ["var"], "__posattrs__": [],
        "alpha": _attr(node, "alpha", 0.2)})


@tf_op("Cast")
def _cast(imp, node):
    return imp.sd._record("cast", [imp.tensor(node.input[0])], {
        "dtype": _np_dtype(_attr(node, "DstT", 1))})


def _reduction(our_op):
    def mapper(imp, node):
        x = imp.tensor(node.input[0])
        axes = imp.const_value(node.input[1])
        axes = [int(a) for a in np.atleast_1d(axes)]
        # axes=[] (reduce over no axes — keras RNN's Max(maximum_iterations,
        # range(0, rank=0)) emits this) is the identity reduction
        return imp.sd._record(our_op, [x], {
            "axis": axes if len(axes) != 1 else axes[0],
            "keepdims": bool(_attr(node, "keep_dims", False))})

    return mapper


TF_OP_MAPPERS["Mean"] = _reduction("mean")
TF_OP_MAPPERS["Sum"] = _reduction("sum")
TF_OP_MAPPERS["Max"] = _reduction("max")
TF_OP_MAPPERS["Min"] = _reduction("min")
TF_OP_MAPPERS["Prod"] = _reduction("prod")


@tf_op("Reshape")
def _reshape(imp, node):
    x = imp.tensor(node.input[0])
    shape = [int(v) for v in imp.const_value(node.input[1])]
    return imp.sd._record("reshape", [x], {"shape": shape})


@tf_op("Transpose")
def _transpose(imp, node):
    x = imp.tensor(node.input[0])
    perm = [int(v) for v in imp.const_value(node.input[1])]
    return imp.sd._record("permute", [x], {"axes": perm})


@tf_op("ExpandDims")
def _expand_dims(imp, node):
    x = imp.tensor(node.input[0])
    axis = int(np.atleast_1d(imp.const_value(node.input[1]))[0])
    return imp.sd._record("expand_dims", [x], {"axis": axis})


@tf_op("Squeeze")
def _squeeze(imp, node):
    dims = _attr(node, "squeeze_dims", []) or None
    return imp.sd._record("squeeze", [imp.tensor(node.input[0])], {
        "axis": dims if dims else None})


@tf_op("ConcatV2")
def _concat(imp, node):
    xs = [imp.tensor(r) for r in node.input[:-1]]
    axis = int(np.atleast_1d(imp.const_value(node.input[-1]))[0])
    return imp.sd._record("concat", xs, {
        "__argspec__": ["var"] * len(xs), "__posattrs__": [], "axis": axis})


@tf_op("Pack")
def _pack(imp, node):
    xs = [imp.tensor(r) for r in node.input]
    return imp.sd._record("stack", xs, {
        "__argspec__": ["var"] * len(xs), "__posattrs__": [],
        "axis": _attr(node, "axis", 0)})


@tf_op("TensorListReserve")
def _tensor_list_reserve(imp, node):
    """A reserved TensorList of static length/element-shape is a dense
    zeros [num_elements, *element_shape] array (see tfimport.list_* ops).
    Dynamic element shapes (freeze with a symbolic batch) are refused —
    the dense representation needs static shapes, like everything else
    under jit."""
    shp = np.atleast_1d(imp.const_value(node.input[0])).astype(np.int64)
    num = int(np.atleast_1d(imp.const_value(node.input[1]))[0])
    if shp.ndim != 1 or any(int(d) < 0 for d in shp) or num < 0:
        raise TFImportError(
            f"TensorListReserve {node.name}: dynamic element_shape "
            f"{shp.tolist()} / num_elements {num}; freeze the graph with "
            "concrete shapes (fixed batch) to import TensorList loops")
    dtype = _np_dtype(_attr(node, "element_dtype", 1))
    # lazy zeros via tfimport.fill — a dense numpy constant here would
    # embed an O(T·batch·hidden) zeros array in the graph (and every
    # serialization of it) for nothing; XLA materializes fill at trace
    # time for free
    zero = imp.sd.constant(_uniq(imp.sd, f"{node.name}_zero"),
                           np.zeros((), dtype))
    return imp.sd._record("tfimport.fill", [zero], {
        "__argspec__": ["attr", "var"],
        "__posattrs__": [[num, *[int(d) for d in shp]]]})


@tf_op("TensorListFromTensor")
def _tensor_list_from_tensor(imp, node):
    return imp.tensor(node.input[0])


@tf_op("TensorListStack")
def _tensor_list_stack(imp, node):
    return imp.tensor(node.input[0])


@tf_op("TensorListGetItem")
def _tensor_list_get_item(imp, node):
    handle, idx = imp.tensor(node.input[0]), imp.tensor(node.input[1])
    return imp.sd._record("tfimport.list_get", [handle, idx], {
        "__argspec__": ["var", "var"], "__posattrs__": []})


@tf_op("TensorListSetItem")
def _tensor_list_set_item(imp, node):
    handle = imp.tensor(node.input[0])
    idx = imp.tensor(node.input[1])
    item = imp.tensor(node.input[2])
    return imp.sd._record("tfimport.list_set", [handle, idx, item], {
        "__argspec__": ["var", "var", "var"], "__posattrs__": []})


@tf_op("TensorListLength")
def _tensor_list_length(imp, node):
    return imp.sd._record("tfimport.list_length", [imp.tensor(node.input[0])],
                          {"__argspec__": ["var"], "__posattrs__": []})


def _init_var(imp, ref):
    """Resolve a loop-entry input, promoting host-known values (folded
    shape math like keras' maximum_iterations) to true sd constants —
    the samediff scan-lowering detects static trip counts by init
    var_type, and a host-folded ARRAY var would hide the static value."""
    from deeplearning4j_tpu.autodiff.samediff import VariableType

    parts = ref.lstrip("^").split(":")
    name = parts[0]
    idx0 = len(parts) == 1 or parts[-1] in ("0", "")
    v = imp.tensor(ref)  # ensures the producer (and any folding) ran
    # consts is keyed by NODE name and holds output 0 — never promote a
    # :k>0 ref from it
    if v.var_type != VariableType.CONSTANT and idx0 and name in imp.consts:
        return imp.sd.constant(_uniq(imp.sd, name), imp.consts[name])
    return v


@tf_op("While", "StatelessWhile")
def _while_functional(imp, node):
    """TF2 functional while: cond/body FunctionDefs -> samediff.while_loop
    -> lax.while_loop (or lax.scan when samediff detects a static trip
    count). Loop vars map positionally (While is N-in/N-out)."""
    inits = [_init_var(imp, r) for r in node.input if not r.startswith("^")]
    cond_sd = _import_function(imp, _func_name_attr(node, "cond"), inits)
    body_sd = _import_function(imp, _func_name_attr(node, "body"), inits)
    return imp.sd.while_loop(cond_sd, body_sd, inits)


@tf_op("If", "StatelessIf")
def _if_functional(imp, node):
    """TF2 functional cond: then/else FunctionDefs -> samediff.cond ->
    lax.cond (both branches compiled, one executed — XLA-native)."""
    ins = [r for r in node.input if not r.startswith("^")]
    pred = imp.tensor(ins[0])
    args = [imp.tensor(r) for r in ins[1:]]
    t_sd = _import_function(imp, _func_name_attr(node, "then_branch"), args)
    f_sd = _import_function(imp, _func_name_attr(node, "else_branch"), args)
    return imp.sd.cond(pred, t_sd, f_sd, args)


@tf_op("StridedSlice")
def _strided_slice(imp, node):
    x = imp.tensor(node.input[0])
    try:
        begin = [int(v) for v in imp.const_value(node.input[1])]
        end = [int(v) for v in imp.const_value(node.input[2])]
        strides = [int(v) for v in imp.const_value(node.input[3])]
    except TFImportError:
        # Loop-var-dependent slicing (x[i] inside a while body): bounds
        # are traced, not host constants. Supported for the pure-index
        # (all-shrink) form — jnp turns x[i, j] with traced scalars into
        # dynamic_slice+squeeze; ranges with traced bounds have no static
        # shape and stay refused.
        bvar = imp.tensor(node.input[1])
        k = (bvar.shape or [1])[0] or 1
        if (_attr(node, "new_axis_mask", 0) or _attr(node, "ellipsis_mask", 0)
                or _attr(node, "begin_mask", 0) or _attr(node, "end_mask", 0)
                or _attr(node, "shrink_axis_mask", 0) != (1 << k) - 1):
            raise TFImportError(
                f"StridedSlice {node.name}: non-constant begin/end is only "
                "supported for pure-index (all-shrink) slices like x[i]")
        return imp.sd._record("tfimport.index_dyn", [x, bvar], {
            "__argspec__": ["var", "var"], "__posattrs__": []})
    return imp.sd._record("tfimport.strided_slice", [x], {
        "__argspec__": ["var"], "__posattrs__": [],
        "begin": begin, "end": end, "strides": strides,
        "begin_mask": _attr(node, "begin_mask", 0),
        "end_mask": _attr(node, "end_mask", 0),
        "shrink_axis_mask": _attr(node, "shrink_axis_mask", 0),
        "new_axis_mask": _attr(node, "new_axis_mask", 0),
        "ellipsis_mask": _attr(node, "ellipsis_mask", 0)})


@tf_op("GatherV2", "Gather")
def _gather(imp, node):
    params, indices = imp.tensor(node.input[0]), imp.tensor(node.input[1])
    axis = 0
    if len(node.input) > 2:
        axis = int(np.atleast_1d(imp.const_value(node.input[2]))[0])
    return imp.sd._record("gather", [params, indices], {
        "__argspec__": ["var", "var"], "__posattrs__": [], "axis": axis})


@tf_op("OneHot")
def _one_hot(imp, node):
    indices = imp.tensor(node.input[0])
    depth = int(np.atleast_1d(imp.const_value(node.input[1]))[0])
    on = float(np.atleast_1d(imp.const_value(node.input[2]))[0])
    off = float(np.atleast_1d(imp.const_value(node.input[3]))[0])
    return imp.sd._record("math.one_hot", [indices], {
        "__argspec__": ["var"], "__posattrs__": [],
        "depth": depth, "on_value": on, "off_value": off,
        "axis": _attr(node, "axis", -1)})


@tf_op("Pad", "PadV2")
def _pad(imp, node):
    x = imp.tensor(node.input[0])
    paddings = [[int(a), int(b)] for a, b in imp.const_value(node.input[1])]
    cval = 0.0
    if len(node.input) > 2:
        cval = float(np.atleast_1d(imp.const_value(node.input[2]))[0])
    return imp.sd._record("tfimport.pad", [x], {
        "__argspec__": ["var"], "__posattrs__": [],
        "paddings": paddings, "constant_value": cval})


@tf_op("Tile")
def _tile(imp, node):
    x = imp.tensor(node.input[0])
    reps = [int(v) for v in imp.const_value(node.input[1])]
    return imp.sd._record("tile", [x], {"reps": reps})


@tf_op("Fill")
def _fill(imp, node):
    dims = [int(v) for v in imp.const_value(node.input[0])]
    value = imp.tensor(node.input[1])
    return imp.sd._record("tfimport.fill", [value], {
        "__argspec__": ["attr", "var"], "__posattrs__": [dims]})


@tf_op("Range")
def _range(imp, node):
    start, limit, delta = (np.atleast_1d(imp.const_value(r))[0]
                           for r in node.input[:3])
    dtype = _np_dtype(_attr(node, "Tidx", _attr(node, "Tout", 1)))
    arr = np.arange(start, limit, delta).astype(dtype)
    return imp.sd.constant(_uniq(imp.sd, node.name), arr)


@tf_op("Conv2D")
def _conv2d(imp, node):
    x, w = imp.tensor(node.input[0]), imp.tensor(node.input[1])
    if _attr(node, "data_format", "NHWC") != "NHWC":
        raise TFImportError("Conv2D NCHW unsupported")
    return imp.sd._record("tfimport.conv2d", [x, w], {
        "__argspec__": ["var", "var"], "__posattrs__": [],
        "strides": _attr(node, "strides", [1, 1, 1, 1]),
        "padding": _attr(node, "padding", "SAME"),
        "dilations": _attr(node, "dilations", [1, 1, 1, 1])})


@tf_op("DepthwiseConv2dNative")
def _depthwise(imp, node):
    x, w = imp.tensor(node.input[0]), imp.tensor(node.input[1])
    return imp.sd._record("tfimport.depthwise_conv2d", [x, w], {
        "__argspec__": ["var", "var"], "__posattrs__": [],
        "strides": _attr(node, "strides", [1, 1, 1, 1]),
        "padding": _attr(node, "padding", "SAME"),
        "dilations": _attr(node, "dilations", [1, 1, 1, 1])})


@tf_op("MaxPool")
def _max_pool(imp, node):
    return imp.sd._record("tfimport.max_pool", [imp.tensor(node.input[0])], {
        "__argspec__": ["var"], "__posattrs__": [],
        "ksize": _attr(node, "ksize"), "strides": _attr(node, "strides"),
        "padding": _attr(node, "padding", "VALID")})


@tf_op("AvgPool")
def _avg_pool(imp, node):
    return imp.sd._record("tfimport.avg_pool", [imp.tensor(node.input[0])], {
        "__argspec__": ["var"], "__posattrs__": [],
        "ksize": _attr(node, "ksize"), "strides": _attr(node, "strides"),
        "padding": _attr(node, "padding", "VALID")})


@tf_op("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3")
def _fused_bn(imp, node):
    if _attr(node, "is_training", True):
        raise TFImportError("FusedBatchNorm training mode unsupported "
                            "(freeze the graph for inference import)")
    x, scale, offset, mean, var = (imp.tensor(r) for r in node.input[:5])
    out = imp.sd._record("tfimport.fused_batch_norm",
                         [x, scale, offset, mean, var], {
                             "__argspec__": ["var"] * 5, "__posattrs__": [],
                             "epsilon": _attr(node, "epsilon", 1e-3)})
    # TF yields 6 outputs (y, batch stats, reserves); only y is consumed in
    # frozen inference graphs.
    return (out,)


@tf_op("Shape")
def _shape(imp, node):
    x = imp.tensor(node.input[0])
    if x.shape is None or any(d is None for d in x.shape):
        raise TFImportError(f"Shape of dynamic tensor {node.input[0]!r}")
    arr = np.asarray(x.shape, np.int32)
    # host-known: downstream shape arithmetic (Pack/StridedSlice chases
    # real exporters emit) folds from this
    imp.consts[node.name] = arr
    return imp.sd.constant(_uniq(imp.sd, node.name), arr)


@tf_op("Split")
def _split(imp, node):
    axis = int(np.atleast_1d(imp.const_value(node.input[0]))[0])
    x = imp.tensor(node.input[1])
    num = _attr(node, "num_split")
    return imp.sd._record("tfimport.split", [x], {
        "__argspec__": ["var"], "__posattrs__": [],
        "num_or_sizes": num, "axis": axis})


def import_tf_graph(
    graph_def,
    inputs: Optional[Dict[str, Tuple]] = None,
    outputs: Optional[Sequence[str]] = None,
) -> Tuple[SameDiff, Dict[str, str], Dict[str, str]]:
    """Import a frozen GraphDef.

    inputs: optional {placeholder_name: (shape, ...)...} overriding/providing
    placeholder shapes (None dims allowed for batch).
    outputs: tensor names to expose; default = nodes nobody consumes.

    Returns (sd, input_map, output_map): maps from TF names to SameDiff
    variable names.
    """
    ensure_tfimport_ops()
    if outputs is None:
        consumed = {r.split(":")[0].lstrip("^")
                    for n in graph_def.node for r in n.input}
        outputs = [n.name for n in graph_def.node
                   if n.name not in consumed and n.op not in ("Const", "NoOp")]
    sd = SameDiff.create()
    imp = _GraphImporter(graph_def, dict(inputs or {}), sd)
    out_map = imp.run(list(outputs))
    # imp.vars membership: unconsumed placeholders (the lowered control-
    # flow form emits unused_control_flow_input stubs) are skipped by the
    # walk and must not be advertised as feedable inputs
    in_map = {n.name: n.name for n in graph_def.node
              if n.op == "Placeholder" and n.name in imp.vars}
    return sd, in_map, out_map


def freeze_tf_function(fn, *example_args):
    """Helper (used by tests/tools): tf.function → frozen GraphDef +
    input/output tensor names."""
    import tensorflow as tf
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )

    conc = tf.function(fn).get_concrete_function(*example_args)
    frozen = convert_variables_to_constants_v2(conc)
    gd = frozen.graph.as_graph_def()
    in_names = [t.name.split(":")[0] for t in frozen.inputs]
    out_names = [t.name for t in frozen.outputs]
    return gd, in_names, out_names


@tf_op("Einsum")
def _einsum(imp, node):
    xs = [imp.tensor(r) for r in node.input]
    return imp.sd._record("tfimport.einsum", xs, {
        "__argspec__": ["var"] * len(xs), "__posattrs__": [],
        "equation": _attr(node, "equation")})


@tf_op("Slice")
def _slice(imp, node):
    x = imp.tensor(node.input[0])
    begin = [int(v) for v in np.atleast_1d(imp.const_value(node.input[1]))]
    size = [int(v) for v in np.atleast_1d(imp.const_value(node.input[2]))]
    if x.shape is None or any(d is None for d in x.shape):
        raise TFImportError("Slice needs a static input shape")
    # TF size=-1 means "to the end of the dim"; lax.dynamic_slice wants
    # concrete sizes — resolve here where the dim is known.
    size = [d - b if s == -1 else s
            for s, b, d in zip(size, begin, x.shape)]
    return imp.sd._record("slice", [x], {
        "__argspec__": ["var"], "__posattrs__": [],
        "begin": begin, "size": size})


@tf_op("SplitV")
def _split_v(imp, node):
    x = imp.tensor(node.input[0])
    sizes = [int(v) for v in np.atleast_1d(imp.const_value(node.input[1]))]
    axis = int(np.atleast_1d(imp.const_value(node.input[2]))[0])
    if sizes.count(-1) > 1:
        raise TFImportError("SplitV: at most one -1 size")
    if -1 in sizes:
        dim = (x.shape or [None])[axis]
        if dim is None:
            raise TFImportError("SplitV with -1 needs a static dim")
        sizes[sizes.index(-1)] = dim - (sum(sizes) + 1)
    # jnp.split takes cut INDICES when given a list — convert sizes.
    idxs = list(np.cumsum(sizes)[:-1])
    return imp.sd._record("tfimport.split", [x], {
        "__argspec__": ["var"], "__posattrs__": [],
        "num_or_sizes": [int(i) for i in idxs], "axis": axis})


@tf_op("Unpack")
def _unpack(imp, node):
    x = imp.tensor(node.input[0])
    return imp.sd._record("unstack", [x], {"axis": _attr(node, "axis", 0)})


@tf_op("ArgMax", "ArgMin")
def _argminmax(imp, node):
    x = imp.tensor(node.input[0])
    axis = int(np.atleast_1d(imp.const_value(node.input[1]))[0])
    op = "argmax" if node.op == "ArgMax" else "argmin"
    return imp.sd._record(op, [x], {"axis": axis})


@tf_op("Cumsum")
def _cumsum(imp, node):
    x = imp.tensor(node.input[0])
    axis = int(np.atleast_1d(imp.const_value(node.input[1]))[0])
    return imp.sd._record("tfimport.cumsum", [x], {
        "__argspec__": ["var"], "__posattrs__": [],
        "axis": axis, "exclusive": bool(_attr(node, "exclusive", False)),
        "reverse": bool(_attr(node, "reverse", False))})


@tf_op("TopKV2")
def _top_k(imp, node):
    x = imp.tensor(node.input[0])
    k = int(np.atleast_1d(imp.const_value(node.input[1]))[0])
    return imp.sd._record("tfimport.top_k", [x], {
        "__argspec__": ["var"], "__posattrs__": [], "k": k})


@tf_op("ResizeBilinear", "ResizeNearestNeighbor")
def _resize(imp, node):
    x = imp.tensor(node.input[0])
    size = [int(v) for v in np.atleast_1d(imp.const_value(node.input[1]))]
    if _attr(node, "align_corners", False):
        raise TFImportError(
            f"{node.op}: align_corners=True (TF1 legacy grid) is not "
            "supported; re-export with tf.image.resize")
    if not _attr(node, "half_pixel_centers", False):
        raise TFImportError(
            f"{node.op}: half_pixel_centers=False (legacy asymmetric grid) "
            "is not supported; re-export with tf.image.resize")
    method = "linear" if node.op == "ResizeBilinear" else "nearest"
    return imp.sd._record("tfimport.resize", [x], {
        "__argspec__": ["var"], "__posattrs__": [],
        "size": size, "method": method})


@tf_op("Conv2DBackpropInput")
def _conv2d_backprop_input(imp, node):
    input_sizes = [int(v)
                   for v in np.atleast_1d(imp.const_value(node.input[0]))]
    w = imp.tensor(node.input[1])
    dy = imp.tensor(node.input[2])
    if _attr(node, "data_format", b"NHWC") not in (b"NHWC", "NHWC"):
        raise TFImportError("Conv2DBackpropInput: only NHWC")
    return imp.sd._record("tfimport.conv2d_backprop_input", [w, dy], {
        "__argspec__": ["var", "var"], "__posattrs__": [],
        "input_sizes": input_sizes, "strides": _attr(node, "strides"),
        "padding": _attr(node, "padding").decode()
        if isinstance(_attr(node, "padding"), bytes)
        else _attr(node, "padding")})


@tf_op("MirrorPad")
def _mirror_pad(imp, node):
    x = imp.tensor(node.input[0])
    paddings = [[int(a), int(b)] for a, b in imp.const_value(node.input[1])]
    mode = _attr(node, "mode", "REFLECT")
    if isinstance(mode, bytes):
        mode = mode.decode()
    return imp.sd._record("tfimport.mirror_pad", [x], {
        "__argspec__": ["var"], "__posattrs__": [],
        "paddings": paddings, "mode": mode})


def import_tf_saved_model(path, *, signature: str = "serving_default",
                          outputs: Optional[Sequence[str]] = None):
    """Import a TF2 SavedModel directory (the container modern TF users
    actually have on disk; the reference predates it and consumed frozen
    .pb only — this wrapper freezes the chosen signature with
    convert_variables_to_constants_v2 and feeds the frozen GraphDef
    through import_tf_graph).

    Returns (sd, input_map, output_map) exactly like import_tf_graph;
    input_map keys are the signature's tensor input names (":0" stripped).
    Requires tensorflow at call time (import-gated, like the oracle tests).
    """
    try:
        import tensorflow as tf
        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2,
        )
    except ImportError as e:  # pragma: no cover - gated dependency
        raise TFImportError(
            "import_tf_saved_model needs tensorflow installed to load and "
            "freeze the SavedModel; export a frozen GraphDef and use "
            "import_tf_graph instead") from e

    loaded = tf.saved_model.load(path)
    sigs = getattr(loaded, "signatures", {})
    if signature not in sigs:
        raise TFImportError(
            f"SavedModel has no signature {signature!r}; available: "
            f"{sorted(sigs)}")
    # lower_control_flow=False keeps While/If functional (FunctionDef
    # branches) instead of lowering to TF1 frames — the functional path
    # supports nesting and is the preferred route for keras RNN layers'
    # TensorList loops
    frozen = convert_variables_to_constants_v2(
        sigs[signature], lower_control_flow=False)
    gd = frozen.graph.as_graph_def()
    # keep full name:idx — _GraphImporter.tensor() uses the index to pick
    # among multi-output ops ("split:1" must not collapse to output 0);
    # ":0" is dropped for cosmetics only.
    out_names = [t.name[:-2] if t.name.endswith(":0") else t.name
                 for t in frozen.outputs]
    return import_tf_graph(gd, outputs=list(outputs or out_names))
