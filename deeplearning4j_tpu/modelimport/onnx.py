"""ONNX model import → SameDiff program (↔ samediff-import-onnx, SURVEY §2.3).

ref: nd4j/samediff-import-onnx (OpMappingRegistry over ONNX NodeProto) —
the same per-op mapper-registry architecture as modelimport/tf.py, reading
the model through the dependency-free wire codec in onnx_proto.py. The
TPU-era difference is downstream: the imported graph compiles as ONE XLA
program instead of per-op interpretation.

Layout: ONNX is NCHW; the imported graph stays NCHW (XLA convolutions take
explicit dimension_numbers, so there is no transposition tax at import).

Policy (same as keras/tf importers): strict refusal — an op or attribute
combination outside the mapped surface raises ONNXImportError rather than
silently importing a wrong graph.

Oracle testing: tests/test_onnx_import.py builds fixture .onnx files with
onnx_proto, verifies the wire format against the `protoc` binary, and
compares imported-graph outputs against torch executing the same weights.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import (
    OP_REGISTRY,
    SameDiff,
    SDVariable,
    register_op,
)
from deeplearning4j_tpu.modelimport.onnx_proto import (
    ATTR_TENSOR,
    GraphProto,
    ModelProto,
    NodeProto,
    TENSOR_DTYPES,
)


class ONNXImportError(Exception):
    pass


# --- jax ops the mappers target (registered under onnximport.*) ------------


def _register_onnximport_ops():
    import jax
    import jax.numpy as jnp

    def gemm(a, b, c=None, alpha=1.0, beta=1.0, trans_a=0, trans_b=0):
        if trans_a:
            a = a.T
        if trans_b:
            b = b.T
        y = alpha * jnp.matmul(a, b)
        if c is not None:
            y = y + beta * c
        return y

    def conv(x, w, b=None, strides=(1, 1), pads=None, dilations=(1, 1),
             group=1, auto_pad="NOTSET"):
        nd = x.ndim - 2
        if auto_pad in ("SAME_UPPER", "SAME_LOWER"):
            # lax 'SAME' pads the lower side first... actually SAME puts the
            # extra pad at the end (upper), matching SAME_UPPER.
            if auto_pad == "SAME_LOWER":
                raise NotImplementedError("auto_pad=SAME_LOWER")
            padding = "SAME"
        elif auto_pad == "VALID" or pads is None:
            padding = [(0, 0)] * nd
        else:
            padding = [(int(pads[i]), int(pads[i + nd])) for i in range(nd)]
        spec = ("NCHW", "OIHW", "NCHW") if nd == 2 else None
        if nd == 1:
            # Run 1D conv as 2D with a unit height axis.
            x2 = x[:, :, None, :]
            w2 = w[:, :, None, :]
            pad2 = "SAME" if padding == "SAME" else [(0, 0)] + list(padding)
            y = jax.lax.conv_general_dilated(
                x2, w2, window_strides=(1,) + tuple(strides),
                padding=pad2, rhs_dilation=(1,) + tuple(dilations),
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
                feature_group_count=group)
            y = y[:, :, 0, :]
        elif nd == 2:
            y = jax.lax.conv_general_dilated(
                x, w, window_strides=tuple(strides), padding=padding,
                rhs_dilation=tuple(dilations), dimension_numbers=spec,
                feature_group_count=group)
        elif nd == 3:
            y = jax.lax.conv_general_dilated(
                x, w, window_strides=tuple(strides), padding=padding,
                rhs_dilation=tuple(dilations),
                dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
                feature_group_count=group)
        else:
            raise NotImplementedError(f"Conv rank {x.ndim}")
        if b is not None:
            y = y + b.reshape((1, -1) + (1,) * nd)
        return y

    def _pool_padding(pads, nd, auto_pad):
        if auto_pad in ("SAME_UPPER",):
            return "SAME"
        if auto_pad == "SAME_LOWER":
            raise NotImplementedError("auto_pad=SAME_LOWER")
        if pads is None:
            return [(0, 0)] * nd
        return [(int(pads[i]), int(pads[i + nd])) for i in range(nd)]

    def max_pool(x, kernel_shape, strides=None, pads=None, auto_pad="NOTSET"):
        nd = len(kernel_shape)
        strides = tuple(strides) if strides else tuple(kernel_shape)
        padding = _pool_padding(pads, nd, auto_pad)
        window = (1, 1) + tuple(kernel_shape)
        stride = (1, 1) + strides
        if padding == "SAME":
            pad_cfg = "SAME"
        else:
            pad_cfg = [(0, 0), (0, 0)] + list(padding)
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, window, stride, pad_cfg)

    def average_pool(x, kernel_shape, strides=None, pads=None,
                     count_include_pad=0, auto_pad="NOTSET"):
        nd = len(kernel_shape)
        strides = tuple(strides) if strides else tuple(kernel_shape)
        padding = _pool_padding(pads, nd, auto_pad)
        window = (1, 1) + tuple(kernel_shape)
        stride = (1, 1) + strides
        pad_cfg = "SAME" if padding == "SAME" else [(0, 0), (0, 0)] + list(padding)
        summed = jax.lax.reduce_window(
            x, 0.0, jax.lax.add, window, stride, pad_cfg)
        if count_include_pad:
            # Fixed kernel-size denominator — correct however the padding
            # was expressed (explicit pads or auto_pad=SAME_*).
            denom = float(np.prod(kernel_shape))
            return summed / denom
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(
            ones, 0.0, jax.lax.add, window, stride, pad_cfg)
        return summed / counts

    def global_average_pool(x):
        return jnp.mean(x, axis=tuple(range(2, x.ndim)), keepdims=True)

    def batch_norm(x, scale, bias, mean, var, epsilon=1e-5):
        shape = (1, -1) + (1,) * (x.ndim - 2)
        inv = scale.reshape(shape) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
        return x * inv + (bias.reshape(shape) - mean.reshape(shape) * inv)

    def layer_norm(x, scale, bias=None, axis=-1, epsilon=1e-5):
        axes = tuple(range(axis % x.ndim, x.ndim))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.var(x, axis=axes, keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + epsilon) * scale
        if bias is not None:
            y = y + bias
        return y

    def reshape_onnx(x, shape, allowzero=0):
        shape = list(shape)
        for i, d in enumerate(shape):
            if d == 0 and not allowzero:
                shape[i] = x.shape[i]
        return jnp.reshape(x, shape)

    def flatten(x, axis=1):
        if axis < 0:
            axis += x.ndim  # ONNX: negative axis counts from the rank
        lead = int(np.prod(x.shape[:axis])) if axis else 1
        return jnp.reshape(x, (lead, -1))

    def slice_onnx(x, starts, ends, axes=None, steps=None):
        axes = list(range(len(starts))) if axes is None else list(axes)
        steps = [1] * len(starts) if steps is None else list(steps)
        idx = [slice(None)] * x.ndim
        for st, en, ax, sp in zip(starts, ends, axes, steps):
            ax = ax % x.ndim
            dim = x.shape[ax]
            st, en = int(st), int(en)
            # ONNX clamps out-of-range (INT_MAX endpoints are idiomatic).
            if st > dim:
                st = dim
            if en > dim:
                en = dim
            idx[ax] = slice(st, en, int(sp))
        return x[tuple(idx)]

    def pad_onnx(x, pads, constant_value=0.0, mode="constant"):
        nd = x.ndim
        widths = [(int(pads[i]), int(pads[i + nd])) for i in range(nd)]
        if mode == "constant":
            return jnp.pad(x, widths, constant_values=constant_value)
        if mode == "reflect":
            return jnp.pad(x, widths, mode="reflect")
        if mode == "edge":
            return jnp.pad(x, widths, mode="edge")
        raise NotImplementedError(f"Pad mode {mode}")

    def reduce_op(kind):
        fns = {"mean": jnp.mean, "sum": jnp.sum, "max": jnp.max,
               "min": jnp.min, "prod": jnp.prod}

        def f(x, axes=None, keepdims=1, noop_with_empty_axes=0):
            if axes is None or len(axes) == 0:
                # ONNX: empty/absent axes reduces ALL dims unless
                # noop_with_empty_axes=1 (then identity).
                if noop_with_empty_axes:
                    return x
                axes = None
            else:
                axes = tuple(int(a) for a in axes)
            return fns[kind](x, axis=axes, keepdims=bool(keepdims))

        return f

    def cast(x, to):
        if to not in TENSOR_DTYPES:
            raise NotImplementedError(f"Cast to ONNX dtype {to}")
        return x.astype(TENSOR_DTYPES[to])

    def hard_sigmoid(x, alpha=0.2, beta=0.5):
        return jnp.clip(alpha * x + beta, 0.0, 1.0)

    def lrn(x, size, alpha=1e-4, beta=0.75, bias=1.0):
        # ONNX LRN: across channels (axis 1), window `size` centered.
        half_lo = (size - 1) // 2
        half_hi = size - 1 - half_lo
        sq = jnp.square(x)
        acc = jax.lax.reduce_window(
            sq, 0.0, jax.lax.add,
            (1, size, 1, 1), (1, 1, 1, 1),
            [(0, 0), (half_lo, half_hi), (0, 0), (0, 0)])
        return x / jnp.power(bias + (alpha / size) * acc, beta)

    for name, fn in {
        "gemm": gemm, "conv": conv, "max_pool": max_pool,
        "average_pool": average_pool,
        "global_average_pool": global_average_pool,
        "batch_norm": batch_norm, "layer_norm": layer_norm,
        "reshape": reshape_onnx, "flatten": flatten, "slice": slice_onnx,
        "pad": pad_onnx, "cast": cast, "hard_sigmoid": hard_sigmoid,
        "lrn": lrn,
        "reduce_mean": reduce_op("mean"), "reduce_sum": reduce_op("sum"),
        "reduce_max": reduce_op("max"), "reduce_min": reduce_op("min"),
        "reduce_prod": reduce_op("prod"),
        "matmul": jnp.matmul,
        "transpose": lambda x, perm=None: jnp.transpose(x, perm),
        "concat": lambda *xs, axis: jnp.concatenate(xs, axis=axis),
        "softmax": lambda x, axis=-1: jax.nn.softmax(x, axis=axis),
        "log_softmax": lambda x, axis=-1: jax.nn.log_softmax(x, axis=axis),
        "leaky_relu": lambda x, alpha=0.01: jnp.where(x >= 0, x, alpha * x),
        "elu": lambda x, alpha=1.0: jnp.where(x >= 0, x, alpha * (jnp.exp(x) - 1)),
        "clip": lambda x, lo=None, hi=None: jnp.clip(x, lo, hi),
        "gather": lambda x, idx, axis=0: jnp.take(x, idx.astype("int32"), axis=axis),
        "unsqueeze": lambda x, axes: jnp.expand_dims(x, tuple(int(a) for a in axes)),
        "squeeze": lambda x, axes=None: jnp.squeeze(
            x, None if axes is None else tuple(int(a) for a in axes)),
        "where": jnp.where,
        "erf": jax.lax.erf,
        "gelu": jax.nn.gelu,
        "prelu": lambda x, slope: jnp.where(x >= 0, x, slope * x),
        "expand": lambda x, shape: jnp.broadcast_to(
            x, np.broadcast_shapes(tuple(x.shape), tuple(shape))),
    }.items():
        register_op(f"onnximport.{name}", fn)


_ONNX_OPS_READY = False


def ensure_onnximport_ops():
    global _ONNX_OPS_READY
    if not _ONNX_OPS_READY:
        _register_onnximport_ops()
        _ONNX_OPS_READY = True


# --- mapper registry -------------------------------------------------------

# mapper(importer, node) -> SDVariable | tuple
ONNX_OP_MAPPERS: Dict[str, Callable] = {}


def onnx_op(*names):
    def deco(fn):
        for n in names:
            ONNX_OP_MAPPERS[n] = fn
        return fn

    return deco


def _simple(op_name):
    """Mapper for ops taking ONNX inputs positionally with no attrs."""

    def mapper(imp: "_GraphImporter", node: NodeProto):
        ins = [imp.tensor(r) for r in node.input if r]
        return imp.sd._record(op_name, ins, {
            "__argspec__": ["var"] * len(ins), "__posattrs__": []})

    return mapper


for onnx_name, our_op in {
    "Add": "add", "Sub": "sub", "Mul": "mul", "Div": "div", "Pow": "pow",
    "Neg": "neg", "Abs": "abs", "Exp": "exp", "Log": "log", "Sqrt": "sqrt",
    "Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
    "Softplus": "softplus", "Erf": "onnximport.erf",
    "Min": "minimum", "Max": "maximum",
    "Equal": "eq", "Greater": "gt", "GreaterOrEqual": "gte",
    "Less": "lt", "LessOrEqual": "lte",
    "Where": "onnximport.where", "MatMul": "onnximport.matmul",
    "PRelu": "onnximport.prelu",
    "Floor": "math.floor", "Ceil": "math.ceil", "Round": "math.round",
    "Sin": "math.sin", "Cos": "math.cos", "Sign": "math.sign",
}.items():
    ONNX_OP_MAPPERS[onnx_name] = _simple(our_op)


def _rec(imp, op, ins, **attrs):
    return imp.sd._record(op, ins, {
        "__argspec__": ["var"] * len(ins), "__posattrs__": [], **attrs})


@onnx_op("Gemm")
def _gemm(imp, node):
    a = node.attrs()
    ins = [imp.tensor(r) for r in node.input if r]
    return _rec(imp, "onnximport.gemm", ins,
                alpha=a.get("alpha", 1.0), beta=a.get("beta", 1.0),
                trans_a=a.get("transA", 0), trans_b=a.get("transB", 0))


@onnx_op("Conv")
def _conv(imp, node):
    a = node.attrs()
    ins = [imp.tensor(r) for r in node.input if r]
    if "kernel_shape" in a:
        nd = len(a["kernel_shape"])
    else:
        # kernel_shape is optional in ONNX; spatial rank comes from the
        # weight tensor [O, I/g, *kernel].
        w_shape = ins[1].shape
        if w_shape is None:
            raise ONNXImportError(
                f"Conv {node.name!r}: no kernel_shape attr and weight "
                "shape unknown")
        nd = len(w_shape) - 2
    return _rec(imp, "onnximport.conv", ins,
                strides=a.get("strides", [1] * nd),
                pads=a.get("pads"), dilations=a.get("dilations", [1] * nd),
                group=a.get("group", 1),
                auto_pad=a.get("auto_pad", "NOTSET"))


@onnx_op("MaxPool")
def _max_pool(imp, node):
    a = node.attrs()
    if a.get("ceil_mode", 0):
        raise ONNXImportError("MaxPool ceil_mode=1 unsupported")
    if len(node.output) > 1 and node.output[1]:
        raise ONNXImportError("MaxPool Indices output unsupported")
    return _rec(imp, "onnximport.max_pool", [imp.tensor(node.input[0])],
                kernel_shape=a["kernel_shape"], strides=a.get("strides"),
                pads=a.get("pads"), auto_pad=a.get("auto_pad", "NOTSET"))


@onnx_op("AveragePool")
def _avg_pool(imp, node):
    a = node.attrs()
    if a.get("ceil_mode", 0):
        raise ONNXImportError("AveragePool ceil_mode=1 unsupported")
    return _rec(imp, "onnximport.average_pool", [imp.tensor(node.input[0])],
                kernel_shape=a["kernel_shape"], strides=a.get("strides"),
                pads=a.get("pads"),
                count_include_pad=a.get("count_include_pad", 0),
                auto_pad=a.get("auto_pad", "NOTSET"))


@onnx_op("GlobalAveragePool")
def _gap(imp, node):
    return _rec(imp, "onnximport.global_average_pool",
                [imp.tensor(node.input[0])])


@onnx_op("BatchNormalization")
def _bn(imp, node):
    a = node.attrs()
    if a.get("training_mode", 0):
        raise ONNXImportError("BatchNormalization training_mode=1 unsupported")
    ins = [imp.tensor(r) for r in node.input[:5]]
    return _rec(imp, "onnximport.batch_norm", ins,
                epsilon=a.get("epsilon", 1e-5))


@onnx_op("LayerNormalization")
def _ln(imp, node):
    a = node.attrs()
    ins = [imp.tensor(r) for r in node.input if r]
    return _rec(imp, "onnximport.layer_norm", ins,
                axis=a.get("axis", -1), epsilon=a.get("epsilon", 1e-5))


@onnx_op("Reshape")
def _reshape(imp, node):
    shape = [int(v) for v in imp.const_value(node.input[1]).reshape(-1)]
    return _rec(imp, "onnximport.reshape", [imp.tensor(node.input[0])],
                shape=shape, allowzero=node.attrs().get("allowzero", 0))


@onnx_op("Flatten")
def _flatten(imp, node):
    return _rec(imp, "onnximport.flatten", [imp.tensor(node.input[0])],
                axis=node.attrs().get("axis", 1))


@onnx_op("Transpose")
def _transpose(imp, node):
    return _rec(imp, "onnximport.transpose", [imp.tensor(node.input[0])],
                perm=node.attrs().get("perm"))


@onnx_op("Concat")
def _concat(imp, node):
    ins = [imp.tensor(r) for r in node.input]
    return _rec(imp, "onnximport.concat", ins, axis=node.attrs()["axis"])


@onnx_op("Softmax")
def _softmax(imp, node):
    return _rec(imp, "onnximport.softmax", [imp.tensor(node.input[0])],
                axis=node.attrs().get("axis", -1))


@onnx_op("LogSoftmax")
def _log_softmax(imp, node):
    return _rec(imp, "onnximport.log_softmax", [imp.tensor(node.input[0])],
                axis=node.attrs().get("axis", -1))


@onnx_op("LeakyRelu")
def _leaky(imp, node):
    return _rec(imp, "onnximport.leaky_relu", [imp.tensor(node.input[0])],
                alpha=node.attrs().get("alpha", 0.01))


@onnx_op("Elu")
def _elu(imp, node):
    return _rec(imp, "onnximport.elu", [imp.tensor(node.input[0])],
                alpha=node.attrs().get("alpha", 1.0))


@onnx_op("HardSigmoid")
def _hard_sigmoid(imp, node):
    a = node.attrs()
    return _rec(imp, "onnximport.hard_sigmoid", [imp.tensor(node.input[0])],
                alpha=a.get("alpha", 0.2), beta=a.get("beta", 0.5))


@onnx_op("LRN")
def _lrn(imp, node):
    a = node.attrs()
    return _rec(imp, "onnximport.lrn", [imp.tensor(node.input[0])],
                size=a["size"], alpha=a.get("alpha", 1e-4),
                beta=a.get("beta", 0.75), bias=a.get("bias", 1.0))


@onnx_op("Clip")
def _clip(imp, node):
    a = node.attrs()
    lo = a.get("min")
    hi = a.get("max")
    if len(node.input) > 1 and node.input[1]:
        lo = float(imp.const_value(node.input[1]))
    if len(node.input) > 2 and node.input[2]:
        hi = float(imp.const_value(node.input[2]))
    return _rec(imp, "onnximport.clip", [imp.tensor(node.input[0])],
                lo=lo, hi=hi)


@onnx_op("Gather")
def _gather(imp, node):
    ins = [imp.tensor(node.input[0]), imp.tensor(node.input[1])]
    return _rec(imp, "onnximport.gather", ins,
                axis=node.attrs().get("axis", 0))


def _axes_attr_or_input(imp, node, idx=1):
    axes = node.attrs().get("axes")
    if axes is None and len(node.input) > idx and node.input[idx]:
        axes = [int(v) for v in imp.const_value(node.input[idx]).reshape(-1)]
    return axes


@onnx_op("Unsqueeze")
def _unsqueeze(imp, node):
    axes = _axes_attr_or_input(imp, node)
    if axes is None:
        raise ONNXImportError("Unsqueeze needs axes")
    return _rec(imp, "onnximport.unsqueeze", [imp.tensor(node.input[0])],
                axes=axes)


@onnx_op("Squeeze")
def _squeeze(imp, node):
    return _rec(imp, "onnximport.squeeze", [imp.tensor(node.input[0])],
                axes=_axes_attr_or_input(imp, node))


@onnx_op("Slice")
def _slice(imp, node):
    a = node.attrs()
    if "starts" in a:  # opset < 10: attributes
        starts, ends = a["starts"], a["ends"]
        axes, steps = a.get("axes"), None
    else:
        starts = [int(v) for v in imp.const_value(node.input[1]).reshape(-1)]
        ends = [int(v) for v in imp.const_value(node.input[2]).reshape(-1)]
        axes = steps = None
        if len(node.input) > 3 and node.input[3]:
            axes = [int(v) for v in imp.const_value(node.input[3]).reshape(-1)]
        if len(node.input) > 4 and node.input[4]:
            steps = [int(v) for v in imp.const_value(node.input[4]).reshape(-1)]
    return _rec(imp, "onnximport.slice", [imp.tensor(node.input[0])],
                starts=list(starts), ends=list(ends), axes=axes, steps=steps)


@onnx_op("Pad")
def _pad(imp, node):
    a = node.attrs()
    mode = a.get("mode", "constant")
    if "pads" in a:  # opset < 11
        pads = a["pads"]
        cval = a.get("value", 0.0)
    else:
        pads = [int(v) for v in imp.const_value(node.input[1]).reshape(-1)]
        cval = 0.0
        if len(node.input) > 2 and node.input[2]:
            cval = float(imp.const_value(node.input[2]))
    return _rec(imp, "onnximport.pad", [imp.tensor(node.input[0])],
                pads=list(pads), constant_value=cval, mode=mode)


@onnx_op("ReduceMean", "ReduceSum", "ReduceMax", "ReduceMin", "ReduceProd")
def _reduce(imp, node):
    kind = node.op_type[len("Reduce"):].lower()
    a = node.attrs()
    axes = _axes_attr_or_input(imp, node)
    return _rec(imp, f"onnximport.reduce_{kind}", [imp.tensor(node.input[0])],
                axes=axes, keepdims=a.get("keepdims", 1),
                noop_with_empty_axes=a.get("noop_with_empty_axes", 0))


@onnx_op("Cast")
def _cast(imp, node):
    return _rec(imp, "onnximport.cast", [imp.tensor(node.input[0])],
                to=node.attrs()["to"])


@onnx_op("Expand")
def _expand(imp, node):
    shape = [int(v) for v in imp.const_value(node.input[1]).reshape(-1)]
    return _rec(imp, "onnximport.expand", [imp.tensor(node.input[0])],
                shape=shape)


@onnx_op("Gelu")
def _gelu(imp, node):
    approximate = node.attrs().get("approximate", "none")
    return _rec(imp, "onnximport.gelu", [imp.tensor(node.input[0])],
                approximate=approximate == "tanh")


@onnx_op("Shape")
def _shape(imp, node):
    v = imp.tensor(node.input[0])
    if v.shape is None or any(d is None for d in v.shape):
        raise ONNXImportError(
            f"Shape of {node.input[0]!r} is not fully static at import")
    arr = np.asarray(v.shape, np.int64)
    name = imp.fresh_const_name(node.name or "shape")
    imp.consts[node.output[0]] = arr
    return imp.sd.constant(name, arr)


@onnx_op("Constant")
def _constant(imp, node):
    a = {at.name: at for at in node.attribute}
    if "value" in a and a["value"].type == ATTR_TENSOR:
        arr = a["value"].t.to_numpy()
    elif "value_float" in a:
        arr = np.asarray(a["value_float"].f, np.float32)
    elif "value_int" in a:
        arr = np.asarray(a["value_int"].i, np.int64)
    elif "value_floats" in a:
        arr = np.asarray(list(a["value_floats"].floats), np.float32)
    elif "value_ints" in a:
        arr = np.asarray(list(a["value_ints"].ints), np.int64)
    else:
        raise ONNXImportError(f"Constant node {node.name!r}: no value attr")
    imp.consts[node.output[0]] = arr
    return imp.sd.constant(imp.fresh_const_name(node.name or "const"), arr)


@onnx_op("Dropout")
def _dropout(imp, node):
    # Inference import: identity (mask output unsupported).
    if len(node.output) > 1 and node.output[1]:
        raise ONNXImportError("Dropout mask output unsupported")
    return imp.tensor(node.input[0])


@onnx_op("Identity")
def _identity(imp, node):
    v = imp.tensor(node.input[0])
    if node.input[0] in imp.consts:
        imp.consts[node.output[0]] = imp.consts[node.input[0]]
    return v


# --- the importer ----------------------------------------------------------


class _GraphImporter:
    """Walks GraphProto nodes, emitting SameDiff ops via the registry
    (↔ samediff-import-onnx's OnnxFrameworkImporter)."""

    def __init__(self, graph: GraphProto, input_shapes: Dict[str, Tuple],
                 sd: SameDiff):
        self.g = graph
        self.sd = sd
        self.input_shapes = input_shapes
        self.vars: Dict[str, Any] = {}   # onnx value name -> SDVariable
        self.consts: Dict[str, np.ndarray] = {}

    def tensor(self, ref: str) -> SDVariable:
        v = self.vars.get(ref)
        if v is None:
            raise ONNXImportError(f"value {ref!r} produced by unknown node")
        return v

    def const_value(self, ref: str) -> np.ndarray:
        if ref not in self.consts:
            raise ONNXImportError(
                f"op needs host-known constant for {ref!r} (shapes/axes/pads "
                "must be initializers or Constant nodes)")
        return self.consts[ref]

    def fresh_const_name(self, base: str) -> str:
        name = base or "const"
        i = 0
        while name in self.sd._vars:
            i += 1
            name = f"{base}__{i}"
        return name

    def run(self, outputs: Sequence[str]) -> Dict[str, str]:
        init_names = set()
        for t in self.g.initializer:
            arr = t.to_numpy()
            self.consts[t.name] = arr
            self.vars[t.name] = self.sd.constant(
                self.fresh_const_name(t.name), arr)
            init_names.add(t.name)

        for vi in self.g.input:
            if vi.name in init_names:
                continue
            shape = self.input_shapes.get(vi.name)
            if shape is None:
                if vi.type is None or vi.type.shape is None:
                    raise ONNXImportError(
                        f"graph input {vi.name!r} needs an input_shapes entry")
                shape = tuple(d if isinstance(d, int) and d > 0 else None
                              for d in vi.type.shape.dims)
            dtype = TENSOR_DTYPES.get(
                vi.type.elem_type if vi.type else 1, "float32")
            self.vars[vi.name] = self.sd.placeholder(vi.name, shape, dtype)

        for node in self.g.node:
            if node.domain not in ("", "ai.onnx"):
                raise ONNXImportError(
                    f"unsupported op domain {node.domain!r} ({node.op_type})")
            mapper = ONNX_OP_MAPPERS.get(node.op_type)
            if mapper is None:
                raise ONNXImportError(
                    f"no mapper for ONNX op {node.op_type!r} (node "
                    f"{node.name!r}); supported: {sorted(ONNX_OP_MAPPERS)}")
            result = mapper(self, node)
            outs = result if isinstance(result, tuple) else (result,)
            for ref, var in zip(node.output, outs):
                if ref:
                    self.vars[ref] = var

        return {out: self.tensor(out).name for out in outputs}


def import_onnx_model(
    model,
    inputs: Optional[Dict[str, Tuple]] = None,
    outputs: Optional[Sequence[str]] = None,
) -> Tuple[SameDiff, Dict[str, str], Dict[str, str]]:
    """Import an ONNX model (path, bytes, or decoded ModelProto).

    inputs: optional {graph_input_name: shape} overriding/providing input
    shapes (None dims allowed for batch). outputs: graph value names to
    expose; default = the graph's declared outputs.

    Returns (sd, input_map, output_map): ONNX value names → SameDiff
    variable names. Mirrors modelimport.tf.import_tf_graph.
    """
    ensure_onnximport_ops()
    if isinstance(model, (str, bytes)):
        data = open(model, "rb").read() if isinstance(model, str) else model
        model = ModelProto.decode(data)
    if model.graph is None:
        raise ONNXImportError("model has no graph")
    g = model.graph
    if outputs is None:
        outputs = [v.name for v in g.output]
    sd = SameDiff.create()
    imp = _GraphImporter(g, dict(inputs or {}), sd)
    out_map = imp.run(list(outputs))
    init_names = {t.name for t in g.initializer}
    in_map = {v.name: v.name for v in g.input if v.name not in init_names}
    return sd, in_map, out_map
