"""ONNX model import → SameDiff program (↔ samediff-import-onnx, SURVEY §2.3).

ref: nd4j/samediff-import-onnx (OpMappingRegistry over ONNX NodeProto) —
the same per-op mapper-registry architecture as modelimport/tf.py, reading
the model through the dependency-free wire codec in onnx_proto.py. The
TPU-era difference is downstream: the imported graph compiles as ONE XLA
program instead of per-op interpretation.

Layout: ONNX is NCHW; the imported graph stays NCHW (XLA convolutions take
explicit dimension_numbers, so there is no transposition tax at import).

Policy (same as keras/tf importers): strict refusal — an op or attribute
combination outside the mapped surface raises ONNXImportError rather than
silently importing a wrong graph.

Oracle testing: tests/test_onnx_import.py builds fixture .onnx files with
onnx_proto, verifies the wire format against the `protoc` binary, and
compares imported-graph outputs against torch executing the same weights.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import (
    OP_REGISTRY,
    SameDiff,
    SDVariable,
    VariableType,
    register_op,
)
from deeplearning4j_tpu.modelimport.onnx_proto import (
    ATTR_TENSOR,
    GraphProto,
    ModelProto,
    NodeProto,
    TENSOR_DTYPES,
)


class ONNXImportError(Exception):
    pass


# --- jax ops the mappers target (registered under onnximport.*) ------------


def _register_onnximport_ops():
    import jax
    import jax.numpy as jnp

    def gemm(a, b, c=None, alpha=1.0, beta=1.0, trans_a=0, trans_b=0):
        if trans_a:
            a = a.T
        if trans_b:
            b = b.T
        y = alpha * jnp.matmul(a, b)
        if c is not None:
            y = y + beta * c
        return y

    def conv(x, w, b=None, strides=(1, 1), pads=None, dilations=(1, 1),
             group=1, auto_pad="NOTSET"):
        nd = x.ndim - 2
        if auto_pad in ("SAME_UPPER", "SAME_LOWER"):
            # lax 'SAME' pads the lower side first... actually SAME puts the
            # extra pad at the end (upper), matching SAME_UPPER.
            if auto_pad == "SAME_LOWER":
                raise NotImplementedError("auto_pad=SAME_LOWER")
            padding = "SAME"
        elif auto_pad == "VALID" or pads is None:
            padding = [(0, 0)] * nd
        else:
            padding = [(int(pads[i]), int(pads[i + nd])) for i in range(nd)]
        spec = ("NCHW", "OIHW", "NCHW") if nd == 2 else None
        if nd == 1:
            # Run 1D conv as 2D with a unit height axis.
            x2 = x[:, :, None, :]
            w2 = w[:, :, None, :]
            pad2 = "SAME" if padding == "SAME" else [(0, 0)] + list(padding)
            y = jax.lax.conv_general_dilated(
                x2, w2, window_strides=(1,) + tuple(strides),
                padding=pad2, rhs_dilation=(1,) + tuple(dilations),
                dimension_numbers=("NCHW", "OIHW", "NCHW"),
                feature_group_count=group)
            y = y[:, :, 0, :]
        elif nd == 2:
            y = jax.lax.conv_general_dilated(
                x, w, window_strides=tuple(strides), padding=padding,
                rhs_dilation=tuple(dilations), dimension_numbers=spec,
                feature_group_count=group)
        elif nd == 3:
            y = jax.lax.conv_general_dilated(
                x, w, window_strides=tuple(strides), padding=padding,
                rhs_dilation=tuple(dilations),
                dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
                feature_group_count=group)
        else:
            raise NotImplementedError(f"Conv rank {x.ndim}")
        if b is not None:
            y = y + b.reshape((1, -1) + (1,) * nd)
        return y

    def _pool_padding(pads, nd, auto_pad):
        if auto_pad in ("SAME_UPPER",):
            return "SAME"
        if auto_pad == "SAME_LOWER":
            raise NotImplementedError("auto_pad=SAME_LOWER")
        if pads is None:
            return [(0, 0)] * nd
        return [(int(pads[i]), int(pads[i + nd])) for i in range(nd)]

    def max_pool(x, kernel_shape, strides=None, pads=None, auto_pad="NOTSET"):
        nd = len(kernel_shape)
        strides = tuple(strides) if strides else tuple(kernel_shape)
        padding = _pool_padding(pads, nd, auto_pad)
        window = (1, 1) + tuple(kernel_shape)
        stride = (1, 1) + strides
        if padding == "SAME":
            pad_cfg = "SAME"
        else:
            pad_cfg = [(0, 0), (0, 0)] + list(padding)
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, window, stride, pad_cfg)

    def average_pool(x, kernel_shape, strides=None, pads=None,
                     count_include_pad=0, auto_pad="NOTSET"):
        nd = len(kernel_shape)
        strides = tuple(strides) if strides else tuple(kernel_shape)
        padding = _pool_padding(pads, nd, auto_pad)
        window = (1, 1) + tuple(kernel_shape)
        stride = (1, 1) + strides
        pad_cfg = "SAME" if padding == "SAME" else [(0, 0), (0, 0)] + list(padding)
        summed = jax.lax.reduce_window(
            x, 0.0, jax.lax.add, window, stride, pad_cfg)
        if count_include_pad:
            # Fixed kernel-size denominator — correct however the padding
            # was expressed (explicit pads or auto_pad=SAME_*).
            denom = float(np.prod(kernel_shape))
            return summed / denom
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(
            ones, 0.0, jax.lax.add, window, stride, pad_cfg)
        return summed / counts

    def global_average_pool(x):
        return jnp.mean(x, axis=tuple(range(2, x.ndim)), keepdims=True)

    def batch_norm(x, scale, bias, mean, var, epsilon=1e-5):
        shape = (1, -1) + (1,) * (x.ndim - 2)
        inv = scale.reshape(shape) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
        return x * inv + (bias.reshape(shape) - mean.reshape(shape) * inv)

    def layer_norm(x, scale, bias=None, axis=-1, epsilon=1e-5):
        axes = tuple(range(axis % x.ndim, x.ndim))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.var(x, axis=axes, keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + epsilon) * scale
        if bias is not None:
            y = y + bias
        return y

    def reshape_onnx(x, shape, allowzero=0):
        shape = list(shape)
        for i, d in enumerate(shape):
            if d == 0 and not allowzero:
                shape[i] = x.shape[i]
        return jnp.reshape(x, shape)

    def flatten(x, axis=1):
        if axis < 0:
            axis += x.ndim  # ONNX: negative axis counts from the rank
        lead = int(np.prod(x.shape[:axis])) if axis else 1
        return jnp.reshape(x, (lead, -1))

    def slice_onnx(x, starts, ends, axes=None, steps=None):
        axes = list(range(len(starts))) if axes is None else list(axes)
        steps = [1] * len(starts) if steps is None else list(steps)
        idx = [slice(None)] * x.ndim
        for st, en, ax, sp in zip(starts, ends, axes, steps):
            ax = ax % x.ndim
            dim = x.shape[ax]
            st, en = int(st), int(en)
            # ONNX clamps out-of-range (INT_MAX endpoints are idiomatic).
            if st > dim:
                st = dim
            if en > dim:
                en = dim
            idx[ax] = slice(st, en, int(sp))
        return x[tuple(idx)]

    def pad_onnx(x, pads, constant_value=0.0, mode="constant"):
        nd = x.ndim
        widths = [(int(pads[i]), int(pads[i + nd])) for i in range(nd)]
        if mode == "constant":
            return jnp.pad(x, widths, constant_values=constant_value)
        if mode == "reflect":
            return jnp.pad(x, widths, mode="reflect")
        if mode == "edge":
            return jnp.pad(x, widths, mode="edge")
        raise NotImplementedError(f"Pad mode {mode}")

    def reduce_op(kind):
        fns = {"mean": jnp.mean, "sum": jnp.sum, "max": jnp.max,
               "min": jnp.min, "prod": jnp.prod}

        def f(x, axes=None, keepdims=1, noop_with_empty_axes=0):
            if axes is None or len(axes) == 0:
                # ONNX: empty/absent axes reduces ALL dims unless
                # noop_with_empty_axes=1 (then identity).
                if noop_with_empty_axes:
                    return x
                axes = None
            else:
                axes = tuple(int(a) for a in axes)
            return fns[kind](x, axis=axes, keepdims=bool(keepdims))

        return f

    def cast(x, to):
        if to not in TENSOR_DTYPES:
            raise NotImplementedError(f"Cast to ONNX dtype {to}")
        return x.astype(TENSOR_DTYPES[to])

    def hard_sigmoid(x, alpha=0.2, beta=0.5):
        return jnp.clip(alpha * x + beta, 0.0, 1.0)

    def lrn(x, size, alpha=1e-4, beta=0.75, bias=1.0):
        # ONNX LRN: across channels (axis 1), window `size` centered.
        half_lo = (size - 1) // 2
        half_hi = size - 1 - half_lo
        sq = jnp.square(x)
        acc = jax.lax.reduce_window(
            sq, 0.0, jax.lax.add,
            (1, size, 1, 1), (1, 1, 1, 1),
            [(0, 0), (half_lo, half_hi), (0, 0), (0, 0)])
        return x / jnp.power(bias + (alpha / size) * acc, beta)

    for name, fn in {
        "gemm": gemm, "conv": conv, "max_pool": max_pool,
        "average_pool": average_pool,
        "global_average_pool": global_average_pool,
        "batch_norm": batch_norm, "layer_norm": layer_norm,
        "reshape": reshape_onnx, "flatten": flatten, "slice": slice_onnx,
        "pad": pad_onnx, "cast": cast, "hard_sigmoid": hard_sigmoid,
        "lrn": lrn,
        "reduce_mean": reduce_op("mean"), "reduce_sum": reduce_op("sum"),
        "reduce_max": reduce_op("max"), "reduce_min": reduce_op("min"),
        "reduce_prod": reduce_op("prod"),
        "matmul": jnp.matmul,
        "transpose": lambda x, perm=None: jnp.transpose(x, perm),
        "concat": lambda *xs, axis: jnp.concatenate(xs, axis=axis),
        "softmax": lambda x, axis=-1: jax.nn.softmax(x, axis=axis),
        "log_softmax": lambda x, axis=-1: jax.nn.log_softmax(x, axis=axis),
        "leaky_relu": lambda x, alpha=0.01: jnp.where(x >= 0, x, alpha * x),
        "elu": lambda x, alpha=1.0: jnp.where(x >= 0, x, alpha * (jnp.exp(x) - 1)),
        "clip": lambda x, lo=None, hi=None: jnp.clip(x, lo, hi),
        "gather": lambda x, idx, axis=0: jnp.take(x, idx.astype("int32"), axis=axis),
        "unsqueeze": lambda x, axes: jnp.expand_dims(x, tuple(int(a) for a in axes)),
        "squeeze": lambda x, axes=None: jnp.squeeze(
            x, None if axes is None else tuple(int(a) for a in axes)),
        "where": jnp.where,
        "erf": jax.lax.erf,
        "gelu": jax.nn.gelu,
        "prelu": lambda x, slope: jnp.where(x >= 0, x, slope * x),
        "expand": lambda x, shape: jnp.broadcast_to(
            x, np.broadcast_shapes(tuple(x.shape), tuple(shape))),
    }.items():
        register_op(f"onnximport.{name}", fn)


def _register_onnximport_ops_ext():
    """Round-4 breadth extension: the op surface real exported models use
    beyond the classic-CNN/transformer core (recurrent ops, resize,
    normalizations, multi-output split/topk, extended reductions)."""
    import jax
    import jax.numpy as jnp

    def mod(a, b, fmod=0):
        return jnp.fmod(a, b) if fmod else jnp.mod(a, b)

    def is_inf(x, detect_negative=1, detect_positive=1):
        pos = jnp.isposinf(x) if detect_positive else jnp.zeros_like(x, bool)
        neg = jnp.isneginf(x) if detect_negative else jnp.zeros_like(x, bool)
        return pos | neg

    def thresholded_relu(x, alpha=1.0):
        return jnp.where(x > alpha, x, 0.0)

    def celu(x, alpha=1.0):
        return jnp.maximum(x, 0.0) + jnp.minimum(
            0.0, alpha * (jnp.exp(x / alpha) - 1.0))

    def shrink(x, bias=0.0, lambd=0.5):
        return jnp.where(x < -lambd, x + bias,
                         jnp.where(x > lambd, x - bias, 0.0))

    def hard_swish(x):
        return x * jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)

    def mish(x):
        return x * jnp.tanh(jax.nn.softplus(x))

    def arg_extreme(kind):
        fn = jnp.argmax if kind == "max" else jnp.argmin

        def f(x, axis=0, keepdims=1):
            out = fn(x, axis=axis).astype(jnp.int64)
            if keepdims:
                out = jnp.expand_dims(out, axis)
            return out

        return f

    def top_k(x, k, axis=-1, largest=1, sorted=1):  # noqa: A002
        if axis % x.ndim != x.ndim - 1:
            x = jnp.moveaxis(x, axis, -1)
        vals, idx = jax.lax.top_k(-x if not largest else x, int(k))
        if not largest:
            vals = -vals
        if axis % x.ndim != x.ndim - 1:
            vals = jnp.moveaxis(vals, -1, axis)
            idx = jnp.moveaxis(idx, -1, axis)
        return vals, idx.astype(jnp.int64)

    def one_hot(indices, values, *, depth, axis=-1):
        off, on = values[0], values[1]
        idx = indices.astype(jnp.int32)
        idx = jnp.where(idx < 0, idx + int(depth), idx)  # ONNX wraps negatives
        oh = jax.nn.one_hot(idx, int(depth), axis=axis)
        return oh * (on - off) + off

    def cumsum(x, axis, exclusive=0, reverse=0):
        ax = int(axis)
        if reverse:
            x = jnp.flip(x, ax)
        out = jnp.cumsum(x, axis=ax)
        if exclusive:
            out = out - x
        if reverse:
            out = jnp.flip(out, ax)
        return out

    def einsum(*xs, equation):
        return jnp.einsum(equation, *xs)

    def reduce_ext(kind):
        def f(x, axes=None, keepdims=1, noop_with_empty_axes=0):
            if axes is None or len(axes) == 0:
                if noop_with_empty_axes:
                    return x
                ax = None
            else:
                ax = tuple(int(a) for a in axes)
            kd = bool(keepdims)
            if kind == "l1":
                return jnp.sum(jnp.abs(x), axis=ax, keepdims=kd)
            if kind == "l2":
                return jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=kd))
            if kind == "log_sum":
                return jnp.log(jnp.sum(x, axis=ax, keepdims=kd))
            if kind == "log_sum_exp":
                return jax.scipy.special.logsumexp(x, axis=ax, keepdims=kd)
            if kind == "sum_square":
                return jnp.sum(jnp.square(x), axis=ax, keepdims=kd)
            raise ValueError(kind)

        return f

    def depth_to_space(x, blocksize, mode="DCR"):
        n, c, h, w = x.shape
        b = blocksize
        if mode == "DCR":
            y = x.reshape(n, b, b, c // (b * b), h, w)
            y = y.transpose(0, 3, 4, 1, 5, 2)
        else:  # CRD
            y = x.reshape(n, c // (b * b), b, b, h, w)
            y = y.transpose(0, 1, 4, 2, 5, 3)
        return y.reshape(n, c // (b * b), h * b, w * b)

    def space_to_depth(x, blocksize):
        n, c, h, w = x.shape
        b = blocksize
        y = x.reshape(n, c, h // b, b, w // b, b)
        y = y.transpose(0, 3, 5, 1, 2, 4)
        return y.reshape(n, c * b * b, h // b, w // b)

    def global_max_pool(x):
        return jnp.max(x, axis=tuple(range(2, x.ndim)), keepdims=True)

    def conv_transpose(x, w, b=None, strides=(1, 1), pads=None, group=1):
        # ONNX/torch weight layout [Cin, Cout/g, *k]; gradient semantics →
        # lax.conv_transpose(transpose_kernel=True) with IOHW numbers.
        nd = x.ndim - 2
        if group != 1:
            raise NotImplementedError("ConvTranspose group != 1")
        pads = [(0, 0)] * nd if pads is None else [
            (int(pads[i]), int(pads[i + nd])) for i in range(nd)]
        # ONNX weight [Cin, Cout/g, *k] is exactly the FORWARD conv's OIHW
        # kernel whose input-gradient this op computes; transpose_kernel=
        # True then swaps I/O and flips spatial axes (torch/Keras
        # gradient-deconv semantics).
        dn = (("NCHW", "OIHW", "NCHW") if nd == 2
              else ("NCDHW", "OIDHW", "NCDHW") if nd == 3
              else None)
        if dn is None:
            raise NotImplementedError(f"ConvTranspose rank {x.ndim}")
        y = jax.lax.conv_transpose(
            x, w, strides=tuple(strides), padding=pads,
            dimension_numbers=dn, transpose_kernel=True)
        if b is not None:
            y = y + b.reshape((1, -1) + (1,) * nd)
        return y

    def instance_norm(x, scale, bias, epsilon=1e-5):
        axes = tuple(range(2, x.ndim))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.var(x, axis=axes, keepdims=True)
        shape = (1, -1) + (1,) * (x.ndim - 2)
        return ((x - mean) * jax.lax.rsqrt(var + epsilon)
                * scale.reshape(shape) + bias.reshape(shape))

    def group_norm(x, scale, bias, num_groups, epsilon=1e-5):
        n, c = x.shape[:2]
        spatial = x.shape[2:]
        g = int(num_groups)

        def per_channel(p):
            # Opset 21: scale/bias are per-channel [C]. Opset 18 defined
            # them per-GROUP [G]; broadcast each group value across its
            # C/G channels (when G == C the two readings coincide).
            if p.shape[0] == c:
                return p
            if p.shape[0] == g:
                return jnp.repeat(p, c // g)
            raise ValueError(
                f"GroupNormalization: scale/bias length {p.shape[0]} "
                f"matches neither channels ({c}) nor num_groups ({g})")

        y = x.reshape(n, g, c // g, *spatial)
        axes = tuple(range(2, y.ndim))
        mean = jnp.mean(y, axis=axes, keepdims=True)
        var = jnp.var(y, axis=axes, keepdims=True)
        y = ((y - mean) * jax.lax.rsqrt(var + epsilon)).reshape(x.shape)
        shape = (1, -1) + (1,) * (x.ndim - 2)
        return (y * per_channel(scale).reshape(shape)
                + per_channel(bias).reshape(shape))

    def split(x, axis=0, split_sizes=None, num_outputs=None):
        if split_sizes is None:
            # Split-18 spec for num_outputs on a non-divisible axis:
            # chunk = ceil(dim / k), last chunk smaller. jnp.split would
            # raise on uneven dims (and the error surfaces at the wrong
            # node once _infer swallows it).
            k = int(num_outputs)
            dim = x.shape[axis]
            chunk = -(-dim // k)
            split_sizes = [chunk] * (k - 1) + [dim - chunk * (k - 1)]
            if split_sizes[-1] <= 0:
                raise ValueError(
                    f"Split: num_outputs={k} too large for axis dim {dim}")
        idxs = np.cumsum(split_sizes)[:-1].tolist()
        return tuple(jnp.split(x, idxs, axis=axis))

    def gather_elements(x, idx, axis=0):
        return jnp.take_along_axis(x, idx.astype(jnp.int32), axis=axis)

    def trilu(x, k=0, upper=1):
        return jnp.triu(x, int(k)) if upper else jnp.tril(x, int(k))

    def resize_nearest_int(x, scales):
        # integer-factor nearest with asymmetric coords == exact repeat
        y = x
        for ax, s in enumerate(scales):
            if s != 1:
                y = jnp.repeat(y, int(s), axis=ax)
        return y

    def resize_linear_half_pixel(x, out_shape):
        # half_pixel via jax.image.resize, whose coordinate transform uses
        # the EFFECTIVE ratio out/in. When the node carried fractional
        # `scales`, sizes = floor(d*s) and ORT would keep the raw scale in
        # the transform — a documented sub-pixel divergence, identical
        # whenever d*s is integral (the overwhelmingly common case).
        import jax.image

        return jax.image.resize(x, tuple(int(d) for d in out_shape),
                                method="linear", antialias=False)

    def _lstm_direction(x_tm, w, r, wb, h0, c0, hidden, reverse):
        """One ONNX LSTM direction: x_tm [T,N,In]; w [4H,In] r [4H,H]
        b [4H] in ONNX iofc gate blocks. Returns (ys [T,N,H], hT, cT)."""
        from deeplearning4j_tpu.ops import rnn as opsrnn

        H = hidden
        order = jnp.concatenate([  # iofc -> ifgo row blocks
            jnp.arange(0, H), jnp.arange(2 * H, 3 * H),
            jnp.arange(3 * H, 4 * H), jnp.arange(H, 2 * H)])
        w_x = jnp.take(w, order, axis=0).T      # [In, 4H]
        w_h = jnp.take(r, order, axis=0).T      # [H, 4H]
        b = jnp.take(wb, order, axis=0) if wb is not None else None
        x_nm = jnp.swapaxes(x_tm, 0, 1)         # [N, T, In]
        init = None
        if h0 is not None or c0 is not None:
            ref = h0 if h0 is not None else c0
            init = opsrnn.LSTMState(
                jnp.zeros_like(ref) if h0 is None else h0,
                jnp.zeros_like(ref) if c0 is None else c0)
        ys, st = opsrnn.lstm(x_nm, w_x, w_h, b, init_state=init,
                             reverse=bool(reverse))
        return jnp.swapaxes(ys, 0, 1), st.h, st.c

    def lstm(*ins, hidden_size, direction="forward", present=()):
        """ONNX LSTM, layout=0: x [T,N,In], w [D,4H,In], r [D,4H,H],
        b [D,8H]. Default activations only. Y [T,D,N,H], Y_h/Y_c [D,N,H].
        ``present`` names which optional inputs follow x/w/r (ONNX leaves
        gaps via empty-string input refs)."""
        it = iter(ins)
        x, w, r = next(it), next(it), next(it)
        b = next(it) if "b" in present else None
        h0 = next(it) if "h0" in present else None
        c0 = next(it) if "c0" in present else None
        H = int(hidden_size)
        dirs = 2 if direction == "bidirectional" else 1
        outs = []
        for d in range(dirs):
            wb = None
            if b is not None:
                wb = b[d, :4 * H] + b[d, 4 * H:]
            rev = (direction == "reverse") or d == 1
            ys, hT, cT = _lstm_direction(
                x, w[d], r[d], wb,
                None if h0 is None else h0[d],
                None if c0 is None else c0[d], H, rev)
            outs.append((ys, hT, cT))
        y = jnp.stack([o[0] for o in outs], axis=1)          # [T,D,N,H]
        y_h = jnp.stack([o[1] for o in outs], axis=0)        # [D,N,H]
        y_c = jnp.stack([o[2] for o in outs], axis=0)
        return y, y_h, y_c

    def gru(*ins, hidden_size, direction="forward", present=()):
        """ONNX GRU, layout=0, linear_before_reset=0, Rb_h must be zero
        (validated at import): x [T,N,In], w [D,3H,In], r [D,3H,H],
        b [D,6H]. Y [T,D,N,H], Y_h [D,N,H]."""
        from deeplearning4j_tpu.ops import rnn as opsrnn

        it = iter(ins)
        x, w, r = next(it), next(it), next(it)
        b = next(it) if "b" in present else None
        h0 = next(it) if "h0" in present else None
        H = int(hidden_size)
        dirs = 2 if direction == "bidirectional" else 1
        order = jnp.concatenate([  # zrh -> rzn row blocks
            jnp.arange(H, 2 * H), jnp.arange(0, H),
            jnp.arange(2 * H, 3 * H)])
        ys_all, h_all = [], []
        for d in range(dirs):
            w_x = jnp.take(w[d], order, axis=0).T
            w_h = jnp.take(r[d], order, axis=0).T
            bb = None
            if b is not None:
                wb, rb = b[d, :3 * H], b[d, 3 * H:]
                bb = jnp.take(wb, order, axis=0) + jnp.concatenate(
                    [jnp.take(rb, order, axis=0)[:2 * H], jnp.zeros((H,))])
            rev = (direction == "reverse") or d == 1
            x_nm = jnp.swapaxes(x, 0, 1)
            ys, hT = opsrnn.gru(x_nm, w_x, w_h, bb,
                                init_h=None if h0 is None else h0[d],
                                reverse=rev)
            ys_all.append(jnp.swapaxes(ys, 0, 1))
            h_all.append(hT)
        return (jnp.stack(ys_all, axis=1), jnp.stack(h_all, axis=0))

    for name, fn in {
        "mod": mod, "is_inf": is_inf, "thresholded_relu": thresholded_relu,
        "celu": celu, "shrink": shrink, "hard_swish": hard_swish,
        "mish": mish,
        "argmax": arg_extreme("max"), "argmin": arg_extreme("min"),
        "top_k": top_k, "one_hot": one_hot, "cumsum": cumsum,
        "einsum": einsum,
        "reduce_l1": reduce_ext("l1"), "reduce_l2": reduce_ext("l2"),
        "reduce_log_sum": reduce_ext("log_sum"),
        "reduce_log_sum_exp": reduce_ext("log_sum_exp"),
        "reduce_sum_square": reduce_ext("sum_square"),
        "depth_to_space": depth_to_space, "space_to_depth": space_to_depth,
        "global_max_pool": global_max_pool,
        "conv_transpose": conv_transpose,
        "instance_norm": instance_norm, "group_norm": group_norm,
        "split": split, "gather_elements": gather_elements, "trilu": trilu,
        "resize_nearest_int": resize_nearest_int,
        "resize_linear_half_pixel": resize_linear_half_pixel,
        "lstm": lstm, "gru": gru,
        "tile": lambda x, repeats: jnp.tile(x, tuple(int(r) for r in repeats)),
        # Loop/Scan accumulation: dense [T, ...] array + dynamic slices
        "list_set": lambda acc, i, item: acc.at[i].set(item),
        "list_get": lambda x, i: x[i],
        "flip0": lambda x: jnp.flip(x, 0),
        "scalar_bool": lambda x: jnp.reshape(x, ()).astype(jnp.bool_),
        "fill": lambda dims, value: jnp.full(tuple(dims), value),
    }.items():
        register_op(f"onnximport.{name}", fn)


_ONNX_OPS_READY = False


def ensure_onnximport_ops():
    global _ONNX_OPS_READY
    if not _ONNX_OPS_READY:
        _register_onnximport_ops()
        _register_onnximport_ops_ext()
        _ONNX_OPS_READY = True


# --- mapper registry -------------------------------------------------------

# mapper(importer, node) -> SDVariable | tuple
ONNX_OP_MAPPERS: Dict[str, Callable] = {}


def onnx_op(*names):
    def deco(fn):
        for n in names:
            ONNX_OP_MAPPERS[n] = fn
        return fn

    return deco


def _simple(op_name):
    """Mapper for ops taking ONNX inputs positionally with no attrs."""

    def mapper(imp: "_GraphImporter", node: NodeProto):
        ins = [imp.tensor(r) for r in node.input if r]
        return imp.sd._record(op_name, ins, {
            "__argspec__": ["var"] * len(ins), "__posattrs__": []})

    return mapper


for onnx_name, our_op in {
    "Add": "add", "Sub": "sub", "Mul": "mul", "Div": "div", "Pow": "pow",
    "Neg": "neg", "Abs": "abs", "Exp": "exp", "Log": "log", "Sqrt": "sqrt",
    "Relu": "relu", "Sigmoid": "sigmoid", "Tanh": "tanh",
    "Softplus": "softplus", "Erf": "onnximport.erf",
    "Min": "minimum", "Max": "maximum",
    "Equal": "eq", "Greater": "gt", "GreaterOrEqual": "gte",
    "Less": "lt", "LessOrEqual": "lte",
    "Where": "onnximport.where", "MatMul": "onnximport.matmul",
    "PRelu": "onnximport.prelu",
    "Floor": "math.floor", "Ceil": "math.ceil", "Round": "math.round",
    "Sin": "math.sin", "Cos": "math.cos", "Sign": "math.sign",
}.items():
    ONNX_OP_MAPPERS[onnx_name] = _simple(our_op)


def _rec(imp, op, ins, **attrs):
    return imp.sd._record(op, ins, {
        "__argspec__": ["var"] * len(ins), "__posattrs__": [], **attrs})


@onnx_op("Gemm")
def _gemm(imp, node):
    a = node.attrs()
    ins = [imp.tensor(r) for r in node.input if r]
    return _rec(imp, "onnximport.gemm", ins,
                alpha=a.get("alpha", 1.0), beta=a.get("beta", 1.0),
                trans_a=a.get("transA", 0), trans_b=a.get("transB", 0))


@onnx_op("Conv")
def _conv(imp, node):
    a = node.attrs()
    ins = [imp.tensor(r) for r in node.input if r]
    if "kernel_shape" in a:
        nd = len(a["kernel_shape"])
    else:
        # kernel_shape is optional in ONNX; spatial rank comes from the
        # weight tensor [O, I/g, *kernel].
        w_shape = ins[1].shape
        if w_shape is None:
            raise ONNXImportError(
                f"Conv {node.name!r}: no kernel_shape attr and weight "
                "shape unknown")
        nd = len(w_shape) - 2
    return _rec(imp, "onnximport.conv", ins,
                strides=a.get("strides", [1] * nd),
                pads=a.get("pads"), dilations=a.get("dilations", [1] * nd),
                group=a.get("group", 1),
                auto_pad=a.get("auto_pad", "NOTSET"))


@onnx_op("MaxPool")
def _max_pool(imp, node):
    a = node.attrs()
    if a.get("ceil_mode", 0):
        raise ONNXImportError("MaxPool ceil_mode=1 unsupported")
    if len(node.output) > 1 and node.output[1]:
        raise ONNXImportError("MaxPool Indices output unsupported")
    return _rec(imp, "onnximport.max_pool", [imp.tensor(node.input[0])],
                kernel_shape=a["kernel_shape"], strides=a.get("strides"),
                pads=a.get("pads"), auto_pad=a.get("auto_pad", "NOTSET"))


@onnx_op("AveragePool")
def _avg_pool(imp, node):
    a = node.attrs()
    if a.get("ceil_mode", 0):
        raise ONNXImportError("AveragePool ceil_mode=1 unsupported")
    return _rec(imp, "onnximport.average_pool", [imp.tensor(node.input[0])],
                kernel_shape=a["kernel_shape"], strides=a.get("strides"),
                pads=a.get("pads"),
                count_include_pad=a.get("count_include_pad", 0),
                auto_pad=a.get("auto_pad", "NOTSET"))


@onnx_op("GlobalAveragePool")
def _gap(imp, node):
    return _rec(imp, "onnximport.global_average_pool",
                [imp.tensor(node.input[0])])


@onnx_op("BatchNormalization")
def _bn(imp, node):
    a = node.attrs()
    if a.get("training_mode", 0):
        raise ONNXImportError("BatchNormalization training_mode=1 unsupported")
    ins = [imp.tensor(r) for r in node.input[:5]]
    return _rec(imp, "onnximport.batch_norm", ins,
                epsilon=a.get("epsilon", 1e-5))


@onnx_op("LayerNormalization")
def _ln(imp, node):
    a = node.attrs()
    ins = [imp.tensor(r) for r in node.input if r]
    return _rec(imp, "onnximport.layer_norm", ins,
                axis=a.get("axis", -1), epsilon=a.get("epsilon", 1e-5))


@onnx_op("Reshape")
def _reshape(imp, node):
    shape = [int(v) for v in imp.const_value(node.input[1]).reshape(-1)]
    return _rec(imp, "onnximport.reshape", [imp.tensor(node.input[0])],
                shape=shape, allowzero=node.attrs().get("allowzero", 0))


@onnx_op("Flatten")
def _flatten(imp, node):
    return _rec(imp, "onnximport.flatten", [imp.tensor(node.input[0])],
                axis=node.attrs().get("axis", 1))


@onnx_op("Transpose")
def _transpose(imp, node):
    return _rec(imp, "onnximport.transpose", [imp.tensor(node.input[0])],
                perm=node.attrs().get("perm"))


@onnx_op("Concat")
def _concat(imp, node):
    ins = [imp.tensor(r) for r in node.input]
    return _rec(imp, "onnximport.concat", ins, axis=node.attrs()["axis"])


@onnx_op("Softmax")
def _softmax(imp, node):
    return _rec(imp, "onnximport.softmax", [imp.tensor(node.input[0])],
                axis=node.attrs().get("axis", -1))


@onnx_op("LogSoftmax")
def _log_softmax(imp, node):
    return _rec(imp, "onnximport.log_softmax", [imp.tensor(node.input[0])],
                axis=node.attrs().get("axis", -1))


@onnx_op("LeakyRelu")
def _leaky(imp, node):
    return _rec(imp, "onnximport.leaky_relu", [imp.tensor(node.input[0])],
                alpha=node.attrs().get("alpha", 0.01))


@onnx_op("Elu")
def _elu(imp, node):
    return _rec(imp, "onnximport.elu", [imp.tensor(node.input[0])],
                alpha=node.attrs().get("alpha", 1.0))


@onnx_op("HardSigmoid")
def _hard_sigmoid(imp, node):
    a = node.attrs()
    return _rec(imp, "onnximport.hard_sigmoid", [imp.tensor(node.input[0])],
                alpha=a.get("alpha", 0.2), beta=a.get("beta", 0.5))


@onnx_op("LRN")
def _lrn(imp, node):
    a = node.attrs()
    return _rec(imp, "onnximport.lrn", [imp.tensor(node.input[0])],
                size=a["size"], alpha=a.get("alpha", 1e-4),
                beta=a.get("beta", 0.75), bias=a.get("bias", 1.0))


@onnx_op("Clip")
def _clip(imp, node):
    a = node.attrs()
    lo = a.get("min")
    hi = a.get("max")
    if len(node.input) > 1 and node.input[1]:
        lo = float(imp.const_value(node.input[1]))
    if len(node.input) > 2 and node.input[2]:
        hi = float(imp.const_value(node.input[2]))
    return _rec(imp, "onnximport.clip", [imp.tensor(node.input[0])],
                lo=lo, hi=hi)


@onnx_op("Gather")
def _gather(imp, node):
    ins = [imp.tensor(node.input[0]), imp.tensor(node.input[1])]
    return _rec(imp, "onnximport.gather", ins,
                axis=node.attrs().get("axis", 0))


def _axes_attr_or_input(imp, node, idx=1):
    axes = node.attrs().get("axes")
    if axes is None and len(node.input) > idx and node.input[idx]:
        axes = [int(v) for v in imp.const_value(node.input[idx]).reshape(-1)]
    return axes


@onnx_op("Unsqueeze")
def _unsqueeze(imp, node):
    axes = _axes_attr_or_input(imp, node)
    if axes is None:
        raise ONNXImportError("Unsqueeze needs axes")
    return _rec(imp, "onnximport.unsqueeze", [imp.tensor(node.input[0])],
                axes=axes)


@onnx_op("Squeeze")
def _squeeze(imp, node):
    return _rec(imp, "onnximport.squeeze", [imp.tensor(node.input[0])],
                axes=_axes_attr_or_input(imp, node))


@onnx_op("Slice")
def _slice(imp, node):
    a = node.attrs()
    if "starts" in a:  # opset < 10: attributes
        starts, ends = a["starts"], a["ends"]
        axes, steps = a.get("axes"), None
    else:
        starts = [int(v) for v in imp.const_value(node.input[1]).reshape(-1)]
        ends = [int(v) for v in imp.const_value(node.input[2]).reshape(-1)]
        axes = steps = None
        if len(node.input) > 3 and node.input[3]:
            axes = [int(v) for v in imp.const_value(node.input[3]).reshape(-1)]
        if len(node.input) > 4 and node.input[4]:
            steps = [int(v) for v in imp.const_value(node.input[4]).reshape(-1)]
    return _rec(imp, "onnximport.slice", [imp.tensor(node.input[0])],
                starts=list(starts), ends=list(ends), axes=axes, steps=steps)


@onnx_op("Pad")
def _pad(imp, node):
    a = node.attrs()
    mode = a.get("mode", "constant")
    if "pads" in a:  # opset < 11
        pads = a["pads"]
        cval = a.get("value", 0.0)
    else:
        pads = [int(v) for v in imp.const_value(node.input[1]).reshape(-1)]
        cval = 0.0
        if len(node.input) > 2 and node.input[2]:
            cval = float(imp.const_value(node.input[2]))
    return _rec(imp, "onnximport.pad", [imp.tensor(node.input[0])],
                pads=list(pads), constant_value=cval, mode=mode)


@onnx_op("ReduceMean", "ReduceSum", "ReduceMax", "ReduceMin", "ReduceProd")
def _reduce(imp, node):
    kind = node.op_type[len("Reduce"):].lower()
    a = node.attrs()
    axes = _axes_attr_or_input(imp, node)
    return _rec(imp, f"onnximport.reduce_{kind}", [imp.tensor(node.input[0])],
                axes=axes, keepdims=a.get("keepdims", 1),
                noop_with_empty_axes=a.get("noop_with_empty_axes", 0))


@onnx_op("Cast")
def _cast(imp, node):
    return _rec(imp, "onnximport.cast", [imp.tensor(node.input[0])],
                to=node.attrs()["to"])


@onnx_op("Expand")
def _expand(imp, node):
    shape = [int(v) for v in imp.const_value(node.input[1]).reshape(-1)]
    return _rec(imp, "onnximport.expand", [imp.tensor(node.input[0])],
                shape=shape)


@onnx_op("Gelu")
def _gelu(imp, node):
    approximate = node.attrs().get("approximate", "none")
    return _rec(imp, "onnximport.gelu", [imp.tensor(node.input[0])],
                approximate=approximate == "tanh")


@onnx_op("Shape")
def _shape(imp, node):
    v = imp.tensor(node.input[0])
    if v.shape is None or any(d is None for d in v.shape):
        raise ONNXImportError(
            f"Shape of {node.input[0]!r} is not fully static at import")
    arr = np.asarray(v.shape, np.int64)
    name = imp.fresh_const_name(node.name or "shape")
    imp.consts[node.output[0]] = arr
    return imp.sd.constant(name, arr)


@onnx_op("Constant")
def _constant(imp, node):
    a = {at.name: at for at in node.attribute}
    if "value" in a and a["value"].type == ATTR_TENSOR:
        arr = a["value"].t.to_numpy()
    elif "value_float" in a:
        arr = np.asarray(a["value_float"].f, np.float32)
    elif "value_int" in a:
        arr = np.asarray(a["value_int"].i, np.int64)
    elif "value_floats" in a:
        arr = np.asarray(list(a["value_floats"].floats), np.float32)
    elif "value_ints" in a:
        arr = np.asarray(list(a["value_ints"].ints), np.int64)
    else:
        raise ONNXImportError(f"Constant node {node.name!r}: no value attr")
    imp.consts[node.output[0]] = arr
    return imp.sd.constant(imp.fresh_const_name(node.name or "const"), arr)


# --- round-4 breadth mappers ----------------------------------------------

for onnx_name, our_op in {
    "Tan": "math.tan", "Asin": "math.asin", "Acos": "math.acos",
    "Atan": "math.atan", "Sinh": "math.sinh", "Cosh": "math.cosh",
    "Asinh": "math.asinh", "Acosh": "math.acosh", "Atanh": "math.atanh",
    "Reciprocal": "math.reciprocal", "Not": "math.logical_not",
    "And": "math.logical_and", "Or": "math.logical_or",
    "Xor": "math.logical_xor", "IsNaN": "math.is_nan",
    "Selu": "selu", "Softsign": "softsign",
    "Mish": "onnximport.mish", "HardSwish": "onnximport.hard_swish",
    "GlobalMaxPool": "onnximport.global_max_pool",
}.items():
    ONNX_OP_MAPPERS[onnx_name] = _simple(our_op)


@onnx_op("Mod")
def _mod(imp, node):
    ins = [imp.tensor(r) for r in node.input]
    return _rec(imp, "onnximport.mod", ins,
                fmod=node.attrs().get("fmod", 0))


@onnx_op("IsInf")
def _is_inf(imp, node):
    a = node.attrs()
    return _rec(imp, "onnximport.is_inf", [imp.tensor(node.input[0])],
                detect_negative=a.get("detect_negative", 1),
                detect_positive=a.get("detect_positive", 1))


@onnx_op("ThresholdedRelu")
def _thresholded_relu(imp, node):
    return _rec(imp, "onnximport.thresholded_relu",
                [imp.tensor(node.input[0])],
                alpha=node.attrs().get("alpha", 1.0))


@onnx_op("Celu")
def _celu(imp, node):
    return _rec(imp, "onnximport.celu", [imp.tensor(node.input[0])],
                alpha=node.attrs().get("alpha", 1.0))


@onnx_op("Shrink")
def _shrink(imp, node):
    a = node.attrs()
    return _rec(imp, "onnximport.shrink", [imp.tensor(node.input[0])],
                bias=a.get("bias", 0.0), lambd=a.get("lambd", 0.5))


@onnx_op("ArgMax", "ArgMin")
def _argextreme(imp, node):
    a = node.attrs()
    if a.get("select_last_index", 0):
        raise ONNXImportError(f"{node.op_type} select_last_index unsupported")
    op = "onnximport.argmax" if node.op_type == "ArgMax" else "onnximport.argmin"
    return _rec(imp, op, [imp.tensor(node.input[0])],
                axis=a.get("axis", 0), keepdims=a.get("keepdims", 1))


@onnx_op("TopK")
def _topk(imp, node):
    a = node.attrs()
    k = int(imp.const_value(node.input[1]).reshape(-1)[0])
    return _rec(imp, "onnximport.top_k", [imp.tensor(node.input[0])],
                k=k, axis=a.get("axis", -1), largest=a.get("largest", 1),
                sorted=a.get("sorted", 1))


@onnx_op("OneHot")
def _one_hot(imp, node):
    depth = int(imp.const_value(node.input[1]).reshape(-1)[0])
    ins = [imp.tensor(node.input[0]), imp.tensor(node.input[2])]
    return imp.sd._record("onnximport.one_hot", ins, {
        "__argspec__": ["var", "var"], "__posattrs__": [],
        "depth": depth, "axis": node.attrs().get("axis", -1)})


@onnx_op("Range")
def _range(imp, node):
    start, limit, delta = (imp.const_value(r).reshape(()) for r in node.input)
    arr = np.arange(start, limit, delta)
    imp.consts[node.output[0]] = arr
    return imp.sd.constant(imp.fresh_const_name(node.name or "range"), arr)


@onnx_op("ConstantOfShape")
def _const_of_shape(imp, node):
    shape = [int(v) for v in imp.const_value(node.input[0]).reshape(-1)]
    a = {at.name: at for at in node.attribute}
    if "value" in a and a["value"].type == ATTR_TENSOR:
        fill = a["value"].t.to_numpy().reshape(-1)[0]
    else:
        fill = np.float32(0.0)
    arr = np.full(shape, fill)
    imp.consts[node.output[0]] = arr
    return imp.sd.constant(imp.fresh_const_name(node.name or "cofs"), arr)


@onnx_op("CumSum")
def _cumsum(imp, node):
    a = node.attrs()
    axis = int(imp.const_value(node.input[1]).reshape(-1)[0])
    return _rec(imp, "onnximport.cumsum", [imp.tensor(node.input[0])],
                axis=axis, exclusive=a.get("exclusive", 0),
                reverse=a.get("reverse", 0))


@onnx_op("Einsum")
def _einsum(imp, node):
    ins = [imp.tensor(r) for r in node.input]
    return _rec(imp, "onnximport.einsum", ins,
                equation=node.attrs()["equation"])


@onnx_op("ReduceL1", "ReduceL2", "ReduceLogSum", "ReduceLogSumExp",
         "ReduceSumSquare")
def _reduce_ext(imp, node):
    kind = {"ReduceL1": "l1", "ReduceL2": "l2", "ReduceLogSum": "log_sum",
            "ReduceLogSumExp": "log_sum_exp",
            "ReduceSumSquare": "sum_square"}[node.op_type]
    a = node.attrs()
    axes = _axes_attr_or_input(imp, node)
    return _rec(imp, f"onnximport.reduce_{kind}", [imp.tensor(node.input[0])],
                axes=axes, keepdims=a.get("keepdims", 1),
                noop_with_empty_axes=a.get("noop_with_empty_axes", 0))


@onnx_op("DepthToSpace")
def _depth_to_space(imp, node):
    a = node.attrs()
    return _rec(imp, "onnximport.depth_to_space", [imp.tensor(node.input[0])],
                blocksize=a["blocksize"], mode=a.get("mode", "DCR"))


@onnx_op("SpaceToDepth")
def _space_to_depth(imp, node):
    return _rec(imp, "onnximport.space_to_depth", [imp.tensor(node.input[0])],
                blocksize=node.attrs()["blocksize"])


@onnx_op("ConvTranspose")
def _conv_transpose(imp, node):
    a = node.attrs()
    if a.get("auto_pad", "NOTSET") != "NOTSET":
        raise ONNXImportError("ConvTranspose auto_pad unsupported")
    if any(a.get("output_padding", [])):
        raise ONNXImportError("ConvTranspose output_padding unsupported")
    if "output_shape" in a:
        raise ONNXImportError("ConvTranspose output_shape unsupported")
    if a.get("group", 1) != 1:
        raise ONNXImportError("ConvTranspose group != 1 unsupported")
    if any(d != 1 for d in a.get("dilations", [])):
        # jax.lax.conv_transpose below runs undilated; importing would
        # silently produce wrong activations AND a wrong output shape.
        raise ONNXImportError("ConvTranspose dilations != 1 unsupported")
    ins = [imp.tensor(r) for r in node.input if r]
    w_shape = ins[1].shape
    nd = (len(a["kernel_shape"]) if "kernel_shape" in a
          else len(w_shape) - 2 if w_shape else 2)
    if nd not in (2, 3):
        raise ONNXImportError(f"ConvTranspose spatial rank {nd} unsupported")
    return _rec(imp, "onnximport.conv_transpose", ins,
                strides=a.get("strides", [1] * nd), pads=a.get("pads"),
                group=1)


@onnx_op("InstanceNormalization")
def _instance_norm(imp, node):
    ins = [imp.tensor(r) for r in node.input[:3]]
    return _rec(imp, "onnximport.instance_norm", ins,
                epsilon=node.attrs().get("epsilon", 1e-5))


@onnx_op("GroupNormalization")
def _group_norm(imp, node):
    a = node.attrs()
    ins = [imp.tensor(r) for r in node.input[:3]]
    return _rec(imp, "onnximport.group_norm", ins,
                num_groups=a["num_groups"], epsilon=a.get("epsilon", 1e-5))


@onnx_op("Split")
def _split(imp, node):
    a = node.attrs()
    split_sizes = a.get("split")
    if split_sizes is None and len(node.input) > 1 and node.input[1]:
        split_sizes = [int(v)
                       for v in imp.const_value(node.input[1]).reshape(-1)]
    x = imp.tensor(node.input[0])
    axis = a.get("axis", 0)
    k = a.get("num_outputs", len(node.output))
    # Validate HERE, where the static dim is known and the error names the
    # node — a raise inside the op fn is swallowed by _infer's eval_shape
    # guard, which records ONE output for the node and crashes downstream
    # with a confusing output-binding error.
    if split_sizes is None and x.shape is not None:
        dim = x.shape[axis if axis >= 0 else axis + len(x.shape)]
        if dim is not None:
            chunk = -(-int(dim) // int(k))
            if int(dim) - chunk * (int(k) - 1) <= 0:
                raise ONNXImportError(
                    f"Split node '{node.name}': num_outputs={k} too large "
                    f"for axis dim {dim}")
    return _rec(imp, "onnximport.split", [x],
                axis=axis, split_sizes=split_sizes, num_outputs=k)


@onnx_op("Tile")
def _tile(imp, node):
    repeats = [int(v) for v in imp.const_value(node.input[1]).reshape(-1)]
    return _rec(imp, "onnximport.tile", [imp.tensor(node.input[0])],
                repeats=repeats)


@onnx_op("GatherElements")
def _gather_elements(imp, node):
    ins = [imp.tensor(node.input[0]), imp.tensor(node.input[1])]
    return _rec(imp, "onnximport.gather_elements", ins,
                axis=node.attrs().get("axis", 0))


@onnx_op("Trilu")
def _trilu(imp, node):
    k = 0
    if len(node.input) > 1 and node.input[1]:
        k = int(imp.const_value(node.input[1]).reshape(-1)[0])
    return _rec(imp, "onnximport.trilu", [imp.tensor(node.input[0])],
                k=k, upper=node.attrs().get("upper", 1))


def _resize_scales_sizes(imp, node, x):
    """Resolve (scales, out_shape) from a Resize/Upsample node's inputs."""
    scales = sizes = None
    # Resize inputs: X, roi, scales, sizes (any of roi/scales empty).
    if node.op_type == "Upsample":
        if len(node.input) > 1 and node.input[1]:
            scales = [float(v)
                      for v in imp.const_value(node.input[1]).reshape(-1)]
        else:
            scales = list(node.attrs().get("scales", []))
    else:
        if len(node.input) > 2 and node.input[2]:
            scales = [float(v)
                      for v in imp.const_value(node.input[2]).reshape(-1)]
        if len(node.input) > 3 and node.input[3]:
            sizes = [int(v)
                     for v in imp.const_value(node.input[3]).reshape(-1)]
    if scales is not None and len(scales) == 0:
        scales = None
    if scales is None and sizes is None:
        raise ONNXImportError(f"{node.op_type}: needs scales or sizes")
    if x.shape is None or any(d is None for d in x.shape):
        # both conversions below need concrete dims
        raise ONNXImportError(
            f"{node.op_type}: input shape must be fully static at import "
            f"(got {x.shape})")
    if sizes is None:
        # Spec: output_size = floor(input_size * scale) — round() would
        # disagree with onnxruntime on fractional scales (5 * 1.5 -> 7,
        # not 8). The epsilon must be RELATIVE: scales arrive float32
        # (~1e-7 ulp), so an intended-integer product reads d*(1 - 1e-7)
        # and a d-independent 1e-9 cannot lift it back over the floor.
        sizes = [int(math.floor(d * s * (1 + 1e-6) + 1e-9))
                 for d, s in zip(x.shape, scales)]
    if scales is None:
        scales = [o / d for o, d in zip(sizes, x.shape)]
    return scales, sizes


@onnx_op("Resize", "Upsample")
def _resize(imp, node):
    a = node.attrs()
    mode = a.get("mode", "nearest")
    coord = a.get("coordinate_transformation_mode",
                  "asymmetric" if node.op_type == "Upsample" else "half_pixel")
    x = imp.tensor(node.input[0])
    scales, sizes = _resize_scales_sizes(imp, node, x)
    if mode == "nearest":
        # exact only for integer upscale factors with asymmetric coords +
        # floor rounding (the classic Upsample) — the repeat identity
        if coord not in ("asymmetric",):
            raise ONNXImportError(
                f"Resize nearest with coordinate mode {coord!r} unsupported "
                "(asymmetric only)")
        if a.get("nearest_mode", "round_prefer_floor") not in (
                "floor", "round_prefer_floor"):
            raise ONNXImportError("Resize nearest_mode unsupported")
        if any(abs(s - round(s)) > 1e-6 or s < 1 for s in scales):
            raise ONNXImportError(
                f"Resize nearest with non-integer scales {scales} unsupported")
        return _rec(imp, "onnximport.resize_nearest_int", [x],
                    scales=[int(round(s)) for s in scales])
    if mode == "linear":
        if coord != "half_pixel":
            raise ONNXImportError(
                f"Resize linear with coordinate mode {coord!r} unsupported "
                "(half_pixel only)")
        return _rec(imp, "onnximport.resize_linear_half_pixel", [x],
                    out_shape=sizes)
    raise ONNXImportError(f"Resize mode {mode!r} unsupported")


def _rnn_common(imp, node, n_gates):
    a = node.attrs()
    if a.get("layout", 0) != 0:
        raise ONNXImportError(f"{node.op_type} layout=1 unsupported")
    if "activations" in a:
        defaults = {2: [b"Sigmoid", b"Tanh"],
                    3: [b"Sigmoid", b"Tanh", b"Tanh"]}[n_gates]
        acts = [v if isinstance(v, bytes) else v.encode()
                for v in a["activations"]]
        dirs = 2 if a.get("direction", "forward") == "bidirectional" else 1
        if acts != defaults * dirs:
            raise ONNXImportError(
                f"{node.op_type} non-default activations {acts} unsupported")
    if "clip" in a:
        raise ONNXImportError(f"{node.op_type} clip unsupported")
    if len(node.input) > 4 and node.input[4]:
        raise ONNXImportError(
            f"{node.op_type} sequence_lens input unsupported")
    direction = a.get("direction", "forward")
    if direction not in ("forward", "reverse", "bidirectional"):
        raise ONNXImportError(f"{node.op_type} direction {direction!r}")
    return a, direction


@onnx_op("LSTM")
def _lstm(imp, node):
    a, direction = _rnn_common(imp, node, n_gates=3)
    if a.get("input_forget", 0):
        raise ONNXImportError("LSTM input_forget unsupported")
    if len(node.input) > 7 and node.input[7]:
        raise ONNXImportError("LSTM peephole input P unsupported")
    ins = [imp.tensor(node.input[i]) for i in range(3)]
    present = []
    for idx, tag in ((3, "b"), (5, "h0"), (6, "c0")):
        if len(node.input) > idx and node.input[idx]:
            ins.append(imp.tensor(node.input[idx]))
            present.append(tag)
    return _rec(imp, "onnximport.lstm", ins,
                hidden_size=a["hidden_size"], direction=direction,
                present=present)


@onnx_op("GRU")
def _gru(imp, node):
    a, direction = _rnn_common(imp, node, n_gates=2)
    if a.get("linear_before_reset", 0):
        raise ONNXImportError("GRU linear_before_reset=1 unsupported")
    H = a["hidden_size"]
    ins = [imp.tensor(node.input[i]) for i in range(3)]
    present = []
    if len(node.input) > 3 and node.input[3]:
        # our gru_cell adds the candidate bias OUTSIDE the reset gate; that
        # matches ONNX linear_before_reset=0 only when Rb_h == 0 — verify
        # on the host-known initializer rather than import wrong math
        bval = imp.consts.get(node.input[3])
        if bval is None:
            raise ONNXImportError("GRU bias must be an initializer")
        if np.any(bval[:, 5 * H:6 * H] != 0):
            raise ONNXImportError(
                "GRU with nonzero recurrent candidate bias Rb_h is "
                "unsupported (linear_before_reset=0 semantics differ)")
        ins.append(imp.tensor(node.input[3]))
        present.append("b")
    if len(node.input) > 5 and node.input[5]:
        ins.append(imp.tensor(node.input[5]))
        present.append("h0")
    return _rec(imp, "onnximport.gru", ins,
                hidden_size=H, direction=direction, present=present)


@onnx_op("Dropout")
def _dropout(imp, node):
    # Inference import: identity (mask output unsupported).
    if len(node.output) > 1 and node.output[1]:
        raise ONNXImportError("Dropout mask output unsupported")
    return imp.tensor(node.input[0])


@onnx_op("Identity")
def _identity(imp, node):
    v = imp.tensor(node.input[0])
    if node.input[0] in imp.consts:
        imp.consts[node.output[0]] = imp.consts[node.input[0]]
    return v


def _make_scan_accumulators(imp, bsd, iter_ph, trip, scan_out_vars,
                            node_name):
    """Preallocated dense accumulators for per-iteration scan outputs
    (shared by Loop and Scan): an outer lazy fill [trip, *elem] per
    output, a body-side placeholder, and a list_set write at the carry's
    iteration index. Returns (outer accs, body output names)."""
    accs, acc_body_outs = [], []
    for sv in scan_out_vars:
        if sv.shape is None or any(d in (None, -1)
                                   for d in (sv.shape or ())):
            raise ONNXImportError(
                f"{node_name!r}: scan output {sv.name!r} has unknown "
                f"shape {sv.shape}; cannot preallocate")
        acc_shape = (trip, *[int(d) for d in sv.shape])
        acc_dtype = str(np.dtype(sv.dtype or "float32"))
        # lazy fill, not a dense zeros constant — no O(T·elem) zero bytes
        # in the graph or its serializations
        acc_zero = imp.sd.constant(
            imp.fresh_const_name(f"{node_name}_acc_zero"),
            np.zeros((), acc_dtype))
        accs.append(imp.sd._record("onnximport.fill", [acc_zero], {
            "__argspec__": ["attr", "var"],
            "__posattrs__": [list(acc_shape)]}))
        acc_ph = bsd.placeholder(
            f"__{node_name}_acc{len(acc_body_outs)}", acc_shape, acc_dtype)
        acc_body_outs.append(bsd._record(
            "onnximport.list_set", [acc_ph, iter_ph, sv], {}).name)
    return accs, acc_body_outs


@onnx_op("If")
def _if_onnx(imp, node):
    """ONNX If → samediff.cond (lax.cond). Branch subgraphs take no
    declared inputs; everything they read is implicit capture, which
    becomes the cond's operand list (union of both branches, fixed
    order, host-known captures inlined as constants)."""
    a = node.attrs()
    then_g, else_g = a.get("then_branch"), a.get("else_branch")
    if not isinstance(then_g, GraphProto) or not isinstance(else_g, GraphProto):
        raise ONNXImportError(
            f"If {node.name!r}: then_branch/else_branch graph attrs missing")
    if len(then_g.output) != len(else_g.output):
        raise ONNXImportError(
            f"If {node.name!r}: branches disagree on output count "
            f"({len(then_g.output)} vs {len(else_g.output)})")
    pred = _rec(imp, "onnximport.scalar_bool", [imp.tensor(node.input[0])])
    all_caps, var_caps = _union_captures(imp, [then_g, else_g])
    t_sub = _import_onnx_subgraph(imp, then_g, [], all_caps, var_caps).sd
    f_sub = _import_onnx_subgraph(imp, else_g, [], all_caps, var_caps).sd
    return imp.sd.cond(pred, t_sub, f_sub,
                       [imp.tensor(c) for c in var_caps])


@onnx_op("Loop")
def _loop_onnx(imp, node):
    """ONNX Loop → samediff.while_loop (lax.while_loop).

    Loop(M?, cond?, v_1..N) with body (iter, cond_in, v_1..N) ->
    (cond_out, v_1..N_out, scan_1..K). The carry is
    (i, cond, v..., captures..., scan accumulators...); captures ride as
    pass-through loop vars (loop-invariant), scan outputs accumulate via
    dynamic_update_slice into a preallocated [M, ...] array.

    Scan outputs need the dense preallocation, so K > 0 additionally
    requires a host-known trip count M and an effectively-constant-true
    loop condition (the standard for-loop export shape); ONNX's
    dynamic-length scan semantics have no static-shape equivalent under
    jit and are refused otherwise.
    """
    a = node.attrs()
    body = a.get("body")
    if not isinstance(body, GraphProto):
        raise ONNXImportError(f"Loop {node.name!r}: body graph attr missing")
    m_ref = node.input[0] if len(node.input) > 0 else ""
    c_ref = node.input[1] if len(node.input) > 1 else ""
    v_inits = [imp.tensor(r) for r in node.input[2:]]
    n_v = len(v_inits)
    if len(body.input) != 2 + n_v:
        raise ONNXImportError(
            f"Loop {node.name!r}: body takes {len(body.input)} inputs, "
            f"expected {2 + n_v}")
    n_scan = len(body.output) - 1 - n_v
    if n_scan < 0:
        raise ONNXImportError(
            f"Loop {node.name!r}: body yields {len(body.output)} outputs "
            f"for {n_v} loop vars")

    sd = imp.sd
    zero = sd.constant(imp.fresh_const_name(f"{node.name}_i0"),
                       np.zeros((), np.int32))
    has_m = bool(m_ref)
    m_var = imp.tensor(m_ref) if has_m else None
    m_const = None
    if has_m and m_ref in imp.consts:
        m_const = int(np.asarray(imp.consts[m_ref]).reshape(()))
    if c_ref:
        cond0 = _rec(imp, "onnximport.scalar_bool", [imp.tensor(c_ref)])
    else:
        cond0 = sd.constant(imp.fresh_const_name(f"{node.name}_true"),
                            np.asarray(True))
    if has_m:
        # first-iteration gate: run iff cond0 AND 0 < M
        cond0 = _rec(imp, "math.logical_and", [
            cond0, _rec(imp, "lt", [zero, m_var])])
    # lax.while_loop cond must be a SCALAR bool; scalar initializers can
    # decode as shape-(1,) tensors, which would poison the whole carry
    cond0 = _rec(imp, "onnximport.scalar_bool", [cond0])

    all_caps, var_caps = _union_captures(imp, [body])
    # iter/cond/v placeholders take the INIT vars' shapes; the body's
    # declared input value-infos are usually shapeless in real exports
    class _Spec:
        def __init__(self, shape, dtype):
            self.shape, self.dtype = shape, dtype

    declared = [_Spec((), "int32"), _Spec((), "bool")] + [
        _Spec(v.shape, v.dtype or "float32") for v in v_inits]
    simp = _import_onnx_subgraph(imp, body, declared, all_caps, var_caps)
    bsd = simp.sd
    iter_ph = bsd._vars[body.input[0].name]
    cond_out = bsd._vars[bsd.branch_outputs[0]]
    v_outs = list(bsd.branch_outputs[1:1 + n_v])
    scan_outs = [bsd._vars[n] for n in bsd.branch_outputs[1 + n_v:]]

    # placeholder DECLARATION order defines the positional carry mapping
    # (_as_branch_fn): [i, cond, v..., caps...] are declared by the
    # subgraph import; M (if any) must come before the accumulators
    m_ph = bsd.placeholder(f"__{node.name}_M", (), "int32") if has_m else None

    # for-loop certification: constant-true initial cond AND a body that
    # provably keeps it true (constant or cond passthrough). Required for
    # scan outputs (an early data-dependent exit would shorten the scan
    # dimension — no static-shape equivalent); when it holds with a
    # host-constant M, the cond graph is emitted in counter form
    # (i < M) so samediff's scan-lowering makes the loop differentiable.
    cond0_true = not c_ref or (
        c_ref in imp.consts
        and bool(np.asarray(imp.consts[c_ref]).reshape(())))
    cond_is_pass = cond_out.name == body.input[1].name
    cond_is_const_true = (
        cond_out.var_type == VariableType.CONSTANT
        and bool(np.asarray(bsd._values[cond_out.name]).reshape(())))
    for_loop = (m_const is not None and cond0_true
                and (cond_is_pass or cond_is_const_true))

    # scan accumulators: preallocated dense arrays, written at carry's i
    accs = []
    acc_body_outs = []
    if n_scan:
        if m_const is None:
            raise ONNXImportError(
                f"Loop {node.name!r}: scan outputs need a host-constant "
                "trip count M (dynamic-length scans have no static shape)")
        if not cond0_true:
            raise ONNXImportError(
                f"Loop {node.name!r}: scan outputs require a constant-true "
                "initial condition (for-loop form)")
        if not (cond_is_pass or cond_is_const_true):
            raise ONNXImportError(
                f"Loop {node.name!r}: scan outputs require a for-loop body "
                "(cond_out must be constant true or the cond passthrough); "
                f"got computed condition {cond_out.name!r}")
        accs, acc_body_outs = _make_scan_accumulators(
            imp, bsd, iter_ph, m_const, scan_outs, node.name)

    # body-side: i+1 and the next-iteration condition
    bsd_one = bsd.constant("__loop_one", np.ones((), np.int32))
    new_i = bsd._record("add", [iter_ph, bsd_one], {})
    cond_next = bsd._record("onnximport.scalar_bool", [cond_out], {})
    if has_m:
        cond_next = bsd._record("math.logical_and", [
            cond_next, bsd._record("lt", [new_i, m_ph], {})], {})
        cond_next = bsd._record("onnximport.scalar_bool", [cond_next], {})
    bsd.branch_outputs = (
        [new_i.name, cond_next.name] + v_outs
        + list(var_caps) + ([m_ph.name] if has_m else []) + acc_body_outs)

    # cond graph: counter form (i < M) for certified for-loops — the
    # samediff replay detects it and compiles lax.scan (differentiable);
    # otherwise a pass-through read of the carried bool (lax.while_loop)
    csd = SameDiff.create()
    ci = csd.placeholder("__i", (), "int32")
    c_ph = csd.placeholder("__cond", (), "bool")
    for i, v in enumerate(v_inits):
        csd.placeholder(f"__v{i}", v.shape, v.dtype or "float32")
    for i, c in enumerate(var_caps):
        cv = imp.tensor(c)
        csd.placeholder(f"__c{i}", cv.shape, cv.dtype or "float32")
    if has_m:
        csd.placeholder("__M", (), "int32")
    for i, acc in enumerate(accs):
        csd.placeholder(f"__a{i}", acc.shape, acc.dtype)
    if for_loop:
        bound = csd.constant("__M_const", np.asarray(m_const, np.int32))
        csd.branch_outputs = [csd._record("lt", [ci, bound], {}).name]
    else:
        csd.branch_outputs = [c_ph.name]

    m_scalar = None
    if has_m:
        m_scalar = sd._record("reshape", [sd._record(
            "cast", [m_var], {"dtype": "int32"})], {"shape": []})
    inits = ([zero, cond0] + v_inits
             + [imp.tensor(c) for c in var_caps]
             + ([m_scalar] if has_m else []) + accs)
    res = sd.while_loop(csd, bsd, inits)
    res = res if isinstance(res, tuple) else (res,)
    v_finals = tuple(res[2:2 + n_v])
    scan_finals = tuple(res[2 + n_v + len(var_caps) + (1 if has_m else 0):])
    return v_finals + scan_finals


@onnx_op("Scan")
def _scan_onnx(imp, node):
    """ONNX Scan → while_loop over a STATIC trip count (the scan-input
    length — known at import, unlike Loop's M), i.e. lax.scan shape:
    carry (i, states..., captures..., scan-inputs..., accumulators...),
    per-step elements read with dynamic_slice, outputs accumulated with
    dynamic_update_slice. Reverse directions flip at the boundary.
    scan axes other than 0 are refused (transpose before/after instead).
    """
    a = node.attrs()
    body = a.get("body")
    if not isinstance(body, GraphProto):
        raise ONNXImportError(f"Scan {node.name!r}: body graph attr missing")
    k = int(a.get("num_scan_inputs", 0))
    n_states = len(node.input) - k
    if k < 1 or n_states < 0:
        raise ONNXImportError(
            f"Scan {node.name!r}: num_scan_inputs={k} with "
            f"{len(node.input)} inputs")
    if len(body.input) != n_states + k:
        raise ONNXImportError(
            f"Scan {node.name!r}: body takes {len(body.input)} inputs, "
            f"expected {n_states + k}")
    n_scan_out = len(body.output) - n_states
    if n_scan_out < 0:
        raise ONNXImportError(
            f"Scan {node.name!r}: body yields {len(body.output)} outputs "
            f"for {n_states} states")
    for key in ("scan_input_axes", "scan_output_axes"):
        axes = a.get(key)
        if axes and any(int(x) != 0 for x in axes):
            raise ONNXImportError(
                f"Scan {node.name!r}: {key}={axes} unsupported (axis 0 "
                "only; transpose around the Scan instead)")
    in_dirs = [int(d) for d in (a.get("scan_input_directions")
                                or [0] * k)]
    out_dirs = [int(d) for d in (a.get("scan_output_directions")
                                 or [0] * n_scan_out)]
    if len(in_dirs) != k or len(out_dirs) != n_scan_out:
        raise ONNXImportError(
            f"Scan {node.name!r}: directions length mismatch "
            f"(inputs {len(in_dirs)}/{k}, outputs "
            f"{len(out_dirs)}/{n_scan_out})")

    sd = imp.sd
    state_inits = [imp.tensor(r) for r in node.input[:n_states]]
    scan_ins = [imp.tensor(r) for r in node.input[n_states:]]
    trip = None
    for v in scan_ins:
        if not v.shape or v.shape[0] in (None, -1):
            raise ONNXImportError(
                f"Scan {node.name!r}: scan input {v.name!r} needs a "
                f"static leading dim, got shape {v.shape}")
        if trip is None:
            trip = int(v.shape[0])
        elif int(v.shape[0]) != trip:
            raise ONNXImportError(
                f"Scan {node.name!r}: scan inputs disagree on length "
                f"({trip} vs {v.shape[0]})")
    scan_ins = [
        _rec(imp, "onnximport.flip0", [v]) if d == 1 else v
        for v, d in zip(scan_ins, in_dirs)]

    all_caps, var_caps = _union_captures(imp, [body])
    # body subgraph, assembled manually: the declared scan-element inputs
    # are COMPUTED (list_get at i), not placeholders, so the carry is
    # [i, states..., captures..., full scan inputs..., accumulators...]
    sub = SameDiff.create()
    simp = _GraphImporter(body, {}, sub)
    i_ph = sub.placeholder(f"__{node.name}_i", (), "int32")
    for vi, v in zip(body.input[:n_states], state_inits):
        simp.vars[vi.name] = sub.placeholder(
            vi.name, v.shape, v.dtype or "float32")
    for c in var_caps:
        v = imp.tensor(c)
        simp.vars[c] = sub.placeholder(c, v.shape, v.dtype or "float32")
    scanin_phs = []
    for j, v in enumerate(scan_ins):
        ph = sub.placeholder(f"__{node.name}_xs{j}", v.shape,
                             v.dtype or "float32")
        scanin_phs.append(ph)
    for vi, ph in zip(body.input[n_states:], scanin_phs):
        simp.vars[vi.name] = sub._record(
            "onnximport.list_get", [ph, i_ph], {})
    _seed_subgraph_constants(imp, simp, body, all_caps)
    simp._process_nodes()
    state_out_names = [simp.tensor(o.name).name
                       for o in body.output[:n_states]]
    scan_out_vars = [simp.tensor(o.name)
                     for o in body.output[n_states:]]

    accs, acc_body_outs = _make_scan_accumulators(
        imp, sub, i_ph, trip, scan_out_vars, node.name)

    one = sub.constant(f"__{node.name}_one", np.ones((), np.int32))
    new_i = sub._record("add", [i_ph, one], {})
    sub.branch_outputs = (
        [new_i.name] + state_out_names + list(var_caps)
        + [ph.name for ph in scanin_phs] + acc_body_outs)

    csd = SameDiff.create()
    ci = csd.placeholder("__i", (), "int32")
    for j, v in enumerate(state_inits):
        csd.placeholder(f"__s{j}", v.shape, v.dtype or "float32")
    for j, c in enumerate(var_caps):
        cv = imp.tensor(c)
        csd.placeholder(f"__c{j}", cv.shape, cv.dtype or "float32")
    for j, v in enumerate(scan_ins):
        csd.placeholder(f"__x{j}", v.shape, v.dtype or "float32")
    for j, acc in enumerate(accs):
        csd.placeholder(f"__a{j}", acc.shape, acc.dtype)
    trip_c = csd.constant("__trip", np.asarray(trip, np.int32))
    csd.branch_outputs = [csd._record("lt", [ci, trip_c], {}).name]

    zero = sd.constant(imp.fresh_const_name(f"{node.name}_i0"),
                       np.zeros((), np.int32))
    inits = ([zero] + state_inits + [imp.tensor(c) for c in var_caps]
             + scan_ins + accs)
    res = sd.while_loop(csd, sub, inits)
    res = res if isinstance(res, tuple) else (res,)
    states_final = list(res[1:1 + n_states])
    accs_final = list(res[1 + n_states + len(var_caps) + k:])
    accs_final = [
        _rec(imp, "onnximport.flip0", [v]) if d == 1 else v
        for v, d in zip(accs_final, out_dirs)]
    return tuple(states_final + accs_final)


# --- host constant folding --------------------------------------------------
# Real exporters (torch.onnx above all) compute shape arguments with small
# on-graph arithmetic chains: Shape → Gather → Unsqueeze → Concat/Mul feeds
# a Reshape or an LSTM initial-state ConstantOfShape. Shape/Constant already
# land in imp.consts; these folders propagate host values through the
# arithmetic so downstream const_value() lookups succeed. Folding is
# best-effort and does not replace the emitted graph ops — it only records
# the host value alongside.


def _fold_axes(node, arrs, key="axes"):
    """axes from attr (opset<13) or trailing const input (opset>=13)."""
    a = node.attrs()
    if key in a:
        ax = a[key]
        return [int(v) for v in (ax if isinstance(ax, (list, tuple)) else [ax])]
    if len(arrs) > 1:
        return [int(v) for v in np.asarray(arrs[1]).reshape(-1)]
    return None


def _fold_cast(node, arrs):
    to = TENSOR_DTYPES.get(int(node.attrs().get("to", 1)))
    return arrs[0].astype(np.dtype(to))


def _fold_div(node, arrs):
    a, b = arrs[0], arrs[1]
    if np.issubdtype(np.asarray(a).dtype, np.integer):
        # ONNX integer Div truncates toward zero (shape math is positive,
        # where trunc == floor)
        return (np.sign(a) * np.sign(b) * (np.abs(a) // np.abs(b))).astype(
            np.asarray(a).dtype)
    return a / b


def _fold_slice(node, arrs):
    x = arrs[0]
    starts = np.asarray(arrs[1]).reshape(-1)
    ends = np.asarray(arrs[2]).reshape(-1)
    axes = (np.asarray(arrs[3]).reshape(-1) if len(arrs) > 3
            else np.arange(len(starts)))
    steps = (np.asarray(arrs[4]).reshape(-1) if len(arrs) > 4
             else np.ones(len(starts), np.int64))
    sl = [slice(None)] * x.ndim
    for s, e, ax, st in zip(starts, ends, axes, steps):
        sl[int(ax)] = slice(int(s), int(e), int(st))
    return x[tuple(sl)]


def _fold_unsqueeze(node, arrs):
    out = arrs[0]
    axes = _fold_axes(node, arrs) or []
    # ONNX negative axes are relative to the OUTPUT rank (input rank +
    # len(axes));
    # normalize before sorting or multiple negative axes land wrong
    out_rank = out.ndim + len(axes)
    for ax in sorted(a + out_rank if a < 0 else a for a in axes):
        out = np.expand_dims(out, int(ax))
    return out


def _fold_squeeze(node, arrs):
    axes = _fold_axes(node, arrs)
    if axes is None:
        return np.squeeze(arrs[0])
    return np.squeeze(arrs[0], axis=tuple(int(a) for a in axes))


def _fold_reduce_prod(node, arrs):
    axes = _fold_axes(node, arrs)
    # "empty axes" = absent attr/input OR an empty axes tensor (opset-18
    # allows both spellings; the runtime reduce_op honors len()==0 too)
    if not axes and node.attrs().get("noop_with_empty_axes", 0):
        return arrs[0]
    return np.prod(arrs[0], axis=(tuple(axes) if axes else None),
                   keepdims=bool(node.attrs().get("keepdims", 1)))


_HOST_FOLDABLE = {
    "Gather": lambda n, a: np.take(a[0], a[1].astype(np.int64),
                                   axis=int(n.attrs().get("axis", 0))),
    "Concat": lambda n, a: np.concatenate(
        [np.atleast_1d(x) for x in a], axis=int(n.attrs().get("axis", 0))),
    "Unsqueeze": _fold_unsqueeze,
    "Squeeze": _fold_squeeze,
    "Add": lambda n, a: a[0] + a[1],
    "Sub": lambda n, a: a[0] - a[1],
    "Mul": lambda n, a: a[0] * a[1],
    "Div": _fold_div,
    "Neg": lambda n, a: -a[0],
    "Cast": _fold_cast,
    "Slice": _fold_slice,
    "ReduceProd": _fold_reduce_prod,
    "Reshape": lambda n, a: a[0].reshape(
        [int(v) for v in np.asarray(a[1]).reshape(-1)]),
    # boolean shape-select chains (torch exports Where/Equal around
    # dynamic-vs-static dims in e.g. HF attention-mask expansion)
    "Equal": lambda n, a: a[0] == a[1],
    "Greater": lambda n, a: a[0] > a[1],
    "Less": lambda n, a: a[0] < a[1],
    "Not": lambda n, a: ~a[0].astype(bool),
    "Where": lambda n, a: np.where(a[0].astype(bool), a[1], a[2]),
    "Expand": lambda n, a: np.broadcast_to(
        a[0], np.broadcast_shapes(
            a[0].shape, tuple(int(v) for v in np.asarray(a[1]).reshape(-1)))),
    "Min": lambda n, a: np.minimum.reduce(a),
    "Max": lambda n, a: np.maximum.reduce(a),
}


# --- the importer ----------------------------------------------------------


class _GraphImporter:
    """Walks GraphProto nodes, emitting SameDiff ops via the registry
    (↔ samediff-import-onnx's OnnxFrameworkImporter)."""

    def __init__(self, graph: GraphProto, input_shapes: Dict[str, Tuple],
                 sd: SameDiff):
        self.g = graph
        self.sd = sd
        self.input_shapes = input_shapes
        self.vars: Dict[str, Any] = {}   # onnx value name -> SDVariable
        self.consts: Dict[str, np.ndarray] = {}

    def tensor(self, ref: str) -> SDVariable:
        v = self.vars.get(ref)
        if v is None:
            raise ONNXImportError(f"value {ref!r} produced by unknown node")
        return v

    def const_value(self, ref: str) -> np.ndarray:
        if ref not in self.consts:
            raise ONNXImportError(
                f"op needs host-known constant for {ref!r} (shapes/axes/pads "
                "must be initializers or Constant nodes)")
        return self.consts[ref]

    def _try_fold(self, node) -> None:
        """Best-effort host evaluation when every input is host-known (see
        _HOST_FOLDABLE above); failures leave the graph untouched."""
        fold = _HOST_FOLDABLE.get(node.op_type)
        if fold is None or node.output[0] in self.consts:
            return
        if not all(r in self.consts for r in node.input if r):
            return
        # Shape-math tensors are tiny; a cap keeps weight-sized initializer
        # chains (Cast/Mul over multi-MB arrays) from being host-evaluated
        # and duplicated into self.consts for no consumer.
        if any(self.consts[r].size > 4096 for r in node.input if r):
            return
        try:
            self.consts[node.output[0]] = np.asarray(
                fold(node, [self.consts[r] for r in node.input if r]))
        except Exception:  # noqa: BLE001 - folding is advisory only
            pass

    def fresh_const_name(self, base: str) -> str:
        name = base or "const"
        i = 0
        while name in self.sd._vars:
            i += 1
            name = f"{base}__{i}"
        return name

    def run(self, outputs: Sequence[str]) -> Dict[str, str]:
        init_names = set()
        for t in self.g.initializer:
            arr = t.to_numpy()
            self.consts[t.name] = arr
            self.vars[t.name] = self.sd.constant(
                self.fresh_const_name(t.name), arr)
            init_names.add(t.name)

        for vi in self.g.input:
            if vi.name in init_names:
                continue
            shape = self.input_shapes.get(vi.name)
            if shape is None:
                if vi.type is None or vi.type.shape is None:
                    raise ONNXImportError(
                        f"graph input {vi.name!r} needs an input_shapes entry")
                shape = tuple(d if isinstance(d, int) and d > 0 else None
                              for d in vi.type.shape.dims)
            dtype = TENSOR_DTYPES.get(
                vi.type.elem_type if vi.type else 1, "float32")
            self.vars[vi.name] = self.sd.placeholder(vi.name, shape, dtype)

        self._process_nodes()
        return {out: self.tensor(out).name for out in outputs}

    def _process_nodes(self) -> None:
        for node in self.g.node:
            if node.domain not in ("", "ai.onnx"):
                raise ONNXImportError(
                    f"unsupported op domain {node.domain!r} ({node.op_type})")
            mapper = ONNX_OP_MAPPERS.get(node.op_type)
            if mapper is None:
                raise ONNXImportError(
                    f"no mapper for ONNX op {node.op_type!r} (node "
                    f"{node.name!r}); supported: {sorted(ONNX_OP_MAPPERS)}")
            result = mapper(self, node)
            outs = result if isinstance(result, tuple) else (result,)
            for ref, var in zip(node.output, outs):
                if ref:
                    self.vars[ref] = var
            self._try_fold(node)


# --- control flow (If / Loop) ----------------------------------------------
#
# ONNX subgraphs (If branches, Loop bodies) reference outer-scope values BY
# NAME (implicit capture) — unlike TF FunctionDefs, which take explicit
# args. Raising onto samediff.cond / samediff.while_loop therefore turns
# every captured name into a branch placeholder (or an inlined constant,
# when the outer value is host-known) bound positionally at the call site.
# Loop compiles to lax.while_loop with carry (i, cond, loop-vars, captures,
# scan accumulators); scan outputs use the dense-accumulator pattern
# (dynamic_update_slice into a preallocated [M, ...] array — the same
# TPU-native representation the TF TensorList import uses).


def _graph_captures(graph: GraphProto) -> list:
    """Names a subgraph reads from the enclosing scope, in discovery
    order — including reads made by nested subgraphs (a nested If inside
    a Loop body captures through BOTH levels unless produced locally)."""
    produced = {t.name for t in graph.initializer}
    produced |= {vi.name for vi in graph.input}
    caps, seen = [], set()
    for node in graph.node:
        for ref in node.input:
            if ref and ref not in produced and ref not in seen:
                seen.add(ref)
                caps.append(ref)
        for a in node.attribute:
            if a.g is not None:
                for c in _graph_captures(a.g):
                    if c not in produced and c not in seen:
                        seen.add(c)
                        caps.append(c)
        produced |= {o for o in node.output if o}
    # A declared output can name an outer value directly (a passthrough
    # branch with no Identity node) — that read is a capture too
    for o in graph.output:
        if o.name and o.name not in produced and o.name not in seen:
            seen.add(o.name)
            caps.append(o.name)
    return caps


def _union_captures(imp: "_GraphImporter", graphs) -> Tuple[list, list]:
    """(all_caps, var_caps): ordered union of the graphs' captures; the
    var_caps subset is NOT host-known in the outer scope and must ride as
    placeholders/loop carry (host-known captures inline as constants so
    shape/axis consumers keep working)."""
    caps, seen = [], set()
    for g in graphs:
        for c in _graph_captures(g):
            if c not in seen:
                seen.add(c)
                caps.append(c)
    var_caps = [c for c in caps if c not in imp.consts]
    for c in var_caps:
        imp.tensor(c)  # fail early with the standard unknown-value error
    return caps, var_caps


def _import_onnx_subgraph(imp: "_GraphImporter", graph: GraphProto,
                          declared_vars, all_caps, var_caps):
    """Import a branch/body GraphProto into a fresh SameDiff.

    Placeholder declaration order (positional contract with
    _as_branch_fn): graph.input (bound to declared_vars' shapes/dtypes)
    first, then var_caps. Host-known captures become subgraph constants.
    branch_outputs = the graph's declared outputs. Returns the importer
    (callers may need to record extra ops, e.g. Loop's accumulators).
    """
    if len(declared_vars) != len(graph.input):
        raise ONNXImportError(
            f"subgraph {graph.name!r} takes {len(graph.input)} inputs, "
            f"got {len(declared_vars)}")
    sub = SameDiff.create()
    simp = _GraphImporter(graph, {}, sub)
    for vi, v in zip(graph.input, declared_vars):
        simp.vars[vi.name] = sub.placeholder(
            vi.name, getattr(v, "shape", None),
            getattr(v, "dtype", None) or "float32")
    for c in var_caps:
        v = imp.tensor(c)
        simp.vars[c] = sub.placeholder(c, v.shape, v.dtype or "float32")
    _seed_subgraph_constants(imp, simp, graph, all_caps)
    simp._process_nodes()
    sub.branch_outputs = [simp.tensor(o.name).name for o in graph.output]
    return simp


def _seed_subgraph_constants(imp, simp, graph, all_caps) -> None:
    """Inline host-known outer captures + the subgraph's own initializers
    as constants of the sub-SameDiff (keeps const_value() working for
    shape/axis consumers inside branch bodies)."""
    for c in all_caps:
        if c in imp.consts:
            arr = imp.consts[c]
            simp.consts[c] = arr
            simp.vars[c] = simp.sd.constant(simp.fresh_const_name(c), arr)
    for t in graph.initializer:
        if t.name in simp.vars:
            # an initializer sharing a declared input's name is that
            # input's DEFAULT value (ONNX default-value form) — the bound
            # placeholder must win or the carried value is silently
            # ignored (mirrors init_names handling in run())
            continue
        arr = t.to_numpy()
        simp.consts[t.name] = arr
        simp.vars[t.name] = simp.sd.constant(
            simp.fresh_const_name(t.name), arr)


def import_onnx_model(
    model,
    inputs: Optional[Dict[str, Tuple]] = None,
    outputs: Optional[Sequence[str]] = None,
) -> Tuple[SameDiff, Dict[str, str], Dict[str, str]]:
    """Import an ONNX model (path, bytes, or decoded ModelProto).

    inputs: optional {graph_input_name: shape} overriding/providing input
    shapes (None dims allowed for batch). outputs: graph value names to
    expose; default = the graph's declared outputs.

    Returns (sd, input_map, output_map): ONNX value names → SameDiff
    variable names. Mirrors modelimport.tf.import_tf_graph.
    """
    ensure_onnximport_ops()
    if isinstance(model, (str, bytes)):
        data = open(model, "rb").read() if isinstance(model, str) else model
        model = ModelProto.decode(data)
    if model.graph is None:
        raise ONNXImportError("model has no graph")
    g = model.graph
    if outputs is None:
        outputs = [v.name for v in g.output]
    sd = SameDiff.create()
    imp = _GraphImporter(g, dict(inputs or {}), sd)
    out_map = imp.run(list(outputs))
    init_names = {t.name for t in g.initializer}
    in_map = {v.name: v.name for v in g.input if v.name not in init_names}
    return sd, in_map, out_map
