"""Minimal ONNX protobuf wire codec — no `onnx` / protoc-gencode needed.

ref: the reference's ONNX import (nd4j/samediff-import-onnx, SURVEY §2.3)
depends on the ONNX protobuf classes; this environment has no `onnx`
package, so this module implements the protobuf wire format (varint /
fixed32 / fixed64 / length-delimited) directly for the ONNX schema subset
the importer needs: ModelProto, GraphProto, NodeProto, AttributeProto,
TensorProto, ValueInfoProto and the nested type/shape messages. Field
numbers follow the public onnx.proto3 schema (stable since IR v3).

Both directions are implemented: decode (the importer) and encode (test
fixtures build .onnx files in-process). tests/test_onnx_import.py verifies
the wire format against the `protoc` binary as an independent oracle, so
encode/decode cannot be merely self-consistent.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# --- wire primitives -------------------------------------------------------

_WT_VARINT, _WT_64BIT, _WT_LEN, _WT_32BIT = 0, 1, 2, 5


def _write_varint(buf: bytearray, value: int) -> None:
    if value < 0:
        value &= (1 << 64) - 1  # two's-complement 64-bit, proto int64 rule
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            buf.append(b | 0x80)
        else:
            buf.append(b)
            return


def _read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift >= 70:
            raise ValueError("malformed varint")


def _signed64(value: int) -> int:
    return value - (1 << 64) if value >= (1 << 63) else value


def _write_tag(buf: bytearray, num: int, wt: int) -> None:
    _write_varint(buf, (num << 3) | wt)


def _write_len_delim(buf: bytearray, num: int, payload: bytes) -> None:
    _write_tag(buf, num, _WT_LEN)
    _write_varint(buf, len(payload))
    buf.extend(payload)


def _skip(data: bytes, pos: int, wt: int) -> int:
    if wt == _WT_VARINT:
        _, pos = _read_varint(data, pos)
    elif wt == _WT_64BIT:
        pos += 8
    elif wt == _WT_LEN:
        n, pos = _read_varint(data, pos)
        pos += n
    elif wt == _WT_32BIT:
        pos += 4
    else:
        raise ValueError(f"unsupported wire type {wt}")
    return pos


def _iter_fields(data: bytes):
    """Yield (field_number, wire_type, value_or_span) over a message."""
    pos = 0
    end = len(data)
    while pos < end:
        tag, pos = _read_varint(data, pos)
        num, wt = tag >> 3, tag & 7
        if wt == _WT_VARINT:
            val, pos = _read_varint(data, pos)
            yield num, wt, val
        elif wt == _WT_64BIT:
            yield num, wt, data[pos:pos + 8]
            pos += 8
        elif wt == _WT_LEN:
            n, pos = _read_varint(data, pos)
            yield num, wt, data[pos:pos + n]
            pos += n
        elif wt == _WT_32BIT:
            yield num, wt, data[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wt}")


def _packed_or_single_varints(wt: int, val) -> List[int]:
    """proto3 packed-by-default repeated ints; accept both encodings."""
    if wt == _WT_VARINT:
        return [val]
    out = []
    pos = 0
    while pos < len(val):
        v, pos = _read_varint(val, pos)
        out.append(v)
    return out


def _packed_or_single_f32(wt: int, val) -> List[float]:
    if wt == _WT_32BIT:
        return [struct.unpack("<f", val)[0]]
    return list(np.frombuffer(val, "<f4").tolist())


def _packed_or_single_f64(wt: int, val) -> List[float]:
    if wt == _WT_64BIT:
        return [struct.unpack("<d", val)[0]]
    return list(np.frombuffer(val, "<f8").tolist())


def _write_packed_varints(buf: bytearray, num: int, values) -> None:
    if not values:
        return
    payload = bytearray()
    for v in values:
        _write_varint(payload, int(v))
    _write_len_delim(buf, num, bytes(payload))


# --- messages --------------------------------------------------------------


@dataclass
class TensorShapeProto:
    # Each dim: int (dim_value), str (dim_param), or None (unknown).
    dims: List[Any] = field(default_factory=list)

    def encode(self) -> bytes:
        buf = bytearray()
        for d in self.dims:
            inner = bytearray()
            if isinstance(d, int):
                _write_tag(inner, 1, _WT_VARINT)
                _write_varint(inner, d)
            elif isinstance(d, str):
                _write_len_delim(inner, 2, d.encode())
            _write_len_delim(buf, 1, bytes(inner))
        return bytes(buf)

    @classmethod
    def decode(cls, data: bytes) -> "TensorShapeProto":
        dims = []
        for num, wt, val in _iter_fields(data):
            if num == 1 and wt == _WT_LEN:
                dim: Any = None
                for n2, wt2, v2 in _iter_fields(val):
                    if n2 == 1 and wt2 == _WT_VARINT:
                        dim = _signed64(v2)
                    elif n2 == 2 and wt2 == _WT_LEN:
                        dim = v2.decode()
                dims.append(dim)
        return cls(dims)


@dataclass
class TypeProto:
    elem_type: int = 0
    shape: Optional[TensorShapeProto] = None

    def encode(self) -> bytes:
        tensor = bytearray()
        if self.elem_type:
            _write_tag(tensor, 1, _WT_VARINT)
            _write_varint(tensor, self.elem_type)
        if self.shape is not None:
            _write_len_delim(tensor, 2, self.shape.encode())
        buf = bytearray()
        _write_len_delim(buf, 1, bytes(tensor))  # tensor_type oneof
        return bytes(buf)

    @classmethod
    def decode(cls, data: bytes) -> "TypeProto":
        out = cls()
        for num, wt, val in _iter_fields(data):
            if num == 1 and wt == _WT_LEN:  # tensor_type
                for n2, wt2, v2 in _iter_fields(val):
                    if n2 == 1 and wt2 == _WT_VARINT:
                        out.elem_type = v2
                    elif n2 == 2 and wt2 == _WT_LEN:
                        out.shape = TensorShapeProto.decode(v2)
        return out


@dataclass
class ValueInfoProto:
    name: str = ""
    type: Optional[TypeProto] = None

    def encode(self) -> bytes:
        buf = bytearray()
        if self.name:
            _write_len_delim(buf, 1, self.name.encode())
        if self.type is not None:
            _write_len_delim(buf, 2, self.type.encode())
        return bytes(buf)

    @classmethod
    def decode(cls, data: bytes) -> "ValueInfoProto":
        out = cls()
        for num, wt, val in _iter_fields(data):
            if num == 1 and wt == _WT_LEN:
                out.name = val.decode()
            elif num == 2 and wt == _WT_LEN:
                out.type = TypeProto.decode(val)
        return out


# onnx TensorProto.DataType values
TENSOR_DTYPES: Dict[int, str] = {
    1: "float32", 2: "uint8", 3: "int8", 4: "uint16", 5: "int16",
    6: "int32", 7: "int64", 9: "bool", 10: "float16", 11: "float64",
    12: "uint32", 13: "uint64", 16: "bfloat16",
}
_DTYPE_TO_ONNX = {v: k for k, v in TENSOR_DTYPES.items()}


@dataclass
class TensorProto:
    dims: List[int] = field(default_factory=list)
    data_type: int = 0
    raw_data: bytes = b""
    float_data: List[float] = field(default_factory=list)
    int32_data: List[int] = field(default_factory=list)
    int64_data: List[int] = field(default_factory=list)
    double_data: List[float] = field(default_factory=list)
    name: str = ""

    def encode(self) -> bytes:
        buf = bytearray()
        _write_packed_varints(buf, 1, self.dims)
        if self.data_type:
            _write_tag(buf, 2, _WT_VARINT)
            _write_varint(buf, self.data_type)
        if self.float_data:
            _write_len_delim(
                buf, 4, np.asarray(self.float_data, "<f4").tobytes())
        _write_packed_varints(buf, 5, self.int32_data)
        _write_packed_varints(buf, 7, self.int64_data)
        if self.name:
            _write_len_delim(buf, 8, self.name.encode())
        if self.raw_data:
            _write_len_delim(buf, 9, self.raw_data)
        if self.double_data:
            _write_len_delim(
                buf, 10, np.asarray(self.double_data, "<f8").tobytes())
        return bytes(buf)

    @classmethod
    def decode(cls, data: bytes) -> "TensorProto":
        out = cls()
        for num, wt, val in _iter_fields(data):
            if num == 1:
                out.dims.extend(_signed64(v)
                                for v in _packed_or_single_varints(wt, val))
            elif num == 2 and wt == _WT_VARINT:
                out.data_type = val
            elif num == 4:
                out.float_data.extend(_packed_or_single_f32(wt, val))
            elif num == 5:
                out.int32_data.extend(
                    _signed64(v) for v in _packed_or_single_varints(wt, val))
            elif num == 7:
                out.int64_data.extend(
                    _signed64(v) for v in _packed_or_single_varints(wt, val))
            elif num == 8 and wt == _WT_LEN:
                out.name = val.decode()
            elif num == 9 and wt == _WT_LEN:
                out.raw_data = val
            elif num == 10:
                out.double_data.extend(_packed_or_single_f64(wt, val))
        return out

    # -- numpy bridge --

    def to_numpy(self) -> np.ndarray:
        if self.data_type not in TENSOR_DTYPES:
            raise ValueError(f"unsupported ONNX tensor dtype {self.data_type}")
        np_dtype = TENSOR_DTYPES[self.data_type]
        shape = tuple(self.dims)
        if self.raw_data:
            if np_dtype == "bfloat16":
                import ml_dtypes

                arr = np.frombuffer(self.raw_data, ml_dtypes.bfloat16)
            else:
                arr = np.frombuffer(self.raw_data, np.dtype(np_dtype).newbyteorder("<"))
            return arr.reshape(shape).astype(np_dtype)
        if self.float_data:
            return np.asarray(self.float_data, "float32").reshape(shape).astype(np_dtype)
        if self.double_data:
            return np.asarray(self.double_data, "float64").reshape(shape).astype(np_dtype)
        if self.int64_data:
            return np.asarray(self.int64_data, "int64").reshape(shape).astype(np_dtype)
        if self.int32_data:
            # int32_data also carries bool/int8/int16/uint8/uint16/float16/
            # bfloat16 per spec; the 16-bit float types are stored as raw
            # bit patterns in the low uint16, NOT as numeric values.
            raw = np.asarray(self.int32_data, "int64")
            if np_dtype in ("float16", "bfloat16"):
                bits = raw.astype(np.uint16)
                if np_dtype == "bfloat16":
                    import ml_dtypes

                    return bits.view(ml_dtypes.bfloat16).reshape(shape)
                return bits.view(np.float16).reshape(shape)
            return raw.reshape(shape).astype(np_dtype)
        return np.zeros(shape, np_dtype)

    @classmethod
    def from_numpy(cls, arr: np.ndarray, name: str = "") -> "TensorProto":
        arr = np.ascontiguousarray(arr)
        key = arr.dtype.name
        if key not in _DTYPE_TO_ONNX:
            raise ValueError(f"unsupported numpy dtype {arr.dtype}")
        return cls(dims=list(arr.shape), data_type=_DTYPE_TO_ONNX[key],
                   raw_data=arr.astype(arr.dtype.newbyteorder("<")).tobytes(),
                   name=name)


# AttributeProto.AttributeType
ATTR_FLOAT, ATTR_INT, ATTR_STRING, ATTR_TENSOR = 1, 2, 3, 4
ATTR_GRAPH = 5
ATTR_FLOATS, ATTR_INTS, ATTR_STRINGS = 6, 7, 8


@dataclass
class AttributeProto:
    name: str = ""
    type: int = 0
    f: float = 0.0
    i: int = 0
    s: bytes = b""
    t: Optional[TensorProto] = None
    g: Optional["GraphProto"] = None  # control-flow branch/body graphs
    floats: List[float] = field(default_factory=list)
    ints: List[int] = field(default_factory=list)
    strings: List[bytes] = field(default_factory=list)

    def value(self):
        if self.type == ATTR_FLOAT:
            return self.f
        if self.type == ATTR_INT:
            return self.i
        if self.type == ATTR_STRING:
            return self.s.decode()
        if self.type == ATTR_TENSOR:
            return self.t
        if self.type == ATTR_GRAPH:
            return self.g
        if self.type == ATTR_FLOATS:
            return list(self.floats)
        if self.type == ATTR_INTS:
            return list(self.ints)
        if self.type == ATTR_STRINGS:
            return [s.decode() for s in self.strings]
        raise ValueError(f"unsupported attribute type {self.type} ({self.name})")

    def encode(self) -> bytes:
        buf = bytearray()
        if self.name:
            _write_len_delim(buf, 1, self.name.encode())
        if self.type == ATTR_FLOAT:
            _write_tag(buf, 2, _WT_32BIT)
            buf.extend(struct.pack("<f", self.f))
        elif self.type == ATTR_INT:
            _write_tag(buf, 3, _WT_VARINT)
            _write_varint(buf, self.i)
        elif self.type == ATTR_STRING:
            _write_len_delim(buf, 4, self.s)
        elif self.type == ATTR_TENSOR:
            _write_len_delim(buf, 5, self.t.encode())
        elif self.type == ATTR_GRAPH:
            _write_len_delim(buf, 6, self.g.encode())
        elif self.type == ATTR_FLOATS:
            _write_len_delim(buf, 7, np.asarray(self.floats, "<f4").tobytes())
        elif self.type == ATTR_INTS:
            _write_packed_varints(buf, 8, self.ints)
        elif self.type == ATTR_STRINGS:
            for s in self.strings:
                _write_len_delim(buf, 9, s)
        _write_tag(buf, 20, _WT_VARINT)
        _write_varint(buf, self.type)
        return bytes(buf)

    @classmethod
    def decode(cls, data: bytes) -> "AttributeProto":
        out = cls()
        for num, wt, val in _iter_fields(data):
            if num == 1 and wt == _WT_LEN:
                out.name = val.decode()
            elif num == 2 and wt == _WT_32BIT:
                out.f = struct.unpack("<f", val)[0]
            elif num == 3 and wt == _WT_VARINT:
                out.i = _signed64(val)
            elif num == 4 and wt == _WT_LEN:
                out.s = val
            elif num == 5 and wt == _WT_LEN:
                out.t = TensorProto.decode(val)
            elif num == 6 and wt == _WT_LEN:
                # GraphProto is defined later in this module; by decode
                # time (runtime) the name resolves
                out.g = GraphProto.decode(val)
            elif num == 7:
                out.floats.extend(_packed_or_single_f32(wt, val))
            elif num == 8:
                out.ints.extend(
                    _signed64(v) for v in _packed_or_single_varints(wt, val))
            elif num == 9 and wt == _WT_LEN:
                out.strings.append(val)
            elif num == 20 and wt == _WT_VARINT:
                out.type = val
        if not out.type:
            # Pre-IR3 writers omit `type`; infer from the populated field.
            if out.t is not None:
                out.type = ATTR_TENSOR
            elif out.g is not None:
                out.type = ATTR_GRAPH
            elif out.floats:
                out.type = ATTR_FLOATS
            elif out.ints:
                out.type = ATTR_INTS
            elif out.strings:
                out.type = ATTR_STRINGS
            elif out.s:
                out.type = ATTR_STRING
        return out


@dataclass
class NodeProto:
    input: List[str] = field(default_factory=list)
    output: List[str] = field(default_factory=list)
    name: str = ""
    op_type: str = ""
    attribute: List[AttributeProto] = field(default_factory=list)
    domain: str = ""

    def attrs(self) -> Dict[str, Any]:
        return {a.name: a.value() for a in self.attribute}

    def encode(self) -> bytes:
        buf = bytearray()
        for s in self.input:
            _write_len_delim(buf, 1, s.encode())
        for s in self.output:
            _write_len_delim(buf, 2, s.encode())
        if self.name:
            _write_len_delim(buf, 3, self.name.encode())
        if self.op_type:
            _write_len_delim(buf, 4, self.op_type.encode())
        for a in self.attribute:
            _write_len_delim(buf, 5, a.encode())
        if self.domain:
            _write_len_delim(buf, 7, self.domain.encode())
        return bytes(buf)

    @classmethod
    def decode(cls, data: bytes) -> "NodeProto":
        out = cls()
        for num, wt, val in _iter_fields(data):
            if num == 1 and wt == _WT_LEN:
                out.input.append(val.decode())
            elif num == 2 and wt == _WT_LEN:
                out.output.append(val.decode())
            elif num == 3 and wt == _WT_LEN:
                out.name = val.decode()
            elif num == 4 and wt == _WT_LEN:
                out.op_type = val.decode()
            elif num == 5 and wt == _WT_LEN:
                out.attribute.append(AttributeProto.decode(val))
            elif num == 7 and wt == _WT_LEN:
                out.domain = val.decode()
        return out


@dataclass
class GraphProto:
    node: List[NodeProto] = field(default_factory=list)
    name: str = ""
    initializer: List[TensorProto] = field(default_factory=list)
    input: List[ValueInfoProto] = field(default_factory=list)
    output: List[ValueInfoProto] = field(default_factory=list)
    value_info: List[ValueInfoProto] = field(default_factory=list)

    def encode(self) -> bytes:
        buf = bytearray()
        for n in self.node:
            _write_len_delim(buf, 1, n.encode())
        if self.name:
            _write_len_delim(buf, 2, self.name.encode())
        for t in self.initializer:
            _write_len_delim(buf, 5, t.encode())
        for v in self.input:
            _write_len_delim(buf, 11, v.encode())
        for v in self.output:
            _write_len_delim(buf, 12, v.encode())
        for v in self.value_info:
            _write_len_delim(buf, 13, v.encode())
        return bytes(buf)

    @classmethod
    def decode(cls, data: bytes) -> "GraphProto":
        out = cls()
        for num, wt, val in _iter_fields(data):
            if num == 1 and wt == _WT_LEN:
                out.node.append(NodeProto.decode(val))
            elif num == 2 and wt == _WT_LEN:
                out.name = val.decode()
            elif num == 5 and wt == _WT_LEN:
                out.initializer.append(TensorProto.decode(val))
            elif num == 11 and wt == _WT_LEN:
                out.input.append(ValueInfoProto.decode(val))
            elif num == 12 and wt == _WT_LEN:
                out.output.append(ValueInfoProto.decode(val))
            elif num == 13 and wt == _WT_LEN:
                out.value_info.append(ValueInfoProto.decode(val))
        return out


@dataclass
class OperatorSetIdProto:
    domain: str = ""
    version: int = 0

    def encode(self) -> bytes:
        buf = bytearray()
        if self.domain:
            _write_len_delim(buf, 1, self.domain.encode())
        if self.version:
            _write_tag(buf, 2, _WT_VARINT)
            _write_varint(buf, self.version)
        return bytes(buf)

    @classmethod
    def decode(cls, data: bytes) -> "OperatorSetIdProto":
        out = cls()
        for num, wt, val in _iter_fields(data):
            if num == 1 and wt == _WT_LEN:
                out.domain = val.decode()
            elif num == 2 and wt == _WT_VARINT:
                out.version = _signed64(val)
        return out


@dataclass
class ModelProto:
    ir_version: int = 8
    producer_name: str = ""
    graph: Optional[GraphProto] = None
    opset_import: List[OperatorSetIdProto] = field(default_factory=list)

    def encode(self) -> bytes:
        buf = bytearray()
        if self.ir_version:
            _write_tag(buf, 1, _WT_VARINT)
            _write_varint(buf, self.ir_version)
        if self.producer_name:
            _write_len_delim(buf, 2, self.producer_name.encode())
        if self.graph is not None:
            _write_len_delim(buf, 7, self.graph.encode())
        for op in self.opset_import:
            _write_len_delim(buf, 8, op.encode())
        return bytes(buf)

    @classmethod
    def decode(cls, data: bytes) -> "ModelProto":
        out = cls(ir_version=0)
        for num, wt, val in _iter_fields(data):
            if num == 1 and wt == _WT_VARINT:
                out.ir_version = _signed64(val)
            elif num == 2 and wt == _WT_LEN:
                out.producer_name = val.decode()
            elif num == 7 and wt == _WT_LEN:
                out.graph = GraphProto.decode(val)
            elif num == 8 and wt == _WT_LEN:
                out.opset_import.append(OperatorSetIdProto.decode(val))
        return out
