"""Incident bundles: auto-captured, self-contained post-mortems.

When a sentinel detector fires, the evidence that explains it — the
anomalous steps, the queue state, the stacks burning the time — exists
for seconds. A human curling ``/debug/profile`` afterwards captures the
*recovery*, not the anomaly. This module captures evidence AT firing
time, automatically, into one bounded on-disk bundle:

``<incidents_dir>/<incident-id>/``

- ``incident.json``       — manifest: detector, open/close times, state,
  artifact table (the fetch surface's index row);
- ``verdict.json``        — the detector's judgement: observed sample vs
  rolling baseline (median/MAD), score, thresholds, transition history;
- ``metrics.prom`` / ``metrics.json`` — full registry scrape at firing;
- ``flightrecorder.json`` — the black-box event ring (bounded window);
- ``spans.json``          — the most recent finished spans;
- ``requests.json``       — the request ledger's worst requests of the
  anomaly window (bad outcomes first, then by latency), each with its
  tail-retained span tree — "which requests were suffering, and where
  did their time go" inside the bundle itself;
- ``flames.txt`` (+ meta in the manifest) — the host stack sampler's
  collapsed flame data (dense over the anomaly: the sentinel armed the
  high-rate window at *suspect*);
- ``profile.json``        — asynchronous: when a profile hook is
  registered (ModelServer registers a live-traffic ``jax.profiler``
  capture; ``Trainer.fit`` registers a capture of the *next N steps*),
  a short device capture lands here moments after the bundle opens.

The bundle directory is staged under a dot-prefixed temp name and
renamed into place, so a reader listing the incidents dir never sees a
half-written bundle. Retention is bounded (``max_bundles``; oldest
closed bundles pruned first). Every open/close is a flight event
(``incident.open`` / ``incident.close``) and counts in
``incident_bundles_total{detector=}`` / ``incidents_open``.

Consumers: ``GET /debug/incidents`` (index) and
``GET /debug/incidents/<id>`` (full bundle) on ``ModelServer``; the
federation snapshot carries each worker's index so
``GET /cluster/debug/incidents`` shows the cohort view and cohort
teardown dossiers reference open incidents.

Stdlib only (jax is touched only inside the step-capture path, lazily).
"""

from __future__ import annotations

import itertools
import json
import os
import re
import shutil
import threading
import time
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence

from deeplearning4j_tpu.observability import metrics as _metrics
from deeplearning4j_tpu.observability import trace as _trace
from deeplearning4j_tpu.observability.flightrecorder import (
    get_flight_recorder,
    record_event,
)

_ID_SAFE_RE = re.compile(r"[^A-Za-z0-9_.\-]+")
# incident ids are path components served back over HTTP: the fetch
# route must only ever resolve names this shape (no separators, no dots
# leading) — belt and suspenders against traversal
INCIDENT_ID_RE = re.compile(r"^inc-[0-9]{13}-[0-9]{3}-[A-Za-z0-9_.\-]+$")
# artifact names come from on-disk manifests the manager merely ADOPTED
# (_load_existing), so the fetch surface treats them as untrusted: a
# strict allowlist (no separators, no leading dot, so never '..' or a
# hidden/staging file) keeps ``bundle_dir / name`` inside the bundle
_ARTIFACT_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.\-]*$")

ENV_INCIDENT_DIR = "DL4J_TPU_INCIDENT_DIR"


def _worst_requests(window_s: float) -> dict:
    """The request ledger's worst requests of the trailing window with
    their retained span trees (reqlog.postmortem) — lazy import, never
    raises, degrades to an empty document when no ledger exists."""
    try:
        from deeplearning4j_tpu.observability.reqlog import postmortem

        return postmortem(window_s)
    except Exception:  # noqa: BLE001 — one artifact, never the bundle
        return {"window_seconds": window_s, "count": 0, "requests": []}


def _sentinel_metrics():
    try:
        if not _metrics.enabled():
            return None
        from deeplearning4j_tpu.observability.sentinel import (
            get_sentinel_metrics,
        )

        return get_sentinel_metrics()
    except Exception:  # noqa: BLE001 — metrics never fail the pipeline
        return None


class IncidentManager:
    """Owns one incidents directory: bundle writes, retention, index.

    ``max_bundles`` bounds disk (oldest closed incidents pruned first —
    an open incident is live evidence and survives pruning unless
    everything else is open too). ``flight_window_s`` /
    ``max_flight_events`` / ``span_limit`` bound the bundle's artifact
    sizes; ``profile_timeout_s`` bounds how long the async profile
    thread waits on a hook.
    """

    def __init__(self, dir, *, max_bundles: int = 16,
                 flight_window_s: float = 180.0,
                 max_flight_events: int = 2048,
                 span_limit: int = 512,
                 profile_timeout_s: float = 60.0):
        if max_bundles < 1:
            raise ValueError(f"max_bundles must be >= 1, got {max_bundles}")
        self.dir = Path(dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.max_bundles = int(max_bundles)
        self.flight_window_s = float(flight_window_s)
        self.max_flight_events = int(max_flight_events)
        self.span_limit = int(span_limit)
        self.profile_timeout_s = float(profile_timeout_s)
        self._lock = threading.Lock()
        self._seq = itertools.count(1)
        self._manifests: Dict[str, dict] = {}
        self._load_existing()

    # -- disk ----------------------------------------------------------------

    def _load_existing(self):
        """Adopt bundles already on disk (a restarted process keeps
        serving its previous incidents; stale 'open' ones from a dead
        process read as open until pruned)."""
        for p in sorted(self.dir.glob("inc-*/incident.json")):
            try:
                man = json.loads(p.read_text())
            except OSError:
                continue  # unreadable right now: leave it alone
            except ValueError:
                man = None
            # adopted manifests are untrusted disk content: the id must
            # match the directory it came from AND the strict id shape,
            # or a crafted incident.json could point retention's rmtree
            # / the fetch surface outside the incidents dir
            if isinstance(man, dict) and man.get("id") == p.parent.name \
                    and INCIDENT_ID_RE.match(str(man["id"])):
                self._manifests[man["id"]] = man
            else:
                # un-adoptable bundle (forged or corrupt manifest): it
                # would never enter _manifests, so retention could never
                # prune it and it would occupy the "bounded" dir forever
                # — drop it now. Our own writers stage + rename, so a
                # valid bundle is never visible in this state.
                shutil.rmtree(p.parent, ignore_errors=True)

    def _write_manifest(self, bundle_dir: Path, manifest: dict):
        tmp = bundle_dir / ".incident.json.tmp"
        tmp.write_text(json.dumps(manifest, indent=2, default=str))
        os.replace(tmp, bundle_dir / "incident.json")

    def _update_open_gauge(self):
        sm = _sentinel_metrics()
        if sm is not None:
            sm.incidents_open.set(float(sum(
                1 for m in self._manifests.values()
                if m.get("state") == "open")))

    # -- open ----------------------------------------------------------------

    def open_incident(self, verdict: dict, *,
                      registries: Optional[Sequence] = None,
                      sampler=None, profile: bool = True) -> str:
        """Capture + write one bundle; returns the incident id. The
        synchronous artifacts land atomically (staged dir, renamed into
        place); the device profile (if any hook is registered) is
        captured on a background thread and added to the final dir —
        it is a capture of the *next* steps/requests by definition."""
        detector = _ID_SAFE_RE.sub("-", str(
            verdict.get("detector", "unknown"))) or "unknown"
        opened_at = time.time()
        with self._lock:
            iid = f"inc-{int(opened_at * 1000):013d}-" \
                  f"{next(self._seq) % 1000:03d}-{detector}"
        regs = (list(registries) if registries is not None
                else [_metrics.default_registry()])
        flight = get_flight_recorder().dump(
            last_seconds=self.flight_window_s,
            max_events=self.max_flight_events)
        spans = [s.to_json()
                 for s in _trace.get_tracer().spans()[-self.span_limit:]]
        flames = sampler.dump() if sampler is not None else None
        requests_doc = _worst_requests(self.flight_window_s)
        hooks = profile_hooks() if profile else {}

        staging = self.dir / f".staging-{iid}"
        staging.mkdir(parents=True, exist_ok=True)
        try:
            (staging / "verdict.json").write_text(
                json.dumps(verdict, indent=2, default=str))
            try:
                # the bundle is a self-contained post-mortem read by
                # humans, never scraped by a classic parser: keep the
                # exemplar suffixes (slow bucket -> trace id) in the
                # text artifact too
                (staging / "metrics.prom").write_text(
                    _metrics.render_text_multi(regs, openmetrics=True))
                (staging / "metrics.json").write_text(
                    json.dumps(_metrics.render_json_multi(regs),
                               default=str))
            except Exception as e:  # noqa: BLE001 — a bad registry must
                (staging / "metrics.prom").write_text(  # not lose the rest
                    f"# scrape failed: {e}\n")
                (staging / "metrics.json").write_text(
                    json.dumps({"error": str(e)[:200]}))
            (staging / "flightrecorder.json").write_text(
                json.dumps(flight, default=str))
            (staging / "spans.json").write_text(
                json.dumps({"count": len(spans), "spans": spans},
                           default=str))
            (staging / "requests.json").write_text(
                json.dumps(requests_doc, default=str))
            (staging / "flames.txt").write_text(
                (flames or {}).get("collapsed", ""))
            manifest = {
                "id": iid,
                "detector": verdict.get("detector"),
                "state": "open",
                "opened_at": opened_at,
                "closed_at": None,
                "score": verdict.get("score"),
                "observed": verdict.get("observed"),
                "baseline": verdict.get("baseline"),
                "profile": ("pending" if hooks else "none"),
                "profile_hooks": sorted(hooks),
                "sampler": ({k: v for k, v in flames.items()
                             if k != "collapsed"}
                            if flames is not None else None),
                "artifacts": ["verdict.json", "metrics.prom",
                              "metrics.json", "flightrecorder.json",
                              "spans.json", "requests.json",
                              "flames.txt"],
            }
            self._write_manifest(staging, manifest)
            final = self.dir / iid
            os.rename(staging, final)
        except Exception:
            shutil.rmtree(staging, ignore_errors=True)
            raise
        with self._lock:
            self._manifests[iid] = manifest
            self._prune_locked()
            self._update_open_gauge()
        sm = _sentinel_metrics()
        if sm is not None:
            sm.incident_bundles_total.inc(
                detector=str(verdict.get("detector", "unknown")))
        record_event("incident.open", id=iid,
                     detector=verdict.get("detector"),
                     score=verdict.get("score"),
                     observed=verdict.get("observed"))
        if hooks:
            threading.Thread(
                target=self._capture_profile, args=(iid, dict(hooks)),
                daemon=True, name=f"incident-profile-{iid[-8:]}").start()
        return iid

    def _capture_profile(self, iid: str, hooks: Dict[str, Callable]):
        """Run every registered profile hook (sequentially: jax has one
        global profiler session) and attach the results to the bundle.
        Each hook gets at most ``profile_timeout_s``: a hung hook must
        not leave the bundle's profile pending forever, and the built-in
        hooks tolerate an abandoned waiter (they clean up their own
        profiler session on the owning thread)."""
        results = {}
        for name, fn in sorted(hooks.items()):
            box: dict = {}

            def _run(fn=fn, box=box):
                try:
                    box["result"] = fn()
                except Exception as e:  # noqa: BLE001 — one failed hook
                    box["result"] = {"available": False,  # is a recorded
                                     "reason": str(e)[:300]}  # outcome

            t = threading.Thread(target=_run, daemon=True,
                                 name=f"incident-hook-{name}")
            t.start()
            t.join(self.profile_timeout_s)
            if t.is_alive():
                results[name] = {
                    "available": False,
                    "reason": ("hook did not return within "
                               f"{self.profile_timeout_s:g}s")}
            else:
                results[name] = box["result"]
        bundle_dir = self.dir / iid
        with self._lock:
            if self._manifests.get(iid) is None or not bundle_dir.is_dir():
                return  # pruned while capturing
        # the profile payload can be large (flame captures, host-sampler
        # dumps) — serialize and write it OUTSIDE the incident lock; only
        # this capture thread writes this bundle's profile.json
        try:
            tmp = bundle_dir / ".profile.json.tmp"
            tmp.write_text(json.dumps(
                {"captured_at": time.time(), "captures": results},
                default=str))
            os.replace(tmp, bundle_dir / "profile.json")
            wrote = True
        except OSError:
            wrote = False
        with self._lock:
            man = self._manifests.get(iid)
            if man is not None:
                if wrote:
                    man["profile"] = "done"
                    if "profile.json" not in man.setdefault(
                            "artifacts", []):
                        man["artifacts"].append("profile.json")
                    # small rewrite; artifact list + status flip must
                    # stay atomic with retention's locked prune walk
                    self._write_manifest(bundle_dir, man)
                else:
                    man["profile"] = "failed"
        if man is None:
            # pruned while writing: our write may have raced retention's
            # rmtree and resurrected a dir holding only profile.json —
            # such a dir has no incident.json, is invisible to the
            # adoption scan, and would leak forever. Reclaim it.
            shutil.rmtree(bundle_dir, ignore_errors=True)

    # -- close / retention ---------------------------------------------------

    def close_incident(self, incident_id: str,
                       resolution: Optional[dict] = None) -> bool:
        """Mark an incident closed (idempotent); returns True when it
        transitioned open→closed."""
        with self._lock:
            man = self._manifests.get(incident_id)
            if man is None or man.get("state") == "closed":
                return False
            man["state"] = "closed"
            man["closed_at"] = time.time()
            man["duration_s"] = round(
                man["closed_at"] - float(man.get("opened_at", 0.0)), 3)
            bundle_dir = self.dir / incident_id
            try:
                if resolution is not None:
                    # analysis: allow(blocking-under-lock) — bounded
                    # caller-provided dict (~1 KB); the artifact list and
                    # the closed flip must stay atomic with the write
                    (bundle_dir / "resolution.json").write_text(
                        json.dumps(resolution, indent=2, default=str))
                    if "resolution.json" not in man.get("artifacts", []):
                        man.setdefault("artifacts",
                                       []).append("resolution.json")
                self._write_manifest(bundle_dir, man)
            except OSError:
                pass
            self._update_open_gauge()
        record_event("incident.close", id=incident_id,
                     detector=man.get("detector"),
                     duration_s=man.get("duration_s"))
        return True

    def _prune_locked(self):
        """Drop the oldest bundles beyond ``max_bundles`` (closed first;
        open ones only when everything remaining is open)."""
        if len(self._manifests) <= self.max_bundles:
            return
        by_age = sorted(self._manifests.values(),
                        key=lambda m: (m.get("state") == "open",
                                       m.get("opened_at", 0.0)))
        excess = len(self._manifests) - self.max_bundles
        for man in by_age[:excess]:
            iid = man["id"]
            self._manifests.pop(iid, None)
            shutil.rmtree(self.dir / iid, ignore_errors=True)

    # -- read surface --------------------------------------------------------

    def index(self) -> List[dict]:
        """Compact manifest rows, newest first — the ``/debug/incidents``
        list and the federation snapshot's per-worker incident index."""
        with self._lock:
            rows = sorted(self._manifests.values(),
                          key=lambda m: -float(m.get("opened_at", 0.0)))
            return [{k: m.get(k) for k in
                     ("id", "detector", "state", "opened_at", "closed_at",
                      "duration_s", "score", "observed", "profile")}
                    for m in rows]

    def open_count(self) -> int:
        with self._lock:
            return sum(1 for m in self._manifests.values()
                       if m.get("state") == "open")

    def get(self, incident_id: str) -> Optional[dict]:
        """The full bundle — manifest plus every artifact inline (JSON
        artifacts parsed, text artifacts as strings)."""
        if not INCIDENT_ID_RE.match(incident_id or ""):
            return None
        with self._lock:
            man = self._manifests.get(incident_id)
            if man is None:
                return None
            man = dict(man)
        bundle_dir = self.dir / incident_id
        out = {"manifest": man, "artifacts": {}}
        for name in man.get("artifacts", []):
            name = str(name)
            if not _ARTIFACT_NAME_RE.match(name):
                # adopted-manifest artifact names are untrusted: a name
                # with a separator or leading dot could read outside the
                # bundle over the debug surface — never serve it
                continue
            path = bundle_dir / name
            try:
                text = path.read_text()
            except OSError:
                out["artifacts"][name] = None
                continue
            if name.endswith(".json"):
                try:
                    out["artifacts"][name] = json.loads(text)
                except ValueError:
                    out["artifacts"][name] = text
            else:
                out["artifacts"][name] = text
        return out


# -- process-global manager ---------------------------------------------------

_MANAGER: Optional[IncidentManager] = None
_manager_lock = threading.Lock()


def get_incident_manager(create: bool = False) -> Optional[IncidentManager]:
    """The process incident manager. ``create=True`` makes one when none
    exists: directory from ``DL4J_TPU_INCIDENT_DIR`` or a per-process
    temp dir (bounded retention keeps it small either way)."""
    global _MANAGER
    with _manager_lock:
        if _MANAGER is None and create:
            import tempfile

            d = os.environ.get(ENV_INCIDENT_DIR) or os.path.join(
                tempfile.gettempdir(), f"dl4j-tpu-incidents-{os.getpid()}")
            _MANAGER = IncidentManager(d)
        return _MANAGER


def set_incident_manager(mgr: Optional[IncidentManager]) -> None:
    global _MANAGER
    with _manager_lock:
        _MANAGER = mgr


def incident_index() -> List[dict]:
    """The process's incident index, or [] — what the federation
    snapshot embeds (never creates a manager as a side effect)."""
    mgr = get_incident_manager()
    if mgr is None:
        return []
    try:
        return mgr.index()
    except Exception:  # noqa: BLE001 — telemetry never fails the caller
        return []


# -- profile hooks ------------------------------------------------------------

_PROFILE_HOOKS: Dict[str, Callable[[], dict]] = {}
_hooks_lock = threading.Lock()


def register_profile_hook(name: str, fn: Callable[[], dict]) -> None:
    """Register a device-capture hook the incident pipeline runs right
    after a bundle opens. The hook returns a JSON-serializable dict
    (``{"available": bool, ...}``). Last registration per name wins."""
    with _hooks_lock:
        _PROFILE_HOOKS[name] = fn


def unregister_profile_hook(name: str, fn: Optional[Callable] = None) -> None:
    """Remove a hook; with ``fn`` given, only when it is still the
    registered one (a stopped server must not unhook its successor).
    Equality, not identity: bound methods are re-created per attribute
    access, so ``server._hook is server._hook`` is False while ``==``
    compares the underlying (instance, function) pair."""
    with _hooks_lock:
        if fn is None or _PROFILE_HOOKS.get(name) == fn:
            _PROFILE_HOOKS.pop(name, None)


def profile_hooks() -> Dict[str, Callable[[], dict]]:
    with _hooks_lock:
        return dict(_PROFILE_HOOKS)


# -- train-side step capture --------------------------------------------------
#
# The serving hook captures by wall time (live traffic keeps the device
# busy); training wants "the next N steps" — the capture must start and
# stop on step boundaries inside the fit loop. The fit loop calls
# note_train_step() once per iteration (a no-op global check when no
# capture is pending); request_step_capture() is called from the
# incident profile thread and blocks until the capture completes or
# times out.


class _StepCapture:
    def __init__(self, n_steps: int):
        self.n_steps = int(n_steps)
        self.done = threading.Event()
        self.result: dict = {"available": False, "reason": "not started"}
        self.abandoned = False
        self._started = False
        self._dir: Optional[str] = None
        self._t0 = 0.0
        self._steps = 0

    def abort(self, reason: str) -> None:
        """Tear down a capture that will never complete — the waiter
        timed out or the fit loop ended. MUST run on the fit thread (the
        thread driving ``on_step``), so a live ``jax.profiler`` session
        is stopped by the same thread that started it and can never be
        left open to wedge every future capture in the process."""
        if self._started and not self.done.is_set():
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        if not self.done.is_set():
            self.result = {"available": False, "reason": reason}
            self.done.set()

    def on_step(self):
        import glob
        import tempfile

        import jax

        if not self._started:
            self._dir = tempfile.mkdtemp(prefix="dl4j-tpu-incident-steps-")
            try:
                jax.profiler.start_trace(self._dir)
            except Exception as e:  # noqa: BLE001 — e.g. another capture
                self.result = {"available": False,       # holds the session
                               "reason": f"profiler busy: {e}"[:300]}
                self.done.set()
                raise _CaptureFinished()
            self._t0 = time.monotonic()
            self._started = True
            return
        self._steps += 1
        if self._steps < self.n_steps:
            return
        wall_ms = (time.monotonic() - self._t0) * 1000.0
        try:
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            self.result = {"available": False, "reason": str(e)[:300]}
            self.done.set()
            raise _CaptureFinished()
        hits = sorted(glob.glob(os.path.join(
            self._dir, "**", "*.trace.json.gz"), recursive=True),
            key=os.path.getmtime)
        self.result = {
            "available": bool(hits), "kind": "train_steps",
            "steps": self._steps, "duration_ms": round(wall_ms, 1),
            "trace_dir": self._dir,
            "trace_file": hits[-1] if hits else None,
            "trace_bytes": (os.path.getsize(hits[-1]) if hits else 0),
        }
        if not hits:
            self.result["reason"] = "profiler produced no trace file"
        self.done.set()
        raise _CaptureFinished()


class _CaptureFinished(Exception):
    pass


_TRAIN_CAPTURE: Optional[_StepCapture] = None
_train_lock = threading.Lock()
_TRAIN_FIT_DEPTH = 0


def enter_training() -> None:
    """Called by ``Trainer.fit`` on entry: marks live training and
    auto-registers the ``train`` profile hook (capture of the next N
    steps) the first time."""
    global _TRAIN_FIT_DEPTH
    with _train_lock:
        _TRAIN_FIT_DEPTH += 1
    register_profile_hook("train", _train_profile_hook)


def exit_training() -> None:
    global _TRAIN_FIT_DEPTH, _TRAIN_CAPTURE
    cap = None
    with _train_lock:
        _TRAIN_FIT_DEPTH = max(0, _TRAIN_FIT_DEPTH - 1)
        if _TRAIN_FIT_DEPTH == 0 and _TRAIN_CAPTURE is not None:
            cap, _TRAIN_CAPTURE = _TRAIN_CAPTURE, None
    if cap is not None:
        # fit ended mid-capture: stop a live trace (this runs on the fit
        # thread) and fail the waiter fast instead of letting it burn
        # its full timeout
        cap.abort("training ended before the capture completed")


def training_active() -> bool:
    return _TRAIN_FIT_DEPTH > 0


def note_train_step() -> None:
    """Per-step hook in ``Trainer.fit``. Fast path: one global load and
    None check. When a capture is pending, starts/advances/stops the
    ``jax.profiler`` trace on step boundaries."""
    global _TRAIN_CAPTURE
    cap = _TRAIN_CAPTURE
    if cap is None:
        return
    if cap.abandoned:
        # the waiter gave up: stop any live trace from the fit thread
        # (never leave the global profiler session open) and clear
        cap.abort("capture abandoned by its waiter")
        with _train_lock:
            if _TRAIN_CAPTURE is cap:
                _TRAIN_CAPTURE = None
        return
    try:
        cap.on_step()
    except _CaptureFinished:
        with _train_lock:
            if _TRAIN_CAPTURE is cap:
                _TRAIN_CAPTURE = None
    except Exception as e:  # noqa: BLE001 — capture must never kill a fit
        cap.result = {"available": False, "reason": str(e)[:300]}
        cap.done.set()
        with _train_lock:
            if _TRAIN_CAPTURE is cap:
                _TRAIN_CAPTURE = None


def request_step_capture(n_steps: int = 8,
                         timeout_s: float = 30.0) -> dict:
    """Arm a device capture of the next ``n_steps`` training steps and
    wait (bounded) for it; returns the capture document. Unavailable
    fast when no fit loop is live or a capture is already pending."""
    global _TRAIN_CAPTURE
    cap = _StepCapture(n_steps)
    with _train_lock:
        # depth check must share the install's critical section: a fit
        # exiting between them would strand a capture no thread will
        # ever service (exit_training aborts under this same lock)
        if _TRAIN_FIT_DEPTH <= 0:
            return {"available": False,
                    "reason": "no training loop is live"}
        if _TRAIN_CAPTURE is not None:
            return {"available": False,
                    "reason": "a step capture is already pending"}
        _TRAIN_CAPTURE = cap
    if not cap.done.wait(timeout_s):
        # do NOT clear _TRAIN_CAPTURE here: a trace the fit thread
        # started must be stopped by the fit thread (next step or fit
        # exit), or the leaked global profiler session would wedge
        # every future capture in the process
        cap.abandoned = True
        return {"available": False,
                "reason": f"capture did not complete within {timeout_s:g}s"}
    return cap.result


def _train_profile_hook() -> dict:
    return request_step_capture()
