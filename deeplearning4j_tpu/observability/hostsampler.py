"""Always-on host stack sampler: cheap continuous flame data.

``/debug/profile`` captures the *device* side on demand; nothing
captures the *host* side continuously — yet most production anomalies
(a wedged data pipeline, a lock convoy in the serving path, a runaway
background compile) live in host Python, and by the time a human
attaches a profiler the anomalous seconds are gone. This module is the
always-on answer: a daemon thread walking ``sys._current_frames()`` at
a low default rate (~20 Hz), folding every thread's stack into bounded
aggregated flame data the incident pipeline can snapshot the instant a
detector fires.

Design constraints, in order:

- **idle-cheap**: one sample is a ``sys._current_frames()`` call plus a
  frame walk per live thread — tens of microseconds for a typical
  process. At 20 Hz that is well under 0.1% of a core (the ``sentinel``
  bench config gates the whole always-on plane < 2% of step time).
- **bounded**: stacks fold to ``module:function`` frames (no line
  numbers — line-level detail explodes cardinality without aiding the
  "where is the time going" question), depth-capped, and the aggregate
  table caps distinct stacks; overflow folds into a counted
  ``<overflow>`` bucket instead of growing without bound.
- **armable**: :meth:`arm` raises the rate (default 200 Hz) for a
  bounded window — the sentinel arms it when a detector turns
  *suspect*, so by the time the detector *fires* the flame data over
  the anomalous window is dense, then the rate decays back by itself.

Export is the classic collapsed-stack format (``frame;frame;frame N``
per line — flamegraph.pl / speedscope / pyspy-compatible), with the
thread name as the root frame so one document shows every thread's
flame side by side.

Stdlib only; no jax, no registry requirement (the sampler feeds the
sentinel metric bundle opportunistically when one exists).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

DEFAULT_HZ = 20.0
DEFAULT_ARMED_HZ = 200.0
DEFAULT_MAX_DEPTH = 48
DEFAULT_MAX_STACKS = 2048

_OVERFLOW_KEY = "<overflow>"


def fold_frame(frame, max_depth: int = DEFAULT_MAX_DEPTH) -> str:
    """Fold one thread's live frame chain to ``mod:fn;mod:fn;...``
    (root first). Modules render as their basename without extension —
    ``module:function`` granularity keeps the table small and stable
    across line-level code motion."""
    parts: List[str] = []
    depth = 0
    while frame is not None and depth < max_depth:
        code = frame.f_code
        mod = os.path.splitext(os.path.basename(code.co_filename))[0]
        parts.append(f"{mod}:{code.co_name}")
        frame = frame.f_back
        depth += 1
    parts.reverse()  # root (outermost call) first: flamegraph convention
    return ";".join(parts) if parts else "<no-frames>"


class HostStackSampler:
    """Bounded aggregating sampler over ``sys._current_frames()``.

    ``hz``/``armed_hz``: the base and armed sampling rates.
    ``max_depth``: frames kept per stack. ``max_stacks``: distinct
    folded stacks held before overflow folding.
    """

    def __init__(self, *, hz: float = DEFAULT_HZ,
                 armed_hz: float = DEFAULT_ARMED_HZ,
                 max_depth: int = DEFAULT_MAX_DEPTH,
                 max_stacks: int = DEFAULT_MAX_STACKS):
        if hz <= 0 or armed_hz <= 0:
            raise ValueError(f"hz/armed_hz must be > 0, got {hz}/{armed_hz}")
        self.hz = float(hz)
        self.armed_hz = float(armed_hz)
        self.max_depth = int(max_depth)
        self.max_stacks = int(max_stacks)
        self._lock = threading.Lock()
        # (thread_name, folded_stack) -> sample count
        self._stacks: Dict[Tuple[str, str], int] = {}
        self._samples_total = 0
        self._overflow_total = 0
        self._armed_until = 0.0
        self._armed_hz_now = armed_hz
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- sampling ------------------------------------------------------------

    def sample(self) -> int:
        """Take one sample of every live thread (the sampler's own
        thread excluded); returns the number of stacks folded in."""
        me = threading.get_ident()
        frames = sys._current_frames()
        # thread names resolve through the live thread table; a thread
        # the table doesn't know (C-created) keeps its ident as name
        names = {t.ident: t.name for t in threading.enumerate()}
        folded = 0
        with self._lock:
            for ident, frame in frames.items():
                if ident == me:
                    continue
                key = (str(names.get(ident, ident)),
                       fold_frame(frame, self.max_depth))
                if key not in self._stacks and \
                        len(self._stacks) >= self.max_stacks:
                    self._overflow_total += 1
                    key = (key[0], _OVERFLOW_KEY)
                    if key not in self._stacks and \
                            len(self._stacks) >= self.max_stacks + 64:
                        continue  # even overflow rows are bounded
                self._stacks[key] = self._stacks.get(key, 0) + 1
                folded += 1
            self._samples_total += 1
        self._feed_metrics()
        return folded

    def _feed_metrics(self):
        """Opportunistically mirror the sampler's counters into the
        sentinel metric bundle — guarded so the sampler works with no
        registry at all (and survives registry resets mid-sample)."""
        try:
            from deeplearning4j_tpu.observability import metrics as _m

            if not _m.enabled():
                return
            from deeplearning4j_tpu.observability.sentinel import (
                get_sentinel_metrics,
            )

            sm = get_sentinel_metrics()
            sm.hostsampler_samples_total.inc()
            with self._lock:
                n = len(self._stacks)
            sm.hostsampler_stacks.set(float(n))
        except Exception:  # noqa: BLE001 — telemetry never fails the sampler
            pass

    # -- arming --------------------------------------------------------------

    def arm(self, seconds: float, hz: Optional[float] = None) -> None:
        """Raise the sampling rate to ``hz`` (default ``armed_hz``) for
        ``seconds``; extends (never shortens) an existing window. The
        sentinel calls this when a detector turns suspect, so the flame
        data over the anomalous window is dense by firing time."""
        until = time.monotonic() + max(0.0, float(seconds))
        with self._lock:
            self._armed_until = max(self._armed_until, until)
            self._armed_hz_now = float(hz) if hz else self.armed_hz
        self._wake.set()  # re-evaluate the sleep interval now

    @property
    def armed(self) -> bool:
        with self._lock:
            return time.monotonic() < self._armed_until

    def current_hz(self) -> float:
        with self._lock:
            if time.monotonic() < self._armed_until:
                return self._armed_hz_now
        return self.hz

    # -- export --------------------------------------------------------------

    @property
    def samples_total(self) -> int:
        with self._lock:
            return self._samples_total

    def collapsed(self) -> str:
        """The aggregate as collapsed-stack text: one
        ``thread;frame;frame count`` line per distinct (thread, stack),
        highest counts first — flamegraph.pl / speedscope ready."""
        with self._lock:
            rows = sorted(self._stacks.items(), key=lambda kv: -kv[1])
        return "\n".join(
            f"{name};{stack} {count}" for (name, stack), count in rows)

    def dump(self) -> dict:
        """JSON-serializable summary + the collapsed document (what the
        incident bundle embeds)."""
        with self._lock:
            n_stacks = len(self._stacks)
            threads = sorted({name for name, _ in self._stacks})
            samples = self._samples_total
            overflow = self._overflow_total
            armed = time.monotonic() < self._armed_until
        return {
            "hz": self.hz, "armed_hz": self.armed_hz, "armed": armed,
            "samples_total": samples, "unique_stacks": n_stacks,
            "max_stacks": self.max_stacks,
            "overflow_samples_total": overflow,
            "threads": threads,
            "collapsed": self.collapsed(),
        }

    def reset(self) -> None:
        with self._lock:
            self._stacks.clear()
            self._samples_total = 0
            self._overflow_total = 0

    # -- lifecycle -----------------------------------------------------------

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "HostStackSampler":
        if self.running:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="host-stack-sampler")
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.sample()
            except Exception:  # noqa: BLE001 — the sampler must survive
                pass           # interpreter-state races; next tick retries
            self._wake.wait(1.0 / self.current_hz())
            self._wake.clear()

    def stop(self):
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def __enter__(self) -> "HostStackSampler":
        return self.start()

    def __exit__(self, *exc):
        self.stop()


# -- process-global sampler ---------------------------------------------------

_SAMPLER: Optional[HostStackSampler] = None
_sampler_lock = threading.Lock()


def get_host_sampler(*, start: bool = False) -> HostStackSampler:
    """The process sampler (created lazily, NOT started unless asked —
    ``ModelServer.start`` and the sentinel pass ``start=True``)."""
    global _SAMPLER
    with _sampler_lock:
        if _SAMPLER is None:
            _SAMPLER = HostStackSampler()
        s = _SAMPLER
    if start:
        s.start()
    return s


def set_host_sampler(s: Optional[HostStackSampler]) -> None:
    """Swap the process sampler (tests); the old one is stopped."""
    global _SAMPLER
    with _sampler_lock:
        old, _SAMPLER = _SAMPLER, s
    if old is not None and old is not s:
        old.stop()
