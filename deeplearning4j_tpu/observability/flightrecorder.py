"""Black-box flight recorder: the last N seconds of *events*, always on.

The metrics registry answers "how much", spans answer "where did this
request's time go" — neither answers the post-mortem question "what
HAPPENED in the 30 seconds before the crash?". This module is the
aviation-style answer: a bounded, thread-safe ring of structured events
that every layer feeds continuously (train steps, admissions/sheds,
rollbacks, checkpoint verify/quarantine, fault injections, SLO alert
transitions) plus periodic compact registry snapshots, so the timeline
around any incident is reconstructable from the ring alone.

Consumers:

- ``utils/crash.py`` attaches ``dump()`` to every crash report — a crash
  dump ships its own timeline;
- ``ModelServer`` serves ``GET /debug/flightrecorder`` — the live ring
  over HTTP;
- tests assert on event sequences instead of scraping logs.

Cost discipline: ``record_event`` is one dict build + deque append under
a lock (~1 µs); producers on hot paths additionally gate on
``metrics.enabled()`` like every other instrument. ``set_recording(False)``
is the recorder's own kill switch so ``bench.py observability`` can
price the recorder separately from the rest of the telemetry.

Stdlib only; safe to import from any layer (imports nothing but
``observability.metrics`` lazily, for snapshots).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional

DEFAULT_CAPACITY = 4096
# cap on distinct series a registry snapshot event may carry — a snapshot
# must stay one compact ring entry, not a full scrape
SNAPSHOT_SERIES_CAP = 256


def _identity_fields() -> dict:
    """Worker identity stamped onto every event envelope when this
    process runs under a cluster supervisor (``DL4J_TPU_WORKER_ID``
    armed) — merged cluster dossiers attribute events without guessing
    which ring they came from. Empty (no extra keys) standalone."""
    wid = os.environ.get("DL4J_TPU_WORKER_ID")
    if wid is None:
        return {}
    try:
        return {"worker": int(wid),
                "generation": int(
                    os.environ.get("DL4J_TPU_GENERATION", "1") or 1)}
    except ValueError:
        return {}


class FlightRecorder:
    """Bounded ring of ``{"t", "kind", "data"}`` events, oldest evicted.

    ``data`` is nested (never merged into the envelope) so producer keys
    can never clobber ``t``/``kind``. Eviction is counted
    (``dropped_total``) — a dump that lost history says so.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._events: deque = deque(maxlen=capacity)
        self._dropped = 0
        self._lock = threading.Lock()

    def record(self, kind: str, /, **data) -> dict:
        """Append one event; returns it (already enveloped). ``kind`` is
        positional-only so a producer may carry ``kind``/``t`` keys in
        its data payload. Under a cluster supervisor the envelope also
        carries ``worker``/``generation`` (identity lives in the
        envelope, not ``data``, so producer keys can't clobber it)."""
        ev = {"t": time.time(), "kind": kind,
              **_identity_fields(), "data": data}
        with self._lock:
            if len(self._events) == self.capacity:
                self._dropped += 1
            self._events.append(ev)
        return ev

    @property
    def dropped_total(self) -> int:
        with self._lock:
            return self._dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def events(self, *, last_seconds: Optional[float] = None,
               kinds: Optional[Iterable[str]] = None,
               max_events: Optional[int] = None) -> List[dict]:
        """Snapshot of the ring, oldest first, optionally windowed to the
        trailing ``last_seconds``, filtered to ``kinds``, and capped to
        the NEWEST ``max_events`` (the incident pipeline bounds its
        bundle artifact with this — when history is cut, it is the old
        end that goes)."""
        with self._lock:
            snap = list(self._events)
        if last_seconds is not None:
            cutoff = time.time() - last_seconds
            snap = [e for e in snap if e["t"] >= cutoff]
        if kinds is not None:
            want = set(kinds)
            snap = [e for e in snap if e["kind"] in want]
        if max_events is not None and len(snap) > max_events:
            snap = snap[-max_events:]
        return snap

    def dump(self, last_seconds: Optional[float] = None,
             kinds: Optional[Iterable[str]] = None,
             max_events: Optional[int] = None) -> dict:
        """The black-box dump: JSON-serializable, self-describing."""
        evs = self.events(last_seconds=last_seconds, kinds=kinds,
                          max_events=max_events)
        out = {
            "capacity": self.capacity,
            "dropped_total": self.dropped_total,
            "window_seconds": last_seconds,
            "count": len(evs),
            "events": evs,
        }
        ident = _identity_fields()
        if ident:
            try:
                nw = int(os.environ.get("DL4J_TPU_NUM_WORKERS", "1") or 1)
            except ValueError:
                nw = 1
            out["worker_identity"] = dict(ident, num_workers=nw)
        return out

    def clear(self):
        with self._lock:
            self._events.clear()
            self._dropped = 0

    # -- periodic registry snapshots ----------------------------------------

    def snapshot_registries(self, registries=None) -> dict:
        """Record one compact ``metrics.snapshot`` event: every counter /
        gauge family summed over its label sets (histograms contribute
        their ``_count``). The SLO evaluator calls this each tick, so the
        ring carries a coarse metric timeline between discrete events."""
        from deeplearning4j_tpu.observability import metrics as _m

        if registries is None:
            registries = [_m.default_registry()]
        series: Dict[str, float] = {}
        for reg in registries:
            for inst in reg.instruments():
                if len(series) >= SNAPSHOT_SERIES_CAP:
                    break
                doc = inst.to_json()
                if doc["type"] in ("counter", "gauge"):
                    series[doc["name"]] = float(
                        sum(s["value"] for s in doc["samples"]))
                elif doc["type"] == "histogram":
                    series[doc["name"] + "_count"] = float(
                        sum(s["count"] for s in doc["samples"]))
        return self.record("metrics.snapshot", series=series,
                           truncated=len(series) >= SNAPSHOT_SERIES_CAP)


# -- process-global recorder --------------------------------------------------

_RECORDER = FlightRecorder()
_RECORDING = True


def get_flight_recorder() -> FlightRecorder:
    """The process-global ring every built-in producer feeds."""
    return _RECORDER


def set_flight_recorder(rec: Optional[FlightRecorder]) -> FlightRecorder:
    """Swap the global recorder (tests); None installs a fresh ring."""
    global _RECORDER
    _RECORDER = rec if rec is not None else FlightRecorder()
    return _RECORDER


def set_recording(flag: bool):
    """Recorder kill switch (independent of ``metrics.set_enabled`` so the
    bench can price the recorder alone)."""
    global _RECORDING
    _RECORDING = bool(flag)


def recording_enabled() -> bool:
    return _RECORDING


def record_event(kind: str, /, **data) -> Optional[dict]:
    """The one-liner producers call; no-op (returns None) when recording
    is switched off."""
    if not _RECORDING:
        return None
    return _RECORDER.record(kind, **data)
