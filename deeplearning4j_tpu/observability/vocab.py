"""Declared vocabularies for cross-plane string identifiers.

The flight recorder, the SLO evaluator, and the fleet debug endpoints
all key on *strings*: a flight event's ``kind``, a metric family name, a
``DL4J_TPU_*`` env knob. Strings drift — PR review history shows the
same defect class recurring (an event kind spelled two ways, a metric
family the rule file can't validate, knobs documented in GUIDE.md long
after the code grew them). This module is the single declaration point
for the flight-event ``kind`` vocabulary; ``analysis/vocabpass.py``
statically checks that every literal kind recorded anywhere in the
package appears here, so adding an event without declaring it is a
tier-1 failure, not a doc chore.

Grouped by producing plane. Keep the groups sorted; the analysis check
does not care, but reviewers diff this file.
"""

from __future__ import annotations

# serving data plane (server.py / registry.py / warmup.py)
SERVING_KINDS = frozenset({
    "serving.admission_cap",
    "serving.brownout",
    "serving.circuit",
    "serving.deploy",
    "serving.drain",
    "serving.error",
    "serving.fallback",
    "serving.fallback_error",
    "serving.fallback_prewarm",
    "serving.fallback_prewarm_failed",
    "serving.recompile_after_warm",
    "serving.rollback",
    "serving.shed",
    "serving.start",
    "serving.stop",
    "serving.warmup_complete",
    "serving.warmup_error",
    "serving.worker_crash",
})

# generative serving engine (generation.py)
GENERATION_KINDS = frozenset({
    "generation.compile",
    "generation.error",
    "generation.join",
    "generation.leave",
    "generation.preempt",
    "generation.request",
    "generation.shed",
    "generation.warmup",
})

# fleet router tier (router.py)
ROUTER_KINDS = frozenset({
    "router.backend",
    "router.backend_added",
    "router.backend_removed",
    "router.backend_warming",
    "router.deploy",
    "router.drain",
    "router.park",
    "router.readmit",
    "router.retry",
    "router.retry_budget_exhausted",
    "router.shed",
    "router.start",
    "router.stop",
    "router.stream_broken",
})

# fleet autoscaler / self-healing control loop (serving/autoscaler.py)
AUTOSCALER_KINDS = frozenset({
    "autoscaler.gave_up",
    "autoscaler.page_in",
    "autoscaler.replace",
    "autoscaler.scale_in",
    "autoscaler.scale_out",
    "autoscaler.start",
    "autoscaler.stop",
})

# training + data pipeline (trainer.py / iterators.py)
TRAIN_KINDS = frozenset({
    "data.auto_prefetch",
    "data.starved",
    "train.data_recovered",
    "train.data_starvation",
    "train.epoch",
    "train.step",
})

# resilience: recovery hooks, elastic supervisor, fault injection
RESILIENCE_KINDS = frozenset({
    "checkpoint.quarantined",
    "checkpoint.verify_failed",
    "collective.timeout",
    "fault.injected",
    "resilience.checkpoint_skip",
    "resilience.lr_cut",
    "resilience.rollback",
    "resilience.skip_batch",
    "supervisor.cluster_dossier",
    "supervisor.complete",
    "supervisor.expand",
    "supervisor.expand_ready",
    "supervisor.gave_up",
    "supervisor.launch",
    "supervisor.probe",
    "supervisor.restart",
    "supervisor.shrink",
    "supervisor.shrink_denied",
    "supervisor.slot_marked_dead",
    "supervisor.worker_exit",
    "supervisor.worker_hang",
})

# cold-start plane (runtime/compilecache.py + serving/warmstart.py)
COMPILE_KINDS = frozenset({
    "compile_cache.activate",
    "compile_cache.quarantined",
    "compile_cache.sealed",
})

# observability plane's own events (sentinel, SLO, profiling, recorder)
OBSERVABILITY_KINDS = frozenset({
    "anomaly.transition",
    "debug.profile",
    "incident.close",
    "incident.open",
    "metrics.snapshot",
    "slo.transition",
})

# concurrency/invariant sanitizers (analysis/lockcheck.py)
SANITIZER_KINDS = frozenset({
    "sanitizer.violation",
})

# request & prefix caching tier (serving/cache.py, serving/prefixkv.py,
# the router's fleet-level lookup)
CACHE_KINDS = frozenset({
    "cache.hit",
    "cache.invalidate",
    "cache.prefix_evict",
    "cache.prefix_insert",
    "cache.pressure",
    "cache.purge",
    "cache.stale_serve",
})

# traffic replay + scripted game-days (resilience/replay.py,
# resilience/gameday.py)
REPLAY_KINDS = frozenset({
    "gameday.act",
    "gameday.complete",
    "gameday.gate",
    "gameday.report",
    "gameday.start",
    "replay.complete",
    "replay.start",
})

# historical telemetry tier (observability/timeseries.py,
# observability/usage.py)
TELEMETRY_KINDS = frozenset({
    "capacity.verdict",
    "tsdb.restore",
    "tsdb.start",
    "tsdb.stop",
    "usage.overflow",
})

EVENT_KINDS = frozenset().union(
    SERVING_KINDS, GENERATION_KINDS, ROUTER_KINDS, TRAIN_KINDS,
    RESILIENCE_KINDS, COMPILE_KINDS, OBSERVABILITY_KINDS,
    SANITIZER_KINDS, CACHE_KINDS, REPLAY_KINDS, TELEMETRY_KINDS,
    AUTOSCALER_KINDS)


def known_event_kinds() -> frozenset:
    """The full declared flight-event ``kind`` vocabulary."""
    return EVENT_KINDS
